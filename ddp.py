"""TPU-native distributed training entrypoint.

Name-and-flag-compatible with the reference's ``ddp.py`` CLI
(``/root/reference/ddp.py:291-318``): ``python ddp.py [flags]`` trains the
selected model. Where the reference needs ``torch.distributed.launch`` to
spawn one process per GPU, a single invocation here drives every local TPU
chip, and one invocation per *host* (see ``launch/``) scales the same code
to a pod.
"""

from __future__ import annotations

import sys

from pytorch_ddp_template_tpu import parse_args
from pytorch_ddp_template_tpu.data import (
    MemmapDataset,
    Subset,
    SyntheticImageDataset,
    SyntheticRegressionDataset,
    SyntheticTokenDataset,
)
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init, shutdown
from pytorch_ddp_template_tpu.train import Trainer
from pytorch_ddp_template_tpu.utils import get_logger

log = get_logger("ddp")


def train_eval_split(config, train_ds):
    """``(train_ds, eval_ds)``: a held-out split for evaluation.

    Synthetic sources regenerate with a different seed (same distribution,
    disjoint stream); file-backed stores hold out their tail rows — the
    rung where held-out eval matters most must not silently skip it.
    """
    eval_seed = config.seed + 10_000
    n = max(128, config.train_batch_size)
    if isinstance(train_ds, MemmapDataset):
        held = min(max(n, len(train_ds) // 10), len(train_ds) // 2)
        split = len(train_ds) - held
        return (Subset(train_ds, 0, split),
                Subset(train_ds, split, len(train_ds)))
    if isinstance(train_ds, SyntheticImageDataset):
        return train_ds, SyntheticImageDataset(
            samples=n,
            image_size=train_ds.image_size,
            num_classes=train_ds.num_classes,
            seed=eval_seed,
        )
    if isinstance(train_ds, SyntheticTokenDataset):
        return train_ds, SyntheticTokenDataset(
            samples=n, seq_len=train_ds.arrays["input_ids"].shape[1],
            vocab=train_ds.vocab, seed=eval_seed, padded=train_ds.padded,
        )
    if isinstance(train_ds, SyntheticRegressionDataset):
        return train_ds, SyntheticRegressionDataset(samples=n, seed=eval_seed)
    return train_ds, None


def main(argv: list[str] | None = None) -> int:
    config = parse_args(argv)
    ctx = init(config)
    try:
        task, dataset = build(config.model, config)
        eval_ds = None
        if config.eval_data_dir:
            # a dedicated held-out store (e.g. the CIFAR-10 test split)
            # beats a tail holdout of the training store
            eval_ds = MemmapDataset(config.eval_data_dir)
        elif config.eval_steps or config.eval_only:
            dataset, eval_ds = train_eval_split(config, dataset)
        trainer = Trainer(config, ctx, task, dataset, eval_dataset=eval_ds)
        if config.eval_only:
            # evaluate a saved model, no training (the reference cannot do
            # this at all: its checkpoints have no load path, ddp.py:293)
            if trainer.ckpt.latest_step() is None:
                raise FileNotFoundError(
                    f"--eval_only: no checkpoints under {config.output_dir} "
                    "(evaluating a fresh init is almost never intended; "
                    "train first or point --output_dir at a run)"
                )
            if not config.resume and config.global_step == 0:
                # restore_or_init would hand back the fresh init — garbage
                # metrics under the checkpoint's name
                raise ValueError(
                    "--eval_only with --no_resume would evaluate random "
                    "init; drop --no_resume or pin --global-step"
                )
            if config.eval_data_dir is None and isinstance(dataset, Subset):
                # tail holdout of a file store: only valid if the TRAINING
                # run carved the SAME tail out — otherwise these rows were
                # trained on and the "held-out" metrics are a leak
                want = config.global_step or None
                saved = trainer.ckpt.read_config(want) or {}
                if not saved.get("eval_steps") or saved.get("eval_data_dir"):
                    # eval_steps=0 trained on the whole store; a dedicated
                    # eval_data_dir ALSO trained on the whole store (the
                    # holdout came from elsewhere) — either way the tail
                    # rows went through training
                    raise ValueError(
                        "--eval_only: the training run held nothing out of "
                        "this store (its tail rows were trained on); pass "
                        "--eval_data_dir with a genuinely held-out store"
                    )
                if saved.get("_train_batch_size") is None:
                    raise ValueError(
                        "--eval_only: this checkpoint predates batch-size "
                        "provenance, so the holdout split point cannot be "
                        "verified; re-save a checkpoint with the current "
                        "version or pass --eval_data_dir"
                    )
                if saved.get("_train_batch_size") != config.train_batch_size:
                    raise ValueError(
                        "--eval_only: global train batch "
                        f"({config.train_batch_size}) differs from the "
                        "training run's recorded "
                        f"({saved.get('_train_batch_size')}); the holdout "
                        "split point would move and leak training rows "
                        "into eval — match the training batch size and "
                        "device count"
                    )
            state, step = trainer.restore_or_init()
            results = trainer.evaluate(state)
            log.info("eval_only", {"step": step, **results})
            from pytorch_ddp_template_tpu.utils import is_main_process

            if is_main_process():
                import json
                from pathlib import Path

                out = Path(config.output_dir) / f"eval_{step}.json"
                out.write_text(json.dumps({"step": step, **results},
                                          indent=2))
            return 0
        state = trainer.train()
        if eval_ds is not None:
            final = trainer.evaluate(state)
            log.info("final eval", dict(final))
        return 0
    finally:
        shutdown()


if __name__ == "__main__":
    sys.exit(main())
