"""TPU-native distributed training entrypoint.

Name-and-flag-compatible with the reference's ``ddp.py`` CLI
(``/root/reference/ddp.py:291-318``): ``python ddp.py [flags]`` trains the
selected model. Where the reference needs ``torch.distributed.launch`` to
spawn one process per GPU, a single invocation here drives every local TPU
chip, and one invocation per *host* (see ``launch/``) scales the same code
to a pod.
"""

from __future__ import annotations

import sys

from pytorch_ddp_template_tpu import parse_args
from pytorch_ddp_template_tpu.data import (
    SyntheticImageDataset,
    SyntheticRegressionDataset,
    SyntheticTokenDataset,
)
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init, shutdown
from pytorch_ddp_template_tpu.train import Trainer
from pytorch_ddp_template_tpu.utils import get_logger

log = get_logger("ddp")


def make_eval_dataset(config, train_ds):
    """A held-out synthetic split: same distribution, different seed."""
    eval_seed = config.seed + 10_000
    n = max(128, config.train_batch_size)
    if isinstance(train_ds, SyntheticImageDataset):
        return SyntheticImageDataset(
            samples=n,
            image_size=train_ds.image_size,
            num_classes=train_ds.num_classes,
            seed=eval_seed,
        )
    if isinstance(train_ds, SyntheticTokenDataset):
        return SyntheticTokenDataset(
            samples=n, seq_len=train_ds.arrays["input_ids"].shape[1],
            vocab=train_ds.vocab, seed=eval_seed, padded=train_ds.padded,
        )
    if isinstance(train_ds, SyntheticRegressionDataset):
        return SyntheticRegressionDataset(samples=n, seed=eval_seed)
    return None


def main(argv: list[str] | None = None) -> int:
    config = parse_args(argv)
    ctx = init(config)
    try:
        task, dataset = build(config.model, config)
        eval_ds = make_eval_dataset(config, dataset) if config.eval_steps else None
        trainer = Trainer(config, ctx, task, dataset, eval_dataset=eval_ds)
        state = trainer.train()
        if eval_ds is not None:
            final = trainer.evaluate(state)
            log.info("final eval", dict(final))
        return 0
    finally:
        shutdown()


if __name__ == "__main__":
    sys.exit(main())
