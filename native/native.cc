// Host-side native runtime: the C++ layer of the TPU framework.
//
// The reference's native surface is third-party CUDA/C++ it links against
// (NCCL collectives, CUDA runtime, apex — SURVEY.md 2c); its first-party
// code is pure Python. On TPU the collective/compute roles belong to
// XLA/Pallas, so the native layer lives where TPU training actually
// bottlenecks on the host: the input pipeline (SURVEY.md 7 hard part (e)).
//
// Exports (C ABI, bound via ctypes in pytorch_ddp_template_tpu/native.py):
//   ddp_permutation  - seeded Fisher-Yates epoch permutation (the
//                      DistributedSampler reshuffle, ddp.py:213-214, as a
//                      native kernel; counter-based seeding = set_epoch)
//   ddp_synth_u8     - threaded per-sample synthetic byte generation
//                      (ImageNet-shaped sample fabrication at memory
//                      bandwidth instead of a Python per-sample loop)
//   ddp_gather_rows  - threaded strided row gather (host-side batch
//                      assembly: dataset rows -> contiguous batch slab)
//
// Determinism: splitmix64 seeding + xoshiro256** streams, keyed by
// (seed, epoch) or (seed, sample_index) counters only - never by call
// order - so every host computes identical data independently, which is
// what makes the per-host disjoint loading scheme coherent without any
// cross-host communication.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kGolden = 0x9e3779b97f4a7c15ULL;

inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += kGolden);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

struct Xoshiro256 {
  uint64_t s[4];

  explicit Xoshiro256(uint64_t seed) {
    uint64_t sm = seed;
    for (auto& w : s) w = splitmix64(sm);
  }

  static inline uint64_t rotl(uint64_t x, int k) {
    return (x << k) | (x >> (64 - k));
  }

  inline uint64_t next() {
    const uint64_t result = rotl(s[1] * 5, 7) * 9;
    const uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }

  // uniform integer in [0, bound) without modulo bias (Lemire)
  inline uint64_t bounded(uint64_t bound) {
    while (true) {
      uint64_t x = next();
      __uint128_t m = static_cast<__uint128_t>(x) * bound;
      uint64_t l = static_cast<uint64_t>(m);
      if (l >= bound || l >= (-bound) % bound) return m >> 64;
    }
  }
};

inline uint64_t mix2(uint64_t a, uint64_t b) {
  uint64_t st = a * kGolden + b;
  return splitmix64(st);
}

}  // namespace

extern "C" {

// Fisher-Yates permutation of [0, n) keyed on (seed, epoch).
// out must hold n int64 values.
void ddp_permutation(uint64_t seed, uint64_t epoch, int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  Xoshiro256 rng(mix2(seed, epoch));
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(rng.bounded(static_cast<uint64_t>(i) + 1));
    int64_t tmp = out[i];
    out[i] = out[j];
    out[j] = tmp;
  }
}

// Fill out[k * bytes_per_sample ...] with the deterministic byte stream of
// sample indices[k], stream keyed on (seed, index). Threaded over samples.
void ddp_synth_u8(uint64_t seed, const int64_t* indices, int64_t n_samples,
                  int64_t bytes_per_sample, uint8_t* out, int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int64_t> cursor{0};
  auto worker = [&]() {
    while (true) {
      int64_t k = cursor.fetch_add(1);
      if (k >= n_samples) return;
      Xoshiro256 rng(mix2(seed, static_cast<uint64_t>(indices[k])));
      uint8_t* dst = out + k * bytes_per_sample;
      int64_t full = bytes_per_sample / 8;
      for (int64_t w = 0; w < full; ++w) {
        uint64_t x = rng.next();
        std::memcpy(dst + w * 8, &x, 8);
      }
      int64_t rem = bytes_per_sample - full * 8;
      if (rem) {
        uint64_t x = rng.next();
        std::memcpy(dst + full * 8, &x, rem);
      }
    }
  };
  if (n_threads == 1) {
    worker();
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

// Gather rows: out[k] = src[indices[k]] for row_bytes-sized rows.
// The host-side batch assembly (DataLoader collate equivalent) as one
// threaded memcpy sweep.
void ddp_gather_rows(const uint8_t* src, const int64_t* indices,
                     int64_t n_rows, int64_t row_bytes, uint8_t* out,
                     int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int64_t> cursor{0};
  auto worker = [&]() {
    while (true) {
      int64_t k = cursor.fetch_add(1);
      if (k >= n_rows) return;
      std::memcpy(out + k * row_bytes, src + indices[k] * row_bytes,
                  row_bytes);
    }
  };
  if (n_threads == 1) {
    worker();
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

}  // extern "C"
