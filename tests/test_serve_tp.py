"""Tensor-parallel decode (r21): the ring-sharded decode program in the
paged serving engine.

The acceptance anchors: TP decode through the engine is token-for-token
identical to single-replica greedy (pinned across tp degree x int8 KV x
speculative decoding), the engine still holds exactly ONE compiled
decode program (two in spec mode: draft + verify), the rotating-argmax
head matches the dense head bit-for-bit (odd vocab/seq padding, no-bias,
tie-break-to-lowest-id), paged attention over model-sharded heads
matches the replicated pool, the refusal matrix names a reason per
refused template flag, and ``/metrics`` exports live
``tpuddp_serve_tp_*`` gauges.
"""

import dataclasses
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_ddp_template_tpu.models.gpt import gpt_tiny
from pytorch_ddp_template_tpu.ops.lm_head import (
    greedy_decode, tp_greedy_decode, tp_head_geometry,
)
from pytorch_ddp_template_tpu.parallel.shard_map_compat import shard_map
from pytorch_ddp_template_tpu.runtime.context import MODEL_AXIS
from pytorch_ddp_template_tpu.serve import ServeConfig, ServeEngine
from pytorch_ddp_template_tpu.serve.decode_ops import _paged_attention_xla

VOCAB = 256

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="TP decode needs >= 2 devices")


def mesh2():
    return Mesh(np.array(jax.devices()[:2]).reshape(1, 2),
                ("data", "model"))


@pytest.fixture(scope="module")
def tiny():
    model = gpt_tiny(vocab_size=VOCAB, seq_len=128)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
        train=False)["params"])
    return model, params


# -- the rotating-argmax head ----------------------------------------------

class TestTpGreedyDecode:
    def dense(self, h, tab, bias=None):
        logits = h.astype(jnp.float32) @ tab.T.astype(jnp.float32)
        if bias is not None:
            logits = logits + bias
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def case(self, vocab, s, block, with_bias, seed=0):
        rng = np.random.RandomState(seed)
        h = jnp.asarray(rng.randn(s, 64).astype(np.float32))
        tab = jnp.asarray(rng.randn(vocab, 64).astype(np.float32))
        bias = (jnp.asarray(rng.randn(vocab).astype(np.float32))
                if with_bias else None)
        return h, tab, bias

    @pytest.mark.parametrize("vocab,s,block,with_bias", [
        (103, 5, 16, True),    # odd vocab AND odd slot count: both pad
        (VOCAB, 4, 64, False),  # power-of-two, no bias
        (257, 7, 8192, True),  # block wider than the shard: clamped
    ])
    def test_matches_dense_head(self, vocab, s, block, with_bias):
        h, tab, bias = self.case(vocab, s, block, with_bias)
        got = tp_greedy_decode(h, tab, mesh2(), bias=bias, block=block)
        ref = self.dense(h, tab, bias)
        assert got.shape == (s,) and got.dtype == jnp.int32
        assert (np.asarray(got) == np.asarray(ref)).all()
        # and the single-device blockwise head agrees too
        assert (np.asarray(greedy_decode(h, tab, bias=bias, block=block))
                == np.asarray(ref)).all()

    def test_quant_wire_matches_dequantized_dense(self):
        # int8 wire: every shard folds logits of the SAME
        # quantize->dequantize hidden, so the ring must equal the dense
        # argmax of that reconstruction exactly
        from pytorch_ddp_template_tpu.ops.quant import (
            dequantize, quantize_channel,
        )

        h, tab, bias = self.case(103, 6, 16, True, seed=3)
        got = tp_greedy_decode(h, tab, mesh2(), bias=bias, block=16,
                               quant="int8")
        hq, hs = quantize_channel(h, "int8", axes=-1)
        ref = self.dense(dequantize(hq, hs), tab, bias)
        assert (np.asarray(got) == np.asarray(ref)).all()

    def test_ties_break_to_lowest_id_across_shards(self):
        # duplicate row on BOTH vocab shards of a 2-way ring: the
        # argmax must pick the lowest absolute id whatever shard visit
        # order the rotation produces
        rng = np.random.RandomState(1)
        vocab = 300  # shards rows [0, 150) and [150, 300)
        tab = np.asarray(rng.randn(vocab, 64), np.float32)
        tab[290] = tab[3]  # exact tie across shards
        h = jnp.asarray(tab[3] * 10.0)[None, :]
        for block in (7, 64, 8192):
            got = tp_greedy_decode(h, jnp.asarray(tab), mesh2(),
                                   block=block)
            assert int(got[0]) == 3, (block, int(got[0]))

    def test_geometry_is_the_single_source(self):
        # the engine pads the table at placement with the same numbers
        # the ring consumes — whole local blocks, n * vs total rows
        for vocab, n, block in [(103, 2, 16), (50257, 4, 8192),
                                (256, 2, 8192)]:
            blk, vs, pad_v = tp_head_geometry(vocab, n, block)
            assert vs % blk == 0
            assert n * vs == vocab + pad_v
            assert pad_v < n * blk


# -- paged attention over model-sharded heads ------------------------------

class TestPagedAttentionHeadSharded:
    def test_matches_replicated_pool(self):
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(3, 2, 32).astype(np.float32))
        kp = jnp.asarray(rng.randn(12, 16, 2, 32).astype(np.float32))
        vp = jnp.asarray(rng.randn(12, 16, 2, 32).astype(np.float32))
        tb = jnp.asarray(rng.randint(0, 12, (3, 4)).astype(np.int32))
        ln = jnp.asarray(np.array([37, 9, 64], np.int32))
        ref = _paged_attention_xla(q, kp, vp, tb, ln)

        def local(q_l, kp_l, vp_l):
            return _paged_attention_xla(q_l, kp_l, vp_l, tb, ln)

        got = shard_map(
            local, mesh=mesh2(),
            in_specs=(P(None, MODEL_AXIS, None),
                      P(None, None, MODEL_AXIS, None),
                      P(None, None, MODEL_AXIS, None)),
            out_specs=P(None, MODEL_AXIS, None), check_vma=False,
        )(q, kp, vp)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5)


# -- the TP engine: token-for-token + the compile pin ----------------------

PROMPTS = [[5, 9, 2], [7, 1, 1, 3, 8, 2], [4] * 10, [1, 2]]


def run_engine(model, params, mesh=None, **overrides):
    cfg = dict(block_size=4, num_blocks=64, max_slots=4, max_model_len=64)
    cfg.update(overrides)
    eng = ServeEngine(model, params, ServeConfig(**cfg), mesh=mesh)
    ids = [eng.submit(p, max_new_tokens=12).id for p in PROMPTS]
    out = eng.run()
    return {i: list(out[i]) for i in ids}, eng


class TestTpEngineParity:
    @pytest.fixture(scope="class")
    def ref_out(self, tiny):
        model, params = tiny
        out, eng = run_engine(model, params)
        assert eng.decode_programs() == 1
        return out

    def tp_twin(self, tiny, **model_overrides):
        model, params = tiny
        return dataclasses.replace(model, tp_overlap=True,
                                   **model_overrides), params

    def test_token_parity_and_one_program(self, tiny, ref_out):
        model, params = self.tp_twin(tiny)
        got, eng = run_engine(model, params, mesh=mesh2())
        assert got == ref_out
        # the tentpole's compile contract: TP decode is still exactly
        # ONE compiled decode program, however sequences grow
        assert eng.decode_programs() == 1
        assert eng._tp == 2

    def test_token_parity_int8_kv(self, tiny):
        model, params = tiny
        ref, _ = run_engine(model, params, kv_quant="int8")
        tp_m, _ = self.tp_twin(tiny)
        got, _ = run_engine(tp_m, params, mesh=mesh2(), kv_quant="int8")
        assert got == ref

    def test_token_parity_spec_and_two_programs(self, tiny):
        model, params = tiny
        ref, _ = run_engine(model, params, spec_k=3, draft_depth=1)
        tp_m, _ = self.tp_twin(tiny)
        got, eng = run_engine(tp_m, params, mesh=mesh2(), spec_k=3,
                              draft_depth=1)
        assert got == ref
        # spec x TP: draft + verify, one program each — the chained
        # draft feed must not hash as a second program
        assert eng.decode_programs() == 2

    def test_token_parity_quant_wire(self, tiny, ref_out):
        # int8 ring wire on THIS model is lossless end to end (the
        # argmax margins dominate the quantization error); the pin
        # keeps the wire honest rather than asserting a general theorem
        model, params = self.tp_twin(tiny, quant_compute="int8")
        got, eng = run_engine(model, params, mesh=mesh2())
        assert got == ref_out
        assert eng._quant == "int8"

    def test_gspmd_mesh_path_unchanged(self, tiny, ref_out):
        # a mesh WITHOUT tp_overlap keeps the r19 GSPMD path: same
        # tokens, no ring program, tp degree 1
        model, params = tiny
        got, eng = run_engine(model, params, mesh=mesh2())
        assert got == ref_out
        assert eng._tp == 1


# -- the refusal matrix ----------------------------------------------------

class TestRefusalMatrix:
    def test_training_only_flags_refused_named(self, tiny):
        model, params = tiny
        for flag, match in [
            ("fsdp_overlap", "no gradients or optimizer state"),
            ("ddp_overlap", "no gradient all-reduce"),
        ]:
            bad = dataclasses.replace(model, **{flag: True})
            with pytest.raises(ValueError, match=match):
                ServeEngine(bad, params, ServeConfig())

    def test_moe_refused_named(self, tiny):
        model, params = tiny
        moe = dataclasses.replace(model, moe_experts=4)
        with pytest.raises(ValueError, match="expert-parallel"):
            ServeEngine(moe, params, ServeConfig())

    def test_tp_without_model_axis_refused_named(self, tiny):
        model, params = tiny
        tp_m = dataclasses.replace(model, tp_overlap=True)
        with pytest.raises(ValueError, match="live model axis"):
            ServeEngine(tp_m, params, ServeConfig())  # no mesh at all
        data_only = Mesh(np.array(jax.devices()[:2]).reshape(2, 1),
                         ("data", "model"))
        with pytest.raises(ValueError, match="model axis 1"):
            ServeEngine(tp_m, params, ServeConfig(), mesh=data_only)

    def test_quant_compute_without_tp_refused_named(self, tiny):
        model, params = tiny
        q = dataclasses.replace(model, quant_compute="int8")
        with pytest.raises(ValueError, match="TP ring wire"):
            ServeEngine(q, params, ServeConfig())

    def test_max_slots_not_ring_divisible_refused(self, tiny):
        model, params = tiny
        tp_m = dataclasses.replace(model, tp_overlap=True)
        with pytest.raises(ValueError, match="max_slots"):
            ServeEngine(tp_m, params,
                        ServeConfig(block_size=4, num_blocks=64,
                                    max_slots=3, max_model_len=64),
                        mesh=mesh2())

    def test_pallas_under_tp_refused(self, tiny, monkeypatch):
        model, params = tiny
        tp_m = dataclasses.replace(model, tp_overlap=True)
        monkeypatch.setenv("PAGED_IMPL", "pallas")
        with pytest.raises(ValueError, match="xla gather"):
            ServeEngine(tp_m, params,
                        ServeConfig(block_size=4, num_blocks=64,
                                    max_slots=4, max_model_len=64),
                        mesh=mesh2())

    @pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
    def test_heads_not_divisible_refused(self, tiny):
        model, params = tiny  # 2 heads cannot shard 4 ways
        tp_m = dataclasses.replace(model, tp_overlap=True)
        mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(1, 4),
                     ("data", "model"))
        with pytest.raises(ValueError, match="num_heads"):
            ServeEngine(tp_m, params,
                        ServeConfig(block_size=4, num_blocks=64,
                                    max_slots=4, max_model_len=64),
                        mesh=mesh4)


# -- observability ---------------------------------------------------------

class TestServeTpObs:
    def test_describe_and_live_gauges(self, tiny):
        from pytorch_ddp_template_tpu.obs.server import StatusServer

        model, params = tiny
        tp_m = dataclasses.replace(model, tp_overlap=True)
        status = StatusServer(0)
        status.start()
        try:
            eng = ServeEngine(
                tp_m, params,
                ServeConfig(block_size=4, num_blocks=64, max_slots=4,
                            max_model_len=64),
                mesh=mesh2(), status=status)
            desc = eng.describe_tp()
            assert desc["serve_tp_degree"] == 2
            # the quantized wire is strictly narrower than the wide one
            assert (desc["serve_tp_ring_wire_mb_per_step_quant"]
                    < desc["serve_tp_ring_wire_mb_per_step_wide"])
            # quant off: the actual wire IS the wide wire
            assert (desc["serve_tp_ring_wire_mb_per_step"]
                    == desc["serve_tp_ring_wire_mb_per_step_wide"])
            # pool residency halves across a 2-way head shard
            assert (desc["serve_tp_kv_pool_bytes_per_shard"] * 2
                    == eng.kv.pool_bytes())
            eng.submit([1, 2, 3, 4], max_new_tokens=5)
            eng.run()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status.port}/metrics",
                    timeout=10) as resp:
                text = resp.read().decode()
            assert "tpuddp_serve_tp_degree" in text
            assert "tpuddp_serve_tp_ring_wire_mb_per_step" in text
            assert "tpuddp_serve_tp_kv_pool_bytes_per_shard" in text
        finally:
            status.close()

    def test_wire_accounting_shapes(self):
        from pytorch_ddp_template_tpu.parallel.collective_matmul import (
            STACK_RINGS_FWD, tp_decode_wire_bytes_per_step,
        )

        wide = tp_decode_wire_bytes_per_step(
            slots=8, embed=64, num_layers=2, n=2)
        # fwd-only: 4 stack rings per layer + the head bundle; each
        # ring moves (n-1) * slots lanes of embed f32
        lanes = (2 - 1) * 8
        assert wide == (2 * STACK_RINGS_FWD * lanes * 64 * 4
                        + lanes * (64 * 4 + 2 * 4))
        quant = tp_decode_wire_bytes_per_step(
            slots=8, embed=64, num_layers=2, n=2, quant="int8")
        assert quant < wide
        # degenerate ring: nothing moves
        assert tp_decode_wire_bytes_per_step(
            slots=8, embed=64, num_layers=2, n=1) == 0


# -- the committed BENCH_MODE=serve_tp record ------------------------------

def test_serve_tp_record_committed_and_affirmative():
    """The committed round-21 record must carry the acceptance
    evidence: token-for-token parity with single-replica greedy
    (FLOPs-matched pair recorded), the one-compiled-decode-program pin,
    and ring schedule evidence in the decode program's own HLO."""
    import pathlib

    path = (pathlib.Path(__file__).resolve().parents[1]
            / "bench_records" / "serve_tp_cpu_r21.jsonl")
    assert path.is_file(), "run BENCH_MODE=serve_tp to record the legs"
    rows = [json.loads(s) for s in path.read_text().splitlines() if s]
    head = rows[0]
    assert head["metric"] == "serve_tp_vs_single_replica"
    assert not head.get("error")
    assert head["serve_tp_degree"] >= 2
    assert head["tp_lossless_checked"] is True
    assert head["decode_zero_recompile"] is True
    assert head["decode_programs"] == 1
    # FLOPs-matched pair present (CPU ratio is informational — the ring
    # pays real ppermute cost for no memory-bandwidth win off-chip)
    assert head["tokens_per_sec_tp"] > 0
    assert head["tokens_per_sec_single_replica"] > 0
    assert head["value"] > 0
    # ring schedule in evidence in the compiled decode program
    assert head["hlo_independent_ring_bodies"] > 0
    assert head["metrics_gauges_live"] is True
    # the quantized-wire ablation row: marked, lossless, narrower wire
    quant = [r for r in rows if r.get("tp_degree")]
    assert quant, "quant wire ablation row missing"
    assert quant[0]["quant_compute"] == "int8"
    assert quant[0]["tp_lossless_checked"] is True
    assert quant[0]["value"] < quant[0]["wire_mb_wide"]
