"""Attention op numerics: blockwise and Pallas-flash (interpret mode on
CPU) against the plain XLA formulation, forward + backward.

The reference has no attention op to compare against (SURVEY.md §5.7); the
XLA einsum path is the ground truth here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
)
from pytorch_ddp_template_tpu.ops.flash import flash_attention

B, S, H, D = 1, 64, 2, 32


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return tuple(
        jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_reference(qkv, causal):
    q, k, v = qkv
    ref = dot_product_attention(q, k, v, causal=causal)
    blk = blockwise_attention(q, k, v, causal=causal, block_size=16)
    np.testing.assert_allclose(ref, blk, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(qkv, causal):
    q, k, v = qkv
    ref = dot_product_attention(q, k, v, causal=causal)
    fl = flash_attention(q, k, v, causal=causal, block_size=32)
    np.testing.assert_allclose(ref, fl, atol=2e-5)


def test_flash_gradients_match(qkv):
    q, k, v = qkv

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    ref_fn = loss(lambda q, k, v: dot_product_attention(q, k, v, causal=True))
    fl_fn = loss(
        lambda q, k, v: flash_attention(q, k, v, causal=True, block_size=32)
    )
    g_ref = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(fl_fn, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(a, b, atol=1e-5 * max(scale, 1.0))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 32])
def test_flash_backward_kernel_all_shapes(qkv, causal, block):
    """The Pallas backward (dq and dk/dv kernels) across block counts;
    causal=True exercises the skip + DMA-redirect paths (equal blocks —
    the gcd wrapper always tiles self-attention that way; unequal blocks
    are covered by the cross-attention test below)."""
    q, k, v = qkv

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal)),
        argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss(
        lambda q, k, v: flash_attention(q, k, v, causal=causal,
                                        block_size=block)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(a, b, atol=1e-5 * max(scale, 1.0))


def test_flash_backward_unequal_blocks_cross_attention():
    """q len 64 / kv len 48 with block_size 32 tiles as block_q=32,
    block_kv=16 — the mixed-block on_diag predicate and grid shapes the
    equal-block tests can never reach."""
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((1, 64, 2, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 48, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 48, 2, 32)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(lambda q, k, v: dot_product_attention(q, k, v)),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss(lambda q, k, v: flash_attention(q, k, v,
                                                         block_size=32)),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(a, b, atol=1e-5 * max(scale, 1.0))


def test_flash_backward_xla_fallback_matches(qkv, monkeypatch):
    """FLASH_BWD=xla routes the custom vjp to the scan fallback; grads
    must match the Pallas backward (and therefore the reference)."""
    q, k, v = qkv

    def grads():
        return jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(
                q, k, v, causal=True, block_size=32) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    # an ambient FLASH_BWD=xla would make this a vacuous self-comparison
    monkeypatch.delenv("FLASH_BWD", raising=False)
    jax.clear_caches()
    g_pallas = grads()
    monkeypatch.setenv("FLASH_BWD", "xla")
    jax.clear_caches()  # the env var is read at trace time
    g_xla = grads()
    monkeypatch.delenv("FLASH_BWD")
    jax.clear_caches()
    for a, b in zip(g_pallas, g_xla):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_flash_backward_bf16(qkv):
    """bf16 inputs: grads come back bf16 with f32 accumulation inside."""
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_size=32).astype(jnp.float32) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=True) ** 2), argnums=(0, 1, 2))(
        q, k, v)
    for g, r in zip(grads, ref):
        assert g.dtype == jnp.bfloat16
        scale = float(jnp.abs(r).max())
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(r, np.float32),
                                   atol=0.05 * max(scale, 1.0))


def test_padding_mask_blockwise(qkv):
    q, k, v = qkv
    keep = jnp.arange(S) < S // 2  # mask out the second half of kv
    mask = jnp.broadcast_to(keep[None, None, None, :], (B, 1, S, S))
    ref = dot_product_attention(q, k, v, mask=mask)
    blk = blockwise_attention(q, k, v, mask=mask, block_size=16)
    np.testing.assert_allclose(ref, blk, atol=2e-5)
    # masked-out kv must not influence the output
    k2 = k.at[:, S // 2 :].set(123.0)
    v2 = v.at[:, S // 2 :].set(-7.0)
    ref2 = dot_product_attention(q, k2, v2, mask=mask)
    np.testing.assert_allclose(ref, ref2, atol=2e-5)


def test_fully_masked_rows_zero_not_nan():
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 8, 1, 8)), jnp.float32)
        for _ in range(3)
    )
    mask = jnp.zeros((1, 1, 8, 8), bool)
    out = blockwise_attention(q, k, v, mask=mask, block_size=4)
    assert not bool(jnp.isnan(out).any())
    np.testing.assert_allclose(out, jnp.zeros_like(out), atol=1e-6)


class TestFlashDispatch:
    """Auto-dispatch policy + explicit-path input validation
    (VERDICT.md round-3 weak #4)."""

    def test_degraded_block_raises_on_tpu_path(self, qkv):
        # seq 1000: gcd(1000, 512) = 8 — a pathological Mosaic tile; the
        # compiled (non-interpret) path must refuse, not degrade
        rng = np.random.default_rng(1)
        q, k, v = (
            jnp.asarray(rng.standard_normal((1, 1000, 2, 64)), jnp.float32)
            for _ in range(3)
        )
        with pytest.raises(ValueError, match="128"):
            flash_attention(q, k, v, interpret=False)

    def test_interpret_mode_small_blocks_still_allowed(self, qkv):
        # CI shapes run sub-128 blocks in the CPU interpreter by design
        q, k, v = qkv
        out = flash_attention(q, k, v, block_size=16)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_auto_threshold_follows_measurements(self, monkeypatch):
        from pytorch_ddp_template_tpu.ops import attention as A

        monkeypatch.setattr(A.jax, "default_backend", lambda: "tpu")
        short = jnp.zeros((1, 512, 8, 64))
        long = jnp.zeros((1, 1024, 8, 64))
        odd = jnp.zeros((1, 1000, 8, 64))
        cross_kv = jnp.zeros((1, 250, 8, 64))
        assert A._pick_impl("auto", short, short) == "xla"  # unmeasured
        assert A._pick_impl("auto", long, long) == "flash"  # recorded win
        assert A._pick_impl("auto", odd, odd) == "xla"  # unaligned seq
        # cross-attention with a kv length the kernel would refuse: auto
        # must route to XLA, not pick a path that raises
        assert A._pick_impl("auto", long, cross_kv) == "xla"
        assert A._pick_impl("flash", short, short) == "flash"  # explicit


def test_flash_disable_env_forces_xla(monkeypatch):
    """FLASH_DISABLE=1 (trace-time) must force the XLA path out of auto
    dispatch even on a TPU backend — the ablation/kill-switch knob."""
    from pytorch_ddp_template_tpu.ops.attention import _pick_impl

    q = jnp.zeros((1, 2048, 2, 64))
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert _pick_impl("auto", q, q) == "flash"
    monkeypatch.setenv("FLASH_DISABLE", "1")
    assert _pick_impl("auto", q, q) == "xla"
    # explicit impl choices are not overridden — only auto dispatch
    assert _pick_impl("blockwise", q, q) == "blockwise"
