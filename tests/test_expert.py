"""Expert-parallel mechanism proof: all_to_all top-1 dispatch over the
``expert`` mesh axis must equal dense per-token expert application, with
production capacity semantics (overflow → dropped to zero)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.parallel.expert import (
    expert_apply,
    stack_expert_params,
)
from pytorch_ddp_template_tpu.runtime import make_mesh

D = 8


def expert_fn(w, x):
    return jnp.tanh(x @ w["kernel"]) * w["scale"]


def make_expert(rng):
    kw, ks = jax.random.split(rng)
    return {"kernel": jax.random.normal(kw, (D, D)) * 0.5,
            "scale": 1.0 + jax.random.uniform(ks, (D,))}


def routed_input(n_tokens, n_experts, rng):
    """Tokens whose top-1 route is known: strong spike at coord t % E."""
    x = jax.random.normal(rng, (n_tokens, D)) * 0.01
    dest = np.arange(n_tokens) % n_experts
    x = x.at[np.arange(n_tokens), dest].add(3.0)
    return x, dest


@pytest.mark.parametrize("n_experts,n_tokens", [(2, 8), (4, 16)])
def test_matches_dense_routing(n_experts, n_tokens):
    mesh = make_mesh(f"expert:{n_experts}", jax.devices()[:n_experts])
    rngs = jax.random.split(jax.random.PRNGKey(0), n_experts + 1)
    experts = [make_expert(rngs[i]) for i in range(n_experts)]
    gate_w = jnp.eye(D)[:, :n_experts]  # argmax of first E coords
    x, dest = routed_input(n_tokens, n_experts, rngs[-1])

    params = stack_expert_params(experts, mesh)
    got = expert_apply(params, expert_fn, gate_w, x, mesh)

    want = np.stack([
        np.asarray(expert_fn(experts[int(dest[t])], x[t][None])[0])
        for t in range(n_tokens)
    ])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_capacity_overflow_drops_to_zero():
    """All of one rank's tokens route to expert 0; capacity 1 keeps only
    the first, the rest emit zeros (the residual-stream convention)."""
    n_experts, local = 2, 4
    mesh = make_mesh(f"expert:{n_experts}", jax.devices()[:n_experts])
    rngs = jax.random.split(jax.random.PRNGKey(1), 3)
    experts = [make_expert(rngs[i]) for i in range(n_experts)]
    gate_w = jnp.eye(D)[:, :n_experts]
    x = jax.random.normal(rngs[-1], (n_experts * local, D)) * 0.01
    x = x.at[:, 0].add(3.0)  # every token → expert 0

    params = stack_expert_params(experts, mesh)
    got = np.asarray(expert_apply(params, expert_fn, gate_w, x, mesh,
                                  capacity=1))
    # per source rank: first token kept, remaining three dropped
    for r in range(n_experts):
        blk = got[r * local:(r + 1) * local]
        want_first = np.asarray(expert_fn(experts[0], x[r * local][None])[0])
        np.testing.assert_allclose(blk[0], want_first, rtol=1e-5, atol=1e-6)
        assert (blk[1:] == 0).all()


def test_expert_count_mismatch_refused():
    mesh = make_mesh("expert:2", jax.devices()[:2])
    rngs = jax.random.split(jax.random.PRNGKey(2), 4)
    experts = [make_expert(rngs[i]) for i in range(4)]
    params = jax.tree.map(lambda *xs: jnp.stack(xs), *experts)
    x = jnp.zeros((8, D))
    with pytest.raises(ValueError, match="expert axis"):
        expert_apply(params, expert_fn, jnp.eye(D)[:, :2], x, mesh)


def test_gradients_flow_through_dispatch():
    """Reverse-mode AD through pack → all_to_all → expert → all_to_all →
    unpack must reproduce dense per-token expert gradients — the MoE
    mechanism is trainable, not just a fwd proof. (The argmax router is
    non-differentiable by construction, as in production top-k MoE.)"""
    n_experts = 2
    mesh = make_mesh(f"expert:{n_experts}", jax.devices()[:n_experts])
    rngs = jax.random.split(jax.random.PRNGKey(3), n_experts + 1)
    experts = [make_expert(rngs[i]) for i in range(n_experts)]
    gate_w = jnp.eye(D)[:, :n_experts]
    x, dest = routed_input(8, n_experts, rngs[-1])

    def loss_moe(params):
        return jnp.sum(expert_apply(params, expert_fn, gate_w, x, mesh) ** 2)

    def loss_dense(exp_list):
        ys = [expert_fn(exp_list[int(dest[t])], x[t][None])[0]
              for t in range(8)]
        return jnp.sum(jnp.stack(ys) ** 2)

    g_moe = jax.grad(loss_moe)(stack_expert_params(experts, mesh))
    g_dense = jax.grad(loss_dense)(experts)
    for i in range(n_experts):
        for key in ("kernel", "scale"):
            np.testing.assert_allclose(
                np.asarray(g_moe[key][i]), np.asarray(g_dense[i][key]),
                rtol=1e-5, atol=1e-6,
            )
