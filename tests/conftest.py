"""Test harness: 8 virtual CPU devices, the JAX answer to "test collectives
without a cluster" (SURVEY.md §4). Must run before the first jax import."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Some plugin platforms (e.g. the axon TPU tunnel) ignore the JAX_PLATFORMS
# env var — force CPU through the config API as well.
jax.config.update("jax_platforms", "cpu")
try:  # jax >= 0.5 spelling; XLA_FLAGS above covers driver environments
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:  # noqa: BLE001 - older jax: XLA_FLAGS alone applies
    pass
# Sharding-invariant PRNG, matching runtime.init(): set ONCE for the whole
# suite so a test that happens to run init() first cannot flip every later
# test's random streams mid-process (see runtime/context.py for the
# GSPMD-partitioned-threefry drift this fixes).
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402

# Build the native host runtime (plain g++; ~1s). Tests that need it
# skip with a reason if the build fails.
_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import subprocess  # noqa: E402

# unconditional: make no-ops when the .so is newer than native.cc, and
# rebuilds after source edits (a stale-binary guard, not just a bootstrap)
subprocess.run(["make", "-C", os.path.join(_root, "native")],
               capture_output=True, check=False)


def pytest_configure(config):
    # tier-1 (ROADMAP.md) runs `-m 'not slow'` under a hard 870s budget;
    # `slow` marks the heavy long-tail (deep parity sweeps, multi-subprocess
    # CLI compositions) that the full `pytest tests/` run still covers
    config.addinivalue_line(
        "markers", "slow: excluded from the budgeted tier-1 run"
    )


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
