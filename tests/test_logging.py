"""Unit tests for the structured process-aware logger (SURVEY.md §4:
"logger formatting utils.py:12-31" is a named test seam)."""

import io
import logging
import warnings

from pytorch_ddp_template_tpu.utils import logging as tlog


def make_logger(name):
    log = logging.getLogger(name)
    log.handlers.clear()
    log.propagate = False
    log.setLevel(logging.INFO)
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(tlog.StructuredFormatter(tlog.LOG_FORMAT))
    handler.addFilter(tlog.ProcessInfoFilter())
    handler.addFilter(tlog.MainProcessLevelFilter())
    log.addHandler(handler)
    return log, stream


def test_structured_kv_pairs_appended():
    log, stream = make_logger("t.kv")
    log.info("training", {"lr": 0.001, "step": 7})
    out = stream.getvalue()
    assert "training" in out
    assert "[lr=0.001]" in out
    assert "[step=7]" in out


def test_plain_message_untouched():
    log, stream = make_logger("t.plain")
    log.info("hello %d", 42)
    assert "hello 42" in stream.getvalue()


def test_process_fields_injected():
    log, stream = make_logger("t.rank")
    log.info("x")
    assert "[host=0/1]" in stream.getvalue()


def test_millisecond_timestamp():
    log, stream = make_logger("t.ts")
    log.info("x")
    first = stream.getvalue().split(" - ")[0]
    # e.g. 2026-07-29 10:00:00.123 — ms suffix present
    assert len(first.rsplit(".", 1)[-1]) == 3


def test_warning_redirection():
    log, stream = make_logger("t.warn")
    tlog.redirect_warnings_to_logger(log)
    try:
        warnings.warn("careful now", UserWarning)
    finally:
        warnings.showwarning = warnings.__dict__.get("_original_showwarning", warnings.showwarning)
    assert "careful now" in stream.getvalue()


def test_get_logger_idempotent_handlers():
    a = tlog.get_logger("t.same")
    b = tlog.get_logger("t.same")
    assert a is b
    assert len(a.handlers) == 1


def test_main_process_gate_passes_warning_always(monkeypatch):
    log, stream = make_logger("t.gate")
    monkeypatch.setattr("pytorch_ddp_template_tpu.utils.dist.process_index", lambda: 3)
    log.info("should be dropped")
    log.warning("should appear")
    out = stream.getvalue()
    assert "should be dropped" not in out
    assert "should appear" in out
