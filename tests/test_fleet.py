"""Round-14 fleet watchtower: obs/fleet.py (cross-host aggregation +
straggler verdict), obs/server.py (/status + /metrics + /healthz,
Prometheus text format), obs/regression.py (perf_baseline.json
restore-compare tripwire), tools/bench_diff.py, and the engine wiring —
the straggler-trigger → sentry-bundle path, the live endpoint during a
real ``Trainer.train()``, the unconditional describe.json snapshot, and
the metrics.jsonl ``schema_version`` stamp."""

import json
import sys
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from pytorch_ddp_template_tpu.obs.fleet import (
    FLEET_WIRE_KEYS,
    FleetMonitor,
    decode_rows,
    encode_window,
)
from pytorch_ddp_template_tpu.obs.regression import (
    PerfBaseline,
    compare_fingerprints,
    config_signature,
    make_fingerprint,
)
from pytorch_ddp_template_tpu.obs.sentry import AnomalySentry
from pytorch_ddp_template_tpu.obs.server import (
    StatusServer,
    prom_escape,
    prom_name,
    prometheus_lines,
)

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))
import bench_diff  # noqa: E402


def window(step=10, wall=5.0, **over):
    w = {k: 0.0 for k in FLEET_WIRE_KEYS}
    w.update(step=float(step), step_wall_ms=wall, frac_host=0.1,
             frac_input=0.05, frac_device=0.85, input_wait_ms=0.2,
             producer_idle_ms=3.0, gp_productive_s=1.0, gp_wall_s=1.1)
    w.update(over)
    return w


def fake_fleet(walls):
    """A faked multi-host exchange: every call returns one row per
    entry of ``walls``, this host's vector with step_wall_ms rewritten."""
    wall_i = FLEET_WIRE_KEYS.index("step_wall_ms")

    def exchange(vec):
        rows = np.stack([vec] * len(walls))
        for i, w in enumerate(walls):
            rows[i, wall_i] = w
        return rows

    return exchange


# -- wire codec ------------------------------------------------------------

class TestWireCodec:
    def test_roundtrip(self):
        w = window(step=7, wall=12.5, anomaly=1.0)
        rows = decode_rows(encode_window(w)[None, :])
        assert len(rows) == 1
        assert rows[0]["host"] == 0
        for k in FLEET_WIRE_KEYS:
            assert rows[0][k] == pytest.approx(w[k], rel=1e-6), k

    def test_missing_keys_ship_as_zero(self):
        vec = encode_window({"step_wall_ms": 3.0})
        rec = decode_rows(vec[None, :])[0]
        assert rec["step_wall_ms"] == pytest.approx(3.0)
        assert rec["frac_input"] == 0.0

    def test_short_rows_zero_fill(self):
        # an older peer shipping fewer columns must not misalign
        rows = decode_rows(np.ones((2, 3), np.float32))
        assert rows[1]["step"] == 1.0
        assert rows[1][FLEET_WIRE_KEYS[-1]] == 0.0

    def test_r15_mem_keys_appended_at_the_end(self):
        """The version seam, pinned (r15 satellite, r16 append): the
        memory columns and the r16 pipeline-bubble column were APPENDED
        to FLEET_WIRE_KEYS — prefix order is frozen, so an old peer's
        rows still align."""
        assert FLEET_WIRE_KEYS[:10] == (
            "step", "step_wall_ms", "frac_input", "frac_device",
            "frac_host", "input_wait_ms", "producer_idle_ms",
            "gp_productive_s", "gp_wall_s", "anomaly")
        assert FLEET_WIRE_KEYS[10:] == ("mem_bytes_in_use",
                                        "mem_frac_of_limit",
                                        "bubble_frac")

    def test_old_width_row_zero_fills_new_mem_keys(self):
        """The documented zero-fill/extra-column tolerance, exercised
        against a REAL old-width row (the r14 wire was 10 columns — a
        mixed-version fleet mid-rolling-upgrade ships exactly this), not
        just trusted from the comment."""
        OLD_WIDTH = 10  # the r14 vector: everything before the mem keys
        old_row = np.arange(1, OLD_WIDTH + 1, dtype=np.float32)
        new_row = encode_window(window(step=2, wall=7.0,
                                       mem_bytes_in_use=5e8,
                                       mem_frac_of_limit=0.5))
        # r15 appended the two mem columns, r16 the bubble column
        assert new_row.shape[0] == OLD_WIDTH + 3
        # old peer's row next to this version's: pad like _default_exchange
        padded = np.zeros_like(new_row)
        padded[:OLD_WIDTH] = old_row
        rows = decode_rows(np.stack([padded, new_row]))
        # the old peer's r14 columns land intact...
        assert rows[0]["step"] == 1.0
        assert rows[0]["step_wall_ms"] == 2.0
        assert rows[0]["anomaly"] == 10.0
        # ...its missing mem columns read zero (degrade, not misalign)...
        assert rows[0]["mem_bytes_in_use"] == 0.0
        assert rows[0]["mem_frac_of_limit"] == 0.0
        # ...and this version's row keeps its mem data
        assert rows[1]["mem_bytes_in_use"] == 5e8
        assert rows[1]["mem_frac_of_limit"] == 0.5
        # extra columns from a NEWER peer are ignored (the other side
        # of the same seam)
        wider = np.concatenate([new_row, [42.0, 43.0]]).astype(np.float32)
        rec = decode_rows(wider[None, :])[0]
        assert set(rec) == {"host", *FLEET_WIRE_KEYS}


# -- aggregation -----------------------------------------------------------

class TestAggregation:
    def test_min_median_max_per_signal(self):
        mon = FleetMonitor()
        hosts = decode_rows(np.stack([
            encode_window(window(wall=w)) for w in (4.0, 10.0, 6.0)]))
        table = mon.aggregate(hosts, step=20)
        sig = table["signals"]["step_wall_ms"]
        assert sig["min"] == pytest.approx(4.0)
        assert sig["median"] == pytest.approx(6.0)
        assert sig["max"] == pytest.approx(10.0)
        assert table["n_hosts"] == 3
        assert [h["host"] for h in table["hosts"]] == [0, 1, 2]

    def test_anomaly_hosts_named(self):
        mon = FleetMonitor()
        hosts = [dict(window(), host=0.0),
                 dict(window(anomaly=1.0), host=1.0)]
        table = mon.aggregate(hosts)
        assert table["anomaly_hosts"] == [1]


# -- straggler detection ---------------------------------------------------

class TestStragglerVerdict:
    def observe_n(self, mon, walls, n, start=0):
        mon._exchange = fake_fleet(walls)
        for i in range(n):
            mon.observe(start + i, window())

    def test_needs_k_consecutive_windows(self):
        fired = []
        mon = FleetMonitor(threshold=0.25, windows=3,
                           on_straggler=lambda s, v: fired.append((s, v)))
        self.observe_n(mon, [5.0, 5.0, 9.0], 2)
        assert fired == []  # two suspect windows < K=3
        self.observe_n(mon, [5.0, 5.0, 9.0], 1, start=2)
        assert len(fired) == 1
        step, verdict = fired[0]
        assert verdict["host"] == 2
        assert verdict["consecutive_windows"] == 3
        assert verdict["excess_pct"] == pytest.approx(80.0)
        assert mon.latest_table["straggler"] == verdict

    def test_recovery_resets_and_rearms(self):
        fired = []
        mon = FleetMonitor(threshold=0.25, windows=2,
                           on_straggler=lambda s, v: fired.append(v))
        self.observe_n(mon, [5.0, 5.0, 9.0], 2)
        assert len(fired) == 1
        # still slow: flagged hosts do NOT re-fire every window
        self.observe_n(mon, [5.0, 5.0, 9.0], 3, start=2)
        assert len(fired) == 1
        # recovers, then degrades again: a NEW episode, a new verdict
        self.observe_n(mon, [5.0, 5.0, 5.0], 1, start=5)
        self.observe_n(mon, [5.0, 5.0, 9.0], 2, start=6)
        assert len(fired) == 2

    def test_headline_persists_for_the_whole_episode(self):
        """The table's straggler slot must stay set on every window of
        an ongoing degradation (scrapers alert on it), not only the
        confirmation window — and clear on recovery."""
        fired = []
        mon = FleetMonitor(threshold=0.25, windows=2,
                           on_straggler=lambda s, v: fired.append(v))
        self.observe_n(mon, [5.0, 5.0, 9.0], 5)
        assert len(fired) == 1  # one verdict per episode...
        strag = mon.latest_table["straggler"]
        assert strag is not None  # ...but the headline stays up
        assert strag["host"] == 2
        assert strag["consecutive_windows"] == 5
        self.observe_n(mon, [5.0, 5.0, 5.0], 1, start=5)
        assert mon.latest_table["straggler"] is None  # recovered

    def test_two_stragglers_both_named(self):
        """A degraded switch can sicken two hosts at once: BOTH get a
        verdict (naming only the slowest would suppress the other for
        its whole episode); the table headline carries the slowest."""
        fired = []
        mon = FleetMonitor(threshold=0.25, windows=2,
                           on_straggler=lambda s, v: fired.append(v))
        self.observe_n(mon, [5.0, 5.0, 9.0, 12.0], 2)
        assert sorted(v["host"] for v in fired) == [2, 3]
        assert mon.latest_table["straggler"]["host"] == 3  # slowest

    def test_interrupted_streak_never_fires(self):
        fired = []
        mon = FleetMonitor(threshold=0.25, windows=3,
                           on_straggler=lambda s, v: fired.append(v))
        for _ in range(4):  # slow-slow-fast forever: never 3 in a row
            self.observe_n(mon, [5.0, 5.0, 9.0], 2)
            self.observe_n(mon, [5.0, 5.0, 5.0], 1)
        assert fired == []

    def test_small_fleet_never_fires(self):
        # with 2 hosts the median straddles both; a slow pair would
        # blame an innocent — the verdict needs >= 3 hosts
        fired = []
        mon = FleetMonitor(threshold=0.1, windows=1,
                           on_straggler=lambda s, v: fired.append(v))
        self.observe_n(mon, [5.0, 50.0], 4)
        assert fired == []
        assert mon.latest_table["n_hosts"] == 2

    def test_exchange_failure_degrades_to_local(self):
        mon = FleetMonitor()

        def broken(vec):
            raise RuntimeError("DCN down")

        mon._exchange = broken
        mon.observe(1, window())
        assert mon.latest_table["n_hosts"] == 1
        assert mon.state()["degraded_to_local"] is True

    def test_observe_never_raises(self):
        mon = FleetMonitor()
        mon.on_straggler = lambda s, v: 1 / 0  # a broken consumer
        mon._exchange = fake_fleet([1.0, 1.0, 99.0])
        mon.observe(0, window())  # must not raise (drain-thread contract)

    def test_constructor_validation(self):
        with pytest.raises(ValueError):
            FleetMonitor(threshold=0.0)
        with pytest.raises(ValueError):
            FleetMonitor(windows=0)


# -- sentry external trigger -----------------------------------------------

class TestExternalTrigger:
    def test_straggler_kind_delivered_once(self):
        s = AnomalySentry("warn")
        s.external_trigger(12, ["host 2 slow"], kind="straggler",
                           scalars={"host": 2})
        trig = s.poll_trigger()
        assert trig["kind"] == "straggler"
        assert trig["step"] == 12
        assert trig["scalars"]["host"] == 2
        assert s.poll_trigger() is None  # exactly-once
        # first-trigger-wins: a later health trigger does not clobber
        s.external_trigger(13, ["again"], kind="straggler")
        assert s.poll_trigger() is None

    def test_health_trigger_carries_anomaly_kind(self):
        s = AnomalySentry("warn")
        s.observe(5, {"loss": float("nan")})
        assert s.poll_trigger()["kind"] == "anomaly"

    def test_state_snapshot(self):
        s = AnomalySentry("halt", window=16)
        s.observe(1, {"loss": 1.0})
        st = s.state()
        assert st == {"mode": "halt", "triggered": False,
                      "trigger": None, "ring_len": 1}
        s.external_trigger(2, ["x"], kind="straggler")
        assert s.state()["triggered"] is True
        assert s.state()["trigger"]["kind"] == "straggler"


# -- prometheus rendering --------------------------------------------------

class TestPrometheus:
    def test_escaping(self):
        assert prom_escape('a"b') == 'a\\"b'
        assert prom_escape("a\\b") == "a\\\\b"
        assert prom_escape("a\nb") == "a\\nb"

    def test_name_sanitised(self):
        assert prom_name("step_time_p50_ms") == "tpuddp_step_time_p50_ms"
        assert prom_name("weird-key.50%") == "tpuddp_weird_key_50_"
        assert prom_name("9lives")[len("tpuddp_"):][0] == "_"

    def snapshot(self):
        return {
            "host": 0, "step": 40, "age_s": 1.5,
            "records": {"progress": {
                "loss": 1.25, "steps_per_sec": 10.0,
                "per_layer_grad_norm": [1.0, 2.0],  # vector: skipped
                "loss_repr": "nan",                  # repr: skipped
                "bad": None}},
            "goodput": {"goodput": 0.9,
                        "buckets_s": {"compile": 3.0, "halted": 0.5}},
            "sentry": {"triggered": True},
            "fleet": {"table": {
                "hosts": [{"host": 0, "step_wall_ms": 5.0},
                          {"host": 1, "step_wall_ms": 9.0}],
                "straggler": {"host": 1}}},
        }

    def test_rendering(self):
        text = prometheus_lines(self.snapshot())
        assert "tpuddp_step{host=\"0\"} 40" in text
        assert "tpuddp_loss{host=\"0\"} 1.25" in text
        assert "# TYPE tpuddp_loss gauge" in text
        assert 'tpuddp_goodput_seconds_total{host="0",bucket="compile"} 3.0' \
            in text
        assert "tpuddp_anomaly_triggered" in text
        assert 'tpuddp_fleet_step_wall_ms{host="1"} 9.0' in text
        assert 'tpuddp_fleet_straggler{host="1"} 1.0' in text
        assert "per_layer_grad_norm" not in text  # vectors skipped
        assert "_repr" not in text
        # every sample line parses as `name{labels} float`
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            name, _, value = line.rpartition(" ")
            float(value)
            assert name.startswith("tpuddp_")

    def test_duplicate_samples_deduped(self):
        """perf_* fields can appear in BOTH the progress record and an
        off-cadence perf record; a duplicate (name, labels) sample makes
        the whole exposition invalid to Prometheus — first wins."""
        snap = self.snapshot()
        snap["records"]["progress"]["perf_mfu"] = 0.4
        snap["records"]["perf"] = {"perf_mfu": 0.39, "perf_step_ms": 2.0}
        text = prometheus_lines(snap)
        mfu_lines = [l for l in text.splitlines()
                     if l.startswith("tpuddp_perf_mfu{")]
        assert mfu_lines == ['tpuddp_perf_mfu{host="0"} 0.4']
        assert 'tpuddp_perf_step_ms{host="0"} 2.0' in text

    def test_non_finite_values_skipped(self):
        snap = self.snapshot()
        snap["records"]["progress"]["loss"] = float("nan")
        text = prometheus_lines(snap)
        assert "tpuddp_loss" not in text
        assert "nan" not in text.lower().replace("tpuddp", "")


# -- status server (no engine, no jax) -------------------------------------

def _get(port, route):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{route}",
                                timeout=5) as r:
        return r.status, r.read().decode()


class TestStatusServer:
    def test_serves_all_routes(self):
        srv = StatusServer(0, host="127.0.0.1")  # ephemeral port
        srv.set_static("describe", {"mesh": {"data": 8}})
        srv.sources["goodput"] = lambda: {"goodput": 0.5,
                                          "buckets_s": {"compile": 1.0}}
        srv.start()
        try:
            srv.note_record("progress", 12, {"loss": 0.5})
            code, body = _get(srv.port, "/status")
            assert code == 200
            snap = json.loads(body)
            assert snap["step"] == 12
            assert snap["records"]["progress"]["loss"] == 0.5
            assert snap["describe"]["mesh"] == {"data": 8}
            assert snap["goodput"]["goodput"] == 0.5
            code, body = _get(srv.port, "/healthz")
            assert code == 200 and json.loads(body)["ok"] is True
            code, body = _get(srv.port, "/metrics")
            assert code == 200
            assert "tpuddp_loss" in body and "tpuddp_goodput_ratio" in body
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.port, "/nope")
            assert e.value.code == 404
        finally:
            srv.close()
        srv.close()  # idempotent

    def test_broken_source_does_not_kill_endpoint(self):
        srv = StatusServer(0, host="127.0.0.1")
        srv.sources["bad"] = lambda: 1 / 0
        srv.start()
        try:
            code, body = _get(srv.port, "/status")
            assert code == 200
            assert json.loads(body)["bad"] == {"error": "source failed"}
        finally:
            srv.close()


# -- perf baseline / regression tripwire -----------------------------------

class TestRegression:
    def fp(self, p50=10.0, mfu=0.4, attempt=1, sig=None):
        return make_fingerprint(
            timer_summary={"step_time_p50_ms": p50,
                           "step_time_p90_ms": p50 * 1.2,
                           "step_time_mean_ms": p50 * 1.05},
            mfu=mfu, wire_bytes_total=1000, frac_host=0.1,
            steps=100, attempt=attempt, config_sig=sig)

    def test_in_band_is_silent(self):
        assert compare_fingerprints(self.fp(), self.fp(p50=11.0),
                                    threshold_pct=20.0) == []

    def test_slower_step_wall_warns_with_delta(self):
        warns = compare_fingerprints(self.fp(p50=10.0),
                                     self.fp(p50=14.0),
                                     threshold_pct=20.0)
        assert any("step_time_p50_ms" in w and "+40.0%" in w
                   for w in warns)

    def test_faster_is_never_a_regression(self):
        assert compare_fingerprints(self.fp(p50=10.0), self.fp(p50=5.0),
                                    threshold_pct=20.0) == []

    def test_lower_mfu_warns_higher_does_not(self):
        assert any("mfu" in w for w in compare_fingerprints(
            self.fp(mfu=0.4), self.fp(mfu=0.2), threshold_pct=20.0))
        assert compare_fingerprints(
            self.fp(mfu=0.2), self.fp(mfu=0.4), threshold_pct=20.0) == []

    def test_missing_signals_skipped(self):
        prior = self.fp()
        current = {k: v for k, v in self.fp(p50=99.0).items()
                   if not k.startswith("step_time")}
        warns = compare_fingerprints(prior, current, threshold_pct=20.0)
        assert not any("step_time" in w for w in warns)

    def test_config_change_named_in_warning(self):
        a = self.fp(p50=10.0, sig={"mesh": "data:8", "model": "mlp"})
        b = self.fp(p50=20.0, sig={"mesh": "data:4", "model": "mlp"})
        warns = compare_fingerprints(a, b, threshold_pct=20.0)
        assert any("config changed" in w and "data:8" in w for w in warns)

    def test_baseline_write_load_history(self, tmp_path):
        b1 = PerfBaseline(tmp_path)
        assert b1.prior is None
        b1.write(self.fp(p50=10.0, attempt=1))
        b2 = PerfBaseline(tmp_path)
        assert b2.prior["step_time_p50_ms"] == pytest.approx(10.0)
        assert b2.compare(self.fp(p50=20.0))  # out of band -> warns
        assert b2.compare(self.fp(p50=10.5)) == []
        b2.write(self.fp(p50=11.0, attempt=2))
        doc = json.loads((tmp_path / "perf_baseline.json").read_text())
        assert doc["fingerprint"]["attempt"] == 2
        assert len(doc["history"]) == 1
        assert doc["history"][0]["attempt"] == 1

    def test_corrupt_baseline_starts_fresh(self, tmp_path):
        (tmp_path / "perf_baseline.json").write_text("{nope")
        b = PerfBaseline(tmp_path)  # must not raise
        assert b.prior is None
        assert b.compare(self.fp()) == []

    def test_config_signature_fields(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig

        sig = config_signature(TrainingConfig(mesh="data:4"), n_devices=4)
        assert sig["mesh"] == "data:4"
        assert sig["n_devices"] == 4
        assert "model" in sig and "scan_layers" in sig


# -- tools/bench_diff.py ---------------------------------------------------

class TestBenchDiff:
    def write(self, path, rows):
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")

    def test_identical_passes(self, tmp_path, capsys):
        a = tmp_path / "a.jsonl"
        self.write(a, [{"metric": "m", "value": 2.0, "unit": "x"}])
        assert bench_diff.main([str(a), str(a)]) == 0
        assert "ok" in capsys.readouterr().out

    def test_slowed_record_drifts(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self.write(a, [{"metric": "m", "value": 2.0}])
        self.write(b, [{"metric": "m", "value": 1.0}])
        assert bench_diff.main([str(a), str(b)]) == 1
        out = capsys.readouterr()
        assert "DRIFT" in out.out and "m" in out.err

    def test_improvement_passes(self, tmp_path):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self.write(a, [{"metric": "m", "value": 2.0}])
        self.write(b, [{"metric": "m", "value": 4.0}])
        assert bench_diff.main([str(a), str(b)]) == 0

    def test_github_format(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self.write(a, [{"metric": "m", "value": 2.0}])
        self.write(b, [{"metric": "m", "value": 1.0}])
        bench_diff.main([str(a), str(b), "--format", "github"])
        out = capsys.readouterr().out
        assert "| metric | base | new | ratio | status |" in out
        assert "| `m` |" in out and "DRIFT" in out

    def test_no_overlap_is_not_a_pass(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self.write(a, [{"metric": "m1", "value": 2.0}])
        self.write(b, [{"metric": "m2", "value": 2.0}])
        assert bench_diff.main([str(a), str(b)]) == 2

    def test_ablation_and_error_rows_skipped(self, tmp_path):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        self.write(a, [{"metric": "m", "value": 5.0, "remat": True},
                       {"metric": "m", "value": 2.0},
                       {"metric": "m", "value": 0.0, "error": "boom"}])
        self.write(b, [{"metric": "m", "value": 2.0}])
        # the ablation 5.0 must not define the bar: 2.0 vs 2.0 passes
        assert bench_diff.main([str(a), str(b)]) == 0

    def test_directories_merge(self, tmp_path):
        d1, d2 = tmp_path / "d1", tmp_path / "d2"
        d1.mkdir(), d2.mkdir()
        self.write(d1 / "x.jsonl", [{"metric": "m", "value": 2.0}])
        self.write(d1 / "y.jsonl", [{"metric": "m", "value": 3.0}])
        self.write(d2 / "z.jsonl", [{"metric": "m", "value": 2.9}])
        # best-of-side: 3.0 vs 2.9 — in band
        assert bench_diff.main([str(d1), str(d2)]) == 0

    def test_ablation_keys_pinned_to_bench(self):
        import bench

        assert tuple(bench_diff.ABLATION_KEYS) == tuple(bench.ABLATION_KEYS)


# -- engine integration ----------------------------------------------------

def make_trainer(out_dir, **overrides):
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(**{
        "model": "mlp", "mesh": "data:8",
        "per_device_train_batch_size": 4, "dataset_size": 512,
        "max_steps": 8, "logging_steps": 4, "save_steps": 0,
        "resume": False, "warmup_steps": 0, "max_grad_norm": 1000.0,
        "output_dir": str(out_dir), **overrides})
    ctx = rt_init(cfg)
    task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
    return Trainer(cfg, ctx, task, ds)


class TestEngineFleet:
    def test_status_endpoint_during_training(self, tmp_path):
        """Integration: /status + /metrics + /healthz answer DURING a
        real Trainer.train() run and the server dies with the run."""
        t = make_trainer(tmp_path, fleet=True, status_port=-1,
                         status_host="127.0.0.1",
                         max_steps=60, logging_steps=2)
        probes = {}

        def probe():
            while not probes.get("done"):
                if t.status is not None and t.status.port:
                    try:
                        for route in ("/status", "/metrics", "/healthz"):
                            code, body = _get(t.status.port, route)
                            probes[route] = (code, body)
                        if json.loads(probes["/status"][1])["step"] >= 2:
                            return
                    except Exception:  # noqa: BLE001 - retry next tick
                        pass
                time.sleep(0.02)

        th = threading.Thread(target=probe)
        th.start()
        try:
            t.train()
        finally:
            probes["done"] = True
            th.join(timeout=30)
        assert probes["/status"][0] == 200
        snap = json.loads(probes["/status"][1])
        assert snap["step"] >= 2
        assert "progress" in snap["records"]
        assert snap["describe"]["mesh"] == {"data": 8}
        assert snap["goodput"]["attempt"] >= 1
        assert (snap["fleet"]["table"] or {}).get("n_hosts") == 1
        assert probes["/healthz"][0] == 200
        assert "tpuddp_step" in probes["/metrics"][1]
        # the server died with the run (connection refused, not frozen)
        with pytest.raises(Exception):
            _get(t.status.port, "/healthz")

    def test_straggler_trigger_to_bundle_end_to_end(self, tmp_path):
        """A faked slow peer in the fleet feed must ride the sentry into
        a complete triage bundle whose trigger.json names the host —
        and warn mode must NOT stop the run."""
        from pytorch_ddp_template_tpu.obs.sentry import BUNDLE_FILES

        t = make_trainer(tmp_path, fleet=True, anomaly="warn",
                         max_steps=20, logging_steps=2,
                         straggler_windows=2)
        t.fleet._exchange = fake_fleet([5.0, 5.0, 42.0])
        state = t.train()
        assert int(state.step) == 20  # warn mode: the run completes
        bundles = sorted((tmp_path / "flight_records").glob("step_*"))
        assert len(bundles) == 1
        names = {p.name for p in bundles[0].iterdir()}
        assert set(BUNDLE_FILES) <= names
        trig = json.loads((bundles[0] / "trigger.json").read_text())
        assert trig["kind"] == "straggler"
        assert trig["scalars"]["host"] == 2
        assert trig["scalars"]["consecutive_windows"] == 2
        assert "host 2" in trig["reasons"][0]
        # satellite: the bundle records which host dumped and which host
        # owns the trace — the straggler verdict is fleet-replicated, so
        # only the NAMED host captures (this host defers: no profile/)
        assert trig["host"] == 0
        assert trig["trace_host"] == 2
        assert "profile" not in names

    def test_straggler_without_sentry_warns_only(self, tmp_path, monkeypatch):
        """--fleet with --anomaly off: the verdict logs a warning but
        produces no bundle (the sentry owns the triage machinery)."""
        from pytorch_ddp_template_tpu.train import engine

        warned = []
        monkeypatch.setattr(
            engine.log, "warning",
            lambda msg, *a: warned.append(str(msg)))
        t = make_trainer(tmp_path, fleet=True, anomaly="off",
                         max_steps=12, logging_steps=2,
                         straggler_windows=2)
        t.fleet._exchange = fake_fleet([5.0, 5.0, 42.0])
        t.train()
        assert any("straggler" in w for w in warned)
        assert not (tmp_path / "flight_records").exists()

    def test_describe_json_written_unconditionally(self, tmp_path):
        """Satellite: every run leaves the config+mesh+overlap snapshot
        in output_dir — not only flight bundles."""
        t = make_trainer(tmp_path)
        t.train()
        snap = json.loads((tmp_path / "describe.json").read_text())
        assert snap["mesh"] == {"data": 8}
        assert snap["config"]["model"] == "mlp"
        assert snap["attempt"] == 1
        assert "mesh" in snap["describe"]
        assert snap["config"]["per_device_train_batch_size"] == 4

    def test_metrics_schema_version_stamped(self, tmp_path):
        """Satellite: every metrics.jsonl record carries schema_version
        so bench_diff/external scrapers can evolve safely."""
        from pytorch_ddp_template_tpu.train.metrics import SCHEMA_VERSION

        t = make_trainer(tmp_path)
        t.train()
        recs = [json.loads(l) for l in
                (tmp_path / "metrics.jsonl").read_text().splitlines()]
        assert recs
        assert all(r["schema_version"] == SCHEMA_VERSION for r in recs)

    def test_perf_baseline_written_and_restore_compare_warns(
            self, tmp_path, monkeypatch):
        """The restore-compare path: attempt 1 writes
        perf_baseline.json; a tampered (much faster) baseline makes
        attempt 2 WARN with the regression delta."""
        from pytorch_ddp_template_tpu.train import engine

        t = make_trainer(tmp_path, max_steps=24, logging_steps=2)
        t.train()
        path = tmp_path / "perf_baseline.json"
        doc = json.loads(path.read_text())
        fp = doc["fingerprint"]
        assert fp["attempt"] == 1
        assert fp["step_time_p50_ms"] > 0
        assert "config_sig" in fp

        # tamper: claim the prior attempt was 100x faster
        for k in list(fp):
            if k.startswith("step_time"):
                fp[k] = fp[k] / 100.0
        path.write_text(json.dumps(doc))

        warned = []
        monkeypatch.setattr(
            engine.log, "warning",
            lambda msg, *a: warned.append(str(msg)))
        t2 = make_trainer(tmp_path, max_steps=24, logging_steps=2)
        t2.train()
        regs = [w for w in warned if "perf regression" in w]
        assert regs, "no regression warning on an out-of-band restart"
        assert "step_time_p50_ms" in " ".join(regs)
        # and attempt 2 rewrote the baseline with its own numbers
        doc2 = json.loads(path.read_text())
        assert doc2["fingerprint"]["step_time_p50_ms"] > fp["step_time_p50_ms"]
        assert doc2["history"], "prior fingerprint must be kept"

    def test_in_band_restart_is_silent(self, tmp_path, monkeypatch):
        from pytorch_ddp_template_tpu.train import engine

        t = make_trainer(tmp_path, max_steps=24, logging_steps=2)
        t.train()
        warned = []
        monkeypatch.setattr(
            engine.log, "warning",
            lambda msg, *a: warned.append(str(msg)))
        t2 = make_trainer(tmp_path, max_steps=24, logging_steps=2,
                          regression_pct=400.0)  # huge band: never out
        t2.train()
        assert not any("perf regression" in w for w in warned)


# -- config validation -----------------------------------------------------

class TestConfigValidation:
    def test_fleet_needs_a_cadence(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig

        with pytest.raises(ValueError, match="cadence"):
            TrainingConfig(fleet=True, logging_steps=0, perf_every=0)
        TrainingConfig(fleet=True, logging_steps=0, perf_every=5)  # ok

    def test_bounds(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig

        with pytest.raises(ValueError, match="status_port"):
            TrainingConfig(status_port=-2)
        TrainingConfig(status_port=-1)  # ephemeral sentinel: valid
        with pytest.raises(ValueError, match="straggler_threshold"):
            TrainingConfig(straggler_threshold=0)
        with pytest.raises(ValueError, match="straggler_windows"):
            TrainingConfig(straggler_windows=0)
        with pytest.raises(ValueError, match="regression_pct"):
            TrainingConfig(regression_pct=0)

    def test_cli_flags_parse(self):
        from pytorch_ddp_template_tpu.config import parse_args

        cfg = parse_args(["--fleet", "--status_port", "8090",
                          "--straggler_threshold", "0.5",
                          "--straggler_windows", "4",
                          "--regression_pct", "10"])
        assert cfg.fleet and cfg.status_port == 8090
        assert cfg.straggler_threshold == 0.5
        assert cfg.straggler_windows == 4
        assert cfg.regression_pct == 10.0
