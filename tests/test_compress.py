"""Compressed-DDP grad collectives (``--ddp_overlap`` / ``--grad_comm`` /
``--grad_error_feedback``, parallel/compress.py): the quantizers must be
bounded and unbiased, the compressed wire must reduce exactly (fp32) or
within quantization bounds (bf16/int8), the error-feedback residual must
telescope (sum of applied updates == sum of true gradients minus one final
residual), the overlapped scan must reproduce straight-line values and
grads, refusals must fail with intent, and checkpoints must round-trip the
residual forward AND backward compatibly."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.parallel.compress import (
    CHUNK,
    compressed_allreduce,
    ddp_overlap_scan,
    dequantize_int8,
    init_residual,
    padded_size,
    quantize_int8,
    stochastic_round_bf16,
    validate_ddp_mesh,
    wire_bytes_per_step,
)
from pytorch_ddp_template_tpu.runtime import make_mesh

#: same tolerance family as tests/test_overlap.py: observed fp32-path gap
#: vs the GSPMD baseline is reduction reassociation at the last f32 ulp
#: (~4e-9 on params, ~1e-7 on a token-mean loss); 1e-6 is pure headroom
TOL = 1e-6


def _max_abs_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# -- quantizer units -------------------------------------------------------

class TestQuantizers:
    def test_int8_roundtrip_error_bounded_per_bucket(self):
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (4, 2 * CHUNK)).astype(np.float32) * 3.0)
        q, scale = quantize_int8(x, jax.random.PRNGKey(1))
        back = dequantize_int8(q, scale)
        # stochastic rounding moves at most one quantum = one bucket scale
        err = jnp.abs(back.reshape(4, 2, CHUNK) - x.reshape(4, 2, CHUNK))
        assert float(jnp.max(err - scale.reshape(4, 2, 1))) <= 1e-6

    def test_int8_zero_bucket_stays_exact_zero(self):
        x = jnp.zeros((1, CHUNK))
        q, scale = quantize_int8(x, jax.random.PRNGKey(0))
        assert float(jnp.abs(dequantize_int8(q, scale)).max()) == 0.0

    def test_int8_stochastic_rounding_unbiased(self):
        """Mean over many independent rounding draws must converge to the
        true value (the satellite's unbiasedness pin): |bias| is held to a
        few standard errors of the quantum-sized per-draw noise."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((CHUNK,)).astype(np.float32))
        n_draws = 512
        keys = jax.random.split(jax.random.PRNGKey(3), n_draws)
        draws = jax.vmap(
            lambda k: dequantize_int8(*quantize_int8(x[None], k))[0])(keys)
        mean = np.asarray(jnp.mean(draws, axis=0))
        quantum = float(jnp.max(jnp.abs(x))) / 127.0
        # per-draw SR error is Bernoulli over one quantum: sd <= q/2
        bound = 4.0 * 0.5 * quantum / np.sqrt(n_draws)
        assert np.max(np.abs(mean - np.asarray(x))) < bound + 1e-7

    def test_bf16_stochastic_rounding_bounded_and_unbiased(self):
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((256,)).astype(np.float32))
        n_draws = 512
        keys = jax.random.split(jax.random.PRNGKey(5), n_draws)
        draws = jax.vmap(
            lambda k: stochastic_round_bf16(x, k).astype(jnp.float32))(keys)
        # each draw within one bf16 ulp (7 explicit mantissa bits ->
        # relative spacing up to 2^-7 just above a power of two)
        rel = jnp.max(jnp.abs(draws - x[None]) / jnp.abs(x)[None])
        assert float(rel) <= 2.0 ** -7 + 1e-6
        mean = np.asarray(jnp.mean(draws, axis=0))
        ulp = np.abs(np.asarray(x)) * 2.0 ** -7
        # per-draw SR error is Bernoulli over one ulp: sd <= ulp/2
        bound = 4.0 * 0.5 * ulp / np.sqrt(n_draws)
        assert np.max(np.abs(mean - np.asarray(x)) - bound) < 1e-7


# -- the wire --------------------------------------------------------------

def _partials(n, shape, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((n,) + shape).astype(np.float32)
                       * scale)


class TestCompressedAllreduce:
    def test_fp32_matches_dense_sum(self, devices):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        host = {"a": _partials(n, (300,), 0), "b": _partials(n, (3, 5), 1)}
        sharded = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P("data"))),
            host)
        out, res = compressed_allreduce(sharded, mesh, "fp32")
        assert res is None
        for k, v in host.items():
            want = np.asarray(v).sum(axis=0)
            got = np.asarray(out[k])
            for row in got:  # every replica row holds the identical sum
                np.testing.assert_allclose(row, want, atol=1e-5)

    @pytest.mark.parametrize("mode", ["bf16", "int8"])
    def test_lossy_modes_error_bounded(self, devices, mode):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        host = {"w": _partials(n, (2 * CHUNK,), 2)}
        sharded = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P("data"))),
            host)
        out, _ = compressed_allreduce(sharded, mesh, mode,
                                      rng=jax.random.PRNGKey(0))
        want = np.asarray(host["w"]).sum(axis=0)
        got = np.asarray(out["w"])[0]
        # n quantized contributions + one re-quantized sum: error is a
        # few quanta of the (absmax-sized) bucket scales
        scale = np.abs(np.asarray(host["w"])).max() / (
            127.0 if mode == "int8" else 256.0)
        bound = (n + 2) * scale * (2.0 if mode == "bf16" else 1.0)
        # bf16's "scale" is value-relative; use the sum's own magnitude
        if mode == "bf16":
            bound = (np.abs(np.asarray(host["w"])).sum(0).max()) * 2 ** -7
        assert np.max(np.abs(got - want)) < bound

    def test_error_feedback_telescopes_exactly(self, devices):
        """Sum of compressed outputs + every replica's final residual ==
        sum of true inputs (exact identity, satellite pin), and the
        cumulative EF error is strictly smaller than no-EF's random walk."""
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        F = 2 * CHUNK
        pad = padded_size(F, n)
        sh = NamedSharding(mesh, P("data"))
        residual = {"w": jax.device_put(jnp.zeros((n, pad)), sh)}
        steps, key = 20, jax.random.PRNGKey(7)
        # jit ONCE: a bare compressed_allreduce call builds a fresh
        # shard_map per invocation and would re-trace every step
        ef_call = jax.jit(lambda g, r, k: compressed_allreduce(
            g, mesh, "int8", rng=k, residual=r))
        ne_call = jax.jit(lambda g, k: compressed_allreduce(
            g, mesh, "int8", rng=k))
        total_true = np.zeros((F,), np.float64)
        total_ef = np.zeros((F,), np.float64)
        total_no_ef = np.zeros((F,), np.float64)
        for t in range(steps):
            g = {"w": jax.device_put(_partials(n, (F,), 100 + t), sh)}
            total_true += np.asarray(g["w"]).sum(axis=0)
            k = jax.random.fold_in(key, t)
            out_ef, residual = ef_call(g, residual, k)
            total_ef += np.asarray(out_ef["w"])[0]
            out_ne, _ = ne_call(g, k)
            total_no_ef += np.asarray(out_ne["w"])[0]
        res_sum = np.asarray(residual["w"]).sum(axis=0)[:F]
        # the telescoping identity (f32 arithmetic headroom only)
        np.testing.assert_allclose(total_ef + res_sum, total_true,
                                   atol=5e-4)
        ef_err = np.abs(total_ef - total_true).max()
        no_ef_err = np.abs(total_no_ef - total_true).max()
        assert ef_err <= np.abs(res_sum).max() + 5e-4
        assert ef_err < no_ef_err

    def test_refusals(self, devices):
        mesh = make_mesh("data:-1")
        with pytest.raises(ValueError, match="unknown grad_comm"):
            compressed_allreduce({"w": jnp.zeros((8, 4))}, mesh, "fp16")
        with pytest.raises(ValueError, match="stochastic rounding"):
            compressed_allreduce({"w": jnp.zeros((8, 4))}, mesh, "int8")
        with pytest.raises(ValueError, match="no-op by construction"):
            compressed_allreduce({"w": jnp.zeros((8, 4))}, mesh, "fp32",
                                 residual={"w": jnp.zeros((8, 256))})
        with pytest.raises(ValueError, match="data-parallel meshes only"):
            validate_ddp_mesh(make_mesh("data:4,model:2"))
        with pytest.raises(ValueError, match="mesh"):
            validate_ddp_mesh(None)


# -- the scan --------------------------------------------------------------

class TestDdpOverlapScan:
    def test_matches_reference_values_and_grads(self, devices):
        """Toy stack y_{k+1} = tanh(y_k @ W_k): the per-layer-reduced
        custom-vjp scan agrees with straight-line math in value and in
        grads wrt weights AND input (the --grad_comm fp32 parity pin)."""
        mesh = make_mesh("data:-1")
        L, d, B = 4, 6, 16
        rng = np.random.default_rng(1)
        w_host = rng.standard_normal((L, d, d)).astype(np.float32) * 0.3
        x_host = rng.standard_normal((B, d)).astype(np.float32)
        stacked = {"w": jnp.asarray(w_host)}
        x = jax.device_put(jnp.asarray(x_host),
                           NamedSharding(mesh, P("data")))

        def apply_one(w, y, k, extras):
            return jnp.tanh(y @ w["w"])

        def overlap_loss(stacked, x):
            return jnp.mean(ddp_overlap_scan(
                apply_one, stacked, x, (), (), mesh) ** 2)

        def ref_loss(w, x):
            y = x
            for k in range(L):
                y = jnp.tanh(y @ w[k])
            return jnp.mean(y ** 2)

        lo, (gs, gx) = jax.jit(
            jax.value_and_grad(overlap_loss, argnums=(0, 1)))(stacked, x)
        lr, (gw_ref, gx_ref) = jax.jit(
            jax.value_and_grad(ref_loss, argnums=(0, 1)))(
            jnp.asarray(w_host), jnp.asarray(x_host))
        np.testing.assert_allclose(float(lo), float(lr), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(gs["w"]), np.asarray(gw_ref),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   atol=1e-5)

    def test_int8_residual_cotangent_telescopes(self, devices):
        """int8 through the scan: grads land within quantization error of
        the true grads, and the residual cotangent carries exactly the
        error kept back — truth = compressed + summed residual."""
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        L, d, B = 3, 6, 16
        rng = np.random.default_rng(3)
        stacked = {"w": jnp.asarray(
            rng.standard_normal((L, d, d)).astype(np.float32) * 0.3)}
        x = jax.device_put(
            jnp.asarray(rng.standard_normal((B, d)).astype(np.float32)),
            NamedSharding(mesh, P("data")))
        res = jax.tree.map(
            lambda r: jax.device_put(r, NamedSharding(mesh, P(None, "data"))),
            init_residual(stacked, n))
        key = jax.random.PRNGKey(9)

        def apply_one(w, y, k, extras):
            return jnp.tanh(y @ w["w"])

        def loss(stacked, res, x, mode, r):
            return jnp.mean(ddp_overlap_scan(
                apply_one, stacked, x, (), (), mesh, grad_comm=mode,
                residual=r, comm_rng=key if mode != "fp32" else None) ** 2)

        _, gw_true = jax.jit(jax.value_and_grad(
            lambda s: loss(s, None, x, "fp32", None)))(stacked)
        _, (gw8, res_ct) = jax.jit(jax.value_and_grad(
            lambda s, r: loss(s, r, x, "int8", r), argnums=(0, 1)))(
            stacked, res)
        recon = gw8["w"] + jnp.sum(res_ct["w"], axis=1)[
            :, : d * d].reshape(L, d, d)
        np.testing.assert_allclose(np.asarray(recon),
                                   np.asarray(gw_true["w"]), atol=1e-5)
        # and int8 alone is close-but-not-exact (compression really ran)
        assert 0 < _max_abs_diff(gw8, gw_true) < 0.1

    def test_refusals(self, devices):
        mesh = make_mesh("data:-1")
        stacked = {"w": jnp.zeros((2, 4, 4))}
        with pytest.raises(ValueError, match="needs comm_rng"):
            ddp_overlap_scan(lambda w, y, k, e: y, stacked,
                             jnp.zeros((8, 4)), (), (), mesh,
                             grad_comm="int8")
        with pytest.raises(ValueError, match="no-op by construction"):
            ddp_overlap_scan(lambda w, y, k, e: y, stacked,
                             jnp.zeros((8, 4)), (), (), mesh,
                             grad_comm="fp32", residual={"w": jnp.zeros(1)})
        with pytest.raises(ValueError, match="empty stacked"):
            ddp_overlap_scan(lambda w, y, k, e: y, {}, jnp.zeros((8, 4)),
                             (), (), mesh)


# -- wire bytes ------------------------------------------------------------

def test_wire_bytes_ratios(devices):
    stacked = {"k": jnp.zeros((4, 64, 64)), "b": jnp.zeros((4, 64))}
    n = 8
    fp32 = wire_bytes_per_step(stacked, n, "fp32")
    bf16 = wire_bytes_per_step(stacked, n, "bf16")
    int8 = wire_bytes_per_step(stacked, n, "int8")
    assert bf16 / fp32 == 0.5
    assert int8 / fp32 <= 0.3  # the acceptance bar: <= 0.3x on the wire
    with pytest.raises(ValueError, match="unknown grad_comm"):
        wire_bytes_per_step(stacked, n, "fp8")


# -- config + registry refusals --------------------------------------------

def test_config_refusals():
    with pytest.raises(ValueError, match="unknown --grad_comm"):
        TrainingConfig(grad_comm="fp16")
    with pytest.raises(ValueError, match="replicated params"):
        TrainingConfig(ddp_overlap=True, fsdp=True)
    with pytest.raises(ValueError, match="replicated params"):
        TrainingConfig(ddp_overlap=True, fsdp_overlap=True,
                       scan_layers=True)
    with pytest.raises(ValueError, match="only exists under --ddp_overlap"):
        TrainingConfig(grad_comm="int8")
    with pytest.raises(ValueError, match="no error to"):
        TrainingConfig(ddp_overlap=True, scan_layers=True,
                       grad_error_feedback=True)
    with pytest.raises(ValueError, match="accumulation"):
        TrainingConfig(ddp_overlap=True, scan_layers=True,
                       grad_comm="int8", grad_error_feedback=True,
                       gradient_accumulation_steps=2)


def test_registry_refusals(devices):
    mesh = make_mesh("data:-1")
    with pytest.raises(ValueError, match="needs --scan_layers"):
        build("gpt-tiny", TrainingConfig(model="gpt-tiny",
                                         ddp_overlap=True), mesh=mesh)
    with pytest.raises(ValueError, match="MoE"):
        build("gpt-moe-tiny",
              TrainingConfig(model="gpt-moe-tiny", scan_layers=True,
                             ddp_overlap=True), mesh=mesh)
    # r22: pipe×ddp now COMPOSES (slot-boundary masked reduces) — the
    # remaining refusal on a pipe-less mesh is the missing pipe axis
    with pytest.raises(ValueError, match="pipe"):
        build("gpt-pipe-tiny",
              TrainingConfig(model="gpt-pipe-tiny", scan_layers=True,
                             ddp_overlap=True), mesh=mesh)
    with pytest.raises(ValueError, match="no transformer layer stack"):
        build("mlp", TrainingConfig(model="mlp", scan_layers=True,
                                    ddp_overlap=True), mesh=mesh)
    with pytest.raises(ValueError, match="data-parallel meshes only"):
        build("gpt-tiny",
              TrainingConfig(model="gpt-tiny", scan_layers=True,
                             ddp_overlap=True, mesh="data:4,model:2"),
              mesh=make_mesh("data:4,model:2"))


# -- model-path parity -----------------------------------------------------

def _pair(name, **overrides):
    cfg_b = TrainingConfig(model=name, dataset_size=32, scan_layers=True)
    cfg_o = TrainingConfig(model=name, dataset_size=32, scan_layers=True,
                           ddp_overlap=True, **overrides)
    mesh = make_mesh("data:-1")
    task_b, ds = build(name, cfg_b, mesh=mesh)
    task_o, _ = build(name, cfg_o, mesh=mesh)
    batch = {k: jax.device_put(np.asarray(v),
                               NamedSharding(mesh, P("data")))
             for k, v in ds.batch(np.arange(8)).items()}
    return task_b, task_o, batch, mesh


@pytest.mark.slow  # ~20s of model jits; the scan/wire units above are the
#                    tier-1 tripwire, this is the model-level pin
def test_gpt_tiny_loss_and_grad_parity(devices):
    """fp32 comms: loss and every grad leaf agree between the GSPMD
    baseline scan and the per-layer-reduced path."""
    task_b, task_o, batch, mesh = _pair("gpt-tiny")
    assert task_o.model.ddp_overlap and task_o.model.mesh is mesh
    key = jax.random.PRNGKey(0)
    params, _ = task_b.init(key, batch)
    params = nn.meta.unbox(params)

    def loss_of(task):
        def f(p):
            loss, _, _ = task.loss(p, {}, batch, None, train=False)
            return loss
        return jax.jit(jax.value_and_grad(f))

    lb, gb = loss_of(task_b)(params)
    lo, go = loss_of(task_o)(params)
    np.testing.assert_allclose(float(lb), float(lo), atol=TOL)
    assert _max_abs_diff(gb, go) < TOL


@pytest.mark.slow
@pytest.mark.parametrize("name", ["gpt-tiny", "bert-tiny", "vit-tiny"])
def test_engine_step_parity(name, devices):
    """One full jitted optimizer step per family under --grad_comm fp32:
    the per-layer-reduced path updates every weight to within TOL of the
    GSPMD baseline. Dropout is cloned OFF (bert-tiny defaults 0.1): with
    dropout active the paths draw per-layer streams differently by design
    (the overlap path folds the layer index and data coordinate where
    nn.scan splits) — statistically equivalent, documented in README, not
    the math this test pins."""
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    task_b, task_o, batch, mesh = _pair(name)
    task_b.model = task_b.model.clone(dropout_rate=0.0)
    task_o.model = task_o.model.clone(dropout_rate=0.0)
    cfg = TrainingConfig(model=name, warmup_steps=0)
    key = jax.random.PRNGKey(0)
    states, metrics = {}, {}
    for tag, task in (("default", task_b), ("overlap", task_o)):
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(cfg, total_steps=10)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars=extra, opt_state=tx.init(params),
                           rng=jax.random.clone(key))
        state = shard_tree(state, mesh)
        step = make_train_step(task, tx, schedule)
        states[tag], metrics[tag] = step(state, batch)
    np.testing.assert_allclose(np.asarray(metrics["default"]["loss"]),
                               np.asarray(metrics["overlap"]["loss"]),
                               atol=TOL)
    assert _max_abs_diff(states["default"].params,
                         states["overlap"].params) < TOL


@pytest.mark.slow
def test_engine_step_int8_error_feedback(devices):
    """Whole-engine int8+EF step: the residual rides TrainState, comes
    back updated (non-zero) through the cotangent channel, the params
    stay within quantization distance of the fp32-path update, and a
    second step consumes the first step's residual."""
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    task_b, task_o, batch, mesh = _pair(
        "gpt-tiny", grad_comm="int8", grad_error_feedback=True)
    cfg = TrainingConfig(model="gpt-tiny", warmup_steps=0)
    key = jax.random.PRNGKey(0)

    def make_state(task):
        params, extra = task.init(key, batch)
        residual = (extra.pop("comm_residual", None)
                    if isinstance(extra, dict) else None)
        tx, schedule = make_optimizer(cfg, total_steps=10)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars=extra, opt_state=tx.init(params),
                           rng=jax.random.clone(key),
                           comm_residual=residual)
        state = shard_tree(state, mesh)
        if state.comm_residual is not None:
            sh = NamedSharding(mesh, P(None, "data"))
            state = state.replace(comm_residual=jax.tree.map(
                lambda x: jax.device_put(x, sh), state.comm_residual))
        return make_train_step(task, tx, schedule), state

    step_b, state_b = make_state(task_b)
    step_o, state_o = make_state(task_o)
    assert state_b.comm_residual is None
    assert state_o.comm_residual is not None
    new_b, _ = step_b(state_b, batch)
    new_o, m = step_o(state_o, batch)
    assert np.isfinite(float(m["loss"]))
    gap = _max_abs_diff(new_b.params, new_o.params)
    assert 0 < gap < 1e-3  # compression ran; update stayed in its band
    res_max = max(float(jnp.abs(l).max())
                  for l in jax.tree.leaves(new_o.comm_residual))
    assert res_max > 0
    new_o2, m2 = step_o(new_o, batch)
    assert np.isfinite(float(m2["loss"]))
    # eval on the int8 model must not demand an rng (backward never runs)
    ev_loss, _, _ = task_o.loss(new_o2.params, new_o2.extra_vars, batch,
                                None, train=False)
    assert np.isfinite(float(ev_loss))


# -- checkpoint forward/backward compatibility -----------------------------

def _tiny_state(with_residual: bool):
    from pytorch_ddp_template_tpu.train.engine import TrainState

    residual = {"layers": jnp.full((2, 4, 8), 0.25)} if with_residual else None
    return TrainState(
        step=jnp.asarray(3, jnp.int32),
        params={"w": jnp.arange(6.0).reshape(2, 3)},
        extra_vars={},
        opt_state={"m": jnp.ones((2, 3))},
        rng=jax.random.PRNGKey(0),
        comm_residual=residual,
    )


class TestCheckpointResidualCompat:
    def test_pre_residual_checkpoint_zero_inits_residual(self, tmp_path):
        """Forward compat: a checkpoint written WITHOUT a residual (the
        pre-r9 layout — saving with comm_residual=None produces exactly
        it) restores into an error-feedback run with the residual
        zero-initialised instead of crashing."""
        from pytorch_ddp_template_tpu.checkpoint.manager import (
            CheckpointManager,
        )

        ckpt = CheckpointManager(tmp_path / "ck")
        ckpt.save(3, _tiny_state(False), TrainingConfig())
        ckpt.wait()
        template = _tiny_state(True).replace(
            comm_residual={"layers": jnp.zeros((2, 4, 8))})
        state, _ = ckpt.restore(None, template)
        np.testing.assert_array_equal(
            np.asarray(state.params["w"]),
            np.arange(6.0).reshape(2, 3))
        assert float(jnp.abs(state.comm_residual["layers"]).max()) == 0.0
        ckpt.close()

    def test_residual_checkpoint_roundtrip_and_ignored_when_off(
            self, tmp_path):
        """Backward compat both ways: an EF checkpoint restores its
        residual values into an EF run, and restores cleanly (residual
        ignored) into a run with error feedback off."""
        from pytorch_ddp_template_tpu.checkpoint.manager import (
            CheckpointManager,
        )

        ckpt = CheckpointManager(tmp_path / "ck")
        ckpt.save(3, _tiny_state(True), TrainingConfig())
        ckpt.wait()
        # EF on: values round-trip
        template = _tiny_state(True).replace(
            comm_residual={"layers": jnp.zeros((2, 4, 8))})
        state, _ = ckpt.restore(None, template)
        np.testing.assert_allclose(
            np.asarray(state.comm_residual["layers"]), 0.25)
        # EF off: the residual item is never requested — no crash, None
        state_off, _ = ckpt.restore(None, _tiny_state(False))
        assert state_off.comm_residual is None
        np.testing.assert_array_equal(
            np.asarray(state_off.params["w"]),
            np.arange(6.0).reshape(2, 3))
        ckpt.close()

    @pytest.mark.slow  # two Trainer builds + train-step compiles
    def test_trainer_resume_across_ef_toggle(self, tmp_path):
        """CLI-level: a run trained WITHOUT error feedback resumes into a
        --grad_error_feedback run (zero residual) and trains on — the
        restore path, template build and residual placement compose."""
        from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
        from pytorch_ddp_template_tpu.train.engine import Trainer

        mesh = make_mesh("data:-1")
        key = jax.random.PRNGKey(0)

        def trainer(**overrides):
            kw = dict(
                model="gpt-tiny", mesh="data:-1", dataset_size=64,
                per_device_train_batch_size=1, max_steps=1,
                logging_steps=0, save_steps=0, seed=0,
                output_dir=str(tmp_path / "out"), scan_layers=True,
                ddp_overlap=True)
            kw.update(overrides)
            cfg = TrainingConfig(**kw)
            ctx = RuntimeContext(mesh=mesh, seed_key=key,
                                 host_key=jax.random.fold_in(key, 0),
                                 config=cfg)
            task, ds = build(cfg.model, cfg, mesh=mesh)
            return Trainer(cfg, ctx, task, ds)

        t1 = trainer()
        state = t1.train()
        assert state.comm_residual is None
        t1.ckpt.close()
        t2 = trainer(grad_comm="int8", grad_error_feedback=True,
                     max_steps=2)
        state2, start = t2.restore_or_init()
        assert start == 1
        assert state2.comm_residual is not None
        assert max(float(jnp.abs(l).max())
                   for l in jax.tree.leaves(state2.comm_residual)) == 0.0
        final = t2.train()
        assert int(final.step) == 2
        assert max(float(jnp.abs(l).max())
                   for l in jax.tree.leaves(final.comm_residual)) > 0
        t2.ckpt.close()


# -- quantizer edge cases (r17 satellite: direct units for the paths
# previously only exercised through compressed_allreduce) -------------------

class TestQuantizerEdgeCases:
    def test_int8_single_element_chunks(self):
        """chunk=1: every value is its own bucket — scale == |x| and the
        roundtrip is exact up to one stochastic quantum (|x|/127)."""
        x = jnp.asarray(np.random.default_rng(11).standard_normal(
            (1, 8)).astype(np.float32) * 5.0)
        q, scale = quantize_int8(x, jax.random.PRNGKey(0), chunk=1)
        assert q.shape == (1, 8, 1) and scale.shape == (1, 8, 1)
        back = dequantize_int8(q, scale)
        err = np.abs(np.asarray(back) - np.asarray(x))
        assert np.all(err <= np.abs(np.asarray(x)) / 127.0 + 1e-7)

    def test_int8_mixed_zero_channels(self):
        """All-zero buckets next to live ones: the zero buckets must
        dequantize to exact zeros (scale pinned 1.0, not 0/0) while the
        live buckets stay bounded."""
        x = jnp.concatenate([jnp.zeros((1, CHUNK)),
                             jnp.ones((1, CHUNK)) * 3.0], axis=-1)
        q, scale = quantize_int8(x, jax.random.PRNGKey(1))
        back = np.asarray(dequantize_int8(q, scale))
        assert np.abs(back[0, :CHUNK]).max() == 0.0
        assert np.abs(back[0, CHUNK:] - 3.0).max() <= 3.0 / 127.0 + 1e-7

    def test_chunk_non_divisible_tail_pads_and_roundtrips(self):
        """A 300-element leaf does not divide CHUNK: padded_size pads to
        whole buckets per replica, the real entries survive the
        compressed exchange within bound, and the pad region returns
        exact zeros (all-zero buckets)."""
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        host = {"w": _partials(n, (300,), 42)}
        assert 300 % CHUNK != 0 and padded_size(300, n) % (n * CHUNK) == 0
        sharded = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P("data"))),
            host)
        out, _ = compressed_allreduce(sharded, mesh, "int8",
                                      rng=jax.random.PRNGKey(2))
        want = np.asarray(host["w"]).sum(axis=0)
        got = np.asarray(out["w"])[0]
        scale = np.abs(np.asarray(host["w"])).max() / 127.0
        assert got.shape == (300,)
        assert np.max(np.abs(got - want)) < (n + 2) * scale

    def test_stochastic_round_bf16_zero_and_sign(self):
        x = jnp.asarray([0.0, -0.0, 1.5, -1.5], jnp.float32)
        out = np.asarray(stochastic_round_bf16(
            x, jax.random.PRNGKey(3)).astype(jnp.float32))
        assert out[0] == 0.0 and out[1] == 0.0
        assert out[2] > 0 and out[3] < 0


# -- EF under ddp×tp (r17 satellite: the r11 named refusal, lifted) --------

class TestErrorFeedbackUnderTp:
    def test_residual_sized_for_model_shards(self, devices):
        """init_residual with tp specs: model-sharded kernels get
        (L, data, model, padded_local) with the LOCAL element count;
        model-replicated leaves keep full width per shard."""
        from pytorch_ddp_template_tpu.parallel.compress import (
            local_shard_elems, residual_shape_tp,
        )

        spec_k = P(None, None, "model")   # stacked column kernel
        spec_b = P(None, None)            # stacked replicated bias
        assert local_shard_elems((2, 32, 64), spec_k, 2) == 32 * 32
        assert local_shard_elems((2, 64), spec_b, 2) == 64
        shape = residual_shape_tp((2, 32, 64), 4, 2, spec_k)
        assert shape == (2, 4, 2, padded_size(32 * 32, 4))
        with pytest.raises(ValueError, match="not divisible"):
            local_shard_elems((2, 32, 63), spec_k, 2)

    @pytest.mark.slow  # ~20s of jits; the residual/spec units above stay tier-1
    def test_composed_telescoping_identity(self, devices):
        """The acceptance pin at the composed geometry: on data×model,
        each (data, model) coordinate's compressed per-shard grads plus
        its residual cotangent reconstruct the true fp32 grads — the
        telescoping identity surviving the model-sharded drain."""
        mesh = make_mesh("data:4,model:2")
        cfg = TrainingConfig(
            model="gpt-tiny", mesh="data:4,model:2", scan_layers=True,
            ddp_overlap=True, tp_overlap=True, grad_comm="int8",
            grad_error_feedback=True, warmup_steps=0)
        task, _ = build("gpt-tiny", cfg, mesh=mesh)
        batch = {"input_ids": jax.device_put(
            jnp.asarray(np.random.default_rng(0).integers(
                0, 1024, (8, 128)), jnp.int32),
            NamedSharding(mesh, P("data")))}
        params, extra = task.init(jax.random.PRNGKey(0), batch)
        residual = extra.pop("comm_residual")
        # every leaf carries the 4D model-sharded layout
        for leaf in jax.tree.leaves(residual):
            assert leaf.ndim == 4 and leaf.shape[1:3] == (4, 2)
        res_sh = NamedSharding(mesh, P(None, "data", "model"))
        residual = jax.tree.map(
            lambda x: jax.device_put(x, res_sh), residual)

        def loss_fn(p, ev):
            loss, _, _ = task.loss(p, ev, batch, jax.random.PRNGKey(1),
                                   train=True)
            return loss

        ev_in = {**extra, "comm_residual": residual}
        _, (grads, ev_ct) = jax.jit(jax.value_and_grad(
            loss_fn, argnums=(0, 1)))(params, ev_in)
        res_ct = ev_ct["comm_residual"]
        # the residual updated (compression really ran, error kept back)
        assert max(float(jnp.abs(l).max())
                   for l in jax.tree.leaves(res_ct)) > 0
        # telescoping: int8 grads + residual == exact-fp32-comms grads.
        # Build the fp32-wire twin (EF off) from the SAME init.
        cfg32 = TrainingConfig(
            model="gpt-tiny", mesh="data:4,model:2", scan_layers=True,
            ddp_overlap=True, tp_overlap=True, warmup_steps=0)
        task32, _ = build("gpt-tiny", cfg32, mesh=mesh)

        def loss32(p):
            loss, _, _ = task32.loss(p, extra, batch,
                                     jax.random.PRNGKey(1), train=True)
            return loss

        _, g32 = jax.jit(jax.value_and_grad(loss32))(params)
        stack8 = nn.meta.unbox(grads)["decoder"]["layers"]
        stack32 = nn.meta.unbox(g32)["decoder"]["layers"]
        flat8, _ = jax.tree_util.tree_flatten_with_path(stack8)
        flat_res = jax.tree.leaves(res_ct)
        flat32 = jax.tree.leaves(stack32)
        from pytorch_ddp_template_tpu.parallel.schedule import (
            stacked_tp_specs,
        )
        specs = jax.tree.leaves(
            stacked_tp_specs(stack32, mesh),
            is_leaf=lambda s: isinstance(s, P))
        assert len(flat8) == len(flat_res) == len(flat32) == len(specs)
        checked_rep = checked_shard = 0
        model_size = 2
        for (path, g8), res, gt, spec in zip(flat8, flat_res, flat32,
                                             specs):
            entries = tuple(spec)[1:]
            model_dims = [i for i, e in enumerate(entries)
                          if e is not None and "model" in (
                              (e,) if isinstance(e, str) else tuple(e))]
            L = gt.shape[0]
            g8_np, gt_np, res_np = (np.asarray(g8), np.asarray(gt),
                                    np.asarray(res))
            if not model_dims:
                # replicated leaves: every (d, m) coordinate saw the
                # same full-width grads — any model column's residual
                # summed over data reconstructs the truth
                per_layer = int(np.prod(gt.shape[1:]))
                recon = (g8_np.reshape(L, -1)
                         + res_np[:, :, 0, :].sum(axis=1)[:, :per_layer])
                np.testing.assert_allclose(
                    recon, gt_np.reshape(L, -1), atol=5e-4)
                checked_rep += 1
                continue
            # model-SHARDED kernels — the leaves residual_shape_tp
            # exists for: coordinate m's residual compensates exactly
            # its local slice, so the identity must hold PER COLUMN
            (md,) = model_dims  # block kernels shard on one dim
            axis = md + 1  # + the leading layer dim
            loc = gt.shape[axis] // model_size
            per_local = int(np.prod(gt.shape[1:])) // model_size
            for m in range(model_size):
                sl = [slice(None)] * gt_np.ndim
                sl[axis] = slice(m * loc, (m + 1) * loc)
                recon = (g8_np[tuple(sl)].reshape(L, -1)
                         + res_np[:, :, m, :].sum(axis=1)[:, :per_local])
                np.testing.assert_allclose(
                    recon, gt_np[tuple(sl)].reshape(L, -1), atol=5e-4)
            checked_shard += 1
        assert checked_rep >= 4   # LNs + row biases
        assert checked_shard >= 6  # qkv/out/fc1/fc2 kernels + col biases

    @pytest.mark.slow  # full trainer under ddp×tp; identity math stays tier-1
    def test_trainer_runs_ef_under_tp(self, devices, tmp_path):
        """Engine-level composition: the Trainer inits the 4D residual,
        places it P(None, data, model), trains, and the residual leaves
        update — the CLI surface of the lifted refusal."""
        from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
        from pytorch_ddp_template_tpu.train.engine import Trainer

        cfg = TrainingConfig(
            model="gpt-tiny", mesh="data:4,model:2", scan_layers=True,
            ddp_overlap=True, tp_overlap=True, grad_comm="int8",
            grad_error_feedback=True, warmup_steps=0, max_steps=2,
            per_device_train_batch_size=2, dataset_size=64,
            logging_steps=1, save_steps=0, eval_steps=0, resume=False,
            output_dir=str(tmp_path))
        mesh = make_mesh(cfg.mesh)
        key = jax.random.PRNGKey(0)
        ctx = RuntimeContext(mesh=mesh, seed_key=key,
                             host_key=jax.random.fold_in(key, 0),
                             config=cfg)
        task, ds = build(cfg.model, cfg, mesh=mesh)
        t = Trainer(cfg, ctx, task, ds)
        state = t.train()
        assert int(state.step) == 2
        assert state.comm_residual is not None
        assert max(float(jnp.abs(l).max())
                   for l in jax.tree.leaves(state.comm_residual)) > 0
        t.ckpt.close()
