"""Round-18 elastic fleet: hot checkpoints (checkpoint/hot.py),
reshard-on-restore (checkpoint/reshard.py + CheckpointManager), the
partial-save fallback, the supervisor policy (train/supervisor.py) over
the production sentry→supervisor path, deterministic fault injection
(--inject_fault), the goodput ``hot_checkpoint_save``/``evict_resume``
buckets, and the fleet-exchange retry-with-backoff satellite.

The ACCEPTANCE test (r13 CLI convention, slow set) drives ``ddp.main``:
train on 8 virtual devices with hot snapshots → killed by an injected
hard crash → rerun on 4 devices with the OTHER layer layout → restores
from the hot snapshot, reshards in-restore, trains to completion with
loss/param parity vs an uninterrupted run at float tolerance, and the
goodput/perf_baseline artifacts account for the whole episode."""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.checkpoint.hot import HotCheckpointManager
from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.obs.goodput import BUCKETS, GoodputLedger
from pytorch_ddp_template_tpu.train.supervisor import (
    FaultInjector,
    Supervisor,
)

REPO = Path(__file__).resolve().parent.parent


def make_trainer(out_dir, **overrides):
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(**{
        "model": "mlp", "mesh": "data:8",
        "per_device_train_batch_size": 4, "dataset_size": 512,
        "max_steps": 8, "logging_steps": 0, "save_steps": 0,
        "resume": True, "warmup_steps": 0, "max_grad_norm": 1000.0,
        "output_dir": str(out_dir), **overrides})
    ctx = rt_init(cfg)
    task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
    return Trainer(cfg, ctx, task, ds)


# -- hot checkpoints -------------------------------------------------------

class TestHotCheckpoints:
    def _state(self, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "step": np.asarray(seed, np.int32),
            "params": {"w": rng.standard_normal((4, 3)).astype(np.float32),
                       "b": rng.standard_normal(3).astype(np.float32)},
            "opt_state": [{"mu": rng.standard_normal((4, 3))
                           .astype(np.float32)}],
            "rng": np.zeros(2, np.uint32),
        }

    def test_save_restore_roundtrip_bit_exact(self, tmp_path):
        cfg = TrainingConfig(output_dir=str(tmp_path))
        hot = HotCheckpointManager(tmp_path)
        state = self._state(7)
        assert hot.save(7, state, cfg) is not None
        rec = hot.latest_valid()
        assert rec is not None and rec.step == 7
        for a, b in zip(jax.tree.leaves(rec.body),
                        jax.tree.leaves(state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert rec.config["output_dir"] == str(tmp_path)

    def test_generations_prune_to_keep(self, tmp_path):
        cfg = TrainingConfig(output_dir=str(tmp_path))
        hot = HotCheckpointManager(tmp_path, keep=2)
        for s in (1, 2, 3, 4):
            hot.save(s, self._state(s), cfg)
        gens = hot.generations()
        assert [g[1] for g in gens] == [3, 4]  # newest `keep` survive

    def test_corrupt_newest_falls_back_to_previous_generation(
            self, tmp_path):
        """The fault-injection kind the restore side must survive: a
        byte-flipped newest snapshot fails its CRC and the previous
        generation restores instead."""
        cfg = TrainingConfig(output_dir=str(tmp_path))
        hot = HotCheckpointManager(tmp_path)
        hot.save(1, self._state(1), cfg)
        hot.save(2, self._state(2), cfg)
        assert hot.corrupt_latest() is not None
        rec = hot.latest_valid()
        assert rec is not None and rec.step == 1  # fell back, logged

    def test_incomplete_staging_dir_is_invisible(self, tmp_path):
        """Atomicity: a kill mid-save leaves only a staging dir, which
        discovery ignores entirely."""
        cfg = TrainingConfig(output_dir=str(tmp_path))
        hot = HotCheckpointManager(tmp_path)
        hot.save(5, self._state(5), cfg)
        staging = hot.base / ".staging_gen_00000099_0"
        staging.mkdir()
        (staging / "arrays.npz").write_bytes(b"partial")
        assert [g[1] for g in hot.generations()] == [5]
        assert hot.latest_valid().step == 5

    def test_residual_markers_index_the_combined_arrays(self, tmp_path):
        """A residual-carrying state snapshots body + residual into ONE
        arrays list; the residual tree's leaf markers must be offset
        past the body's leaves (a residual-local numbering would
        silently substitute body leaves on restore)."""
        import dataclasses

        @dataclasses.dataclass
        class S:
            step: object
            params: object
            comm_residual: object

            def replace(self, **kw):
                return dataclasses.replace(self, **kw)

        res = [np.full((2, 4, 8), 7.0, np.float32)]
        state = S(step=np.asarray(3, np.int32),
                  params={"w": np.arange(12, dtype=np.float32)},
                  comm_residual=res)
        cfg = TrainingConfig(output_dir=str(tmp_path))
        hot = HotCheckpointManager(tmp_path)
        hot.save(3, state, cfg)
        rec = hot.latest_valid()
        np.testing.assert_array_equal(np.asarray(rec.residual[0]), res[0])
        np.testing.assert_array_equal(np.asarray(rec.body["params"]["w"]),
                                      state.params["w"])

    def test_missing_manifest_generation_skipped(self, tmp_path):
        cfg = TrainingConfig(output_dir=str(tmp_path))
        hot = HotCheckpointManager(tmp_path)
        hot.save(1, self._state(1), cfg)
        hot.save(2, self._state(2), cfg)
        newest = hot.generations()[-1][2]
        (newest / "manifest.json").unlink()
        assert hot.latest_valid().step == 1


# -- EF-residual re-bucketing ---------------------------------------------

class TestResidualRebucket:
    def test_telescoping_sum_preserved_across_data_degree(self):
        from pytorch_ddp_template_tpu.parallel.compress import (
            rebucket_residual,
        )

        rng = np.random.default_rng(0)
        raw = rng.standard_normal((3, 4, 16)).astype(np.float32)
        raw[:, :, 10:] = 0.0  # the padding region quantizes zeros to zero
        out = rebucket_residual(raw, (3, 2, 16))
        assert out.shape == (3, 2, 16)
        np.testing.assert_allclose(out.sum(axis=1), raw.sum(axis=1),
                                   rtol=1e-6, atol=1e-6)
        # shrinking the padded width only drops the zero region
        out2 = rebucket_residual(raw, (3, 8, 12))
        np.testing.assert_allclose(out2.sum(axis=1), raw.sum(axis=1)[:, :12],
                                   rtol=1e-6, atol=1e-6)

    def test_layer_count_change_refused(self):
        from pytorch_ddp_template_tpu.parallel.compress import (
            rebucket_residual,
        )

        with pytest.raises(ValueError, match="layer count"):
            rebucket_residual(np.zeros((3, 4, 16), np.float32), (2, 4, 16))


# -- partial durable save fallback ----------------------------------------

class TestPartialSaveFallback:
    def test_truncated_newest_step_falls_back_to_complete_step(
            self, tmp_path):
        """Crash mid-save: the newest orbax step dir exists but its
        array payload is truncated — auto-latest restore logs the skip
        and restores the previous COMPLETE step instead of raising."""
        t = make_trainer(tmp_path, max_steps=8, save_steps=4)
        t.train()
        t.ckpt.close()
        assert sorted(int(p.name.split("_")[1]) for p in
                      Path(tmp_path).glob("checkpoint_*")) == [4, 8]
        # truncate every array-payload file of the newest step
        for f in (Path(tmp_path) / "checkpoint_8" / "state").rglob("*"):
            if f.is_file() and f.stat().st_size > 256:
                f.write_bytes(b"\0")
        t2 = make_trainer(tmp_path, max_steps=8, save_steps=4)
        state, start = t2.restore_or_init()
        t2.ckpt.close()
        assert start == 4  # fell back past the partial step 8

    def test_pinned_step_does_not_fall_back(self, tmp_path):
        """--global_step pins an exact step: a corrupt pinned step must
        refuse, never silently restore a different one."""
        t = make_trainer(tmp_path, max_steps=8, save_steps=4)
        t.train()
        t.ckpt.close()
        for f in (Path(tmp_path) / "checkpoint_8" / "state").rglob("*"):
            if f.is_file() and f.stat().st_size > 256:
                f.write_bytes(b"\0")
        t2 = make_trainer(tmp_path, max_steps=8, save_steps=4,
                          global_step=8)
        with pytest.raises(Exception):
            t2.restore_or_init()
        t2.ckpt.close()


# -- reshard-on-restore through the durable tier ---------------------------

def test_durable_reshard_scanned_to_unrolled_parity(tmp_path):
    """The refusal→reshard transition, durable half: a scanned gpt-tiny
    checkpoint restores into an unrolled run directly (the pre-r18
    engine refused this config), bit-exact with the offline converter's
    restack (same core)."""
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.parallel.stacking import (
        restack_layer_trees,
    )
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    base = dict(model="gpt-tiny", mesh="data:8",
                per_device_train_batch_size=1, dataset_size=64,
                max_steps=2, logging_steps=0, save_steps=2,
                warmup_steps=0, seed=11, output_dir=str(tmp_path))
    cfg = TrainingConfig(**base, scan_layers=True)
    ctx = rt_init(cfg)
    task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
    t = Trainer(cfg, ctx, task, ds)
    state = t.train()
    scanned = jax.device_get(state.params)
    t.ckpt.close()

    cfg2 = TrainingConfig(**base, scan_layers=False)
    task2, ds2 = build(cfg2.model, cfg2, mesh=ctx.mesh)
    t2 = Trainer(cfg2, ctx, task2, ds2)
    state2, start = t2.restore_or_init()
    t2.ckpt.close()
    assert start == 2
    diff = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(
        jax.tree.leaves(restack_layer_trees(
            jax.device_get(state2.params))),
        jax.tree.leaves(scanned)))
    assert diff == 0.0


# -- supervisor ------------------------------------------------------------

def fake_fleet(walls):
    """Injected 3-host exchange: host i reports walls[i] — the fault
    arrives exactly where a real straggler's numbers do (the transport),
    so the verdict → supervisor path is the production one."""
    from pytorch_ddp_template_tpu.obs.fleet import FLEET_WIRE_KEYS

    wall_i = FLEET_WIRE_KEYS.index("step_wall_ms")

    def exchange(vec):
        rows = np.stack([vec] * len(walls))
        for i, w in enumerate(walls):
            rows[i, wall_i] = w
        return rows

    return exchange


class TestSupervisor:
    def test_action_table(self, tmp_path):
        s = Supervisor("act", tmp_path)
        s.on_verdict("regression", 5, {"warnings": ["x"]})
        assert s.poll() is None  # observe-only kinds never stop the run
        s.on_verdict("mem_pressure", 6, {})
        dec = s.poll()
        assert dec["action"] == "restart" and dec["kind"] == "mem_pressure"
        assert s.poll() is None  # exactly-once
        doc = json.loads((tmp_path / "supervisor.json").read_text())
        assert len(doc["decisions"]) == 2
        assert doc["eviction"] is None

    def test_act_mode_evicts_and_resumes_on_healthy_subset(self, tmp_path):
        """E2E through the production sentry→supervisor path: an
        injected slow-host straggler verdict in --supervise act produces
        checkpoint → evict-the-named-host → coordinated stop; the next
        attempt resumes and its restart gap books to `evict_resume`."""
        t = make_trainer(tmp_path, fleet=True, anomaly="warn",
                         supervise="act", max_steps=500, logging_steps=2,
                         straggler_windows=2)
        t.fleet._exchange = fake_fleet([5.0, 5.0, 42.0])
        state = t.train()
        stopped_at = int(state.step)
        assert 0 < stopped_at < 500  # the supervisor stopped the run
        assert t.ckpt.latest_step() == stopped_at  # checkpoint landed
        t.ckpt.close()
        doc = json.loads((tmp_path / "supervisor.json").read_text())
        assert doc["eviction"] == {"host": 2, "step": doc["eviction"]["step"],
                                   "kind": "straggler"}
        assert any(d["acted"] and d["action"] == "evict"
                   for d in doc["decisions"])
        gp = json.loads((tmp_path / "goodput.json").read_text())
        assert gp["evicted"] is True and gp["completed"] is False
        # the sentry still owns triage: the straggler bundle exists too
        assert list((tmp_path / "flight_records").glob("step_*"))

        # attempt 2 = the healthy-subset resume (the evicted host is
        # gone from the relaunch; in-process that is just a resume):
        # the chosen downtime books to evict_resume, not halted
        t2 = make_trainer(tmp_path, max_steps=stopped_at + 4)
        state2 = t2.train()
        t2.ckpt.close()
        assert int(state2.step) == stopped_at + 4
        gp2 = json.loads((tmp_path / "goodput.json").read_text())
        assert gp2["attempt"] == 2
        assert gp2["buckets"]["evict_resume"] > 0.0
        assert gp2["buckets"]["halted"] == 0.0

    def test_warn_mode_logs_would_be_action_only(self, tmp_path):
        import logging

        # the repo's loggers set propagate=False (progress-bar-safe
        # handler), so capture with a handler on the engine logger
        # directly rather than caplog's root-based capture
        records: list[logging.LogRecord] = []
        handler = logging.Handler()
        handler.emit = records.append
        eng_log = logging.getLogger("pytorch_ddp_template_tpu.train.engine")
        eng_log.addHandler(handler)
        try:
            t = make_trainer(tmp_path, fleet=True, anomaly="warn",
                             supervise="warn", max_steps=20,
                             logging_steps=2, straggler_windows=2)
            t.fleet._exchange = fake_fleet([5.0, 5.0, 42.0])
            state = t.train()
            t.ckpt.close()
        finally:
            eng_log.removeHandler(handler)
        assert int(state.step) == 20  # warn mode never stops the run
        assert any("supervisor (warn mode) would act" in r.getMessage()
                   for r in records)
        doc = json.loads((tmp_path / "supervisor.json").read_text())
        assert doc["decisions"] and not any(d["acted"]
                                            for d in doc["decisions"])
        gp = json.loads((tmp_path / "goodput.json").read_text())
        assert gp["evicted"] is False

    def test_metrics_export_supervisor_gauges(self):
        from pytorch_ddp_template_tpu.obs.server import prometheus_lines

        text = prometheus_lines({
            "step": 10,
            "supervisor": {"mode": "act", "acted": True,
                           "decisions": [{"action": "evict", "acted": True,
                                          "host": 2, "kind": "straggler",
                                          "step": 10}]},
        })
        assert "tpuddp_supervisor_decisions_total" in text
        assert 'tpuddp_supervisor_acted{host="0"} 1.0' in text
        assert 'evicted_host="2"' in text


# -- supervisor hysteresis (r19, ROADMAP r18 open (d)) ---------------------
#
# A flapping host passes the straggler attribution every time it flaps;
# without hysteresis each flap becomes checkpoint -> evict -> resume and
# the fleet spends its life restarting. Two guards, both enforced from
# the supervisor.json ledger so they hold ACROSS attempts: a cooldown
# after any acted stop, and a max-K-evictions-per-day budget. The tests
# inject the flapping verdicts directly (the production path delivers
# them through on_verdict either way).


class TestSupervisorHysteresis:
    def evict_once(self, d, **kw):
        s = Supervisor("act", d, **kw)
        s.on_verdict("straggler", 10, {"host": 2})
        dec = s.poll()
        assert dec is not None and dec["action"] == "evict"
        s.mark_acted(dec)
        return s

    def test_flapping_host_hits_cooldown_across_attempts(self, tmp_path):
        self.evict_once(tmp_path, cooldown_s=600)
        # the relaunch: the SAME host flaps again immediately
        s2 = Supervisor("act", tmp_path, cooldown_s=600)
        s2.on_verdict("straggler", 12, {"host": 2})
        assert s2.poll() is None  # vetoed: no second stop
        doc = json.loads((tmp_path / "supervisor.json").read_text())
        last = doc["decisions"][-1]
        assert last["suppressed"] == "cooldown"
        assert last["action"] == "observe"
        assert doc["suppressed_total"] == 1

    def test_eviction_budget_from_ledger(self, tmp_path):
        # two acted evictions across two attempts exhaust a budget of 2
        self.evict_once(tmp_path, cooldown_s=0, evict_budget_per_day=2)
        self.evict_once(tmp_path, cooldown_s=0, evict_budget_per_day=2)
        s3 = Supervisor("act", tmp_path, cooldown_s=0,
                        evict_budget_per_day=2)
        s3.on_verdict("straggler", 30, {"host": 0})
        assert s3.poll() is None
        doc = json.loads((tmp_path / "supervisor.json").read_text())
        assert doc["decisions"][-1]["suppressed"] == "budget"
        # the stop history is carried forward, not just the last attempt
        assert len(doc["stop_history"]) >= 1

    def test_restart_spends_cooldown_not_evict_budget(self, tmp_path):
        self.evict_once(tmp_path, cooldown_s=0, evict_budget_per_day=1)
        s2 = Supervisor("act", tmp_path, cooldown_s=0,
                        evict_budget_per_day=1)
        # budget exhausted for evict...
        s2.on_verdict("straggler", 20, {"host": 1})
        assert s2.poll() is None
        # ...but a mem_pressure restart drains no host: still allowed
        s3 = Supervisor("act", tmp_path, cooldown_s=0,
                        evict_budget_per_day=1)
        s3.on_verdict("mem_pressure", 21, {})
        assert s3.poll()["action"] == "restart"

    def test_zero_disables_the_guards(self, tmp_path):
        self.evict_once(tmp_path, cooldown_s=0, evict_budget_per_day=0)
        s2 = Supervisor("act", tmp_path, cooldown_s=0,
                        evict_budget_per_day=0)
        s2.on_verdict("straggler", 11, {"host": 2})
        assert s2.poll()["action"] == "evict"  # immediate re-evict allowed

    def test_corrupt_ledger_starts_fresh(self, tmp_path):
        (tmp_path / "supervisor.json").write_text("{not json")
        s = Supervisor("act", tmp_path, cooldown_s=600)
        s.on_verdict("straggler", 5, {"host": 1})
        assert s.poll()["action"] == "evict"  # no invented history

    def test_state_reports_guards(self, tmp_path):
        s = Supervisor("warn", tmp_path, cooldown_s=120,
                       evict_budget_per_day=3)
        st = s.state()
        assert st["cooldown_s"] == 120
        assert st["evict_budget_per_day"] == 3
        assert st["suppressed_total"] == 0


# -- goodput buckets -------------------------------------------------------

class TestGoodputElasticBuckets:
    def test_new_buckets_exist(self):
        assert "hot_checkpoint_save" in BUCKETS
        assert "evict_resume" in BUCKETS

    def test_evicted_gap_books_to_evict_resume(self, tmp_path):
        l1 = GoodputLedger(tmp_path)
        l1.add("productive_step", 5.0)
        l1.evicted = True
        l1.flush()
        l2 = GoodputLedger(tmp_path, now=time.time() + 30.0)
        tot = l2.totals()
        assert tot["evict_resume"] == pytest.approx(30.0, abs=2.0)
        assert tot["halted"] == 0.0

    def test_organic_preemption_still_books_halted(self, tmp_path):
        l1 = GoodputLedger(tmp_path)
        l1.flush()
        l2 = GoodputLedger(tmp_path, now=time.time() + 30.0)
        tot = l2.totals()
        assert tot["halted"] == pytest.approx(30.0, abs=2.0)
        assert tot["evict_resume"] == 0.0

    def test_split_iteration_hot_bucket(self, tmp_path):
        led = GoodputLedger(tmp_path)
        led.split_iteration(1.0, hot_save_s=0.3, save_s=0.2)
        tot = led.totals()
        assert tot["hot_checkpoint_save"] == pytest.approx(0.3)
        assert tot["checkpoint_save"] == pytest.approx(0.2)
        assert tot["productive_step"] == pytest.approx(0.5)


# -- fleet exchange retry (satellite) --------------------------------------

class TestFleetExchangeRetry:
    def _window(self, step=10):
        from pytorch_ddp_template_tpu.obs.fleet import FLEET_WIRE_KEYS

        w = {k: 0.0 for k in FLEET_WIRE_KEYS}
        w.update(step=float(step), step_wall_ms=5.0)
        return w

    def test_transient_failure_retried_within_window(self):
        from pytorch_ddp_template_tpu.obs.fleet import FleetMonitor

        calls = {"n": 0}

        def flaky(vec):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("coordinator blip")
            return np.stack([vec, vec, vec])

        mon = FleetMonitor(exchange=flaky)
        mon.observe(10, self._window())
        assert calls["n"] == 2  # retried and succeeded inside the window
        assert mon.latest_table["n_hosts"] == 3
        assert mon.state()["degraded_to_local"] is False

    def test_degrades_then_reprobes_and_recovers(self):
        from pytorch_ddp_template_tpu.obs.fleet import (
            EXCHANGE_RETRIES,
            FleetMonitor,
        )

        state = {"healthy": False, "calls": 0}

        def exchange(vec):
            state["calls"] += 1
            if not state["healthy"]:
                raise RuntimeError("transport down")
            return np.stack([vec, vec])

        mon = FleetMonitor(exchange=exchange)
        mon.observe(10, self._window(10))
        assert state["calls"] == EXCHANGE_RETRIES + 1  # bounded retries
        assert mon.state()["degraded_to_local"] is True
        assert mon.latest_table["n_hosts"] == 1  # this window: local only
        state["healthy"] = True
        mon.observe(12, self._window(12))  # next window re-probes
        assert mon.state()["degraded_to_local"] is False
        assert mon.latest_table["n_hosts"] == 2

    def test_default_exchange_round_is_step_keyed(self):
        """Retry idempotence: the KV round number is the window's step
        (fleet-agreed), not a per-call counter a retry would desync."""
        import pytorch_ddp_template_tpu.obs.fleet as fleet_mod

        vec = fleet_mod.encode_window(self._window(37))
        # single-process short-circuit returns the local row and never
        # touches a counter — the step-keyed protocol has no per-call
        # state to desynchronise
        rows = fleet_mod._default_exchange(vec)
        assert rows.shape[0] == 1
        assert int(vec[0]) == 37


# -- fault injection -------------------------------------------------------

class TestFaultInjector:
    def test_parse_grammar(self):
        fi = FaultInjector.parse("slow-host:12:0.05")
        assert (fi.kind, fi.step, fi.param) == ("slow-host", 12, 0.05)
        assert FaultInjector.parse("") is None
        assert FaultInjector.parse(None) is None
        for bad in ("crash", "crash:x", "nope:3", "crash:0", "crash:3:z"):
            with pytest.raises(ValueError):
                FaultInjector.parse(bad)

    def test_config_validates_fault_spec(self):
        with pytest.raises(ValueError, match="inject_fault"):
            TrainingConfig(inject_fault="bogus:3")

    def test_slow_host_injects_delay_from_step(self):
        fi = FaultInjector.parse("slow-host:3:0.01")
        t0 = time.perf_counter()
        fi.maybe_fire(2)
        assert time.perf_counter() - t0 < 0.005  # before the step: free
        t0 = time.perf_counter()
        fi.maybe_fire(3)
        fi.maybe_fire(4)
        assert time.perf_counter() - t0 >= 0.02  # keeps firing

    def test_corrupt_hot_snapshot_through_trainer(self, tmp_path):
        """--inject_fault corrupt-hot-snapshot:N through a real run:
        the newest hot generation fails validation afterwards and the
        restore falls back (older generation or durable)."""
        t = make_trainer(tmp_path, max_steps=6, save_steps=6,
                         hot_save_steps=2,
                         inject_fault="corrupt-hot-snapshot:4")
        t.train()
        t.ckpt.close()
        hot = HotCheckpointManager(tmp_path)
        rec = hot.latest_valid()
        # gen@6 is newest and valid; gen@4 was corrupted in place. Drop
        # gen@6 to face the restore with the corrupt one directly:
        import shutil

        shutil.rmtree(hot.generations()[-1][2])
        rec = hot.latest_valid()
        assert rec is None or rec.step < 4  # corrupt gen never validates
        t2 = make_trainer(tmp_path, max_steps=6, save_steps=6)
        state, start = t2.restore_or_init()
        t2.ckpt.close()
        assert start == 6  # durable step 6 still restores the run


# -- hot tier through the engine -------------------------------------------

class TestEngineHotTier:
    def test_hot_preferred_over_older_durable(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=7, save_steps=5,
                         hot_save_steps=1)
        t.train()
        t.ckpt.close()
        # durable: 5 and the final 7; wipe the final durable save so the
        # hot tier is genuinely newer (the crash scenario: the final
        # save never ran)
        import shutil

        shutil.rmtree(tmp_path / "checkpoint_7")
        t2 = make_trainer(tmp_path, max_steps=9, hot_save_steps=1)
        state, start = t2.restore_or_init()
        t2.ckpt.close()
        assert start == 7  # the hot snapshot, not durable step 5

    def test_torn_newest_durable_prefers_newer_hot_snapshot(self, tmp_path):
        """Crash mid-durable-save: the newest orbax step dir is torn, so
        the durable fallback lands on an older complete step — but the
        hot tier holds a newer snapshot than that fallback, and the
        restore must take it (the exact scenario the hot layer exists
        for; a latest_step()-only comparison would skip it)."""
        t = make_trainer(tmp_path, max_steps=8, save_steps=4,
                         hot_save_steps=3)
        t.train()
        t.ckpt.close()
        # durable: 4, 8; hot gens: 3, 6. Tear durable step 8
        for f in (Path(tmp_path) / "checkpoint_8" / "state").rglob("*"):
            if f.is_file() and f.stat().st_size > 256:
                f.write_bytes(b"\0")
        t2 = make_trainer(tmp_path, max_steps=8, hot_save_steps=3)
        state, start = t2.restore_or_init()
        t2.ckpt.close()
        assert start == 6  # hot@6 beats the durable fallback to 4

    def test_hot_only_all_corrupt_falls_back_to_fresh_init(self, tmp_path):
        """No durable tier and every hot generation corrupt: nothing is
        restorable, so the resume must fresh-init loudly instead of
        raising (a raise would crash-loop under a relauncher)."""
        import shutil

        t = make_trainer(tmp_path, max_steps=4, hot_save_steps=2)
        t.train()
        t.ckpt.close()
        for d in Path(tmp_path).glob("checkpoint_*"):
            shutil.rmtree(d)  # hot-only now
        hot = HotCheckpointManager(tmp_path)
        for _, _, p in hot.generations():
            payload = p / "arrays.npz"
            size = payload.stat().st_size
            with open(payload, "r+b") as f:
                f.seek(size // 2)
                f.write(b"\xff" * 64)
        t2 = make_trainer(tmp_path, max_steps=4, hot_save_steps=2)
        state, start = t2.restore_or_init()
        t2.ckpt.close()
        assert start == 0  # fresh start, not a crash

    def test_goodput_books_hot_bucket(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=6, hot_save_steps=2,
                         logging_steps=2)
        t.train()
        t.ckpt.close()
        gp = json.loads((tmp_path / "goodput.json").read_text())
        assert gp["buckets"]["hot_checkpoint_save"] > 0.0


# -- the committed BENCH_MODE=elastic record -------------------------------

def test_elastic_record_committed_and_affirmative():
    """The committed round-18 record must carry the acceptance
    evidence: hot-save step-time ratio inside the >= 0.9 neutrality
    band, MTTR (kill -> first frontier-advancing step) and lost steps
    STRICTLY below durable-only with hot snapshots, and the
    fault-injection fallback legs green."""
    path = REPO / "bench_records" / "elastic_cpu_r18.jsonl"
    assert path.is_file(), "run BENCH_MODE=elastic to record the legs"
    rows = [json.loads(s) for s in path.read_text().splitlines() if s]
    last = rows[-1]
    assert last["metric"] == "elastic_hot_overhead_ratio"
    assert last["value"] >= 0.9 and last["vs_baseline"] >= 1.0
    assert last["mttr_hot_below_durable"] is True
    assert last["mttr_hot_s"] < last["mttr_durable_s"]
    assert last["lost_steps_hot_below_durable"] is True
    assert last["lost_steps_hot"] < last["lost_steps_durable"]
    assert last["hot_resume_used_hot_snapshot"] is True
    assert last["resume_attempt"] == 2
    assert last["corrupt_snapshot_fallback_ok"] is True
    assert last["partial_save_fallback_ok"] is True


# -- THE ACCEPTANCE TEST (r13 CLI convention) ------------------------------

ACCEPT_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
import json, os
import numpy as np

import ddp
code = ddp.main({args!r})
assert code == 0, code

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init
from pytorch_ddp_template_tpu.train import Trainer
from pytorch_ddp_template_tpu.parallel.stacking import (
    detect_layer_layout, restack_layer_trees)

cfg = TrainingConfig.from_json(json.dumps({cfg!r}))
ctx = init(cfg)
task, ds = build(cfg.model, cfg)
t = Trainer(cfg, ctx, task, ds)
state, step = t.restore_or_init()
params = jax.device_get(state.params)
if detect_layer_layout(params) == "unrolled":
    params = restack_layer_trees(params)
leaves = [np.asarray(x).ravel() for x in jax.tree.leaves(params)]
print("FINGERPRINT", json.dumps({{"step": step,
      "digest": [float(np.sum(v)) for v in leaves],
      "l2": [float(np.sum(v * v)) for v in leaves]}}))
"""


def _accept_run(outdir, *, devices, scan, pdbs, max_steps, extra=(),
                expect_rc=0):
    cfg = dict(model="gpt-tiny", mesh=f"data:{devices}",
               per_device_train_batch_size=pdbs, dataset_size=256,
               max_steps=max_steps, logging_steps=5, save_steps=12,
               seed=7, warmup_steps=0, output_dir=str(outdir),
               scan_layers=scan)
    args = ["--model", "gpt-tiny", "--mesh", f"data:{devices}",
            "--per_device_train_batch_size", str(pdbs),
            "--dataset_size", "256", "--max_steps", str(max_steps),
            "--logging_steps", "5", "--save_steps", "12",
            "--seed", "7", "--output_dir", str(outdir)]
    if scan:
        args.append("--scan_layers")
    args += list(extra)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env["PYTHONPATH"] = str(REPO)
    p = subprocess.run(
        [sys.executable, "-u", "-c",
         ACCEPT_SCRIPT.format(args=args, cfg=cfg)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540)
    if expect_rc != 0:
        assert p.returncode == expect_rc, \
            f"expected rc={expect_rc}, got {p.returncode}:\n" \
            f"{p.stdout[-3000:]}\n{p.stderr[-2000:]}"
        return None, p
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    for line in p.stdout.splitlines():
        if line.startswith("FINGERPRINT "):
            return json.loads(line[len("FINGERPRINT "):]), p
    raise AssertionError(f"no fingerprint:\n{p.stdout[-2000:]}")


@pytest.mark.slow  # three full CLI subprocesses with compiles — the r18
#                    acceptance run (the r13 convention: slow set, still
#                    covered by `pytest tests/`)
def test_acceptance_crash_reshard_resume(tmp_path):
    """ddp.main to step 60 on 8 virtual devices (scanned, hot snapshots
    every 2) → killed by an injected hard crash at step 27 → rerun on 4
    devices with the UNROLLED layout (global batch held constant) →
    restores from the hot snapshot at 26 (> durable 24), reshards
    in-restore, trains to 60 with param/loss parity vs an uninterrupted
    run at float tolerance; goodput shows attempt 2 with
    hot_checkpoint_save + halted accounting, and any perf-regression
    WARN names the config change instead of crying wolf."""
    base = tmp_path / "uninterrupted"
    elastic = tmp_path / "elastic"

    baseline, _ = _accept_run(base, devices=8, scan=True, pdbs=2,
                              max_steps=60)
    assert baseline["step"] == 60

    # crashed leg: hard os._exit(137) at step 27, hot snapshots every 2
    _, p1 = _accept_run(elastic, devices=8, scan=True, pdbs=2,
                        max_steps=60,
                        extra=["--hot_save_steps", "2",
                               "--inject_fault", "crash:27"],
                        expect_rc=137)
    ckpts = sorted(int(d.name.split("_")[1])
                   for d in elastic.glob("checkpoint_*"))
    assert ckpts == [12, 24], ckpts  # durable tier stopped at 24
    hot_steps = sorted(int(d.name.split("_step_")[1])
                       for d in (elastic / "hot").glob("gen_*"))
    assert hot_steps[-1] == 26  # the recovery point the crash left
    # the crashed attempt still left a perf yardstick (r18: the
    # fingerprint persists at the perf cadence once the timer is steady)
    assert (elastic / "perf_baseline.json").is_file()

    # resharded resume: 4 devices, unrolled layout, same global batch
    resumed, p2 = _accept_run(elastic, devices=4, scan=False, pdbs=4,
                              max_steps=60,
                              extra=["--hot_save_steps", "2"])
    assert resumed["step"] == 60
    out = p2.stdout + p2.stderr
    assert "restored from hot snapshot" in out
    assert "reshard-on-restore: converting" in out
    describe = json.loads((elastic / "describe.json").read_text())
    assert describe["resumed_at_step"] == 26
    assert describe["attempt"] == 2
    assert describe["mesh"] == {"data": 4}

    # loss/param parity vs the uninterrupted run at float tolerance
    # (8->4 devices changes reduction order, nothing else)
    np.testing.assert_allclose(resumed["digest"], baseline["digest"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(resumed["l2"], baseline["l2"],
                               rtol=1e-4, atol=1e-5)
    base_metrics = [json.loads(l) for l in
                    (base / "metrics.jsonl").read_text().splitlines()]
    el_metrics = [json.loads(l) for l in
                  (elastic / "metrics.jsonl").read_text().splitlines()]
    last = {r["step"]: r["loss"] for r in base_metrics if "loss" in r}
    last_el = {r["step"]: r["loss"] for r in el_metrics if "loss" in r}
    assert 60 in last and 60 in last_el
    np.testing.assert_allclose(last_el[60], last[60], rtol=1e-3)

    # goodput: attempt 2, hot tier booked, the crash gap booked halted
    gp = json.loads((elastic / "goodput.json").read_text())
    assert gp["attempt"] == 2
    assert gp["buckets"]["hot_checkpoint_save"] > 0.0
    assert gp["buckets"]["halted"] > 0.0
    assert gp["buckets"]["evict_resume"] == 0.0  # no supervisor ran

    # the regression tripwire compared against the crashed attempt's
    # baseline: silence is fine (in band), but any WARN must name the
    # config change (8 devices scanned -> 4 unrolled), never a false
    # regression
    for line in out.splitlines():
        if "perf regression vs prior attempt" in line:
            assert "config changed" in line, line
    baseline_doc = json.loads((elastic / "perf_baseline.json").read_text())
    sig = baseline_doc["fingerprint"]["config_sig"]
    assert sig["n_devices"] == 4 and sig["scan_layers"] is False
