"""Decomposed TP collective matmuls (``--tp_overlap``,
parallel/collective_matmul.py): the ring-scheduled execution path must be
numerically interchangeable with the GSPMD-default TP path (same Megatron
weight layout, same math, different schedule — column ops bit-exact, row
ops/head last-ulp), refuse configurations it cannot serve with named
numbers, and keep the shared ring helpers (parallel/ring.py) honest on
both degenerate and virtual-8-device meshes."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.ops.lm_head import lm_head_loss, tp_lm_head_loss
from pytorch_ddp_template_tpu.parallel.collective_matmul import (
    hlo_tp_evidence,
    tp_column_dense,
    tp_row_dense,
    tp_wire_bytes_per_step,
    validate_tp_mesh,
)
from pytorch_ddp_template_tpu.parallel.ring import (
    axis_size,
    ring_perm,
    ring_source,
)
from pytorch_ddp_template_tpu.parallel.shard_map_compat import shard_map
from pytorch_ddp_template_tpu.runtime import make_mesh

#: observed gap between the two TP execution paths: the column op's
#: per-chunk dot is the same full-E contraction as the gathered matmul
#: (bit-exact); the row op and the ring head reassociate their cross-
#: device sums in ring order (last-f32-ulp — relative ~1e-6 regardless of
#: magnitude, which is why the grad checks are rtol-based). 1e-5 is pure
#: headroom.
TOL = 1e-5


def _mesh24():
    return make_mesh("data:2,model:4")


def _max_abs_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _assert_close(a, b, rtol=TOL, atol=TOL):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


# -- ring helper units (first direct coverage of parallel/ring.py) ---------

class TestRingHelpers:
    def test_ring_perm_is_single_hop_neighbour_cycle(self):
        assert ring_perm(4) == [(0, 1), (1, 2), (2, 3), (3, 0)]
        assert ring_perm(1) == [(0, 0)]
        for n in (1, 2, 8):
            srcs, dsts = zip(*ring_perm(n))
            assert sorted(srcs) == sorted(dsts) == list(range(n))

    def test_ring_source_tracks_rotate_after_consume(self):
        """Pure-python simulation of the rotate-after-consume schedule:
        after r applications of ring_perm, device ``my`` holds the chunk
        that originated at ``ring_source(my, r, n)``."""
        for n in (1, 2, 5, 8):
            held = list(range(n))  # held[d] = origin of d's current chunk
            for r in range(n):
                for d in range(n):
                    assert held[d] == ring_source(d, r, n)
                rotated = [None] * n
                for src, dst in ring_perm(n):
                    rotated[dst] = held[src]
                held = rotated
            assert held == list(range(n))  # full circle

    @pytest.mark.parametrize("spec,axis", [("data:-1", "data"),
                                           ("data:8,model:1", "model")])
    def test_axis_size_inside_shard_map(self, devices, spec, axis):
        """axis_size resolves the named-axis size inside a shard_map body
        on both a live 8-way axis and a degenerate size-1 axis (the
        pre-0.5 core.axis_frame fallback included)."""
        mesh = make_mesh(spec)
        n = mesh.shape[axis]

        def body(x):
            return x + axis_size(axis)

        out = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                        check_vma=False)(jnp.zeros(()))
        assert int(out) == n

    def test_device_rotation_matches_ring_source(self, devices):
        """One real ppermute rotation per step on the 8-device mesh: the
        chunk ids land exactly where ring_source says they should."""
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        perm = ring_perm(n)

        def body(ids):
            my = jax.lax.axis_index("data")
            rows = [ids]  # step 0: everyone holds their own chunk
            for _ in range(n - 1):
                ids = jax.lax.ppermute(ids, "data", perm)
                rows.append(ids)
            return jnp.stack(rows), jnp.stack(
                [ring_source(my, r, n) for r in range(n)])[:, None]

        held, predicted = shard_map(
            body, mesh=mesh, in_specs=P("data"),
            out_specs=(P(None, "data"), P(None, "data")), check_vma=False,
        )(jnp.arange(n, dtype=jnp.int32))
        np.testing.assert_array_equal(np.asarray(held),
                                      np.asarray(predicted))


# -- op-level parity -------------------------------------------------------

class TestColumnDense:
    def test_forward_bit_exact_and_grads(self, devices):
        mesh = _mesh24()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 16, 32)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((32, 64)) * 0.1, jnp.float32)
        b = jnp.asarray(rng.standard_normal((64,)) * 0.1, jnp.float32)

        ref = lambda x, w, b: x @ w + b
        tp = lambda x, w, b: tp_column_dense(x, [w], [b], mesh)[0]
        # the per-chunk dot is the same full-E contraction the gathered
        # matmul performs: bit-exact, not merely close
        np.testing.assert_array_equal(np.asarray(jax.jit(tp)(x, w, b)),
                                      np.asarray(ref(x, w, b)))
        gr = jax.grad(lambda *a: (ref(*a) ** 2).sum(), (0, 1, 2))(x, w, b)
        gt = jax.jit(jax.grad(lambda *a: (tp(*a) ** 2).sum(),
                              (0, 1, 2)))(x, w, b)
        _assert_close(gr, gt)

    def test_fused_qkv_single_ring_matches_separate(self, devices):
        """Several kernels share ONE rotation of the activation: outputs
        (incl. trailing head dims) match per-projection references."""
        mesh = _mesh24()
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        ks = [jnp.asarray(rng.standard_normal((16, 4, 8)) * 0.2, jnp.float32)
              for _ in range(3)]
        bs = [jnp.asarray(rng.standard_normal((4, 8)) * 0.2, jnp.float32)
              for _ in range(3)]
        outs = jax.jit(lambda x, ks, bs: tp_column_dense(x, ks, bs, mesh))(
            x, ks, bs)
        for y, k, b in zip(outs, ks, bs):
            expect = jnp.einsum("bte,ehd->bthd", x, k) + b
            np.testing.assert_array_equal(np.asarray(y), np.asarray(expect))

    def test_divisibility_refused_with_numbers(self, devices):
        mesh = _mesh24()
        x = jnp.zeros((2, 6, 8))  # T=6 % model:4 != 0
        with pytest.raises(ValueError, match=r"sequence length \(6\).*\(4\)"):
            tp_column_dense(x, [jnp.zeros((8, 8))], [jnp.zeros((8,))], mesh)
        x = jnp.zeros((2, 8, 8))
        with pytest.raises(ValueError, match=r"feature width \(6\)"):
            tp_column_dense(x, [jnp.zeros((8, 6))], [jnp.zeros((6,))], mesh)


class TestRowDense:
    def test_forward_and_grads_match_reference(self, devices):
        mesh = _mesh24()
        rng = np.random.default_rng(2)
        h = jnp.asarray(rng.standard_normal((4, 16, 64)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((64, 32)) * 0.1, jnp.float32)
        b = jnp.asarray(rng.standard_normal((32,)) * 0.1, jnp.float32)

        ref = lambda h, w, b: h @ w + b
        tp = lambda h, w, b: tp_row_dense(h, w, b, mesh)
        _assert_close(jax.jit(tp)(h, w, b), ref(h, w, b))
        gr = jax.grad(lambda *a: (ref(*a) ** 2).sum(), (0, 1, 2))(h, w, b)
        gt = jax.jit(jax.grad(lambda *a: (tp(*a) ** 2).sum(),
                              (0, 1, 2)))(h, w, b)
        _assert_close(gr, gt)

    def test_multidim_contraction_heads_kv(self, devices):
        """The out-projection shape: (B,T,H,D) against (H,D,E)."""
        mesh = _mesh24()
        rng = np.random.default_rng(3)
        h = jnp.asarray(rng.standard_normal((2, 8, 4, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((4, 8, 16)) * 0.2, jnp.float32)
        b = jnp.asarray(rng.standard_normal((16,)) * 0.2, jnp.float32)
        out = jax.jit(lambda *a: tp_row_dense(*a, mesh))(h, w, b)
        expect = jnp.einsum("bthd,hde->bte", h, w) + b
        assert _max_abs_diff(out, expect) < TOL

    def test_shape_mismatch_refused(self, devices):
        mesh = _mesh24()
        with pytest.raises(ValueError, match="do not match kernel"):
            tp_row_dense(jnp.zeros((2, 8, 8)), jnp.zeros((4, 16)),
                         jnp.zeros((16,)), mesh)


def test_scanned_grad_composition(devices):
    """The structure pin (collective_matmul.py module note): the ring ops
    inside a flax lifted ``nn.scan`` under ``jax.grad`` must neither leak
    tracers (the inverted custom_vjp-around-shard_map nesting did) nor
    lose parity with the unrolled reference."""
    mesh = _mesh24()

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x, _):
            k = self.param("k", nn.initializers.normal(0.2), (16, 16))
            b = self.param("b", nn.initializers.zeros, (16,))
            (y,) = tp_column_dense(x, [k], [b], mesh)
            return x + jnp.tanh(y), None

    class Stack(nn.Module):
        @nn.compact
        def __call__(self, x):
            blk = nn.scan(Block, variable_axes={"params": 0},
                          split_rngs={"params": True}, length=2)
            x, _ = blk(name="layers")(x, None)
            return x

    x = jnp.asarray(np.random.default_rng(4).standard_normal((2, 8, 16)),
                    jnp.float32)
    params = Stack().init(jax.random.PRNGKey(0), x)

    def loss(p, x):
        return (Stack().apply(p, x) ** 2).sum()

    l, g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(params, x)

    def ref_loss(p, x):
        ks = p["params"]["layers"]["k"]
        bs = p["params"]["layers"]["b"]
        for i in range(2):
            x = x + jnp.tanh(x @ ks[i] + bs[i])
        return (x ** 2).sum()

    lr, gr = jax.jit(jax.value_and_grad(ref_loss, argnums=(0, 1)))(params, x)
    np.testing.assert_allclose(float(l), float(lr), rtol=1e-6)
    _assert_close(g, gr)


# -- TP ring LM head -------------------------------------------------------

class TestTpLmHead:
    def test_matches_single_table_head(self, devices):
        """Odd T (15) and V (101): the internal seq/vocab padding must be
        invisible — logp, argmax prediction, and every grad agree with
        the single-table blockwise head."""
        mesh = _mesh24()
        rng = np.random.default_rng(5)
        B, T, E, V = 4, 15, 32, 101
        hidden = jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32)
        table = jnp.asarray(rng.standard_normal((V, E)) * 0.1, jnp.float32)
        bias = jnp.asarray(rng.standard_normal((V,)) * 0.1, jnp.float32)
        targets = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)

        lp_ref, pred_ref = lm_head_loss(hidden, table, targets, bias=bias,
                                        block=32)
        lp_tp, pred_tp = jax.jit(
            lambda h, t, b: tp_lm_head_loss(h, t, targets, mesh, bias=b,
                                            block=32))(hidden, table, bias)
        assert _max_abs_diff(lp_ref, lp_tp) < TOL
        np.testing.assert_array_equal(np.asarray(pred_ref),
                                      np.asarray(pred_tp))

        def mk(fn):
            return jax.jit(jax.grad(
                lambda h, t, b: -fn(h, t, b)[0].mean(), (0, 1, 2)))

        gr = mk(lambda h, t, b: lm_head_loss(h, t, targets, bias=b,
                                             block=32))(hidden, table, bias)
        gt = mk(lambda h, t, b: tp_lm_head_loss(h, t, targets, mesh, bias=b,
                                                block=32))(hidden, table,
                                                           bias)
        assert _max_abs_diff(gr, gt) < TOL

    def test_no_bias_path(self, devices):
        mesh = _mesh24()
        rng = np.random.default_rng(6)
        hidden = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        table = jnp.asarray(rng.standard_normal((64, 16)) * 0.1, jnp.float32)
        targets = jnp.asarray(rng.integers(0, 64, (2, 8)), jnp.int32)
        lp_ref, _ = lm_head_loss(hidden, table, targets, block=16)
        lp_tp, _ = jax.jit(lambda h, t: tp_lm_head_loss(
            h, t, targets, mesh, block=16))(hidden, table)
        assert _max_abs_diff(lp_ref, lp_tp) < TOL


# -- refusals with intent --------------------------------------------------

class TestRefusals:
    def test_config_level(self):
        with pytest.raises(ValueError, match="needs --scan_layers"):
            TrainingConfig(model="gpt-tiny", tp_overlap=True)
        # r11: the composed schedules are legal now — ddp×tp and fsdp×tp
        # construct (mesh consistency is validated at build/parse time)
        TrainingConfig(model="gpt-tiny", scan_layers=True,
                       tp_overlap=True, ddp_overlap=True)
        TrainingConfig(model="gpt-tiny", scan_layers=True,
                       tp_overlap=True, fsdp_overlap=True)
        # plain GSPMD FSDP still refuses: only the explicit gather
        # pipeline can carry the model placement through its specs
        with pytest.raises(ValueError, match="--fsdp_overlap"):
            TrainingConfig(model="gpt-tiny", scan_layers=True,
                           tp_overlap=True, fsdp=True)
        # r17: EF×tp composes — the residual leaves are sized for the
        # model-sharded layout (compress.residual_shape_tp); the config
        # constructs and the composed telescoping test in
        # tests/test_compress.py pins the numerics
        TrainingConfig(model="gpt-tiny", scan_layers=True,
                       tp_overlap=True, ddp_overlap=True,
                       grad_comm="int8", grad_error_feedback=True)

    def test_mesh_level(self, devices):
        with pytest.raises(ValueError, match="mesh"):
            validate_tp_mesh(None)
        with pytest.raises(ValueError, match="data-only / model:1"):
            validate_tp_mesh(make_mesh("data:-1"))
        with pytest.raises(ValueError, match="data-only / model:1"):
            validate_tp_mesh(make_mesh("data:8,model:1"))
        with pytest.raises(ValueError, match="seq"):
            validate_tp_mesh(make_mesh("data:2,model:2,seq:2"))

    def test_registry_level(self, devices):
        cfg = lambda name, **kw: TrainingConfig(
            model=name, scan_layers=True, tp_overlap=True, **kw)
        tp_mesh = _mesh24()
        # data-only mesh: nothing to decompose
        with pytest.raises(ValueError, match="no TP matmul to overlap"):
            build("gpt-tiny", cfg("gpt-tiny"), mesh=make_mesh("data:-1"))
        # families without a transformer stack: the co-required
        # --scan_layers gate names the problem before the TP one can
        with pytest.raises(ValueError, match="no transformer layer stack"):
            build("mlp", cfg("mlp"), mesh=tp_mesh)
        # MoE: expert dispatch needs in-region handling
        with pytest.raises(ValueError, match="MoE"):
            build("gpt-moe-tiny", cfg("gpt-moe-tiny"), mesh=tp_mesh)
        # r22: pipe×tp now COMPOSES (boundary-hoisted psums) — the
        # remaining refusal on a pipe-less mesh is the missing pipe axis
        with pytest.raises(ValueError, match="pipe"):
            build("gpt-pipe-tiny", cfg("gpt-pipe-tiny"), mesh=tp_mesh)

    def test_geometry_level(self, devices):
        # gpt-tiny has 2 heads: model:4 cannot split them
        with pytest.raises(ValueError, match=r"num_heads \(2\).*\(4\)"):
            task, ds = build("gpt-tiny",
                             TrainingConfig(model="gpt-tiny",
                                            scan_layers=True,
                                            tp_overlap=True,
                                            dataset_size=32),
                             mesh=_mesh24())
            batch = ds.batch(np.arange(4))
            task.init(jax.random.PRNGKey(0),
                      {k: jnp.asarray(v) for k, v in batch.items()})
        # vit-tiny: 17 tokens (16 patches + cls) never divide the ring
        with pytest.raises(ValueError, match=r"sequence length \(17\)"):
            task, ds = build("vit-tiny",
                             TrainingConfig(model="vit-tiny",
                                            scan_layers=True,
                                            tp_overlap=True,
                                            dataset_size=32),
                             mesh=make_mesh("data:4,model:2"))
            batch = ds.batch(np.arange(4))
            task.init(jax.random.PRNGKey(0),
                      {k: jnp.asarray(v) for k, v in batch.items()})

    def test_context_parallel_attention_refused(self, devices):
        from pytorch_ddp_template_tpu.models.transformer import (
            TransformerEncoder,
        )

        enc = TransformerEncoder(
            num_layers=2, num_heads=2, head_dim=8, mlp_dim=32,
            scan_layers=True, tp_overlap=True, attn_impl="ring",
            mesh=make_mesh("data:4,model:2"))
        with pytest.raises(ValueError, match="context-parallel"):
            enc.init(jax.random.PRNGKey(0), jnp.zeros((2, 8, 16)))


# -- describe() / wire accounting ------------------------------------------

class TestDescribeAndWires:
    def test_wire_bytes_scaling(self):
        kw = dict(batch=8, seq=128, embed=64, num_layers=2)
        assert tp_wire_bytes_per_step(**kw, n=1) == {"stack": 0, "head": 0}
        one = tp_wire_bytes_per_step(**kw, n=2)
        two = tp_wire_bytes_per_step(**kw, n=3)
        # (n-1) scaling of the per-ring payload
        assert two["stack"] * 1 == one["stack"] * 2
        assert one["head"] == 0  # no vocab -> no head rings
        withv = tp_wire_bytes_per_step(**kw, n=2, vocab=1024)
        assert withv["head"] > 0 and withv["stack"] == one["stack"]
        # bf16 halves the activation payload term
        half = tp_wire_bytes_per_step(**kw, n=2, itemsize=2)
        assert half["stack"] == one["stack"] // 2

    def test_describe_reports_tp_fields(self, devices):
        from pytorch_ddp_template_tpu.parallel.sharding import describe

        mesh = make_mesh("data:4,model:2")
        d = describe(mesh, TrainingConfig(model="gpt-tiny"))
        assert d["tp_mode"] == "gspmd-default"  # live model axis, flag off
        assert "tp_mode" not in describe(make_mesh("data:-1"),
                                         TrainingConfig(model="gpt-tiny"))

        cfg = TrainingConfig(model="gpt-tiny", scan_layers=True,
                             tp_overlap=True)
        task, _ = build("gpt-tiny", cfg, mesh=mesh)
        d = describe(mesh, cfg, model=task.model)
        assert d["tp_mode"] == "ring-decomposed"
        # batch follows the mesh describe() was handed (data:4), not the
        # config.mesh string (default data:-1 -> all 8 devices)
        wires = tp_wire_bytes_per_step(
            batch=cfg.per_device_train_batch_size * 4, seq=128, embed=64,
            num_layers=2, n=2, vocab=1024)
        assert d["tp_wire_mb_stack"] == round(wires["stack"] / 1e6, 3)
        assert d["tp_wire_mb_head"] == round(wires["head"] / 1e6, 3)
        assert d["tp_wire_mb_per_step"] == round(
            (wires["stack"] + wires["head"]) / 1e6, 3)

    def test_registry_forces_fused_head(self, devices):
        """The ring vocab head IS the LM head under --tp_overlap: the
        registry must flip fused_head on so the (B,T,V) logits tensor
        never materialises."""
        task, _ = build("gpt-tiny",
                        TrainingConfig(model="gpt-tiny", scan_layers=True,
                                       tp_overlap=True),
                        mesh=make_mesh("data:4,model:2"))
        assert task.model.fused_head and task.model.tp_overlap
        assert task.model.mesh is not None


# -- model-level parity ----------------------------------------------------

def _pair(name):
    mesh = make_mesh("data:4,model:2")
    cfg_d = TrainingConfig(model=name, dataset_size=32, scan_layers=True,
                           fused_head=True)
    cfg_t = TrainingConfig(model=name, dataset_size=32, scan_layers=True,
                           tp_overlap=True)
    task_d, ds = build(name, cfg_d, mesh=mesh)
    task_t, _ = build(name, cfg_t, mesh=mesh)
    batch = {k: jax.device_put(np.asarray(v),
                               NamedSharding(mesh, P("data")))
             for k, v in ds.batch(np.arange(8)).items()}
    return task_d, task_t, batch, mesh


def test_gpt_tiny_loss_and_grad_parity(devices):
    """The tier-1 tripwire: loss and every grad leaf agree between the
    GSPMD-default TP path and the ring-decomposed path on a data:4,model:2
    mesh (fused_head on both sides so the head math is the same blockwise
    recurrence, just differently scheduled)."""
    task_d, task_t, batch, mesh = _pair("gpt-tiny")
    assert task_t.model.tp_overlap and task_t.model.mesh is mesh
    params, _ = task_d.init(jax.random.PRNGKey(0), batch)
    params = nn.meta.unbox(params)

    def loss_of(task):
        def f(p):
            loss, _, _ = task.loss(p, {}, batch, None, train=False)
            return loss
        return jax.jit(jax.value_and_grad(f))

    ld, gd = loss_of(task_d)(params)
    lt, gt = loss_of(task_t)(params)
    np.testing.assert_allclose(float(ld), float(lt), atol=TOL)
    assert _max_abs_diff(gd, gt) < TOL


@pytest.mark.slow  # two train-step compiles per family
@pytest.mark.parametrize("name", ["gpt-tiny", "bert-tiny"])
def test_engine_step_parity(name, devices):
    """One full jitted optimizer step per LM family: the decomposed path
    updates every weight to within TOL of the GSPMD-default TP path.
    Dropout cloned OFF (bert-tiny defaults 0.1): the two paths draw
    per-layer streams identically only without it (same nn.scan split),
    and stream equality is not the math this test pins."""
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    task_d, task_t, batch, mesh = _pair(name)
    task_d.model = task_d.model.clone(dropout_rate=0.0)
    task_t.model = task_t.model.clone(dropout_rate=0.0)
    cfg = TrainingConfig(model=name, warmup_steps=0)
    key = jax.random.PRNGKey(0)
    states, metrics = {}, {}
    for tag, task in (("default", task_d), ("tp", task_t)):
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(cfg, total_steps=10)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars=extra, opt_state=tx.init(params),
                           rng=jax.random.clone(key))
        state = shard_tree(state, mesh)
        step = make_train_step(task, tx, schedule)
        states[tag], metrics[tag] = step(state, batch)
    np.testing.assert_allclose(np.asarray(metrics["default"]["loss"]),
                               np.asarray(metrics["tp"]["loss"]),
                               atol=TOL)
    assert _max_abs_diff(states["default"].params,
                         states["tp"].params) < TOL


@pytest.mark.slow
def test_hlo_ring_evidence(devices):
    """Compiled train step under --tp_overlap: both the forward and the
    backward must carry dot-carrying loop bodies whose ppermutes touch
    only loop-carried state (compute-independent — the schedulability
    witness the latency-hiding scheduler needs). Attribution: bodies in
    the loss-only lowering are forward rings; the grad lowering must add
    strictly more independent bodies (its backward rings)."""
    task_d, task_t, batch, mesh = _pair("gpt-tiny")
    params, _ = task_t.init(jax.random.PRNGKey(0), batch)
    params = nn.meta.unbox(params)

    def loss(p):
        return task_t.loss(p, {}, batch, None, train=False)[0]

    fwd = jax.jit(loss).lower(params).compile()
    grad = jax.jit(jax.grad(loss)).lower(params).compile()
    ev_fwd = hlo_tp_evidence(fwd.as_text())
    ev_full = hlo_tp_evidence(grad.as_text())
    assert ev_fwd["independent_ring_bodies"] > 0, ev_fwd
    assert (ev_full["independent_ring_bodies"]
            > ev_fwd["independent_ring_bodies"]), (ev_fwd, ev_full)
    # every ring body is clean: no ppermute consumes its own step's dot
    assert ev_full["independent_ring_bodies"] == ev_full["ring_bodies"]
