"""Tests for the CLI/config surface (reference flags: ddp.py:292-309)."""

import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig, parse_args


def test_defaults_match_reference():
    cfg = parse_args([])
    assert cfg.max_grad_norm == 1000.0  # ddp.py:305 default
    assert cfg.gradient_accumulation_steps == 1
    assert cfg.num_train_epochs == 3.0
    assert cfg.max_steps == -1
    assert cfg.seed == 42
    assert cfg.output_dir == "outputs"


def test_reference_spelling_aliases():
    cfg = parse_args([
        "--per_gpu_train_batch_size", "32",
        "--no_cuda",
        "--fp16",
        "--global-step", "500",
        "--local_rank", "2",  # accepted, ignored
    ])
    assert cfg.per_device_train_batch_size == 32
    assert cfg.cpu is True
    assert cfg.bf16 is True
    assert cfg.global_step == 500


def test_json_roundtrip(tmp_path):
    cfg = parse_args(["--seed", "7", "--warmup_steps", "100"])
    path = cfg.save(tmp_path)
    restored = TrainingConfig.from_json(path.read_text())
    assert restored == cfg


def test_from_json_ignores_unknown_keys():
    cfg = TrainingConfig.from_json('{"seed": 9, "not_a_field": true}')
    assert cfg.seed == 9


def test_train_batch_size_scales_with_devices(devices):
    cfg = TrainingConfig(per_device_train_batch_size=4)
    assert cfg.train_batch_size == 4 * len(devices)  # 8 virtual devices


def test_unknown_flag_rejected():
    with pytest.raises(SystemExit):
        parse_args(["--definitely_not_a_flag"])


def test_remat_flag_reaches_model():
    from pytorch_ddp_template_tpu.models import build

    cfg = parse_args(["--remat", "--model", "resnet18"])
    assert cfg.remat is True
    task, _ = build(cfg.model, cfg)
    assert task.model.remat is True
    # models without the knob fail loudly, not silently un-rematerialised
    with pytest.raises(ValueError, match="remat"):
        build("mlp", parse_args(["--remat", "--model", "mlp"]))


def test_fused_head_flag_reaches_model():
    from pytorch_ddp_template_tpu.models import build

    cfg = parse_args(["--fused_head", "--model", "gpt-tiny"])
    task, _ = build(cfg.model, cfg)
    assert task.model.fused_head is True
    with pytest.raises(ValueError, match="fused_head"):
        build("resnet18", parse_args(["--fused_head", "--model", "resnet18"]))


def test_preempt_sync_steps_deprecation_warning():
    """--preempt_sync_steps has been accepted-and-unused since the
    host-sync-free hot loop; passing it must say so (once), omitting it
    must stay silent and keep the historical default for config dumps."""
    import warnings

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        cfg = parse_args(["--preempt_sync_steps", "4"])
    assert cfg.preempt_sync_steps == 4
    assert any(issubclass(w.category, DeprecationWarning)
               and "preempt_sync_steps" in str(w.message) for w in rec)

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        cfg = parse_args([])
    assert cfg.preempt_sync_steps == 8
    assert not any(issubclass(w.category, DeprecationWarning) for w in rec)


def test_fsdp_overlap_implies_fsdp():
    # CLI path and direct-construction path both apply the implication
    cfg = parse_args(["--fsdp_overlap", "--scan_layers"])
    assert cfg.fsdp_overlap is True and cfg.fsdp is True
    assert TrainingConfig(fsdp_overlap=True).fsdp is True
    # and the implication survives a JSON round-trip unambiguously
    assert TrainingConfig.from_json(cfg.to_json()).fsdp is True


def test_xla_overlap_flags_parse():
    cfg = parse_args(["--xla_overlap_flags"])
    assert cfg.xla_overlap_flags is True
    assert parse_args([]).xla_overlap_flags is False
