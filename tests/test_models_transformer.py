"""Transformer model families (BERT MLM, ViT): shapes, loss semantics,
determinism, remat parity, and a short loss-goes-down run through the real
engine (the reference's implicit verification strategy, SURVEY.md §4,
applied to the rungs the reference never had)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.models.bert import MlmTask, bert_tiny
from pytorch_ddp_template_tpu.models.vit import vit_tiny


def _loss_for(name, batch_size=8):
    cfg = TrainingConfig(model=name, dataset_size=32)
    task, ds = build(name, cfg)
    batch = {k: jnp.asarray(v) for k, v in ds.batch(np.arange(batch_size)).items()}
    params, extra = task.init(jax.random.PRNGKey(0), batch)
    return task, params, extra, batch


def test_bert_tiny_loss_and_shapes():
    task, params, extra, batch = _loss_for("bert-tiny")
    loss, _, metrics = task.loss(params, extra, batch, jax.random.PRNGKey(1))
    # fresh model on uniform-random tokens: loss ~ ln(vocab)
    assert abs(float(loss) - np.log(1024)) < 1.0
    assert 0.0 <= float(metrics["mlm_accuracy"]) <= 1.0


def test_bert_masking_is_dynamic_per_step():
    task, params, extra, batch = _loss_for("bert-tiny")
    l1, _, _ = task.loss(params, extra, batch, jax.random.PRNGKey(1))
    l2, _, _ = task.loss(params, extra, batch, jax.random.PRNGKey(2))
    l1b, _, _ = task.loss(params, extra, batch, jax.random.PRNGKey(1))
    assert float(l1) != float(l2)  # different rng -> different mask
    assert float(l1) == float(l1b)  # same rng -> deterministic


def test_vit_tiny_loss_and_shapes():
    task, params, extra, batch = _loss_for("vit-tiny")
    loss, _, metrics = task.loss(params, extra, batch, jax.random.PRNGKey(1))
    assert abs(float(loss) - np.log(10)) < 0.7
    logits, _, _ = task._apply(params, extra, batch, None, train=False)
    assert logits.shape == (8, 10)


def test_vit_remat_matches_no_remat():
    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)
    m1 = vit_tiny(num_classes=10)
    m2 = vit_tiny(num_classes=10, remat=True)
    params = m1.init(jax.random.PRNGKey(0), img, train=False)["params"]
    out1 = m1.apply({"params": params}, img, train=False)
    out2 = m2.apply({"params": params}, img, train=False)
    np.testing.assert_allclose(out1, out2, atol=1e-5)


def test_bert_attention_mask_blocks_padding():
    model = bert_tiny(seq_len=32, vocab_size=64)
    ids = jnp.ones((2, 32), jnp.int32)
    attn_mask = (jnp.arange(32) < 16).astype(jnp.int32)[None].repeat(2, 0)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    base = model.apply({"params": params}, ids, attn_mask, train=False)
    # tokens in the masked-out region must not affect kept positions
    ids2 = ids.at[:, 16:].set(7)
    out2 = model.apply({"params": params}, ids2, attn_mask, train=False)
    np.testing.assert_allclose(base[:, :16], out2[:, :16], atol=1e-4)


@pytest.mark.parametrize("name", ["bert-tiny", "vit-tiny"])
def test_loss_goes_down_through_engine(name, tmp_path):
    from pytorch_ddp_template_tpu.models.task import Task  # noqa: F401
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    cfg = TrainingConfig(
        model=name, dataset_size=32, per_device_train_batch_size=1,
        learning_rate=1e-2, max_grad_norm=1.0, warmup_steps=0,
    )
    mesh = make_mesh("data:-1", jax.devices())
    key = jax.random.PRNGKey(0)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=cfg)
    task, ds = build(name, cfg)
    n = jax.device_count()
    batch = {k: jnp.asarray(v) for k, v in ds.batch(np.arange(n)).items()}
    params, extra = task.init(key, batch)
    tx, schedule = make_optimizer(cfg, total_steps=10)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       extra_vars=extra, opt_state=tx.init(params),
                       rng=jax.random.clone(key))
    step = make_train_step(task, tx, schedule)
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)  # same batch: must overfit
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
