"""Native host runtime (native/native.cc via ctypes): correctness against
an independent pure-Python implementation of the same splitmix64 /
xoshiro256** streams, plus integration with the data layer.

Skips (with a visible reason) if the library isn't built —
``make -C native`` is the one-command build."""

import numpy as np
import pytest

from pytorch_ddp_template_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="libddptpu_native.so not built (make -C native)"
)

MASK = (1 << 64) - 1
GOLDEN = 0x9E3779B97F4A7C15


def _splitmix64(state):
    state = (state + GOLDEN) & MASK
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & MASK
    return state, (z ^ (z >> 31)) & MASK


def _mix2(a, b):
    st = (a * GOLDEN + b) & MASK
    _, out = _splitmix64(st)
    return out


class _Xoshiro:
    def __init__(self, seed):
        self.s = []
        st = seed
        for _ in range(4):
            st, w = _splitmix64(st)
            self.s.append(w)

    @staticmethod
    def _rotl(x, k):
        return ((x << k) | (x >> (64 - k))) & MASK

    def next(self):
        s = self.s
        result = (self._rotl((s[1] * 5) & MASK, 7) * 9) & MASK
        t = (s[1] << 17) & MASK
        s[2] ^= s[0]
        s[3] ^= s[1]
        s[1] ^= s[2]
        s[0] ^= s[3]
        s[2] ^= t
        s[3] = self._rotl(s[3], 45)
        return result

    def bounded(self, bound):
        while True:
            x = self.next()
            m = x * bound
            low = m & MASK
            if low >= bound or low >= (-bound) % (1 << 64) % bound:
                return m >> 64


def _ref_permutation(seed, epoch, n):
    out = list(range(n))
    rng = _Xoshiro(_mix2(seed, epoch))
    for i in range(n - 1, 0, -1):
        j = rng.bounded(i + 1)
        out[i], out[j] = out[j], out[i]
    return np.asarray(out)


def _ref_synth(seed, index, nbytes):
    rng = _Xoshiro(_mix2(seed, index))
    out = b""
    while len(out) < nbytes:
        out += int(rng.next()).to_bytes(8, "little")
    return np.frombuffer(out[:nbytes], np.uint8)


def test_permutation_matches_python_reference():
    got = native.permutation(42, 3, 257)
    want = _ref_permutation(42, 3, 257)
    np.testing.assert_array_equal(got, want)


def test_permutation_is_valid_and_epoch_dependent():
    p0 = native.permutation(7, 0, 10_000)
    p1 = native.permutation(7, 1, 10_000)
    assert sorted(p0) == list(range(10_000))
    assert not np.array_equal(p0, p1)
    np.testing.assert_array_equal(p0, native.permutation(7, 0, 10_000))


def test_synth_matches_python_reference():
    idx = np.array([0, 5, 123456], np.int64)
    got = native.synth_u8(9, idx, 75)  # odd size exercises the tail word
    for row, i in zip(got, idx):
        np.testing.assert_array_equal(row, _ref_synth(9, int(i), 75))


def test_synth_threaded_matches_single_thread():
    idx = np.arange(64, dtype=np.int64)
    a = native.synth_u8(1, idx, 1024, n_threads=1)
    b = native.synth_u8(1, idx, 1024, n_threads=8)
    np.testing.assert_array_equal(a, b)


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.standard_normal((100, 17)).astype(np.float32)
    idx = rng.integers(0, 100, 40)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])
    src3 = rng.integers(0, 255, (50, 4, 6), dtype=np.uint8)
    np.testing.assert_array_equal(native.gather_rows(src3, idx % 50), src3[idx % 50])


def test_image_dataset_uses_native_and_is_deterministic():
    from pytorch_ddp_template_tpu.data.dataset import SyntheticImageDataset

    ds = SyntheticImageDataset(samples=32, image_size=8, num_classes=4, seed=3)
    b1 = ds.batch(np.array([0, 7, 31]))
    b2 = ds.batch(np.array([0, 7, 31]))
    np.testing.assert_array_equal(b1["image"], b2["image"])
    assert b1["image"].shape == (3, 8, 8, 3)
    # different seed -> different pixels
    ds2 = SyntheticImageDataset(samples=32, image_size=8, num_classes=4, seed=4)
    assert not np.array_equal(b1["image"], ds2.batch(np.array([0, 7, 31]))["image"])
