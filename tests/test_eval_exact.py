"""Exactly-once eval coverage (VERDICT.md round-3 missing #5).

The reference's ``evaluate`` is a stub (``/root/reference/ddp.py:123-124``)
and its ``DistributedSampler`` double-counts wrap-around padding; here every
held-out example must contribute to eval metrics exactly once, globally,
even when the holdout size divides neither the process count nor the global
batch. The mechanism: ``shard_validity`` marks wrap-around padding,
``ShardedLoader(with_validity=True)`` pads the ragged tail with weight-0
examples, tasks compute weighted metrics + a ``__denom__``, and
``Trainer.evaluate`` aggregates ``sum(metric*denom)/sum(denom)``.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.data import SyntheticRegressionDataset
from pytorch_ddp_template_tpu.data.loader import ShardedLoader
from pytorch_ddp_template_tpu.data.sampler import shard_indices, shard_validity
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init, make_mesh
from pytorch_ddp_template_tpu.train import Trainer


class TestShardValidity:
    def test_valid_entries_cover_each_index_exactly_once(self):
        length, shards = 103, 4
        seen: list[int] = []
        for s in range(shards):
            idx = shard_indices(length, shards, s, seed=1, epoch=2, shuffle=True)
            val = shard_validity(length, shards, s)
            assert len(idx) == len(val)
            seen.extend(int(i) for i in idx[val])
        assert sorted(seen) == list(range(length))

    def test_no_padding_when_length_divides(self):
        for s in range(4):
            assert shard_validity(100, 4, s).all()


class TestLoaderValidity:
    def test_batches_full_shape_weights_count_dataset(self):
        ds = SyntheticRegressionDataset(103, seed=0)
        mesh = make_mesh("data:8", jax.devices())
        loader = ShardedLoader(ds, mesh, 16, shuffle=True, with_validity=True)
        batches = loader._host_batches(0)
        assert len(batches) == loader.steps_per_epoch
        assert all(len(i) == 16 and len(w) == 16 for i, w in batches)
        idx_all = np.concatenate([i for i, _ in batches])
        w_all = np.concatenate([w for _, w in batches])
        assert w_all.sum() == 103
        # weight-1 entries cover the dataset exactly once
        assert sorted(idx_all[w_all == 1.0]) == list(range(103))

    def test_assembled_batch_carries_weight_array(self):
        ds = SyntheticRegressionDataset(40, seed=0)
        mesh = make_mesh("data:8", jax.devices())
        loader = ShardedLoader(ds, mesh, 16, shuffle=False, with_validity=True)
        batches = list(loader.epoch(0))
        assert len(batches) == 3  # ceil(40/16), tail padded not dropped
        for b in batches:
            assert b["__weight__"].shape == (16,)
        total = sum(float(jnp.sum(b["__weight__"])) for b in batches)
        assert total == 40.0

    def test_validity_rejects_accum(self):
        ds = SyntheticRegressionDataset(64, seed=0)
        mesh = make_mesh("data:8", jax.devices())
        with pytest.raises(ValueError, match="accum"):
            ShardedLoader(ds, mesh, 16, with_validity=True, accum_steps=2)


class TestWeightedTaskLoss:
    """Weight-0 examples must not influence any metric: replace a weighted-
    out example with garbage and nothing may change."""

    def _assert_invariant(self, task, batch_a, batch_b, w):
        la, _, ma = task.loss(*self._args(task, batch_a, w), train=False)
        lb, _, mb = task.loss(*self._args(task, batch_b, w), train=False)
        assert float(la) == pytest.approx(float(lb), rel=1e-6)
        for k in ma:
            assert float(ma[k]) == pytest.approx(float(mb[k]), rel=1e-6), k

    @staticmethod
    def _args(task, batch, w):
        params = batch.pop("__params__")
        batch = dict(batch)
        batch["__weight__"] = w
        return (params, {}, batch, None)

    def test_classification(self):
        class PoolClassifier(nn.Module):
            @nn.compact
            def __call__(self, x, *, train=True):
                return nn.Dense(7)(x.mean(axis=(1, 2)))

        from pytorch_ddp_template_tpu.models.task import ClassificationTask

        task = ClassificationTask(PoolClassifier())
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8)
        lab = rng.integers(0, 7, (4,))
        params, _ = task.init(jax.random.PRNGKey(0),
                              {"image": jnp.asarray(img), "label": jnp.asarray(lab)})
        garbage = img.copy()
        garbage[3] = 255 - garbage[3]
        w = jnp.asarray([1.0, 1.0, 1.0, 0.0])
        a = {"image": jnp.asarray(img), "label": jnp.asarray(lab),
             "__params__": params}
        b = {"image": jnp.asarray(garbage), "label": jnp.asarray(lab),
             "__params__": params}
        self._assert_invariant(task, a, b, w)

    def test_mlm(self):
        from pytorch_ddp_template_tpu.models.bert import MlmTask, bert_tiny

        task = MlmTask(bert_tiny(seq_len=16, vocab_size=256))
        rng = np.random.default_rng(1)
        ids = rng.integers(0, 256, (4, 16))
        params, _ = task.init(jax.random.PRNGKey(0),
                              {"input_ids": jnp.asarray(ids)})
        garbage = ids.copy()
        garbage[3] = (garbage[3] + 17) % 256
        w = jnp.asarray([1.0, 1.0, 1.0, 0.0])
        a = {"input_ids": jnp.asarray(ids), "__params__": params}
        b = {"input_ids": jnp.asarray(garbage), "__params__": params}
        self._assert_invariant(task, a, b, w)

    def test_causal_lm(self):
        from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, gpt_tiny

        task = CausalLmTask(gpt_tiny(seq_len=16, vocab_size=64))
        rng = np.random.default_rng(2)
        ids = rng.integers(0, 64, (4, 16))
        params, _ = task.init(jax.random.PRNGKey(0),
                              {"input_ids": jnp.asarray(ids)})
        garbage = ids.copy()
        garbage[3] = (garbage[3] + 29) % 64
        w = jnp.asarray([1.0, 1.0, 1.0, 0.0])
        a = {"input_ids": jnp.asarray(ids), "__params__": params}
        b = {"input_ids": jnp.asarray(garbage), "__params__": params}
        self._assert_invariant(task, a, b, w)


class TestEvaluateExact:
    def _trainer(self, tmp_path, eval_size):
        cfg = TrainingConfig(
            output_dir=str(tmp_path / "o"), max_steps=2,
            per_device_train_batch_size=4, dataset_size=256,
            logging_steps=0, save_steps=0,
        )
        ctx = init(cfg)
        task, ds = build("mlp", cfg)
        eval_ds = SyntheticRegressionDataset(eval_size, seed=7)
        return Trainer(cfg, ctx, task, ds, eval_dataset=eval_ds), task, eval_ds

    def test_matches_whole_set_statistic(self, tmp_path):
        # 103 examples, global batch 32: neither divides — the hard case
        t, task, eval_ds = self._trainer(tmp_path, 103)
        state, _ = t.restore_or_init()
        ev = t.evaluate(state)

        whole = eval_ds.batch(np.arange(103))
        params = jax.device_get(state.params)
        loss, _, _ = task.loss(params, {}, jax.tree.map(jnp.asarray, dict(whole)),
                               None, train=False)
        assert ev["eval_loss"] == pytest.approx(float(loss), rel=1e-5)

    def test_holdout_smaller_than_one_batch(self, tmp_path):
        t, task, eval_ds = self._trainer(tmp_path, 10)
        state, _ = t.restore_or_init()
        ev = t.evaluate(state)
        whole = eval_ds.batch(np.arange(10))
        params = jax.device_get(state.params)
        loss, _, _ = task.loss(params, {}, jax.tree.map(jnp.asarray, dict(whole)),
                               None, train=False)
        assert ev["eval_loss"] == pytest.approx(float(loss), rel=1e-5)


class TestEvaluateExactContextParallel:
    @pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
    def test_weighted_eval_on_seq_mesh(self, tmp_path):
        """Exactly-once eval composed with context parallelism: holdout of
        37 on a data:2,seq:2 mesh (batch 8) — weights shard over data,
        sequences over seq, and the aggregate must still be the whole-set
        statistic.

        Tolerance rationale (round 8): this test was parked with a ~4e-4
        relative "numeric drift" that root-caused to the PRNG, not to fp
        reassociation — under the legacy non-partitionable threefry
        lowering, GSPMD spatially partitioning the sharded jitted eval
        drew DIFFERENT uniform bits than the eager reference leg (the
        observed 4x-scaled values are shifted lane counters), so the two
        legs scored different 15% MLM subsets and even the __denom__
        values disagreed. With ``jax_threefry_partitionable=True``
        (runtime.init + conftest) both legs draw identical masks and the
        per-batch losses agree to the last printed digit; rel=1e-4 is
        therefore pure headroom for cross-batch f32 aggregation order and
        needed no widening."""
        from pytorch_ddp_template_tpu.data import SyntheticTokenDataset

        cfg = TrainingConfig(
            output_dir=str(tmp_path / "o"), max_steps=2, model="bert-long-tiny",
            mesh="data:2,seq:2,model:2", per_device_train_batch_size=4,
            dataset_size=64, logging_steps=0, save_steps=0,
        )
        ctx = init(cfg)
        task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
        eval_ds = SyntheticTokenDataset(samples=37, seq_len=512, vocab=1024,
                                        seed=9, padded=True)
        t = Trainer(cfg, ctx, task, ds, eval_dataset=eval_ds)
        state, _ = t.restore_or_init()
        ev = t.evaluate(state)
        assert np.isfinite(ev["eval_loss"]) and np.isfinite(ev["eval_mlm_accuracy"])

        # reference: same loader batching, but task.loss evaluated eagerly
        # on host arrays (MLM corruption is keyed per batch shape, so a
        # single whole-set batch would draw different masks; what this test
        # pins is that the sharded jitted eval path aggregates the exact
        # same weighted statistic as unsharded eager math)
        from pytorch_ddp_template_tpu.data.loader import ShardedLoader

        loader = ShardedLoader(eval_ds, ctx.mesh, t.config.train_batch_size,
                               seed=0, shuffle=False, with_validity=True,
                               seq_dims=task.seq_dims)
        params = jax.device_get(state.params)
        extra = jax.device_get(state.extra_vars)
        num = {"loss": 0.0, "mlm_accuracy": 0.0}
        den = 0.0
        for idx, w in loader._host_batches(0):
            host = {k: jnp.asarray(v) for k, v in eval_ds.batch(idx).items()}
            host["__weight__"] = jnp.asarray(w)
            loss, _, m = task.loss(params, extra, host, None, train=False)
            d = float(m["__denom__"])
            num["loss"] += float(loss) * d
            num["mlm_accuracy"] += float(m["mlm_accuracy"]) * d
            den += d
        assert ev["eval_loss"] == pytest.approx(num["loss"] / den, rel=1e-4)
        assert ev["eval_mlm_accuracy"] == pytest.approx(
            num["mlm_accuracy"] / den, rel=1e-4)
