"""Decomposed FSDP (``--fsdp_overlap``, parallel/overlap.py): the
prefetch-pipelined execution path must be numerically interchangeable with
the GSPMD-default FSDP path (same stacked sharded weights, same math,
different schedule), refuse configurations it cannot serve, and show the
schedule signature in compiled HLO — collectives in the layer-loop bodies
that do not consume the body's own compute."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.parallel.overlap import (
    UNSPLIT,
    hlo_overlap_evidence,
    make_layer_gather,
    overlap_scan,
    overlap_split_dims,
    validate_overlap_mesh,
)
from pytorch_ddp_template_tpu.parallel.sharding import fsdp_reshard
from pytorch_ddp_template_tpu.runtime import make_mesh

TINY = ["gpt-tiny", "bert-tiny", "vit-tiny"]

#: observed parity gap between the two FSDP execution paths is ~2e-9
#: (layer-granular split is bit-exact; the custom-vjp recompute
#: reassociates within-layer-split grads at the last f32 ulp); 1e-6 is
#: pure headroom, far below any training-visible scale
TOL = 1e-6


def _max_abs_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# -- gather/scatter units --------------------------------------------------

class TestLayerGather:
    def test_split_dims_mirror_fsdp_reshard(self, devices):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        # layer-granular (L % n == 0), within-layer fallback, and unsplit
        stacked = {
            "deep": jnp.zeros((n, 4, 6)),       # L==n -> dim 0
            "short": jnp.zeros((2, 3 * n, 6)),  # L=2 -> dim 1 (largest)
            "odd": jnp.zeros((2, 3, 5)),        # nothing divides -> unsplit
        }
        dims = overlap_split_dims(stacked, n)
        assert dims == {"deep": 0, "short": 1, "odd": UNSPLIT}
        # the chooser must agree with where fsdp_reshard actually splits
        placed = fsdp_reshard(stacked, mesh, prefer_dim=0)
        assert placed["deep"].sharding.spec[0] == "data"
        assert tuple(placed["short"].sharding.spec)[:2] == (None, "data")

    @pytest.mark.parametrize("num_layers", [None, 2])
    def test_gather_reproduces_slices_bit_exact(self, devices, num_layers):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        L = num_layers or n
        rng = np.random.default_rng(0)
        host = {
            "w": rng.standard_normal((L, 3 * n, 4)).astype(np.float32),
            "b": rng.standard_normal((L, 5)).astype(np.float32),
        }
        stacked = fsdp_reshard(jax.tree.map(jnp.asarray, host), mesh,
                               prefer_dim=0)
        gather, scatter = make_layer_gather(mesh, stacked, L)
        jg = jax.jit(gather)
        for k in range(L):
            out = jg(stacked, jnp.asarray(k, jnp.int32))
            for key in host:
                np.testing.assert_array_equal(np.asarray(out[key]),
                                              host[key][k])

    def test_scatter_writes_only_layer_k(self, devices):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        L = n
        stacked = fsdp_reshard(
            {"w": jnp.zeros((L, 2 * n, 3))}, mesh, prefer_dim=0)
        gather, scatter = make_layer_gather(mesh, stacked, L)
        g = {"w": jnp.full((2 * n, 3), 7.0)}
        out = np.asarray(jax.jit(scatter)(g, jnp.asarray(1, jnp.int32))["w"])
        expect = np.zeros((L, 2 * n, 3), np.float32)
        expect[1] = 7.0
        np.testing.assert_array_equal(out, expect)


class TestOverlapScan:
    def test_matches_reference_values_and_grads(self, devices):
        """Toy stack: y_{k+1} = tanh(y_k @ W_k). The pipelined scan (and
        its hand-written backward) must agree with straight-line math in
        both value and grads wrt weights AND input."""
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        L, d = n, 6
        rng = np.random.default_rng(1)
        w_host = rng.standard_normal((L, d, d)).astype(np.float32) * 0.3
        x_host = rng.standard_normal((4, d)).astype(np.float32)
        stacked = fsdp_reshard({"w": jnp.asarray(w_host)}, mesh,
                               prefer_dim=0)

        def apply_one(w, y, k, extras):
            return jnp.tanh(y @ w["w"])

        def overlap_loss(stacked, x):
            return jnp.sum(
                overlap_scan(apply_one, stacked, x, (), mesh) ** 2)

        def ref_loss(w, x):
            y = x
            for k in range(L):
                y = jnp.tanh(y @ w[k])
            return jnp.sum(y ** 2)

        x = jnp.asarray(x_host)
        lo, (gs, gx) = jax.jit(
            jax.value_and_grad(overlap_loss, argnums=(0, 1)))(stacked, x)
        lr, (gw_ref, gx_ref) = jax.jit(
            jax.value_and_grad(ref_loss, argnums=(0, 1)))(
            jnp.asarray(w_host), x)
        np.testing.assert_allclose(float(lo), float(lr), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(gs["w"]), np.asarray(gw_ref),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   atol=1e-5)

    def test_single_layer_stack(self, devices):
        mesh = make_mesh("data:-1")
        stacked = {"w": jnp.eye(4)[None]}  # L=1, unsplit
        out = jax.jit(lambda s, x: overlap_scan(
            lambda w, y, k, e: y @ w["w"], s, x, (), mesh))(
            stacked, jnp.ones((2, 4)))
        np.testing.assert_array_equal(np.asarray(out), np.ones((2, 4)))


# -- model-path parity -----------------------------------------------------

def _pair(name):
    cfg_d = TrainingConfig(model=name, dataset_size=32, scan_layers=True,
                           fsdp=True)
    cfg_o = TrainingConfig(model=name, dataset_size=32, scan_layers=True,
                           fsdp_overlap=True)
    mesh = make_mesh("data:-1")
    task_d, ds = build(name, cfg_d, mesh=mesh)
    task_o, _ = build(name, cfg_o, mesh=mesh)
    batch = {k: jax.device_put(np.asarray(v),
                               NamedSharding(mesh, P("data")))
             for k, v in ds.batch(np.arange(8)).items()}
    return task_d, task_o, batch, mesh


@pytest.mark.slow  # ~17s of model jits; the gather/scan units above are
#                    the tier-1 tripwire, this is the model-level pin
def test_gpt_tiny_loss_and_grad_parity(devices):
    """Within-layer-split regime (2 layers on 8 devices): loss and every
    grad leaf agree between the GSPMD-default and decomposed paths."""
    task_d, task_o, batch, mesh = _pair("gpt-tiny")
    assert task_o.model.fsdp_overlap and task_o.model.mesh is mesh
    key = jax.random.PRNGKey(0)
    params, _ = task_d.init(key, batch)
    params = fsdp_reshard(nn.meta.unbox(params), mesh, prefer_dim=0)

    def loss_of(task):
        def f(p):
            loss, _, _ = task.loss(p, {}, batch, None, train=False)
            return loss
        return jax.jit(jax.value_and_grad(f))

    ld, gd = loss_of(task_d)(params)
    lo, go = loss_of(task_o)(params)
    np.testing.assert_allclose(float(ld), float(lo), atol=TOL)
    assert _max_abs_diff(gd, go) < TOL


def test_refusals_fail_with_intent(devices):
    mesh = make_mesh("data:-1")
    with pytest.raises(ValueError, match="needs --scan_layers"):
        build("gpt-tiny", TrainingConfig(model="gpt-tiny",
                                         fsdp_overlap=True), mesh=mesh)
    with pytest.raises(ValueError, match="MoE"):
        build("gpt-moe-tiny",
              TrainingConfig(model="gpt-moe-tiny", scan_layers=True,
                             fsdp_overlap=True), mesh=mesh)
    # r22: pipe×fsdp now COMPOSES (slot-boundary gather/scatter waves)
    # — the remaining refusal on a pipe-less mesh is the missing axis
    with pytest.raises(ValueError, match="pipe"):
        build("gpt-pipe-tiny",
              TrainingConfig(model="gpt-pipe-tiny", scan_layers=True,
                             fsdp_overlap=True), mesh=mesh)
    with pytest.raises(ValueError, match="no transformer layer stack"):
        build("mlp", TrainingConfig(model="mlp", scan_layers=True,
                                    fsdp_overlap=True), mesh=mesh)
    with pytest.raises(ValueError, match="data-axis FSDP only"):
        validate_overlap_mesh(make_mesh("data:4,model:2"))
    with pytest.raises(ValueError, match="mesh"):
        validate_overlap_mesh(None)


@pytest.mark.slow
@pytest.mark.parametrize("name", TINY)
def test_engine_step_parity(name, devices):
    """One full jitted optimizer step per family: the decomposed path
    updates every weight to within TOL of the GSPMD-default path (slow:
    two train-step compiles per family). Dropout is cloned OFF (bert-tiny
    defaults 0.1): with dropout active the two paths draw per-layer
    streams differently by design (overlap folds the layer index where
    nn.scan splits) — statistically equivalent, documented in README, and
    not the math this test pins."""
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    task_d, task_o, batch, mesh = _pair(name)
    task_d.model = task_d.model.clone(dropout_rate=0.0)
    task_o.model = task_o.model.clone(dropout_rate=0.0)
    cfg = TrainingConfig(model=name, warmup_steps=0)
    key = jax.random.PRNGKey(0)
    states, metrics = {}, {}
    for tag, task in (("default", task_d), ("overlap", task_o)):
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(cfg, total_steps=10)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars=extra, opt_state=tx.init(params),
                           rng=jax.random.clone(key))
        state = shard_tree(state, mesh)
        state = state.replace(
            params=fsdp_reshard(state.params, mesh, prefer_dim=0),
            opt_state=fsdp_reshard(state.opt_state, mesh, prefer_dim=0),
        )
        step = make_train_step(task, tx, schedule)
        states[tag], metrics[tag] = step(state, batch)
    np.testing.assert_allclose(np.asarray(metrics["default"]["loss"]),
                               np.asarray(metrics["overlap"]["loss"]),
                               atol=TOL)
    assert _max_abs_diff(states["default"].params,
                         states["overlap"].params) < TOL


@pytest.mark.slow
def test_parity_against_unrolled_fsdp(devices):
    """Scan-off cross-check: the decomposed path agrees with the plain
    UNROLLED FSDP model too (through the unrolled->scanned init
    interchangeability pinned by test_scan_layers)."""
    mesh = make_mesh("data:-1")
    cfg_u = TrainingConfig(model="gpt-tiny", dataset_size=32, fsdp=True)
    task_u, ds = build("gpt-tiny", cfg_u, mesh=mesh)
    task_d, task_o, batch, _ = _pair("gpt-tiny")
    key = jax.random.PRNGKey(0)
    params_u, _ = task_u.init(key, batch)
    params_s, _ = task_o.init(key, batch)
    pu = fsdp_reshard(nn.meta.unbox(params_u), mesh)
    ps = fsdp_reshard(nn.meta.unbox(params_s), mesh, prefer_dim=0)

    def loss_of(task, p):
        return float(jax.jit(
            lambda p: task.loss(p, {}, batch, None, train=False)[0])(p))

    assert abs(loss_of(task_u, pu) - loss_of(task_o, ps)) < TOL


@pytest.mark.slow
def test_hlo_evidence_and_memory(devices):
    """Depth-8 (layer-granular) compiled train step: the loop bodies must
    show compute-independent collectives (the prefetch/re-gather), and
    the decomposed path's temp memory must stay within ~2 gathered layers
    of the default path's (the live-range bound; in practice it is far
    BELOW default, since the custom-vjp backward never stacks gathered
    weights as residuals)."""
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    mesh = make_mesh("data:-1")
    vocab, seq, depth = 128, 32, 8
    ids = np.random.default_rng(0).integers(0, vocab, (8, seq))
    batch = {"input_ids": jax.device_put(
        np.asarray(ids, np.int32), NamedSharding(mesh, P("data")))}
    cfg = TrainingConfig(warmup_steps=0)
    key = jax.random.PRNGKey(0)

    compiled = {}
    layer_bytes = None
    for overlap in (False, True):
        model = GptDecoder(vocab_size=vocab, max_len=seq, num_layers=depth,
                           num_heads=2, head_dim=16, mlp_dim=64,
                           scan_layers=True, fsdp_overlap=overlap,
                           mesh=mesh if overlap else None)
        task = CausalLmTask(model)
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(cfg, total_steps=10)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars=extra, opt_state=tx.init(params),
                           rng=jax.random.clone(key))
        state = shard_tree(state, mesh)
        state = state.replace(
            params=fsdp_reshard(state.params, mesh, prefer_dim=0),
            opt_state=fsdp_reshard(state.opt_state, mesh, prefer_dim=0),
        )
        if layer_bytes is None:
            stacked = state.params["decoder"]["layers"]
            layer_bytes = sum(
                l.size * l.dtype.itemsize for l in jax.tree.leaves(stacked)
            ) // depth
        compiled[overlap] = make_train_step(task, tx, schedule).lower(
            state, batch).compile()

    ev = hlo_overlap_evidence(compiled[True].as_text())
    assert ev["prefetch_gather_independent"], ev
    assert ev["bwd_regather_independent"], ev
    # every loop body carries collectives; the forward one is ALL
    # independent (pure prefetch)
    assert any(r["compute_dependent_collectives"] == 0
               for r in ev["bodies"]), ev
    try:
        t_default = compiled[False].memory_analysis().temp_size_in_bytes
        t_overlap = compiled[True].memory_analysis().temp_size_in_bytes
    except Exception:  # pragma: no cover - backend without the API
        return
    assert t_overlap <= t_default + 2.5 * layer_bytes, (
        f"gathered live range exceeded two layers: overlap temp "
        f"{t_overlap} vs default {t_default} + 2.5*{layer_bytes}"
    )
