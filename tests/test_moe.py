"""MoE model family: the expert-parallel mechanism integrated into a real
transformer (``gpt-moe-tiny``). Pins path equivalence (all_to_all dispatch
== dense routing), engine compatibility on an expert mesh, and learning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import available_models, build
from pytorch_ddp_template_tpu.models.moe import MoeMlpBlock
from pytorch_ddp_template_tpu.runtime import make_mesh


def make_trainer(tmp_path, mesh_spec, **over):
    """gpt-moe-tiny Trainer on the given mesh (shared by every class here)."""
    from pytorch_ddp_template_tpu.runtime import init
    from pytorch_ddp_template_tpu.train import Trainer

    kw = dict(
        output_dir=str(tmp_path / "o"), model="gpt-moe-tiny",
        mesh=mesh_spec, per_device_train_batch_size=4, dataset_size=256,
        logging_steps=0, save_steps=0, max_steps=12,
        learning_rate=1e-2, optimizer="adam",
    )
    kw.update(over)
    cfg = TrainingConfig(**kw)
    ctx = init(cfg)
    task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
    return Trainer(cfg, ctx, task, ds)


class TestMoeBlock:
    def test_dispatch_equals_dense_path(self):
        """Same params, same input: the all_to_all expert-parallel path and
        the dense fallback must agree (capacity never drops under top-1)."""
        d, t = 16, 32
        mesh = make_mesh("expert:4", jax.devices()[:4])
        x = jax.random.normal(jax.random.PRNGKey(0), (2, t // 2, d))

        dispatch = MoeMlpBlock(num_experts=4, mlp_dim=32, mesh=mesh)
        dense = MoeMlpBlock(num_experts=4, mlp_dim=32, mesh=None)
        params = dispatch.init(jax.random.PRNGKey(1), x, train=False)
        y_dispatch = dispatch.apply(params, x, train=False)
        y_dense = dense.apply(params, x, train=False)
        np.testing.assert_allclose(np.asarray(y_dispatch),
                                   np.asarray(y_dense), rtol=1e-5, atol=1e-5)

    def test_registered(self):
        assert "gpt-moe-tiny" in available_models()


class TestMoeTraining:
    @pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
    def test_trains_on_expert_mesh(self, tmp_path):
        """Full engine over data:2,expert:4 (one expert per rank, so the
        all_to_all dispatch path is live in the hot loop) — sharded
        batches, expert-sharded weights; loss must descend."""
        t = make_trainer(tmp_path, "data:2,expert:4")
        state, _ = t.restore_or_init()
        losses = []
        for epoch in range(2):
            for batch in t.loader.epoch(epoch):
                state, metrics = t.train_step(state, batch)
                losses.append(float(metrics["loss"]))
        k = len(losses) // 4
        assert sum(losses[-k:]) / k < sum(losses[:k]) / k, losses

    @pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
    def test_expert_weights_sharded_over_expert_axis(self, tmp_path):
        t = make_trainer(tmp_path, "data:2,expert:4")
        state, _ = t.restore_or_init()
        flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
        moe_leaves = [
            (jax.tree_util.keystr(path), leaf) for path, leaf in flat
            if "w_in" in jax.tree_util.keystr(path)
        ]
        assert moe_leaves, "no MoE expert weights found in params"
        for name, leaf in moe_leaves:
            spec = leaf.sharding.spec
            assert len(spec) >= 1 and spec[0] == "expert", (name, spec)


class TestRouterGradient:
    def test_gate_receives_gradient(self):
        """The top-1 softmax scale must give the router a nonzero gradient
        — argmax alone would freeze routing at initialization forever."""
        d = 16
        block = MoeMlpBlock(num_experts=4, mlp_dim=32, mesh=None)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, d))
        params = block.init(jax.random.PRNGKey(1), x, train=False)

        def loss(p):
            return jnp.sum(block.apply(p, x, train=False) ** 2)

        import flax.linen as nn

        g = jax.grad(loss)(params)
        gate_grad = np.asarray(nn.meta.unbox(g)["params"]["gate"])
        assert np.abs(gate_grad).max() > 0, "router gate gradient is zero"


class TestLoadBalanceLoss:
    def test_aux_loss_in_train_metrics_and_drives_gate(self, tmp_path):
        """Training must carry the Switch load-balance term: present in
        metrics, >= 1 (its minimum, at uniform routing), and feeding the
        gate a balance gradient beyond the top-1 scale."""
        t = make_trainer(tmp_path, "data:8", per_device_train_batch_size=1,
                         dataset_size=64, max_steps=2,
                         learning_rate=1e-3, optimizer="sgd")
        state, _ = t.restore_or_init()
        state, metrics = t.train_step(state, next(iter(t.loader.epoch(0))))
        aux = float(metrics["aux_loss"])
        assert np.isfinite(aux) and aux >= 1.0 - 1e-3, aux

    def test_eval_metrics_carry_no_aux(self, tmp_path):
        """Eval reports model quality, not the training regulariser."""
        cfg = TrainingConfig(
            output_dir=str(tmp_path / "o"), model="gpt-moe-tiny",
            mesh="data:8", per_device_train_batch_size=1, dataset_size=64,
            logging_steps=0, save_steps=0,
        )
        from pytorch_ddp_template_tpu.runtime import init

        ctx = init(cfg)
        task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
        batch = {k: jnp.asarray(v) for k, v in ds.batch(np.arange(8)).items()}
        params, extra = task.init(jax.random.PRNGKey(0), batch)
        _, _, m = task.loss(params, extra, batch, None, train=False)
        assert "aux_loss" not in m


class TestZero1Composition:
    @pytest.mark.slow  # full moe+zero1 train; spec/dispatch units stay tier-1
    def test_moe_trains_with_zero1_optimizer_sharding(self, tmp_path):
        """ZeRO-1 (opt state sharded over data) composed with expert-
        sharded MoE weights: one step must run and descend-capable state
        must remain finite — the two sharding passes touch the same
        opt-state tree and must not fight."""
        t = make_trainer(tmp_path, "data:2,expert:4",
                         per_device_train_batch_size=2, dataset_size=64,
                         max_steps=2, learning_rate=1e-3, zero1=True)
        state, _ = t.restore_or_init()
        state, metrics = t.train_step(state, next(iter(t.loader.epoch(0))))
        assert np.isfinite(float(metrics["loss"]))
        # at least one non-scalar adam moment actually sharded over data
        from pytorch_ddp_template_tpu.runtime.context import DATA_AXIS

        def uses_data(leaf):
            spec = getattr(getattr(leaf, "sharding", None), "spec", ()) or ()
            return any(
                DATA_AXIS in ((s,) if isinstance(s, str) else tuple(s or ()))
                for s in spec if s is not None
            )
        sharded = [l for l in jax.tree.leaves(state.opt_state)
                   if hasattr(l, "ndim") and l.ndim > 0 and uses_data(l)]
        assert sharded, "no optimizer-state leaf sharded over data"
