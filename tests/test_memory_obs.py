"""Round-15 memory X-ray: obs/memory.py (compile-time split + donation
audit, live-buffer census, the runtime watermark monitor with its
capacity tripwire), the phase tracking behind per-phase peak attribution,
the engine wiring — mem records through the production telemetry drain,
the mem_pressure trigger → sentry bundle path with ``memory.json``
forensics, the injected-OOM crash bundle, /metrics HBM gauges, and the
peak-HBM stamp in perf_baseline.json."""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.obs.memory import (
    MemoryMonitor,
    compile_memory_split,
    device_memory_rows,
    donation_audit,
    donation_warnings,
    forensics_payload,
    live_buffer_census,
    looks_like_oom,
    static_memory_model,
)


@pytest.fixture(scope="module")
def donated_lowered():
    f = jax.jit(lambda s, b: {k: v + b for k, v in s.items()},
                donate_argnums=(0,))
    return f.lower({"a": jnp.ones((64,)), "b": jnp.ones((16,))},
                   jnp.ones(()))


@pytest.fixture(scope="module")
def undonated_lowered():
    f = jax.jit(lambda s, b: {k: v + b for k, v in s.items()})
    return f.lower({"a": jnp.ones((64,)), "b": jnp.ones((16,))},
                   jnp.ones(()))


# -- compile-time split ------------------------------------------------------

class TestCompileMemorySplit:
    def test_split_fields_and_projection(self, donated_lowered):
        split = compile_memory_split(donated_lowered.compile())
        assert split is not None
        # 64 + 16 floats in, same out (>=: XLA may add tuple/padding
        # overhead — the split reports XLA's numbers, not ours)
        assert split["argument_bytes"] >= 4 * (64 + 16 + 1)
        assert split["output_bytes"] >= 4 * (64 + 16)
        # donated state aliases: outputs reuse the argument buffers
        assert split["alias_bytes"] == 4 * (64 + 16)
        assert split["projected_peak_bytes"] == (
            split["argument_bytes"] + split["output_bytes"]
            - split["alias_bytes"] + split["temp_bytes"]
            + split["generated_code_bytes"])

    def test_broken_backend_yields_none_not_zeros(self):
        class Broken:
            def memory_analysis(self):
                raise RuntimeError("unimplemented on this PJRT backend")

        class Absent:
            def memory_analysis(self):
                return None

        assert compile_memory_split(Broken()) is None
        assert compile_memory_split(Absent()) is None

    def test_partial_analysis_is_no_analysis(self):
        class Partial:
            def memory_analysis(self):
                class Stats:  # argument bytes only — not a usable split
                    argument_size_in_bytes = 123
                return Stats()

        assert compile_memory_split(Partial()) is None


# -- donation audit ----------------------------------------------------------

class TestDonationAudit:
    def test_donated_state_is_clean(self, donated_lowered):
        audit = donation_audit(donated_lowered.args_info)
        assert audit["available"]
        assert audit["donated_leaves"] == 2
        assert audit["undonated_leaves"] == 0
        assert audit["donated_bytes"] == 4 * (64 + 16)
        model = static_memory_model(donated_lowered.compile(),
                                    donated_lowered.args_info)
        assert model["donation_honoured"] is True
        assert donation_warnings(model) == []

    def test_undonated_state_is_named(self, undonated_lowered):
        audit = donation_audit(undonated_lowered.args_info)
        assert audit["undonated_leaves"] == 2
        assert audit["undonated_bytes"] == 4 * (64 + 16)
        assert len(audit["undonated_paths"]) == 2
        assert any("a" in p for p in audit["undonated_paths"])
        model = static_memory_model(undonated_lowered.compile(),
                                    undonated_lowered.args_info)
        warns = donation_warnings(model)
        assert warns and "NOT donated" in warns[0]
        assert "doubled state footprint" in warns[0]

    def test_unhonoured_donation_warns(self, donated_lowered):
        # donation requested, but XLA aliased (nearly) nothing: the
        # cross-check must flag it even though every leaf says donated
        model = static_memory_model(donated_lowered.compile(),
                                    donated_lowered.args_info)
        model["split"] = dict(model["split"], alias_bytes=0)
        model["donation_honoured"] = False
        warns = donation_warnings(model)
        assert warns and "unhonoured donation" in warns[0]

    def test_missing_args_info_is_unavailable_not_invented(self):
        audit = donation_audit(None)
        assert audit == {"available": False}
        model = static_memory_model(object(), None)
        assert model["available"] is False  # broken compiled too
        assert model["donation"] == {"available": False}
        assert "donation_honoured" not in model
        assert donation_warnings(model) == []


# -- live-buffer census ------------------------------------------------------

class TestLiveBufferCensus:
    def test_buckets_by_shape_dtype_sharding(self):
        keep = [jnp.ones((128, 4), jnp.float32) for _ in range(3)]
        keep.append(jnp.ones((7,), jnp.int32))
        census = live_buffer_census()
        assert census["available"]
        assert census["n_arrays"] >= 4
        big = next(b for b in census["buckets"]
                   if b["shape"] == "(128, 4)" and b["dtype"] == "float32")
        assert big["count"] >= 3
        assert big["bytes"] >= 3 * 128 * 4 * 4
        assert census["total_bytes"] >= sum(
            b["bytes"] for b in census["buckets"])
        del keep

    def test_sorted_and_bounded(self):
        arrays = [np.ones((n + 1,), np.float32) for n in range(10)]
        # numpy arrays quack enough (shape/dtype/nbytes, no sharding)
        census = live_buffer_census(arrays=arrays, top=4)
        sizes = [b["bytes"] for b in census["buckets"]]
        assert sizes == sorted(sizes, reverse=True)
        assert len(census["buckets"]) == 4
        assert census["truncated"]["buckets"] == 6
        # nothing silently dropped: head + tail == total
        assert (sum(sizes) + census["truncated"]["bytes"]
                == census["total_bytes"])

    def test_empty_is_fine(self):
        census = live_buffer_census(arrays=[])
        assert census["n_arrays"] == 0
        assert census["buckets"] == []
        assert census["truncated"] is None


# -- runtime rows + degradation ---------------------------------------------

class TestDeviceMemoryRows:
    def test_cpu_backend_degrades_to_none(self):
        # this jaxlib's CPU devices report no memory_stats: the poller
        # must say "unmeasurable", never a 0-byte watermark
        assert device_memory_rows(jax.devices()) is None

    def test_rows_shape_with_a_reporting_device(self):
        class FakeDev:
            device_kind = "fake-hbm"

            def memory_stats(self):
                return {"bytes_in_use": 100, "peak_bytes_in_use": 150,
                        "bytes_limit": 1000}

        class DeadDev:
            device_kind = "dead"

            def memory_stats(self):
                raise RuntimeError("no stats")

        rows = device_memory_rows([FakeDev(), DeadDev()])
        assert rows == [{"device": 0, "kind": "fake-hbm",
                         "bytes_in_use": 100, "peak_bytes_in_use": 150,
                         "bytes_limit": 1000}]


def fake_poll_seq(fracs, limit=1000):
    """A poll returning one device whose usage walks through ``fracs``
    of ``limit`` (repeating the last one)."""
    it = {"i": 0}

    def poll():
        f = fracs[min(it["i"], len(fracs) - 1)]
        it["i"] += 1
        return [{"device": 0, "kind": "fake", "bytes_in_use": int(limit * f),
                 "peak_bytes_in_use": int(limit * f), "bytes_limit": limit}]

    return poll


class TestMemoryMonitor:
    def test_watermark_and_record_fields(self):
        mon = MemoryMonitor(poll=fake_poll_seq([0.5, 0.7, 0.6]))
        recs = [mon.observe(s) for s in (1, 2, 3)]
        assert recs[0]["mem_measured"] == 1.0
        assert recs[0]["mem_bytes_in_use"] == 500.0
        assert recs[0]["mem_frac_of_limit"] == 0.5
        assert recs[2]["mem_watermark_bytes"] == 700.0  # high watermark
        assert mon.peak_hbm_bytes() == 700.0
        assert list(recs[0]["mem_bytes_in_use_per_device"]) == [500.0]
        assert mon.state()["limit_bytes"] == 1000.0
        assert len(mon.records()) == 3

    def test_tripwire_once_per_episode_and_rearm(self):
        fired = []
        mon = MemoryMonitor(
            budget_frac=0.9,
            on_pressure=lambda step, v: fired.append((step, v)),
            poll=fake_poll_seq([0.5, 0.95, 0.97, 0.5, 0.93]))
        for s in range(5):
            mon.observe(s)
        # one verdict for the 0.95/0.97 episode, one for the 0.93 one
        assert [s for s, _ in fired] == [1, 4]
        step, verdict = fired[0]
        assert verdict["frac_of_limit"] == 0.95
        assert verdict["budget_frac"] == 0.9
        assert verdict["bytes_limit"] == 1000

    def test_no_limit_no_tripwire(self):
        fired = []
        mon = MemoryMonitor(
            on_pressure=lambda s, v: fired.append(v),
            poll=lambda: [{"device": 0, "kind": "x", "bytes_in_use": 999,
                           "peak_bytes_in_use": 999, "bytes_limit": 0}])
        rec = mon.observe(1)
        assert fired == []
        assert "mem_frac_of_limit" not in rec  # unknown limit: no ratio

    def test_static_degradation_is_labelled(self):
        mon = MemoryMonitor(poll=lambda: None)
        assert mon.observe(1) is None  # no stats AND no model: nothing
        mon.set_static_model({"available": True, "split": {
            "argument_bytes": 10, "output_bytes": 5, "temp_bytes": 20,
            "generated_code_bytes": 1, "alias_bytes": 5,
            "projected_peak_bytes": 31}})
        rec = mon.observe(2)
        assert rec["mem_measured"] == 0.0
        assert rec["mem_projected_peak_bytes"] == 31.0
        assert "mem_bytes_in_use" not in rec  # a projection, not a reading
        assert mon.peak_hbm_bytes() == 31.0  # fingerprint falls back

    def test_never_raises(self):
        def broken():
            raise RuntimeError("poll exploded")

        mon = MemoryMonitor(poll=broken)
        assert mon.observe(1) is None

    def test_budget_frac_validated(self):
        with pytest.raises(ValueError, match="budget_frac"):
            MemoryMonitor(budget_frac=0.0)
        with pytest.raises(ValueError, match="budget_frac"):
            MemoryMonitor(budget_frac=1.5)

    def test_startup_warning_over_budget(self):
        mon = MemoryMonitor(budget_frac=0.9,
                            poll=fake_poll_seq([0.1], limit=1000))
        mon.set_static_model({"available": True, "split": {
            "argument_bytes": 100, "output_bytes": 50, "temp_bytes": 900,
            "generated_code_bytes": 0, "alias_bytes": 50,
            "projected_peak_bytes": 1000}})
        warns = mon.startup_warnings()
        assert warns and "memory budget tripwire" in warns[0]
        assert "--mem_budget_frac" in warns[0]

    def test_startup_silent_without_limit_or_in_budget(self):
        # CPU: no limit → unmeasurable, not a pass or a fail
        mon = MemoryMonitor(poll=lambda: None)
        mon.set_static_model({"available": True, "split": {
            "argument_bytes": 100, "output_bytes": 50, "temp_bytes": 900,
            "generated_code_bytes": 0, "alias_bytes": 50,
            "projected_peak_bytes": 1000}})
        assert mon.startup_warnings() == []
        # in budget: silent
        mon2 = MemoryMonitor(budget_frac=0.9,
                             poll=fake_poll_seq([0.1], limit=10_000))
        mon2.set_static_model({"available": True, "split": {
            "argument_bytes": 100, "output_bytes": 50, "temp_bytes": 900,
            "generated_code_bytes": 0, "alias_bytes": 50,
            "projected_peak_bytes": 1000}})
        assert mon2.startup_warnings() == []

    def test_phase_attribution_samples_named_phases(self):
        from pytorch_ddp_template_tpu.utils.profiler import annotate

        mon = MemoryMonitor(poll=fake_poll_seq([0.2, 0.8]))
        with annotate("eval"):
            mon.observe(1)
        mon.observe(2)  # outside any span
        peaks = mon.state()["phase_peaks"]
        assert peaks["eval"] == 200.0
        assert peaks["between_steps"] == 800.0

    def test_wire_signals_zero_fill_when_unmeasured(self):
        mon = MemoryMonitor(poll=lambda: None)
        assert mon.wire_signals() == {"mem_bytes_in_use": 0.0,
                                      "mem_frac_of_limit": 0.0}
        mon2 = MemoryMonitor(poll=fake_poll_seq([0.5]))
        mon2.observe(1)
        assert mon2.wire_signals() == {"mem_bytes_in_use": 500.0,
                                       "mem_frac_of_limit": 0.5}


# -- forensics ---------------------------------------------------------------

class TestForensics:
    def test_payload_with_monitor(self):
        mon = MemoryMonitor(poll=fake_poll_seq([0.5]))
        mon.set_static_model({"available": True, "split": {"temp_bytes": 7}})
        mon.observe(3)
        p = forensics_payload(mon)
        assert p["census"]["available"]
        assert p["static_model"]["split"]["temp_bytes"] == 7
        assert p["records"][-1]["step"] == 3
        assert p["watermark_bytes"] == 500.0

    def test_payload_without_monitor(self):
        # an OOM crash on a run without --mem_report still gets a census
        p = forensics_payload(None)
        assert p["census"]["available"]
        assert p["static_model"] is None
        assert p["records"] == []

    def test_looks_like_oom(self):
        assert looks_like_oom(MemoryError())
        assert looks_like_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1234 bytes"))
        assert looks_like_oom(RuntimeError("Failed to allocate 8GB"))
        assert looks_like_oom(RuntimeError("device OOM at step 12"))
        assert not looks_like_oom(ValueError("shapes do not match"))
        # the bare acronym matches on word boundaries only: mentioning
        # BLOOM/ZOOM must not route a crash into memory triage
        assert not looks_like_oom(RuntimeError(
            "checkpoint for BLOOM-560m not found"))

        # an exception whose __str__ raises must not raise OUT of the
        # classifier — it runs in the engine's crash handler before the
        # best-effort dump guard, and a secondary raise there would
        # mask the real crash and lose the flight record entirely
        class BrokenStr(RuntimeError):
            def __str__(self):
                raise ValueError("broken __str__")

        assert looks_like_oom(BrokenStr()) is False


# -- phase tracking ----------------------------------------------------------

class TestCurrentPhase:
    def test_stack_push_pop_and_nesting(self):
        from pytorch_ddp_template_tpu.utils.profiler import (
            annotate, current_phase,
        )

        assert current_phase() == "between_steps"
        with annotate("input_wait"):
            assert current_phase() == "input_wait"
            with annotate("device_wait"):
                assert current_phase() == "device_wait"
            assert current_phase() == "input_wait"
        assert current_phase() == "between_steps"

    def test_disabled_annotations_report_between_steps(self):
        from pytorch_ddp_template_tpu.utils.profiler import (
            annotate, current_phase, set_phase_annotations,
        )

        try:
            set_phase_annotations(False)
            with annotate("eval"):
                assert current_phase() == "between_steps"
        finally:
            set_phase_annotations(True)


# -- fingerprint direction ---------------------------------------------------

class TestPeakHbmFingerprint:
    def test_peak_hbm_in_fingerprint_and_direction(self):
        from pytorch_ddp_template_tpu.obs.regression import (
            compare_fingerprints, make_fingerprint,
        )

        prior = make_fingerprint(timer_summary={"step_time_p50_ms": 10.0},
                                 peak_hbm_bytes=1e9)
        worse = make_fingerprint(timer_summary={"step_time_p50_ms": 10.0},
                                 peak_hbm_bytes=1.5e9)
        warns = compare_fingerprints(prior, worse, threshold_pct=20.0)
        assert warns and "peak_hbm_bytes" in warns[0]
        # shrinking memory is an improvement, not a regression
        better = make_fingerprint(timer_summary={"step_time_p50_ms": 10.0},
                                  peak_hbm_bytes=0.5e9)
        assert compare_fingerprints(prior, better, threshold_pct=20.0) == []
        # absent on either side: skipped, never invented
        no_mem = make_fingerprint(timer_summary={"step_time_p50_ms": 10.0})
        assert compare_fingerprints(no_mem, worse, threshold_pct=20.0) == []


# -- config ------------------------------------------------------------------

class TestMemConfig:
    def test_budget_frac_bounds(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig

        with pytest.raises(ValueError, match="mem_budget_frac"):
            TrainingConfig(mem_budget_frac=0.0)
        with pytest.raises(ValueError, match="mem_budget_frac"):
            TrainingConfig(mem_budget_frac=1.1)
        TrainingConfig(mem_budget_frac=1.0)  # inclusive top

    def test_mem_report_needs_a_cadence(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig

        with pytest.raises(ValueError, match="cadence"):
            TrainingConfig(mem_report=True, logging_steps=0, perf_every=0)
        TrainingConfig(mem_report=True, logging_steps=0, perf_every=5)

    def test_cli_flags_parse(self):
        from pytorch_ddp_template_tpu.config import parse_args

        cfg = parse_args(["--mem_report", "--mem_budget_frac", "0.8"])
        assert cfg.mem_report
        assert cfg.mem_budget_frac == 0.8


# -- /metrics gauges ---------------------------------------------------------

class TestPrometheusMemGauges:
    def test_per_device_hbm_gauges(self):
        from pytorch_ddp_template_tpu.obs.server import prometheus_lines

        text = prometheus_lines({
            "host": 0, "step": 5,
            "records": {"mem": {"mem_bytes_in_use": 500.0,
                                "mem_frac_of_limit": 0.5,
                                "mem_bytes_in_use_per_device": [500.0]}},
            "memory": {
                "watermark_bytes": 700.0, "limit_bytes": 1000.0,
                "pressure_active": False,
                "devices": [
                    {"device": 0, "bytes_in_use": 500,
                     "peak_bytes_in_use": 700, "bytes_limit": 1000},
                    {"device": 1, "bytes_in_use": 400,
                     "peak_bytes_in_use": 600, "bytes_limit": 1000},
                ],
                "static": {"split": {"projected_peak_bytes": 900}},
            },
        })
        # per-device family under its OWN names: the host-level record
        # gauges (tpuddp_mem_bytes_in_use{host}) and the per-device
        # samples must not share a metric name, or PromQL sums over the
        # family double-count
        assert 'tpuddp_mem_device_bytes_in_use{host="0",device="0"} 500' in text
        assert 'tpuddp_mem_device_bytes_in_use{host="0",device="1"} 400' in text
        assert 'tpuddp_mem_device_peak_bytes{host="0",device="1"} 600' in text
        assert 'tpuddp_mem_device_limit_bytes{host="0",device="0"} 1000' in text
        assert 'tpuddp_mem_bytes_in_use{host="0"} 500' in text  # record gauge
        assert 'tpuddp_mem_bytes_in_use{host="0",device' not in text
        assert "tpuddp_mem_watermark_bytes" in text
        assert "tpuddp_mem_watermark_frac_of_limit" in text
        assert "tpuddp_mem_pressure_active" in text
        assert "tpuddp_mem_projected_peak_bytes" in text
        # the per-device vector in the record is a JSONL-only channel
        assert "per_device" not in text

    def test_no_memory_section_no_invented_gauges(self):
        from pytorch_ddp_template_tpu.obs.server import prometheus_lines

        text = prometheus_lines({"host": 0, "step": 1, "records": {}})
        assert "tpuddp_mem_" not in text


# -- engine integration ------------------------------------------------------

def make_trainer(out_dir, **overrides):
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(**{
        "model": "mlp", "mesh": "data:8",
        "per_device_train_batch_size": 4, "dataset_size": 512,
        "max_steps": 8, "logging_steps": 2, "save_steps": 0,
        "resume": False, "warmup_steps": 0, "max_grad_norm": 1000.0,
        "output_dir": str(out_dir), **overrides})
    ctx = rt_init(cfg)
    task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
    return Trainer(cfg, ctx, task, ds)


class TestEngineMemory:
    def test_mem_records_through_production_drain(self, tmp_path):
        """--mem_report on CPU: the static-degradation mem records land
        in metrics.jsonl (labelled mem_measured=0), the compile split +
        donation audit land on the monitor, and the clean-exit baseline
        carries peak_hbm_bytes."""
        t = make_trainer(tmp_path, mem_report=True)
        t.train()
        st = t.memory.state()
        split = (st["static"] or {}).get("split")
        assert split and split["argument_bytes"] > 0
        audit = st["static"]["donation"]
        assert audit["available"] and audit["undonated_leaves"] == 0
        assert audit["donated_leaves"] > 0
        recs = [json.loads(l) for l in
                (tmp_path / "metrics.jsonl").read_text().splitlines()]
        mem_recs = [r for r in recs if "mem_measured" in r]
        assert mem_recs, "no kind=mem records reached the writer"
        assert all(r["mem_measured"] == 0.0 for r in mem_recs)  # CPU
        assert mem_recs[0]["mem_projected_peak_bytes"] == pytest.approx(
            split["projected_peak_bytes"])
        bl = json.loads((tmp_path / "perf_baseline.json").read_text())
        assert bl["fingerprint"]["peak_hbm_bytes"] == pytest.approx(
            split["projected_peak_bytes"])

    def test_mem_pressure_trigger_to_bundle(self, tmp_path):
        """A faked memory_stats crossing the budget mid-run must ride
        the drain-thread tripwire into the sentry and dump a triage
        bundle with kind=mem_pressure and memory.json forensics — in
        warn mode the run completes."""
        from pytorch_ddp_template_tpu.obs.sentry import BUNDLE_FILES

        t = make_trainer(tmp_path, mem_report=True, anomaly="warn",
                         max_steps=24)
        t.memory._poll = fake_poll_seq([0.5, 0.97], limit=10**9)
        state = t.train()
        assert int(state.step) == 24  # warn mode: the run completes
        bundles = sorted((tmp_path / "flight_records").glob("step_*"))
        assert len(bundles) == 1
        names = {p.name for p in bundles[0].iterdir()}
        assert set(BUNDLE_FILES) <= names
        assert "memory.json" in names
        trig = json.loads((bundles[0] / "trigger.json").read_text())
        assert trig["kind"] == "mem_pressure"
        assert trig["scalars"]["frac_of_limit"] == 0.97
        assert "--mem_budget_frac" in trig["reasons"][0]
        mem = json.loads((bundles[0] / "memory.json").read_text())
        assert mem["census"]["available"]
        assert mem["static_model"]["split"]["argument_bytes"] > 0
        assert mem["records"], "the last-K mem ring is missing"

    def test_oom_crash_dumps_forensics(self, tmp_path):
        """An allocation-failure exception mid-loop must leave a crash
        bundle whose memory.json carries the census AND the compile-time
        split — the production flight-recorder path, no bench scaffolding."""
        t = make_trainer(tmp_path, mem_report=True, anomaly="warn",
                         max_steps=16)
        orig = t.train_step
        calls = {"n": 0}

        def poisoned(state, batch, *rest):
            calls["n"] += 1
            if calls["n"] == 4:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory allocating "
                    "99999999 bytes")
            return orig(state, batch, *rest)

        poisoned.lower = orig.lower
        t.train_step = poisoned
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            t.train()
        bundles = sorted((tmp_path / "flight_records").glob("step_*"))
        assert bundles
        trig = json.loads((bundles[0] / "trigger.json").read_text())
        assert trig["mode"] == "crash"
        assert trig["oom"] is True
        mem = json.loads((bundles[0] / "memory.json").read_text())
        assert mem["census"]["available"]
        assert mem["census"]["n_arrays"] > 0
        assert mem["static_model"]["split"]["temp_bytes"] is not None

    def test_oom_crash_without_mem_report_still_gets_census(self, tmp_path):
        t = make_trainer(tmp_path, anomaly="warn", max_steps=16)
        orig = t.train_step

        def poisoned(state, batch, *rest):
            raise MemoryError("host allocator gave up")

        t.train_step = poisoned
        with pytest.raises(MemoryError):
            t.train()
        bundles = sorted((tmp_path / "flight_records").glob("step_*"))
        assert bundles
        mem = json.loads((bundles[0] / "memory.json").read_text())
        assert mem["census"]["available"]
        assert mem["static_model"] is None  # nothing invented

    def test_non_oom_crash_without_monitor_has_no_memory_json(self, tmp_path):
        t = make_trainer(tmp_path, anomaly="warn", max_steps=16)

        def poisoned(state, batch, *rest):
            raise ValueError("not a memory problem")

        t.train_step = poisoned
        with pytest.raises(ValueError):
            t.train()
        bundles = sorted((tmp_path / "flight_records").glob("step_*"))
        assert bundles
        assert not (bundles[0] / "memory.json").exists()

    def test_tampered_baseline_memory_regression_warns(
            self, tmp_path, monkeypatch):
        """The r14 restore-compare convention, memory edition: attempt 1
        writes perf_baseline.json with peak_hbm_bytes; a tampered (much
        smaller) baseline makes attempt 2 WARN that the memory footprint
        regressed — even though nothing about its speed changed."""
        from pytorch_ddp_template_tpu.train import engine

        t = make_trainer(tmp_path, mem_report=True, max_steps=24)
        t.train()
        path = tmp_path / "perf_baseline.json"
        doc = json.loads(path.read_text())
        fp = doc["fingerprint"]
        assert fp["peak_hbm_bytes"] > 0
        # tamper: claim the prior attempt fit in a tenth of the memory
        fp["peak_hbm_bytes"] = fp["peak_hbm_bytes"] / 10.0
        # keep the step-time signals in-band so ONLY memory regresses
        path.write_text(json.dumps(doc))

        warned = []
        monkeypatch.setattr(
            engine.log, "warning",
            lambda msg, *a: warned.append(str(msg)))
        t2 = make_trainer(tmp_path, mem_report=True, max_steps=24,
                          regression_pct=20.0)
        t2.train()
        regs = [w for w in warned if "perf regression" in w]
        assert regs, "no regression warning for the grown memory footprint"
        assert any("peak_hbm_bytes" in w for w in regs)

    def test_status_endpoint_serves_memory(self, tmp_path):
        import urllib.request

        t = make_trainer(tmp_path, mem_report=True, status_port=-1,
                         status_host="127.0.0.1", max_steps=60)
        t.memory._poll = fake_poll_seq([0.5], limit=10**9)
        snap = {}
        metrics_text = [""]
        orig = t.train_step

        def probing(state, batch, *rest):
            out = orig(state, batch, *rest)
            if not snap and t.status is not None and t.status.port:
                try:
                    body = urllib.request.urlopen(
                        f"http://127.0.0.1:{t.status.port}/status",
                        timeout=2).read().decode()
                    s = json.loads(body)
                    if (s.get("memory") or {}).get("polls", 0) > 0:
                        snap.update(s)
                        metrics_text[0] = urllib.request.urlopen(
                            f"http://127.0.0.1:{t.status.port}/metrics",
                            timeout=2).read().decode()
                except Exception:  # noqa: BLE001 - retry next step
                    pass
            return out

        probing.lower = orig.lower
        t.train_step = probing
        t.train()
        assert snap, "no /status snapshot with memory polls was captured"
        assert snap["memory"]["watermark_bytes"] == 5e8
        assert "tpuddp_mem_device_bytes_in_use" in metrics_text[0]
        assert "tpuddp_mem_watermark_bytes" in metrics_text[0]
