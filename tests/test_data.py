"""Data-layer tests: DistributedSampler-equivalent semantics (SURVEY.md §7
hard part (a)) and global batch assembly on the 8-device CPU mesh."""

import numpy as np
import pytest

from pytorch_ddp_template_tpu.data import (
    ArrayDataset,
    ShardedLoader,
    SyntheticRegressionDataset,
    epoch_batches,
    shard_indices,
)
from pytorch_ddp_template_tpu.runtime import make_mesh


class TestShardIndices:
    def test_disjoint_cover_with_padding(self):
        length, shards = 103, 4  # ragged: pad to 104
        all_idx = [shard_indices(length, shards, s, seed=1, epoch=0) for s in range(shards)]
        sizes = {len(a) for a in all_idx}
        assert sizes == {26}  # equal count per shard
        union = np.concatenate(all_idx)
        assert set(union.tolist()) == set(range(length))  # full cover
        assert len(union) == 104  # exactly one duplicated sample (padding)

    def test_disjoint_without_padding(self):
        all_idx = [shard_indices(100, 4, s, seed=0, epoch=0) for s in range(4)]
        union = np.concatenate(all_idx)
        assert sorted(union.tolist()) == list(range(100))  # exact partition

    def test_epoch_reshuffles_deterministically(self):
        a0 = shard_indices(1000, 4, 2, seed=5, epoch=0)
        a0_again = shard_indices(1000, 4, 2, seed=5, epoch=0)
        a1 = shard_indices(1000, 4, 2, seed=5, epoch=1)
        np.testing.assert_array_equal(a0, a0_again)
        assert not np.array_equal(a0, a1)

    def test_no_shuffle_is_strided(self):
        idx = shard_indices(12, 3, 1, shuffle=False)
        np.testing.assert_array_equal(idx, [1, 4, 7, 10])

    def test_drop_last(self):
        all_idx = [shard_indices(10, 4, s, shuffle=False, drop_last=True) for s in range(4)]
        assert all(len(a) == 2 for a in all_idx)
        assert sorted(np.concatenate(all_idx).tolist()) == list(range(8))

    def test_errors(self):
        with pytest.raises(ValueError):
            shard_indices(10, 4, 4)
        with pytest.raises(ValueError):
            shard_indices(0, 1, 0)


class TestEpochBatches:
    def test_chunking(self):
        batches = epoch_batches(np.arange(10), 3)
        assert [len(b) for b in batches] == [3, 3, 3]  # tail dropped

    def test_keep_tail(self):
        batches = epoch_batches(np.arange(10), 3, drop_last=False)
        assert [len(b) for b in batches] == [3, 3, 3, 1]


class TestDatasets:
    def test_synthetic_deterministic(self):
        a = SyntheticRegressionDataset(100, seed=3)
        b = SyntheticRegressionDataset(100, seed=3)
        np.testing.assert_array_equal(a.arrays["x"], b.arrays["x"])
        assert a.arrays["x"].shape == (100, 10)
        assert a.arrays["y"].shape == (100, 5)

    def test_batch_gather(self):
        ds = ArrayDataset(x=np.arange(20).reshape(10, 2))
        out = ds.batch(np.array([3, 1]))
        np.testing.assert_array_equal(out["x"], [[6, 7], [2, 3]])

    def test_mismatched_lengths_rejected(self):
        with pytest.raises(ValueError):
            ArrayDataset(x=np.zeros(3), y=np.zeros(4))


class TestShardedLoader:
    def test_global_batch_sharded_over_mesh(self, devices):
        mesh = make_mesh("data:-1")
        ds = SyntheticRegressionDataset(256, seed=0)
        loader = ShardedLoader(ds, mesh, global_batch_size=32, seed=0, prefetch=0)
        batches = list(loader.epoch(0))
        assert len(batches) == loader.steps_per_epoch == 256 // 32
        b = batches[0]
        assert b["x"].shape == (32, 10)
        assert b["y"].shape == (32, 5)
        # sharded over 8 devices: 4 rows per device
        assert b["x"].addressable_shards[0].data.shape == (4, 10)

    def test_prefetch_equals_sync(self, devices):
        mesh = make_mesh("data:-1")
        ds = SyntheticRegressionDataset(128, seed=0)
        sync = list(ShardedLoader(ds, mesh, 32, seed=9, prefetch=0).epoch(2))
        pre = list(ShardedLoader(ds, mesh, 32, seed=9, prefetch=2).epoch(2))
        assert len(sync) == len(pre)
        for s, p in zip(sync, pre):
            np.testing.assert_array_equal(np.asarray(s["x"]), np.asarray(p["x"]))

    def test_epoch_order_changes(self, devices):
        mesh = make_mesh("data:-1")
        ds = SyntheticRegressionDataset(128, seed=0)
        loader = ShardedLoader(ds, mesh, 32, seed=0, prefetch=0)
        e0 = np.asarray(next(iter(loader.epoch(0)))["x"])
        e1 = np.asarray(next(iter(loader.epoch(1)))["x"])
        assert not np.array_equal(e0, e1)

    def test_works_with_model_axis_in_mesh(self, devices):
        mesh = make_mesh("data:4,model:2")
        ds = SyntheticRegressionDataset(64, seed=0)
        loader = ShardedLoader(ds, mesh, 16, prefetch=0)
        b = next(iter(loader.epoch(0)))
        # batch dim split over data(4) only; replicated over model(2)
        assert b["x"].shape == (16, 10)
        assert b["x"].addressable_shards[0].data.shape == (4, 10)

    def test_indivisible_batch_rejected(self, devices):
        mesh = make_mesh("data:-1")
        ds = SyntheticRegressionDataset(64)
        with pytest.raises(ValueError):
            ShardedLoader(ds, mesh, 12)  # 12 % 8 != 0


class TestLoaderRobustness:
    def test_abandoned_generator_stops_producer(self, devices):
        import threading
        from pytorch_ddp_template_tpu.runtime import make_mesh

        mesh = make_mesh("data:-1")
        ds = SyntheticRegressionDataset(512, seed=0)
        loader = ShardedLoader(ds, mesh, 32, prefetch=2)
        gen = loader.epoch(0)
        next(gen)  # consume one, abandon the rest
        gen.close()
        import time
        deadline = time.time() + 5
        while time.time() < deadline:
            if not any(t.name == "loader-prefetch" and t.is_alive()
                       for t in threading.enumerate()):
                break
            time.sleep(0.05)
        assert not any(t.name == "loader-prefetch" and t.is_alive()
                       for t in threading.enumerate())

    def test_accum_micro_dim_divisibility_checked(self, devices):
        from pytorch_ddp_template_tpu.runtime import make_mesh

        mesh = make_mesh("data:-1")  # data axis = 8
        ds = SyntheticRegressionDataset(512, seed=0)
        with pytest.raises(ValueError, match="micro batch"):
            # global 24 % data 8 == 0, but micro dim 24/2=12 and 12 % 8 != 0
            ShardedLoader(ds, mesh, 24, accum_steps=2)


class TestInputWaitCounters:
    """The loader's host input-path accounting (PR 1's counters, first
    direct unit coverage here): gather_s = producer work, consumer_wait_s
    = time the training loop stalled on the loader, producer_idle_s =
    time the prefetch thread sat blocked on a full queue. Timing asserts
    are relational/loose — this box is 2-core and noisy."""

    class _SlowBatch:
        """Dataset proxy whose batch() sleeps — a controllably slow
        producer without touching real gather code."""

        def __init__(self, inner, delay_s):
            self._inner, self._delay = inner, delay_s

        def __len__(self):
            return len(self._inner)

        def batch(self, idx):
            import time as _t

            _t.sleep(self._delay)
            return self._inner.batch(idx)

    def test_prefetch0_wait_is_the_gather_itself(self, devices):
        mesh = make_mesh("data:-1")
        ds = SyntheticRegressionDataset(128, seed=0)
        loader = ShardedLoader(ds, mesh, 32, prefetch=0)
        list(loader.epoch(0))
        s = loader.stats
        assert s["batches"] == 4
        assert s["gather_s"] > 0
        # no prefetch thread exists: the gather IS the consumer stall, and
        # nothing can be "producer idle"
        assert s["consumer_wait_s"] == s["gather_s"]
        assert s["producer_idle_s"] == 0.0

    def test_prefetch2_slow_producer_charges_consumer_wait(self, devices):
        mesh = make_mesh("data:-1")
        ds = self._SlowBatch(SyntheticRegressionDataset(128, seed=0), 0.05)
        loader = ShardedLoader(ds, mesh, 32, prefetch=2)
        list(loader.epoch(0))
        s = loader.stats
        assert s["batches"] == 4
        # an input-bound loop: the consumer genuinely waited on the
        # producer's sleeps (most of 4 x 50ms lands on the consumer)
        assert s["consumer_wait_s"] > 0.05
        assert s["gather_s"] > 4 * 0.05  # sleeps counted as producer work

    def test_prefetch2_slow_consumer_charges_producer_idle(self, devices):
        import time as _t

        mesh = make_mesh("data:-1")
        ds = SyntheticRegressionDataset(256, seed=0)
        loader = ShardedLoader(ds, mesh, 32, prefetch=2)
        waited = 0.0
        for _ in loader.epoch(0):
            _t.sleep(0.05)  # compute-bound loop: the queue stays full
            waited += 0.05
        s = loader.stats
        assert s["batches"] == 8
        # the producer spent real time blocked on the full queue...
        assert s["producer_idle_s"] > 0.05
        # ...and the consumer's wait stayed a small fraction of its own
        # "compute" time (the input path has slack, and the counters must
        # say so — this is the signal the engine logs as input_wait_ms)
        assert s["consumer_wait_s"] < waited
