"""GPT causal-LM family: loss semantics, the causality invariant (future
tokens must not affect past logits) on every attention impl, and
context-parallel causal training end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.models.gpt import gpt_tiny


def test_gpt_tiny_loss_near_uniform():
    cfg = TrainingConfig(model="gpt-tiny", dataset_size=32)
    task, ds = build("gpt-tiny", cfg)
    batch = {k: jnp.asarray(v) for k, v in ds.batch(np.arange(8)).items()}
    params, extra = task.init(jax.random.PRNGKey(0), batch)
    loss, _, metrics = task.loss(params, extra, batch, jax.random.PRNGKey(1))
    assert abs(float(loss) - np.log(1024)) < 0.5
    assert 0.0 <= float(metrics["next_token_accuracy"]) <= 1.0


@pytest.mark.parametrize("impl", ["xla", "blockwise", "flash"])
def test_causality_invariant(impl):
    """Changing token t must not change logits at positions < t."""
    model = gpt_tiny(seq_len=64, vocab_size=128, attn_impl=impl)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (2, 64)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    base = model.apply({"params": params}, ids, train=False)
    ids2 = ids.at[:, 40:].set(7)  # rewrite the future
    out2 = model.apply({"params": params}, ids2, train=False)
    np.testing.assert_allclose(base[:, :40], out2[:, :40], atol=1e-4)
    # sanity: the future DID change
    assert not np.allclose(base[:, 40:], out2[:, 40:], atol=1e-4)


@pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
def test_gpt_context_parallel_end_to_end(tmp_path):
    """gpt-long-tiny (causal ring attention) through the full Trainer on a
    data×seq mesh; causality holds under sequence sharding."""
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        model="gpt-long-tiny", mesh="data:2,seq:4", dataset_size=64,
        per_device_train_batch_size=1, max_steps=4, logging_steps=0,
        save_steps=0, learning_rate=5e-3, max_grad_norm=1.0,
        output_dir=str(tmp_path), resume=False,
    )
    mesh = make_mesh(cfg.mesh, jax.devices())
    key = jax.random.PRNGKey(cfg.seed)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=cfg)
    task, ds = build(cfg.model, cfg, mesh=mesh)
    state = Trainer(cfg, ctx, task, ds).train()
    assert int(state.step) == 4


def test_ring_causal_matches_blockwise_through_model():
    """The same weights must give the same model output (final hidden
    states — gpt_long is fused_head) whether attention runs
    ring-distributed over the seq axis or locally blockwise. Head parity
    for the fused path is pinned in tests/test_lm_head.py."""
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.models.gpt import gpt_long

    mesh = make_mesh("data:2,seq:4", jax.devices())
    ring_model = gpt_long(seq_len=64, vocab_size=128, mesh=mesh,
                          num_layers=2, num_heads=2, head_dim=32, mlp_dim=64)
    local_model = gpt_long(seq_len=64, vocab_size=128, mesh=None,
                           num_layers=2, num_heads=2, head_dim=32, mlp_dim=64)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 128, (2, 64)), jnp.int32)
    params = local_model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    local = local_model.apply({"params": params}, ids, train=False)
    ring = jax.jit(
        lambda p, i: ring_model.apply({"params": p}, i, train=False)
    )(params, ids)
    np.testing.assert_allclose(local, np.asarray(ring), atol=2e-4)

    # and through the fused blockwise head: the full task loss agrees too
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask

    batch = {"input_ids": ids}
    l_local, _, _ = CausalLmTask(local_model).loss(params, {}, batch, None,
                                                   train=False)
    l_ring, _, _ = CausalLmTask(ring_model).loss(params, {}, batch, None,
                                                 train=False)
    np.testing.assert_allclose(float(l_local), float(l_ring), rtol=1e-4)
