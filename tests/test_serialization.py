"""First direct unit tests for ``utils.serialization.json_sanitize``
(added r12, exercised only through sentry bundles until now; r13 extends
it to device arrays, nested containers and an unserialisable-object
fallback). The contract under test: whatever goes in, ``json.dumps(...,
allow_nan=False)`` must accept what comes out, and non-finite spellings
must survive in ``_repr`` siblings."""

import json

import jax.numpy as jnp
import numpy as np

from pytorch_ddp_template_tpu.utils.serialization import json_sanitize


def dumps(record):
    """The enforcement the writers apply: raises on any non-finite that
    dodged the sanitiser."""
    return json.dumps(json_sanitize(record), allow_nan=False)


class TestNonFiniteScalars:
    def test_nan_becomes_null_with_repr(self):
        out = json_sanitize({"loss": float("nan")})
        assert out["loss"] is None
        assert out["loss_repr"] == "nan"

    def test_inf_spellings_preserved(self):
        out = json_sanitize({"a": float("inf"), "b": float("-inf")})
        assert out["a"] is None and out["a_repr"] == "inf"
        assert out["b"] is None and out["b_repr"] == "-inf"

    def test_finite_values_untouched(self):
        rec = {"f": 1.5, "i": 3, "s": "x", "b": True, "n": None}
        assert json_sanitize(rec) == rec

    def test_dumps_accepts_everything(self):
        text = dumps({"loss": float("nan"), "grad": float("inf"),
                      "ok": 1.0})
        parsed = json.loads(text)  # a COMPLIANT parser must accept it
        assert parsed["loss"] is None and parsed["ok"] == 1.0


class TestLists:
    def test_flat_list_with_nan(self):
        out = json_sanitize({"v": [1.0, float("nan"), 2.0]})
        assert out["v"] == [1.0, None, 2.0]
        assert out["v_repr"] == "[1.0, nan, 2.0]"

    def test_clean_list_gets_no_repr(self):
        out = json_sanitize({"v": [1.0, 2.0]})
        assert out["v"] == [1.0, 2.0]
        assert "v_repr" not in out

    def test_nested_list_stays_parseable(self):
        out = json_sanitize({"m": [[1.0, float("nan")], [2.0, 3.0]]})
        assert out["m"] == [[1.0, None], [2.0, 3.0]]
        json.loads(dumps({"m": [[1.0, float("nan")]]}))


class TestNestedDicts:
    def test_recursion(self):
        out = json_sanitize({"outer": {"inner": float("nan"), "k": 1}})
        assert out["outer"]["inner"] is None
        assert out["outer"]["inner_repr"] == "nan"
        assert out["outer"]["k"] == 1

    def test_dict_inside_list(self):
        out = json_sanitize({"l": [{"x": float("inf")}]})
        assert out["l"][0]["x"] is None
        assert out["l"][0]["x_repr"] == "inf"


class TestDeviceArrays:
    """The triage/ledger paths hand whole device values to the sanitiser
    (the r13 contract): 0-d arrays become numbers, vectors become lists,
    non-finite elements still sanitise."""

    def test_numpy_scalar(self):
        out = json_sanitize({"x": np.float32(2.5)})
        assert out["x"] == 2.5
        json.loads(dumps({"x": np.float32(2.5)}))

    def test_numpy_nan_scalar(self):
        out = json_sanitize({"x": np.float64("nan")})
        assert out["x"] is None and out["x_repr"] == "nan"

    def test_jax_scalar_and_vector(self):
        out = json_sanitize({"s": jnp.float32(1.5),
                             "v": jnp.asarray([1.0, 2.0])})
        assert out["s"] == 1.5
        assert out["v"] == [1.0, 2.0]

    def test_jax_vector_with_nonfinite(self):
        v = jnp.asarray([1.0, float("nan"), float("inf")])
        out = json_sanitize({"v": v})
        assert out["v"] == [1.0, None, None]
        assert "nan" in out["v_repr"] and "inf" in out["v_repr"]
        json.loads(dumps({"v": v}))

    def test_numpy_matrix_nests(self):
        out = json_sanitize({"m": np.ones((2, 2), np.float32)})
        assert out["m"] == [[1.0, 1.0], [1.0, 1.0]]


class TestUnserialisableFallback:
    def test_object_becomes_repr(self):
        class Opaque:
            def __repr__(self):
                return "<opaque thing>"

        out = json_sanitize({"o": Opaque()})
        assert out["o"] == "<opaque thing>"
        json.loads(dumps({"o": Opaque()}))

    def test_object_inside_list(self):
        class Opaque:
            def __repr__(self):
                return "<elem>"

        out = json_sanitize({"l": [1, Opaque()]})
        assert out["l"] == [1, "<elem>"]

    def test_bool_is_not_mistaken_for_int(self):
        out = json_sanitize({"flag": True})
        assert out["flag"] is True


class TestWriterIntegration:
    def test_metrics_writer_path_round_trips(self, tmp_path):
        """The MetricsWriter's exact call pattern: sanitize + allow_nan
        enforcement on a record carrying the sentry's worst case."""
        rec = {"step": 3, "loss": float("nan"),
               "per_layer_grad_norm": [1.0, float("inf")]}
        parsed = json.loads(dumps(rec))
        assert parsed["step"] == 3
        assert parsed["loss"] is None
        assert parsed["per_layer_grad_norm"] == [1.0, None]
        assert parsed["loss_repr"] == "nan"  # the spelling survives
        assert parsed["per_layer_grad_norm_repr"] == "[1.0, inf]"
