"""Worker process for the two-process distributed rehearsal.

Launched (twice) by ``test_multiprocess.py``. Everything the single-process
suite can only fake runs for real here: ``jax.distributed.initialize``
rendezvous (the reference's ``init_process_group``, ``/root/reference/
ddp.py:103``), the init-time native-RNG agreement allgather, per-process
loader sharding feeding ``make_array_from_process_local_data``, SPMD train
steps over a cross-process mesh, cross-host divergence detection, and an
orbax multi-host save/restore round-trip.

Writes ``result_<proc>.json`` into the work dir; exit code 0 iff all
stages ran.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path


def main() -> int:
    proc_id, coord, workdir = int(sys.argv[1]), sys.argv[2], Path(sys.argv[3])
    repo = Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo))

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.checkpoint.manager import CheckpointManager
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.data import SyntheticRegressionDataset
    from pytorch_ddp_template_tpu.data.loader import ShardedLoader
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.parallel import shard_tree
    from pytorch_ddp_template_tpu.runtime import init, shutdown
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )
    from pytorch_ddp_template_tpu.utils import divergence

    result: dict = {"proc": proc_id}

    cfg = TrainingConfig(
        cpu=True,
        coordinator_address=coord,
        num_processes=2,
        process_id=proc_id,
        mesh="data:8",
        per_device_train_batch_size=2,
        dataset_size=256,
        output_dir=str(workdir / "ckpt"),
        warmup_steps=0,
    )
    ctx = init(cfg)  # exercises rendezvous + native-RNG agreement allgather
    result["process_count"] = jax.process_count()
    result["local_devices"] = jax.local_device_count()
    result["global_devices"] = jax.device_count()

    # -- loader: per-process disjoint cover --------------------------------
    ds = SyntheticRegressionDataset(100, seed=0)
    loader = ShardedLoader(ds, ctx.mesh, 16, seed=5, shuffle=True)
    idx = np.concatenate([i for i, _ in loader._host_batches(0)])
    result["loader_indices"] = [int(i) for i in idx]

    # -- SPMD train steps over the cross-process mesh, with FSDP -----------
    # mlp-wide so the 1024-wide weights have a data-dividable dim: params
    # and optimizer state live sharded ACROSS THE TWO PROCESSES, and the
    # orbax round-trip below saves/restores genuinely distributed arrays
    from pytorch_ddp_template_tpu.parallel import fsdp_reshard

    task, train_ds = build("mlp-wide", cfg)
    train_loader = ShardedLoader(train_ds, ctx.mesh, cfg.train_batch_size,
                                 seed=cfg.seed)
    tx, schedule = make_optimizer(cfg, total_steps=100)
    batches = iter(train_loader.epoch(0))
    first = next(batches)
    params, extra = task.init(ctx.seed_key, first)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       extra_vars=extra, opt_state=tx.init(params),
                       rng=jax.random.clone(ctx.seed_key))
    state = shard_tree(state, ctx.mesh)
    state = state.replace(params=fsdp_reshard(state.params, ctx.mesh),
                          opt_state=fsdp_reshard(state.opt_state, ctx.mesh))
    result["fsdp_param_sharded"] = any(
        "data" in str(x.sharding.spec)
        for x in jax.tree.leaves(state.params)
        if hasattr(x, "sharding") and x.ndim >= 1
    )
    step = make_train_step(task, tx, schedule)
    state, metrics = step(state, first)
    state, metrics = step(state, next(batches))
    result["loss"] = float(metrics["loss"])

    # -- divergence detector: agreement, then an injected param flip -------
    result["divergence_clean"] = divergence.check(state.params, step=2)
    probe = {"w": jnp.ones((4,)) * (1.0 + proc_id)}  # differs per process
    result["divergence_flagged"] = not divergence.check(probe, step=2)

    # -- orbax multi-host save/restore round-trip --------------------------
    ckpt = CheckpointManager(workdir / "ckpt")
    ckpt.save(2, state, cfg, force=True)
    ckpt.wait()
    template = jax.tree.map(jnp.zeros_like, state)
    restored, cfg_dict = ckpt.restore(2, template)

    def shards_equal(a, b):
        # FSDP leaves span both processes: a whole-array fetch is illegal
        # by design — compare this process's addressable shards
        if not hasattr(a, "addressable_shards"):
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        return all(
            np.array_equal(np.asarray(x.data), np.asarray(y.data))
            for x, y in zip(a.addressable_shards, b.addressable_shards)
        )

    same = jax.tree.map(shards_equal, state.params, restored.params)
    result["ckpt_roundtrip"] = all(jax.tree.leaves(same))
    result["ckpt_step"] = int(restored.step)
    ckpt.close()

    (workdir / f"result_{proc_id}.json").write_text(json.dumps(result))
    shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
