"""File-backed data rung (VERDICT r1 #6): store round-trip, memmap gather
parity, on-device augmentation, and resnet18 training from disk through
the full Trainer. Reference analogue: ``/root/reference/dataset.py:6-17``
+ ``ddp.py:148-152`` (host-RAM only; this generalises it to disk)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.data.filestore import (
    MemmapDataset,
    StoreWriter,
    materialize,
    write_store,
)
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import make_mesh
from pytorch_ddp_template_tpu.runtime.context import RuntimeContext


def _arrays(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": rng.integers(0, 256, (n, 8, 8, 3), dtype=np.uint8),
        "label": rng.integers(0, 10, (n,), dtype=np.int32),
    }


def test_store_roundtrip(tmp_path):
    arrays = _arrays()
    write_store(tmp_path / "store", arrays, chunk=64)
    ds = MemmapDataset(tmp_path / "store")
    assert len(ds) == 200
    idx = np.asarray([0, 5, 199, 5])
    got = ds.batch(idx)
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k][idx])
    # large batches route through the native threaded gather when built
    idx_big = np.arange(128) % 200
    got_big = ds.batch(idx_big)
    for k in arrays:
        np.testing.assert_array_equal(got_big[k], arrays[k][idx_big])


def test_store_writer_schema_enforced(tmp_path):
    with StoreWriter(tmp_path / "s") as w:
        w.append(_arrays(16))
        with pytest.raises(ValueError, match="schema"):
            w.append({"image": np.zeros((4, 9, 9, 3), np.uint8),
                      "label": np.zeros((4,), np.int32)})
        w.append(_arrays(8, seed=1))
    meta = json.loads((tmp_path / "s" / "meta.json").read_text())
    assert meta["samples"] == 24


def test_incomplete_store_rejected(tmp_path):
    d = tmp_path / "broken"
    d.mkdir()
    (d / "image.bin").write_bytes(b"\x00" * 64)  # no meta.json
    with pytest.raises(FileNotFoundError, match="meta.json"):
        MemmapDataset(d)


def test_truncated_bin_rejected(tmp_path):
    write_store(tmp_path / "s", _arrays(32))
    path = tmp_path / "s" / "image.bin"
    path.write_bytes(path.read_bytes()[:-7])
    with pytest.raises(ValueError, match="bytes"):
        MemmapDataset(tmp_path / "s")


def test_materialize_matches_source(tmp_path):
    cfg = TrainingConfig(model="resnet18", dataset_size=96)
    _, synth = build("resnet18", cfg)
    materialize(synth, tmp_path / "s", chunk=40)
    ds = MemmapDataset(tmp_path / "s")
    idx = np.arange(96)
    a, b = synth.batch(idx), ds.batch(idx)
    np.testing.assert_array_equal(a["image"], b["image"])
    np.testing.assert_array_equal(a["label"], b["label"])


def test_augment_on_device():
    from pytorch_ddp_template_tpu.models.task import ClassificationTask

    cfg = TrainingConfig(model="resnet18", dataset_size=32, augment="crop-flip")
    task, ds = build("resnet18", cfg)
    assert isinstance(task, ClassificationTask) and task.augment == "crop-flip"
    batch = {k: jnp.asarray(v) for k, v in ds.batch(np.arange(8)).items()}
    params, extra = task.init(jax.random.PRNGKey(0), batch)

    l1, _, _ = task.loss(params, extra, batch, jax.random.PRNGKey(1))
    l1b, _, _ = task.loss(params, extra, batch, jax.random.PRNGKey(1))
    l2, _, _ = task.loss(params, extra, batch, jax.random.PRNGKey(2))
    le, _, _ = task.loss(params, extra, batch, None, train=False)
    assert float(l1) == float(l1b)  # deterministic in rng
    assert float(l1) != float(l2)  # augmentation actually varies
    assert np.isfinite(float(le))  # eval path: no augmentation, no rng


@pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
def test_resnet18_trains_from_disk(tmp_path):
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(model="resnet18", dataset_size=64, seed=3)
    _, synth = build("resnet18", cfg)
    materialize(synth, tmp_path / "store", samples=64)

    file_cfg = TrainingConfig(
        model="resnet18", data_dir=str(tmp_path / "store"),
        per_device_train_batch_size=2, max_steps=3, logging_steps=0,
        save_steps=0, output_dir=str(tmp_path / "out"), resume=False,
        augment="crop-flip", max_grad_norm=1.0,
    )
    mesh = make_mesh("data:8", jax.devices())
    task, ds = build(file_cfg.model, file_cfg)
    assert isinstance(ds, MemmapDataset)
    key = jax.random.PRNGKey(file_cfg.seed)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=file_cfg)
    trainer = Trainer(file_cfg, ctx, task, ds)
    state = trainer.train()
    assert int(state.step) == 3


def test_data_dir_rejected_for_storeless_models(tmp_path):
    write_store(tmp_path / "s", _arrays(32))
    cfg = TrainingConfig(model="mlp", data_dir=str(tmp_path / "s"))
    with pytest.raises(ValueError, match="not supported"):
        build("mlp", cfg)


def test_gpt_trains_from_token_store(tmp_path):
    """VERDICT r4 #4: --data_dir works for the token families — materialise
    the synthetic token source, then build + train gpt-tiny from disk with
    batch-level equality against the in-RAM source."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(model="gpt-tiny", dataset_size=64, seed=3)
    _, synth = build("gpt-tiny", cfg)
    materialize(synth, tmp_path / "store", samples=64)

    file_cfg = TrainingConfig(
        model="gpt-tiny", data_dir=str(tmp_path / "store"),
        per_device_train_batch_size=2, max_steps=3, logging_steps=0,
        save_steps=0, output_dir=str(tmp_path / "out"), resume=False,
    )
    task, ds = build(file_cfg.model, file_cfg)
    assert isinstance(ds, MemmapDataset)
    idx = np.arange(16)
    ref, got = synth.batch(idx), ds.batch(idx)
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k])

    mesh = make_mesh("data:8", jax.devices())
    key = jax.random.PRNGKey(file_cfg.seed)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=file_cfg)
    state = Trainer(file_cfg, ctx, task, ds).train()
    assert int(state.step) == 3


@pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
def test_padded_long_model_trains_from_token_store(tmp_path):
    """The long-context (padded) families consume attention_mask from the
    store; the mask key is required and the Trainer runs from disk."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(model="bert-long-tiny", dataset_size=32, seed=3)
    _, synth = build("bert-long-tiny", cfg)
    materialize(synth, tmp_path / "store", samples=32)

    file_cfg = TrainingConfig(
        model="bert-long-tiny", data_dir=str(tmp_path / "store"),
        per_device_train_batch_size=2, max_steps=2, logging_steps=0,
        save_steps=0, output_dir=str(tmp_path / "out"), resume=False,
    )
    task, ds = build(file_cfg.model, file_cfg)
    assert isinstance(ds, MemmapDataset)
    assert "attention_mask" in ds.arrays
    mesh = make_mesh("data:8", jax.devices())
    key = jax.random.PRNGKey(file_cfg.seed)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=file_cfg)
    state = Trainer(file_cfg, ctx, task, ds).train()
    assert int(state.step) == 2


def test_token_store_validation(tmp_path):
    # an image store offered to a token model: missing input_ids
    write_store(tmp_path / "img", _arrays(32))
    cfg = TrainingConfig(model="gpt-tiny", data_dir=str(tmp_path / "img"))
    with pytest.raises(ValueError, match="input_ids"):
        build("gpt-tiny", cfg)

    # wrong sequence length
    write_store(tmp_path / "short", {
        "input_ids": np.zeros((16, 64), np.int32)})
    cfg = TrainingConfig(model="gpt-tiny", data_dir=str(tmp_path / "short"))
    with pytest.raises(ValueError, match=r"expects \[128\]"):
        build("gpt-tiny", cfg)

    # token ids beyond the model vocab (gpt-tiny vocab 1024)
    write_store(tmp_path / "oob", {
        "input_ids": np.full((16, 128), 5000, np.int32)})
    cfg = TrainingConfig(model="gpt-tiny", data_dir=str(tmp_path / "oob"))
    with pytest.raises(ValueError, match="vocab"):
        build("gpt-tiny", cfg)

    # a long-context (padded) model requires the attention_mask key
    write_store(tmp_path / "nomask", {
        "input_ids": np.zeros((16, 512), np.int32)})
    cfg = TrainingConfig(model="bert-long-tiny",
                         data_dir=str(tmp_path / "nomask"))
    with pytest.raises(ValueError, match="attention_mask"):
        build("bert-long-tiny", cfg)


def test_store_dtype_and_label_range_validated(tmp_path):
    bad_dtype = {
        "image": np.zeros((16, 32, 32, 3), np.float32),
        "label": np.zeros((16,), np.int32),
    }
    write_store(tmp_path / "f32", bad_dtype)
    cfg = TrainingConfig(model="resnet18", data_dir=str(tmp_path / "f32"))
    with pytest.raises(ValueError, match="uint8"):
        build("resnet18", cfg)

    bad_label = {
        "image": np.zeros((16, 32, 32, 3), np.uint8),
        "label": np.full((16,), 10, np.int32),  # resnet18 has 10 classes
    }
    write_store(tmp_path / "lbl", bad_label)
    cfg = TrainingConfig(model="resnet18", data_dir=str(tmp_path / "lbl"))
    with pytest.raises(ValueError, match="classes"):
        build("resnet18", cfg)


def test_file_backed_eval_split_holds_out_tail(tmp_path):
    import ddp as cli

    write_store(tmp_path / "s", {
        "image": np.zeros((200, 32, 32, 3), np.uint8),
        "label": np.zeros((200,), np.int32),
    })
    cfg = TrainingConfig(model="resnet18", data_dir=str(tmp_path / "s"),
                         per_device_train_batch_size=2, eval_steps=1)
    _, ds = build("resnet18", cfg)
    train, ev = cli.train_eval_split(cfg, ds)
    assert len(train) + len(ev) == 200
    assert len(ev) >= cfg.train_batch_size
    # disjoint: eval rows are the store's tail
    ev_batch = ev.batch(np.arange(len(ev)))
    assert len(ev_batch["label"]) == len(ev)


def test_store_shape_mismatch_rejected(tmp_path):
    write_store(tmp_path / "s", _arrays(32))  # 8x8 images
    cfg = TrainingConfig(model="resnet18", data_dir=str(tmp_path / "s"))
    with pytest.raises(ValueError, match="expects"):
        build("resnet18", cfg)
