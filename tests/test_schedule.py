"""Unified decomposed-scan framework (parallel/schedule.py): the composed
fsdp×tp and ddp×tp execution paths must be numerically interchangeable
with the FLOPs-matched GSPMD default on the same ``data×model`` mesh
(loss + every grad leaf, rtol per the r10 ring-reassociation convention),
the static TP-spec table must agree with the init-time flax metadata,
the combinations that remain unsupported must refuse with named reasons
at the earliest level (config parse > registry build > mesh validation),
and the composed lowering must show BOTH axes' collectives compute-
independent in one scanned body (slow leg)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_ddp_template_tpu.config import TrainingConfig, parse_args
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.parallel.overlap import overlap_scan
from pytorch_ddp_template_tpu.parallel.schedule import (
    PlainSchedule,
    decomposed_scan,
    hlo_composed_evidence,
    stacked_tp_specs,
    validate_schedule_mesh,
)
from pytorch_ddp_template_tpu.parallel.sharding import (
    active_rules, fsdp_reshard,
)
from pytorch_ddp_template_tpu.runtime import make_mesh

#: the r10 convention: column ops bit-exact, row ops / ring head / gather
#: psums reassociate cross-device sums at the last f32 ulp; 1e-5 is pure
#: headroom (observed composed-vs-default grad gap ~3e-8)
TOL = 1e-5


def _max_abs_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _mesh42():
    return make_mesh("data:4,model:2")


# -- toy-level skeleton units ----------------------------------------------

class TestDecomposedScanToy:
    def _ref(self, tree, x, L):
        y = x
        for k in range(L):
            h = jnp.tanh(y @ tree["w1"][k] + tree["b1"][k])
            y = y + h @ tree["w2"][k] + tree["b2"][k]
        return (y ** 2).sum()

    def _host_tree(self, L, E, F):
        rng = np.random.default_rng(0)
        return {
            "w1": (rng.standard_normal((L, E, F)) * 0.2).astype(np.float32),
            "b1": (rng.standard_normal((L, F)) * 0.1).astype(np.float32),
            "w2": (rng.standard_normal((L, F, E)) * 0.2).astype(np.float32),
            "b2": (rng.standard_normal((L, E)) * 0.1).astype(np.float32),
        }

    def test_plain_schedule_matches_reference(self, devices):
        """The null weight schedule (tp-only shape): slice + GSPMD apply
        + per-layer grad stacking, values and grads vs straight-line."""
        L, E, F = 3, 4, 6
        host = self._host_tree(L, E, F)
        tree = jax.tree.map(jnp.asarray, host)
        x = jnp.asarray(np.random.default_rng(1).standard_normal((2, E)),
                        jnp.float32)

        def apply_one(w, y, k, extras):
            return y + jnp.tanh(y @ w["w1"] + w["b1"]) @ w["w2"] + w["b2"]

        def loss(tree, x):
            return (decomposed_scan(PlainSchedule(), apply_one, tree, x,
                                    ()) ** 2).sum()

        l, (g, gx) = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1)))(tree, x)
        lr, (gr, gxr) = jax.jit(jax.value_and_grad(
            lambda t, x: self._ref(t, x, L), argnums=(0, 1)))(tree, x)
        np.testing.assert_allclose(float(l), float(lr), rtol=1e-6)
        assert _max_abs_diff(g, gr) < 1e-5
        assert _max_abs_diff(gx, gxr) < 1e-5

    def test_fsdp_gather_with_tp_specs_matches_reference(self, devices):
        """fsdp×tp at the op level: stacked weights split over ``data``
        on the layer dim AND ``model`` on their Megatron dims; the gather
        pipeline (overlap_scan with tp_specs) leaves the model sharding
        intact while the block's ring matmuls rotate over ``model``."""
        from pytorch_ddp_template_tpu.parallel.collective_matmul import (
            tp_column_dense, tp_row_dense,
        )

        mesh = _mesh42()
        L, B, T, E, F = 4, 8, 16, 8, 16
        host = self._host_tree(L, E, F)
        tp_specs = {"w1": P(None, None, "model"), "b1": P(None, "model"),
                    "w2": P(None, "model", None), "b2": P(None, None)}
        placed = {
            "w1": P("data", None, "model"), "b1": P("data", "model"),
            "w2": P("data", "model", None), "b2": P("data", None),
        }
        stacked = {k: jax.device_put(jnp.asarray(v),
                                     NamedSharding(mesh, placed[k]))
                   for k, v in host.items()}
        x = jnp.asarray(np.random.default_rng(2).standard_normal((B, T, E)),
                        jnp.float32)

        def apply_one(w, y, k, extras):
            (h,) = tp_column_dense(y, [w["w1"]], [w["b1"]], mesh)
            return y + tp_row_dense(jnp.tanh(h), w["w2"], w["b2"], mesh)

        def loss(stacked, x):
            return (overlap_scan(apply_one, stacked, x, (), mesh,
                                 tp_specs=tp_specs) ** 2).sum()

        l, (g, gx) = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1)))(stacked, x)

        def ref(tree, x):
            y = x
            for k in range(L):
                h = jnp.tanh(y @ tree["w1"][k] + tree["b1"][k])
                y = y + h @ tree["w2"][k] + tree["b2"][k]
            return (y ** 2).sum()

        lr, (gr, gxr) = jax.jit(jax.value_and_grad(
            ref, argnums=(0, 1)))(jax.tree.map(jnp.asarray, host), x)
        np.testing.assert_allclose(float(l), float(lr), rtol=1e-5)
        for k in host:
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(gr[k]),
                                       rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gxr),
                                   rtol=1e-4, atol=1e-4)
        # the gather left the model placement intact: grads land in the
        # stacked layout with BOTH axes still on their dims
        assert "data" in str(g["w1"].sharding.spec)
        assert "model" in str(g["w1"].sharding.spec)


# -- the static spec table vs init-time flax metadata ----------------------

def test_stacked_tp_specs_match_init_metadata(devices):
    """The apply-time spec table (_BLOCK_LOGICAL_AXES) must agree
    leaf-for-leaf with what flax's logical annotations resolve to at init
    — the two sources cannot be allowed to drift."""
    mesh = _mesh42()
    cfg = TrainingConfig(model="gpt-tiny", dataset_size=32,
                         scan_layers=True, tp_overlap=True)
    task, ds = build("gpt-tiny", cfg, mesh=mesh)
    batch = {k: jnp.asarray(np.asarray(v))
             for k, v in ds.batch(np.arange(8)).items()}
    boxed, _ = task.init(jax.random.PRNGKey(0), batch)

    def find_layers(tree):
        if isinstance(tree, dict):
            for key, sub in tree.items():
                if key == "layers":
                    return sub
                found = find_layers(sub)
                if found is not None:
                    return found
        return None

    layers_boxed = find_layers(boxed)
    assert layers_boxed is not None
    meta_shardings = nn.logical_to_mesh_sharding(
        nn.get_partition_spec(layers_boxed), mesh, active_rules(mesh))
    derived = stacked_tp_specs(nn.meta.unbox(layers_boxed), mesh)

    flat_meta = jax.tree_util.tree_flatten_with_path(meta_shardings)[0]
    flat_derived = jax.tree_util.tree_flatten_with_path(
        derived, is_leaf=lambda v: isinstance(v, P))[0]
    assert len(flat_meta) == len(flat_derived) > 0
    for (path_m, sharding), (path_d, spec) in zip(flat_meta, flat_derived):
        assert path_m == path_d
        meta_spec = tuple(getattr(sharding, "spec", sharding))
        pad = max(len(meta_spec), len(tuple(spec)))
        norm = lambda s: tuple(s) + (None,) * (pad - len(tuple(s)))
        assert norm(meta_spec) == norm(spec), (path_m, meta_spec, spec)


# -- model-level composed parity (the tier-1 tripwire) ---------------------

def test_composed_loss_and_grad_parity(devices):
    """fsdp×tp AND ddp×tp vs the FLOPs-matched GSPMD default on a
    data:4,model:2 mesh: loss and every grad leaf within the r10 rtol
    convention. One default task serves both comparisons (eval-mode loss
    is placement-independent; the composed paths get the params in their
    own layouts)."""
    mesh = _mesh42()

    def mk(**kw):
        cfg = TrainingConfig(model="gpt-tiny", dataset_size=32,
                             scan_layers=True, **kw)
        return build("gpt-tiny", cfg, mesh=mesh)

    task_default, ds = mk(fused_head=True)
    task_ft, _ = mk(fsdp_overlap=True, tp_overlap=True)
    task_dt, _ = mk(ddp_overlap=True, tp_overlap=True)
    assert task_ft.model.fsdp_overlap and task_ft.model.tp_overlap
    assert task_dt.model.ddp_overlap and task_dt.model.tp_overlap
    batch = {k: jax.device_put(np.asarray(v),
                               NamedSharding(mesh, P("data")))
             for k, v in ds.batch(np.arange(8)).items()}
    params, _ = task_default.init(jax.random.PRNGKey(0), batch)
    params = nn.meta.unbox(params)

    def loss_of(task):
        def f(p):
            loss, _, _ = task.loss(p, {}, batch, None, train=False)
            return loss
        return jax.jit(jax.value_and_grad(f))

    ld, gd = loss_of(task_default)(params)

    # ddp×tp: replicated (model-sharded) params, region over data×model
    ldt, gdt = loss_of(task_dt)(params)
    np.testing.assert_allclose(float(ld), float(ldt), atol=TOL)
    assert _max_abs_diff(gd, gdt) < TOL

    # fsdp×tp: the SAME params in the fsdp×tp layout (layer/within-layer
    # data split on top of the model split — gpt-tiny's 2 layers on
    # data:4 exercise the within-layer fallback with masked tp dims)
    pf = fsdp_reshard(params, mesh, prefer_dim=0)
    lft, gft = loss_of(task_ft)(pf)
    np.testing.assert_allclose(float(ld), float(lft), atol=TOL)
    assert _max_abs_diff(gd, gft) < TOL


# -- describe(): one coherent overlap block ---------------------------------

def test_describe_unified_overlap_block(devices):
    """A composed run must report ONE coherent schedule summary (axes,
    composed flag, combined wire total) instead of three disjoint
    fragments; the legacy per-axis keys stay as aliases for the
    bench-record contract tests."""
    from pytorch_ddp_template_tpu.parallel.sharding import describe

    mesh = _mesh42()
    cfg = TrainingConfig(model="gpt-tiny", scan_layers=True,
                         ddp_overlap=True, tp_overlap=True,
                         grad_comm="int8")
    task, _ = build("gpt-tiny", cfg, mesh=mesh)
    d = describe(mesh, cfg, model=task.model)
    block = d["overlap"]
    assert block["schedule"] == {"ddp": "per-layer-overlapped-reduce",
                                 "tp": "ring-decomposed"}
    assert sorted(block["decomposed_axes"]) == ["ddp", "tp"]
    assert block["composed"] is True
    # combined wire total covers every component present
    assert block["wire_mb_per_step"] == pytest.approx(
        block.get("tp_mb", 0) + block.get("grad_mb", 0))
    assert block["tp_mb"] == d["tp_wire_mb_per_step"]  # alias agreement
    # legacy keys still present (aliases)
    assert d["tp_mode"] == "ring-decomposed"
    assert d["ddp_mode"] == "per-layer-overlapped-reduce"
    assert d["grad_comm"] == "int8"

    # single-axis run: block present, composed False
    cfg1 = TrainingConfig(model="gpt-tiny", scan_layers=True,
                          fsdp_overlap=True)
    d1 = describe(make_mesh("data:-1"), cfg1)
    assert d1["overlap"]["schedule"] == {"fsdp": "decomposed-prefetch"}
    assert d1["overlap"]["composed"] is False

    # gspmd-default everywhere: no decomposed axes
    d2 = describe(mesh, TrainingConfig(model="gpt-tiny", fsdp=True))
    assert d2["overlap"]["decomposed_axes"] == []


# -- refusals with intent ---------------------------------------------------

class TestRefusals:
    def test_mesh_level_named_reasons(self, devices):
        # fsdp with a live model axis and no tp schedule
        with pytest.raises(ValueError, match="data-axis FSDP only"):
            validate_schedule_mesh(_mesh42(), fsdp=True)
        # ddp with a live model axis and no tp schedule
        with pytest.raises(ValueError, match="data-parallel meshes only"):
            validate_schedule_mesh(_mesh42(), ddp=True)
        # tp without a model axis
        with pytest.raises(ValueError, match="no TP matmul to overlap"):
            validate_schedule_mesh(make_mesh("data:-1"), ddp=True, tp=True)
        # axes outside data×model
        with pytest.raises(ValueError, match="seq"):
            validate_schedule_mesh(make_mesh("data:2,model:2,seq:2"),
                                   fsdp=True, tp=True)
        with pytest.raises(ValueError, match="mesh"):
            validate_schedule_mesh(None, fsdp=True)

    def test_parse_time_mesh_consistency(self):
        base = ["--model", "gpt-tiny", "--scan_layers"]
        # tp without a live model axis in --mesh: named at parse time,
        # not deep inside shard_map spec construction
        with pytest.raises(ValueError, match="no live model axis"):
            parse_args(base + ["--tp_overlap"])
        with pytest.raises(ValueError, match="no live model axis"):
            parse_args(base + ["--tp_overlap", "--mesh", "data:4,model:1"])
        # ddp/fsdp with a live model axis and no TP schedule
        with pytest.raises(ValueError, match="pass --tp_overlap too"):
            parse_args(base + ["--ddp_overlap", "--mesh", "data:4,model:2"])
        with pytest.raises(ValueError, match="pass --tp_overlap too"):
            parse_args(base + ["--fsdp_overlap", "--mesh",
                               "data:4,model:2"])
        # axes outside data×model
        with pytest.raises(ValueError, match="live axes"):
            parse_args(base + ["--tp_overlap", "--fsdp_overlap", "--mesh",
                               "data:2,model:2,seq:2"])
        # the consistent composed spellings parse
        cfg = parse_args(base + ["--tp_overlap", "--fsdp_overlap",
                                 "--mesh", "data:4,model:2"])
        assert cfg.fsdp and cfg.fsdp_overlap and cfg.tp_overlap
        cfg = parse_args(base + ["--tp_overlap", "--ddp_overlap",
                                 "--mesh", "data:4,model:2",
                                 "--grad_comm", "int8"])
        assert cfg.ddp_overlap and cfg.tp_overlap
        # wildcard model counts as live
        cfg = parse_args(base + ["--tp_overlap", "--mesh",
                                 "data:4,model:-1"])
        assert cfg.tp_overlap

    def test_registry_level(self, devices):
        mesh = _mesh42()
        # MoE: refused for every composed spelling
        with pytest.raises(ValueError, match="MoE"):
            build("gpt-moe-tiny",
                  TrainingConfig(model="gpt-moe-tiny", scan_layers=True,
                                 fsdp_overlap=True, tp_overlap=True),
                  mesh=mesh)
        with pytest.raises(ValueError, match="MoE"):
            build("gpt-moe-tiny",
                  TrainingConfig(model="gpt-moe-tiny", scan_layers=True,
                                 ddp_overlap=True, tp_overlap=True),
                  mesh=mesh)
        # pipe × the scan-family overlap flags: refused with the pipe
        # composition named (r16 — --scan_layers itself is now the
        # stage-local scan and accepted)
        with pytest.raises(ValueError, match="pipelined entries"):
            build("gpt-pipe-tiny",
                  TrainingConfig(model="gpt-pipe-tiny", scan_layers=True,
                                 fsdp_overlap=True, tp_overlap=True),
                  mesh=mesh)
        # fsdp×ddp stays impossible (params cannot be both sharded and
        # replicated) — named at config level
        with pytest.raises(ValueError, match="pick one execution mode"):
            TrainingConfig(model="gpt-tiny", scan_layers=True,
                           fsdp_overlap=True, ddp_overlap=True,
                           tp_overlap=True)


# -- engine-level composed steps (slow: train-step compiles) ----------------

@pytest.mark.slow
@pytest.mark.parametrize("compose", ["fsdp_tp", "ddp_tp"])
def test_engine_step_parity_composed(compose, devices):
    """One full jitted optimizer step per composed mode vs its
    FLOPs-matched GSPMD default: every weight within TOL. Dropout cloned
    OFF (the composed paths fold layer/shard indices where nn.scan
    splits — statistically equivalent, not the math this pins)."""
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    mesh = _mesh42()

    def mk(**kw):
        cfg = TrainingConfig(model="gpt-tiny", dataset_size=32,
                             scan_layers=True, **kw)
        task, ds = build("gpt-tiny", cfg, mesh=mesh)
        task.model = task.model.clone(dropout_rate=0.0)
        return task, ds

    if compose == "fsdp_tp":
        task_d, ds = mk(fused_head=True, fsdp=True)
        task_c, _ = mk(fsdp_overlap=True, tp_overlap=True)
        reshard = True
    else:
        task_d, ds = mk(fused_head=True)
        task_c, _ = mk(ddp_overlap=True, tp_overlap=True)
        reshard = False
    batch = {k: jax.device_put(np.asarray(v),
                               NamedSharding(mesh, P("data")))
             for k, v in ds.batch(np.arange(8)).items()}
    cfg = TrainingConfig(model="gpt-tiny", warmup_steps=0)
    key = jax.random.PRNGKey(0)
    states, metrics = {}, {}
    for tag, task in (("default", task_d), ("composed", task_c)):
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(cfg, total_steps=10)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars=extra, opt_state=tx.init(params),
                           rng=jax.random.clone(key))
        state = shard_tree(state, mesh)
        if reshard:
            state = state.replace(
                params=fsdp_reshard(state.params, mesh, prefer_dim=0),
                opt_state=fsdp_reshard(state.opt_state, mesh,
                                       prefer_dim=0))
        step = make_train_step(task, tx, schedule)
        states[tag], metrics[tag] = step(state, batch)
    np.testing.assert_allclose(np.asarray(metrics["default"]["loss"]),
                               np.asarray(metrics["composed"]["loss"]),
                               atol=TOL)
    assert _max_abs_diff(states["default"].params,
                         states["composed"].params) < TOL


@pytest.mark.slow
def test_hlo_composed_evidence(devices):
    """Depth-4 fsdp×tp compiled train step: ≥1 dot-carrying scanned body
    must show compute-independent gather-family collectives AND reach
    compute-independent ring ppermutes (directly or via its nested ring
    loops) — the composed-schedule witness."""
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    mesh = _mesh42()
    vocab, seq, depth = 128, 32, 4
    ids = np.random.default_rng(0).integers(0, vocab, (8, seq))
    batch = {"input_ids": jax.device_put(
        np.asarray(ids, np.int32), NamedSharding(mesh, P("data")))}
    model = GptDecoder(vocab_size=vocab, max_len=seq, num_layers=depth,
                       num_heads=2, head_dim=16, mlp_dim=64,
                       scan_layers=True, fsdp_overlap=True,
                       tp_overlap=True, fused_head=True, mesh=mesh)
    task = CausalLmTask(model)
    params, extra = task.init(jax.random.PRNGKey(0), batch)
    tx, schedule = make_optimizer(
        TrainingConfig(warmup_steps=0), total_steps=10)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       extra_vars=extra, opt_state=tx.init(params),
                       rng=jax.random.PRNGKey(0))
    state = shard_tree(state, mesh)
    state = state.replace(
        params=fsdp_reshard(state.params, mesh, prefer_dim=0),
        opt_state=fsdp_reshard(state.opt_state, mesh, prefer_dim=0))
    compiled = make_train_step(task, tx, schedule).lower(
        state, batch).compile()
    ev = hlo_composed_evidence(compiled.as_text())
    assert ev["independent_gather_bodies"] > 0, ev
    assert ev["independent_ring_bodies"] > 0, ev
    assert ev["composed_overlap_independent"], ev
