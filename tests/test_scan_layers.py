"""Scan-over-layers (``--scan_layers``, models/transformer.py): the scanned
single-block stack must be numerically interchangeable with the unrolled
loop — identical init (Task.init stacks the unrolled per-layer RNG
streams), identical forward/grads/eval metrics on a fixed batch, lossless
checkpoint layout conversion (tools/convert_checkpoint.py) — while trace
time stops growing with depth (the whole point: O(1) compile time)."""

import collections
import importlib.util
import time
from pathlib import Path

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.parallel.stacking import (
    detect_layer_layout,
    restack_layer_trees,
    unroll_layer_trees,
)

REPO = Path(__file__).resolve().parent.parent

TINY = ["gpt-tiny", "bert-tiny", "vit-tiny"]


def _convert_tool():
    spec = importlib.util.spec_from_file_location(
        "convert_checkpoint", REPO / "tools" / "convert_checkpoint.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _pair(name, batch_size=4, **over):
    """(unrolled task, scanned task, batch) for one registry entry."""
    cfg_u = TrainingConfig(model=name, dataset_size=32, **over)
    cfg_s = TrainingConfig(model=name, dataset_size=32, scan_layers=True,
                           **over)
    task_u, ds = build(name, cfg_u)
    task_s, _ = build(name, cfg_s)
    batch = {k: jnp.asarray(v)
             for k, v in ds.batch(np.arange(batch_size)).items()}
    return task_u, task_s, batch


def _count(params):
    return sum(int(np.prod(l.shape))
               for l in jax.tree.leaves(nn.meta.unbox(params)))


def _max_abs_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# -- init interchangeability ---------------------------------------------

def _assert_init_interchangeable(params_u, params_s):
    """--scan_layers at seed S starts from the SAME weights as the
    unrolled run at seed S: Task.init derives scanned init by stacking the
    unrolled per-layer RNG streams. Pins layout detection, param count
    (stacking must not invent or drop a single scalar), bit-equality, and
    per-layer-distinct streams (the classic scan pitfall would make every
    layer identical)."""
    assert detect_layer_layout(nn.meta.unbox(params_u)) == "unrolled"
    assert detect_layer_layout(nn.meta.unbox(params_s)) == "scanned"
    assert _count(params_u) == _count(params_s)
    restacked = restack_layer_trees(params_u)
    assert (jax.tree.structure(nn.meta.unbox(restacked))
            == jax.tree.structure(nn.meta.unbox(params_s)))
    assert _max_abs_diff(nn.meta.unbox(restacked),
                         nn.meta.unbox(params_s)) == 0.0
    unstacked = unroll_layer_trees(nn.meta.unbox(params_s))

    def layers_of(tree):
        found = []

        def walk(t):
            if isinstance(t, dict):
                if "layer_0" in t:
                    found.append(t)
                for v in t.values():
                    walk(v)

        walk(tree)
        return found

    (layer_dict,) = layers_of(unstacked)
    assert _max_abs_diff(layer_dict["layer_0"], layer_dict["layer_1"]) > 0.0


def _assert_native_init_structure_matches(task_s, batch, params_s):
    """The scanned module's own flax init (nn.scan split-rng streams — the
    path Task.init replaces) must still agree on structure/shapes, so any
    restacked tree is a drop-in for scan apply."""
    native = jax.eval_shape(
        lambda: task_s.model.init(jax.random.PRNGKey(0),
                                  *task_s.model_inputs(batch), train=False)
    )["params"]
    unboxed_native = nn.meta.unbox(native)
    unboxed = nn.meta.unbox(params_s)
    assert (jax.tree.structure(unboxed_native)
            == jax.tree.structure(unboxed))
    for a, b in zip(jax.tree.leaves(unboxed_native), jax.tree.leaves(unboxed)):
        assert a.shape == b.shape


# -- forward / grad / metrics parity -------------------------------------

# tier-1 runs the full no-remat sweep plus the gpt remat-scan pair; the
# bert/vit remat variants ride in the full (slow-inclusive) run — same
# code path, and the 870s tier-1 budget is the binding constraint
PARITY_CASES = [(name, False) for name in TINY] + [
    ("gpt-tiny", True),
    pytest.param("bert-tiny", True, marks=pytest.mark.slow),
    pytest.param("vit-tiny", True, marks=pytest.mark.slow),
]


@pytest.mark.parametrize("name,remat", PARITY_CASES)
def test_loss_grad_and_eval_metric_parity(name, remat):
    over = {"remat": True} if remat else {}
    task_u, task_s, batch = _pair(name, **over)
    key = jax.random.PRNGKey(0)
    params_u, extra_u = task_u.init(key, batch)
    params_s, extra_s = task_s.init(key, batch)
    if not remat:  # init interchangeability, pinned per family
        _assert_init_interchangeable(params_u, params_s)
        if name == "gpt-tiny":
            _assert_native_init_structure_matches(task_s, batch, params_s)
    pu, ps = nn.meta.unbox(params_u), nn.meta.unbox(params_s)

    # one traced computation per layout: eval-mode loss + metrics
    # (dropout off, masking deterministic) and grads together
    def val_and_grad(task, p, extra):
        def f(p):
            loss, _, metrics = task.loss(p, extra, batch, None, train=False)
            return loss, metrics

        (loss, metrics), grads = jax.value_and_grad(f, has_aux=True)(p)
        return loss, metrics, grads

    lu, mu, gu = val_and_grad(task_u, pu, extra_u)
    ls, ms, gs = val_and_grad(task_s, ps, extra_s)

    # the scanned stack must produce the identical eval curve
    np.testing.assert_allclose(np.asarray(lu), np.asarray(ls), atol=1e-5)
    assert set(mu) == set(ms)
    for k in mu:
        np.testing.assert_allclose(np.asarray(mu[k]), np.asarray(ms[k]),
                                   atol=1e-5, err_msg=k)
    # grads through the respective layouts agree layer-for-layer
    assert _max_abs_diff(restack_layer_trees(gu), gs) < 2e-4


def test_moe_train_loss_and_aux_parity():
    """moe_experts>0 inside the scan body: the sown load-balance terms
    stack per layer instead of arriving as separate scalars — total and
    aux must agree with the unrolled stack exactly (same init streams)."""
    task_u, task_s, batch = _pair("gpt-moe-tiny")
    key = jax.random.PRNGKey(0)
    params_u, extra_u = task_u.init(key, batch)
    params_s, extra_s = task_s.init(key, batch)
    lu, _, mu = task_u.loss(nn.meta.unbox(params_u), extra_u, batch,
                            jax.random.PRNGKey(1), train=True)
    ls, _, ms = task_s.loss(nn.meta.unbox(params_s), extra_s, batch,
                            jax.random.PRNGKey(1), train=True)
    np.testing.assert_allclose(np.asarray(lu), np.asarray(ls), atol=1e-5)
    np.testing.assert_allclose(np.asarray(mu["aux_loss"]),
                               np.asarray(ms["aux_loss"]), atol=1e-5)
    assert np.asarray(ms["aux_loss"]).shape == ()  # stacked sow reduced


@pytest.mark.slow
def test_train_step_parity_through_engine():
    """One jitted optimizer step (gpt-tiny, dropout-free): scanned and
    unrolled runs starting from the same seed produce the same loss and
    the same updated weights — the whole-engine interchangeability."""
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    cfg = TrainingConfig(model="gpt-tiny", dataset_size=32, warmup_steps=0)
    task_u, task_s, batch = _pair("gpt-tiny")
    key = jax.random.PRNGKey(0)
    tx, schedule = make_optimizer(cfg, total_steps=10)
    states, metrics = {}, {}
    for tag, task in (("unrolled", task_u), ("scanned", task_s)):
        params, extra = task.init(key, batch)
        params = nn.meta.unbox(params)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars=extra, opt_state=tx.init(params),
                           rng=jax.random.clone(key))
        step = make_train_step(task, tx, schedule)
        state, m = step(state, batch)
        states[tag], metrics[tag] = state, m
    np.testing.assert_allclose(np.asarray(metrics["unrolled"]["loss"]),
                               np.asarray(metrics["scanned"]["loss"]),
                               atol=1e-5)
    assert _max_abs_diff(restack_layer_trees(states["unrolled"].params),
                         states["scanned"].params) < 2e-4


# -- checkpoint layout conversion ----------------------------------------

def _tiny_trainer(tmp_path, subdir, scan_layers):
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        model="gpt-tiny", dataset_size=32, per_device_train_batch_size=1,
        max_steps=2, save_steps=2, logging_steps=0, warmup_steps=0,
        optimizer="momentum", scan_layers=scan_layers,
        output_dir=str(tmp_path / subdir),
    )
    mesh = make_mesh("data:-1", jax.devices())
    key = jax.random.PRNGKey(0)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=cfg)
    task, ds = build(cfg.model, cfg)
    return Trainer(cfg, ctx, task, ds), cfg


def test_convert_state_tree_roundtrip():
    """Fast tier-1 twin of the orbax integration test below: the whole
    TrainState-shaped tree (params + optimizer mirrors + scalars) converts
    unrolled→scanned→unrolled bit-exact, and the layout walk catches the
    refusal cases — no model build, no filesystem."""
    from pytorch_ddp_template_tpu.parallel.stacking import stack_layer_tree

    tool = _convert_tool()
    rng = np.random.default_rng(0)
    normal = lambda *s: rng.standard_normal(s).astype(np.float32)
    layer = lambda: {"attention": {"kernel": normal(4, 4)},
                     "mlp": {"bias": normal(3)}}
    layers = {f"layer_{i}": layer() for i in range(3)}
    # optimizer mirror carries the same per-layer subtrees params do; a
    # NamedTuple node models a LIVE optax state (ScaleByAdamState et al.),
    # which needs splat reconstruction, not an iterable
    TraceState = collections.namedtuple("TraceState", ["trace"])
    state = {
        "step": np.asarray(7),
        "params": {"decoder": dict(layers), "wte": normal(8, 4)},
        "opt_state": [TraceState(trace={"decoder": {
            f"layer_{i}": layer() for i in range(3)}})],
    }
    scanned = tool.convert_state(state, "scanned")
    assert detect_layer_layout(scanned) == "scanned"
    assert isinstance(scanned["opt_state"][0], TraceState)
    stacked = scanned["params"]["decoder"]["layers"]
    assert stacked["attention"]["kernel"].shape == (3, 4, 4)
    back = tool.convert_state(scanned, "unrolled")
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(back)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="already in the scanned layout"):
        tool.convert_state(scanned, "scanned")
    with pytest.raises(ValueError, match="inconsistent leading dims"):
        tool.convert_state(
            {"layers": {"a": np.zeros((2, 3)), "b": np.zeros((4, 3))}},
            "unrolled")
    # stack_layer_tree and nn.scan agree on the boxed-axis bookkeeping
    boxed = [{"w": nn.Partitioned(jnp.ones((2, 2)), names=("mlp", None))}
             for _ in range(2)]
    out = stack_layer_tree(boxed)
    assert out["w"].names == ("layers", "mlp", None)


def test_lossy_mismatch_restore_still_fails_with_intent(tmp_path):
    """r18 transition pin, refusal half: reshard-on-restore lifted the
    layout-mismatch refusal (the success half rides
    test_checkpoint_conversion_roundtrip_and_mismatch and
    tests/test_elastic.py), but a GENUINELY lossy mismatch — here a
    checkpoint missing the whole param/optimizer state, standing in for
    a changed model geometry — must still refuse with intent, naming
    the offline converter and --no_resume."""
    from pytorch_ddp_template_tpu.checkpoint.manager import CheckpointManager

    cfg = TrainingConfig(model="gpt-tiny", dataset_size=32,
                         per_device_train_batch_size=1, scan_layers=False,
                         optimizer="momentum",  # match _tiny_trainer: the
                         #                        optimizer check fires first
                         output_dir=str(tmp_path / "unrolled"))
    mngr = CheckpointManager(cfg.output_dir)
    mngr.save(3, {"step": np.zeros((), np.int32)}, cfg, force=True)
    mngr.wait()
    mngr.close()
    trainer, _ = _tiny_trainer(tmp_path, "unrolled", scan_layers=True)
    with pytest.raises(ValueError, match="convert_checkpoint"):
        trainer.restore_or_init()
    trainer.ckpt.close()


@pytest.mark.slow
def test_checkpoint_conversion_roundtrip_and_mismatch(tmp_path):
    """save unrolled → convert → restore under --scan_layers (and the
    reverse), plus the fail-with-intent mismatched-layout restore. The
    checkpoint is written through the production CheckpointManager;
    ``optimizer=momentum`` gives the opt_state param-shaped mirrors, so
    the converter's walk over non-param subtrees is exercised too.
    (slow: orbax manager + Trainer template setup; the fast tree-level
    twin above plus the engine's config check stay tier-1.)"""
    from pytorch_ddp_template_tpu.checkpoint.manager import CheckpointManager
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer,
    )

    tool = _convert_tool()
    cfg = TrainingConfig(
        model="gpt-tiny", dataset_size=32, per_device_train_batch_size=1,
        optimizer="momentum", warmup_steps=0,
        output_dir=str(tmp_path / "unrolled"),
    )
    task_u, _, batch = _pair("gpt-tiny", optimizer="momentum")
    params, extra = task_u.init(jax.random.PRNGKey(0), batch)
    params = nn.meta.unbox(params)
    tx, _ = make_optimizer(cfg, total_steps=10)
    state = TrainState(step=jnp.asarray(2, jnp.int32), params=params,
                       extra_vars=extra, opt_state=tx.init(params),
                       rng=jax.random.PRNGKey(1))
    mngr = CheckpointManager(str(tmp_path / "unrolled"))
    mngr.save(2, state, cfg, force=True)
    mngr.wait()
    mngr.close()
    saved_params = jax.device_get(params)

    # r18 transition pin, success half: restoring the unrolled
    # checkpoint under --scan_layers — the exact config the pre-r18
    # engine refused with "convert it with tools/convert_checkpoint.py"
    # — now reshards in-restore, bit-exact with the offline converter
    # run below (same restacking core, run in-process)
    mis_trainer, _ = _tiny_trainer(tmp_path, "unrolled", scan_layers=True)
    mis_state, mis_start = mis_trainer.restore_or_init()
    mis_trainer.ckpt.close()
    assert mis_start == 2
    assert _max_abs_diff(restack_layer_trees(saved_params),
                         jax.device_get(mis_state.params)) == 0.0

    # convert -> a --scan_layers run restores the restacked weights (and
    # momentum mirrors) through the full Trainer template path
    step = tool.convert_checkpoint(str(tmp_path / "unrolled"),
                                   str(tmp_path / "scanned"), "scanned")
    assert step == 2
    scan_trainer, _ = _tiny_trainer(tmp_path, "scanned", scan_layers=True)
    scan_state, start = scan_trainer.restore_or_init()
    scan_trainer.ckpt.close()
    assert start == 2
    assert _max_abs_diff(restack_layer_trees(saved_params),
                         jax.device_get(scan_state.params)) == 0.0

    # reverse conversion round-trips the whole state bit-exact
    tool.convert_checkpoint(str(tmp_path / "scanned"),
                            str(tmp_path / "back"), "unrolled")
    back = CheckpointManager(str(tmp_path / "back"))
    step_b, state_b, cfg_b = back.restore_raw()
    back.close()
    assert step_b == 2 and cfg_b["scan_layers"] is False
    assert _max_abs_diff(saved_params, state_b["params"]) == 0.0
    orig_opt = jax.device_get(jax.tree.leaves(state.opt_state))
    back_opt = jax.tree.leaves(state_b["opt_state"])
    assert len(orig_opt) == len(back_opt)
    for a, b in zip(orig_opt, back_opt):
        assert np.asarray(a).shape == np.asarray(b).shape
        assert np.array_equal(np.asarray(a), np.asarray(b))

    # converting a checkpoint already in the target layout is refused
    with pytest.raises(ValueError, match="already in the scanned layout"):
        tool.convert_checkpoint(str(tmp_path / "scanned"),
                                str(tmp_path / "noop"), "scanned")


def test_convert_state_refuses_layerless_tree():
    tool = _convert_tool()
    with pytest.raises(ValueError, match="no transformer layer stack"):
        tool.convert_state({"params": {"dense": {"kernel": np.zeros((2, 2))}}},
                           "scanned")


# -- config surface -------------------------------------------------------

def test_scan_layers_rejected_where_it_cannot_apply():
    with pytest.raises(ValueError, match="no transformer layer stack"):
        build("mlp", TrainingConfig(model="mlp", scan_layers=True))
    # gpt-pipe entries now ACCEPT the flag as a stage-local scan (r16)
    task, _ = build("gpt-pipe-tiny",
                    TrainingConfig(model="gpt-pipe-tiny",
                                   scan_layers=True))
    assert task.scan_layers is True


def test_fsdp_prefers_leading_layer_dim():
    """Under --scan_layers the FSDP split lands on the stacked layer dim
    (uniform, always-dividable) instead of each leaf's largest dim."""
    from pytorch_ddp_template_tpu.parallel.sharding import fsdp_reshard
    from pytorch_ddp_template_tpu.runtime import make_mesh

    mesh = make_mesh("data:-1", jax.devices())
    n = mesh.shape["data"]
    leaf = jnp.zeros((n, 4 * n))  # largest dim is 1, leading dim is 0
    def spec2(x):  # normalise trailing Nones: P("data") == P("data", None)
        s = tuple(x.sharding.spec)
        return s + (None,) * (2 - len(s))

    default = fsdp_reshard({"w": leaf}, mesh)
    preferred = fsdp_reshard({"w": leaf}, mesh, prefer_dim=0)
    assert spec2(default["w"]) == (None, "data")
    assert spec2(preferred["w"]) == ("data", None)
    # a leaf whose preferred dim does not divide falls back to largest
    odd = jnp.zeros((n + 1, 4 * n))
    fallback = fsdp_reshard({"w": odd}, mesh, prefer_dim=0)
    assert spec2(fallback["w"]) == (None, "data")


# -- compile-time regression guard ---------------------------------------

@pytest.mark.parametrize("depths", [(2, 8)])
def test_trace_time_stays_flat_in_depth(depths):
    """Tracing the scanned train step at depth 8 must cost about what
    depth 2 costs — a re-unrolling regression (scan silently falling back
    to a Python loop) would show ~4x. Wall-time-loose (3x bound, floored
    denominator) so the noisy 2-core host cannot flake it."""
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    cfg = TrainingConfig(warmup_steps=0)
    batch = {"input_ids": jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32)}
    tx, schedule = make_optimizer(cfg, total_steps=10)

    def trace_seconds(depth):
        model = GptDecoder(vocab_size=128, max_len=16, num_layers=depth,
                           num_heads=2, head_dim=8, mlp_dim=32,
                           scan_layers=True)
        task = CausalLmTask(model)
        # shape-only init (eval_shape) + zeros: the guard times TRACING,
        # so real weights would only add eager init cost to the test
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), batch["input_ids"],
                               train=False))["params"]
        params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                              nn.meta.unbox(shapes))
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars={}, opt_state=tx.init(params),
                           rng=jax.random.PRNGKey(1))
        step = make_train_step(task, tx, schedule)
        t0 = time.perf_counter()
        step.lower(state, batch)
        return time.perf_counter() - t0

    shallow, deep = depths
    t_shallow = min(trace_seconds(shallow) for _ in range(2))
    t_deep = min(trace_seconds(deep) for _ in range(2))
    assert t_deep <= 3.0 * max(t_shallow, 0.05), (
        f"trace time grew {t_deep / max(t_shallow, 1e-9):.1f}x from depth "
        f"{shallow} to {deep} — did the scan re-unroll?"
    )
