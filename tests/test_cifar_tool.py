"""CIFAR-10 binary converter (tools/cifar10_to_store.py): the real-data
ingestion rung. The parser owns the record format (1 label byte + 3072
channel-planar pixels); these tests pin the byte layout, the NHWC
transpose, store round-trip, and the malformed-input failure modes."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import cifar10_to_store as c2s  # noqa: E402

from pytorch_ddp_template_tpu.data.filestore import MemmapDataset  # noqa: E402


class TestParser:
    def test_byte_layout_and_transpose(self, tmp_path):
        # one hand-built record: label 7, R-plane all 10, G all 20, B all 30
        rec = np.empty(c2s.RECORD_BYTES, np.uint8)
        rec[0] = 7
        rec[1:1025] = 10
        rec[1025:2049] = 20
        rec[2049:] = 30
        f = tmp_path / "one.bin"
        f.write_bytes(rec.tobytes())
        images, labels = c2s.parse_batch_file(f)
        assert labels.tolist() == [7]
        assert images.shape == (1, 32, 32, 3) and images.dtype == np.uint8
        assert (images[0, :, :, 0] == 10).all()  # R plane → channel 0
        assert (images[0, :, :, 1] == 20).all()
        assert (images[0, :, :, 2] == 30).all()

    def test_truncated_file_raises(self, tmp_path):
        f = tmp_path / "bad.bin"
        f.write_bytes(b"\x00" * (c2s.RECORD_BYTES - 1))
        with pytest.raises(ValueError, match="record"):
            c2s.parse_batch_file(f)

    def test_cifar100_style_labels_raise(self, tmp_path):
        rec = np.zeros(c2s.RECORD_BYTES, np.uint8)
        rec[0] = 42  # CIFAR-100 fine label — not valid CIFAR-10
        f = tmp_path / "c100.bin"
        f.write_bytes(rec.tobytes())
        with pytest.raises(ValueError, match="CIFAR-100"):
            c2s.parse_batch_file(f)


class TestConvertRoundTrip:
    def test_fabricate_convert_load(self, tmp_path):
        src, train, test = tmp_path / "src", tmp_path / "tr", tmp_path / "te"
        c2s.fabricate(src, samples=50, seed=3)
        assert sorted(p.name for p in src.glob("*.bin")) == sorted(
            c2s.TRAIN_FILES + c2s.TEST_FILES
        )
        n_train = c2s.convert(src, train, c2s.TRAIN_FILES)
        n_test = c2s.convert(src, test, c2s.TEST_FILES)
        ds = MemmapDataset(train)
        assert len(ds) == n_train
        assert ds.arrays["image"].shape == (n_train, 32, 32, 3)
        assert ds.arrays["image"].dtype == np.uint8
        assert ds.arrays["label"].dtype == np.int32
        assert 0 <= ds.arrays["label"].min() <= ds.arrays["label"].max() <= 9
        assert len(MemmapDataset(test)) == n_test
        # fabricated classes are separable: same-class images correlate
        # more with their class prototype than cross-class (sanity that the
        # stand-in corpus is learnable, not noise)
        lab = np.asarray(ds.arrays["label"])
        img = np.asarray(ds.arrays["image"], np.float32)
        if (lab == lab[0]).sum() >= 2:
            same = img[lab == lab[0]]
            other = img[lab != lab[0]]
            d_same = np.abs(same[0] - same[1]).mean()
            d_cross = np.abs(same[0] - other[0]).mean()
            assert d_same < d_cross

    def test_missing_files_raise(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="data_batch"):
            c2s.convert(tmp_path, tmp_path / "out", c2s.TRAIN_FILES)

    def test_registry_accepts_converted_store(self, tmp_path):
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models import build

        src, out = tmp_path / "src", tmp_path / "store"
        c2s.fabricate(src, samples=50, seed=0)
        c2s.convert(src, out, c2s.TEST_FILES)
        cfg = TrainingConfig(model="resnet18", data_dir=str(out))
        task, ds = build("resnet18", cfg)
        batch = ds.batch(np.arange(4))
        assert batch["image"].shape == (4, 32, 32, 3)
