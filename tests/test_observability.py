"""Observability subsystems (SURVEY.md §5.1/§5.2 — absent in the
reference, first-class here): profiler + divergence (r6) and the round-12
flight recorder — in-step health pack, anomaly sentry, flight-record
bundles, NaN-safe telemetry serialisation, and the HLO schedule report."""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.obs.health import HEALTH_KEYS, health_metrics
from pytorch_ddp_template_tpu.obs.hlo_report import (
    check_overlap_expectations,
    collective_evidence,
    op_census,
    ring_evidence,
    schedule_report,
)
from pytorch_ddp_template_tpu.obs.sentry import (
    BUNDLE_FILES,
    AnomalySentry,
    FlightRecorder,
)
from pytorch_ddp_template_tpu.utils.divergence import check, fingerprint
from pytorch_ddp_template_tpu.utils.profiler import StepTimer, TraceWindow
from pytorch_ddp_template_tpu.utils.serialization import json_sanitize


def make_trainer(tmp_path, **overrides):
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    defaults = dict(
        model="mlp", dataset_size=256, per_device_train_batch_size=2,
        logging_steps=0, save_steps=0, max_steps=10,
        output_dir=str(tmp_path), resume=False,
    )
    defaults.update(overrides)
    cfg = TrainingConfig(**defaults)
    mesh = make_mesh("data:-1", jax.devices())
    key = jax.random.PRNGKey(0)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=cfg)
    task, ds = build(cfg.model, cfg)
    return Trainer(cfg, ctx, task, ds)


# -- r6 subsystems ---------------------------------------------------------

def test_fingerprint_detects_any_leaf_change():
    tree = {"a": jnp.arange(8.0), "b": {"w": jnp.ones((3, 3))}}
    fp = np.asarray(fingerprint(tree))
    tree2 = {"a": jnp.arange(8.0).at[3].add(1e-3), "b": {"w": jnp.ones((3, 3))}}
    fp2 = np.asarray(fingerprint(tree2))
    assert not np.array_equal(fp, fp2)
    np.testing.assert_array_equal(fp, np.asarray(fingerprint(tree)))


def test_check_single_process_true():
    assert check({"w": jnp.ones(4)}) is True


def test_step_timer_summary():
    t = StepTimer()
    assert t.summary() == {}
    for _ in range(5):
        t.tick()
    s = t.summary()
    assert set(s) == {"step_time_p50_ms", "step_time_p90_ms",
                      "step_time_p99_ms", "step_time_mean_ms"}
    assert all(v >= 0 for v in s.values())


def test_step_timer_wraparound(monkeypatch):
    """Capacity boundary: after more ticks than capacity, the oldest
    samples are evicted and the summaries describe exactly the newest
    ``capacity`` intervals (a long run's percentiles must track the
    recent regime, not the whole history)."""
    from pytorch_ddp_template_tpu.utils import profiler

    # deterministic clock: tick i closes an interval of exactly i seconds
    # (1, 2, ..., 9); capacity 4 must keep {6, 7, 8, 9}
    times = iter([float(x) for x in np.cumsum([0] + list(range(1, 10)))])
    monkeypatch.setattr(profiler.time, "perf_counter", lambda: next(times))
    t = StepTimer(capacity=4)
    for _ in range(10):
        t.tick()
    assert list(t._times) == [6.0, 7.0, 8.0, 9.0]
    s = t.summary()
    assert s["step_time_p50_ms"] == pytest.approx(7.5e3)
    assert s["step_time_mean_ms"] == pytest.approx(7.5e3)
    # the discard path still advances the boundary without recording
    t2 = StepTimer(capacity=4)
    times2 = iter([0.0, 1.0, 3.0])
    monkeypatch.setattr(profiler.time, "perf_counter", lambda: next(times2))
    t2.tick()
    t2.tick(discard=True)
    assert t2.tick() == pytest.approx(2.0)


def test_trace_window_writes_profile(tmp_path):
    tw = TraceWindow(tmp_path, start_step=1, num_steps=2)
    assert tw.active is False
    for step in range(5):
        tw.step(step)
        jnp.sum(jnp.arange(16.0)).block_until_ready()
    tw.close()
    profile_dir = tmp_path / "profile"
    assert profile_dir.exists()
    assert any(profile_dir.rglob("*.xplane.pb")), list(profile_dir.rglob("*"))


def test_trainer_with_profiling_and_divergence(tmp_path):
    t = make_trainer(tmp_path, max_steps=14, logging_steps=5,
                     profile_steps=2, divergence_check_steps=5)
    state = t.train()
    assert int(state.step) == 14
    assert (tmp_path / "profile").exists()


# -- NaN-safe serialisation (satellite: the sink must survive what the
# sentry surfaces) ---------------------------------------------------------

def test_json_sanitize_scalars_lists_nested():
    rec = json_sanitize({
        "ok": 1.5, "n": 3, "s": "x", "b": True, "none": None,
        "bad": float("nan"), "inf": float("-inf"),
        "vec": [1.0, float("inf"), 2.0],
        "good_vec": [1.0, 2.0],
        "nested": {"deep": float("nan")},
    })
    assert rec["ok"] == 1.5 and rec["n"] == 3 and rec["b"] is True
    assert rec["bad"] is None and rec["bad_repr"] == "nan"
    assert rec["inf"] is None and rec["inf_repr"] == "-inf"
    assert rec["vec"] == [1.0, None, 2.0] and "inf" in rec["vec_repr"]
    assert rec["good_vec"] == [1.0, 2.0] and "good_vec_repr" not in rec
    assert rec["nested"]["deep"] is None
    json.dumps(rec, allow_nan=False)  # must not raise


def test_metrics_writer_nan_roundtrips_as_null(tmp_path):
    """A NaN scalar must land as standard JSON (null + ``<key>_repr``),
    not the bare ``NaN`` token that breaks every compliant parser —
    round-tripped through json.loads to prove it."""
    from pytorch_ddp_template_tpu.train.metrics import MetricsWriter

    w = MetricsWriter(tmp_path)
    w.write(7, {"loss": float("nan"), "grad_norm": 1.25})
    w.close()
    raw = (tmp_path / "metrics.jsonl").read_text()
    assert "NaN" not in raw  # the non-standard token never appears
    row = json.loads(raw.splitlines()[0])
    assert row["step"] == 7
    assert row["loss"] is None and row["loss_repr"] == "nan"
    assert row["grad_norm"] == 1.25


def test_metrics_writer_vector_channel(tmp_path):
    """Flat lists (the per-layer health vector) are a JSONL-only channel;
    non-finite elements sanitise element-wise."""
    from pytorch_ddp_template_tpu.train.metrics import MetricsWriter

    w = MetricsWriter(tmp_path)
    w.write(3, {"per_layer_grad_norm": [0.5, float("inf"), 2.0]})
    w.close()
    row = json.loads((tmp_path / "metrics.jsonl").read_text().splitlines()[0])
    assert row["per_layer_grad_norm"] == [0.5, None, 2.0]
    assert "inf" in row["per_layer_grad_norm_repr"]


def test_telemetry_fetch_handles_vectors(tmp_path):
    """The drain-side host conversion must pass device VECTORS through as
    lists (scalars stay floats; windows still mean)."""
    from pytorch_ddp_template_tpu.train.metrics import _to_host

    host = _to_host({
        "vec": jnp.asarray([1.0, 2.0, 3.0]),
        "scalar": jnp.float32(4.0),
        "window": [jnp.float32(1.0), jnp.float32(3.0)],
    })
    assert host["vec"] == [1.0, 2.0, 3.0]
    assert host["scalar"] == 4.0
    assert host["window"] == 2.0


# -- in-step health pack ---------------------------------------------------

def test_health_metrics_norms_and_counts():
    params = {"w": jnp.full((4, 4), 2.0), "b": jnp.zeros(4)}
    updates = {"w": jnp.full((4, 4), 0.02), "b": jnp.zeros(4)}
    grads = {"w": jnp.ones((4, 4)).at[0, 0].set(jnp.nan),
             "b": jnp.array([1.0, jnp.inf, 0.0, 0.0])}
    h = health_metrics(loss=jnp.float32(jnp.nan), grads=grads,
                       params=params, updates=updates)
    assert float(h["param_norm"]) == pytest.approx(8.0)
    assert float(h["update_ratio"]) == pytest.approx(0.01)
    assert int(h["nonfinite_loss"]) == 1
    assert int(h["nonfinite_grads"]) == 2
    assert "per_layer_grad_norm" not in h  # no scanned stack in the tree
    assert "ef_residual_norm" not in h


def test_health_metrics_per_layer_vector_from_stacked_tree():
    """Under --scan_layers the stacked (L, ...) grads reduce to ONE (L,)
    vector — per-layer norms at the cost of a fused reduction."""
    L = 3
    grads = {"encoder": {"layers": {
        "fc": {"kernel": jnp.stack([jnp.full((2, 2), float(i + 1))
                                    for i in range(L)])},
        "ln": {"scale": jnp.stack([jnp.full((2,), float(i + 1))
                                   for i in range(L)])},
    }}, "head": {"kernel": jnp.ones((2, 2))}}
    params = jax.tree.map(jnp.ones_like, grads)
    h = health_metrics(loss=jnp.float32(1.0), grads=grads, params=params,
                       updates=jax.tree.map(jnp.zeros_like, params))
    per = np.asarray(h["per_layer_grad_norm"])
    assert per.shape == (L,)
    # layer i: kernel 4 elements of (i+1)^2 + scale 2 elements of (i+1)^2
    expect = [math.sqrt(6 * (i + 1) ** 2) for i in range(L)]
    np.testing.assert_allclose(per, expect, rtol=1e-6)
    assert int(h["nonfinite_grads"]) == 0


def test_health_metrics_ef_residual_norm():
    res = {"stack": jnp.full((2, 4), 3.0)}
    h = health_metrics(loss=jnp.float32(1.0), grads={"w": jnp.ones(2)},
                       params={"w": jnp.ones(2)},
                       updates={"w": jnp.zeros(2)}, residual=res)
    assert float(h["ef_residual_norm"]) == pytest.approx(
        math.sqrt(8 * 9.0))


def test_train_step_emits_health_pack(tmp_path):
    """The production step metrics carry the health keys when
    --health_pack is on (the default) and stay bit-stable without."""
    t = make_trainer(tmp_path / "on")
    state, _ = t.restore_or_init()
    batch = next(iter(t.loader.epoch(0)))
    _, metrics = t.train_step(state, batch)
    for k in ("param_norm", "update_ratio", "nonfinite_loss",
              "nonfinite_grads"):
        assert k in metrics, k
    assert int(metrics["nonfinite_loss"]) == 0
    t_off = make_trainer(tmp_path / "off", health_pack=False)
    state_off, _ = t_off.restore_or_init()
    batch_off = next(iter(t_off.loader.epoch(0)))
    _, metrics_off = t_off.train_step(state_off, batch_off)
    assert not any(k in metrics_off for k in HEALTH_KEYS)


# -- anomaly sentry --------------------------------------------------------

def steady(sentry, n, *, loss=1.0, start=0):
    for i in range(n):
        sentry.observe(start + i, {"loss": loss, "grad_norm": 0.5,
                                   "nonfinite_loss": 0.0,
                                   "nonfinite_grads": 0.0})


def test_sentry_rejects_unknown_mode():
    with pytest.raises(ValueError, match="anomaly"):
        AnomalySentry("typo")


def test_sentry_nonfinite_triggers_immediately():
    s = AnomalySentry("warn")
    s.observe(0, {"loss": float("nan"), "grad_norm": 1.0})
    trig = s.poll_trigger()
    assert trig is not None and trig["step"] == 0
    assert any("non-finite" in r for r in trig["reasons"])
    assert s.poll_trigger() is None  # delivered exactly once


def test_sentry_nonfinite_counter_triggers():
    s = AnomalySentry("halt")
    s.observe(4, {"loss": 1.0, "grad_norm": 1.0, "nonfinite_grads": 3.0})
    trig = s.poll_trigger()
    assert trig is not None and "nonfinite_grads=3" in trig["reasons"][0]


def test_sentry_spike_needs_history_then_fires():
    s = AnomalySentry("warn", threshold=10.0, min_history=16)
    # a spike BEFORE min_history finite samples: no trigger (cold start)
    s.observe(0, {"loss": 100.0, "grad_norm": 0.5})
    assert not s.triggered
    steady(s, 32, start=1)
    assert not s.triggered  # the early outlier aged out of the window
    s.observe(50, {"loss": 50.0, "grad_norm": 0.5})
    trig = s.poll_trigger()
    assert trig is not None
    assert any("loss spike" in r for r in trig["reasons"])


def test_sentry_steady_and_drifting_stream_no_trigger():
    s = AnomalySentry("warn", threshold=10.0, min_history=16)
    # smooth exponential-ish decay — the normal shape of a healthy loss
    for i in range(200):
        s.observe(i, {"loss": 2.0 * (0.99 ** i) + 0.5,
                      "grad_norm": 1.0 - i * 1e-3})
    assert not s.triggered


def test_sentry_ring_eviction_and_snapshot():
    s = AnomalySentry("warn", window=8)
    steady(s, 20)
    recs = s.records()
    assert len(recs) == 8
    assert [r["step"] for r in recs] == list(range(12, 20))
    assert recs[0]["loss"] == 1.0


# -- flight recorder -------------------------------------------------------

def test_flight_recorder_bundle_complete_and_parseable(tmp_path):
    from pytorch_ddp_template_tpu.config import TrainingConfig

    rec = FlightRecorder(tmp_path)
    ring = [{"step": i, "loss": 1.0} for i in range(4)]
    ring.append({"step": 4, "loss": float("nan")})
    d = rec.dump(step=4, trigger={"step": 4, "reasons": ["loss non-finite"],
                                  "scalars": {"loss": float("nan")}},
                 ring=ring, config=TrainingConfig(),
                 describe_snapshot={"mesh": {"data": 8}},
                 fingerprint=[1.0, float("nan")])
    assert d.parent == tmp_path / "flight_records"
    names = {p.name for p in d.iterdir()}
    assert set(BUNDLE_FILES) <= names
    # every artifact is STANDARD json (the bundle's raison d'être is
    # non-finite values — they must not poison it)
    trig = json.loads((d / "trigger.json").read_text())
    assert trig["scalars"]["loss"] is None
    assert trig["scalars"]["loss_repr"] == "nan"
    rows = [json.loads(l) for l in (d / "ring.jsonl").read_text().splitlines()]
    assert rows[-1]["loss"] is None and rows[-1]["loss_repr"] == "nan"
    fp = json.loads((d / "fingerprint.json").read_text())
    assert fp["fingerprint"] == [1.0, None]
    assert json.loads((d / "config.json").read_text())["seed"] == 42
    # a re-trigger at the same step gets its own directory
    d2 = rec.dump(step=4, trigger={"step": 4, "reasons": ["again"]}, ring=[])
    assert d2 != d and d2.name.startswith("step_00000004.")


# -- engine integration ----------------------------------------------------

def test_engine_crash_closes_trace_and_dumps(tmp_path):
    """Satellite 3: an exception mid-loop must still stop the live
    profiler capture (the crashed run's partial profile is the one you
    want most) and give the flight recorder its chance to dump."""
    t = make_trainer(tmp_path, max_steps=30, profile_steps=10,
                     anomaly="warn")
    calls = {"n": 0}
    orig = t.train_step

    def exploding(state, batch, *rest):
        calls["n"] += 1
        if calls["n"] == 13:  # inside the profile window [10, 20)
            raise RuntimeError("injected step failure")
        return orig(state, batch, *rest)

    t.train_step = exploding
    with pytest.raises(RuntimeError, match="injected step failure"):
        t.train()
    # the partially-captured trace was flushed, not lost
    profile_dir = tmp_path / "profile"
    assert profile_dir.exists()
    assert any(profile_dir.rglob("*.xplane.pb")), list(profile_dir.rglob("*"))
    # and the crash bundle exists with the exception named
    bundles = sorted((tmp_path / "flight_records").glob("step_*"))
    assert bundles, "crash must leave a flight record"
    trig = json.loads((bundles[0] / "trigger.json").read_text())
    assert trig["mode"] == "crash"
    assert any("injected step failure" in r for r in trig["reasons"])
    # telemetry sink was closed by train()'s finally despite the raise
    assert t.telemetry._closed


def test_anomaly_halt_end_to_end(tmp_path):
    """A NaN'd loss mid-run: the sentry triggers off the drained health
    feed, the flight recorder dumps a complete bundle (including the
    post-trigger trace), and halt stops the run cleanly with a
    checkpoint — the full production triage path."""
    t = make_trainer(tmp_path, max_steps=40, logging_steps=5,
                     save_steps=0, anomaly="halt")
    calls = {"n": 0}
    orig = t.train_step

    def poisoned(state, batch, *rest):
        state, m = orig(state, batch, *rest)
        calls["n"] += 1
        if calls["n"] == 8:
            m = dict(m)
            m["loss"] = m["loss"] * jnp.float32(float("nan"))
        return state, m

    t.train_step = poisoned
    state = t.train()
    assert int(state.step) < 40, "halt must stop the run early"
    assert t.ckpt.latest_step() == int(state.step)  # clean resume point
    bundles = sorted((tmp_path / "flight_records").glob("step_*"))
    assert len(bundles) == 1
    names = {p.name for p in bundles[0].iterdir()}
    assert set(BUNDLE_FILES) <= names
    assert "profile" in names  # the post-trigger TraceWindow capture
    trig = json.loads((bundles[0] / "trigger.json").read_text())
    # r14 satellite: the bundle records which host dumped and traced
    # (an anomaly trigger traces wherever it fired)
    assert trig["kind"] == "anomaly"
    assert trig["host"] == 0 and trig["trace_host"] == 0
    ring = [json.loads(l)
            for l in (bundles[0] / "ring.jsonl").read_text().splitlines()]
    assert ring, "ring buffer must hold the pre-trigger history"
    # the poisoned step is in the ring, sanitised (healthy steps drained
    # after the trigger may follow it — the dump happens on the loop
    # thread one poll later)
    assert any(r["loss"] is None and r.get("loss_repr") == "nan"
               for r in ring)
    # the NaN also flowed through the logging-boundary progress record
    # as standard JSON
    raw = (tmp_path / "metrics.jsonl").read_text()
    assert "NaN" not in raw


def test_warn_trigger_inside_profile_window_survives(tmp_path):
    """A trigger whose 4-step flight capture would collide with the
    --profile_steps window must SKIP the flight trace (one live profiler
    trace per process), not raise 'Profile has already been started' and
    kill a run that warn mode promises never to cost."""
    t = make_trainer(tmp_path, max_steps=24, profile_steps=10,
                     anomaly="warn")
    calls = {"n": 0}
    orig = t.train_step

    def poisoned(state, batch, *rest):
        state, m = orig(state, batch, *rest)
        calls["n"] += 1
        if calls["n"] == 7:  # flight window [~8, ~12) overlaps [10, 20)
            m = dict(m)
            m["loss"] = m["loss"] * jnp.float32(float("nan"))
        return state, m

    t.train_step = poisoned
    state = t.train()  # must complete, not crash at the window boundary
    assert int(state.step) == 24
    bundles = sorted((tmp_path / "flight_records").glob("step_*"))
    assert bundles, "the bundle still dumps; only the trace is skipped"
    assert not (bundles[0] / "profile").exists()
    # the user's requested profile window still captured
    assert any((tmp_path / "profile").rglob("*.xplane.pb"))


def test_hlo_report_writes_json_and_logs(tmp_path):
    """--hlo_report compiles the step ahead of the loop and leaves the
    schedule report on disk; a plain data-parallel run has no overlap
    flags, so zero tripwire warnings."""
    t = make_trainer(tmp_path, max_steps=2, hlo_report=True)
    state = t.train()
    assert int(state.step) == 2
    rep = json.loads((tmp_path / "hlo_report.json").read_text())
    for k in ("ops", "wire_mb_estimate", "gather", "ring", "composed",
              "warnings", "compile_s"):
        assert k in rep, k
    assert rep["warnings"] == []


# -- HLO schedule report (text-level) --------------------------------------

# hand-written HLO with one dot-carrying loop body whose all-gather is
# compute-INDEPENDENT (operand %w is loop-carried) and whose all-reduce is
# compute-DEPENDENT (operand %d is this body's dot) — the r8 signature
_HLO_OVERLAPPED = """\
HloModule synthetic

%body (p: (s32[], f32[8,8])) -> (s32[], f32[8,8]) {
  %p = (s32[], f32[8,8]{1,0}) parameter(0)
  %i = s32[] get-tuple-element(%p), index=0
  %w = f32[8,8]{1,0} get-tuple-element(%p), index=1
  %g = f32[8,8]{1,0} all-gather(%w), replica_groups={{0,1}}
  %d = f32[8,8]{1,0} dot(%g, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %r = f32[8,8]{1,0} all-reduce(%d), to_apply=%sum
  ROOT %t = (s32[], f32[8,8]{1,0}) tuple(%i, %r)
}

ENTRY %main (x: f32[8,8]) -> f32[8,8] {
  ROOT %out = f32[8,8]{1,0} copy(%x)
}
"""

# the de-overlapped twin: the gather consumes the dot — no schedulable
# freedom anywhere; likewise the ring body's ppermute
_HLO_SERIAL = """\
HloModule synthetic_serial

%body (p: (s32[], f32[8,8])) -> (s32[], f32[8,8]) {
  %p = (s32[], f32[8,8]{1,0}) parameter(0)
  %i = s32[] get-tuple-element(%p), index=0
  %w = f32[8,8]{1,0} get-tuple-element(%p), index=1
  %d = f32[8,8]{1,0} dot(%w, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %g = f32[8,8]{1,0} all-gather(%d), replica_groups={{0,1}}
  ROOT %t = (s32[], f32[8,8]{1,0}) tuple(%i, %g)
}

%ring (q: (s32[], f32[4,4])) -> (s32[], f32[4,4]) {
  %q = (s32[], f32[4,4]{1,0}) parameter(0)
  %j = s32[] get-tuple-element(%q), index=0
  %v = f32[4,4]{1,0} get-tuple-element(%q), index=1
  %d2 = f32[4,4]{1,0} dot(%v, %v), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %cp = f32[4,4]{1,0} collective-permute(%d2), source_target_pairs={{0,1},{1,0}}
  ROOT %t2 = (s32[], f32[4,4]{1,0}) tuple(%j, %cp)
}

ENTRY %main (x: f32[8,8]) -> f32[8,8] {
  ROOT %out = f32[8,8]{1,0} copy(%x)
}
"""


def test_collective_evidence_classifies_synthetic_bodies():
    ev = collective_evidence(_HLO_OVERLAPPED)
    assert len(ev["bodies"]) == 1
    body = ev["bodies"][0]
    assert body["dots"] == 1 and body["collectives"] == 2
    assert body["compute_independent_collectives"] == 1
    assert body["compute_dependent_collectives"] == 1
    assert ev["prefetch_gather_independent"] is True
    serial = collective_evidence(_HLO_SERIAL)
    assert all(r["compute_independent_collectives"] == 0
               for r in serial["bodies"])
    assert serial["prefetch_gather_independent"] is False


def test_ring_evidence_counts_clean_bodies():
    ev = ring_evidence(_HLO_SERIAL)
    assert ev["ring_bodies"] == 1  # the %ring body carries a ppermute
    assert ev["independent_ring_bodies"] == 0  # but it consumes the dot


def test_op_census_counts_and_wire_bytes():
    census = op_census(_HLO_OVERLAPPED)
    assert census["all-gather"]["count"] == 1
    assert census["all-gather"]["wire_bytes"] == 8 * 8 * 4
    assert census["all-reduce"]["count"] == 1


def test_schedule_report_shape():
    rep = schedule_report(_HLO_OVERLAPPED)
    assert rep["gather"]["independent_bodies"] == 1
    assert rep["gather"]["dependent_collectives"] == 1
    assert rep["ring"]["ring_bodies"] == 0
    assert rep["wire_mb_estimate"] >= 0


def test_tripwire_flags_de_overlapped_config():
    """The acceptance tripwire: a config CLAIMING overlap whose compiled
    program shows no schedulable freedom must WARN — per axis, with the
    reason named."""
    from pytorch_ddp_template_tpu.config import TrainingConfig

    cfg = TrainingConfig(scan_layers=True, fsdp_overlap=True,
                         tp_overlap=True, mesh="data:2,model:2")
    rep = schedule_report(_HLO_SERIAL)
    warns = check_overlap_expectations(rep, cfg,
                                       {"data": 2, "model": 2})
    assert any("--fsdp_overlap" in w for w in warns)
    assert any("--tp_overlap" in w for w in warns)
    # degenerate axes are NOT degraded schedules: no collectives compile
    # at size 1, so the tripwire stays silent
    assert check_overlap_expectations(rep, cfg,
                                      {"data": 1, "model": 1}) == []
    # and a healthy overlapped program passes the fsdp check
    ok = schedule_report(_HLO_OVERLAPPED)
    warns_ok = check_overlap_expectations(
        ok, TrainingConfig(scan_layers=True, fsdp_overlap=True),
        {"data": 2})
    assert warns_ok == []


def test_ddp_tripwire_wants_inscan_reduce():
    from pytorch_ddp_template_tpu.config import TrainingConfig

    cfg = TrainingConfig(scan_layers=True, ddp_overlap=True)
    # _HLO_SERIAL's gather body still has an in-body reduce → no warning
    assert check_overlap_expectations(
        schedule_report(_HLO_SERIAL), cfg, {"data": 2}) == []
    # a program with NO collective in any dot-carrying body → warning
    no_coll = _HLO_SERIAL.replace(
        "  %g = f32[8,8]{1,0} all-gather(%d), replica_groups={{0,1}}\n", ""
    ).replace("tuple(%i, %g)", "tuple(%i, %d)")
    warns = check_overlap_expectations(
        schedule_report(no_coll), cfg, {"data": 2})
    assert any("--ddp_overlap" in w for w in warns)


@pytest.mark.slow
def test_hlo_report_matches_composed_evidence_on_real_schedule(devices):
    """Acceptance: --hlo_report's counts on the composed fsdp×tp schedule
    must equal the r11 ``hlo_composed_evidence`` leg's (same walkers, one
    home), report zero tripwire warnings for the genuinely-composed
    program — and flag the SAME geometry compiled WITHOUT the overlap
    execution (the deliberately de-overlapped configuration)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.parallel.schedule import (
        hlo_composed_evidence,
    )
    from pytorch_ddp_template_tpu.parallel.sharding import (
        fsdp_reshard, shard_tree,
    )
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer, make_train_step,
    )

    mesh = make_mesh("data:4,model:2", jax.devices())
    vocab, seq, depth = 512, 32, 2
    ids = np.random.default_rng(0).integers(0, vocab, (8, seq))
    batch = {"input_ids": jax.device_put(
        np.asarray(ids, np.int32), NamedSharding(mesh, P("data")))}
    key = jax.random.PRNGKey(0)
    cfg = TrainingConfig(warmup_steps=0, max_grad_norm=1000.0)
    tx, sched = make_optimizer(cfg, total_steps=100)

    def compiled_text(composed: bool):
        model = GptDecoder(
            vocab_size=vocab, max_len=seq, num_layers=depth, num_heads=4,
            head_dim=8, mlp_dim=64, scan_layers=True, fused_head=True,
            fsdp_overlap=composed, tp_overlap=composed,
            mesh=mesh if composed else None)
        task = CausalLmTask(model)
        params, extra = task.init(key, batch)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, extra_vars=extra,
            opt_state=tx.init(params), rng=jax.random.clone(key))
        state = shard_tree(state, mesh)
        if composed:
            state = state.replace(
                params=fsdp_reshard(state.params, mesh, prefer_dim=0),
                opt_state=fsdp_reshard(state.opt_state, mesh, prefer_dim=0))
        return make_train_step(task, tx, sched).lower(
            state, batch).compile().as_text()

    claim = TrainingConfig(scan_layers=True, fsdp_overlap=True,
                           tp_overlap=True, mesh="data:4,model:2")
    text = compiled_text(composed=True)
    ev = hlo_composed_evidence(text)
    rep = schedule_report(text)
    assert (rep["composed"]["independent_gather_bodies"]
            == ev["independent_gather_bodies"] > 0)
    assert (rep["composed"]["independent_ring_bodies"]
            == ev["independent_ring_bodies"] > 0)
    assert rep["composed"]["composed_overlap_independent"] is True
    assert check_overlap_expectations(rep, claim, dict(mesh.shape)) == []

    # the de-overlapped configuration: same claim, GSPMD-default program
    rep_off = schedule_report(compiled_text(composed=False))
    warns = check_overlap_expectations(rep_off, claim, dict(mesh.shape))
    assert warns, "the tripwire must flag the de-overlapped schedule"
    assert any("--tp_overlap" in w for w in warns)
