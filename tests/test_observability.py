"""Profiler + divergence subsystems (SURVEY.md §5.1/§5.2 — absent in the
reference, first-class here)."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ddp_template_tpu.utils.divergence import check, fingerprint
from pytorch_ddp_template_tpu.utils.profiler import StepTimer, TraceWindow


def test_fingerprint_detects_any_leaf_change():
    tree = {"a": jnp.arange(8.0), "b": {"w": jnp.ones((3, 3))}}
    fp = np.asarray(fingerprint(tree))
    tree2 = {"a": jnp.arange(8.0).at[3].add(1e-3), "b": {"w": jnp.ones((3, 3))}}
    fp2 = np.asarray(fingerprint(tree2))
    assert not np.array_equal(fp, fp2)
    np.testing.assert_array_equal(fp, np.asarray(fingerprint(tree)))


def test_check_single_process_true():
    assert check({"w": jnp.ones(4)}) is True


def test_step_timer_summary():
    t = StepTimer()
    assert t.summary() == {}
    for _ in range(5):
        t.tick()
    s = t.summary()
    assert set(s) == {"step_time_p50_ms", "step_time_p90_ms",
                      "step_time_p99_ms", "step_time_mean_ms"}
    assert all(v >= 0 for v in s.values())


def test_trace_window_writes_profile(tmp_path):
    tw = TraceWindow(tmp_path, start_step=1, num_steps=2)
    for step in range(5):
        tw.step(step)
        jnp.sum(jnp.arange(16.0)).block_until_ready()
    tw.close()
    profile_dir = tmp_path / "profile"
    assert profile_dir.exists()
    assert any(profile_dir.rglob("*.xplane.pb")), list(profile_dir.rglob("*"))


def test_trainer_with_profiling_and_divergence(tmp_path):
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        model="mlp", dataset_size=256, per_device_train_batch_size=2,
        max_steps=14, logging_steps=5, save_steps=0, output_dir=str(tmp_path),
        profile_steps=2, divergence_check_steps=5, resume=False,
    )
    mesh = make_mesh("data:-1", jax.devices())
    key = jax.random.PRNGKey(0)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=cfg)
    task, ds = build("mlp", cfg)
    state = Trainer(cfg, ctx, task, ds).train()
    assert int(state.step) == 14
    assert (tmp_path / "profile").exists()
