"""The host-sync-free hot loop (ISSUE 1 tentpole): async telemetry
delivery guarantees, the steady-state no-host-sync discipline, the
device-side preemption-stop reduction, and the bench-side guards that ride
along (ablation-aware ``_last_recorded``)."""

import importlib.util
import json
import threading
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig, parse_args
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init
from pytorch_ddp_template_tpu.train import Trainer
from pytorch_ddp_template_tpu.train.metrics import (
    AsyncTelemetry,
    MetricsWriter,
    SyncTelemetry,
    make_telemetry,
)

REPO = Path(__file__).resolve().parent.parent


def make_trainer(tmp_path, **overrides) -> Trainer:
    defaults = dict(
        output_dir=str(tmp_path / "out"),
        per_device_train_batch_size=4,
        dataset_size=512,
        logging_steps=0,
        save_steps=0,
        max_steps=8,
        seed=0,
        resume=False,
    )
    defaults.update(overrides)
    cfg = TrainingConfig(**defaults)
    ctx = init(cfg)
    task, ds = build(cfg.model, cfg)
    return Trainer(cfg, ctx, task, ds)


class TestAsyncTelemetrySink:
    def test_flushes_completely_on_close(self, tmp_path):
        """Every emitted record — device arrays, windows, lazy dicts —
        lands in the JSONL before close() returns; nothing is dropped."""
        w = MetricsWriter(tmp_path)
        tel = AsyncTelemetry(w)
        xs = jnp.arange(6, dtype=jnp.float32)  # one dispatch, six scalars
        for i in range(6):
            tel.emit(i, {
                "x": xs[i],                                # device scalar
                "win": [xs[i], xs[i] + 2.0],               # raw window
                "lazy": (lambda i=i: {"p50": float(i)}),   # deferred dict
                "host": 1.5,
            })
        tel.close()
        rows = [json.loads(l) for l in
                (tmp_path / "metrics.jsonl").read_text().splitlines()]
        assert [r["step"] for r in rows] == list(range(6))
        for i, r in enumerate(rows):
            assert r["x"] == pytest.approx(float(i))
            assert r["win"] == pytest.approx(i + 1.0)  # mean of (i, i+2)
            assert r["p50"] == pytest.approx(float(i))
            assert r["host"] == 1.5

    def test_close_idempotent_and_late_emit_inline(self, tmp_path):
        w = MetricsWriter(tmp_path)
        tel = AsyncTelemetry(w)
        tel.emit(1, {"a": 1.0})
        tel.close()
        tel.close()  # no-op
        tel.emit(2, {"a": 2.0})  # post-close: written inline, not dropped
        rows = [json.loads(l) for l in
                (tmp_path / "metrics.jsonl").read_text().splitlines()]
        assert [r["step"] for r in rows] == [1, 2]

    def test_trainer_crash_still_flushes_final_interval(self, tmp_path, monkeypatch):
        """The trainer closes the sink in a finally: a crash after the last
        logging emit must not lose that interval's scalars."""
        t = make_trainer(tmp_path, logging_steps=2, max_steps=6)

        def boom(*a, **k):
            raise RuntimeError("boom")

        # poison the end-of-training save: the loop finishes (and emits at
        # step 6) before train() raises out of the final checkpoint
        monkeypatch.setattr(t.ckpt, "save", boom)
        with pytest.raises(RuntimeError, match="boom"):
            t.train()
        rows = [json.loads(l) for l in
                (tmp_path / "out" / "metrics.jsonl").read_text().splitlines()]
        assert any(r["step"] == 6 and "loss" in r for r in rows), rows

    def test_sync_mode_writes_inline_same_keys(self, tmp_path):
        """--telemetry sync produces the same record schema, synchronously
        (the host_overhead_pct before-leg must differ in WHEN, not WHAT)."""
        wa = MetricsWriter(tmp_path / "a")
        ws = MetricsWriter(tmp_path / "s")
        ta, ts = AsyncTelemetry(wa), SyncTelemetry(ws)
        rec = {"loss": [jnp.float32(3.0)], "lr": jnp.float32(0.1)}
        ta.emit(5, dict(rec))
        ts.emit(5, dict(rec))
        ta.close()
        ts.close()
        ra = json.loads((tmp_path / "a" / "metrics.jsonl").read_text())
        rs = json.loads((tmp_path / "s" / "metrics.jsonl").read_text())
        assert set(ra) == set(rs)
        assert ra["loss"] == rs["loss"] == pytest.approx(3.0)

    def test_make_telemetry_rejects_unknown(self, tmp_path):
        w = MetricsWriter(tmp_path)
        with pytest.raises(ValueError, match="telemetry"):
            make_telemetry("typo", w)


class TestSteadyStateNoHostSync:
    def test_loop_emits_device_arrays_and_writes_off_thread(self, tmp_path, monkeypatch):
        """The tier-1 discipline check: over N steps the loop hands the
        sink *device* values (no inline float conversions), all writer
        writes happen on the drain thread, and the only main-thread
        ``jax.device_get`` calls are the bounded-depth fence reads
        (≤ one per step)."""
        t = make_trainer(tmp_path, logging_steps=2, max_steps=8)
        state, _ = t.restore_or_init()

        get_counts: dict[int, int] = {}
        real_get = jax.device_get

        def counting_get(x):
            ident = threading.get_ident()
            get_counts[ident] = get_counts.get(ident, 0) + 1
            return real_get(x)

        monkeypatch.setattr(jax, "device_get", counting_get)

        emitted = []
        orig_emit = t.telemetry.emit

        def spy_emit(step, scalars, kind="progress"):
            emitted.append((step, dict(scalars)))
            orig_emit(step, scalars, kind)

        monkeypatch.setattr(t.telemetry, "emit", spy_emit)

        write_threads = []
        orig_write = t.metrics_writer.write

        def spy_write(step, scalars):
            write_threads.append(threading.get_ident())
            orig_write(step, scalars)

        monkeypatch.setattr(t.metrics_writer, "write", spy_write)

        main = threading.get_ident()
        t._train_loop(state, 0, {"sig": None})
        t.telemetry.close()

        # 4 logging intervals over 8 steps reached the sink
        assert [s for s, _ in emitted] == [2, 4, 6, 8]
        for _, scalars in emitted:
            # losses arrive as the raw device-scalar window, lr/grad_norm
            # as device arrays — proof the loop converted nothing inline
            assert isinstance(scalars["loss"], list)
            assert all(isinstance(x, jax.Array) for x in scalars["loss"])
            assert isinstance(scalars["lr"], jax.Array)
            assert isinstance(scalars["grad_norm"], jax.Array)
            assert callable(scalars["timer"])  # percentiles deferred too
        # every TB/JSONL write ran on the drain thread, never the loop
        assert write_threads and all(i != main for i in write_threads)
        # main thread: fence reads only — at most one per step
        assert get_counts.get(main, 0) <= 8, get_counts
        # and the conversions really happened somewhere else
        drain_gets = sum(v for k, v in get_counts.items() if k != main)
        assert drain_gets >= 4  # ≥ one fetch per interval

    def test_bounded_inflight_caps_dispatch_depth(self, tmp_path):
        """max_inflight_steps=1 must still train correctly (the fence just
        bites every step)."""
        t = make_trainer(tmp_path, logging_steps=2, max_steps=6,
                         max_inflight_steps=1)
        state = t.train()
        assert int(state.step) == 6


class TestDeviceSideStopAgreement:
    def test_stop_flag_reduction_ors_across_devices(self, tmp_path):
        """The jitted step's stop_agreed is a device-side OR of per-device
        votes: a single dissenting device's 1 must surface — this is the
        single-host proof of the mechanism the two-process SIGTERM
        rehearsal exercises across real processes (only one of two
        signalled)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pytorch_ddp_template_tpu.train.engine import (
            make_stop_flags, make_train_step,
        )

        t = make_trainer(tmp_path)
        step = make_train_step(t.task, t.tx, t.schedule, 1, with_stop=True)
        state, _ = t.restore_or_init()
        batch = next(iter(t.loader.epoch(0)))

        mesh = t.ctx.mesh
        flags = make_stop_flags(mesh, False)
        assert flags.shape == (mesh.devices.size,)
        state, m = step(state, batch, flags)
        assert int(m["stop_agreed"]) == 0

        # one device (= one "process" worth of vote) flips to 1
        sharding = NamedSharding(mesh, P(mesh.axis_names))
        devs = list(mesh.devices.reshape(-1))
        arrays = [
            jax.device_put(np.asarray([1 if i == 3 else 0], np.int32), d)
            for i, d in enumerate(devs)
        ]
        mixed = jax.make_array_from_single_device_arrays(
            (len(devs),), sharding, arrays
        )
        state, m = step(state, batch, mixed)
        assert int(m["stop_agreed"]) == 1

    def test_single_process_sigterm_stops_without_device_roundtrip(self, tmp_path):
        """Single-process stop stays a pure host decision: the local flag
        set mid-run stops the loop and checkpoints (the engine builds no
        stop-flags arrays when process_count == 1)."""
        import os
        import signal
        import time

        t = make_trainer(tmp_path, max_steps=200_000, dataset_size=4096)
        assert t._with_stop is False

        before = signal.getsignal(signal.SIGTERM)

        def fire_when_armed():
            deadline = time.time() + 120
            while (time.time() < deadline
                   and signal.getsignal(signal.SIGTERM) == before):
                time.sleep(0.05)
            time.sleep(0.2)
            os.kill(os.getpid(), signal.SIGTERM)

        shooter = threading.Thread(target=fire_when_armed, daemon=True)
        shooter.start()
        state = t.train()
        assert 0 < int(state.step) < 200_000
        assert t.ckpt.latest_step() == int(state.step)


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench_for_test",
                                                  REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestLastRecordedAblationGuard:
    def test_prefers_clean_record_over_newer_ablation(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BENCH_RECORDS_DIR", str(tmp_path))
        (tmp_path / "a_clean.jsonl").write_text(
            json.dumps({"metric": "m", "value": 10.0, "unit": "u"}) + "\n")
        (tmp_path / "b_ablated.jsonl").write_text(
            json.dumps({"metric": "m", "value": 99.0, "unit": "u",
                        "remat": True}) + "\n")
        bench = _load_bench()
        best = bench._last_recorded("m")
        assert best["value"] == 10.0
        assert "ablation_flags" not in best

    def test_only_ablated_surfaces_with_flags(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BENCH_RECORDS_DIR", str(tmp_path))
        (tmp_path / "only.jsonl").write_text(
            json.dumps({"metric": "m2", "value": 7.0, "unit": "u",
                        "dense_head": True, "flash_disabled": True}) + "\n")
        bench = _load_bench()
        best = bench._last_recorded("m2")
        assert best["value"] == 7.0
        assert best["ablation_flags"] == ["dense_head", "flash_disabled"]


class TestNewConfigSurface:
    def test_telemetry_and_inflight_flags_parse(self):
        cfg = parse_args(["--telemetry", "sync", "--max_inflight_steps", "4"])
        assert cfg.telemetry == "sync"
        assert cfg.max_inflight_steps == 4
        assert parse_args([]).telemetry == "async"
        assert parse_args([]).max_inflight_steps == 2


class TestPipeMicrobatchClampWarning:
    def test_serialising_clamp_refuses(self, tmp_path, monkeypatch):
        """gcd clamp below --pipe_microbatches must be loud: a coprime
        batch/microbatch combination silently serialises the pipeline
        (round-5 advisor finding; r16 escalated the fully-serialising
        case from a one-shot warning to a named refusal — partial
        clamps still warn, tests/test_pipeline.py)."""
        from pytorch_ddp_template_tpu.runtime import make_mesh

        cfg = TrainingConfig(
            model="gpt-pipe-tiny", mesh="data:4,pipe:2",
            per_device_train_batch_size=1, pipe_microbatches=4,
            dataset_size=64, output_dir=str(tmp_path), resume=False,
        )
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, _ = build(cfg.model, cfg, mesh=mesh)

        import flax.linen as nn

        # 4 rows over data:4 → per_replica 1, gcd(4,1)=1 → the pipeline
        # would fully serialise: a refusal naming both fixes
        ids = np.asarray(
            np.random.default_rng(0).integers(0, 1024, (4, 128)), np.int32)
        params, _ = task.init(jax.random.PRNGKey(0), {"input_ids": ids})
        with pytest.raises(ValueError, match="serialise"):
            task._apply_inputs(nn.meta.unbox(params), {},
                               (jnp.asarray(ids),), None, False)
