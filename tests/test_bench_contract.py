"""Driver-contract tests for bench.py: every mode must emit exactly one
parseable JSON line with the required keys on stdout, and failures must be
JSON too (the driver records whatever this prints — a stack trace instead
of a line is a lost round's evidence)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def run_bench(extra_env: dict, timeout: int = 420) -> tuple[int, list[dict], str]:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({"BENCH_CPU": "1", "BENCH_WARMUP": "1", "BENCH_STEPS": "2",
                "JAX_PLATFORMS": "cpu", **extra_env})
    p = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=timeout)
    lines = []
    for line in p.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            lines.append(json.loads(line))
    return p.returncode, lines, p.stdout + p.stderr


REQUIRED = {"metric", "value", "unit", "vs_baseline"}


def test_train_mode_contract():
    code, lines, out = run_bench({"BENCH_MODE": "train", "BENCH_MODEL": "mlp",
                                  "BENCH_BATCH": "8"})
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    assert REQUIRED <= set(lines[0])
    assert lines[0]["value"] > 0


def test_e2e_mode_reports_both_paths():
    code, lines, out = run_bench({"BENCH_MODE": "e2e", "BENCH_MODEL": "mlp",
                                  "BENCH_BATCH": "8",
                                  "BENCH_OUTPUT": "/tmp/bench_e2e_test"})
    assert code == 0, out[-2000:]
    assert len(lines) == 1
    row = lines[0]
    assert REQUIRED <= set(row)
    assert "cached_batch_per_chip" in row and "input_path_overhead_pct" in row
    assert row["data_source"] == "synthetic"


def test_scaling_mode_flags_degenerate_single_device():
    code, lines, out = run_bench({"BENCH_MODE": "scaling", "BENCH_MODEL": "mlp",
                                  "BENCH_BATCH": "8", "BENCH_CPU_DEVICES": "1"})
    assert code == 0, out[-2000:]
    row = lines[-1]
    assert row["degenerate"] is True
    assert row["vs_baseline"] == 0.0  # a 1-chip sweep must not read as a pass


@pytest.mark.slow
def test_compile_mode_contract():
    """BENCH_MODE=compile: one JSON line carrying the per-depth unrolled vs
    scanned compile table and the throughput-neutrality step-time leg
    (slow: a subprocess compiling four tiny models — the committed record
    in bench_records/compile_scan_cpu_r7.jsonl is the tier-1-visible
    evidence; tests/test_scan_layers.py's trace-time guard is the fast
    re-unrolling tripwire)."""
    # depths deliberately unsorted and warmup 0: the headline must come
    # from the DEEPEST row, and the step-time leg must not need a warmup
    # metric to fence on
    code, lines, out = run_bench({
        "BENCH_MODE": "compile", "BENCH_DEPTHS": "2,1", "BENCH_BATCH": "2",
        "BENCH_SEQ": "16", "BENCH_WARMUP": "0", "BENCH_STEPS": "2",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "scan_compile_speedup_2L"
    assert row["value"] > 0
    depth2 = next(r for r in row["compile_table"] if r["depth"] == 2)
    assert row["value"] == depth2["compile_speedup"]
    assert [r["depth"] for r in row["compile_table"]] == [2, 1]
    for r in row["compile_table"]:
        assert r["unrolled_total_s"] > 0 and r["scanned_total_s"] > 0
    assert row["step_time_unrolled_ms"] > 0
    assert row["step_time_scanned_ms"] > 0


def test_unknown_mode_fails_as_json():
    code, lines, out = run_bench({"BENCH_MODE": "typo"})
    assert code == 1
    assert len(lines) == 1, out[-2000:]
    assert lines[0]["value"] == 0.0
    assert "error" in lines[0]


def test_twoproc_record_within_band():
    """The committed two-process perf record (tools/twoproc_bench.py,
    VERDICT r4 #7) must exist and sit in the sane band: the cross-process
    path neither collapsed nor reported impossible speedup."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "twoproc_cpu_r5.jsonl"
    assert path.is_file(), "run tools/twoproc_bench.py to record the probe"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"] == "twoproc_train_steps_per_sec"
    assert last["value"] > 0
    assert 0.05 <= last["ratio_vs_single"] <= 3.0
    assert last["twoproc_psum_1mib_ms"] > 0


@pytest.mark.slow
def test_overlap_mode_contract():
    """BENCH_MODE=overlap: one JSON line carrying the decomposed-FSDP
    pair — bit-parity, HLO schedule evidence, memory live-range and the
    step-time ratio (slow: a subprocess compiling two depth-2 train
    steps; the committed record in bench_records/overlap_cpu_r8.jsonl is
    the tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "overlap", "BENCH_CPU_DEVICES": "4",
        "BENCH_DEPTH": "4", "BENCH_SEQ": "16", "BENCH_BATCH": "1",
        "BENCH_WARMUP": "1", "BENCH_STEPS": "2",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "fsdp_overlap_step_ratio_4L"
    assert row["degenerate"] is False
    assert row["value"] > 0
    # the two execution paths trained the same model: tight parity
    assert abs(row["loss_default"] - row["loss_overlap"]) < 1e-5
    assert row["parity_max_abs_diff"] < 1e-6
    # schedule evidence present and affirmative on the CPU partitioner
    assert row["hlo_prefetch_gather_independent"] is True
    assert row["hlo_bwd_regather_independent"] is True
    assert row["hlo_bodies"]
    if row.get("temp_overlap_mb") is not None:
        assert row["live_range_ok"] is True


def test_overlap_record_committed_and_affirmative():
    """The committed round-8 CPU record must exist and actually show the
    evidence the round claims: HLO schedule booleans true, parity at fp
    tolerance, live range within two gathered layers."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "overlap_cpu_r8.jsonl"
    assert path.is_file(), "run BENCH_MODE=overlap to record the pair"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"].startswith("fsdp_overlap_step_ratio")
    assert last["hlo_prefetch_gather_independent"] is True
    assert last["hlo_bwd_regather_independent"] is True
    assert last["parity_max_abs_diff"] < 1e-6
    assert last["live_range_ok"] is True
    # neutrality-or-better on the recorded pair (0.9 band -> vs_baseline)
    assert last["vs_baseline"] >= 1.0


@pytest.mark.slow
def test_comms_mode_contract():
    """BENCH_MODE=comms: one JSON line carrying the compressed-DDP legs —
    fp32 bit-parity, per-layer in-scan HLO reduce evidence, wire-byte
    ratios and the convergence fields (slow: a subprocess compiling six
    small train steps; the committed record in
    bench_records/comms_cpu_r9.jsonl is the tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "comms", "BENCH_CPU_DEVICES": "4",
        "BENCH_DEPTH": "2", "BENCH_SEQ": "16", "BENCH_BATCH": "1",
        "BENCH_WARMUP": "1", "BENCH_STEPS": "2", "BENCH_CONV_STEPS": "4",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "ddp_overlap_step_ratio_2L"
    assert row["degenerate"] is False
    assert row["value"] > 0
    # the two execution paths trained the same model: tight parity
    assert abs(row["loss_default"] - row["loss_overlap"]) < 1e-5
    assert row["parity_max_abs_diff"] < 1e-6
    # per-layer reduce really lives inside a dot-carrying loop body
    assert row["hlo_per_layer_reduce"] is True
    assert row["hlo_inscan_reduce_collectives"] >= row["depth"]
    # wire-byte contract: bf16 halves, int8 at most 0.3x
    assert row["wire_bf16_vs_fp32"] == 0.5
    assert row["wire_int8_vs_fp32"] <= 0.3
    for k in ("loss_dev_int8_ef", "loss_dev_int8_no_ef",
              "param_dist_int8_ef", "param_dist_int8_no_ef"):
        assert k in row


@pytest.mark.slow
def test_tp_mode_contract():
    """BENCH_MODE=tp: one JSON line carrying the decomposed-TP legs —
    default-vs-ring parity, the column-op bit probe, fwd/bwd HLO ring
    evidence, wire split and the memory fields (slow: a subprocess
    compiling three small train steps; the committed record in
    bench_records/tp_cpu_r10.jsonl is the tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "tp", "BENCH_CPU_DEVICES": "4",
        "BENCH_DEPTH": "2", "BENCH_SEQ": "32", "BENCH_VOCAB": "512",
        "BENCH_BATCH": "1", "BENCH_WARMUP": "1", "BENCH_STEPS": "2",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "tp_overlap_step_ratio_2L"
    assert row["degenerate"] is False
    assert row["value"] > 0
    # the two execution paths trained the same model: tight parity
    assert abs(row["loss_default"] - row["loss_tp"]) < 1e-5
    assert row["parity_max_abs_diff"] < 1e-6
    assert row["col_bit_exact"] is True
    # ring evidence: compute-independent ppermute chains in BOTH passes
    assert row["hlo_fwd_ring_independent"] is True
    assert row["hlo_bwd_ring_independent"] is True
    assert row["hlo_fwd_independent_ring_bodies"] > 0
    assert row["hlo_bwd_independent_ring_bodies"] > 0
    # wire split present and consistent
    assert row["tp_wire_mb_per_step"] == pytest.approx(
        row["tp_wire_mb_stack"] + row["tp_wire_mb_head"], abs=2e-3)
    # memory leg computed (its True/False verdict needs a real vocab —
    # the committed-record test asserts it; tiny-vocab temps are noise)
    assert "live_range_ok" in row


def test_tp_mode_single_chip_degenerate():
    """One device = no model axis: the tp mode must emit a degenerate
    zero-value line (r8 convention), never a fake pass."""
    code, lines, out = run_bench({
        "BENCH_MODE": "tp", "BENCH_CPU_DEVICES": "1",
    })
    assert code == 0, out[-2000:]
    row = lines[-1]
    assert row["degenerate"] is True
    assert row["value"] == 0.0 and row["vs_baseline"] == 0.0


def test_tp_record_committed_and_affirmative():
    """The committed round-10 CPU record must exist and actually show the
    evidence the round claims: column bit-exactness, default-vs-ring
    parity at fp tolerance, independent ring bodies in both fwd and bwd,
    the never-materialised-logits live range, and neutrality-or-better on
    the FLOPs-matched step-time pair."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "tp_cpu_r10.jsonl"
    assert path.is_file(), "run BENCH_MODE=tp to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"].startswith("tp_overlap_step_ratio")
    assert last["degenerate"] is False
    assert last["col_bit_exact"] is True
    assert last["parity_max_abs_diff"] < 1e-6
    assert last["hlo_fwd_ring_independent"] is True
    assert last["hlo_bwd_ring_independent"] is True
    assert last["live_range_ok"] is True
    # neutrality-or-better on the recorded pair (0.9 band -> vs_baseline)
    assert last["vs_baseline"] >= 1.0


@pytest.mark.slow
def test_overlap3d_mode_contract():
    """BENCH_MODE=overlap3d: one JSON line carrying the composed
    fsdp×tp legs — parity vs the FLOPs-matched GSPMD default, the
    both-axes HLO schedule evidence (gather-family collectives AND ring
    ppermutes compute-independent reachable from one scanned body), the
    ddp×tp eval probe and the step-time ratio (slow: a subprocess
    compiling three small train steps; the committed record in
    bench_records/overlap3d_cpu_r11.jsonl is the tier-1-visible
    evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "overlap3d", "BENCH_CPU_DEVICES": "4",
        "BENCH_DEPTH": "2", "BENCH_SEQ": "32", "BENCH_VOCAB": "512",
        "BENCH_BATCH": "1", "BENCH_WARMUP": "1", "BENCH_STEPS": "2",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "overlap3d_step_ratio_2L"
    assert row["degenerate"] is False
    assert row["value"] > 0
    # the two execution paths trained the same model: tight parity
    assert abs(row["loss_default"] - row["loss_composed"]) < 1e-5
    assert row["parity_max_abs_diff"] < 1e-6
    # the ddp×tp composition probes clean too
    assert abs(row["loss_ddp_tp_probe"] - row["loss_ddp_tp_ref"]) < 1e-5
    assert row["ddp_tp_parity_max_abs_diff"] < 1e-6
    # BOTH axes' collectives compute-independent in one scanned body
    assert row["hlo_independent_gather_bodies"] > 0
    assert row["hlo_independent_ring_bodies"] > 0
    assert row["hlo_composed_overlap_independent"] is True
    # wire split present and consistent
    assert row["tp_wire_mb_per_step"] == pytest.approx(
        row["tp_wire_mb_stack"] + row["tp_wire_mb_head"], abs=2e-3)


def test_overlap3d_mode_too_few_devices_degenerate():
    """Fewer than data:2 × model:2 devices = nothing to compose: the
    overlap3d mode must emit a degenerate zero-value line (r8
    convention), never a fake pass."""
    code, lines, out = run_bench({
        "BENCH_MODE": "overlap3d", "BENCH_CPU_DEVICES": "2",
    })
    assert code == 0, out[-2000:]
    row = lines[-1]
    assert row["degenerate"] is True
    assert row["value"] == 0.0 and row["vs_baseline"] == 0.0


def test_overlap3d_record_committed_and_affirmative():
    """The committed round-11 CPU record must exist and actually show
    the evidence the round claims: composed-vs-default parity at fp
    tolerance, the ddp×tp probe clean, both axes' collectives
    compute-independent in one scanned body, and neutrality-or-better
    on the FLOPs-matched step-time pair."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "overlap3d_cpu_r11.jsonl"
    assert path.is_file(), "run BENCH_MODE=overlap3d to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"].startswith("overlap3d_step_ratio")
    assert last["degenerate"] is False
    assert last["parity_max_abs_diff"] < 1e-6
    assert last["ddp_tp_parity_max_abs_diff"] < 1e-6
    assert last["hlo_composed_overlap_independent"] is True
    assert last["hlo_independent_gather_bodies"] > 0
    assert last["hlo_independent_ring_bodies"] > 0
    # neutrality-or-better on the recorded pair (0.9 band -> vs_baseline)
    assert last["vs_baseline"] >= 1.0


@pytest.mark.slow
def test_obs_mode_contract():
    """BENCH_MODE=obs: one JSON line carrying the observability legs —
    the health-pack+sentry overhead pair, the injected-NaN flight-record
    completeness proof and the HLO census smoke (slow: a subprocess
    compiling two train steps and driving a full Trainer run; the
    committed record in bench_records/obs_cpu_r12.jsonl is the
    tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "obs", "BENCH_MODEL": "mlp",
        "BENCH_BATCH": "8", "BENCH_WARMUP": "1", "BENCH_STEPS": "3",
        "BENCH_NAN_STEP": "6", "BENCH_OUTPUT": "/tmp/bench_obs_contract",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "obs_overhead_ratio"
    assert row["value"] > 0
    assert row["sentry_false_positive"] is False
    # the injected NaN produced a complete triage bundle and halted the
    # run early through the production stop machinery
    assert row["flight_bundle_complete"] is True, row["flight_bundle_files"]
    assert row["flight_halted_early"] is True
    assert row["flight_halted_at_step"] > row["nan_injected_at_step"]
    for k in ("step_time_plain_ms", "step_time_obs_ms", "sentry_ring_len",
              "hlo_collective_ops", "hlo_wire_mb_estimate"):
        assert k in row, k


def test_obs_record_committed_and_affirmative():
    """The committed round-12 CPU record must exist and actually show the
    evidence the round claims: health-pack+sentry step-time ratio within
    the 0.9 band against sentry-off, no sentry false positive on the
    healthy leg, and the injected-NaN run leaving a complete
    flight-record bundle (all BUNDLE_FILES + the post-trigger trace)."""
    import json
    from pathlib import Path

    from pytorch_ddp_template_tpu.obs.sentry import BUNDLE_FILES

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "obs_cpu_r12.jsonl"
    assert path.is_file(), "run BENCH_MODE=obs to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"] == "obs_overhead_ratio"
    assert last["value"] >= 0.9  # neutrality band: obs costs <= ~11%
    assert last["vs_baseline"] >= 1.0
    assert last["sentry_false_positive"] is False
    assert last["sentry_ring_len"] > 0
    assert last["flight_bundle_complete"] is True
    assert last["flight_halted_early"] is True
    assert set(BUNDLE_FILES) <= set(last["flight_bundle_files"])
    assert "profile" in last["flight_bundle_files"]


@pytest.mark.slow
def test_perf_mode_contract():
    """BENCH_MODE=perf: one JSON line carrying the round-13 step-time
    X-ray legs — the attribution+annotations neutrality pair over the
    full production loop, the calibrated-peak MFU-sanity leg, the
    fraction-sum check and the goodput-ledger completeness proof (slow:
    seven full Trainer runs in a subprocess; the committed record in
    bench_records/perf_cpu_r13.jsonl is the tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "perf", "BENCH_MODEL": "mlp",
        "BENCH_BATCH": "8", "BENCH_WARMUP": "1", "BENCH_STEPS": "6",
        "BENCH_LOG_STEPS": "2", "BENCH_OUTPUT": "/tmp/bench_perf_contract",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "perf_attribution_overhead_ratio"
    assert row["value"] > 0
    # MFU sanity: in (0, 1] and consistent with the FLOPs-matched step
    # time (the calibrated peak pins the expectation near 0.25)
    assert 0.0 < row["mfu_reported"] <= 1.0
    assert row["mfu_consistent"] is True
    assert row["model_gflops_per_step"] >= 0
    # the four fractions are a partition of wall time
    assert 0.98 <= row["frac_sum"] <= 1.02
    for k in ("frac_compute", "frac_comm", "frac_host", "frac_input"):
        assert 0.0 <= row[k] <= 1.0, k
    # goodput ledger written with the full bucket set
    assert row["goodput_file_complete"] is True
    assert row["goodput"] is not None


def test_perf_record_committed_and_affirmative():
    """The committed round-13 CPU record must exist and actually show
    the evidence the round claims: attribution+annotations inside the
    0.9 step-time band, MFU in (0, 1] and consistent with the
    FLOPs-matched step time, fractions summing to ~1, and a complete
    goodput ledger."""
    import json
    from pathlib import Path

    from pytorch_ddp_template_tpu.obs.goodput import BUCKETS

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "perf_cpu_r13.jsonl"
    assert path.is_file(), "run BENCH_MODE=perf to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"] == "perf_attribution_overhead_ratio"
    assert last["value"] >= 0.9  # neutrality band: the X-ray is ~free
    assert last["vs_baseline"] >= 1.0
    assert 0.0 < last["mfu_reported"] <= 1.0
    assert last["mfu_consistent"] is True
    assert 0.98 <= last["frac_sum"] <= 1.02
    assert last["goodput_file_complete"] is True
    # the record is historical: it must carry every bucket of ITS round
    # (BUCKETS has since grown — r18 added the elastic splits), and
    # nothing outside today's ledger
    r13_buckets = {"productive_step", "compile", "checkpoint_save",
                   "restore", "input_stall", "eval", "halted", "other"}
    assert r13_buckets <= set(last["goodput_buckets_s"])
    assert set(last["goodput_buckets_s"]) <= set(BUCKETS)
    assert last["goodput_buckets_s"]["compile"] > 0


@pytest.mark.slow
def test_fleet_mode_contract():
    """BENCH_MODE=fleet: one JSON line carrying the round-14 fleet
    watchtower legs — the fleet+status+sentry neutrality pair over the
    full production loop, the live endpoint scrape, the
    injected-straggler bundle and the bench_diff tripwire pair (slow:
    seven full Trainer runs in a subprocess; the committed record in
    bench_records/fleet_cpu_r14.jsonl is the tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "fleet", "BENCH_MODEL": "mlp",
        "BENCH_BATCH": "8", "BENCH_WARMUP": "1", "BENCH_STEPS": "6",
        "BENCH_LOG_STEPS": "2", "BENCH_OUTPUT": "/tmp/bench_fleet_contract",
    })
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "fleet_overhead_ratio"
    assert row["value"] > 0
    assert row["fleet_exchanges"] > 0
    # live endpoints answered during the run
    assert row["status_http_ok"] is True
    assert row["metrics_http_ok"] is True
    assert row["healthz_ok"] is True
    assert row["status_has_fleet_table"] is True
    # the injected straggler produced a named bundle; the trace belongs
    # to the named host (the fake host 2), recorded in trigger.json
    assert row["straggler_bundle_complete"] is True
    assert row["straggler_trigger_kind"] == "straggler"
    assert row["straggler_named_host"] == 2
    assert row["straggler_trace_host"] == 2
    # the committed records pass the tripwire; a slowed copy trips it
    assert row["bench_diff_committed_rc"] == 0
    assert row["bench_diff_slowed_rc"] != 0


def test_fleet_record_committed_and_affirmative():
    """The committed round-14 CPU record must exist and actually show
    the evidence the round claims: fleet+status+sentry inside the 0.9
    step-time band, all three endpoints live mid-run, the injected
    straggler riding the sentry into a complete bundle naming host 2,
    and tools/bench_diff.py passing the committed records while
    tripping on a synthetically slowed copy."""
    import json
    from pathlib import Path

    from pytorch_ddp_template_tpu.obs.sentry import BUNDLE_FILES

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "fleet_cpu_r14.jsonl"
    assert path.is_file(), "run BENCH_MODE=fleet to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"] == "fleet_overhead_ratio"
    assert last["value"] >= 0.9  # neutrality band: the watchtower is ~free
    assert last["vs_baseline"] >= 1.0
    assert last["fleet_exchanges"] > 0
    assert last["status_http_ok"] is True
    assert last["metrics_http_ok"] is True
    assert last["healthz_ok"] is True
    assert last["straggler_bundle_complete"] is True
    assert set(BUNDLE_FILES) <= set(last["straggler_bundle_files"])
    assert last["straggler_trigger_kind"] == "straggler"
    assert last["straggler_named_host"] == 2
    assert last["straggler_trace_host"] == 2  # the NAMED host traces
    assert last["bench_diff_committed_rc"] == 0
    assert last["bench_diff_slowed_rc"] != 0


@pytest.mark.slow
def test_mem_mode_contract():
    """BENCH_MODE=mem: one JSON line carrying the round-15 memory-X-ray
    legs — the mem_report neutrality pair over the full production loop,
    the remat A/B sign-consistency check against raw memory_analysis,
    the faked-pressure bundle with /metrics HBM gauges scraped live, and
    the injected-OOM forensics bundle (slow: eight full Trainer runs +
    two AOT compiles in a subprocess; the committed record in
    bench_records/mem_cpu_r15.jsonl is the tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "mem", "BENCH_MODEL": "gpt-tiny",
        "BENCH_BATCH": "8", "BENCH_WARMUP": "1", "BENCH_STEPS": "6",
        "BENCH_LOG_STEPS": "2", "BENCH_OOM_STEP": "4",
        "BENCH_OUTPUT": "/tmp/bench_mem_contract",
    }, timeout=600)
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["metric"] == "mem_overhead_ratio"
    assert row["value"] > 0
    assert row["mem_records_written"] > 0
    # CPU: the static-degradation path is what this host pins
    assert row["mem_measured"] == 0.0
    assert row["static_split_temp_bytes"] > 0
    # remat shrinks temps, and the production split agrees with the raw
    # analysis in sign
    assert row["remat_temp_delta_bytes"] < 0
    assert row["remat_delta_sign_consistent"] is True
    # faked pressure rode the sentry into a bundle with forensics, and
    # /metrics exposed the per-device HBM gauges mid-run
    assert row["pressure_bundle_complete"] is True
    assert row["pressure_trigger_kind"] == "mem_pressure"
    assert row["metrics_http_mem_gauges"] is True
    # the injected OOM left complete forensics through the crash path
    assert row["oom_raised"] is True
    assert row["oom_forensics_complete"] is True


def test_mem_record_committed_and_affirmative():
    """The committed round-15 CPU record must exist and actually show
    the evidence the round claims: mem_report inside the 0.9 step-time
    band, kind="mem" records written (static-degradation on this CPU
    host, labelled as such), the remat A/B temp-bytes delta negative and
    sign-consistent with memory_analysis, the mem_pressure bundle
    complete, live HBM gauges, and the injected-OOM forensics bundle
    complete (census + compile-time split) through the production
    flight-recorder path."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "mem_cpu_r15.jsonl"
    assert path.is_file(), "run BENCH_MODE=mem to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"] == "mem_overhead_ratio"
    assert last["value"] >= 0.9  # neutrality band: the X-ray is ~free
    assert last["vs_baseline"] >= 1.0
    assert last["mem_records_written"] > 0
    assert last["mem_measured"] == 0.0  # CPU: static model, labelled
    assert last["static_split_temp_bytes"] > 0
    assert last["static_split_projected_peak_bytes"] > 0
    assert last["remat_temp_delta_bytes"] < 0  # remat shrinks temps
    assert last["remat_delta_sign_consistent"] is True
    assert last["pressure_bundle_complete"] is True
    assert last["pressure_trigger_kind"] == "mem_pressure"
    assert last["pressure_frac_of_limit"] > 0.9
    assert last["metrics_http_mem_gauges"] is True
    assert last["oom_raised"] is True
    assert last["oom_trigger_mode"] == "crash"
    assert last["oom_trigger_flagged"] is True
    assert last["oom_census_arrays"] > 0
    assert last["oom_forensics_complete"] is True


def test_bench_diff_ablation_keys_match_ci_gate():
    """r15 satellite: tools/ci_bench_check.sh is a thin wrapper over
    tools/bench_diff.py — the self-check over the committed records must
    exit 0 (the tripwire is armed and every committed record parses)."""
    p = subprocess.run(["bash", "tools/ci_bench_check.sh"], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "### bench_diff" in p.stdout  # the github-format table


def test_comms_record_committed_and_affirmative():
    """The committed round-9 CPU record must exist and actually show the
    evidence the round claims: >= depth independent in-scan reduces, int8
    wire bytes <= 0.3x fp32, fp32 parity at fp tolerance, error feedback
    beating no-EF on both deviation metrics, and neutrality-or-better on
    the FLOPs-matched step-time pair."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "comms_cpu_r9.jsonl"
    assert path.is_file(), "run BENCH_MODE=comms to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"].startswith("ddp_overlap_step_ratio")
    assert last["parity_max_abs_diff"] < 1e-6
    assert last["hlo_per_layer_reduce"] is True
    assert last["hlo_inscan_reduce_collectives"] >= last["depth"]
    assert last["wire_int8_vs_fp32"] <= 0.3
    assert last["ef_beats_no_ef"] is True
    assert last["loss_dev_int8_ef"] < last["loss_dev_int8_no_ef"]
    assert last["param_dist_int8_ef"] < last["param_dist_int8_no_ef"]
    # neutrality-or-better on the recorded pair (0.9 band -> vs_baseline)
    assert last["vs_baseline"] >= 1.0


@pytest.mark.slow
def test_pipe_mode_contract():
    """BENCH_MODE=pipe: one JSON line carrying the round-16 pipeline
    legs — schedule parity vs sequential stages, the FLOPs-matched
    gpipe/1f1b/zb step-ratio pair, bubble fractions from the static
    model and from measured branch times, the slot-loop HLO evidence
    and the gpipe-vs-1f1b live-range comparison (slow: ~7 fused-loss
    compiles in a subprocess; the committed record in
    bench_records/pipe_cpu_r16.jsonl is the tier-1-visible evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "pipe", "BENCH_CPU_DEVICES": "8",
        "BENCH_PIPE": "2", "BENCH_MICRO": "2", "BENCH_MICRO_MEM": "4",
        "BENCH_SEQ": "32", "BENCH_BATCH": "4", "BENCH_STEPS": "2",
        "BENCH_WARMUP": "1",
    }, timeout=1800)
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    assert row["value"] > 0
    assert row["degenerate"] is False
    assert max(row["parity_max_rel_grad"].values()) < 5e-3
    assert row["bubble_frac"]["zb"]["static"] < \
        row["bubble_frac"]["1f1b"]["static"]
    # the measured ordering is a recorded leg, not an assert: branch
    # timings on a loaded host can jitter (the COMMITTED record pins it)
    assert "bubble_measured_ordering_ok" in row
    assert row["hlo_pipe"]["1f1b"]["pipe_sends_independent"] is True
    assert row["hlo_pipe"]["zb"]["dw_ops_present"] is True


def test_pipe_mode_degenerate_without_devices():
    """Fewer than 4 devices cannot carve a pipe×data mesh: the mode
    must emit the labelled degenerate record, not a fake ratio."""
    code, lines, out = run_bench({
        "BENCH_MODE": "pipe", "BENCH_CPU_DEVICES": "1",
    }, timeout=240)
    assert code == 0, out[-2000:]
    row = lines[-1]
    assert row["degenerate"] is True
    assert row["value"] == 0.0


def test_pipe_record_committed_and_affirmative():
    """The committed round-16 CPU record must exist and actually show
    the evidence the round claims: grad parity across all three
    schedules within the float32 conventions, the FLOPs-matched 1f1b
    step ratio inside the 0.9 band and zb at-or-above 1f1b's band, the
    measured bubble fraction for zb strictly below 1f1b's, the
    slot-loop ppermutes compute-independent with zb's deferred-dw ops
    present, and the 1f1b-vs-gpipe live-range gap (O(P) vs O(M)
    activation residency)."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "pipe_cpu_r16.jsonl"
    assert path.is_file(), "run BENCH_MODE=pipe to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"].startswith("pipe_step_ratio_1f1b")
    assert last["degenerate"] is False
    # FLOPs-matched step ratios: 1f1b within the 0.9 band of gpipe on
    # WALL time; zb >= 1f1b in the lockstep schedule model at MEASURED
    # branch times (this 1-core host time-slices the 8 virtual
    # devices, so its wall clock tracks total work and charges zb the
    # tap-deferral traffic while giving it no bubble to fill — the
    # wall ratio is recorded and labelled, the real-chip triplet rides
    # tools/tpu_followup.sh legs_r16)
    assert last["value"] >= 0.9
    assert last["vs_baseline"] >= 1.0
    assert last["ratio_zb_vs_1f1b_modeled"] >= 1.0
    assert 0.5 <= last["ratio_zb_vs_1f1b_wall"]  # recorded, labelled
    assert "wall_caveat" in last
    # parity: every schedule reproduces sequential-stage autodiff
    assert max(last["parity_max_rel_grad"].values()) < 5e-3
    # the zero-bubble claim, on the static model AND with measured
    # branch times: zb's bubble strictly below 1f1b's
    bf = last["bubble_frac"]
    assert bf["zb"]["static"] < bf["1f1b"]["static"]
    assert bf["zb"]["measured"] < bf["1f1b"]["measured"]
    assert last["bubble_measured_ordering_ok"] is True
    # slot-loop schedulability witness + the dx/dw split's presence
    for kind in ("1f1b", "zb"):
        assert last["hlo_pipe"][kind]["pipe_sends_independent"] is True
        assert last["hlo_pipe"][kind]["slot_bodies"] >= 1
    assert last["hlo_pipe"]["zb"]["dw_ops_present"] is True
    # activation residency: AD-through-the-loop gpipe saves every
    # tick's residuals; 1f1b keeps the in-flight window and recomputes
    assert last["live_range_ok"] is True
    assert last["temp_bytes"]["1f1b"] < last["temp_bytes"]["gpipe"]


def test_pipe_compose_mode_degenerate_without_devices():
    """BENCH_MODE=pipe_compose on fewer than 4 devices cannot carve any
    composed mesh: the labelled degenerate record, value 0, pointing at
    the TPU followup — never a fake ratio."""
    code, lines, out = run_bench({
        "BENCH_MODE": "pipe_compose", "BENCH_CPU_DEVICES": "1",
    }, timeout=240)
    assert code == 0, out[-2000:]
    row = lines[-1]
    assert REQUIRED <= set(row)
    assert row["degenerate"] is True
    assert row["value"] == 0.0
    assert "legs_r22" in row.get("note", "")


def test_pipe_compose_record_committed_and_affirmative():
    """The committed round-22 CPU record must actually show the compose
    evidence the round claims: pipe×tp AND pipe×ddp parity against
    sequential stages inside the float32 band, the FLOPs-matched step
    ratio in band, and — the tentpole invariant — ZERO collectives
    reachable from any conditional's branch_computations in BOTH legs
    (a divergent-branch collective is a deadlock on real hardware)."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench_records" / \
        "pipe_compose_cpu_r22.jsonl"
    assert path.is_file(), "run BENCH_MODE=pipe_compose to record the legs"
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert records
    last = records[-1]
    assert last["metric"].startswith("pipe_compose_step_ratio")
    assert last["degenerate"] is False
    assert last["tp_leg_skipped"] is False
    # FLOPs-matched wall ratio: the band is generous (0.5) because the
    # 1-core host serialises the compose waves as pure extra work; the
    # lockstep win rides tools/tpu_followup.sh legs_r22
    assert last["value"] >= 0.5
    assert last["vs_baseline"] >= 1.0
    assert "wall_caveat" in last
    legs = last["compose_legs"]
    assert set(legs) == {"tp", "ddp"}
    for name, leg in legs.items():
        # parity vs sequential stages, float32 conventions
        assert leg["parity_max_rel_grad"] < 5e-3, name
        assert leg["loss_composed"] == pytest.approx(
            leg["loss_seq_ref"], rel=1e-4), name
        # the r22 invariant on the real lowering
        hlo = leg["hlo"]
        assert hlo["pipe_sends_independent"] is True, name
        assert hlo["branch_computation_count"] >= 1, name
        assert hlo["branch_collectives"] == 0, name
        assert hlo["branch_collectives_free"] is True, name
    assert legs["tp"]["mesh"] == "data:2,model:2,pipe:2"
    assert legs["ddp"]["mesh"] == "data:4,pipe:2"


@pytest.mark.slow
def test_quant_mode_contract():
    """BENCH_MODE=quant: one JSON line carrying the round-17
    low-precision evidence — the off bit-parity pin, per-dtype roundtrip
    bounds, the FLOPs-matched step triplet, the narrow ring-wire ratios,
    the HLO quant tripwire counts and the convergence-tracking pair
    (slow: a subprocess compiling ~8 small models; the committed record
    in bench_records/quant_cpu_r17.jsonl is the tier-1-visible
    evidence)."""
    code, lines, out = run_bench({
        "BENCH_MODE": "quant", "BENCH_CPU_DEVICES": "8",
        "BENCH_BATCH": "1", "BENCH_SEQ": "64", "BENCH_DEPTH": "2",
        "BENCH_WARMUP": "1", "BENCH_STEPS": "2",
        "BENCH_CONV_STEPS": "6",
    }, timeout=900)
    assert code == 0, out[-2000:]
    assert len(lines) == 1, out[-2000:]
    row = lines[0]
    assert REQUIRED <= set(row)
    # the off position may not perturb the shipped numerics
    assert row["parity_off_bitexact"] is True
    for mode in ("int8", "fp8"):
        assert row["roundtrip"][mode]["ok"] is True
    # quantized compute must survive compilation on both geometries
    assert row["hlo_quant_dots_present"] is True
    assert row["degenerate"] is False  # 8 devices carve data:4,model:2
    assert row["hlo_tp_narrow_ppermutes"] >= 1
    assert row["hlo_tp_hoisted_ring_bodies"] >= 1
    assert row["hlo_tp_quant_warnings"] == []
    # the acceptance bar: narrow ring wire <= 0.5x fp32
    assert row["wire_int8_vs_fp32"] <= 0.5
    assert row["wire_fp8_vs_fp32"] <= 0.5
    assert row["vs_baseline"] >= 1.0


def test_quant_record_committed_and_affirmative():
    """The committed BENCH_MODE=quant record must carry the round-17
    acceptance evidence: off bit-parity, roundtrip bounds met, narrow
    wire <= 0.5x fp32 in the ring legs, the quant tripwire green on
    both geometries, and the convergence-tracking pair with both narrow
    modes actually training (loss deviation in the documented band)."""
    path = REPO / "bench_records" / "quant_cpu_r17.jsonl"
    assert path.is_file(), "run BENCH_MODE=quant to record the legs"
    rows = [json.loads(s) for s in path.read_text().splitlines() if s]
    last = rows[-1]
    assert last["metric"].startswith("quant_ring_wire_saving_int8")
    assert last["value"] >= 2.0 and last["vs_baseline"] >= 1.0
    assert last["parity_off_bitexact"] is True
    for mode in ("int8", "fp8"):
        assert last["roundtrip"][mode]["ok"] is True
    assert last["hlo_quant_dots_present"] is True
    assert last["hlo_tp_narrow_ppermutes"] >= 1
    assert last["hlo_tp_hoisted_ring_bodies"] >= 1
    assert last["hlo_tp_quant_warnings"] == []
    assert last["wire_int8_vs_fp32"] <= 0.5
    assert last["wire_fp8_vs_fp32"] <= 0.5
    # convergence-tracking pair (r9 convention): both modes train and
    # track the fp32 curve — the documented tolerance band for the
    # NARROW tracking geometry (BENCH.md round-17)
    assert last["int8_trained"] is True and last["fp8_trained"] is True
    assert last["loss_dev_int8"] < 0.05
    assert last["loss_dev_fp8"] < 0.05
    # the CPU record must say what it cannot prove: no narrow MXU here
    assert last["cpu_no_narrow_mxu"] is True


@pytest.mark.slow
def test_spec_mode_contract():
    """BENCH_MODE=spec emits the headline record FIRST then one
    ablation-marked row per draft depth, all on one invocation, with
    the lossless re-check and the two-program pin carried as fields
    (slow: four serving engines compiled in a subprocess; the committed
    record in bench_records/ is this run's production twin)."""
    code, lines, out = run_bench(
        {"BENCH_MODE": "spec", "BENCH_SPEC_REQUESTS": "8",
         "BENCH_SPEC_DEPTHS": "2"}, timeout=900)
    assert code == 0, out[-2000:]
    assert len(lines) == 2, out[-2000:]  # headline + one depth ablation
    head, abl = lines
    assert REQUIRED <= set(head)
    assert head["metric"] == "serve_spec_accepted_per_target_step"
    assert head["value"] > 1.0
    assert head["spec_lossless_checked"] is True
    assert head["decode_zero_recompile"] is True
    assert head["decode_programs"] == 2
    assert head["draft_programs"] == 1 and head["verify_programs"] == 1
    assert head["spec_flops_per_token_ratio"] > 0
    # the headline row must not carry the literal ablation keys ...
    assert not any(head.get(k) for k in ("spec_k", "draft_depth"))
    # ... and the ablation row MUST (bench_diff skips it as a headline)
    assert abl["draft_depth"] == 2 and abl["spec_k"]
    assert abl["spec_lossless_checked"] is True
