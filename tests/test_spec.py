"""Speculative decoding (r20): draft construction, the adaptive-k
controller, KV truncate/rollback, the k-token batch-verify helper, and
the engine-level acceptance anchors.

The acceptance anchors: speculative greedy decode is token-for-token
identical to the plain engine (mixed-length continuous batches, eos
mid-window, int8 KV, external draft checkpoint), the compile cache
holds exactly TWO decode programs in spec mode (draft + verify — the
plain decode program never traces), and rollback leaves the paged
allocator leak-free (alloc == free at drain).
"""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.linen as nn

from pytorch_ddp_template_tpu.models.gpt import GptDecoder, gpt_tiny
from pytorch_ddp_template_tpu.parallel.stacking import restack_layer_trees
from pytorch_ddp_template_tpu.serve import (
    AdaptiveK, PagedKVCache, ServeConfig, ServeEngine, adopt_draft_checkpoint,
    draft_seq_id, make_draft_params,
)
from pytorch_ddp_template_tpu.serve.kv_cache import NULL_BLOCK
from pytorch_ddp_template_tpu.serve.scheduler import Request

VOCAB = 256

#: mixed-length continuous-batching workload: more requests than decode
#: slots, staggered prompt and output lengths, so admission churns and
#: slots re-fill mid-flight — the regime the lossless pin must hold in
WORKLOAD = [
    ([5, 6, 7], 20),
    ([1, 2, 3, 4, 5, 6, 7, 8], 9),
    ([9, 8, 7, 6], 15),
    ([42], 12),
    ([11, 12, 13, 14, 15, 16], 6),
    ([200, 100, 50], 17),
]


@pytest.fixture(scope="module")
def tiny():
    """(model, unboxed params, fused-head twin) — one init per module."""
    model = gpt_tiny(vocab_size=VOCAB, seq_len=128)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
        train=False)["params"])
    fused = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=2,
                       num_heads=2, head_dim=32, mlp_dim=128,
                       fused_head=True)
    return model, params, fused


def ref_generate(fused, params, prompt, n):
    """The unbatched reference loop: full forward per token, dense
    logits, argmax — what the engine must reproduce token-for-token."""
    toks = list(prompt)
    out = []
    for _ in range(n):
        h = fused.apply({"params": params}, jnp.asarray([toks]),
                        train=False)
        logits = h[0, -1] @ params["wte"]["embedding"].T
        tok = int(jnp.argmax(logits))
        toks.append(tok)
        out.append(tok)
    return out


def make_engine(model, params, **overrides):
    cfg = dict(block_size=4, num_blocks=64, max_slots=3, max_model_len=64)
    cfg.update(overrides)
    return ServeEngine(model, params, ServeConfig(**cfg))


def run_workload(eng, workload=WORKLOAD):
    reqs = [eng.submit(p, max_new_tokens=n) for p, n in workload]
    out = eng.run()
    return [out[r.id] for r in reqs]


# -- draft construction ----------------------------------------------------

class TestDraftParams:
    def test_sliced_draft_shares_by_reference(self, tiny):
        _, params, _ = tiny
        sp = restack_layer_trees(params)
        draft = make_draft_params(sp, 1)
        # zero-copy shares: the SAME arrays, not equal copies
        assert draft["wte"] is sp["wte"]
        assert draft["wpe"] is sp["wpe"]
        assert draft["final_ln"] is sp["final_ln"]
        stack = draft["decoder"]["layers"]
        depth = jax.tree_util.tree_leaves(stack)[0].shape[0]
        assert depth == 1
        full = sp["decoder"]["layers"]
        for d_leaf, f_leaf in zip(jax.tree_util.tree_leaves(stack),
                                  jax.tree_util.tree_leaves(full)):
            assert np.array_equal(np.asarray(d_leaf), np.asarray(f_leaf[:1]))

    @pytest.mark.parametrize("depth", [0, 3, -1])
    def test_depth_out_of_range_refused(self, tiny, depth):
        _, params, _ = tiny
        sp = restack_layer_trees(params)
        with pytest.raises(ValueError, match="out of range"):
            make_draft_params(sp, depth)

    def test_adopt_checkpoint_infers_depth_and_shares_embeddings(self, tiny):
        _, params, _ = tiny
        sp = restack_layer_trees(params)
        shallow = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=1,
                             num_heads=2, head_dim=32, mlp_dim=128)
        raw = shallow.init(jax.random.PRNGKey(3),
                           jnp.zeros((1, 8), jnp.int32),
                           train=False)["params"]
        draft, depth = adopt_draft_checkpoint(raw, sp)
        assert depth == 1
        # embeddings are the TARGET's (shared table == tied head) ...
        assert draft["wte"] is sp["wte"]
        assert draft["wpe"] is sp["wpe"]
        # ... the stack and final LayerNorm are the checkpoint's own
        own = nn.meta.unbox(raw)
        assert np.array_equal(
            np.asarray(draft["final_ln"]["scale"]),
            np.asarray(own["final_ln"]["scale"]))

    def test_adopt_deeper_than_target_refused(self, tiny):
        _, params, _ = tiny
        shallow = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=1,
                             num_heads=2, head_dim=32, mlp_dim=128)
        raw1 = shallow.init(jax.random.PRNGKey(3),
                            jnp.zeros((1, 8), jnp.int32),
                            train=False)["params"]
        target1 = restack_layer_trees(nn.meta.unbox(raw1))
        with pytest.raises(ValueError, match="DEEPER"):
            adopt_draft_checkpoint(params, target1)  # 2 layers into 1

    def test_adopt_width_mismatch_refused(self, tiny):
        _, params, _ = tiny
        sp = restack_layer_trees(params)
        narrow = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=1,
                            num_heads=2, head_dim=16, mlp_dim=64)
        raw = narrow.init(jax.random.PRNGKey(3),
                          jnp.zeros((1, 8), jnp.int32),
                          train=False)["params"]
        with pytest.raises(ValueError, match="embed width"):
            adopt_draft_checkpoint(raw, sp)

    def test_draft_seq_id_never_collides(self):
        ids = [draft_seq_id(r) for r in range(1000)]
        assert all(d < 0 for d in ids)          # request ids are >= 0
        assert len(set(ids)) == len(ids)


# -- the adaptive-k controller (pure bookkeeping) --------------------------

class TestAdaptiveK:
    def req(self):
        return Request(id=0, prompt=[1], max_new_tokens=32)

    def test_starts_at_k_max_then_tracks_evidence(self):
        ctrl = AdaptiveK(4)
        r = self.req()
        assert ctrl.k_for(r) == 4          # optimistic start
        ctrl.update(r, drafted=4, accepted=1)   # rejection at position 2
        assert r.draft_k == 2              # accepted + 1: what the round
        #                                    proved profitable
        ctrl.update(r, drafted=2, accepted=2)   # full accept
        assert r.draft_k == 3              # grow by one
        ctrl.update(r, drafted=3, accepted=3)
        ctrl.update(r, drafted=4, accepted=4)
        assert r.draft_k == 4              # capped at k_max
        ctrl.update(r, drafted=4, accepted=0)
        assert r.draft_k == 1              # total rejection floors at 1

    def test_rolling_accept_rate_ewma(self):
        ctrl = AdaptiveK(4, ema=0.5)
        r = self.req()
        ctrl.update(r, drafted=4, accepted=4)
        assert ctrl.accept_rate == 1.0     # first round seeds the EWMA
        ctrl.update(r, drafted=4, accepted=0)
        assert ctrl.accept_rate == 0.5
        assert r.spec_drafted == 8 and r.spec_accepted == 4

    def test_disabled_controller_pins_k_max(self):
        ctrl = AdaptiveK(3, enabled=False)
        r = self.req()
        assert ctrl.k_for(r) == 3
        ctrl.update(r, drafted=3, accepted=0)
        assert ctrl.k_for(r) == 3          # no shrink when disabled
        assert ctrl.accept_rate == 0.0     # the EWMA still meters

    def test_bad_k_max_refused(self):
        with pytest.raises(ValueError, match="k_max"):
            AdaptiveK(0)


# -- KV rollback: truncate -------------------------------------------------

class TestTruncate:
    def kv(self, **kw):
        base = dict(num_layers=2, num_heads=2, head_dim=8, num_blocks=8,
                    block_size=4)
        base.update(kw)
        return PagedKVCache(**base)

    def test_truncate_pops_blocks_back_to_free_list(self):
        kv = self.kv()
        kv.alloc(1, 10)                    # 3 blocks
        assert kv.truncate(1, 4) == 2      # back to one block
        assert kv.seq_len(1) == 4
        assert kv.free_blocks() == 6
        assert kv.stats()["free_count"] == 2
        blk, off = kv.append_slot(1)       # regrow: the popped block reused
        assert off == 0 and kv.blocks_used() == 2

    def test_truncate_within_block_frees_nothing(self):
        kv = self.kv()
        kv.alloc(1, 6)                     # 2 blocks
        assert kv.truncate(1, 5) == 0      # same block count, shorter len
        assert kv.seq_len(1) == 5
        assert kv.blocks_used() == 2

    def test_truncate_grow_refused(self):
        kv = self.kv()
        kv.alloc(1, 4)
        with pytest.raises(ValueError, match="GROW"):
            kv.truncate(1, 5)

    def test_truncate_unknown_seq_refused(self):
        kv = self.kv()
        with pytest.raises(KeyError):
            kv.truncate(9, 0)


# -- the sampling seam -----------------------------------------------------

class TestSamplingSeam:
    def test_greedy_bitwise_identical_to_greedy_decode(self):
        from pytorch_ddp_template_tpu.ops.lm_head import (
            greedy_decode, sample_tokens,
        )

        hidden = jax.random.normal(jax.random.PRNGKey(0), (5, 64))
        table = jax.random.normal(jax.random.PRNGKey(1), (VOCAB, 64))
        a = np.asarray(greedy_decode(hidden, table, block=100))
        b = np.asarray(sample_tokens(hidden, table, policy="greedy",
                                     block=100))
        assert np.array_equal(a, b)        # the v1 seam is a bitwise no-op

    def test_unknown_policy_refused_named(self):
        from pytorch_ddp_template_tpu.ops.lm_head import sample_tokens

        hidden = jnp.zeros((1, 8))
        table = jnp.zeros((16, 8))
        with pytest.raises(ValueError, match="greedy"):
            sample_tokens(hidden, table, policy="nucleus")

    def test_engine_refuses_unknown_policy_at_init(self, tiny):
        model, params, _ = tiny
        with pytest.raises(ValueError, match="sampling"):
            make_engine(model, params, sampling="top_p")


# -- the k-token batch-verify helper ---------------------------------------

class TestVerifyForward:
    def test_partial_window_matches_sequential_and_scraps_tail(self, tiny):
        """THE satellite unit: a 3-token window inside a 5-lane verify
        call (k not filling the compiled window) must produce, on its
        active lanes, exactly the tokens sequential decode would have —
        and the padded tail lanes must write ONLY null-block scrap."""
        from pytorch_ddp_template_tpu.ops.lm_head import greedy_decode
        from pytorch_ddp_template_tpu.serve.model import verify_forward

        model, params, fused = tiny
        ref = ref_generate(fused, params, [5, 9, 2, 7], 8)

        eng = make_engine(model, params)   # plain engine: target only
        r = eng.submit([5, 9, 2, 7], max_new_tokens=20)
        eng.step()                         # prefill + 1 decode
        eng.step()                         # decode
        assert r.tokens == ref[:3]
        n0 = eng.kv.seq_len(r.id)          # prompt + 2 decoded positions

        k_cap, k_act = 5, 3
        positions = np.zeros((1, k_cap), np.int32)
        ctx = np.zeros((1, k_cap), np.int32)
        wb = np.full((1, k_cap), NULL_BLOCK, np.int32)
        wo = np.zeros((1, k_cap), np.int32)
        tables = np.full((1, k_cap, eng.max_blocks), NULL_BLOCK, np.int32)
        for j in range(k_act):
            positions[0, j] = n0 + j
            ctx[0, j] = n0 + j + 1
            wb[0, j], wo[0, j] = eng.kv.append_slot(r.id)
        tables[0, :k_act] = eng.kv.padded_table(r.id, eng.max_blocks)
        # window [t_last, d_1, d_2] with the TRUE continuation as drafts
        window = np.zeros((1, k_cap), np.int32)
        window[0, :k_act] = [ref[2], ref[3], ref[4]]

        before = {k: np.asarray(v) for k, v in eng.kv.pool.items()}
        hidden, pool = verify_forward(
            eng.params, eng.kv.pool, jnp.asarray(window),
            jnp.asarray(positions), jnp.asarray(tables), jnp.asarray(ctx),
            jnp.asarray(wb), jnp.asarray(wo), dtype=model.dtype)
        assert hidden.shape[:2] == (1, k_cap)
        y = np.asarray(greedy_decode(hidden.reshape(k_cap, -1),
                                     eng.params["wte"]["embedding"]))
        # active lanes reproduce sequential greedy decode exactly
        assert list(y[:k_act]) == ref[3:6]
        # padded tail lanes touched ONLY the null block's scrap space
        owned = set(eng.kv.table(r.id)) | {NULL_BLOCK}
        for key, arr in pool.items():
            changed = np.nonzero(np.any(
                np.asarray(arr) != before[key],
                axis=tuple(range(2, arr.ndim)) + (0,)))[0]
            assert set(changed.tolist()) <= owned, key


# -- the engine: lossless, compile pin, rollback ---------------------------

def spec_engine(model, params, **overrides):
    base = dict(spec_k=4, draft_depth=1)
    base.update(overrides)
    return make_engine(model, params, **base)


class TestSpecEngine:
    @pytest.mark.parametrize("spec_cfg", [
        dict(spec_k=4, draft_depth=1),
        dict(spec_k=4, draft_depth=2),   # full-depth draft: the m==k
        #                                  always-accept degenerate path
        dict(spec_k=1, draft_depth=1),   # minimal window
        dict(spec_k=3, draft_depth=1, spec_adaptive=False),
    ], ids=["k4d1", "k4d2-full-accept", "k1d1", "k3d1-fixed"])
    def test_lossless_mixed_length_continuous(self, tiny, spec_cfg):
        """THE acceptance anchor: speculative greedy output is
        token-for-token identical to the plain engine across a
        mixed-length continuously-batched workload."""
        model, params, fused = tiny
        base = run_workload(make_engine(model, params))
        spec = run_workload(spec_engine(model, params, **spec_cfg))
        assert spec == base
        # and the plain engine itself anchors to the unbatched reference
        assert base[0] == ref_generate(fused, params, WORKLOAD[0][0],
                                       WORKLOAD[0][1])

    def test_full_depth_draft_always_accepts(self, tiny):
        model, params, _ = tiny
        eng = spec_engine(model, params, draft_depth=2)
        run_workload(eng)
        st = eng.stats()
        assert st["serve_spec_accept_rate"] == 1.0
        assert st["serve_spec_draft_depth"] == 2

    def test_two_compiled_decode_programs_pin(self, tiny):
        """The compile-count contract: draft + verify are the ONLY
        decode programs, however sequences grow or k adapts — and a
        second batch of different lengths adds none."""
        model, params, _ = tiny
        eng = spec_engine(model, params)
        eng.submit([1, 2, 3], max_new_tokens=20)
        eng.submit([4, 5, 6, 7, 8], max_new_tokens=17)
        eng.run()
        assert eng.decode_programs() == 2
        eng.submit([9] * 11, max_new_tokens=9)
        eng.run()
        assert eng.decode_programs() == 2
        # the plain decode program never traced in spec mode
        assert eng._decode_fn._cache_size() == 0
        assert eng._spec._draft_decode_fn._cache_size() == 1
        assert eng._spec._verify_fn._cache_size() == 1

    def test_rollback_leak_free_at_drain(self, tiny):
        """Every rejected draft tail rolls back through the free list:
        at drain the allocator holds nothing and lifetime alloc equals
        lifetime free — target AND draft lanes."""
        model, params, _ = tiny
        eng = spec_engine(model, params)
        run_workload(eng)
        st = eng.kv.stats()
        assert st["blocks_used"] == 0
        assert st["tokens_resident"] == 0
        assert st["alloc_count"] == st["free_count"]
        assert st["alloc_count"] > 0
        assert eng._committed == {}
        assert eng.scheduler.idle()

    def test_eos_mid_window_matches_baseline(self, tiny):
        """A verify round that commits past the eos must discard the
        tail — exactly the tokens the baseline never emits."""
        model, params, fused = tiny
        ref = ref_generate(fused, params, [5, 6, 7], 8)
        eos = ref[2]
        base = make_engine(model, params, eos_id=eos)
        rb = base.submit([5, 6, 7], max_new_tokens=8)
        spec = spec_engine(model, params, eos_id=eos)
        rs = spec.submit([5, 6, 7], max_new_tokens=8)
        assert spec.run()[rs.id] == base.run()[rb.id] == ref[:3]

    def test_int8_kv_spec_lossless_vs_int8_plain(self, tiny):
        """Spec mode composes with the r17 int8 KV pool: quantized
        gather-KV greedy decode with and without speculation agree."""
        model, params, _ = tiny
        base = run_workload(make_engine(model, params, kv_quant="int8"),
                            WORKLOAD[:4])
        spec = run_workload(spec_engine(model, params, kv_quant="int8"),
                            WORKLOAD[:4])
        assert spec == base

    def test_admission_reserves_draft_lanes(self, tiny):
        """Spec admission doubles the worst-case block commit: with a
        pool sized for two doubled requests, the third queues instead
        of admitting into an OOM — and everything still finishes."""
        model, params, _ = tiny
        # budget 14 usable; plen 4 + max_new 8 -> 3 blocks -> 6 doubled
        eng = spec_engine(model, params, num_blocks=15)
        reqs = [eng.submit([7, 7, 7, 7], max_new_tokens=8)
                for _ in range(3)]
        eng.step()
        assert eng.scheduler.active() == 2       # third held back
        out = eng.run()
        assert all(len(out[r.id]) == 8 for r in reqs)
        assert eng.kv.stats()["blocks_used"] == 0

    def test_unadmittable_request_refused_with_spec_hint(self, tiny):
        model, params, _ = tiny
        eng = spec_engine(model, params, num_blocks=9)
        with pytest.raises(ValueError, match="doubles the reservation"):
            eng.submit([1, 2, 3, 4], max_new_tokens=16)  # 5 blocks * 2 > 8

    def test_draft_params_without_spec_k_refused(self, tiny):
        model, params, _ = tiny
        sp = restack_layer_trees(params)
        with pytest.raises(ValueError, match="spec_k"):
            ServeEngine(model, params,
                        ServeConfig(block_size=4, num_blocks=64,
                                    max_slots=3, max_model_len=64),
                        draft_params=make_draft_params(sp, 1))

    def test_spec_stats_fields_affirmative(self, tiny):
        model, params, _ = tiny
        eng = spec_engine(model, params)
        run_workload(eng)
        st = eng.stats()
        assert st["serve_spec_k_max"] == 4
        assert st["serve_spec_draft_depth"] == 1
        assert 0.0 <= st["serve_spec_accept_rate"] <= 1.0
        assert 0.0 <= st["serve_spec_accept_rate_rolling"] <= 1.0
        # the wager pays: > 1 committed token per target verify step
        assert st["serve_spec_accepted_per_target_step"] > 1.0
        # every token past each request's prefill-emitted first token
        # came through a verify round
        assert st["serve_spec_committed_total"] == sum(
            n for _, n in WORKLOAD) - len(WORKLOAD)
        assert (st["serve_spec_accepted_total"]
                <= st["serve_spec_drafted_total"])
        assert st["serve_spec_draft_s_total"] > 0
        assert st["serve_spec_verify_s_total"] > 0
        assert st["serve_spec_verify_steps"] <= st["serve_spec_draft_steps"]


# -- the draft-checkpoint workflow -----------------------------------------

class TestDraftCheckpointSeam:
    def save_ckpt(self, tmp_path, name, params):
        from pytorch_ddp_template_tpu.checkpoint.manager import (
            CheckpointManager,
        )
        from pytorch_ddp_template_tpu.config import TrainingConfig

        state = {"step": jnp.int32(7), "params": params,
                 "rng": jax.random.PRNGKey(1)}
        cfg = TrainingConfig(model="gpt-tiny",
                             output_dir=str(tmp_path / f"{name}_out"))
        mngr = CheckpointManager(tmp_path / name)
        mngr.save(7, state, cfg, force=True)
        mngr.wait()
        mngr.close()
        return tmp_path / name

    def test_from_checkpoint_with_draft_dir_is_lossless(self, tiny,
                                                        tmp_path):
        """The --num_layers workflow end-to-end: an independently
        initialised 1-layer checkpoint adopts as the draft through
        from_checkpoint(draft_dir=...), and the output is STILL
        token-for-token the plain engine's — draft weights only ever
        move the acceptance rate."""
        model, params, _ = tiny
        target_dir = self.save_ckpt(tmp_path, "target", params)
        shallow = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=1,
                             num_heads=2, head_dim=32, mlp_dim=128)
        raw = nn.meta.unbox(shallow.init(
            jax.random.PRNGKey(9), jnp.zeros((1, 8), jnp.int32),
            train=False)["params"])
        draft_dir = self.save_ckpt(tmp_path, "draft", raw)

        eng = ServeEngine.from_checkpoint(
            target_dir, model,
            ServeConfig(block_size=4, num_blocks=64, max_slots=3,
                        max_model_len=64, spec_k=3),
            draft_dir=draft_dir)
        assert eng._spec is not None and eng._spec.depth == 1
        base = run_workload(make_engine(model, params), WORKLOAD[:4])
        spec = run_workload(eng, WORKLOAD[:4])
        assert spec == base
        assert eng.stats()["serve_spec_draft_depth"] == 1

    def test_draft_depth_conflicting_with_checkpoint_refused(self, tiny,
                                                             tmp_path):
        model, params, _ = tiny
        shallow = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=1,
                             num_heads=2, head_dim=32, mlp_dim=128)
        raw = nn.meta.unbox(shallow.init(
            jax.random.PRNGKey(9), jnp.zeros((1, 8), jnp.int32),
            train=False)["params"])
        with pytest.raises(ValueError, match="inferred"):
            ServeEngine(model, params,
                        ServeConfig(block_size=4, num_blocks=64,
                                    max_slots=3, max_model_len=64,
                                    spec_k=3, draft_depth=2),
                        draft_params=raw)


# -- the --num_layers training knob ----------------------------------------

class TestNumLayersKnob:
    def test_build_overrides_depth(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models.registry import build

        cfg = TrainingConfig(model="gpt-tiny", output_dir="/tmp/nl",
                             num_layers=1)
        task, _ = build("gpt-tiny", cfg)
        assert task.model.num_layers == 1

    def test_depthless_model_refused_named(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models.registry import build

        cfg = TrainingConfig(model="mlp", output_dir="/tmp/nl",
                             num_layers=1)
        with pytest.raises(ValueError, match="num_layers"):
            build("mlp", cfg)

    def test_negative_refused(self):
        from pytorch_ddp_template_tpu.config import TrainingConfig

        with pytest.raises(ValueError, match="num_layers"):
            TrainingConfig(model="gpt-tiny", output_dir="/tmp/nl",
                           num_layers=-1)


# -- obs wiring ------------------------------------------------------------

class TestSpecObs:
    def test_metrics_gauges_live(self, tiny):
        from pytorch_ddp_template_tpu.obs.server import StatusServer

        model, params, _ = tiny
        status = StatusServer(0)
        status.start()
        try:
            eng = ServeEngine(
                model, params,
                ServeConfig(block_size=4, num_blocks=64, max_slots=2,
                            max_model_len=64, spec_k=3, draft_depth=1),
                status=status)
            eng.submit([1, 2, 3, 4], max_new_tokens=8)
            eng.run()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status.port}/metrics",
                    timeout=10) as resp:
                text = resp.read().decode()
            assert "tpuddp_serve_spec_accept_rate" in text
            assert "tpuddp_serve_spec_accepted_per_target_step" in text
            assert "tpuddp_serve_spec_draft_depth" in text
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status.port}/status",
                    timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["serve"]["config"]["spec_k"] == 3
        finally:
            status.close()

    def test_goodput_serve_draft_bucket(self, tiny, tmp_path):
        from pytorch_ddp_template_tpu.obs.goodput import (
            BUCKETS, GoodputLedger,
        )

        assert "serve_draft" in BUCKETS
        model, params, _ = tiny
        ledger = GoodputLedger(tmp_path)
        eng = ServeEngine(
            model, params,
            ServeConfig(block_size=4, num_blocks=64, max_slots=2,
                        max_model_len=64, spec_k=3, draft_depth=1),
            goodput=ledger)
        eng.submit([1, 2, 3], max_new_tokens=8)
        eng.run()
        tot = ledger.totals()
        assert tot["serve_draft"] > 0.0
        assert tot["serve_decode"] > 0.0    # verify wall stays in decode


# -- the committed BENCH_MODE=spec record ----------------------------------

def test_spec_record_committed_and_affirmative():
    """The committed round-20 record must carry the acceptance
    evidence: accepted tokens per target step > 1 with the draft's
    FLOPs accounted, the two-program compile pin for BOTH spec
    programs, losslessness re-checked inside the bench, and the
    live-gauges proof."""
    import pathlib

    path = (pathlib.Path(__file__).resolve().parents[1]
            / "bench_records" / "spec_cpu_r20.jsonl")
    assert path.is_file(), "run BENCH_MODE=spec to record the legs"
    rows = [json.loads(s) for s in path.read_text().splitlines() if s]
    head = rows[0]
    assert head["metric"] == "serve_spec_accepted_per_target_step"
    assert head["value"] > 1.0 and head["vs_baseline"] >= 1.0
    # the FLOPs wager stated, not hidden: the draft+verify path's
    # useful-FLOPs-per-emitted-token ratio vs plain decode
    assert head["spec_flops_per_token_ratio"] > 0
    assert head["accepted_per_target_step_flops_adj"] > 1.0
    assert 0.0 < head["accept_rate"] <= 1.0
    assert head["decode_zero_recompile"] is True
    assert head["decode_programs"] == 2
    assert head["draft_programs"] == 1 and head["verify_programs"] == 1
    assert head["spec_lossless_checked"] is True
    assert head["metrics_gauges_live"] is True
    assert head["goodput_serve_draft_s"] > 0
    # the headline is the honest config: not an ablation row
    assert not head.get("draft_depth") and not head.get("spec_k")
    assert head["spec_k_max"] >= 1 and head["spec_draft_depth"] >= 1
    # the depth ablation rows: marked as ablations, spanning depths
    abl = [r for r in rows if r.get("draft_depth")]
    assert len(abl) >= 2, "draft_depth ablation rows missing"
    depths = {r["draft_depth"] for r in abl}
    assert len(depths) >= 2
