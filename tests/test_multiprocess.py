"""Two-process distributed rehearsal (VERDICT.md round-3 missing #3).

The reference's *primary* mode is multi-process (``torch.distributed.launch``
spawning ranks, ``/root/reference/ddp.py:103``); everything else in this
suite runs ``jax.process_count() == 1``. Here two real processes (4 virtual
CPU devices each) rendezvous through ``jax.distributed.initialize`` and run
the full stack: sharded loading, SPMD train steps over the cross-process
mesh, divergence detection of an injected param flip, and an orbax
multi-host checkpoint round-trip. See ``two_process_worker.py`` for what
each worker runs.
"""

import json
import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

WORKER = Path(__file__).resolve().parent / "two_process_worker.py"
PREEMPT_WORKER = Path(__file__).resolve().parent / "two_process_preempt_worker.py"
REPO = WORKER.parent.parent

# jaxlib builds without cross-process CPU collectives kill the worker at
# jax.distributed init with this wording — an environment limitation, not
# a regression: skip (with the backend named) so tier-1 output tells the
# two apart instead of reporting a fail
_BACKEND_LIMIT = re.compile(
    r"[Mm]ultiprocess computations aren'?t implemented on the "
    r"(\w+) backend"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pair(worker: Path, tmp_path, timeout: int = 300) -> list[str]:
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = str(REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), coord, str(tmp_path)],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for p, out in zip(procs, outs):
        if p.returncode != 0:
            m = _BACKEND_LIMIT.search(out)
            if m is not None:
                pytest.skip(
                    "this jaxlib has no multiprocess computations on the "
                    f"{m.group(1)} backend (jax.distributed init refused) — "
                    "environmental, not a regression; the two-process "
                    "rehearsal needs a backend with cross-process collectives"
                )
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    return outs


def test_two_process_rehearsal(tmp_path):
    _run_pair(WORKER, tmp_path)

    results = {}
    for i in range(2):
        path = tmp_path / f"result_{i}.json"
        assert path.is_file(), f"worker {i} wrote no result"
        results[i] = json.loads(path.read_text())

    for r in results.values():
        # the distributed context was real, not degenerate
        assert r["process_count"] == 2
        assert r["local_devices"] == 4
        assert r["global_devices"] == 8
        assert np.isfinite(r["loss"])
        # replicated state agreed; the injected flip was caught
        assert r["divergence_clean"] is True
        assert r["divergence_flagged"] is True
        # FSDP: weights really lived sharded across the two processes
        assert r["fsdp_param_sharded"] is True
        # orbax round-trip restored bit-identical params at the right step
        # (with FSDP on, those are genuinely distributed arrays)
        assert r["ckpt_roundtrip"] is True
        assert r["ckpt_step"] == 2

    # SPMD: both processes computed the identical replicated loss
    assert results[0]["loss"] == results[1]["loss"]

    # DistributedSampler semantics across real processes: disjoint shards
    # covering the dataset (100 examples, batch 16: 96 drawn, no overlap)
    a = set(results[0]["loader_indices"])
    b = set(results[1]["loader_indices"])
    assert len(results[0]["loader_indices"]) == len(a) == 48
    assert len(results[1]["loader_indices"]) == len(b) == 48
    assert not a & b
    assert a | b <= set(range(100))


def test_two_process_preemption_agreement(tmp_path):
    """SIGTERM lands on only ONE process; the device-side agreement (stop
    votes reduced inside the jitted step, read through the bounded
    dispatch-depth barrier — no blocking allgather cadence) must stop both
    at the SAME step and write one coherent cross-process checkpoint — a
    host acting on its local flag alone would strand its peer in
    collective train steps (ADVICE.md round-4 medium finding)."""
    _run_pair(PREEMPT_WORKER, tmp_path)

    results = {}
    for i in range(2):
        path = tmp_path / f"preempt_result_{i}.json"
        assert path.is_file(), f"worker {i} wrote no result"
        results[i] = json.loads(path.read_text())

    s0, s1 = results[0]["stop_step"], results[1]["stop_step"]
    # the whole point: both processes broke out at the same global step,
    # even though only one of them ever received the signal
    assert s0 == s1
    # stop happened via the agreement path, not at the unreachable
    # max_steps (device-side agreement lands within max_inflight_steps of
    # the vote — no sync-cadence rounding exists anymore)
    assert 0 < s0 < 100_000
    # the preemption checkpoint is the agreed step on both processes
    assert results[0]["latest_ckpt"] == results[1]["latest_ckpt"] == s0
