"""Worker for the cross-process preemption-agreement rehearsal.

Launched (twice) by ``test_multiprocess.py``. Cluster schedulers deliver
SIGTERM to *every* host, at arbitrary skew — and a host that acts on its
local flag alone breaks out of the loop at its own global_step, leaving
its peer stuck in collective train steps against nobody (the reference's
pre-elastic launcher simply dies, SURVEY.md §5.3). Here only process 0 is
signalled; the *device-side* agreement (per-process stop votes reduced
inside the jitted step, ``train/engine.py::make_stop_flags`` — no host
allgather cadence exists anymore) must spread the vote and stop BOTH
processes at the same global step, landing one coherent cross-process
checkpoint.

Writes ``preempt_result_<proc>.json``; exit code 0 iff training exited
cleanly through the preemption path.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
from pathlib import Path


def main() -> int:
    proc_id, coord, workdir = int(sys.argv[1]), sys.argv[2], Path(sys.argv[3])
    repo = Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo))

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init, shutdown
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        cpu=True,
        coordinator_address=coord,
        num_processes=2,
        process_id=proc_id,
        mesh="data:8",
        per_device_train_batch_size=2,
        dataset_size=512,
        output_dir=str(workdir / "ckpt"),
        warmup_steps=0,
        max_steps=100_000,  # unreachable: only SIGTERM ends this run
        logging_steps=4,
        save_steps=0,
        max_inflight_steps=2,  # stop must land within 2 steps of the vote
        model="mlp",
    )
    ctx = init(cfg)
    task, ds = build("mlp", cfg)
    trainer = Trainer(cfg, ctx, task, ds)

    if proc_id == 0:
        # the "scheduler" preempts only this host; agreement must spread
        # it. Fire only once the first metrics line proves the train loop
        # (and thus the SIGTERM handler) is live — a fixed delay races
        # handler registration and would kill the process outright. The
        # file itself is created (empty) at Trainer construction, so wait
        # for content, not existence.
        metrics_path = workdir / "ckpt" / "metrics.jsonl"

        def _preempt_when_training() -> None:
            import time

            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                if metrics_path.exists() and metrics_path.stat().st_size > 0:
                    os.kill(os.getpid(), signal.SIGTERM)
                    return
                time.sleep(0.2)

        t = threading.Thread(target=_preempt_when_training, daemon=True)
        t.start()

    state = trainer.train()
    result = {
        "proc": proc_id,
        "stop_step": int(state.step),
        "latest_ckpt": trainer.ckpt.latest_step(),
    }
    (workdir / f"preempt_result_{proc_id}.json").write_text(json.dumps(result))
    shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
