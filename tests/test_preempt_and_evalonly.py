"""Graceful preemption (SIGTERM → checkpoint → clean exit → auto-resume)
and the --eval_only CLI mode. The reference's pre-elastic launcher dies on
any signal with nothing resumable (SURVEY.md §5.3), and its checkpoints
have no load path at all (``/root/reference/ddp.py:293`` vs ``:206``)."""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import ddp
from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init
from pytorch_ddp_template_tpu.train import Trainer


def _args(out, extra=()):
    return [
        "--model", "mlp", "--mesh", "data:8",
        "--per_device_train_batch_size", "8", "--dataset_size", "256",
        "--save_steps", "0", "--logging_steps", "0", "--seed", "5",
        "--output_dir", str(out), *extra,
    ]


class TestSigtermGracefulStop:
    def test_sigterm_checkpoints_and_resumes(self, tmp_path):
        cfg = TrainingConfig(
            model="mlp", mesh="data:8", per_device_train_batch_size=8,
            dataset_size=256, max_steps=200_000, save_steps=0,
            logging_steps=0, seed=5, output_dir=str(tmp_path / "o"),
        )
        ctx = init(cfg)
        task, ds = build(cfg.model, cfg)
        t = Trainer(cfg, ctx, task, ds)

        # deliver SIGTERM only once train() has installed its handler
        # (getsignal is thread-safe; an early signal under SIG_DFL would
        # kill pytest outright) — the 200k-step budget then guarantees the
        # stop came from the signal, not completion
        before = signal.getsignal(signal.SIGTERM)

        def fire_when_armed():
            deadline = time.time() + 120
            while (time.time() < deadline
                   and signal.getsignal(signal.SIGTERM) == before):
                time.sleep(0.05)
            time.sleep(0.3)  # let a few steps run under the new handler
            os.kill(os.getpid(), signal.SIGTERM)

        shooter = threading.Thread(target=fire_when_armed, daemon=True)
        shooter.start()
        state = t.train()  # must RETURN (graceful), not die
        stopped_at = int(state.step)
        assert 0 < stopped_at < 200_000  # stopped early, after real steps
        assert t.ckpt.latest_step() == stopped_at  # checkpoint landed

        # the next run resumes exactly where the signal stopped this one
        t2 = Trainer(cfg, ctx, task, ds)
        _, start = t2.restore_or_init()
        assert start == stopped_at

    def test_handler_restored_after_train(self, tmp_path):
        before = signal.getsignal(signal.SIGTERM)
        cfg = TrainingConfig(
            model="mlp", mesh="data:8", per_device_train_batch_size=8,
            dataset_size=64, max_steps=2, save_steps=0, logging_steps=0,
            output_dir=str(tmp_path / "o"),
        )
        ctx = init(cfg)
        task, ds = build(cfg.model, cfg)
        Trainer(cfg, ctx, task, ds).train()
        assert signal.getsignal(signal.SIGTERM) == before


class TestKitchenSink:
    @pytest.mark.slow  # two full CLI subprocesses (~41s): moved to the
    #                    slow set in r10 to keep the grown suite inside
    #                    the 870s budget (the r8/r9 convention)
    def test_all_round4_flags_compose(self, tmp_path):
        """--fsdp + --remat + --fused_head + --optimizer lamb + eval +
        resume, on a data x model mesh, through the real CLI: the flags
        must compose, checkpoint, genuinely resume, and run eval."""
        import pathlib

        out = str(tmp_path / "o")
        args = ["--model", "gpt-tiny", "--mesh", "data:4,model:2",
                "--fsdp", "--remat", "--fused_head",
                "--optimizer", "lamb", "--learning_rate", "3e-3",
                "--weight_decay", "0.01",
                "--per_device_train_batch_size", "1", "--dataset_size", "64",
                "--eval_steps", "4", "--logging_steps", "0",
                "--save_steps", "4", "--output_dir", out]
        assert ddp.main(args + ["--max_steps", "4"]) == 0
        assert ddp.main(args + ["--max_steps", "8"]) == 0
        ckpts = sorted(p.name for p in pathlib.Path(out).glob("checkpoint_*"))
        assert "checkpoint_4" in ckpts and "checkpoint_8" in ckpts
        # eval really ran under this composition, and metrics.jsonl
        # (append-mode across runs) holds exactly ONE step-4 eval line —
        # a restart-from-0 instead of a resume would have logged it twice
        evals = [line for line in
                 (pathlib.Path(out) / "metrics.jsonl").read_text().splitlines()
                 if '"eval_loss"' in line]
        assert sum('"step": 4,' in line for line in evals) == 1, evals
        assert sum('"step": 8,' in line for line in evals) == 1, evals

    @pytest.mark.slow  # ~20s two-run CLI composition — moved to the slow
    #                    set in r11 to keep the grown tier-1 suite inside
    #                    the 870s budget (the r8–r10 convention; the full
    #                    `pytest tests/` run still covers it)
    def test_pipeline_flags_compose(self, tmp_path):
        """gpt-pipe-tiny + accumulation + eval + resume on a data x pipe
        mesh through the real CLI: the round-5 pipeline entry composes
        with the engine's accum scan, exactly-once eval, and checkpoint
        resume."""
        import pathlib

        out = str(tmp_path / "p")
        args = ["--model", "gpt-pipe-tiny", "--mesh", "data:4,pipe:2",
                "--gradient_accumulation_steps", "2",
                "--pipe_microbatches", "2",
                "--per_device_train_batch_size", "2", "--dataset_size", "128",
                "--eval_steps", "2", "--logging_steps", "0",
                "--save_steps", "2", "--output_dir", out]
        assert ddp.main(args + ["--max_steps", "2"]) == 0
        assert ddp.main(args + ["--max_steps", "4"]) == 0
        ckpts = sorted(p.name for p in pathlib.Path(out).glob("checkpoint_*"))
        assert "checkpoint_2" in ckpts and "checkpoint_4" in ckpts
        evals = [line for line in
                 (pathlib.Path(out) / "metrics.jsonl").read_text().splitlines()
                 if '"eval_loss"' in line]
        assert sum('"step": 2,' in line for line in evals) == 1, evals
        assert sum('"step": 4,' in line for line in evals) == 1, evals


class TestEvalOnly:
    def test_eval_only_without_checkpoint_fails_with_intent(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="eval_only"):
            ddp.main(_args(tmp_path / "fresh",
                           ["--eval_only", "--max_steps", "4"]))

    @pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
    def test_eval_only_tail_holdout_leak_rejected(self, tmp_path):
        """A training run that used the WHOLE file store (eval_steps=0)
        must not later have its tail rows presented as held-out."""
        from pytorch_ddp_template_tpu.data.filestore import write_store

        rng = np.random.default_rng(0)
        store = write_store(tmp_path / "store", {
            "image": rng.integers(0, 255, (512, 32, 32, 3)).astype("uint8"),
            "label": rng.integers(0, 10, (512,)).astype("int32"),
        })
        out = tmp_path / "run"
        args = ["--model", "resnet18", "--mesh", "data:8",
                "--data_dir", str(store),
                "--per_device_train_batch_size", "4", "--max_steps", "2",
                "--save_steps", "0", "--logging_steps", "0",
                "--output_dir", str(out)]
        assert ddp.main(args) == 0
        with pytest.raises(ValueError, match="held nothing out"):
            ddp.main(args + ["--eval_only"])

        # a run that DID hold the tail out (eval_steps>0) evaluates fine —
        # but not at a different global batch (the split point would move)
        out2 = tmp_path / "run2"
        args2 = [a if a != str(out) else str(out2) for a in args]
        args2 += ["--eval_steps", "2"]
        assert ddp.main(args2) == 0
        assert ddp.main(args2 + ["--eval_only"]) == 0
        assert (out2 / "eval_2.json").is_file()
        bad = list(args2)
        bad[bad.index("--per_device_train_batch_size") + 1] = "8"
        with pytest.raises(ValueError, match="split point would move"):
            ddp.main(bad + ["--eval_only"])

    def test_eval_only_reports_on_saved_checkpoint(self, tmp_path):
        out = tmp_path / "run"
        assert ddp.main(_args(out, ["--max_steps", "6"])) == 0
        assert ddp.main(_args(out, ["--eval_only"])) == 0
        report = json.loads((out / "eval_6.json").read_text())
        assert report["step"] == 6
        eval_keys = [k for k in report if k.startswith("eval_")]
        assert eval_keys, report
        assert all(np.isfinite(report[k]) for k in eval_keys)
