"""Low-precision compute path (``--quant_compute``, ops/quant.py + the
quantized ring kernels in parallel/collective_matmul.py): the quantizers
must be bounded per channel (all-zero channels exactly zero), the scaled
narrow dots must be algebraically exact given the quantized operands, the
Pallas fused kernel must match the XLA lowering, quant_dense must agree
with the plain dense within the documented per-dtype bounds in value AND
grads, the block/ring integrations must keep the param tree
bit-interchangeable with the default path (off == default bitwise), the
refusal matrix must fail with intent, and the evidence stack (describe()
block, per-dtype peak rows, the --hlo_report quant tripwire) must report
what actually compiled."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.ops.quant import (
    FP8_BWD_DTYPE,
    FP8_FWD_DTYPE,
    QUANT_COMPUTE_MODES,
    dequantize,
    quant_dense,
    quant_dot,
    quant_matmul_pallas,
    quantize_channel,
    roundtrip_rel_error_bound,
)
from pytorch_ddp_template_tpu.runtime import make_mesh

TOL_REL = {"int8": 0.05, "fp8": 0.25}  # loose per-dtype parity bands


def _rand(shape, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32) * scale)


def _rel(a, b):
    denom = float(jnp.max(jnp.abs(b))) + 1e-9
    return float(jnp.max(jnp.abs(a - b))) / denom


# -- quantizer units -------------------------------------------------------

class TestQuantizeChannel:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_roundtrip_bounded_per_channel(self, mode):
        x = _rand((8, 64), 1, 3.0)
        q, s = quantize_channel(x, mode, axes=-1)
        err = jnp.max(jnp.abs(dequantize(q, s) - x), axis=-1)
        amax = jnp.max(jnp.abs(x), axis=-1)
        bound = roundtrip_rel_error_bound(mode)
        assert float(jnp.max(err / amax)) <= bound + 1e-7

    def test_all_zero_channels_stay_exact_zero(self):
        # mixed rows: zero channels must dequantize to exact zeros even
        # next to live ones (scale pinned to 1.0, never 0/0)
        x = jnp.concatenate([jnp.zeros((2, 32)), _rand((2, 32), 2)], axis=0)
        for mode in ("int8", "fp8"):
            q, s = quantize_channel(x, mode, axes=-1)
            back = dequantize(q, s)
            assert float(jnp.max(jnp.abs(back[:2]))) == 0.0
            assert float(jnp.max(jnp.abs(back[2:]))) > 0.0

    def test_single_element_channels(self):
        # one element per channel: absmax == the value, so int8 encodes
        # +-127 exactly and the roundtrip is (near-)exact
        x = _rand((16, 1), 3)
        q, s = quantize_channel(x, "int8", axes=-1)
        np.testing.assert_allclose(np.asarray(dequantize(q, s)),
                                   np.asarray(x), rtol=1e-6)

    def test_stochastic_rounding_unbiased(self):
        x = _rand((64,), 4)
        keys = jax.random.split(jax.random.PRNGKey(0), 256)
        draws = jax.vmap(lambda k: dequantize(
            *quantize_channel(x, "int8", axes=-1, key=k)))(keys)
        quantum = float(jnp.max(jnp.abs(x))) / 127.0
        bias = np.max(np.abs(np.asarray(jnp.mean(draws, 0)) - np.asarray(x)))
        assert bias < 4.0 * 0.5 * quantum / np.sqrt(256) + 1e-7

    def test_fp8_dtypes_and_grad_mode(self):
        x = _rand((4, 8), 5)
        q, _ = quantize_channel(x, "fp8", axes=-1)
        assert q.dtype == FP8_FWD_DTYPE
        qg, _ = quantize_channel(x, "fp8", axes=-1, grad=True)
        assert qg.dtype == FP8_BWD_DTYPE

    def test_unknown_mode_refused(self):
        with pytest.raises(ValueError, match="unknown mode"):
            quantize_channel(jnp.zeros((4, 4)), "int4")
        with pytest.raises(ValueError, match="unknown mode"):
            quantize_channel(jnp.zeros((4, 4)), "off")


def test_quant_dot_exact_given_quantized_operands():
    """The scaled dot is algebraically exact: quant_dot must equal
    dequantize-then-matmul to float tolerance (the only error in the
    path is the operand rounding, never the scale algebra)."""
    a = _rand((8, 32), 6)
    w = _rand((32, 16), 7)
    for mode in ("int8", "fp8"):
        aq, as_ = quantize_channel(a, mode, axes=-1)
        wq, ws = quantize_channel(w, mode, axes=0)
        got = quant_dot(aq, as_, wq, ws.reshape(1, -1))
        want = dequantize(aq, as_) @ dequantize(wq, ws)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_pallas_kernel_matches_xla_lowering():
    a = _rand((16, 64), 8)
    w = _rand((64, 32), 9)
    for mode in ("int8", "fp8"):
        aq, as_ = quantize_channel(a, mode, axes=-1)
        wq, ws = quantize_channel(w, mode, axes=0)
        ws2 = ws.reshape(1, -1)
        xla = quant_dot(aq, as_, wq, ws2)
        fused = quant_matmul_pallas(aq, as_, wq, ws2, interpret=True)
        # int8 accumulates in int32 in both lowerings: bit-equal; fp8
        # accumulation order may differ at the last f32 ulp
        np.testing.assert_allclose(np.asarray(fused), np.asarray(xla),
                                   rtol=1e-6, atol=1e-6)


def test_quant_impl_env(monkeypatch):
    from pytorch_ddp_template_tpu.ops import quant as Q

    monkeypatch.setenv("QUANT_IMPL", "nope")
    with pytest.raises(ValueError, match="QUANT_IMPL"):
        Q.quant_impl()
    monkeypatch.setenv("QUANT_IMPL", "pallas")
    assert Q.quant_impl() == "pallas"
    monkeypatch.delenv("QUANT_IMPL")
    assert Q.quant_impl() == "xla"


class TestQuantDense:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_value_and_grads_near_plain(self, mode):
        x = _rand((4, 8, 32), 10)
        k = _rand((32, 4, 8), 11)
        b = _rand((4, 8), 12, 0.1)

        def plain(x, k, b):
            return jnp.einsum("bte,ehd->bthd", x, k) + b

        def q(x, k, b):
            return quant_dense(x, k, b, 1, mode)

        y, yr = q(x, k, b), plain(x, k, b)
        assert _rel(y, yr) < TOL_REL[mode]
        g = jax.grad(lambda *a: jnp.sum(q(*a) ** 2), argnums=(0, 1, 2))(
            x, k, b)
        gr = jax.grad(lambda *a: jnp.sum(plain(*a) ** 2),
                      argnums=(0, 1, 2))(x, k, b)
        for a_, r_ in zip(g, gr):
            assert _rel(a_, r_) < 2 * TOL_REL[mode]

    def test_two_axis_contraction(self):
        # the out-projection shape: (B,T,H,D) x (H,D,E)
        x = _rand((2, 4, 2, 8), 13)
        k = _rand((2, 8, 16), 14)
        y = quant_dense(x, k, jnp.zeros(16), 2, "int8")
        yr = jnp.einsum("bthd,hde->bte", x, k)
        assert _rel(y, yr) < TOL_REL["int8"]

    def test_pallas_impl_through_quant_dense(self, monkeypatch):
        monkeypatch.setenv("QUANT_IMPL", "pallas")
        jax.clear_caches()
        x, k, b = _rand((8, 32), 15), _rand((32, 16), 16), jnp.zeros(16)
        y = quant_dense(x, k, b, 1, "int8")
        monkeypatch.setenv("QUANT_IMPL", "xla")
        jax.clear_caches()
        y2 = quant_dense(x, k, b, 1, "int8")
        np.testing.assert_allclose(np.asarray(y), np.asarray(y2),
                                   rtol=1e-6, atol=1e-6)
        jax.clear_caches()


# -- ring kernels ----------------------------------------------------------

class TestQuantRingKernels:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_column_parity_and_grads(self, devices, mode):
        from pytorch_ddp_template_tpu.parallel.collective_matmul import (
            tp_column_dense,
        )

        mesh = make_mesh("data:2,model:4", jax.devices())
        x, w, b = _rand((4, 16, 32), 20), _rand((32, 64), 21), \
            _rand((64,), 22, 0.1)

        def col(quant):
            return lambda x, w, b: jnp.sum(tp_column_dense(
                x, [w], [b], mesh, quant=quant)[0] ** 2)

        ref, gr = jax.value_and_grad(col("off"), argnums=(0, 1, 2))(x, w, b)
        got, g = jax.value_and_grad(col(mode), argnums=(0, 1, 2))(x, w, b)
        assert abs(float(got) - float(ref)) / abs(float(ref)) < TOL_REL[mode]
        for a_, r_ in zip(g, gr):
            assert _rel(a_, r_) < 2 * TOL_REL[mode]

    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_row_parity_and_grads(self, devices, mode):
        from pytorch_ddp_template_tpu.parallel.collective_matmul import (
            tp_row_dense,
        )

        mesh = make_mesh("data:2,model:4", jax.devices())
        h, w, b = _rand((4, 16, 64), 23), _rand((64, 32), 24), \
            _rand((32,), 25, 0.1)

        def row(quant):
            return lambda h, w, b: jnp.sum(tp_row_dense(
                h, w, b, mesh, quant=quant) ** 2)

        ref, gr = jax.value_and_grad(row("off"), argnums=(0, 1, 2))(h, w, b)
        got, g = jax.value_and_grad(row(mode), argnums=(0, 1, 2))(h, w, b)
        assert abs(float(got) - float(ref)) / abs(float(ref)) < TOL_REL[mode]
        for a_, r_ in zip(g, gr):
            assert _rel(a_, r_) < 2 * TOL_REL[mode]

    def test_unknown_quant_refused(self, devices):
        from pytorch_ddp_template_tpu.parallel.collective_matmul import (
            tp_column_dense, tp_row_dense_local,
        )

        mesh = make_mesh("data:2,model:4", jax.devices())
        with pytest.raises(ValueError, match="unknown quant_compute"):
            tp_column_dense(jnp.zeros((2, 8, 8)), [jnp.zeros((8, 8))],
                            [jnp.zeros(8)], mesh, quant="int4")
        with pytest.raises(ValueError, match="unknown quant_compute"):
            tp_row_dense_local(jnp.zeros((2, 8, 8)), jnp.zeros((8, 8)),
                               jnp.zeros(8), quant="int4")


# -- block / task integration ----------------------------------------------

def _gpt_tiny_loss_and_grad(cfg_kwargs, mesh=None, batch_rows=4):
    key = jax.random.PRNGKey(0)
    batch = {"input_ids": jnp.asarray(
        np.random.default_rng(0).integers(0, 1024, (batch_rows, 128)),
        jnp.int32)}
    cfg = TrainingConfig(model="gpt-tiny", **cfg_kwargs)
    task, _ = build("gpt-tiny", cfg, mesh=mesh)
    params, extra = task.init(key, batch)

    def lf(p):
        loss, _, _ = task.loss(p, extra, batch, jax.random.PRNGKey(1),
                               train=True)
        return loss

    loss, grads = jax.jit(jax.value_and_grad(lf))(params)
    return float(loss), grads, params


def test_quant_off_is_bitwise_the_default_path(devices):
    """--quant_compute off must not perturb the shipped numerics by one
    bit — same loss, same grads, same param tree as a build that never
    mentions the flag."""
    l0, g0, p0 = _gpt_tiny_loss_and_grad({})
    l1, g1, p1 = _gpt_tiny_loss_and_grad({"quant_compute": "off"})
    assert l0 == l1
    for a, b in zip(jax.tree.leaves(nn.meta.unbox(g0)),
                    jax.tree.leaves(nn.meta.unbox(g1))):
        assert bool(jnp.all(a == b))


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_quant_block_param_tree_interchangeable_and_close(devices, mode):
    """The _DenseParams twins keep checkpoints bit-interchangeable with
    the default path, and the quantized loss/grads track the fp32 ones
    within the per-dtype band."""
    l0, g0, p0 = _gpt_tiny_loss_and_grad({})
    lm, gm, pm = _gpt_tiny_loss_and_grad({"quant_compute": mode})
    for a, b in zip(jax.tree.leaves(nn.meta.unbox(p0)),
                    jax.tree.leaves(nn.meta.unbox(pm))):
        assert a.shape == b.shape and bool(jnp.all(a == b))
    assert abs(lm - l0) / abs(l0) < TOL_REL[mode]
    rel = max(_rel(a, b) for a, b in zip(
        jax.tree.leaves(nn.meta.unbox(gm)),
        jax.tree.leaves(nn.meta.unbox(g0))))
    assert rel < 10 * TOL_REL[mode]  # grads amplify through the stack


@pytest.mark.slow  # ~15s scan×tp compile; per-mode quant parity stays tier-1
def test_quant_composes_with_scan_and_tp(devices):
    mesh = make_mesh("data:4,model:2", jax.devices())
    l, g, _ = _gpt_tiny_loss_and_grad(
        {"quant_compute": "int8", "scan_layers": True, "tp_overlap": True,
         "mesh": "data:4,model:2"}, mesh=mesh, batch_rows=8)
    assert np.isfinite(l)
    l0, _, _ = _gpt_tiny_loss_and_grad(
        {"scan_layers": True, "tp_overlap": True, "mesh": "data:4,model:2"},
        mesh=mesh, batch_rows=8)
    assert abs(l - l0) / abs(l0) < TOL_REL["int8"]


# -- refusal matrix --------------------------------------------------------

class TestRefusals:
    def test_config_level(self):
        with pytest.raises(ValueError, match="unknown --quant_compute"):
            TrainingConfig(model="gpt-tiny", quant_compute="int4")
        # every legal mode constructs
        for mode in QUANT_COMPUTE_MODES:
            TrainingConfig(model="gpt-tiny", quant_compute=mode)

    def test_registry_level(self, devices):
        cfg = TrainingConfig(model="mlp", quant_compute="int8")
        with pytest.raises(ValueError, match="transformer families only"):
            build("mlp", cfg)
        cfg = TrainingConfig(model="gpt-moe-tiny", quant_compute="int8")
        with pytest.raises(ValueError, match="MoE entries"):
            build("gpt-moe-tiny", cfg)
        cfg = TrainingConfig(model="gpt-pipe-tiny", quant_compute="int8",
                             mesh="data:4,pipe:2")
        with pytest.raises(ValueError, match="pipelined"):
            build("gpt-pipe-tiny", cfg)

    def test_encoder_level(self, devices):
        from pytorch_ddp_template_tpu.models.transformer import (
            TransformerEncoder,
        )

        enc = TransformerEncoder(num_layers=1, num_heads=2, head_dim=8,
                                 mlp_dim=16, moe_experts=2,
                                 quant_compute="int8")
        with pytest.raises(ValueError, match="MoE blocks"):
            enc.init(jax.random.PRNGKey(0), jnp.zeros((2, 4, 16)))
        enc = TransformerEncoder(num_layers=1, num_heads=2, head_dim=8,
                                 mlp_dim=16, quant_compute="int4")
        with pytest.raises(ValueError, match="unknown quant_compute"):
            enc.init(jax.random.PRNGKey(0), jnp.zeros((2, 4, 16)))


# -- evidence stack --------------------------------------------------------

def test_describe_quant_block(devices):
    from pytorch_ddp_template_tpu.parallel.sharding import describe

    mesh = make_mesh("data:4,model:2", jax.devices())
    cfg = TrainingConfig(model="gpt-tiny", scan_layers=True,
                         tp_overlap=True, quant_compute="int8",
                         mesh="data:4,model:2")
    task, _ = build("gpt-tiny", cfg, mesh=mesh)
    d = describe(mesh, cfg, None, model=task.model)
    q = d["quant"]
    assert q["mode"] == "int8"
    assert q["master_weights"] == "fp32"
    assert q["paths"] == ["ring_collective_matmul"]
    assert 0 < q["narrow_flops_frac"] < 1
    assert q["tp_wire_stack_ratio"] <= 0.5
    # off: no block at all
    cfg_off = TrainingConfig(model="gpt-tiny")
    d_off = describe(mesh, cfg_off, None)
    assert "quant" not in d_off


def test_quant_wire_accounting(devices):
    from pytorch_ddp_template_tpu.parallel.collective_matmul import (
        tp_wire_bytes_per_step,
    )

    kw = dict(batch=8, seq=128, embed=128, num_layers=4, n=4, vocab=1024)
    wide = tp_wire_bytes_per_step(**kw)
    for mode in ("int8", "fp8"):
        narrow = tp_wire_bytes_per_step(quant=mode, **kw)
        # 1 byte + 4/128 scale overhead vs 4 bytes = 0.2578x
        assert narrow["stack"] / wide["stack"] == pytest.approx(
            (1 + 4 / 128) / 4, rel=1e-6)
        assert narrow["head"] == wide["head"]  # head not quantized in v1


def test_peak_flops_per_dtype_rows():
    from pytorch_ddp_template_tpu.obs.attribution import (
        PerfAttribution, peak_flops_for,
    )

    assert peak_flops_for("TPU v5e", dtype="int8") == 394e12
    assert peak_flops_for("TPU v6e", dtype="fp8") == 1836e12
    # generations without the narrow path: absent, never invented
    assert peak_flops_for("TPU v5e", dtype="fp8") is None
    assert peak_flops_for("TPU v3", dtype="int8") is None
    with pytest.raises(ValueError, match="unknown dtype"):
        peak_flops_for("TPU v5e", dtype="int4")
    # the override wins regardless of dtype
    assert peak_flops_for("cpu", 1.5, dtype="int8") == 1.5e12

    cm = {"flops_per_step": 1e12}
    perf = PerfAttribution(cm, device_kind="TPU v5e", n_devices=2,
                           compute_dtype="int8")
    d = perf.describe()
    assert d["quant_compute"] == "int8"
    assert d["peak_tflops_int8"] == pytest.approx(2 * 394.0)
    assert d["quant_peak_headroom"] == pytest.approx(2.0)
    out = perf.interval(wall_s=1.0, steps=1)
    assert out["perf_mfu_vs_quant_peak"] == pytest.approx(
        1e12 / (2 * 394e12), abs=5e-5)  # the record rounds to 4 places
    assert out["perf_mfu"] > out["perf_mfu_vs_quant_peak"]
    # CPU: no narrow row -> no headroom keys, nothing invented
    perf_cpu = PerfAttribution(cm, device_kind="cpu",
                               compute_dtype="int8")
    assert "quant_peak_headroom" not in perf_cpu.describe()
    assert "perf_mfu_vs_quant_peak" not in perf_cpu.interval(
        wall_s=1.0, steps=1)


SYNTHETIC_NARROW_HLO = """
HloModule toy
%ring_body (p: (s8[4,8], f32[4,1], f32[8,8])) -> (s8[4,8], f32[4,1], f32[8,8]) {
  %p = parameter(0)
  %q = s8[4,8]{1,0} get-tuple-element(%p), index=0
  %s = f32[4,1]{1,0} get-tuple-element(%p), index=1
  %acc = f32[8,8]{1,0} get-tuple-element(%p), index=2
  %qc = f32[4,8]{1,0} convert(s8[4,8]{1,0} %q)
  %dot.1 = f32[4,8]{1,0} dot(f32[4,8]{1,0} %qc, f32[8,8]{1,0} %acc), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %pp = s8[4,8]{1,0} collective-permute(s8[4,8]{1,0} %q), source_target_pairs={{0,1},{1,0}}
  ROOT %t = (s8[4,8], f32[4,1], f32[8,8]) tuple(%pp, %s, %acc)
}
ENTRY %main (a: f32[4,8]) -> f32[4,8] {
  %a = parameter(0)
  %w8 = s8[8,8]{1,0} constant({...})
  %wc = f32[8,8]{1,0} convert(s8[8,8]{1,0} %w8)
  ROOT %dot.2 = f32[4,8]{1,0} dot(f32[4,8]{1,0} %a, f32[8,8]{1,0} %wc), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


def test_quant_evidence_synthetic():
    from pytorch_ddp_template_tpu.obs.hlo_report import quant_evidence

    ev = quant_evidence(SYNTHETIC_NARROW_HLO)
    # both dots are narrow-fed (operands are converts FROM s8)
    assert ev["narrow_dots"] == 2
    assert ev["quant_dots_present"] is True
    assert ev["narrow_ppermutes"] == 1
    # the ring body converts FROM narrow only — quantization hoisted
    assert ev["hoisted_quant_ring_bodies"] == 1
    assert ev["requant_ring_bodies"] == 0
    # a wide program carries nothing
    wide = quant_evidence("ENTRY %m (a: f32[4]) -> f32[4] {\n"
                          "  ROOT %a = parameter(0)\n}")
    assert wide["quant_dots_present"] is False


SYNTHETIC_REQUANT_HLO = """
HloModule toy
%ring_body (p: (s8[4,8], f32[8,8])) -> (s8[4,8], f32[8,8]) {
  %p = parameter(0)
  %q = s8[4,8]{1,0} get-tuple-element(%p), index=0
  %acc = f32[8,8]{1,0} get-tuple-element(%p), index=1
  %qc = f32[4,8]{1,0} convert(s8[4,8]{1,0} %q)
  %dot.1 = f32[4,8]{1,0} dot(f32[4,8]{1,0} %qc, f32[8,8]{1,0} %acc), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %rq = s8[4,8]{1,0} convert(f32[4,8]{1,0} %dot.1)
  %pp = s8[4,8]{1,0} collective-permute(s8[4,8]{1,0} %rq), source_target_pairs={{0,1},{1,0}}
  ROOT %t = (s8[4,8], f32[8,8]) tuple(%pp, %acc)
}
ENTRY %main (a: f32[4,8]) -> f32[4,8] {
  %a = parameter(0)
  ROOT %id = f32[4,8]{1,0} copy(f32[4,8]{1,0} %a)
}
"""


def test_quant_evidence_requant_body_not_hoisted():
    # a ring body that re-quantizes its payload per hop (convert TO a
    # narrow result feeding the ppermute) must count as a requant body,
    # not a hoisted one — this is the regression the tripwire exists to
    # catch (the hoisting witness must read the RESULT dtype of the
    # convert, not the operand's)
    from pytorch_ddp_template_tpu.obs.hlo_report import (
        check_overlap_expectations, quant_evidence, schedule_report,
    )

    ev = quant_evidence(SYNTHETIC_REQUANT_HLO)
    assert ev["narrow_ppermutes"] == 1
    assert ev["narrow_ring_bodies"] == 1
    assert ev["hoisted_quant_ring_bodies"] == 0
    assert ev["requant_ring_bodies"] == 1
    # and with zero hoisted bodies the composed tripwire fires
    cfg = TrainingConfig(model="gpt-tiny", scan_layers=True,
                         tp_overlap=True, quant_compute="int8",
                         mesh="data:2,model:2")
    report = schedule_report(SYNTHETIC_REQUANT_HLO)
    warns = check_overlap_expectations(report, cfg,
                                       {"data": 2, "model": 2})
    assert any("re-quantizes inside the loop" in w for w in warns)


def test_quant_tripwire_warns_on_wide_program():
    from pytorch_ddp_template_tpu.obs.hlo_report import (
        check_overlap_expectations, schedule_report,
    )

    cfg = TrainingConfig(model="gpt-tiny", scan_layers=True,
                         tp_overlap=True, quant_compute="int8",
                         mesh="data:2,model:2")
    report = schedule_report("ENTRY %m (a: f32[4]) -> f32[4] {\n"
                             "  ROOT %a = parameter(0)\n}")
    warns = check_overlap_expectations(report, cfg,
                                       {"data": 2, "model": 2})
    assert any("NO narrow-dtype dots" in w for w in warns)
    assert any("ring wire is wide" in w for w in warns)
    # quant off: no quant warnings
    cfg_off = TrainingConfig(model="gpt-tiny")
    warns_off = check_overlap_expectations(report, cfg_off, {"data": 2})
    assert not any("quant" in w.lower() for w in warns_off)
