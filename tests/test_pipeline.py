"""Pipeline-parallel mechanism proof (VERDICT.md round-3 weak #7: give
``PIPE_AXIS`` a mechanism or delete it). The GPipe fill/drain schedule over
``ppermute`` must reproduce plain sequential stage application exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)
from pytorch_ddp_template_tpu.runtime import make_mesh


def stage_fn(w, x):
    return jnp.tanh(x @ w["kernel"] + w["bias"])


def make_stage(rng, d):
    kw, kb = jax.random.split(rng)
    return {"kernel": jax.random.normal(kw, (d, d)) * 0.5,
            "bias": jax.random.normal(kb, (d,)) * 0.1}


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 3), (2, 1)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    d, mb = 8, 4
    mesh = make_mesh(f"pipe:{n_stages}", jax.devices()[:n_stages])
    rngs = jax.random.split(jax.random.PRNGKey(0), n_stages + 1)
    stages = [make_stage(rngs[i], d) for i in range(n_stages)]
    x = jax.random.normal(rngs[-1], (n_micro, mb, d))

    params = stack_stage_params(stages, mesh)
    got = pipeline_apply(params, stage_fn, x, mesh)

    want = x
    for w in stages:
        want = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_composes_with_data_axis():
    """pipe:2 alongside a data axis: the pipeline runs per data shard."""
    d, mb, n_micro = 8, 4, 2
    mesh = make_mesh("data:2,pipe:2", jax.devices()[:4])
    rngs = jax.random.split(jax.random.PRNGKey(1), 3)
    stages = [make_stage(rngs[i], d) for i in range(2)]
    x = jax.random.normal(rngs[-1], (n_micro, mb, d))

    params = stack_stage_params(stages, mesh)
    got = pipeline_apply(params, stage_fn, x, mesh)
    want = x
    for w in stages:
        want = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_stage_count_mismatch_refused():
    """4 stacked stages on a pipe:2 mesh would silently drop stages 1 and 3
    (each rank slices [0] of its 2-stage shard) — must raise instead."""
    d = 8
    mesh = make_mesh("pipe:2", jax.devices()[:2])
    rngs = jax.random.split(jax.random.PRNGKey(2), 5)
    stages = [make_stage(rngs[i], d) for i in range(4)]
    params = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    x = jax.random.normal(rngs[-1], (2, 4, d))
    with pytest.raises(ValueError, match="pipe axis"):
        pipeline_apply(params, stage_fn, x, mesh)


def test_gradients_flow_through_schedule():
    """The fill/drain loop has a static trip count (lowers to scan), so
    reverse-mode AD through the ppermute hops must reproduce sequential
    stage gradients — the pipeline is trainable, not just a fwd proof."""
    d = 4
    mesh = make_mesh("pipe:2", jax.devices()[:2])
    rngs = jax.random.split(jax.random.PRNGKey(3), 3)
    stages = [make_stage(rngs[i], d) for i in range(2)]
    x = jax.random.normal(rngs[-1], (3, 2, d))

    def loss_pipe(params):
        return jnp.sum(pipeline_apply(params, stage_fn, x, mesh) ** 2)

    def loss_seq(stage_list):
        y = x
        for w in stage_list:
            y = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(y)
        return jnp.sum(y ** 2)

    g_pipe = jax.grad(loss_pipe)(stack_stage_params(stages, mesh))
    g_seq = jax.grad(loss_seq)(stages)
    for i in range(2):
        for key in ("kernel", "bias"):
            np.testing.assert_allclose(
                np.asarray(g_pipe[key][i]), np.asarray(g_seq[i][key]),
                rtol=1e-5, atol=1e-6,
            )
