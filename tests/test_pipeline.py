"""Pipeline-parallel mechanism proof (VERDICT.md round-3 weak #7: give
``PIPE_AXIS`` a mechanism or delete it). The GPipe fill/drain schedule over
``ppermute`` must reproduce plain sequential stage application exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)
from pytorch_ddp_template_tpu.runtime import make_mesh


def stage_fn(w, x):
    return jnp.tanh(x @ w["kernel"] + w["bias"])


def make_stage(rng, d):
    kw, kb = jax.random.split(rng)
    return {"kernel": jax.random.normal(kw, (d, d)) * 0.5,
            "bias": jax.random.normal(kb, (d,)) * 0.1}


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 3), (2, 1)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    d, mb = 8, 4
    mesh = make_mesh(f"pipe:{n_stages}", jax.devices()[:n_stages])
    rngs = jax.random.split(jax.random.PRNGKey(0), n_stages + 1)
    stages = [make_stage(rngs[i], d) for i in range(n_stages)]
    x = jax.random.normal(rngs[-1], (n_micro, mb, d))

    params = stack_stage_params(stages, mesh)
    got = pipeline_apply(params, stage_fn, x, mesh)

    want = x
    for w in stages:
        want = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_composes_with_data_axis():
    """pipe:2 alongside a data axis: the pipeline runs per data shard."""
    d, mb, n_micro = 8, 4, 2
    mesh = make_mesh("data:2,pipe:2", jax.devices()[:4])
    rngs = jax.random.split(jax.random.PRNGKey(1), 3)
    stages = [make_stage(rngs[i], d) for i in range(2)]
    x = jax.random.normal(rngs[-1], (n_micro, mb, d))

    params = stack_stage_params(stages, mesh)
    got = pipeline_apply(params, stage_fn, x, mesh)
    want = x
    for w in stages:
        want = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_stage_count_mismatch_refused():
    """4 stacked stages on a pipe:2 mesh would silently drop stages 1 and 3
    (each rank slices [0] of its 2-stage shard) — must raise instead."""
    d = 8
    mesh = make_mesh("pipe:2", jax.devices()[:2])
    rngs = jax.random.split(jax.random.PRNGKey(2), 5)
    stages = [make_stage(rngs[i], d) for i in range(4)]
    params = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    x = jax.random.normal(rngs[-1], (2, 4, d))
    with pytest.raises(ValueError, match="pipe axis"):
        pipeline_apply(params, stage_fn, x, mesh)


def test_gradients_flow_through_schedule():
    """The fill/drain loop has a static trip count (lowers to scan), so
    reverse-mode AD through the ppermute hops must reproduce sequential
    stage gradients — the pipeline is trainable, not just a fwd proof."""
    d = 4
    mesh = make_mesh("pipe:2", jax.devices()[:2])
    rngs = jax.random.split(jax.random.PRNGKey(3), 3)
    stages = [make_stage(rngs[i], d) for i in range(2)]
    x = jax.random.normal(rngs[-1], (3, 2, d))

    def loss_pipe(params):
        return jnp.sum(pipeline_apply(params, stage_fn, x, mesh) ** 2)

    def loss_seq(stage_list):
        y = x
        for w in stage_list:
            y = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(y)
        return jnp.sum(y ** 2)

    g_pipe = jax.grad(loss_pipe)(stack_stage_params(stages, mesh))
    g_seq = jax.grad(loss_seq)(stages)
    for i in range(2):
        for key in ("kernel", "bias"):
            np.testing.assert_allclose(
                np.asarray(g_pipe[key][i]), np.asarray(g_seq[i][key]),
                rtol=1e-5, atol=1e-6,
            )


class TestPipelinedGptEntry:
    """gpt-pipe-tiny: the user-launchable PP path (VERDICT r4 weak #3)."""

    def _build(self, tmp_path, **overrides):
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models import build
        from pytorch_ddp_template_tpu.runtime.context import RuntimeContext

        defaults = dict(
            model="gpt-pipe-tiny", mesh="data:4,pipe:2",
            per_device_train_batch_size=2, dataset_size=128,
            max_steps=2, logging_steps=0, save_steps=0,
            output_dir=str(tmp_path / "out"), resume=False, seed=0,
        )
        defaults.update(overrides)
        cfg = TrainingConfig(**defaults)
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, ds = build(cfg.model, cfg, mesh=mesh)
        key = jax.random.PRNGKey(cfg.seed)
        ctx = RuntimeContext(mesh=mesh, seed_key=key,
                             host_key=jax.random.fold_in(key, 0), config=cfg)
        return cfg, ctx, task, ds

    @pytest.mark.slow  # ~14s of stage-stacked jits; the schedule-level
    # parity above and the clamp-warning tests below stay in tier-1
    def test_matches_sequential_blocks(self, tmp_path):
        """The pipelined forward must equal running the same block params
        sequentially (embed → layers in order → ln → tied head)."""
        import flax.linen as nn

        cfg, ctx, task, ds = self._build(tmp_path)
        batch = {"input_ids": np.asarray(
            np.random.default_rng(0).integers(0, 1024, (8, 128)), np.int32)}
        params, _ = task.init(jax.random.PRNGKey(1), batch)
        logits, _, _ = task._apply_inputs(
            nn.meta.unbox(params), {}, (jnp.asarray(batch["input_ids"]),),
            None, False)

        p = nn.meta.unbox(params)
        x = (p["wte"][batch["input_ids"]] + p["wpe"][None]).astype(task.dtype)
        blocks = p["blocks"]
        flat = jax.tree.map(
            lambda a: a.reshape(task.num_layers, *a.shape[2:]), blocks)
        for i in range(task.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], flat)
            x = task._block.apply({"params": layer}, x, None, train=False)
        h = task._ln.apply({"params": p["final_ln"]}, x.astype(jnp.float32))
        want = (h.astype(task.dtype) @ p["wte"].T.astype(task.dtype))
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(want, np.float32),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow  # ~39s whole-Trainer run (now under the default
    # 1f1b schedule); the tier-1 fused-parity class covers the
    # schedule-level numerics cheaply
    def test_trains_through_trainer_with_stage_sharding(self, tmp_path):
        from pytorch_ddp_template_tpu.train.engine import Trainer

        cfg, ctx, task, ds = self._build(tmp_path)
        t = Trainer(cfg, ctx, task, ds)
        state, _ = t.restore_or_init()
        # stage stacks really live split over the pipe axis
        stage_leaves = jax.tree.leaves(state.params["blocks"])
        assert stage_leaves and all(
            "pipe" in str(x.sharding.spec) for x in stage_leaves)
        final = t.train()
        assert int(final.step) == 2

    def test_refuses_mesh_without_pipe_axis(self, tmp_path):
        """build() succeeds under a pipe-less mesh (dataset-only tooling
        like tools/make_file_dataset.py must keep working), but the task
        refuses at first use — before any training."""
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models import build

        cfg = TrainingConfig(model="gpt-pipe-tiny", mesh="data:8")
        task, ds = build(cfg.model, cfg)  # must not raise
        batch = {"input_ids": np.zeros((4, 128), np.int32)}
        with pytest.raises(ValueError, match="pipe axis"):
            task.init(jax.random.PRNGKey(0), batch)

    @pytest.mark.slow  # ~17s deep grad-parity sweep (long-tail; the
    # toy-stage grad test above pins the schedule's backward in tier-1)
    def test_gradients_match_sequential_with_data_axis(self, tmp_path):
        """pipe x data composition: with the microbatch dim sharded over
        ``data``, gradients of the pipelined loss must still equal the
        sequential-stack reference."""
        import flax.linen as nn

        cfg, ctx, task, ds = self._build(tmp_path)
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, 1024, (8, 128)), jnp.int32)
        params, _ = task.init(jax.random.PRNGKey(2), batch={"input_ids": ids})
        params = nn.meta.unbox(params)

        def loss_pipe(p):
            logits, _, _ = task._apply_inputs(p, {}, (ids,), None, False)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        def loss_seq(p):
            x = (p["wte"][ids] + p["wpe"][None]).astype(task.dtype)
            flat = jax.tree.map(
                lambda a: a.reshape(task.num_layers, *a.shape[2:]),
                p["blocks"])
            for i in range(task.num_layers):
                layer = jax.tree.map(lambda a, i=i: a[i], flat)
                x = task._block.apply({"params": layer}, x, None, train=False)
            h = task._ln.apply({"params": p["final_ln"]},
                               x.astype(jnp.float32))
            logits = h.astype(task.dtype) @ p["wte"].T.astype(task.dtype)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
        g_seq = jax.jit(jax.grad(loss_seq))(params)
        flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
        flat_s = jax.tree.leaves(g_seq)
        assert len(flat_p) == len(flat_s)
        for (path, a), b in zip(flat_p, flat_s):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=str(path))


@pytest.mark.slow  # ~20s two-Trainer save/resume cycle; generic resume is
# tier-1-covered by test_fault_recovery on the dense entries
def test_pipelined_entry_checkpoint_resume(tmp_path):
    """The stacked (pipe-sharded, Partitioned-annotated) stage params must
    survive an orbax save/restore and continue training — the stacked
    layout is unlike every other zoo entry's tree."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    def make(max_steps):
        cfg = TrainingConfig(
            model="gpt-pipe-tiny", mesh="data:4,pipe:2",
            per_device_train_batch_size=2, dataset_size=128,
            max_steps=max_steps, logging_steps=0, save_steps=2,
            output_dir=str(tmp_path / "out"), seed=0,
            pipe_microbatches=2,
        )
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, ds = build(cfg.model, cfg, mesh=mesh)
        key = jax.random.PRNGKey(cfg.seed)
        ctx = RuntimeContext(mesh=mesh, seed_key=key,
                             host_key=jax.random.fold_in(key, 0), config=cfg)
        return Trainer(cfg, ctx, task, ds)

    t = make(2)
    final = t.train()
    assert t.ckpt.latest_step() == 2

    t2 = make(4)
    state, start = t2.restore_or_init()
    assert start == 2
    # restored stage stacks are bit-identical and still pipe-sharded
    a = jax.tree.leaves(final.params["blocks"])[0]
    b = jax.tree.leaves(state.params["blocks"])[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert "pipe" in str(b.sharding.spec)
    final2 = t2.train()
    assert int(final2.step) == 4


def test_pipelined_entry_refusal_matrix():
    """r22: the refusal matrix shrank to the genuinely-impossible
    combos. pipe×{tp,ddp,fsdp} BUILD (one compose wave per run, hoisted
    to the slot boundary — parallel/pipeline.py); what stays refused,
    with the reason named: plain GSPMD --fsdp (silent re-gather), more
    than one compose flag, compose on a non-1f1b schedule, and
    --grad_error_feedback (no per-step residual thread through the
    slot loop)."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build

    # the lifted crosses: each compose flag builds on its mesh
    builds = [
        (dict(tp_overlap=True, scan_layers=True), "data:2,model:2,pipe:2"),
        (dict(ddp_overlap=True), "data:4,pipe:2"),
        (dict(fsdp_overlap=True, scan_layers=True), "data:4,pipe:2"),
    ]
    for kwargs, spec in builds:
        cfg = TrainingConfig(model="gpt-pipe-tiny", mesh=spec, **kwargs)
        mesh = make_mesh(spec, jax.devices())
        task, _ = build(cfg.model, cfg, mesh=mesh)
        assert task is not None

    # what remains refused, with intent
    mesh = make_mesh("data:4,pipe:2", jax.devices())
    cases = [
        (dict(fsdp=True), "--fsdp", "data:4,pipe:2"),
        (dict(tp_overlap=True, ddp_overlap=True, scan_layers=True),
         "ONE", "data:2,model:2,pipe:2"),
        (dict(ddp_overlap=True, pipe_schedule="gpipe"), "1f1b",
         "data:4,pipe:2"),
        (dict(ddp_overlap=True, grad_comm="int8",
              grad_error_feedback=True), "--grad_error_feedback",
         "data:4,pipe:2"),
    ]
    for kwargs, needle, spec in cases:
        cfg = TrainingConfig(model="gpt-pipe-tiny", mesh=spec, **kwargs)
        with pytest.raises(ValueError) as e:
            build(cfg.model, cfg, mesh=make_mesh(spec, jax.devices()))
        assert needle in str(e.value)


def test_validate_schedule_mesh_pipe():
    """The schedule's mesh validation (parallel/schedule.py), r22 form:
    pipe×data composes; pipe×data×model composes WITH tp=True and
    pipe×data with ddp/fsdp=True; a model axis without tp, multiple
    compose flags, tp without a model axis and a pipe-less mesh are
    refused with named reasons."""
    from pytorch_ddp_template_tpu.parallel.schedule import (
        PipelineSchedule, validate_schedule_mesh,
    )

    mesh = make_mesh("data:4,pipe:2", jax.devices())
    assert validate_schedule_mesh(mesh, pipe=True) is mesh
    sched = PipelineSchedule(mesh, "zb", 4)
    assert sched.n_stages == 2
    assert 0.0 < sched.bubble_fraction() < 1.0
    assert sched.wire_bytes_per_step(4, 128, 64) > 0
    # r22 compose acceptances
    assert validate_schedule_mesh(mesh, pipe=True, ddp=True) is mesh
    assert validate_schedule_mesh(mesh, pipe=True, fsdp=True) is mesh
    tp_mesh = make_mesh("data:2,model:2,pipe:2", jax.devices())
    assert validate_schedule_mesh(tp_mesh, pipe=True, tp=True) is tp_mesh
    sched_tp = PipelineSchedule(tp_mesh, "1f1b", 4, tp=True)
    assert sched_tp.compose == "tp"
    assert sched_tp.tp_wave_bytes_per_step(4, 32, 16, 2, 2) > 0
    assert sched_tp.tp_wave_bytes_per_step(4, 32, 16, 2, 1) == 0
    # what stays refused, with intent
    with pytest.raises(ValueError, match="pipe"):
        validate_schedule_mesh(make_mesh("data:8", jax.devices()),
                               pipe=True)
    with pytest.raises(ValueError, match="model"):
        validate_schedule_mesh(tp_mesh, pipe=True)  # live model, no tp
    with pytest.raises(ValueError, match="model"):
        validate_schedule_mesh(mesh, pipe=True, tp=True)  # tp, no model
    with pytest.raises(ValueError, match="ONE|one"):
        validate_schedule_mesh(tp_mesh, pipe=True, tp=True, ddp=True)
    with pytest.raises(ValueError, match="pipe schedule"):
        PipelineSchedule(mesh, "nope", 4)


class TestMicrobatchClampPolicy:
    """The microbatch-clamp policy (models/gpt_pipe.py): a clamp to 1
    microbatch fully serialises every schedule (bubble (P-1)/P) and is
    REFUSED with the fix spelled out (r16 — escalated from the r6
    one-shot warning); a partial clamp warns once at trace time; a
    dividing count stays silent."""

    def _records_of(self, n_micro, batch):
        import logging

        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models import build

        cfg = TrainingConfig(model="gpt-pipe-tiny", mesh="data:4,pipe:2",
                             pipe_microbatches=n_micro)
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, _ = build(cfg.model, cfg, mesh=mesh)
        params, _ = task.init(jax.random.PRNGKey(0), batch)
        # the module logger does not propagate (utils/logging.py), so
        # capture with a handler attached directly to it
        records: list[logging.LogRecord] = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        log = logging.getLogger("pytorch_ddp_template_tpu.models.gpt_pipe")
        handler = Capture()
        log.addHandler(handler)
        try:
            import flax.linen as nn

            for _ in range(2):  # twice: the warning must fire ONCE
                task._apply_inputs(nn.meta.unbox(params), {},
                                   (jnp.asarray(batch["input_ids"]),),
                                   None, False)
        finally:
            log.removeHandler(handler)
        return [r for r in records if "clamped" in r.getMessage()]

    def test_refuses_when_clamp_serialises(self):
        # per-replica batch = 8/4 = 2; gcd(3, 2) = 1 -> the pipeline
        # would fully serialise: a named refusal with the fix, not a
        # warning the bubble then eats invisibly
        batch = {"input_ids": np.zeros((8, 128), np.int32)}
        with pytest.raises(ValueError, match="serialise"):
            self._records_of(3, batch)
        # the message names both levers
        try:
            self._records_of(3, batch)
        except ValueError as e:
            assert "--pipe_microbatches" in str(e)
            assert "batch" in str(e)

    def test_warns_once_on_partial_clamp(self):
        # gcd(4, 2) = 2: still pipelining, but less than requested
        batch = {"input_ids": np.zeros((8, 128), np.int32)}
        warned = self._records_of(4, batch)
        assert len(warned) == 1
        assert warned[0].levelname == "WARNING"

    def test_silent_when_dividing(self):
        # gcd(2, 2) = 2 == requested -> no clamp, no warning
        batch = {"input_ids": np.zeros((8, 128), np.int32)}
        assert self._records_of(2, batch) == []


# -- r16: slot tables, fused schedules, zero-bubble split -----------------


class TestPipeTables:
    """The slot-table generator (parallel/pipeline.py): structural
    invariants, residency bounds and the bubble model — host-side
    numpy, no tracing."""

    @pytest.mark.parametrize("kind", ["1f1b", "zb"])
    @pytest.mark.parametrize("mp", [(1, 2), (2, 4), (3, 2), (4, 3),
                                    (8, 2)])
    def test_every_unit_exactly_once_and_ordered(self, kind, mp):
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            WORK_B, WORK_BDW, WORK_BDX, WORK_F, build_pipe_table,
        )

        M, P = mp
        tab = build_pipe_table(kind, M, P)  # builder verifies deps
        want_b = WORK_B if kind == "1f1b" else WORK_BDX
        seen = {}
        for t in range(tab.n_slots):
            for p in range(P):
                w = int(tab.work[t, p])
                if w:
                    seen[(p, int(tab.mb[t, p]), w)] = t
        for p in range(P):
            for i in range(M):
                assert (p, i, WORK_F) in seen
                assert (p, i, want_b) in seen
                # zb never schedules dw in-loop: every unit drains in
                # the batched post-loop wave
                assert (p, i, WORK_BDW) not in seen
        assert tab.wave_count == (M * P if kind == "zb" else 0)

    def test_1f1b_residency_is_in_flight_not_microbatches(self):
        """THE 1F1B claim: activation slots track the in-flight count
        (<= P), not M — at M=8 on 2 stages the store stays 2 slots."""
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            build_pipe_table,
        )

        assert build_pipe_table("1f1b", 8, 2).n_aslots == 2
        assert build_pipe_table("1f1b", 8, 4).n_aslots == 4
        assert build_pipe_table("1f1b", 2, 4).n_aslots == 2

    @pytest.mark.parametrize("mp", [(2, 4), (4, 4), (3, 2), (8, 2)])
    def test_zb_bubble_strictly_below_1f1b(self, mp):
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            schedule_bubble_fraction,
        )

        M, P = mp
        zb = schedule_bubble_fraction("zb", M, P)
        f1 = schedule_bubble_fraction("1f1b", M, P)
        gp = schedule_bubble_fraction("gpipe", M, P)
        assert 0.0 < zb < f1 < 1.0
        assert gp == pytest.approx((P - 1) / (M + P - 1))
        # degenerate geometries: no pipeline, no bubble
        assert schedule_bubble_fraction("zb", 4, 1) == 0.0

    def test_refusals(self):
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            build_pipe_table,
        )

        with pytest.raises(ValueError, match="unknown schedule"):
            build_pipe_table("gpipe", 4, 2)  # masked loop has no table
        with pytest.raises(ValueError, match="n_micro"):
            build_pipe_table("zb", 0, 2)


class TestPipeTableInternals:
    """r22 satellite: the first direct pins on build_pipe_table's
    intermediate structures — arrival maps, store-slot interval
    packing, and the bubble model under MEASURED (non-unit) branch
    costs. Host-side numpy only."""

    @staticmethod
    def _placements(tab):
        """Recover (f_slot, b_slot) from the work/mb rows."""
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            WORK_B, WORK_BDX, WORK_F,
        )

        M, P = tab.n_micro, tab.n_stages
        f = np.full((P, M), -1, np.int64)
        b = np.full((P, M), -1, np.int64)
        for t in range(tab.n_slots):
            for p in range(P):
                w = int(tab.work[t, p])
                if w == WORK_F:
                    f[p, int(tab.mb[t, p])] = t
                elif w in (WORK_B, WORK_BDX):
                    b[p, int(tab.mb[t, p])] = t
        return f, b

    @pytest.mark.parametrize("kind", ["1f1b", "zb"])
    @pytest.mark.parametrize("mp", [(2, 2), (4, 3), (8, 2), (3, 4)])
    def test_arrival_maps_mirror_placements(self, kind, mp):
        """A unit produced at slot t is consumable downstream from
        t+1: arr_f_mb[f_slot[p,i]+1, p+1] == i, grads symmetrically
        upstream — stage 0's fwd wire and the last stage's grad wire
        stay -1, and every microbatch arrives exactly once per wire."""
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            build_pipe_table,
        )

        M, P = mp
        tab = build_pipe_table(kind, M, P)
        f, b = self._placements(tab)
        for p in range(P):
            for i in range(M):
                if p + 1 < P:
                    assert tab.arr_f_mb[f[p, i] + 1, p + 1] == i
                if p > 0 and b[p, i] + 1 < tab.n_slots:
                    assert tab.arr_g_mb[b[p, i] + 1, p - 1] == i
        assert np.all(tab.arr_f_mb[:, 0] == -1)
        assert np.all(tab.arr_g_mb[:, P - 1] == -1)
        for p in range(1, P):
            got = sorted(int(i) for i in tab.arr_f_mb[:, p] if i >= 0)
            assert got == list(range(M))
        for p in range(P - 1):
            got = [int(i) for i in tab.arr_g_mb[:, p] if i >= 0]
            assert len(got) == len(set(got))  # at most once per wire

    @pytest.mark.parametrize("kind", ["1f1b", "zb"])
    @pytest.mark.parametrize("mp", [(2, 2), (4, 3), (8, 2)])
    def test_store_slot_packing_no_live_collisions(self, kind, mp):
        """Interval packing: two microbatches whose activation
        lifetimes [arrive, consume] overlap at a stage must hold
        DISTINCT aslots, every assignment stays < n_aslots, and a
        freed slot is reusable (n_aslots <= min(M, live bound))."""
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            WORK_B, WORK_BDX, WORK_F, build_pipe_table,
        )

        M, P = mp
        tab = build_pipe_table(kind, M, P)
        f, b = self._placements(tab)
        # recover each (p, i) -> aslot from the work rows
        amap = {}
        for t in range(tab.n_slots):
            for p in range(P):
                if int(tab.work[t, p]) in (WORK_F, WORK_B, WORK_BDX):
                    key = (p, int(tab.mb[t, p]))
                    s = int(tab.aslot[t, p])
                    assert 0 <= s < tab.n_aslots
                    assert amap.setdefault(key, s) == s  # stable
        for p in range(P):
            for i in range(M):
                for j in range(i + 1, M):
                    lo_i = f[p, i] if p == 0 else f[p - 1, i] + 1
                    lo_j = f[p, j] if p == 0 else f[p - 1, j] + 1
                    if lo_i <= b[p, j] and lo_j <= b[p, i]:
                        assert amap[(p, i)] != amap[(p, j)]
        assert tab.n_aslots <= M or M == 1

    def test_arrival_slot_points_at_consumer_store(self):
        """arr_f_slot names the STORE slot the arriving activation
        lands in — it must equal the consumer stage's packed aslot for
        that microbatch (the wire and the store agree)."""
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            WORK_B, WORK_BDX, WORK_F, build_pipe_table,
        )

        tab = build_pipe_table("1f1b", 4, 3)
        amap = {}
        for t in range(tab.n_slots):
            for p in range(tab.n_stages):
                if int(tab.work[t, p]) in (WORK_F, WORK_B, WORK_BDX):
                    amap[(p, int(tab.mb[t, p]))] = int(tab.aslot[t, p])
        for t in range(tab.n_slots):
            for p in range(tab.n_stages):
                i = int(tab.arr_f_mb[t, p])
                if i >= 0:
                    assert int(tab.arr_f_slot[t, p]) == amap[(p, i)]

    def test_bubble_fraction_consistent_with_makespan(self):
        """schedule_bubble_fraction is exactly 1 - useful/(P*span) of
        schedule_makespan under the SAME measured costs — the bench
        legs rely on this identity when they feed device-measured
        branch times into the static model."""
        from pytorch_ddp_template_tpu.parallel.pipeline import (
            WORK_B, WORK_BDX, WORK_BDW, WORK_F, schedule_bubble_fraction,
            schedule_makespan,
        )

        measured = {WORK_F: 1.7, WORK_B: 4.2, WORK_BDX: 2.9,
                    WORK_BDW: 1.3}
        for kind in ("gpipe", "1f1b", "zb"):
            span, useful = schedule_makespan(kind, 4, 3, measured)
            frac = schedule_bubble_fraction(kind, 4, 3, measured)
            assert frac == pytest.approx(1.0 - useful / (3 * span))
            assert 0.0 < frac < 1.0
        # skewed costs keep the ordering the unit model predicts
        zb = schedule_bubble_fraction("zb", 4, 3, measured)
        f1 = schedule_bubble_fraction("1f1b", 4, 3, measured)
        assert zb < f1


class TestZbTappedBlock:
    """The hand-rolled tapped block twin must reproduce EncoderBlock
    bit-for-bit (same primitives, same order) — the zb dx/dw split is
    only as correct as this equivalence."""

    def _task(self):
        from pytorch_ddp_template_tpu.models.gpt_pipe import (
            PipelinedGptTask,
        )

        mesh = make_mesh("data:4,pipe:2", jax.devices())
        return PipelinedGptTask(mesh, vocab_size=256, seq_len=32,
                                num_layers=2, num_heads=2, head_dim=8,
                                mlp_dim=32, n_micro=2, pipe_schedule="zb")

    def test_tapped_forward_bit_exact(self):
        import flax.linen as nn

        task = self._task()
        params, _ = task.init(jax.random.PRNGKey(0), {
            "input_ids": np.zeros((4, 32), np.int32)})
        blocks = nn.meta.unbox(params["blocks"])
        layer = jax.tree.map(lambda a: a[0, 0], blocks)
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 32, 16)), jnp.float32)
        want = task._block.apply({"params": layer}, x, None, train=False)
        pr = jax.tree.map(
            lambda a: a[0],
            task._make_probes(jax.tree.map(lambda a: a[0], blocks),
                              jax.ShapeDtypeStruct(x.shape, x.dtype)))
        got, taps = task._block_fwd_tapped(layer, x, pr)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert set(taps) == {"x", "h1", "ctx", "x1", "h2", "a1"}

    def test_dw_from_taps_matches_autodiff(self):
        """The deferred dw products == the fused vjp's weight grads for
        one stage: the functional heart of the zero-bubble split."""
        import flax.linen as nn

        task = self._task()
        params, _ = task.init(jax.random.PRNGKey(1), {
            "input_ids": np.zeros((4, 32), np.int32)})
        blocks = nn.meta.unbox(params["blocks"])
        stage_w = jax.tree.map(lambda a: a[0], blocks)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 32, 16)), jnp.float32)
        gy = jnp.asarray(rng.standard_normal((2, 32, 16)), jnp.float32)

        # reference: full vjp weight grads
        _, pull = jax.vjp(lambda w, h: task._stage_fwd(w, h), stage_w, x)
        gw_ref, _ = pull(gy)

        # split: dx pass captures taps + probe grads, dw pass products
        probes = task._make_probes(stage_w, jax.ShapeDtypeStruct(
            x.shape, x.dtype))
        (y, taps), pull2 = jax.vjp(
            lambda x_, pr: task._stage_fwd_tapped(stage_w, x_, pr),
            x, probes)
        gx, g_probes = pull2((gy, jax.tree.map(jnp.zeros_like, taps)))
        gw = task._dw_from_taps(
            stage_w, jax.tree.map(lambda a: a[None], taps),
            jax.tree.map(lambda a: a[None], g_probes))
        flat_r, _ = jax.tree_util.tree_flatten_with_path(gw_ref)
        flat_g = jax.tree.leaves(gw)
        assert len(flat_r) == len(flat_g)
        for (path, a), b in zip(flat_r, flat_g):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6,
                err_msg=jax.tree_util.keystr(path))

        # the dx of the tapped pass equals the fused dx too
        _, pull3 = jax.vjp(lambda h: task._stage_fwd(stage_w, h), x)
        (gx_ref,) = pull3(gy)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   rtol=2e-5, atol=1e-6)


class TestFusedScheduleParity:
    """1f1b and zb task-level loss/grad parity against the gpipe
    baseline (itself pinned against sequential stages above) — the
    repo's float32 tolerance conventions, on a pipe×data mesh."""

    def _build(self, schedule, scan_layers=False):
        from pytorch_ddp_template_tpu.models.gpt_pipe import (
            PipelinedGptTask,
        )

        mesh = make_mesh("data:2,pipe:2", jax.devices()[:4])
        return PipelinedGptTask(mesh, vocab_size=256, seq_len=32,
                                num_layers=2, num_heads=2, head_dim=8,
                                mlp_dim=32, n_micro=2,
                                pipe_schedule=schedule,
                                scan_layers=scan_layers)

    @pytest.fixture(scope="class")
    def reference(self):
        import flax.linen as nn

        task = self._build("gpipe")
        ids = np.asarray(np.random.default_rng(2).integers(
            0, 256, (4, 32)), np.int32)
        batch = {"input_ids": ids}
        params, _ = task.init(jax.random.PRNGKey(3), batch)
        params = nn.meta.unbox(params)

        def f(p):
            total, _, m = task.loss(p, {}, batch, None, train=True)
            return total, m

        (l, m), g = jax.jit(jax.value_and_grad(f, has_aux=True))(params)
        return batch, params, float(l), jax.device_get(g), {
            k: float(v) for k, v in m.items()}

    @pytest.mark.parametrize("schedule,scan", [("1f1b", False),
                                               ("zb", False),
                                               ("zb", True)])
    def test_loss_and_grads_match_gpipe(self, reference, schedule, scan):
        batch, params, l_ref, g_ref, m_ref = reference
        task = self._build(schedule, scan_layers=scan)

        def f(p):
            total, _, m = task.loss(p, {}, batch, None, train=True)
            return total, m

        (l, m), g = jax.jit(jax.value_and_grad(f, has_aux=True))(params)
        assert float(l) == pytest.approx(l_ref, rel=1e-6)
        assert float(m["next_token_accuracy"]) == pytest.approx(
            m_ref["next_token_accuracy"], abs=1e-6)
        g = jax.device_get(g)
        flat_r, _ = jax.tree_util.tree_flatten_with_path(g_ref)
        for (path, a), b in zip(flat_r, jax.tree.leaves(g)):
            a, b = np.asarray(a), np.asarray(b)
            scale = max(float(np.max(np.abs(a))), 1e-6)
            assert float(np.max(np.abs(a - b))) / scale < 2e-4, \
                jax.tree_util.keystr(path)

    def test_eval_path_matches_train_loss(self, reference):
        """train=False routes through the F-only loop + whole-batch
        tail; the metric must agree with the fused schedule's."""
        batch, params, l_ref, _, _ = reference
        task = self._build("zb")
        total, _, m = task.loss(params, {}, batch, None, train=False)
        assert float(total) == pytest.approx(l_ref, rel=1e-5)


class TestComposedScheduleParity:
    """r22 tentpole pin: pipe×tp, pipe×ddp and pipe×fsdp loss/grad
    parity against the gpipe baseline (itself pinned against sequential
    stages above) — same float32 conventions, and the compiled slot
    body must carry ZERO collectives inside branch_computations (the
    boundary-hoisting invariant; a divergent-branch collective is a
    deadlock on real hardware, so this tripwire is the acceptance
    gate, not decoration)."""

    KW = dict(vocab_size=256, seq_len=32, num_layers=2, num_heads=2,
              head_dim=8, mlp_dim=32, n_micro=2)

    def _build(self, compose, **extra):
        from pytorch_ddp_template_tpu.models.gpt_pipe import (
            PipelinedGptTask,
        )

        if compose == "tp":
            mesh = make_mesh("data:2,model:2,pipe:2", jax.devices())
        else:
            mesh = make_mesh("data:2,pipe:2", jax.devices()[:4])
        flags = {}
        if compose != "none":
            flags[f"{compose}_overlap"] = True
        return PipelinedGptTask(mesh, pipe_schedule="1f1b",
                                **flags, **extra, **self.KW)

    @pytest.fixture(scope="class")
    def reference(self):
        import flax.linen as nn

        from pytorch_ddp_template_tpu.models.gpt_pipe import (
            PipelinedGptTask,
        )

        mesh = make_mesh("data:2,pipe:2", jax.devices()[:4])
        task = PipelinedGptTask(mesh, pipe_schedule="gpipe", **self.KW)
        ids = np.asarray(np.random.default_rng(6).integers(
            0, 256, (4, 32)), np.int32)
        batch = {"input_ids": ids}
        params, _ = task.init(jax.random.PRNGKey(7), batch)
        params = nn.meta.unbox(params)

        def f(p):
            total, _, _ = task.loss(p, {}, batch, None, train=True)
            return total

        l, g = jax.jit(jax.value_and_grad(f))(params)
        return batch, params, float(l), jax.device_get(g)

    @pytest.mark.parametrize("compose", ["tp", "ddp", "fsdp"])
    def test_loss_and_grads_match_gpipe(self, reference, compose):
        batch, params, l_ref, g_ref = reference
        task = self._build(compose)

        def f(p):
            total, _, _ = task.loss(p, {}, batch, None, train=True)
            return total

        fn = jax.jit(jax.value_and_grad(f))
        l, g = fn(params)
        assert float(l) == pytest.approx(l_ref, rel=1e-6)
        g = jax.device_get(g)
        flat_r, _ = jax.tree_util.tree_flatten_with_path(g_ref)
        for (path, a), b in zip(flat_r, jax.tree.leaves(g)):
            a, b = np.asarray(a), np.asarray(b)
            scale = max(float(np.max(np.abs(a))), 1e-6)
            assert float(np.max(np.abs(a - b))) / scale < 2e-4, \
                jax.tree_util.keystr(path)

        # the r22 invariant on the REAL lowering: conditionals present
        # (the work switch for ddp/fsdp; guard conds for tp), zero
        # collectives reachable from their branch computations
        from pytorch_ddp_template_tpu.obs.hlo_report import pipe_evidence

        ev = pipe_evidence(fn.lower(params).compile().as_text())
        assert ev["slot_bodies"] >= 1
        assert ev["pipe_sends_independent"] is True
        assert ev["branch_computation_count"] >= 1
        assert ev["branch_collectives"] == 0
        assert ev["branch_collectives_free"] is True

    def test_ddp_lossy_wire_stays_close(self, reference):
        """grad_comm=bf16 per-slot reduces: stochastic rounding is
        unbiased, so the grads stay within a loose band of the fp32
        reference (the exact-parity bar is fp32's)."""
        batch, params, l_ref, g_ref = reference
        task = self._build("ddp", grad_comm="bf16")

        def f(p):
            total, _, _ = task.loss(p, {}, batch,
                                    jax.random.PRNGKey(11), train=True)
            return total

        l, g = jax.jit(jax.value_and_grad(f))(params)
        assert float(l) == pytest.approx(l_ref, rel=1e-6)
        g = jax.device_get(g)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
            a, b = np.asarray(a), np.asarray(b)
            scale = max(float(np.max(np.abs(a))), 1e-6)
            assert float(np.max(np.abs(a - b))) / scale < 5e-2


def test_effective_microbatches_and_bubble_surface():
    """describe() exposes the pipe schedule block: effective
    microbatches after the gcd clamp, the static bubble fraction, and
    the wire budget inside the unified overlap block."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.parallel.sharding import describe

    cfg = TrainingConfig(model="gpt-pipe-tiny", mesh="data:4,pipe:2",
                         per_device_train_batch_size=6,
                         pipe_microbatches=4, pipe_schedule="zb")
    mesh = make_mesh(cfg.mesh, jax.devices())
    task, _ = build(cfg.model, cfg, mesh=mesh)
    assert task.effective_microbatches(cfg.train_batch_size) == 2
    params, _ = task.init(jax.random.PRNGKey(0), {
        "input_ids": np.zeros((24, 128), np.int32)})
    d = describe(mesh, cfg, params)
    assert d["pipe_mode"] == "zb"
    assert d["pipe_stages"] == 2
    assert d["effective_microbatches"] == 2  # gcd(4, 6)
    assert 0.0 < d["pipe_bubble_frac_static"] < 1.0
    assert d["pipe_wire_mb_per_step"] > 0
    assert d["overlap"]["schedule"]["pipe"] == "zb"
    assert "pipe" in d["overlap"]["decomposed_axes"]
    # gpipe is the baseline, not a decomposed schedule
    cfg2 = TrainingConfig(model="gpt-pipe-tiny", mesh="data:4,pipe:2",
                          pipe_schedule="gpipe")
    d2 = describe(mesh, cfg2, params)
    assert d2["overlap"]["schedule"]["pipe"] == "gpipe"
    assert "pipe" not in d2["overlap"]["decomposed_axes"]


def test_scan_layers_accepted_for_pipe_entries():
    """r16 satellite: --scan_layers now means stage-local scan for the
    pipelined entries instead of a refusal; the checkpoint layout is
    unchanged either way."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build

    mesh = make_mesh("data:4,pipe:2", jax.devices())
    cfg = TrainingConfig(model="gpt-pipe-tiny", mesh="data:4,pipe:2",
                         scan_layers=True)
    task, _ = build(cfg.model, cfg, mesh=mesh)
    assert task.scan_layers is True
    p_scan, _ = task.init(jax.random.PRNGKey(0), {
        "input_ids": np.zeros((8, 128), np.int32)})
    cfg2 = TrainingConfig(model="gpt-pipe-tiny", mesh="data:4,pipe:2")
    task2, _ = build(cfg2.model, cfg2, mesh=mesh)
    assert task2.scan_layers is False
    p_plain, _ = task2.init(jax.random.PRNGKey(0), {
        "input_ids": np.zeros((8, 128), np.int32)})
    import flax.linen as nn

    for a, b in zip(jax.tree.leaves(nn.meta.unbox(p_scan)),
                    jax.tree.leaves(nn.meta.unbox(p_plain))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestPipelinedCheckpointConversion:
    """r16 satellite: tools/convert_checkpoint.py handles the (P,
    layers_per_stage, ...) stage stacking — pipelined ↔ scanned ↔
    unrolled round-trips bit-exact, re-stacking to a different pipe
    degree included."""

    def _state(self, p=2, lps=3):
        rng = np.random.default_rng(0)
        blocks = {"attn": {"kernel": rng.standard_normal((p, lps, 4, 4))},
                  "ln": {"scale": rng.standard_normal((p, lps, 4))}}
        return {"params": {"wte": rng.standard_normal((8, 4)),
                           "blocks": blocks},
                "opt_state": {"mu": {"blocks": jax.tree.map(
                    np.copy, blocks)}}}

    def test_round_trips_bit_exact(self):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        from convert_checkpoint import convert_state

        state = self._state()
        scanned = convert_state(state, "scanned")
        assert scanned["params"]["blocks"]["layers"]["attn"][
            "kernel"].shape == (6, 4, 4)
        unrolled = convert_state(self._state(), "unrolled")
        assert "layer_0" in unrolled["params"]["blocks"]
        back = convert_state(scanned, "pipelined", pipe_stages=2)
        for a, b in zip(jax.tree.leaves(back),
                        jax.tree.leaves(state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # repipe 2 -> 3 -> 2 bit-exact (6 layers divide both)
        re3 = convert_state(self._state(), "pipelined", pipe_stages=3)
        assert re3["params"]["blocks"]["attn"]["kernel"].shape == (
            3, 2, 4, 4)
        re2 = convert_state(re3, "pipelined", pipe_stages=2)
        for a, b in zip(jax.tree.leaves(re2), jax.tree.leaves(state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_refusals(self):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        from convert_checkpoint import convert_state

        state = self._state()
        with pytest.raises(ValueError, match="pipe_stages"):
            convert_state(state, "pipelined")  # missing target count
        with pytest.raises(ValueError, match="no-op"):
            convert_state(state, "pipelined", pipe_stages=2)
        with pytest.raises(ValueError, match="divide|%"):
            convert_state(state, "pipelined", pipe_stages=4)  # 6 % 4
        with pytest.raises(ValueError, match="nothing to convert|no"):
            convert_state({"params": {"w": np.zeros((3, 3))}}, "scanned")


def test_pipe_bubble_in_attribution():
    """r16 satellite: the static cost model carries the pipeline bubble
    fraction (zeroed when no pipe axis) and the runtime attribution
    overlays perf_bubble_frac = measured device share × static bubble —
    the fraction quartet still sums to 1.0."""
    from pytorch_ddp_template_tpu.obs.attribution import (
        PerfAttribution, static_cost_model,
    )

    class _NoCost:
        def cost_analysis(self):
            return {}

    cm = static_cost_model(_NoCost(), {"data": 2, "pipe": 4},
                           hlo_text="", pipe_bubble_frac=0.4)
    assert cm["pipe_bubble_frac"] == 0.4
    cm_nopipe = static_cost_model(_NoCost(), {"data": 8}, hlo_text="",
                                  pipe_bubble_frac=0.4)
    assert cm_nopipe["pipe_bubble_frac"] == 0.0

    perf = PerfAttribution(cm, device_kind="host", n_devices=8)
    rec = perf.interval(wall_s=10.0, steps=10, input_wait_s=1.0,
                        device_wait_s=5.0)
    assert rec["perf_bubble_frac"] == pytest.approx(0.5 * 0.4, abs=1e-3)
    quartet = (rec["perf_frac_input"] + rec["perf_frac_host"]
               + rec["perf_frac_comm"] + rec["perf_frac_compute"])
    assert quartet == pytest.approx(1.0, abs=1e-6)
    assert "pipe_bubble_frac_static" in perf.describe()


class TestHloPipeEvidence:
    """obs/hlo_report.pipe_evidence on hand-written HLO: a slot-loop
    body whose ppermutes consume loop state and whose dots live in
    conditional branches is independent; a ppermute fed by a same-body
    dot is not."""

    GOOD = """
HloModule good
%branch_w (p: f32[4,4]) -> f32[4,4] {
  %p = f32[4,4] parameter(0)
  ROOT %d = f32[4,4] dot(%p, %p), metadata={op_name="pipe_stage_dw/dw"}
}
%body (arg: (f32[4,4], s32[])) -> (f32[4,4], s32[]) {
  %arg = (f32[4,4], s32[]) parameter(0)
  %y = f32[4,4] get-tuple-element(%arg), index=0
  %i = s32[] get-tuple-element(%arg), index=1
  %send = f32[4,4] collective-permute(%y), source_target_pairs={{0,1}}
  %w = f32[4,4] conditional(%i, %send, %send), branch_computations={%branch_w, %branch_w}
  ROOT %t = (f32[4,4], s32[]) tuple(%w, %i)
}
ENTRY %main (x: f32[4,4]) -> f32[4,4] {
  %x = f32[4,4] parameter(0)
  ROOT %r = f32[4,4] dot(%x, %x)
}
"""

    BAD = """
HloModule bad
%body (arg: (f32[4,4], s32[])) -> (f32[4,4], s32[]) {
  %arg = (f32[4,4], s32[]) parameter(0)
  %y = f32[4,4] get-tuple-element(%arg), index=0
  %i = s32[] get-tuple-element(%arg), index=1
  %d = f32[4,4] dot(%y, %y)
  %send = f32[4,4] collective-permute(%d), source_target_pairs={{0,1}}
  ROOT %t = (f32[4,4], s32[]) tuple(%send, %i)
}
ENTRY %main (x: f32[4,4]) -> f32[4,4] {
  %x = f32[4,4] parameter(0)
  ROOT %r = f32[4,4] dot(%x, %x)
}
"""

    def test_good_slot_body_independent(self):
        from pytorch_ddp_template_tpu.obs.hlo_report import pipe_evidence

        ev = pipe_evidence(self.GOOD)
        assert ev["slot_bodies"] == 1
        assert ev["independent_send_bodies"] == 1
        assert ev["pipe_sends_independent"] is True
        assert ev["conditional_count"] == 1
        assert ev["dw_ops_present"] is True

    BAD_VIA_COND = """
HloModule bad2
%branch_w (p: f32[4,4]) -> f32[4,4] {
  %p = f32[4,4] parameter(0)
  ROOT %d = f32[4,4] dot(%p, %p)
}
%body (arg: (f32[4,4], s32[])) -> (f32[4,4], s32[]) {
  %arg = (f32[4,4], s32[]) parameter(0)
  %y = f32[4,4] get-tuple-element(%arg), index=0
  %i = s32[] get-tuple-element(%arg), index=1
  %w = f32[4,4] conditional(%i, %y, %y), branch_computations={%branch_w, %branch_w}
  %send = f32[4,4] collective-permute(%w), source_target_pairs={{0,1}}
  ROOT %t = (f32[4,4], s32[]) tuple(%send, %i)
}
ENTRY %main (x: f32[4,4]) -> f32[4,4] {
  %x = f32[4,4] parameter(0)
  ROOT %r = f32[4,4] dot(%x, %x)
}
"""

    def test_dependent_send_flagged(self):
        from pytorch_ddp_template_tpu.obs.hlo_report import pipe_evidence

        ev = pipe_evidence(self.BAD)
        assert ev["slot_bodies"] == 1
        assert ev["pipe_sends_independent"] is False
        assert ev["dw_ops_present"] is False

    def test_send_consuming_the_switch_result_flagged(self):
        """The common lowering keeps the slot's dots INSIDE the switch's
        branch computations — a ppermute consuming the conditional's
        result must still count as compute-dependent (the review case
        the first walker version could not flag)."""
        from pytorch_ddp_template_tpu.obs.hlo_report import pipe_evidence

        ev = pipe_evidence(self.BAD_VIA_COND)
        assert ev["slot_bodies"] == 1
        assert ev["pipe_sends_independent"] is False

    BAD_BRANCH_COLL = """
HloModule bad3
%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}
%branch_w (p: f32[4,4]) -> f32[4,4] {
  %p = f32[4,4] parameter(0)
  %ar = f32[4,4] all-reduce(%p), replica_groups={}, to_apply=%add
  ROOT %d = f32[4,4] dot(%ar, %ar)
}
%body (arg: (f32[4,4], s32[])) -> (f32[4,4], s32[]) {
  %arg = (f32[4,4], s32[]) parameter(0)
  %y = f32[4,4] get-tuple-element(%arg), index=0
  %i = s32[] get-tuple-element(%arg), index=1
  %send = f32[4,4] collective-permute(%y), source_target_pairs={{0,1}}
  %w = f32[4,4] conditional(%i, %send, %send), branch_computations={%branch_w, %branch_w}
  ROOT %t = (f32[4,4], s32[]) tuple(%w, %i)
}
ENTRY %main (x: f32[4,4]) -> f32[4,4] {
  %x = f32[4,4] parameter(0)
  ROOT %r = f32[4,4] dot(%x, %x)
}
"""

    BAD_BRANCH_COLL_NESTED = """
HloModule bad4
%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}
%inner (q: f32[4,4]) -> f32[4,4] {
  %q = f32[4,4] parameter(0)
  ROOT %ar = f32[4,4] all-reduce(%q), replica_groups={}, to_apply=%add
}
%branch_w (p: f32[4,4]) -> f32[4,4] {
  %p = f32[4,4] parameter(0)
  %c = f32[4,4] call(%p), to_apply=%inner
  ROOT %d = f32[4,4] dot(%c, %c)
}
%body (arg: (f32[4,4], s32[])) -> (f32[4,4], s32[]) {
  %arg = (f32[4,4], s32[]) parameter(0)
  %y = f32[4,4] get-tuple-element(%arg), index=0
  %i = s32[] get-tuple-element(%arg), index=1
  %send = f32[4,4] collective-permute(%y), source_target_pairs={{0,1}}
  %w = f32[4,4] conditional(%i, %send, %send), branch_computations={%branch_w, %branch_w}
  ROOT %t = (f32[4,4], s32[]) tuple(%w, %i)
}
ENTRY %main (x: f32[4,4]) -> f32[4,4] {
  %x = f32[4,4] parameter(0)
  ROOT %r = f32[4,4] dot(%x, %x)
}
"""

    def test_branch_collective_counts(self):
        """The r22 compose invariant: GOOD's branches hold only dots
        (free); a direct all-reduce under the predicate counts; so
        does one reached transitively through a called computation —
        the closure matters because XLA freely outlines branch bodies
        into helper computations."""
        from pytorch_ddp_template_tpu.obs.hlo_report import pipe_evidence

        good = pipe_evidence(self.GOOD)
        assert good["branch_computation_count"] >= 1
        assert good["branch_collectives"] == 0
        assert good["branch_collectives_free"] is True

        direct = pipe_evidence(self.BAD_BRANCH_COLL)
        assert direct["branch_collectives"] == 1
        assert direct["branch_collectives_free"] is False

        nested = pipe_evidence(self.BAD_BRANCH_COLL_NESTED)
        assert nested["branch_collectives"] == 1
        assert nested["branch_collectives_free"] is False

    def test_branch_collective_tripwire_warns(self):
        """check_overlap_expectations surfaces the deadlock shape as a
        named warning on pipelined configs — and stays quiet on GOOD."""
        from types import SimpleNamespace

        from pytorch_ddp_template_tpu.obs.hlo_report import (
            check_overlap_expectations, schedule_report,
        )

        cfg = SimpleNamespace(model="gpt-pipe-tiny", pipe_schedule="1f1b",
                              fsdp_overlap=False, ddp_overlap=True,
                              tp_overlap=False)
        axes = {"data": 2, "pipe": 2}
        warns = check_overlap_expectations(
            schedule_report(self.BAD_BRANCH_COLL), cfg, axes)
        assert any("branch_computations" in w for w in warns)
        ok = check_overlap_expectations(
            schedule_report(self.GOOD), cfg, axes)
        assert not any("branch_computations" in w for w in ok)

    def test_tripwire_gating(self):
        """check_overlap_expectations: the pipe check fires only for a
        pipelined model on a live pipe axis, and the zb dw check only
        under pipe_schedule=zb."""
        from types import SimpleNamespace

        from pytorch_ddp_template_tpu.obs.hlo_report import (
            check_overlap_expectations, schedule_report,
        )

        report = schedule_report(self.BAD)
        cfg = SimpleNamespace(model="gpt-pipe-tiny", pipe_schedule="zb",
                              fsdp_overlap=False, ddp_overlap=False,
                              tp_overlap=False)
        warns = check_overlap_expectations(report, cfg,
                                           {"data": 2, "pipe": 2})
        assert len(warns) == 2  # sends dependent + dw missing
        assert any("compute-independent" in w for w in warns)
        assert any("dx/dw split" in w for w in warns)
        # gated off: no pipe axis / non-pipe model / gpipe schedule
        assert check_overlap_expectations(report, cfg, {"data": 8}) == []
        cfg2 = SimpleNamespace(model="gpt-tiny", pipe_schedule="zb",
                               fsdp_overlap=False, ddp_overlap=False,
                               tp_overlap=False)
        assert check_overlap_expectations(
            report, cfg2, {"data": 2, "pipe": 2}) == []
        good = schedule_report(self.GOOD)
        cfg3 = SimpleNamespace(model="gpt-pipe-tiny", pipe_schedule="zb",
                               fsdp_overlap=False, ddp_overlap=False,
                               tp_overlap=False)
        assert check_overlap_expectations(good, cfg3,
                                          {"data": 2, "pipe": 2}) == []


@pytest.mark.slow  # full Trainer run with the fused zb schedule + the
# startup AOT compile for --hlo_report (~2 compiles of the fused loss)
def test_zb_trains_through_trainer_with_hlo_report(tmp_path):
    """THE r16 acceptance config: --model gpt-pipe-tiny --scan_layers
    --pipe_schedule zb --mesh data:2,pipe:2 trains end-to-end through
    the ordinary Trainer, and --hlo_report emits the pipe overlap check
    without tripping."""
    import json as _json
    import logging

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    # the acceptance spelling is --mesh data:2,pipe:2 on 4 devices; the
    # 8-virtual-device test harness carves the same pipe×data shape as
    # data:4,pipe:2 (config/engine size the mesh off jax.device_count())
    cfg = TrainingConfig(
        model="gpt-pipe-tiny", mesh="data:4,pipe:2", scan_layers=True,
        pipe_schedule="zb", per_device_train_batch_size=4,
        dataset_size=64, max_steps=2, logging_steps=0, save_steps=0,
        hlo_report=True, output_dir=str(tmp_path / "out"), resume=False,
        seed=0,
    )
    mesh = make_mesh(cfg.mesh, jax.devices())
    task, ds = build(cfg.model, cfg, mesh=mesh)
    key = jax.random.PRNGKey(cfg.seed)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=cfg)
    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    eng_log = logging.getLogger("pytorch_ddp_template_tpu.train.engine")
    handler = Capture()
    eng_log.addHandler(handler)
    try:
        t = Trainer(cfg, ctx, task, ds)
        final = t.train()
    finally:
        eng_log.removeHandler(handler)
    assert int(final.step) == 2
    report = _json.loads((tmp_path / "out" / "hlo_report.json").read_text())
    assert report["pipe"]["slot_bodies"] >= 1
    assert report["pipe"]["pipe_sends_independent"] is True
    assert report["pipe"]["dw_ops_present"] is True
    assert report["warnings"] == []
    tripped = [r for r in records
               if "schedule tripwire" in r.getMessage()]
    assert tripped == []
