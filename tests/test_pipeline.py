"""Pipeline-parallel mechanism proof (VERDICT.md round-3 weak #7: give
``PIPE_AXIS`` a mechanism or delete it). The GPipe fill/drain schedule over
``ppermute`` must reproduce plain sequential stage application exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)
from pytorch_ddp_template_tpu.runtime import make_mesh


def stage_fn(w, x):
    return jnp.tanh(x @ w["kernel"] + w["bias"])


def make_stage(rng, d):
    kw, kb = jax.random.split(rng)
    return {"kernel": jax.random.normal(kw, (d, d)) * 0.5,
            "bias": jax.random.normal(kb, (d,)) * 0.1}


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 3), (2, 1)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    d, mb = 8, 4
    mesh = make_mesh(f"pipe:{n_stages}", jax.devices()[:n_stages])
    rngs = jax.random.split(jax.random.PRNGKey(0), n_stages + 1)
    stages = [make_stage(rngs[i], d) for i in range(n_stages)]
    x = jax.random.normal(rngs[-1], (n_micro, mb, d))

    params = stack_stage_params(stages, mesh)
    got = pipeline_apply(params, stage_fn, x, mesh)

    want = x
    for w in stages:
        want = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_composes_with_data_axis():
    """pipe:2 alongside a data axis: the pipeline runs per data shard."""
    d, mb, n_micro = 8, 4, 2
    mesh = make_mesh("data:2,pipe:2", jax.devices()[:4])
    rngs = jax.random.split(jax.random.PRNGKey(1), 3)
    stages = [make_stage(rngs[i], d) for i in range(2)]
    x = jax.random.normal(rngs[-1], (n_micro, mb, d))

    params = stack_stage_params(stages, mesh)
    got = pipeline_apply(params, stage_fn, x, mesh)
    want = x
    for w in stages:
        want = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_stage_count_mismatch_refused():
    """4 stacked stages on a pipe:2 mesh would silently drop stages 1 and 3
    (each rank slices [0] of its 2-stage shard) — must raise instead."""
    d = 8
    mesh = make_mesh("pipe:2", jax.devices()[:2])
    rngs = jax.random.split(jax.random.PRNGKey(2), 5)
    stages = [make_stage(rngs[i], d) for i in range(4)]
    params = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    x = jax.random.normal(rngs[-1], (2, 4, d))
    with pytest.raises(ValueError, match="pipe axis"):
        pipeline_apply(params, stage_fn, x, mesh)


def test_gradients_flow_through_schedule():
    """The fill/drain loop has a static trip count (lowers to scan), so
    reverse-mode AD through the ppermute hops must reproduce sequential
    stage gradients — the pipeline is trainable, not just a fwd proof."""
    d = 4
    mesh = make_mesh("pipe:2", jax.devices()[:2])
    rngs = jax.random.split(jax.random.PRNGKey(3), 3)
    stages = [make_stage(rngs[i], d) for i in range(2)]
    x = jax.random.normal(rngs[-1], (3, 2, d))

    def loss_pipe(params):
        return jnp.sum(pipeline_apply(params, stage_fn, x, mesh) ** 2)

    def loss_seq(stage_list):
        y = x
        for w in stage_list:
            y = jax.vmap(lambda xb, w=w: stage_fn(w, xb))(y)
        return jnp.sum(y ** 2)

    g_pipe = jax.grad(loss_pipe)(stack_stage_params(stages, mesh))
    g_seq = jax.grad(loss_seq)(stages)
    for i in range(2):
        for key in ("kernel", "bias"):
            np.testing.assert_allclose(
                np.asarray(g_pipe[key][i]), np.asarray(g_seq[i][key]),
                rtol=1e-5, atol=1e-6,
            )


class TestPipelinedGptEntry:
    """gpt-pipe-tiny: the user-launchable PP path (VERDICT r4 weak #3)."""

    def _build(self, tmp_path, **overrides):
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models import build
        from pytorch_ddp_template_tpu.runtime.context import RuntimeContext

        defaults = dict(
            model="gpt-pipe-tiny", mesh="data:4,pipe:2",
            per_device_train_batch_size=2, dataset_size=128,
            max_steps=2, logging_steps=0, save_steps=0,
            output_dir=str(tmp_path / "out"), resume=False, seed=0,
        )
        defaults.update(overrides)
        cfg = TrainingConfig(**defaults)
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, ds = build(cfg.model, cfg, mesh=mesh)
        key = jax.random.PRNGKey(cfg.seed)
        ctx = RuntimeContext(mesh=mesh, seed_key=key,
                             host_key=jax.random.fold_in(key, 0), config=cfg)
        return cfg, ctx, task, ds

    @pytest.mark.slow  # ~14s of stage-stacked jits; the schedule-level
    # parity above and the clamp-warning tests below stay in tier-1
    def test_matches_sequential_blocks(self, tmp_path):
        """The pipelined forward must equal running the same block params
        sequentially (embed → layers in order → ln → tied head)."""
        import flax.linen as nn

        cfg, ctx, task, ds = self._build(tmp_path)
        batch = {"input_ids": np.asarray(
            np.random.default_rng(0).integers(0, 1024, (8, 128)), np.int32)}
        params, _ = task.init(jax.random.PRNGKey(1), batch)
        logits, _, _ = task._apply_inputs(
            nn.meta.unbox(params), {}, (jnp.asarray(batch["input_ids"]),),
            None, False)

        p = nn.meta.unbox(params)
        x = (p["wte"][batch["input_ids"]] + p["wpe"][None]).astype(task.dtype)
        blocks = p["blocks"]
        flat = jax.tree.map(
            lambda a: a.reshape(task.num_layers, *a.shape[2:]), blocks)
        for i in range(task.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], flat)
            x = task._block.apply({"params": layer}, x, None, train=False)
        h = task._ln.apply({"params": p["final_ln"]}, x.astype(jnp.float32))
        want = (h.astype(task.dtype) @ p["wte"].T.astype(task.dtype))
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(want, np.float32),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow  # ~39s whole-Trainer run; test_pipelined_entry_
    # composes_with_fsdp keeps a Trainer-level pipe step in tier-1
    def test_trains_through_trainer_with_stage_sharding(self, tmp_path):
        from pytorch_ddp_template_tpu.train.engine import Trainer

        cfg, ctx, task, ds = self._build(tmp_path)
        t = Trainer(cfg, ctx, task, ds)
        state, _ = t.restore_or_init()
        # stage stacks really live split over the pipe axis
        stage_leaves = jax.tree.leaves(state.params["blocks"])
        assert stage_leaves and all(
            "pipe" in str(x.sharding.spec) for x in stage_leaves)
        final = t.train()
        assert int(final.step) == 2

    def test_refuses_mesh_without_pipe_axis(self, tmp_path):
        """build() succeeds under a pipe-less mesh (dataset-only tooling
        like tools/make_file_dataset.py must keep working), but the task
        refuses at first use — before any training."""
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models import build

        cfg = TrainingConfig(model="gpt-pipe-tiny", mesh="data:8")
        task, ds = build(cfg.model, cfg)  # must not raise
        batch = {"input_ids": np.zeros((4, 128), np.int32)}
        with pytest.raises(ValueError, match="pipe axis"):
            task.init(jax.random.PRNGKey(0), batch)

    @pytest.mark.slow  # ~17s deep grad-parity sweep (long-tail; the
    # toy-stage grad test above pins the schedule's backward in tier-1)
    def test_gradients_match_sequential_with_data_axis(self, tmp_path):
        """pipe x data composition: with the microbatch dim sharded over
        ``data``, gradients of the pipelined loss must still equal the
        sequential-stack reference."""
        import flax.linen as nn

        cfg, ctx, task, ds = self._build(tmp_path)
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, 1024, (8, 128)), jnp.int32)
        params, _ = task.init(jax.random.PRNGKey(2), batch={"input_ids": ids})
        params = nn.meta.unbox(params)

        def loss_pipe(p):
            logits, _, _ = task._apply_inputs(p, {}, (ids,), None, False)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        def loss_seq(p):
            x = (p["wte"][ids] + p["wpe"][None]).astype(task.dtype)
            flat = jax.tree.map(
                lambda a: a.reshape(task.num_layers, *a.shape[2:]),
                p["blocks"])
            for i in range(task.num_layers):
                layer = jax.tree.map(lambda a, i=i: a[i], flat)
                x = task._block.apply({"params": layer}, x, None, train=False)
            h = task._ln.apply({"params": p["final_ln"]},
                               x.astype(jnp.float32))
            logits = h.astype(task.dtype) @ p["wte"].T.astype(task.dtype)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
        g_seq = jax.jit(jax.grad(loss_seq))(params)
        flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
        flat_s = jax.tree.leaves(g_seq)
        assert len(flat_p) == len(flat_s)
        for (path, a), b in zip(flat_p, flat_s):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=str(path))


@pytest.mark.slow  # ~20s two-Trainer save/resume cycle; generic resume is
# tier-1-covered by test_fault_recovery on the dense entries
def test_pipelined_entry_checkpoint_resume(tmp_path):
    """The stacked (pipe-sharded, Partitioned-annotated) stage params must
    survive an orbax save/restore and continue training — the stacked
    layout is unlike every other zoo entry's tree."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    def make(max_steps):
        cfg = TrainingConfig(
            model="gpt-pipe-tiny", mesh="data:4,pipe:2",
            per_device_train_batch_size=2, dataset_size=128,
            max_steps=max_steps, logging_steps=0, save_steps=2,
            output_dir=str(tmp_path / "out"), seed=0,
            pipe_microbatches=2,
        )
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, ds = build(cfg.model, cfg, mesh=mesh)
        key = jax.random.PRNGKey(cfg.seed)
        ctx = RuntimeContext(mesh=mesh, seed_key=key,
                             host_key=jax.random.fold_in(key, 0), config=cfg)
        return Trainer(cfg, ctx, task, ds)

    t = make(2)
    final = t.train()
    assert t.ckpt.latest_step() == 2

    t2 = make(4)
    state, start = t2.restore_or_init()
    assert start == 2
    # restored stage stacks are bit-identical and still pipe-sharded
    a = jax.tree.leaves(final.params["blocks"])[0]
    b = jax.tree.leaves(state.params["blocks"])[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert "pipe" in str(b.sharding.spec)
    final2 = t2.train()
    assert int(final2.step) == 4


def test_pipelined_entry_composes_with_fsdp(tmp_path):
    """--fsdp on the pipelined entry: stage stacks stay pipe-sharded AND
    gain a data split (ZeRO-3 over the replicas), and training still
    steps. Loss parity with non-fsdp is covered generically for the other
    families; here the composition itself is the test."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        model="gpt-pipe-tiny", mesh="data:4,pipe:2", fsdp=True,
        per_device_train_batch_size=4, dataset_size=128, max_steps=2,
        logging_steps=0, save_steps=0, output_dir=str(tmp_path / "out"),
        resume=False, seed=0,
    )
    mesh = make_mesh(cfg.mesh, jax.devices())
    task, ds = build(cfg.model, cfg, mesh=mesh)
    key = jax.random.PRNGKey(cfg.seed)
    ctx = RuntimeContext(mesh=mesh, seed_key=key,
                         host_key=jax.random.fold_in(key, 0), config=cfg)
    t = Trainer(cfg, ctx, task, ds)
    state, _ = t.restore_or_init()
    specs = [str(x.sharding.spec) for x in
             jax.tree.leaves(state.params["blocks"])]
    assert all("pipe" in s for s in specs)
    assert any("data" in s for s in specs)  # the ZeRO-3 split landed
    state, metrics = t.train_step(state, next(iter(t.loader.epoch(0))))
    assert np.isfinite(float(metrics["loss"]))


class TestMicrobatchClampWarning:
    """The r6 microbatch-clamp warning (models/gpt_pipe.py): a coprime
    --pipe_microbatches / per-replica-batch pair silently serialises the
    pipeline, so the task must say so — once — at trace time, and stay
    silent when the count divides."""

    def _records_of(self, n_micro, batch):
        import logging

        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.models import build

        cfg = TrainingConfig(model="gpt-pipe-tiny", mesh="data:4,pipe:2",
                             pipe_microbatches=n_micro)
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, _ = build(cfg.model, cfg, mesh=mesh)
        params, _ = task.init(jax.random.PRNGKey(0), batch)
        # the module logger does not propagate (utils/logging.py), so
        # capture with a handler attached directly to it
        records: list[logging.LogRecord] = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        log = logging.getLogger("pytorch_ddp_template_tpu.models.gpt_pipe")
        handler = Capture()
        log.addHandler(handler)
        try:
            import flax.linen as nn

            for _ in range(2):  # twice: the warning must fire ONCE
                task._apply_inputs(nn.meta.unbox(params), {},
                                   (jnp.asarray(batch["input_ids"]),),
                                   None, False)
        finally:
            log.removeHandler(handler)
        return [r for r in records if "clamped" in r.getMessage()]

    def test_warns_once_when_coprime(self):
        # per-replica batch = 8/4 = 2; gcd(3, 2) = 1 < 3 -> clamped
        batch = {"input_ids": np.zeros((8, 128), np.int32)}
        warned = self._records_of(3, batch)
        assert len(warned) == 1
        assert warned[0].levelname == "WARNING"

    def test_silent_when_dividing(self):
        # gcd(2, 2) = 2 == requested -> no clamp, no warning
        batch = {"input_ids": np.zeros((8, 128), np.int32)}
        assert self._records_of(2, batch) == []
