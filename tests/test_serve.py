"""Serving engine (r19): paged KV allocator, gather-KV decode attention
(xla + pallas-interpret parity), continuous batching, the compile-cache
pin, the checkpoint→serving seam, and the obs wiring.

The acceptance anchors: greedy decode through the engine matches an
unbatched reference forward loop token-for-token (single-device AND
model-sharded), sequence growth across block boundaries triggers zero
decode recompiles, and ``/metrics`` serves live ``tpuddp_serve_*``
gauges while the engine runs.
"""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.linen as nn

from pytorch_ddp_template_tpu.models.gpt import GptDecoder, gpt_tiny
from pytorch_ddp_template_tpu.serve import (
    ContinuousScheduler, PagedKVCache, ServeConfig, ServeEngine,
)
from pytorch_ddp_template_tpu.serve.decode_ops import (
    _paged_attention_pallas, _paged_attention_xla,
)
from pytorch_ddp_template_tpu.serve.kv_cache import NULL_BLOCK

VOCAB = 256


@pytest.fixture(scope="module")
def tiny():
    """(model, unboxed params, fused-head twin) — one init per module."""
    model = gpt_tiny(vocab_size=VOCAB, seq_len=128)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
        train=False)["params"])
    fused = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=2,
                       num_heads=2, head_dim=32, mlp_dim=128,
                       fused_head=True)
    return model, params, fused


def ref_generate(fused, params, prompt, n):
    """The unbatched reference loop: full forward per token, dense
    logits, argmax — what the engine must reproduce token-for-token."""
    toks = list(prompt)
    out = []
    for _ in range(n):
        h = fused.apply({"params": params}, jnp.asarray([toks]),
                        train=False)
        logits = h[0, -1] @ params["wte"]["embedding"].T
        tok = int(jnp.argmax(logits))
        toks.append(tok)
        out.append(tok)
    return out


def make_engine(model, params, **overrides):
    cfg = dict(block_size=4, num_blocks=64, max_slots=3, max_model_len=64)
    cfg.update(overrides)
    return ServeEngine(model, params, ServeConfig(**cfg))


# -- the allocator ---------------------------------------------------------

class TestPagedKVCache:
    def kv(self, **kw):
        base = dict(num_layers=2, num_heads=2, head_dim=8, num_blocks=8,
                    block_size=4)
        base.update(kw)
        return PagedKVCache(**base)

    def test_alloc_free_reuse(self):
        kv = self.kv()
        a = kv.alloc(1, 10)          # 3 blocks
        assert len(a) == 3 and NULL_BLOCK not in a
        assert kv.free_blocks() == 4
        assert kv.free(1) == 3
        assert kv.free_blocks() == 7
        b = kv.alloc(2, 26)          # 7 blocks — the freed ones reused
        assert len(b) == 7 and set(a) <= set(b)

    def test_oom_refused_named(self):
        kv = self.kv()
        kv.alloc(1, 20)  # 5 of 7
        assert not kv.can_alloc(12)
        with pytest.raises(ValueError, match="exhausted"):
            kv.alloc(2, 12)
        kv.alloc(2, 8)  # 2 blocks still fit

    def test_append_crosses_boundary_lazily(self):
        kv = self.kv()
        kv.alloc(1, 4)  # exactly one full block
        assert kv.blocks_used() == 1
        blk, off = kv.append_slot(1)   # position 4 -> NEW block, offset 0
        assert off == 0 and kv.blocks_used() == 2
        blk2, off2 = kv.append_slot(1)  # position 5 -> same block
        assert (blk2, off2) == (blk, 1)
        assert kv.seq_len(1) == 6

    def test_frag_accounting(self):
        kv = self.kv()
        kv.alloc(1, 5)  # 2 blocks, 3 slack slots
        kv.alloc(2, 4)  # 1 block, 0 slack
        st = kv.stats()
        assert st["frag_slots"] == 3
        assert st["blocks_used"] == 3
        assert st["high_water_blocks"] == 3
        assert st["alloc_count"] == 3
        kv.free(1)
        assert kv.stats()["free_count"] == 2
        assert kv.stats()["high_water_blocks"] == 3  # high water sticks

    def test_padded_table_null_blocks(self):
        kv = self.kv()
        kv.alloc(7, 6)
        row = kv.padded_table(7, 5)
        assert row.shape == (5,) and list(row[2:]) == [NULL_BLOCK] * 3

    def test_null_block_reserved(self):
        kv = self.kv(num_blocks=3)
        a = kv.alloc(1, 8)
        assert NULL_BLOCK not in a
        with pytest.raises(ValueError):
            kv.alloc(2, 1)  # pool truly drained: null block never handed out

    def test_int8_bytes_per_token(self):
        f32 = self.kv().bytes_per_token()
        i8 = self.kv(kv_quant="int8").bytes_per_token()
        # the capacity lever: >= 2x more resident tokens per byte
        assert f32 / i8 >= 2.0


# -- the gather-KV attention path ------------------------------------------

class TestPagedAttention:
    def setup_method(self):
        rng = np.random.RandomState(0)
        self.q = jnp.asarray(rng.randn(3, 2, 32).astype(np.float32))
        self.kp = jnp.asarray(rng.randn(10, 4, 2, 32).astype(np.float32))
        self.vp = jnp.asarray(rng.randn(10, 4, 2, 32).astype(np.float32))
        self.tables = jnp.asarray(
            np.array([[3, 7, 2, 0], [5, 1, 0, 0], [9, 4, 6, 8]], np.int32))
        self.lens = jnp.asarray(np.array([11, 5, 16], np.int32))

    def test_xla_matches_dense_reference(self):
        from pytorch_ddp_template_tpu.ops.attention import (
            dot_product_attention,
        )

        out = _paged_attention_xla(self.q, self.kp, self.vp, self.tables,
                                   self.lens)
        for s in range(3):
            ctx = int(self.lens[s])
            blocks = [int(b) for b in self.tables[s]][: -(-ctx // 4)]
            k = jnp.concatenate([self.kp[b] for b in blocks], 0)[:ctx][None]
            v = jnp.concatenate([self.vp[b] for b in blocks], 0)[:ctx][None]
            ref = dot_product_attention(self.q[s][None, None], k, v)[0, 0]
            np.testing.assert_allclose(np.asarray(out[s]), np.asarray(ref),
                                       atol=1e-5)

    def test_pallas_interpret_matches_xla(self):
        out_x = _paged_attention_xla(self.q, self.kp, self.vp,
                                     self.tables, self.lens)
        out_p = _paged_attention_pallas(self.q, self.kp, self.vp,
                                        self.tables, self.lens)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                                   atol=1e-5)

    def test_inactive_slot_zero_and_finite(self):
        lens = self.lens.at[1].set(0)
        for fn in (_paged_attention_xla, _paged_attention_pallas):
            out = np.asarray(fn(self.q, self.kp, self.vp, self.tables,
                                lens))
            assert np.all(np.isfinite(out))
            assert np.all(out[1] == 0.0)

    def test_int8_pool_within_roundtrip_bound(self):
        from pytorch_ddp_template_tpu.serve.kv_cache import quantize_kv

        kq, ks = quantize_kv(self.kp)
        vq, vs = quantize_kv(self.vp)
        ref = _paged_attention_xla(self.q, self.kp, self.vp, self.tables,
                                   self.lens)
        got = _paged_attention_xla(self.q, kq, vq, self.tables, self.lens,
                                   k_scale=ks, v_scale=vs)
        # int8 KV error stays small (values O(1), per-head scales)
        assert float(jnp.abs(got - ref).max()) < 0.05

    def test_pallas_refuses_int8(self, monkeypatch):
        from pytorch_ddp_template_tpu.serve import decode_ops

        monkeypatch.setenv("PAGED_IMPL", "pallas")
        with pytest.raises(ValueError, match="int8"):
            decode_ops.paged_attention(
                self.q, self.kp, self.vp, self.tables, self.lens,
                k_scale=jnp.ones((10, 4, 2, 1)),
                v_scale=jnp.ones((10, 4, 2, 1)))

    def test_typod_impl_fails_loudly(self, monkeypatch):
        from pytorch_ddp_template_tpu.serve import decode_ops

        monkeypatch.setenv("PAGED_IMPL", "cuda")
        with pytest.raises(ValueError, match="PAGED_IMPL"):
            decode_ops.paged_impl()


# -- the scheduler ---------------------------------------------------------

class TestScheduler:
    def test_fcfs_admission_and_eviction(self):
        s = ContinuousScheduler(2)
        r1 = s.submit([1], 4)
        r2 = s.submit([2], 4)
        r3 = s.submit([3], 4)
        admitted = s.admit(lambda r: True)
        assert [r.id for r in admitted] == [r1.id, r2.id]
        assert s.queue_depth() == 1 and s.active() == 2
        s.finish(r1)
        assert s.active() == 1
        # the freed slot refills the same iteration — the continuous move
        assert [r.id for r in s.admit(lambda r: True)] == [r3.id]

    def test_capacity_gate_blocks_head(self):
        s = ContinuousScheduler(4)
        s.submit([1] * 10, 4)
        s.submit([2], 4)
        # head too big -> FCFS blocks the queue (no reorder)
        assert s.admit(lambda r: len(r.prompt) < 5) == []

    def test_static_batch_waves(self):
        s = ContinuousScheduler(2, static_batch=True)
        r1, r2, r3 = (s.submit([i], 2) for i in range(3))
        assert len(s.admit(lambda r: True)) == 2
        s.finish(r1)
        # static: a half-empty engine admits nothing until DRAINED
        assert s.admit(lambda r: True) == []
        s.finish(r2)
        assert [r.id for r in s.admit(lambda r: True)] == [r3.id]


# -- the engine ------------------------------------------------------------

class TestServeEngine:
    def test_greedy_matches_reference_loop(self, tiny):
        model, params, fused = tiny
        eng = make_engine(model, params)
        prompts = [[5, 9, 2, 77, 31, 8, 200, 3], [1, 2, 3],
                   [40, 41, 42, 43, 44, 45, 46, 47, 48, 49, 50]]
        lens = (10, 6, 12)
        reqs = [eng.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, lens)]
        out = eng.run()
        for p, r, n in zip(prompts, reqs, lens):
            assert out[r.id] == ref_generate(fused, params, p, n)

    def test_greedy_matches_model_sharded(self, tiny):
        model, params, fused = tiny
        devs = jax.devices()
        mesh = jax.sharding.Mesh(
            np.array(devs[:2]).reshape(1, 2), ("data", "model"))
        eng = ServeEngine(
            model, params,
            ServeConfig(block_size=4, num_blocks=64, max_slots=2,
                        max_model_len=64),
            mesh=mesh)
        prompts = [[7, 8, 9, 10, 11], [100, 101]]
        reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        out = eng.run()
        for p, r in zip(prompts, reqs):
            assert out[r.id] == ref_generate(fused, params, p, 8)

    def test_continuous_join_evict_and_drain(self, tiny):
        model, params, _ = tiny
        eng = make_engine(model, params, max_slots=2)
        reqs = [eng.submit([i + 1, i + 2], max_new_tokens=2 + (i % 3))
                for i in range(7)]  # more requests than slots
        out = eng.run()
        assert sorted(out) == sorted(r.id for r in reqs)
        assert all(len(out[r.id]) == 2 + (i % 3)
                   for i, r in enumerate(reqs))
        st = eng.kv.stats()
        assert st["blocks_used"] == 0 and st["tokens_resident"] == 0
        assert eng._committed == {}
        assert eng.scheduler.idle()

    def test_capacity_aware_admission_never_ooms(self, tiny):
        model, params, _ = tiny
        # pool sized so the committed-blocks budget must queue requests
        eng = make_engine(model, params, num_blocks=9, max_slots=3)
        reqs = [eng.submit([1, 2, 3, 4], max_new_tokens=12)
                for _ in range(5)]  # each commits 4 blocks; budget is 8
        out = eng.run()
        assert sorted(out) == sorted(r.id for r in reqs)
        assert all(len(v) == 12 for v in out.values())

    def test_submit_refusals_named(self, tiny):
        model, params, _ = tiny
        eng = make_engine(model, params)
        with pytest.raises(ValueError, match="empty"):
            eng.submit([])
        with pytest.raises(ValueError, match="max_model_len"):
            eng.submit([1] * 60, max_new_tokens=10)

    def test_never_fitting_request_refused_at_submit(self, tiny):
        # FCFS: an unadmittable request at the queue head would starve
        # everything behind it — refuse when it can NEVER fit the pool
        model, params, _ = tiny
        eng = make_engine(model, params, num_blocks=5)
        with pytest.raises(ValueError, match="KV blocks"):
            eng.submit([1, 2, 3], max_new_tokens=30)

    def test_geometry_refusals_named(self, tiny):
        model, params, _ = tiny
        with pytest.raises(ValueError, match="multiple of block_size"):
            make_engine(model, params, block_size=7, max_model_len=64)

    def test_model_refusals_named(self, tiny):
        _, params, _ = tiny
        moe = GptDecoder(vocab_size=VOCAB, max_len=128, num_layers=2,
                         num_heads=2, head_dim=32, mlp_dim=128,
                         moe_experts=4)
        with pytest.raises(ValueError, match="moe_experts"):
            ServeEngine(moe, params, ServeConfig())

    def test_eos_early_stop(self, tiny):
        model, params, fused = tiny
        ref = ref_generate(fused, params, [5, 6, 7], 8)
        eos = ref[2]  # the third generated token, whatever it is
        eng = make_engine(model, params, eos_id=eos)
        r = eng.submit([5, 6, 7], max_new_tokens=8)
        out = eng.run()
        assert out[r.id] == ref[:3]  # stopped AT the eos token

    def test_kv_quant_int8_runs_and_meters(self, tiny):
        model, params, _ = tiny
        eng = make_engine(model, params, kv_quant="int8")
        r = eng.submit([3, 1, 4, 1, 5], max_new_tokens=6)
        out = eng.run()
        assert len(out[r.id]) == 6
        assert all(0 <= t < VOCAB for t in out[r.id])
        assert eng.kv.stats()["kv_quant"] == "int8"


class TestCompileCachePin:
    def test_zero_decode_recompiles_across_block_boundaries(self, tiny):
        """THE serving perf pin: block_size 4 and 20 generated tokens
        force every sequence across multiple block boundaries; the
        decode cache must still hold exactly ONE program, and a second
        batch of different-length sequences must not add any."""
        model, params, _ = tiny
        eng = make_engine(model, params)
        eng.submit([1, 2, 3], max_new_tokens=20)
        eng.submit([4, 5, 6, 7, 8], max_new_tokens=17)
        eng.run()
        assert eng.decode_programs() == 1
        eng.submit([9] * 11, max_new_tokens=9)
        eng.run()
        assert eng.decode_programs() == 1
        # prefill: one program per touched bucket, not per prompt length
        assert eng.prefill_programs() <= len(eng._buckets)


# -- the checkpoint -> serving seam ----------------------------------------

class TestCheckpointSeam:
    @pytest.mark.parametrize("layout", ["unrolled", "scanned"])
    def test_training_checkpoint_serves_bit_parity(self, tiny, tmp_path,
                                                   layout):
        """A training checkpoint (either layer layout) restores into
        the serving template through restore_raw + the r18 converter,
        and the serving prefill is BIT-identical to the flax apply."""
        from pytorch_ddp_template_tpu.checkpoint.manager import (
            CheckpointManager,
        )
        from pytorch_ddp_template_tpu.config import TrainingConfig
        from pytorch_ddp_template_tpu.parallel.stacking import (
            restack_layer_trees,
        )
        from pytorch_ddp_template_tpu.serve.model import prefill_forward

        model, params, fused = tiny
        save_params = (params if layout == "unrolled"
                       else restack_layer_trees(params))
        state = {"step": jnp.int32(7), "params": save_params,
                 "rng": jax.random.PRNGKey(1)}
        cfg = TrainingConfig(model="gpt-tiny",
                             output_dir=str(tmp_path / "out"))
        mngr = CheckpointManager(tmp_path / "ckpt")
        mngr.save(7, state, cfg, force=True)
        mngr.wait()
        mngr.close()

        eng = ServeEngine.from_checkpoint(
            tmp_path / "ckpt", model,
            ServeConfig(block_size=4, num_blocks=32, max_slots=2,
                        max_model_len=64))
        prompt = jnp.asarray([[5, 9, 2, 77, 31, 8, 200, 3]], jnp.int32)
        ref = fused.apply({"params": params}, prompt, train=False)
        got, _, _ = prefill_forward(eng.params, prompt,
                                    dtype=model.dtype,
                                    attn_impl=model.attn_impl)
        assert np.array_equal(np.asarray(ref), np.asarray(got))
        # and it actually serves
        r = eng.submit([5, 9, 2], max_new_tokens=4)
        assert len(eng.run()[r.id]) == 4

    def test_paramless_checkpoint_refused(self, tiny, tmp_path):
        from pytorch_ddp_template_tpu.checkpoint.manager import (
            CheckpointManager,
        )
        from pytorch_ddp_template_tpu.config import TrainingConfig

        model, _, _ = tiny
        mngr = CheckpointManager(tmp_path / "ckpt")
        mngr.save(1, {"step": jnp.int32(1)},
                  TrainingConfig(model="gpt-tiny",
                                 output_dir=str(tmp_path / "o")),
                  force=True)
        mngr.wait()
        mngr.close()
        with pytest.raises(ValueError, match="params"):
            ServeEngine.from_checkpoint(tmp_path / "ckpt", model,
                                        ServeConfig())


# -- obs wiring ------------------------------------------------------------

class TestServeObs:
    def test_metrics_gauges_and_status_live(self, tiny):
        from pytorch_ddp_template_tpu.obs.server import StatusServer

        model, params, _ = tiny
        status = StatusServer(0)
        status.start()
        try:
            eng = ServeEngine(
                model, params,
                ServeConfig(block_size=4, num_blocks=32, max_slots=2,
                            max_model_len=64),
                status=status)
            eng.submit([1, 2, 3, 4], max_new_tokens=5)
            eng.run()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status.port}/metrics",
                    timeout=10) as resp:
                text = resp.read().decode()
            assert "tpuddp_serve_tokens_per_sec" in text
            assert "tpuddp_serve_queue_depth" in text
            assert "tpuddp_serve_blocks_free" in text
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status.port}/status",
                    timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["records"]["serve"]["serve_finished_total"] == 1
            assert doc["serve"]["config"]["block_size"] == 4
        finally:
            status.close()

    def test_goodput_serve_buckets(self, tiny, tmp_path):
        from pytorch_ddp_template_tpu.obs.goodput import (
            BUCKETS, GoodputLedger,
        )

        assert "serve_prefill" in BUCKETS and "serve_decode" in BUCKETS
        model, params, _ = tiny
        ledger = GoodputLedger(tmp_path)
        eng = ServeEngine(
            model, params,
            ServeConfig(block_size=4, num_blocks=32, max_slots=2,
                        max_model_len=64),
            goodput=ledger)
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run()
        tot = ledger.totals()
        assert tot["serve_prefill"] > 0.0
        assert tot["serve_decode"] > 0.0
        ledger.flush()
        doc = json.loads((tmp_path / "goodput.json").read_text())
        assert doc["buckets"]["serve_decode"] > 0.0


# -- the committed BENCH_MODE=serve record ---------------------------------

def test_serve_record_committed_and_affirmative():
    """The committed round-19 record must carry the acceptance
    evidence: continuous batching >= 1.5x static tokens/sec at mixed
    lengths (FLOPs-matched), TTFT and per-token latency recorded, the
    zero-recompile compile-cache pin, and the live-gauges proof."""
    import pathlib

    path = (pathlib.Path(__file__).resolve().parents[1]
            / "bench_records" / "serve_cpu_r19.jsonl")
    assert path.is_file(), "run BENCH_MODE=serve to record the legs"
    rows = [json.loads(s) for s in path.read_text().splitlines() if s]
    head = rows[0]
    assert head["metric"] == "serve_continuous_vs_static"
    assert head["value"] >= 1.5 and head["vs_baseline"] >= 1.0
    assert not head.get("kv_quant")  # the headline is the honest config
    assert head["decode_zero_recompile"] is True
    assert head["decode_programs"] == 1
    assert head["ttft_ms_mean"] > 0 and head["per_token_ms_mean"] > 0
    assert head["tokens_per_sec_per_chip"] > 0
    assert head["metrics_gauges_live"] is True
    assert head["goodput_serve_decode_s"] > 0
    assert head["paged_pallas_parity_max_abs"] < 1e-4
    # the int8 KV ablation row: marked, and carrying the capacity win
    quant = [r for r in rows if r.get("kv_quant") == "int8"]
    assert quant, "int8 KV ablation row missing"
    assert quant[0]["kv_bytes_per_token"] < head["kv_bytes_per_token"] / 2
