"""Smoke tests for the two driver entry points (``__graft_entry__.py``,
``bench.py``) — round 1's only untested files were exactly the two the
driver executes, and both failed there. These run the real code paths on
the CPU harness so regressions surface in CI, not in driver artifacts."""

import json
import os
import subprocess
import sys
import time

import jax
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def test_entry_jits_and_runs():
    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (16, 1000)  # resnet50 logits


@pytest.mark.slow  # 8-device compile; /verify drives the hook directly
def test_dryrun_multichip_8_devices_under_budget():
    import __graft_entry__ as graft

    t0 = time.time()
    graft.dryrun_multichip(8)  # raises/asserts on any failure
    elapsed = time.time() - t0
    # driver timeout budgets are tight under contention; the smoke must
    # stay well clear (runs ~15-20s on one idle CPU core)
    assert elapsed < 90, f"dryrun took {elapsed:.0f}s — too close to timeout"


def _run_bench(env_overrides: dict) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.update(
        BENCH_CPU="1", BENCH_MODEL="mlp-wide", BENCH_WARMUP="1",
        BENCH_STEPS="2", **env_overrides,
    )
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        capture_output=True, text=True, env=env, timeout=180,
    )


@pytest.mark.slow  # bench subprocess; the per-mode contract tests stay tier-1
def test_bench_main_prints_valid_json_on_cpu():
    proc = _run_bench({})
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["metric"] == "mlp_wide_examples_per_sec_per_chip"
    assert payload["value"] > 0
    assert payload["unit"] == "examples/sec/chip"
    assert payload["vs_baseline"] > 0
    assert payload["platform"] == "cpu"


def test_bench_flash_mode_parity_json():
    # interpret-mode Pallas on tiny shapes: numerics vs XLA must agree or
    # the mode raises (and the JSON contract reports it)
    proc = _run_bench({"BENCH_MODE": "flash"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["metric"].startswith("flash_attn_speedup")
    assert payload["full_max_err"] < 2e-4
    assert payload["causal_max_err"] < 2e-4


def test_bench_scaling_mode_sweeps_submeshes():
    proc = _run_bench({
        "BENCH_MODE": "scaling", "BENCH_CPU_DEVICES": "4",
        "BENCH_BATCH": "256",
    })
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "scaling_efficiency_4chips"
    assert [s["n_devices"] for s in payload["sweep"]] == [1, 2, 4]
    assert all(s["per_chip"] > 0 for s in payload["sweep"])


def test_bench_emits_json_line_even_on_hard_failure():
    # a nonsense batch size fails inside run_bench; the driver contract is
    # one parseable JSON line (value 0 + error), rc != 0, no bare traceback
    # as the only output
    proc = _run_bench({"BENCH_BATCH": "-4"})
    assert proc.returncode != 0
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["value"] == 0.0
    assert payload["vs_baseline"] == 0.0
    assert "error" in payload
