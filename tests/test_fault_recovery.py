"""Failure-recovery rehearsal (SURVEY.md §5.3): a training process dies
hard mid-run (``os._exit`` right after a checkpoint lands — no atexit, no
final save), is restarted, and must converge to the exact final state an
uninterrupted run produces — checkpoints + deterministic (seed, step) data
order are the whole recovery story. (The reference's checkpoints could not
even be loaded: ``/root/reference/ddp.py:293`` vs ``:206``.)

Runs in 1-device subprocesses: determinism must come from keying, not luck
in collective scheduling."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
try:  # jax >= 0.5 spelling; older jax defaults to 1 CPU device anyway
    jax.config.update("jax_num_cpu_devices", 1)
except AttributeError:
    pass
import json, os
import numpy as np

crash_at = {crash_at}
if crash_at is not None:
    # die HARD right after checkpoint `crash_at` is durably on disk —
    # simulates a mid-run crash with no clean teardown
    from pytorch_ddp_template_tpu.checkpoint import manager as mgr
    _orig = mgr.CheckpointManager.save
    def save_then_die(self, step, state, config, *, force=False):
        _orig(self, step, state, config, force=force)
        self.wait()
        if step == crash_at:
            os._exit(9)
    mgr.CheckpointManager.save = save_then_die

import ddp
code = ddp.main([
    "--model", "mlp", "--mesh", "data:1",
    "--per_device_train_batch_size", "8", "--dataset_size", "256",
    "--max_steps", "24", "--save_steps", "6", "--logging_steps", "0",
    "--seed", "7", "--learning_rate", "0.01",
    "--output_dir", {outdir!r},
])
assert code == 0

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init
from pytorch_ddp_template_tpu.train import Trainer
cfg = TrainingConfig(output_dir={outdir!r}, model="mlp", mesh="data:1",
                     per_device_train_batch_size=8, dataset_size=256, seed=7)
ctx = init(cfg)
task, ds = build("mlp", cfg)
t = Trainer(cfg, ctx, task, ds)
state, step = t.restore_or_init()
leaves = [np.asarray(x).ravel() for x in jax.tree.leaves(jax.device_get(state.params))]
print("FINGERPRINT", json.dumps({{"step": step,
      "digest": [float(np.sum(v)) for v in leaves]}}))
"""


def _run(outdir: Path, crash_at: int | None = None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = str(REPO)
    p = subprocess.run(
        [sys.executable, "-u", "-c",
         SCRIPT.format(crash_at=crash_at, outdir=str(outdir))],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    if crash_at is not None:
        assert p.returncode == 9, f"expected hard crash:\n{p.stdout[-3000:]}"
        return None
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    for line in p.stdout.splitlines():
        if line.startswith("FINGERPRINT "):
            return json.loads(line[len("FINGERPRINT "):])
    raise AssertionError(f"no fingerprint in output:\n{p.stdout[-2000:]}")


@pytest.mark.slow  # three full CLI subprocesses (~107s): the heaviest
#                    single tier-1 entry, moved to the slow set in r10 to
#                    keep the grown suite inside the 870s budget (the r8/
#                    r9 convention); `pytest tests/` still runs it
def test_crashed_run_resumes_to_identical_state(tmp_path):
    baseline_dir = tmp_path / "uninterrupted"
    crashed_dir = tmp_path / "crashed"
    baseline_dir.mkdir()
    crashed_dir.mkdir()

    baseline = _run(baseline_dir)
    assert baseline["step"] == 24

    assert _run(crashed_dir, crash_at=12) is None  # really died (exit 9)
    ckpts = sorted(int(d.name.split("_")[1])
                   for d in crashed_dir.glob("checkpoint_*"))
    assert ckpts == [6, 12], ckpts  # died after 12; 18/24 never happened

    resumed = _run(crashed_dir)
    assert resumed["step"] == 24
    np.testing.assert_allclose(resumed["digest"], baseline["digest"],
                               rtol=1e-6, atol=1e-8)
