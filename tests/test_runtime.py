"""Runtime/mesh tests on the 8-virtual-device CPU backend (SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.runtime import (
    DATA_AXIS,
    init,
    make_mesh,
    parse_mesh_spec,
)


def test_parse_mesh_spec_wildcard():
    assert parse_mesh_spec("data:-1", 8) == {"data": 8}
    assert parse_mesh_spec("data:-1,model:2", 8) == {"data": 4, "model": 2}
    assert parse_mesh_spec("data:2,model:2,seq:2", 8) == {"data": 2, "model": 2, "seq": 2}


def test_parse_mesh_spec_errors():
    with pytest.raises(ValueError):
        parse_mesh_spec("data:3", 8)  # wrong product
    with pytest.raises(ValueError):
        parse_mesh_spec("data:-1,model:-1", 8)  # two wildcards
    with pytest.raises(ValueError):
        parse_mesh_spec("data:-1,model:3", 8)  # non-dividing


def test_make_mesh_shapes(devices):
    mesh = make_mesh("data:4,model:2")
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (4, 2)
    assert mesh.devices.size == len(devices)


def test_init_returns_context(devices):
    ctx = init(TrainingConfig(mesh="data:-1", seed=123))
    assert ctx.n_devices == 8
    assert ctx.mesh.axis_names == (DATA_AXIS,)
    # shared init key equal on every "host"; host key folded
    assert not np.array_equal(
        jax.random.key_data(ctx.seed_key), jax.random.key_data(ctx.host_key)
    ) or jax.process_index() != 0 or True  # fold_in(0) still changes the key
    assert ctx.config.seed == 123


def test_data_sharding_places_batch(devices):
    ctx = init(TrainingConfig())
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = jax.device_put(x, ctx.data_sharding(None))
    assert arr.sharding.spec == jax.sharding.PartitionSpec("data", None)
    # each device holds 16/8 = 2 rows
    shard = arr.addressable_shards[0]
    assert shard.data.shape == (2, 3)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_seed_determinism():
    ctx1 = init(TrainingConfig(seed=7))
    ctx2 = init(TrainingConfig(seed=7))
    assert np.array_equal(
        jax.random.key_data(ctx1.seed_key), jax.random.key_data(ctx2.seed_key)
    )
