"""Parallelism tests on the 8-virtual-device CPU mesh (SURVEY.md §4):
ring attention exactness, tensor-parallel numerical parity with the
replicated baseline, context-parallel end-to-end training, and the
distributed-semantics invariant (sharded grads == single-device grads).

The reference's parallel surface is DDP only (SURVEY.md §2b); these cover
the axes the TPU framework adds (model, seq) plus the DDP equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.ops.attention import dot_product_attention
from pytorch_ddp_template_tpu.parallel import (
    active_rules,
    describe,
    logical_shardings,
    ring_attention,
    shard_tree,
    ulysses_attention,
)
from pytorch_ddp_template_tpu.runtime import make_mesh
from pytorch_ddp_template_tpu.runtime.context import RuntimeContext


def _ctx(mesh, config):
    key = jax.random.PRNGKey(config.seed)
    return RuntimeContext(mesh=mesh, seed_key=key,
                          host_key=jax.random.fold_in(key, 0), config=config)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(causal):
    mesh = make_mesh("data:2,seq:4", jax.devices())
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 32, 2, 16)), jnp.float32)
        for _ in range(3)
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(ref, out, atol=2e-5)


def test_ring_attention_grads_exact():
    mesh = make_mesh("data:2,seq:4", jax.devices())
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 32, 2, 16)), jnp.float32)
        for _ in range(3)
    )
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ring = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2),
        argnums=(0, 1, 2),
    ))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(a, b, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_kv_mask_exact(causal):
    """Padded batches: a (B, S) key-validity mask rotated around the ring
    must reproduce masked dot-product attention exactly."""
    mesh = make_mesh("data:2,seq:4", jax.devices())
    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 32, 2, 16)), jnp.float32)
        for _ in range(3)
    )
    lengths = jnp.asarray([20, 32])  # sample 0 padded, sample 1 full
    kv_mask = jnp.arange(32)[None, :] < lengths[:, None]  # (B, S)
    ref = dot_product_attention(q, k, v, causal=causal,
                                mask=kv_mask[:, None, None, :])
    out = jax.jit(
        lambda q, k, v, m: ring_attention(q, k, v, mesh, causal=causal,
                                          kv_mask=m)
    )(q, k, v, kv_mask)
    # padded query rows attend to nothing real; compare valid rows exactly
    # and padded rows against the reference's own masked-row output
    np.testing.assert_allclose(ref, out, atol=2e-5)

    g_ref = jax.grad(lambda q: jnp.sum(
        (dot_product_attention(q, k, v, causal=causal,
                               mask=kv_mask[:, None, None, :])
         * kv_mask[..., None, None]) ** 2))(q)
    g_ring = jax.jit(jax.grad(lambda q: jnp.sum(
        (ring_attention(q, k, v, mesh, causal=causal, kv_mask=kv_mask)
         * kv_mask[..., None, None]) ** 2)))(q)
    np.testing.assert_allclose(g_ref, g_ring, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_exact(causal):
    """All-to-all CP must equal dense attention exactly (heads=4 divisible
    by seq:4), with and without a key-padding mask, fwd and grads."""
    mesh = make_mesh("data:2,seq:4", jax.devices())
    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 32, 4, 16)), jnp.float32)
        for _ in range(3)
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(ref, out, atol=2e-5)

    kv_mask = jnp.arange(32)[None, :] < jnp.asarray([24, 32])[:, None]
    ref_m = dot_product_attention(q, k, v, causal=causal,
                                  mask=kv_mask[:, None, None, :])
    out_m = jax.jit(
        lambda q, k, v, m: ulysses_attention(q, k, v, mesh, causal=causal,
                                             kv_mask=m)
    )(q, k, v, kv_mask)
    np.testing.assert_allclose(ref_m, out_m, atol=2e-5)

    g_ref = jax.grad(lambda q: jnp.sum(
        dot_product_attention(q, k, v, causal=causal) ** 2))(q)
    g_uly = jax.jit(jax.grad(lambda q: jnp.sum(
        ulysses_attention(q, k, v, mesh, causal=causal) ** 2)))(q)
    np.testing.assert_allclose(g_ref, g_uly, atol=3e-5)


def test_ulysses_flash_local_impl_fwd_and_grad():
    """Ulysses with impl='flash': the Pallas kernel (fwd AND the custom-vjp
    backward) running INSIDE shard_map — the composition gpt-long-style
    configs hit on TPU. Seq 128 so each post-all-to-all shard still tiles
    a full-width lane block."""
    mesh = make_mesh("data:2,seq:4", jax.devices())
    rng = np.random.default_rng(4)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 128, 4, 32)), jnp.float32)
        for _ in range(3)
    )
    ref = dot_product_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh, causal=True, impl="flash"))(q, k, v)
    np.testing.assert_allclose(ref, out, atol=2e-5)

    g_ref = jax.grad(lambda q: jnp.sum(
        dot_product_attention(q, k, v, causal=True) ** 2))(q)
    g_fl = jax.jit(jax.grad(lambda q: jnp.sum(ulysses_attention(
        q, k, v, mesh, causal=True, impl="flash") ** 2)))(q)
    np.testing.assert_allclose(g_ref, g_fl, atol=3e-5)


def test_ulysses_tp_sp_keeps_heads_split():
    """Under a data×model×seq mesh the heads dim stays split over `model`
    through the all-to-all (no redundant per-model-shard attention)."""
    mesh = make_mesh("data:2,model:2,seq:2", jax.devices())
    rng = np.random.default_rng(4)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 16, 4, 16)), jnp.float32)
        for _ in range(3)
    )
    ref = dot_product_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(ref, out, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh("data:2,seq:4", jax.devices())
    q = jnp.zeros((2, 32, 2, 16))  # 2 heads, seq axis 4
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, q, q, mesh)


@pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
def test_ulysses_end_to_end(tmp_path):
    """bert-long-tiny with cp_impl=ulysses trains through the Trainer on a
    data×seq mesh, padded batches included."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        model="bert-long-tiny", mesh="data:2,seq:4", cp_impl="ulysses",
        dataset_size=64, per_device_train_batch_size=1, max_steps=4,
        logging_steps=0, save_steps=0, learning_rate=5e-3,
        max_grad_norm=1.0, output_dir=str(tmp_path), resume=False,
    )
    mesh = make_mesh(cfg.mesh, jax.devices())
    task, ds = build(cfg.model, cfg)
    assert task.model.attn_impl == "ulysses"
    trainer = Trainer(cfg, _ctx(mesh, cfg), task, ds)
    state = trainer.train()
    assert int(state.step) == 4


def test_tensor_parallel_loss_matches_replicated():
    """Same params, same batch: loss under model-axis sharding must equal
    the replicated-DDP loss (GSPMD collectives are numerically exact)."""
    cfg = TrainingConfig(model="bert-tiny", dataset_size=32, seed=7)
    task, ds = build("bert-tiny", cfg)
    batch = {k: jnp.asarray(v) for k, v in ds.batch(np.arange(8)).items()}
    params, extra = task.init(jax.random.PRNGKey(0), batch)

    def loss_of(params):
        loss, _, _ = task.loss(params, extra, batch, jax.random.PRNGKey(3))
        return loss

    import flax.linen as nn

    base = float(loss_of(nn.meta.unbox(params)))

    mesh = make_mesh("data:4,model:2", jax.devices())
    sharded = shard_tree(params, mesh)
    # the mlp/heads/vocab dims must actually be split over `model`
    specs = jax.tree.map(lambda x: x.sharding.spec, sharded)
    flat = jax.tree.leaves(specs, is_leaf=lambda s: True)
    assert any("model" in str(s) for s in map(str, flat)), flat
    tp = float(jax.jit(loss_of)(sharded))
    assert abs(base - tp) < 1e-4, (base, tp)


@pytest.mark.slow  # full bert-long train; ring-attention parity units stay tier-1
def test_context_parallel_end_to_end(tmp_path):
    """bert-long-tiny (ring attention, seq-sharded batch) trains through
    the full Trainer on a data×seq mesh and the loss decreases."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        model="bert-long-tiny", mesh="data:2,seq:4", dataset_size=64,
        per_device_train_batch_size=1, max_steps=6, logging_steps=3,
        save_steps=0, learning_rate=5e-3, max_grad_norm=1.0,
        output_dir=str(tmp_path), eval_steps=0, resume=False,
    )
    mesh = make_mesh(cfg.mesh, jax.devices())
    # per_device=1 over data:2 -> global micro batch 2 (train_batch_size
    # scales by the data-axis size; the seq:4 group shares each sample)
    task, ds = build(cfg.model, cfg)
    ctx = _ctx(mesh, cfg)
    trainer = Trainer(cfg, ctx, task, ds)
    state = trainer.train()
    assert int(state.step) == 6
    # input_ids must have been seq-sharded by the loader
    batch = next(iter(trainer.loader.epoch(0)))
    assert "seq" in str(batch["input_ids"].sharding.spec)


def test_sharded_grads_equal_single_device_grads():
    """The DDP invariant (SURVEY.md §4): psum'd gradients over the data
    mesh equal gradients of the same loss computed on one device."""
    cfg = TrainingConfig(model="mlp", dataset_size=64)
    task, ds = build("mlp", cfg)
    batch_np = ds.batch(np.arange(16))

    params, extra = task.init(jax.random.PRNGKey(0),
                              {k: jnp.asarray(v) for k, v in batch_np.items()})

    def grads_of(batch):
        def loss_fn(p):
            loss, _, _ = task.loss(p, extra, batch, None)
            return loss
        return jax.grad(loss_fn)(params)

    single = grads_of({k: jnp.asarray(v) for k, v in batch_np.items()})

    mesh = make_mesh("data:8", jax.devices())
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharded_batch = {
        k: jax.device_put(v, NamedSharding(mesh, P("data")))
        for k, v in batch_np.items()
    }
    sharded = jax.jit(grads_of)(sharded_batch)
    for a, b in zip(jax.tree.leaves(single), jax.tree.leaves(sharded)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_train_batch_size_scales_with_data_axis_only():
    """``per_device_train_batch_size`` means per *replica* (reference
    semantics, ddp.py:110-111: batch scales with the number of replicas) —
    under tensor/sequence parallelism a replica is a model×seq device
    group, so the multiplier is the data-axis size, not device_count."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = TrainingConfig(per_device_train_batch_size=3,
                         mesh="data:2,model:2,seq:2")
    assert cfg.data_axis_size == 2
    assert cfg.train_batch_size == 6  # not 3 * device_count() == 24

    # wildcard axes resolve against the device count (8 on this harness)
    assert TrainingConfig(per_device_train_batch_size=3,
                          mesh="data:-1").train_batch_size == 24
    assert TrainingConfig(per_device_train_batch_size=3,
                          mesh="data:-1,model:2").train_batch_size == 12

    # each data shard holds exactly per_device samples on the 3-axis mesh
    mesh = make_mesh(cfg.mesh, jax.devices())
    batch = jax.device_put(
        jnp.zeros((cfg.train_batch_size, 4)), NamedSharding(mesh, P("data"))
    )
    shard_rows = {s.data.shape[0] for s in batch.addressable_shards}
    assert shard_rows == {cfg.per_device_train_batch_size}


def test_zero1_shards_opt_state_and_preserves_numerics(tmp_path):
    """ZeRO-1: momentum state sharded over data; loss trajectory identical
    to the replicated-optimizer run (the update math is unchanged — only
    its placement)."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    def run(zero1, out):
        cfg = TrainingConfig(
            model="mlp-wide", optimizer="momentum", zero1=zero1,
            dataset_size=256, per_device_train_batch_size=4, max_steps=4,
            logging_steps=0, save_steps=0, output_dir=out, resume=False,
            mesh="data:8", max_grad_norm=1.0, seed=11,
        )
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, ds = build(cfg.model, cfg)
        trainer = Trainer(cfg, _ctx(mesh, cfg), task, ds)
        state = trainer.restore_or_init()[0]
        batch = next(iter(trainer.loader.epoch(0)))
        for _ in range(4):
            state, metrics = trainer.train_step(state, batch)
        # specs AFTER the jitted steps: the sharding (and the memory
        # saving) must survive GSPMD propagation, not just init
        specs = [str(x.sharding.spec) for x in jax.tree.leaves(state.opt_state)
                 if hasattr(x, "sharding") and x.ndim >= 1]
        return specs, float(metrics["loss"])

    specs_rep, loss_rep = run(False, str(tmp_path / "a"))
    specs_z1, loss_z1 = run(True, str(tmp_path / "b"))
    assert not any("data" in s for s in specs_rep)
    assert any("data" in s for s in specs_z1), specs_z1
    assert abs(loss_rep - loss_z1) < 1e-6, (loss_rep, loss_z1)


def test_fsdp_shards_params_and_preserves_numerics(tmp_path):
    """FSDP/ZeRO-3: params AND optimizer state sharded over data; loss
    trajectory identical to replicated DDP (GSPMD's gather/scatter
    protocol changes placement, not math)."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    def run(fsdp, out):
        cfg = TrainingConfig(
            model="mlp-wide", optimizer="momentum", fsdp=fsdp,
            dataset_size=256, per_device_train_batch_size=4, max_steps=4,
            logging_steps=0, save_steps=0, output_dir=out, resume=False,
            mesh="data:8", max_grad_norm=1.0, seed=11,
        )
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, ds = build(cfg.model, cfg)
        trainer = Trainer(cfg, _ctx(mesh, cfg), task, ds)
        state = trainer.restore_or_init()[0]
        batch = next(iter(trainer.loader.epoch(0)))
        for _ in range(4):
            state, metrics = trainer.train_step(state, batch)
        # specs AFTER jitted steps: the memory split must survive GSPMD
        # propagation through the whole update, not just init
        pspecs = [str(x.sharding.spec) for x in jax.tree.leaves(state.params)
                  if hasattr(x, "sharding") and x.ndim >= 1]
        ospecs = [str(x.sharding.spec)
                  for x in jax.tree.leaves(state.opt_state)
                  if hasattr(x, "sharding") and x.ndim >= 1]
        return pspecs, ospecs, float(metrics["loss"])

    p_rep, o_rep, loss_rep = run(False, str(tmp_path / "a"))
    p_f, o_f, loss_f = run(True, str(tmp_path / "b"))
    assert not any("data" in s for s in p_rep)
    assert any("data" in s for s in p_f), p_f
    assert any("data" in s for s in o_f), o_f
    assert abs(loss_rep - loss_f) < 1e-6, (loss_rep, loss_f)


def test_fsdp_composes_with_tensor_parallel(tmp_path):
    """data×model mesh + fsdp: TP placement keeps its model axis, the
    free dims pick up data — and the composed step still trains."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(
        model="bert-tiny", optimizer="adam", fsdp=True,
        mesh="data:4,model:2", dataset_size=64,
        per_device_train_batch_size=2, max_steps=2, logging_steps=0,
        save_steps=0, output_dir=str(tmp_path / "o"), resume=False,
    )
    mesh = make_mesh(cfg.mesh, jax.devices())
    task, ds = build(cfg.model, cfg)
    trainer = Trainer(cfg, _ctx(mesh, cfg), task, ds)
    state = trainer.restore_or_init()[0]
    leaves = [x for x in jax.tree.leaves(state.params)
              if hasattr(x, "sharding") and x.ndim >= 1]
    assert any("model" in str(x.sharding.spec) for x in leaves)
    assert any("data" in str(x.sharding.spec) for x in leaves)
    state, metrics = trainer.train_step(
        state, next(iter(trainer.loader.epoch(0))))
    assert np.isfinite(float(metrics["loss"]))


def test_fsdp_checkpoint_resume_roundtrip(tmp_path):
    """FSDP-sharded state must survive orbax save → restore: the restore
    re-places every distributed array with the fsdp shardings and training
    resumes bit-identically (sharded checkpoints are where naive
    save/restore paths classically break)."""
    from pytorch_ddp_template_tpu.train.engine import Trainer

    def make(out, max_steps):
        cfg = TrainingConfig(
            model="mlp-wide", optimizer="momentum", fsdp=True,
            dataset_size=128, per_device_train_batch_size=2,
            max_steps=max_steps, logging_steps=0, save_steps=2,
            output_dir=out, mesh="data:8", seed=3, learning_rate=1e-2,
        )
        mesh = make_mesh(cfg.mesh, jax.devices())
        task, ds = build(cfg.model, cfg)
        return Trainer(cfg, _ctx(mesh, cfg), task, ds)

    out_a, out_b = str(tmp_path / "a"), str(tmp_path / "b")
    final_a = make(out_a, 4).train()  # uninterrupted 4 steps

    # segment 1: same schedule (max_steps=4), interrupted after 2 steps
    t1 = make(out_b, 4)
    state1, _ = t1.restore_or_init()
    it = iter(t1.loader.epoch(0))
    for _ in range(2):
        state1, _ = t1.train_step(state1, next(it))
    t1.ckpt.save(2, state1, t1.config)
    t1.ckpt.wait()

    t = make(out_b, 4)      # segment 2: must restore step 2, run to 4
    state, start = t.restore_or_init()
    assert start == 2
    assert any("data" in str(x.sharding.spec)
               for x in jax.tree.leaves(state.params)
               if hasattr(x, "sharding") and x.ndim >= 1)
    final_b = t.train()
    for a, b in zip(jax.tree.leaves(jax.device_get(final_a.params)),
                    jax.tree.leaves(jax.device_get(final_b.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero1_composes_with_tensor_parallel():
    """On a data×model mesh, zero1 adds `data` to free dims of opt-state
    leaves without disturbing the model-axis param mirror."""
    from pytorch_ddp_template_tpu.parallel import zero1_reshard
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState, make_optimizer,
    )

    cfg = TrainingConfig(model="bert-tiny", optimizer="adam",
                         mesh="data:4,model:2", dataset_size=32)
    mesh = make_mesh(cfg.mesh, jax.devices())
    task, ds = build(cfg.model, cfg)
    batch = {k: jnp.asarray(v) for k, v in ds.batch(np.arange(8)).items()}
    params, extra = task.init(jax.random.PRNGKey(0), batch)
    tx, _ = make_optimizer(cfg, total_steps=10)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       extra_vars=extra, opt_state=tx.init(params),
                       rng=jax.random.PRNGKey(1))
    state = shard_tree(state, mesh)
    z1 = zero1_reshard(state.opt_state, mesh)
    specs = [str(x.sharding.spec) for x in jax.tree.leaves(z1)
             if hasattr(x, "sharding") and x.ndim >= 1]
    assert any("data" in s for s in specs)
    # model-axis placement untouched where it existed
    tp_before = sum("model" in str(x.sharding.spec)
                    for x in jax.tree.leaves(state.opt_state)
                    if hasattr(x, "sharding"))
    tp_after = sum("model" in str(x.sharding.spec)
                   for x in jax.tree.leaves(z1) if hasattr(x, "sharding"))
    assert tp_before == tp_after > 0


def test_describe_and_rules():
    mesh = make_mesh("data:2,model:2,seq:2", jax.devices())
    d = describe(mesh)
    assert d == {
        "mesh": {"data": 2, "model": 2, "seq": 2},
        "data_parallel": 2,
        "tensor_parallel": 2,
        "context_parallel": 2,
        "expert_parallel": 1,
    }
    rules = dict(active_rules(mesh))
    assert rules["mlp"] == "model" and rules["batch"] == "data"
    # data-only mesh: everything else replicated
    rules1 = dict(active_rules(make_mesh("data:8", jax.devices())))
    assert rules1["mlp"] is None and rules1["seq_act"] is None


def test_fsdp_shards_largest_dividable_dim():
    """VERDICT r4 weak #6: the FSDP/ZeRO split picks the LARGEST dividable
    unsharded dim, not the first — a (4, 8192) scale table at data=4 must
    shard the 8192 dim (2048-wide slices), not degrade to 1-row shards."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.parallel.sharding import fsdp_reshard

    mesh = make_mesh("data:4,model:2", jax.devices())
    repl = NamedSharding(mesh, P())
    tree = {
        "table": jax.device_put(jnp.zeros((4, 8192)), repl),
        "square": jax.device_put(jnp.zeros((64, 64)), repl),
        "odd": jax.device_put(jnp.zeros((3, 5)), repl),
        "scalar": jax.device_put(jnp.zeros(()), repl),
    }
    out = fsdp_reshard(tree, mesh)
    assert out["table"].sharding.spec == P(None, "data")
    assert out["square"].sharding.spec in (P("data"), P("data", None))  # tie -> earliest dim
    assert out["odd"].sharding.spec in (P(), P(None, None))  # untouched
    assert out["scalar"].sharding.spec == P()
