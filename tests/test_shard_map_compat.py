"""First direct unit tests for ``parallel/shard_map_compat.py`` — the
jax-version seam EVERY decomposed schedule rides through (fsdp gathers,
ddp reduce regions, TP rings, and since r11 the composed fsdp×tp/ddp×tp
paths). The wrapper must (a) resolve to a real shard_map on this jaxlib,
(b) map the modern ``check_vma`` kwarg onto whatever spelling the
installed jax accepts, and (c) behave identically to the plain function
on replicated specs, on live axes, and on degenerate size-1 axes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_ddp_template_tpu.parallel import shard_map_compat
from pytorch_ddp_template_tpu.parallel.shard_map_compat import shard_map
from pytorch_ddp_template_tpu.runtime import make_mesh


class TestKwargMapping:
    def test_wrapper_found_a_real_shard_map(self):
        assert callable(shard_map_compat._shard_map)

    def test_installed_jax_has_a_known_replication_check_spelling(self):
        """The kwarg-introspection set must contain the core signature and
        (on every jax this repo supports) one of the two replication-check
        spellings — if BOTH vanish the wrapper silently stops disabling
        the check, which the seam's callers rely on for custom collectives."""
        params = shard_map_compat._PARAMS
        assert {"mesh", "in_specs", "out_specs"} <= params
        assert ("check_vma" in params) or ("check_rep" in params), params

    @pytest.mark.parametrize("check_vma", [None, False, True])
    def test_check_vma_values_all_construct_and_run(self, devices, check_vma):
        mesh = make_mesh("data:-1")
        out = shard_map(lambda x: x * 2, mesh=mesh, in_specs=P(),
                        out_specs=P(), check_vma=check_vma)(jnp.ones(()))
        assert float(out) == 2.0


class TestPassthrough:
    def test_replicated_specs_match_plain_function(self, devices):
        mesh = make_mesh("data:-1")
        x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 4)),
                        jnp.float32)
        fn = lambda a: jnp.tanh(a) + 1.0
        out = shard_map(fn, mesh=mesh, in_specs=P(), out_specs=P(),
                        check_vma=False)(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(fn(x)))

    def test_sharded_identity_round_trips(self, devices):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        x = jnp.arange(4 * n, dtype=jnp.float32).reshape(n, 4)
        out = shard_map(lambda a: a, mesh=mesh, in_specs=P("data"),
                        out_specs=P("data"), check_vma=False)(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_region_sees_the_local_shard_shape(self, devices):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        x = jnp.zeros((2 * n, 3))

        def body(a):
            assert a.shape == (2, 3)  # trace-time: per-shard view
            return a

        shard_map(body, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"), check_vma=False)(x)


class TestLiveVsDegenerateAxes:
    @pytest.mark.parametrize("spec,axis", [("data:-1", "data"),
                                           ("data:8,model:1", "model")])
    def test_psum_sums_live_and_passes_through_size1(self, devices, spec,
                                                     axis):
        """A psum over an 8-way live axis multiplies by 8; over a size-1
        axis it is the identity — the degenerate-mesh behaviour every
        schedule's collectives depend on (single-chip runs must not
        change values)."""
        mesh = make_mesh(spec)
        n = mesh.shape[axis]
        out = shard_map(lambda x: jax.lax.psum(x, axis), mesh=mesh,
                        in_specs=P(), out_specs=P(), check_vma=False)(
            jnp.asarray(3.0))
        assert float(out) == pytest.approx(3.0 * n)

    def test_axis_index_enumerates_live_axis(self, devices):
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        out = shard_map(
            lambda: jax.lax.axis_index("data")[None], mesh=mesh,
            in_specs=(), out_specs=P("data"), check_vma=False)()
        np.testing.assert_array_equal(np.asarray(out), np.arange(n))


class TestTranspose:
    def test_grad_of_replicated_input_sums_over_unmentioned_axis(self,
                                                                 devices):
        """shard_map's transpose SUMS a cotangent over every mesh axis
        its input spec does not mention — the mechanism the TP ops use to
        get their per-layer weight-grad psum over ``data`` for free, and
        since r11 the drain the composed schedules merge into. Pin it at
        the seam: d/dw of sum(w * x_sharded) must be the GLOBAL sum of x."""
        mesh = make_mesh("data:-1")
        n = mesh.shape["data"]
        x = jnp.arange(2 * n, dtype=jnp.float32).reshape(n, 2)

        def f(w, x):
            region = shard_map(lambda w_, x_: w_ * x_, mesh=mesh,
                               in_specs=(P(), P("data")),
                               out_specs=P("data"), check_vma=False)
            return region(w, x).sum()

        gw = jax.jit(jax.grad(f))(jnp.asarray(1.0), x)
        assert float(gw) == pytest.approx(float(x.sum()))
