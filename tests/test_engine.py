"""Training-engine tests (SURVEY.md §4): scheduler math, step accounting,
distributed-grad equivalence, loss-goes-down integration, checkpoint
round-trip + resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.data import SyntheticRegressionDataset
from pytorch_ddp_template_tpu.models import build
from pytorch_ddp_template_tpu.runtime import init
from pytorch_ddp_template_tpu.train import Trainer, linear_schedule_with_warmup


def make_trainer(tmp_path, **overrides) -> Trainer:
    defaults = dict(
        output_dir=str(tmp_path / "out"),
        per_device_train_batch_size=4,
        dataset_size=512,
        logging_steps=0,
        save_steps=0,
        max_steps=10,
        seed=0,
        learning_rate=1e-2,
    )
    defaults.update(overrides)
    cfg = TrainingConfig(**defaults)
    ctx = init(cfg)
    task, ds = build(cfg.model, cfg)
    return Trainer(cfg, ctx, task, ds)


class TestSchedule:
    def test_warmup_then_decay(self):
        s = linear_schedule_with_warmup(1.0, warmup_steps=10, total_steps=110)
        assert float(s(0)) == 0.0
        assert float(s(5)) == pytest.approx(0.5)
        assert float(s(10)) == pytest.approx(1.0)
        assert float(s(60)) == pytest.approx(0.5)
        assert float(s(110)) == pytest.approx(0.0)
        assert float(s(200)) == 0.0  # floor past total (ddp.py:58-60)

    def test_zero_warmup_full_lr_at_step0(self):
        s = linear_schedule_with_warmup(0.1, warmup_steps=0, total_steps=100)
        assert float(s(0)) == pytest.approx(0.1)


class TestStepAccounting:
    def test_epoch_math_matches_reference(self, tmp_path):
        # 512 samples / (4*8 global batch) = 16 steps/epoch; 3 epochs = 48
        t = make_trainer(tmp_path, max_steps=-1, num_train_epochs=3.0)
        assert t.steps_per_epoch == 16
        assert t.total_steps == 48
        assert t.num_epochs == 3

    def test_max_steps_override(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=10)
        assert t.total_steps == 10
        assert t.num_epochs == 1

    def test_accum_consumes_more_data_per_step(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=-1, num_train_epochs=1.0,
                         gradient_accumulation_steps=4)
        # 512 / (4*8*4) = 4 optimizer steps per epoch
        assert t.steps_per_epoch == 4


class TestTrainStep:
    def test_loss_goes_down(self, tmp_path):
        # windowed means over two epochs of the SAME data: single-batch
        # comparisons on random regression targets are order-noise
        t = make_trainer(tmp_path, max_steps=32, learning_rate=5e-2)
        state, _ = t.restore_or_init()
        losses = []
        for epoch in range(2):
            for batch in t.loader.epoch(epoch):
                state, metrics = t.train_step(state, batch)
                losses.append(float(metrics["loss"]))
        k = len(losses) // 4
        assert sum(losses[-k:]) / k < sum(losses[:k]) / k, losses

    def test_sharded_grads_equal_single_device(self, tmp_path):
        """The DDP-semantics test: psum'd sharded grads == grads on the
        concatenated batch on one device (SURVEY.md §4)."""
        t = make_trainer(tmp_path)
        state, _ = t.restore_or_init()
        batch = next(iter(t.loader.epoch(0)))

        host_batch = {k: np.asarray(v) for k, v in batch.items()}
        params_local = jax.device_get(state.params)  # snapshot: state is donated

        sharded_state, _ = t.train_step(state, batch)

        # same update computed single-device
        def loss_fn(params):
            loss, _, _ = t.task.loss(params, {}, host_batch, None, train=True)
            return loss
        grads = jax.grad(loss_fn)(params_local)
        lr = float(t.schedule(0))
        expected = jax.tree.map(lambda p, g: p - lr * g, params_local, grads)

        got = jax.device_get(sharded_state.params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6),
            got, expected,
        )

    def test_accum_matches_large_batch(self, tmp_path):
        """accum=4 over micro-batches == one step on the full batch (same
        total examples), verifying clip-after-accumulate ordering."""
        t_accum = make_trainer(tmp_path / "a", gradient_accumulation_steps=4,
                               per_device_train_batch_size=2)
        t_full = make_trainer(tmp_path / "b", gradient_accumulation_steps=1,
                              per_device_train_batch_size=8)
        s_a, _ = t_accum.restore_or_init()
        s_f, _ = t_full.restore_or_init()
        # identical init (same seed)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            jax.device_get(s_a.params), jax.device_get(s_f.params),
        )
        b_a = next(iter(t_accum.loader.epoch(0)))   # (4, 16, ...)
        flat = {k: np.asarray(v).reshape(-1, *np.asarray(v).shape[2:]) for k, v in b_a.items()}
        s_a2, m_a = t_accum.train_step(s_a, b_a)
        s_f2, m_f = t_full.train_step(s_f, jax.device_put(flat))
        assert float(m_a["loss"]) == pytest.approx(float(m_f["loss"]), rel=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
            ),
            jax.device_get(s_a2.params), jax.device_get(s_f2.params),
        )

    def test_grad_clipping_applied(self, tmp_path):
        t = make_trainer(tmp_path, max_grad_norm=1e-6, learning_rate=1.0)
        state, _ = t.restore_or_init()
        before = jax.device_get(state.params)
        state2, metrics = t.train_step(state, next(iter(t.loader.epoch(0))))
        after = jax.device_get(state2.params)
        # update magnitude bounded by lr * max_grad_norm
        max_delta = max(
            float(np.max(np.abs(a - b)))
            for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after))
        )
        assert max_delta <= 1e-6 + 1e-9


class TestCheckpointResume:
    def test_roundtrip_and_resume(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=6, save_steps=3)
        final = t.train()
        assert t.ckpt.latest_step() == 6
        assert 3 in t.ckpt.all_steps()

        # fresh trainer, same output dir → auto-resume at 6; continue to 8
        t2 = make_trainer(tmp_path, max_steps=8, save_steps=0)
        state, start = t2.restore_or_init()
        assert start == 6
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(jax.device_get(state.params))[0]),
            np.asarray(jax.tree.leaves(jax.device_get(final.params))[0]),
        )
        final2 = t2.train()
        assert int(final2.step) == 8

    def test_explicit_global_step_restore(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=6, save_steps=2)
        t.train()
        t3 = make_trainer(tmp_path, global_step=4)
        state, start = t3.restore_or_init()
        assert start == 4

    def test_config_artifact_saved(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=2)
        t.train()
        state = t.init_state()
        _, cfg_dict = t.ckpt.restore(None, state)
        assert cfg_dict["seed"] == 0
        assert cfg_dict["max_steps"] == 2

    def test_keep_checkpoints_gc(self, tmp_path):
        """--keep_checkpoints N: only the newest N step dirs survive, and
        the latest is still restorable (the reference GCs nothing and a
        long run with small --save_steps fills the disk)."""
        t = make_trainer(tmp_path, max_steps=7, save_steps=1,
                         keep_checkpoints=3)
        t.train()
        t.ckpt.wait()
        assert t.ckpt.latest_step() == 7
        assert t.ckpt.all_steps() == [5, 6, 7]

        t2 = make_trainer(tmp_path, max_steps=9, save_steps=0,
                          keep_checkpoints=3)
        state, start = t2.restore_or_init()
        assert start == 7

    def test_keep_checkpoints_zero_keeps_all(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=5, save_steps=1,
                         keep_checkpoints=0)
        t.train()
        t.ckpt.wait()
        assert t.ckpt.all_steps() == [1, 2, 3, 4, 5]


class TestEval:
    def test_eval_metrics_finite(self, tmp_path):
        cfg = TrainingConfig(output_dir=str(tmp_path / "o"), max_steps=2,
                             per_device_train_batch_size=4, dataset_size=256,
                             logging_steps=0, save_steps=0)
        ctx = init(cfg)
        task, ds = build("mlp", cfg)
        eval_ds = SyntheticRegressionDataset(128, seed=99)
        t = Trainer(cfg, ctx, task, ds, eval_dataset=eval_ds)
        state, _ = t.restore_or_init()
        ev = t.evaluate(state)
        assert "eval_loss" in ev and np.isfinite(ev["eval_loss"])


class TestReviewRegressions:
    def test_explicit_global_step_without_checkpoints_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            t = make_trainer(tmp_path, global_step=500)
            t.restore_or_init()

    def test_accum_microbatches_get_distinct_rng(self, tmp_path):
        """Each microbatch in the in-jit scan must receive its own RNG.

        Probe task: 'loss' = uniform(rng), so the step's reported loss is the
        mean over per-microbatch draws. We reconstruct the engine's key
        derivation (fold_in(state.rng, step) then fold_in(·, i)) and assert
        the reported mean matches the two-draw mean, not a single draw —
        which is exactly the identical-mask bug shape.
        """
        from pytorch_ddp_template_tpu.models.task import Task
        from pytorch_ddp_template_tpu.runtime import init as rt_init
        from pytorch_ddp_template_tpu.train.engine import (
            TrainState, make_optimizer, make_train_step,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        class RngProbeTask(Task):
            def __init__(self):
                pass

            def loss(self, params, extra_vars, batch, rng, *, train=True):
                u = jax.random.uniform(rng, ())
                loss = jnp.sum(params["w"]) * 0.0 + u
                return loss, extra_vars, {}

        cfg = TrainingConfig(output_dir=str(tmp_path), per_device_train_batch_size=2,
                             gradient_accumulation_steps=2, learning_rate=0.0)
        ctx = rt_init(cfg)
        task = RngProbeTask()
        tx, sched = make_optimizer(cfg, 10)
        step = make_train_step(task, tx, sched, accum_steps=2)

        batch = {"x": jax.device_put(jnp.ones((2, 16, 4)),
                                     NamedSharding(ctx.mesh, P(None, "data")))}
        params = {"w": jnp.ones((1,))}
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           extra_vars={}, opt_state=tx.init(params),
                           rng=jax.random.clone(ctx.seed_key))
        state = jax.device_put(state, NamedSharding(ctx.mesh, P()))
        _, metrics = step(state, batch)

        base = jax.random.fold_in(ctx.seed_key, 0)  # state.step == 0
        draws = [float(jax.random.uniform(jax.random.fold_in(base, i), ()))
                 for i in range(2)]
        reported = float(metrics["loss"])
        assert reported == pytest.approx(sum(draws) / 2, rel=1e-6)
        assert reported != pytest.approx(draws[0], rel=1e-6)


class TestOptimizers:
    def test_each_optimizer_steps_and_descends(self, tmp_path):
        for kind in ["sgd", "momentum", "adam", "adamw", "lamb", "lars"]:
            t = make_trainer(tmp_path / kind, max_steps=32, optimizer=kind,
                             learning_rate=1e-2, weight_decay=0.01)
            state, _ = t.restore_or_init()
            losses = []
            for epoch in range(2):
                for batch in t.loader.epoch(epoch):
                    state, metrics = t.train_step(state, batch)
                    losses.append(float(metrics["loss"]))
            k = len(losses) // 4
            # strict windowed descent: a no-op optimizer would stay flat
            assert sum(losses[-k:]) / k < sum(losses[:k]) / k, (kind, losses)

    def test_adam_state_checkpoints_round_trip(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=4, optimizer="adam", save_steps=2)
        final = t.train()
        t2 = make_trainer(tmp_path, max_steps=6, optimizer="adam", save_steps=2)
        state, start = t2.restore_or_init()
        assert start == 4
        # the adam moments themselves must round-trip with real values
        def moments(s):
            leaves = [np.asarray(x) for x in jax.tree.leaves(s.opt_state)]
            return [x for x in leaves if x.ndim > 0]
        got, want = moments(state), moments(final)
        assert got and any(np.abs(m).max() > 0 for m in got)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b)

    def test_resume_with_different_optimizer_fails_loudly(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=2, save_steps=2, optimizer="sgd")
        t.train()
        t2 = make_trainer(tmp_path, max_steps=4, optimizer="adam")
        with pytest.raises(ValueError, match="optimizer"):
            t2.restore_or_init()


class TestWeightDecayMask:
    def test_adamw_does_not_decay_1d_params(self, tmp_path):
        """Norms/biases (ndim <= 1) must be excluded from decoupled weight
        decay: with lr frozen via zero grads... instead, isolate decay by
        running adamw with huge weight_decay on zero gradients — 2D kernels
        must shrink, 1D biases must not move."""
        import optax
        from pytorch_ddp_template_tpu.train.engine import make_optimizer

        cfg = TrainingConfig(output_dir=str(tmp_path), optimizer="adamw",
                             weight_decay=0.5, learning_rate=1.0,
                             warmup_steps=0)
        tx, _ = make_optimizer(cfg, total_steps=10)
        params = {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))}
        grads = jax.tree.map(jnp.zeros_like, params)
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        new = optax.apply_updates(params, updates)
        assert float(jnp.abs(new["kernel"] - 1.0).max()) > 0.1  # decayed
        np.testing.assert_array_equal(np.asarray(new["bias"]),
                                      np.ones(4))  # masked: untouched


class TestScheduleShapes:
    def test_cosine_warmup_then_cosine_to_zero(self):
        from pytorch_ddp_template_tpu.train import cosine_schedule_with_warmup

        s = cosine_schedule_with_warmup(1.0, warmup_steps=10, total_steps=110)
        assert float(s(0)) == 0.0
        assert abs(float(s(5)) - 0.5) < 1e-6          # mid-warmup
        assert abs(float(s(10)) - 1.0) < 1e-6         # peak at warmup end
        assert abs(float(s(60)) - 0.5) < 1e-6         # half decay = cos(pi/2)
        assert float(s(110)) < 1e-6                   # zero at total
        assert float(s(200)) < 1e-6                   # floored past total

    def test_constant_holds_after_warmup(self):
        from pytorch_ddp_template_tpu.train import constant_schedule_with_warmup

        s = constant_schedule_with_warmup(0.3, warmup_steps=4, total_steps=100)
        assert abs(float(s(2)) - 0.15) < 1e-7
        assert abs(float(s(4)) - 0.3) < 1e-7
        assert abs(float(s(1000)) - 0.3) < 1e-7

    def test_lr_schedule_flag_reaches_metrics(self, tmp_path):
        t = make_trainer(tmp_path, max_steps=4, lr_schedule="cosine",
                         warmup_steps=2, learning_rate=1e-2)
        state, _ = t.restore_or_init()
        batch = next(iter(t.loader.epoch(0)))
        for _ in range(3):
            state, metrics = t.train_step(state, batch)
        # step 2 = warmup end -> peak; step 3 on the cosine arc below peak
        assert float(metrics["lr"]) < 1e-2
