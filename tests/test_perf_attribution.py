"""Round-13 step-time X-ray: obs/attribution.py (static cost model +
runtime MFU/fraction attribution), obs/goodput.py (the restart-
accumulating wall-clock ledger), the phase annotations, the engine's
retrace→compile-bucket accounting, and the CLI-level proof that
goodput.json survives a kill-and-resume."""

import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from pytorch_ddp_template_tpu.obs.attribution import (
    PEAK_FLOPS,
    PerfAttribution,
    cost_of,
    peak_flops_for,
    static_cost_model,
)
from pytorch_ddp_template_tpu.obs.goodput import BUCKETS, GoodputLedger


# -- static cost model -----------------------------------------------------

@pytest.fixture(scope="module")
def compiled_toy():
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((32, 32), jnp.float32)
    return f.lower(x).compile()


class TestStaticCostModel:
    def test_flops_and_bytes_from_cost_analysis(self, compiled_toy):
        cm = static_cost_model(compiled_toy, {"data": 1})
        # the CPU backend exposes cost analysis: a 32^3 matmul is ~2*32^3
        assert cm["flops_per_step"] > 32 ** 3
        assert cm["hbm_bytes_per_step"] > 0
        # no live axis, no collectives: zero wire either way
        assert cm["wire_bytes_total"] == 0

    def test_wire_split_by_family_and_axis(self):
        hlo = "\n".join([
            "body1 (a: f32[]) -> f32[] {",
            "  %g = f32[1024]{0} all-gather(%p), dimensions={0}",
            "  %r = f32[512]{0} collective-permute(%q), src={{0,1}}",
            "}",
        ])

        class FakeCompiled:  # cost analysis absent: zeros, never raises
            def cost_analysis(self):
                raise RuntimeError("no backend")

        both = static_cost_model(FakeCompiled(),
                                 {"data": 4, "model": 2}, hlo_text=hlo)
        assert both["wire_bytes_data"] == 4096    # gather family -> data
        assert both["wire_bytes_model"] == 2048   # ring family -> model
        assert both["wire_bytes_total"] == 6144
        # a dead axis zeroes ITS family even if the text has the ops
        #  (degenerate collectives in a single-replica program)
        data_only = static_cost_model(FakeCompiled(),
                                      {"data": 4}, hlo_text=hlo)
        assert data_only["wire_bytes_data"] == 4096
        assert data_only["wire_bytes_model"] == 0

    def test_wire_split_on_pipe_tp_mesh(self):
        """r22 satellite: with a model axis live ON a pipe mesh
        (pipe×tp), the TP psums share the all-reduce spelling with the
        data-axis reduce — the split comes from the caller's static
        ring-wire figure, clamped to the census; ppermute bytes go to
        the pipe bucket, never model."""
        hlo = "\n".join([
            "body1 (a: f32[]) -> f32[] {",
            "  %ar = f32[1024]{0} all-reduce(%p), to_apply=%add",
            "  %r = f32[512]{0} collective-permute(%q), src={{0,1}}",
            "}",
        ])

        class FakeCompiled:
            def cost_analysis(self):
                raise RuntimeError("no backend")

        axes = {"data": 2, "model": 2, "pipe": 2}
        cm = static_cost_model(FakeCompiled(), axes, hlo_text=hlo,
                               model_wire_bytes_per_step=1000)
        assert cm["wire_bytes_model"] == 1000   # the static figure
        assert cm["wire_bytes_data"] == 4096 - 1000  # the remainder
        assert cm["wire_bytes_pipe"] == 2048    # boundary hops
        assert cm["wire_bytes_total"] == 4096 + 2048
        # the figure is an estimate: clamp to what the census carries
        big = static_cost_model(FakeCompiled(), axes, hlo_text=hlo,
                                model_wire_bytes_per_step=10 ** 9)
        assert big["wire_bytes_model"] == 4096
        assert big["wire_bytes_data"] == 0
        # pipe×ddp: no model axis → the figure is inert, gather → data
        ddp = static_cost_model(FakeCompiled(),
                                {"data": 4, "pipe": 2}, hlo_text=hlo,
                                model_wire_bytes_per_step=1000)
        assert ddp["wire_bytes_model"] == 0
        assert ddp["wire_bytes_data"] == 4096
        assert ddp["wire_bytes_pipe"] == 2048
        # off pipe meshes the parameter is ignored: r11 families stand
        flat = static_cost_model(FakeCompiled(),
                                 {"data": 4, "model": 2}, hlo_text=hlo,
                                 model_wire_bytes_per_step=1000)
        assert flat["wire_bytes_model"] == 2048  # ring family
        assert flat["wire_bytes_data"] == 4096
        assert flat["wire_bytes_pipe"] == 0

    def test_pipe_bubble_overlay_with_model_axis_live(self):
        """perf_bubble_frac = device share × static bubble must hold
        unchanged at pipe×tp geometry (model axis live), the fraction
        quartet still summing to 1.0, and describe() carrying the pipe
        wire figure."""
        class _NoCost:
            def cost_analysis(self):
                return {}

        hlo = "\n".join([
            "body1 (a: f32[]) -> f32[] {",
            "  %ar = f32[1024]{0} all-reduce(%p), to_apply=%add",
            "  %r = f32[512]{0} collective-permute(%q), src={{0,1}}",
            "}",
        ])
        cm = static_cost_model(_NoCost(), {"data": 2, "model": 2,
                                           "pipe": 2},
                               hlo_text=hlo, pipe_bubble_frac=0.4,
                               model_wire_bytes_per_step=1000)
        assert cm["pipe_bubble_frac"] == 0.4
        perf = PerfAttribution(cm, device_kind="host", n_devices=8)
        rec = perf.interval(wall_s=10.0, steps=10, input_wait_s=1.0,
                            device_wait_s=5.0)
        assert rec["perf_bubble_frac"] == pytest.approx(0.5 * 0.4,
                                                        abs=1e-3)
        quartet = (rec["perf_frac_input"] + rec["perf_frac_host"]
                   + rec["perf_frac_comm"] + rec["perf_frac_compute"])
        assert quartet == pytest.approx(1.0, abs=1e-6)
        desc = perf.describe()
        assert desc["wire_mb_per_step_pipe"] == round(2048 / 1e6, 3)

    def test_cost_of_never_raises(self):
        class Broken:
            def cost_analysis(self):
                raise RuntimeError("nope")

        assert cost_of(Broken()) == {"flops": 0.0, "bytes": 0.0}

    def test_missing_cost_analysis_keys_yield_zeros(self):
        """r15 satellite: a backend whose cost_analysis returns a dict
        WITHOUT the 'flops'/'bytes accessed' keys (or an empty list)
        must degrade to zeros — and zeros must propagate to 'no figure'
        downstream, never an invented estimate."""
        class MissingKeys:
            def cost_analysis(self):
                return {"utilization": 0.5}  # neither flops nor bytes

        class EmptyList:
            def cost_analysis(self):
                return []

        assert cost_of(MissingKeys()) == {"flops": 0.0, "bytes": 0.0}
        assert cost_of(EmptyList()) == {"flops": 0.0, "bytes": 0.0}
        cm = static_cost_model(MissingKeys(), {"data": 1}, hlo_text="")
        assert cm["flops_per_step"] == 0.0
        assert cm["hbm_bytes_per_step"] == 0.0
        # the attribution built on that model emits NO mfu/hbm figures
        attr = PerfAttribution(cm, device_kind="TPU v5e", n_devices=1)
        snap = attr.interval(wall_s=1.0, steps=10, device_wait_s=0.5)
        assert "perf_mfu" not in snap
        assert "perf_tflops_per_sec" not in snap
        assert "perf_hbm_gbps" not in snap
        # the fractions still sum to 1 (device share is all compute)
        assert (snap["perf_frac_compute"] + snap["perf_frac_comm"]
                + snap["perf_frac_host"]
                + snap["perf_frac_input"]) == pytest.approx(1.0, abs=2e-3)

    def test_unknown_hardware_yields_no_mfu_or_memory_figure(self):
        """Unknown device_kind: peak/ICI/HBM lookups are all None —
        MFU, wire rate context and HBM-fraction must be ABSENT (the
        absolute hbm_gbps estimate from cost analysis is still honest),
        never computed against an invented peak."""
        attr = PerfAttribution(
            {"flops_per_step": 1e9, "hbm_bytes_per_step": 1e8,
             "wire_bytes_total": 0},
            device_kind="weird-npu-9000", n_devices=4)
        assert attr.peak_flops is None
        assert attr.ici_bytes_per_sec is None
        assert attr.hbm_bytes_per_sec is None
        snap = attr.interval(wall_s=1.0, steps=10, device_wait_s=0.5)
        assert "perf_mfu" not in snap
        assert "perf_hbm_frac_of_peak" not in snap
        assert snap["perf_hbm_gbps"] > 0  # measured-ish, not peak-relative
        assert snap["perf_frac_comm"] == 0.0  # no bandwidth: all compute


class TestPeakLookup:
    def test_override_wins(self):
        assert peak_flops_for("TPU v5e", override_tflops=2.0) == 2.0e12

    def test_table_substring_match(self):
        assert peak_flops_for("TPU v5e something") == PEAK_FLOPS["TPU v5e"]

    def test_unknown_is_none_not_invented(self):
        assert peak_flops_for("cpu") is None


# -- runtime attribution ---------------------------------------------------

def make_attr(**over):
    cm = {"flops_per_step": 1e9, "hbm_bytes_per_step": 1e8,
          "wire_bytes_data": 1_000_000, "wire_bytes_model": 0,
          "wire_bytes_total": 1_000_000}
    cm.update(over.pop("cost_model", {}))
    kw = dict(device_kind="TPU v5e", n_devices=1)
    kw.update(over)
    return PerfAttribution(cm, **kw)


class TestPerfAttribution:
    def frac_sum(self, snap):
        return (snap["perf_frac_compute"] + snap["perf_frac_comm"]
                + snap["perf_frac_host"] + snap["perf_frac_input"])

    def test_fractions_sum_to_one(self):
        snap = make_attr().interval(wall_s=10.0, steps=100,
                                    input_wait_s=2.0, device_wait_s=5.0)
        assert self.frac_sum(snap) == pytest.approx(1.0, abs=2e-3)
        assert snap["perf_frac_input"] == pytest.approx(0.2, abs=1e-3)

    def test_device_share_splits_compute_vs_comm(self):
        # per-step estimates on v5e: compute 1e9/197e12 ≈ 5.1us, comm
        # 1e6/800e9 = 1.25us — the observed device wait splits ~80/20
        snap = make_attr().interval(wall_s=1.0, steps=100,
                                    device_wait_s=0.8)
        assert snap["perf_frac_comm"] > 0.1
        assert snap["perf_frac_compute"] > snap["perf_frac_comm"]
        assert self.frac_sum(snap) == pytest.approx(1.0, abs=2e-3)

    def test_no_peak_no_mfu_all_device_time_is_compute(self):
        a = make_attr(device_kind="cpu")  # no peak/bw tables, no override
        snap = a.interval(wall_s=1.0, steps=10, device_wait_s=0.5)
        assert "perf_mfu" not in snap
        assert snap["perf_frac_comm"] == 0.0
        assert snap["perf_frac_compute"] == pytest.approx(0.5, abs=1e-3)

    def test_mfu_formula(self):
        a = make_attr(peak_tflops_override=1.0)  # 1 TFLOP/s peak
        snap = a.interval(wall_s=1.0, steps=100)  # 100 x 1e9 flops / 1s
        assert snap["perf_mfu"] == pytest.approx(0.1, abs=1e-3)
        assert 0.0 < snap["perf_mfu"] <= 1.0
        assert snap["perf_hbm_gbps"] == pytest.approx(10.0, rel=1e-3)

    def test_overlong_waits_clamp_never_negative(self):
        snap = make_attr().interval(wall_s=1.0, steps=10,
                                    input_wait_s=5.0, device_wait_s=5.0)
        assert snap["perf_frac_input"] == 1.0
        assert snap["perf_frac_host"] == 0.0
        assert self.frac_sum(snap) == pytest.approx(1.0, abs=2e-3)

    def test_n_devices_scales_peak(self):
        one = make_attr(peak_tflops_override=1.0, n_devices=1)
        four = make_attr(peak_tflops_override=1.0, n_devices=4)
        s1 = one.interval(wall_s=1.0, steps=100)
        s4 = four.interval(wall_s=1.0, steps=100)
        assert s1["perf_mfu"] == pytest.approx(4 * s4["perf_mfu"], rel=1e-3)

    def test_producer_idle_is_slack_not_a_fraction(self):
        snap = make_attr().interval(wall_s=1.0, steps=10,
                                    producer_idle_s=0.7)
        assert snap["perf_producer_idle_ms_per_step"] == pytest.approx(70.0)
        assert self.frac_sum(snap) == pytest.approx(1.0, abs=2e-3)


# -- goodput ledger --------------------------------------------------------

class TestGoodputLedger:
    def test_split_iteration_measured_first_remainder_productive(self, tmp_path):
        led = GoodputLedger(tmp_path)
        led.split_iteration(1.0, input_s=0.2, compile_s=0.3)
        tot = led.totals()
        assert tot["input_stall"] == pytest.approx(0.2)
        assert tot["compile"] == pytest.approx(0.3)
        assert tot["productive_step"] == pytest.approx(0.5)

    def test_split_clamps_to_interval(self, tmp_path):
        led = GoodputLedger(tmp_path)
        led.split_iteration(1.0, input_s=0.8, save_s=0.8)
        tot = led.totals()
        assert tot["input_stall"] == pytest.approx(0.8)
        assert tot["checkpoint_save"] == pytest.approx(0.2)  # clamped
        assert tot["productive_step"] == 0.0
        assert sum(tot.values()) == pytest.approx(1.0)

    def test_unknown_bucket_lands_in_other(self, tmp_path):
        led = GoodputLedger(tmp_path)
        led.add("no_such_bucket", 2.0)
        assert led.totals()["other"] == pytest.approx(2.0)

    def test_flush_writes_schema(self, tmp_path):
        led = GoodputLedger(tmp_path)
        led.add("productive_step", 9.0)
        led.add("compile", 1.0)
        led.flush()
        rec = json.loads((tmp_path / "goodput.json").read_text())
        assert rec["goodput"] == pytest.approx(0.9)
        assert set(BUCKETS) <= set(rec["buckets"])
        assert rec["attempt"] == 1

    def test_restart_accumulates_and_counts_downtime(self, tmp_path):
        first = GoodputLedger(tmp_path)
        first.add("productive_step", 10.0)
        first.flush()
        # the restarted attempt starts 30s after the last heartbeat:
        # the gap is preemption downtime, bucketed `halted`
        second = GoodputLedger(tmp_path, now=time.time() + 30.0)
        second.add("productive_step", 5.0)
        tot = second.totals()
        assert second.attempt == 2
        assert tot["productive_step"] == pytest.approx(15.0)
        assert tot["halted"] == pytest.approx(30.0, abs=2.0)
        second.flush()
        rec = json.loads((tmp_path / "goodput.json").read_text())
        assert rec["attempt"] == 2
        assert rec["buckets"]["productive_step"] == pytest.approx(15.0)
        assert len(rec["attempts_log"]) == 2

    def test_completed_attempt_books_no_downtime(self, tmp_path):
        """Resuming a FINISHED run with a larger budget days later is a
        workflow, not a preemption: the completed marker suppresses the
        halted gap that interrupted attempts book."""
        first = GoodputLedger(tmp_path)
        first.add("productive_step", 10.0)
        first.completed = True  # the engine sets this at budget-reached
        first.flush()
        second = GoodputLedger(tmp_path, now=time.time() + 86400.0)
        assert second.attempt == 2
        assert second.totals()["halted"] == 0.0

    def test_corrupt_ledger_starts_fresh(self, tmp_path):
        (tmp_path / "goodput.json").write_text("{not json")
        led = GoodputLedger(tmp_path)  # must not raise
        assert led.attempt == 1

    def test_clock_skew_gap_clamps_to_zero_and_warns_once(
            self, tmp_path, monkeypatch):
        """r15 satellite: a restart on a clock-skewed host can see the
        prior attempt's heartbeat in the FUTURE — the negative downtime
        gap must clamp to 0 (never a negative `halted` bucket in
        goodput.json) and log one warning naming the skew."""
        from pytorch_ddp_template_tpu.obs import goodput as gp_mod

        first = GoodputLedger(tmp_path)
        first.add("productive_step", 10.0)
        first.flush()
        warned = []
        monkeypatch.setattr(gp_mod.log, "warning",
                            lambda msg, *a: warned.append(str(msg)))
        # this attempt's wall clock reads 300s BEFORE the heartbeat
        second = GoodputLedger(tmp_path, now=time.time() - 300.0)
        assert second.attempt == 2
        assert second.totals()["halted"] == 0.0
        assert len(warned) == 1
        assert "clock skew" in warned[0]
        second.flush()
        rec = json.loads((tmp_path / "goodput.json").read_text())
        assert rec["buckets"]["halted"] >= 0.0
        # and the normal positive-gap path is untouched
        third = GoodputLedger(tmp_path, now=time.time() + 30.0)
        assert third.totals()["halted"] == pytest.approx(30.0, abs=2.0)

    def test_rate_limited_flush(self, tmp_path):
        led = GoodputLedger(tmp_path)
        led.add("productive_step", 1.0)
        led.flush(min_interval_s=3600.0)  # first write always lands
        led.add("productive_step", 99.0)
        led.flush(min_interval_s=3600.0)  # inside the window: skipped
        rec = json.loads((tmp_path / "goodput.json").read_text())
        assert rec["buckets"]["productive_step"] == pytest.approx(1.0)
        led.flush()  # unconditional: the shutdown path
        rec = json.loads((tmp_path / "goodput.json").read_text())
        assert rec["buckets"]["productive_step"] == pytest.approx(100.0)


# -- phase annotations -----------------------------------------------------

class TestPhaseAnnotations:
    def test_annotate_toggles(self):
        from contextlib import nullcontext

        from pytorch_ddp_template_tpu.utils.profiler import (
            annotate, phase_annotations_enabled, set_phase_annotations,
        )

        assert phase_annotations_enabled()
        assert isinstance(annotate("x"), jax.profiler.TraceAnnotation)
        try:
            set_phase_annotations(False)
            assert isinstance(annotate("x"), nullcontext)
            with annotate("x"):  # still a working context manager
                pass
        finally:
            set_phase_annotations(True)

    def test_named_scopes_reach_the_compiled_schedule(self):
        """The decomposed-scan phase names must survive into the
        compiled program's op metadata — that is what makes traces and
        HLO dumps readable."""
        from pytorch_ddp_template_tpu.parallel.schedule import (
            PlainSchedule, decomposed_scan,
        )

        stacked = {"w": jnp.ones((4, 8, 8), jnp.float32)}

        def apply_fn(w, y, k, extras):
            return jnp.tanh(y @ w["w"])

        def run(stacked, x):
            return decomposed_scan(
                PlainSchedule(), apply_fn, stacked, x, ()).sum()

        x = jnp.ones((8,), jnp.float32)
        text = jax.jit(jax.grad(run, argnums=1)).lower(
            stacked, x).compile().as_text()
        assert "sched_weights" in text
        assert "sched_block_fwd" in text
        assert "sched_block_bwd" in text


# -- engine integration ----------------------------------------------------

class TestEngineRetraceAccounting:
    def test_note_dispatch_warns_on_midrun_retrace(self, monkeypatch):
        """Satellite: a mid-run re-trace (shape/bucket change) must log
        its duration instead of masquerading as one slow step, and the
        duration must land in the pending `compile` bucket."""
        from pytorch_ddp_template_tpu.train import engine

        warned, infoed = [], []
        monkeypatch.setattr(engine.log, "warning",
                            lambda msg, *a: warned.append(msg))
        monkeypatch.setattr(engine.log, "info",
                            lambda msg, *a: infoed.append(msg))

        class StepStub:
            def __init__(self):
                self.size = 0

            def _cache_size(self):
                return self.size

        class Host:
            pass

        host = Host()
        host.train_step = StepStub()
        host._jit_cache_size = 0
        host._pending = {"compile": 0.0, "checkpoint_save": 0.0,
                         "eval": 0.0, "other": 0.0}

        host.train_step.size = 1  # startup compile: info, no warning
        engine.Trainer._note_dispatch(host, 0.5)
        assert host._pending["compile"] == pytest.approx(0.5)
        assert not warned and infoed
        engine.Trainer._note_dispatch(host, 0.01)  # cached: no accrual
        assert host._pending["compile"] == pytest.approx(0.5)
        host.train_step.size = 2  # mid-run retrace: warn + accrue
        engine.Trainer._note_dispatch(host, 0.7)
        assert host._pending["compile"] == pytest.approx(1.2)
        assert warned and "re-traced" in warned[0]

    def test_wrapped_step_without_cache_size_is_ignored(self):
        from pytorch_ddp_template_tpu.train.engine import Trainer

        class Host:
            pass

        host = Host()
        host.train_step = lambda *a: None  # bench/test injector wrappers
        host._jit_cache_size = 0
        host._pending = {"compile": 0.0}
        Trainer._note_dispatch(host, 1.0)  # must not raise
        assert host._pending["compile"] == 0.0


def make_trainer(out_dir, **overrides):
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    cfg = TrainingConfig(**{
        "model": "mlp", "mesh": "data:8",
        "per_device_train_batch_size": 4, "dataset_size": 512,
        "max_steps": 8, "logging_steps": 4, "save_steps": 0,
        "resume": False, "warmup_steps": 0, "max_grad_norm": 1000.0,
        "output_dir": str(out_dir), **overrides})
    ctx = rt_init(cfg)
    task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
    return Trainer(cfg, ctx, task, ds)


class TestEngineAttribution:
    def test_perf_report_emits_attribution_and_goodput(self, tmp_path):
        """--perf_report end to end on the production loop: the progress
        record carries MFU + the fractional breakdown (summing to ~1)
        and producer_idle_ms (satellite 2), and goodput.json lands with
        the full bucket set."""
        t = make_trainer(tmp_path, perf_report=True, peak_tflops=1e-4)
        t.train()
        assert t.perf is not None
        assert t.perf.cost_model["flops_per_step"] > 0

        recs = [json.loads(l) for l in
                (tmp_path / "metrics.jsonl").read_text().splitlines()]
        prog = [r for r in recs if "perf_frac_compute" in r]
        assert prog, "no attribution fields reached the progress record"
        last = prog[-1]
        frac_sum = (last["perf_frac_compute"] + last["perf_frac_comm"]
                    + last["perf_frac_host"] + last["perf_frac_input"])
        assert frac_sum == pytest.approx(1.0, abs=2e-3)
        assert 0.0 < last["perf_mfu"] <= 1.0
        assert "producer_idle_ms" in last and "input_wait_ms" in last

        gp = json.loads((tmp_path / "goodput.json").read_text())
        assert set(BUCKETS) <= set(gp["buckets"])
        assert gp["buckets"]["compile"] > 0  # startup compile accounted
        assert gp["goodput"] is not None

    def test_plain_run_still_writes_goodput(self, tmp_path):
        """The ledger is NOT gated on --perf_report: every training job
        accounts its wall-clock."""
        t = make_trainer(tmp_path)
        t.train()
        gp = json.loads((tmp_path / "goodput.json").read_text())
        assert gp["buckets"]["productive_step"] > 0
        # no attribution though: the flag was off
        recs = [json.loads(l) for l in
                (tmp_path / "metrics.jsonl").read_text().splitlines()]
        assert not any("perf_frac_compute" in r for r in recs)


class TestGoodputSurvivesRestart:
    def test_cli_kill_and_resume_accumulates(self, tmp_path):
        """Acceptance: a restarted run's ledger includes the prior
        attempt's buckets, pinned at the CLI level — run to step 4, stop
        (the preemption-shaped exit: checkpoint on disk, ledger on
        disk), rerun the SAME command with a larger budget and
        auto-resume."""
        import ddp

        out = tmp_path / "run"
        args = ["--model", "mlp", "--mesh", "data:8",
                "--per_device_train_batch_size", "4",
                "--dataset_size", "256", "--logging_steps", "2",
                "--save_steps", "4", "--seed", "7",
                "--output_dir", str(out)]
        assert ddp.main(args + ["--max_steps", "4"]) == 0
        first = json.loads((out / "goodput.json").read_text())
        assert first["attempt"] == 1
        assert first["buckets"]["compile"] > 0

        assert ddp.main(args + ["--max_steps", "8"]) == 0
        second = json.loads((out / "goodput.json").read_text())
        assert second["attempt"] == 2
        assert len(second["attempts_log"]) == 2
        # cumulative: every prior bucket is included in the new totals
        for bucket, val in first["buckets"].items():
            assert second["buckets"][bucket] >= val - 1e-6, bucket
        # and the resumed attempt did REAL new work on top
        assert (second["buckets"]["productive_step"]
                > first["buckets"]["productive_step"])
        # the resume itself was accounted (restore bucket grew)
        assert second["buckets"]["restore"] > first["buckets"]["restore"]
