"""Vision model-zoo tests: ResNet shapes, BatchNorm threading, lazy
synthetic image data, and a train-step smoke over the sharded engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.config import TrainingConfig
from pytorch_ddp_template_tpu.data.dataset import SyntheticImageDataset
from pytorch_ddp_template_tpu.models import available_models, build
from pytorch_ddp_template_tpu.models.resnet import ResNet18, ResNet50
from pytorch_ddp_template_tpu.runtime import init
from pytorch_ddp_template_tpu.train import Trainer


class TestResNetModule:
    def test_resnet18_cifar_shapes(self):
        model = ResNet18(num_classes=10, stem="cifar")
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        assert "batch_stats" in variables
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)

    def test_resnet50_imagenet_shapes(self):
        model = ResNet50(num_classes=1000)
        x = jnp.zeros((1, 64, 64, 3))  # stem/stride path is size-agnostic
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (1, 1000)

    def test_param_count_resnet50(self):
        """ResNet-50/ImageNet has the canonical ~25.5M params."""
        model = ResNet50(num_classes=1000)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)),
                               train=False)
        n = sum(np.prod(p.shape) for p in jax.tree.leaves(variables["params"]))
        assert 25_000_000 < n < 26_000_000

    def test_batch_stats_update_in_train_mode(self):
        model = ResNet18(num_classes=10, stem="cifar")
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        _, mutated = model.apply(variables, x, train=True,
                                 mutable=["batch_stats"])
        before = jax.tree.leaves(variables["batch_stats"])
        after = jax.tree.leaves(mutated["batch_stats"])
        assert any(
            not np.allclose(a, b) for a, b in zip(before, after)
        ), "train-mode forward must advance running statistics"

    def test_space_to_depth_stem_matches_imagenet_geometry(self):
        """The s2d stem must reproduce the imagenet stem's downsampling
        (same trunk input resolution) with 12-channel conv input."""
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3))
        base = ResNet50(num_classes=10)
        s2d = ResNet50(num_classes=10, stem="space_to_depth")
        vb = base.init(jax.random.PRNGKey(1), x, train=False)
        vs = s2d.init(jax.random.PRNGKey(1), x, train=False)
        assert s2d.apply(vs, x, train=False).shape == (2, 10)
        # stem kernel is 4x4x12 in, trunk params are shape-identical
        assert vs["params"]["conv_init"]["kernel"].shape == (4, 4, 12, 64)
        assert vb["params"]["conv_init"]["kernel"].shape == (7, 7, 3, 64)
        trunk_b = {k: v for k, v in vb["params"].items() if "block" in k}
        trunk_s = {k: v for k, v in vs["params"].items() if "block" in k}
        assert jax.tree.structure(trunk_b) == jax.tree.structure(trunk_s)

    @pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
    def test_remat_matches_no_remat_forward_and_grad(self):
        """Rematerialised blocks must be a pure scheduling change: identical
        logits, identical gradients, and the BatchNorm mutable collection
        still threads through the lifted transform (the failure mode
        nn.remat can introduce silently)."""
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        labels = jnp.array([0, 1, 2, 3])
        base = ResNet18(num_classes=10, stem="cifar")
        rem = ResNet18(num_classes=10, stem="cifar", remat=True)
        variables = base.init(jax.random.PRNGKey(0), x, train=False)

        def loss_fn(model):
            def f(params):
                logits, mutated = model.apply(
                    {"params": params,
                     "batch_stats": variables["batch_stats"]},
                    x, train=True, mutable=["batch_stats"])
                one_hot = jax.nn.one_hot(labels, 10)
                loss = -jnp.mean(
                    jnp.sum(jax.nn.log_softmax(logits) * one_hot, -1))
                return loss, mutated["batch_stats"]
            return jax.value_and_grad(f, has_aux=True)(variables["params"])

        (l1, stats1), g1 = loss_fn(base)
        (l2, stats2), g2 = loss_fn(rem)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            g1, g2)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
            stats1, stats2)

    def test_bf16_compute_f32_logits(self):
        model = ResNet18(num_classes=10, stem="cifar", dtype=jnp.bfloat16)
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.dtype == jnp.float32


class TestLazyImageDataset:
    def test_deterministic_and_lazy(self):
        a = SyntheticImageDataset(samples=100, image_size=8, num_classes=10, seed=3)
        b = SyntheticImageDataset(samples=100, image_size=8, num_classes=10, seed=3)
        idx = np.array([5, 17, 5, 99])
        ba, bb = a.batch(idx), b.batch(idx)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
        # same index → same sample regardless of position in the batch
        np.testing.assert_array_equal(ba["image"][0], ba["image"][2])
        assert ba["image"].dtype == np.uint8
        assert ba["image"].shape == (4, 8, 8, 3)

    def test_different_seed_differs(self):
        a = SyntheticImageDataset(samples=10, image_size=8, seed=0)
        b = SyntheticImageDataset(samples=10, image_size=8, seed=1)
        assert not np.array_equal(a.batch(np.arange(4))["image"],
                                  b.batch(np.arange(4))["image"])


class TestRegistryVision:
    def test_registered(self):
        names = available_models()
        assert "resnet18" in names and "resnet50" in names

    @pytest.mark.slow  # ~30s full resnet train; registry/shape units stay tier-1
    def test_resnet18_trains_sharded(self, tmp_path):
        cfg = TrainingConfig(
            model="resnet18", output_dir=str(tmp_path), max_steps=2,
            per_device_train_batch_size=2, dataset_size=64,
            logging_steps=0, save_steps=0, learning_rate=1e-2,
        )
        ctx = init(cfg)
        task, ds = build(cfg.model, cfg)
        t = Trainer(cfg, ctx, task, ds)
        state, _ = t.restore_or_init()
        batch = next(iter(t.loader.epoch(0)))
        state, metrics = t.train_step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert 0.0 <= float(metrics["accuracy"]) <= 1.0
        assert int(state.step) == 1
        # batch_stats advanced through the engine's extra_vars threading
        assert state.extra_vars and "batch_stats" in state.extra_vars


@pytest.mark.slow  # heavy long-tail: outside the budgeted tier-1 run
def test_selective_remat_matches_no_remat():
    """--remat_policy save-convs: saving conv outputs by name and
    recomputing only norm/ReLU must leave loss AND grads bit-comparable
    to the un-rematerialised model (same math, different schedule)."""
    from pytorch_ddp_template_tpu.models.resnet import ResNet18

    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (2, 32, 32, 3)), jnp.float32)

    def grads_of(model):
        v = model.init(jax.random.PRNGKey(0), x, train=False)

        def loss(params):
            out, _ = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]}, x,
                train=True, mutable=["batch_stats"])
            return jnp.sum(out.astype(jnp.float32) ** 2)

        return jax.jit(jax.grad(loss))(v["params"])

    base = ResNet18(num_classes=10, stem="cifar")
    sel = ResNet18(num_classes=10, stem="cifar", remat=True,
                   remat_save_convs=True)
    g0, g1 = grads_of(base), grads_of(sel)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
