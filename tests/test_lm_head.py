"""Blockwise LM-head cross-entropy (ops/lm_head.py): numerics against the
dense log-softmax head, gradient parity for the tied table, task-level
equality on the GPT family, and the compiled-memory claim that justifies
its existence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_template_tpu.ops.lm_head import lm_head_loss

B, T, V, E = 2, 16, 103, 8  # V deliberately not a multiple of any block


@pytest.fixture(scope="module")
def case():
    rng = np.random.default_rng(0)
    hidden = jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32)
    table = jnp.asarray(rng.standard_normal((V, E)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)
    return hidden, table, targets


def _dense(hidden, table, targets):
    logits = hidden @ table.T
    logp = jax.nn.log_softmax(logits, -1)
    return (jnp.take_along_axis(logp, targets[..., None], -1)[..., 0],
            jnp.argmax(logits, -1))


@pytest.mark.parametrize("block", [32, 64, 103, 500])
def test_matches_dense_forward(case, block):
    """All tilings, incl. a ragged tail block and block > vocab."""
    hidden, table, targets = case
    lp_d, am_d = _dense(hidden, table, targets)
    lp_b, am_b = lm_head_loss(hidden, table, targets, block=block)
    np.testing.assert_allclose(lp_d, lp_b, atol=1e-5)
    np.testing.assert_array_equal(am_d, am_b)


def test_matches_dense_gradients(case):
    hidden, table, targets = case
    g_d = jax.grad(lambda h, tb: -_dense(h, tb, targets)[0].mean(),
                   argnums=(0, 1))(hidden, table)
    g_b = jax.grad(
        lambda h, tb: -lm_head_loss(h, tb, targets, block=32)[0].mean(),
        argnums=(0, 1))(hidden, table)
    for a, b in zip(g_d, g_b):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_bf16_hidden(case):
    hidden, table, targets = case
    lp_d, _ = _dense(hidden, table, targets)
    lp_b, _ = lm_head_loss(hidden.astype(jnp.bfloat16),
                           table.astype(jnp.bfloat16), targets, block=32)
    np.testing.assert_allclose(lp_d, lp_b, atol=0.15)


def test_gpt_fused_head_equals_dense_task():
    """Same params: the fused-head CausalLmTask must reproduce the dense
    head's loss, accuracy AND gradients (incl. the tied wte table)."""
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, gpt_tiny

    dense_task = CausalLmTask(gpt_tiny())
    fused_task = CausalLmTask(gpt_tiny().clone(fused_head=True))
    rng = np.random.default_rng(2)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 1024, (2, 128)),
                                      jnp.int32)}
    params, extra = dense_task.init(jax.random.PRNGKey(0), batch)

    def run(task, p):
        loss, _, m = task.loss(p, extra, batch, jax.random.PRNGKey(1),
                               train=False)
        return loss, m

    loss_d, m_d = run(dense_task, params)
    loss_f, m_f = run(fused_task, params)
    np.testing.assert_allclose(float(loss_d), float(loss_f), rtol=1e-5)
    np.testing.assert_allclose(float(m_d["next_token_accuracy"]),
                               float(m_f["next_token_accuracy"]), rtol=1e-6)

    g_d = jax.grad(lambda p: run(dense_task, p)[0])(params)
    g_f = jax.grad(lambda p: run(fused_task, p)[0])(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
        g_d, g_f)


def test_bias_matches_dense_forward_and_grad(case):
    """BERT-style (V,) output bias: forward and all three grads."""
    hidden, table, targets = case
    rng = np.random.default_rng(5)
    bias = jnp.asarray(rng.standard_normal((V,)), jnp.float32)

    def dense(h, tb, bi):
        logits = h @ tb.T + bi
        logp = jax.nn.log_softmax(logits, -1)
        return jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]

    lp_d = dense(hidden, table, bias)
    lp_b, _ = lm_head_loss(hidden, table, targets, bias=bias, block=32)
    np.testing.assert_allclose(lp_d, lp_b, atol=1e-5)

    g_d = jax.grad(lambda h, tb, bi: -dense(h, tb, bi).mean(),
                   argnums=(0, 1, 2))(hidden, table, bias)
    g_b = jax.grad(
        lambda h, tb, bi: -lm_head_loss(h, tb, targets, bias=bi,
                                        block=32)[0].mean(),
        argnums=(0, 1, 2))(hidden, table, bias)
    for a, b in zip(g_d, g_b):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_bert_fused_head_equals_dense_task():
    """Same params: fused-head MlmTask == dense MlmTask (loss, accuracy,
    grads incl. the tied table and the vocab bias)."""
    from pytorch_ddp_template_tpu.models.bert import MlmTask, bert_tiny

    dense_task = MlmTask(bert_tiny())
    fused_task = MlmTask(bert_tiny().clone(fused_head=True))
    rng = np.random.default_rng(3)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 1024, (2, 128)),
                                      jnp.int32)}
    params, extra = dense_task.init(jax.random.PRNGKey(0), batch)

    def run(task, p):
        loss, _, m = task.loss(p, extra, batch, jax.random.PRNGKey(1),
                               train=False)
        return loss, m

    loss_d, m_d = run(dense_task, params)
    loss_f, m_f = run(fused_task, params)
    np.testing.assert_allclose(float(loss_d), float(loss_f), rtol=1e-5)
    np.testing.assert_allclose(float(m_d["mlm_accuracy"]),
                               float(m_f["mlm_accuracy"]), rtol=1e-6)
    g_d = jax.grad(lambda p: run(dense_task, p)[0])(params)
    g_f = jax.grad(lambda p: run(fused_task, p)[0])(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
        g_d, g_f)


@pytest.mark.slow  # ~14s composed compile; the blockwise parity units stay tier-1
def test_fused_head_under_tensor_parallel_vocab_sharding(tmp_path):
    """On a data:4,model:2 mesh the tied table is sharded over ``model``
    on its vocab dim; the blockwise head's dynamic_slice then runs over a
    sharded array under GSPMD. The engine-level loss must match the dense
    head bit-for-bit-ish on the same mesh and seed."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init
    from pytorch_ddp_template_tpu.train import Trainer

    def one_step(fused, out):
        cfg = TrainingConfig(
            model="gpt-tiny", mesh="data:4,model:2", fused_head=fused,
            per_device_train_batch_size=1, dataset_size=64, max_steps=1,
            logging_steps=0, save_steps=0, output_dir=out, seed=9,
        )
        ctx = init(cfg)
        task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
        t = Trainer(cfg, ctx, task, ds)
        state, _ = t.restore_or_init()
        # precondition, not vacuous: the tied table really is TP-sharded
        spec = str(state.params["wte"]["embedding"].sharding.spec)
        assert "model" in spec, spec
        state, metrics = t.train_step(state, next(iter(t.loader.epoch(0))))
        return float(metrics["loss"]), float(metrics["next_token_accuracy"])

    loss_d, acc_d = one_step(False, str(tmp_path / "a"))
    loss_f, acc_f = one_step(True, str(tmp_path / "b"))
    np.testing.assert_allclose(loss_d, loss_f, rtol=1e-5)
    np.testing.assert_allclose(acc_d, acc_f, rtol=1e-6)


@pytest.mark.slow  # ~16s accum-scan compile; the blockwise parity units stay tier-1
def test_fused_head_inside_accum_scan(tmp_path):
    """Gradient accumulation runs task.loss inside an in-jit lax.scan —
    the fused head's own vocab scan then nests inside it. accum=2 must
    equal the accum=1 step on the same total batch (per-step loss and
    the next_token_accuracy metric), through the real engine."""
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init
    from pytorch_ddp_template_tpu.train import Trainer

    def one_step(accum, per_dev, out):
        cfg = TrainingConfig(
            model="gpt-tiny", mesh="data:8", fused_head=True,
            gradient_accumulation_steps=accum,
            per_device_train_batch_size=per_dev, dataset_size=64,
            max_steps=1, logging_steps=0, save_steps=0, output_dir=out,
            seed=4,
        )
        ctx = init(cfg)
        task, ds = build(cfg.model, cfg, mesh=ctx.mesh)
        t = Trainer(cfg, ctx, task, ds)
        state, _ = t.restore_or_init()
        state, metrics = t.train_step(state, next(iter(t.loader.epoch(0))))
        return (float(metrics["loss"]),
                float(metrics["next_token_accuracy"]))

    loss_a, acc_a = one_step(2, 1, str(tmp_path / "a"))
    loss_f, acc_f = one_step(1, 2, str(tmp_path / "b"))
    np.testing.assert_allclose(loss_a, loss_f, rtol=1e-5)
    np.testing.assert_allclose(acc_a, acc_f, rtol=1e-6)


def test_peak_memory_scales_with_block_not_vocab():
    """The whole point: XLA's own memory analysis must show the fused
    head's temp allocation is a small fraction of the dense head's
    (B*T*V logits + softmax) at a realistic vocab."""
    b, t, v, e = 2, 256, 50_257, 64
    rng = np.random.default_rng(1)
    hidden = jnp.asarray(rng.standard_normal((b, t, e)), jnp.float32)
    table = jnp.asarray(rng.standard_normal((v, e)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, v, (b, t)), jnp.int32)

    def dense_loss(h, tb):
        return -_dense(h, tb, targets)[0].mean()

    def fused_loss(h, tb):
        return -lm_head_loss(h, tb, targets, block=2048)[0].mean()

    def temp_bytes(fn):
        c = jax.jit(jax.grad(fn, argnums=(0, 1))).lower(hidden, table).compile()
        return c.memory_analysis().temp_size_in_bytes

    dense_tmp, fused_tmp = temp_bytes(dense_loss), temp_bytes(fused_loss)
    # dense holds >= one full (B,T,V) f32 logits tensor in temps
    assert dense_tmp > b * t * v * 4
    assert fused_tmp < dense_tmp / 5, (fused_tmp, dense_tmp)


# -- greedy decode: the standalone online-argmax primitive (r19) -----------
#
# Until r19 the running argmax was only exercised through the loss path's
# accuracy metric; the serving engine now drives it directly, so the
# primitive gets direct pins — including the visit-order tie-break
# invariant the TP ring head has always silently relied on.


class TestGreedyDecode:
    def test_matches_dense_argmax_across_blockings(self, case):
        from pytorch_ddp_template_tpu.ops.lm_head import greedy_decode

        hidden, table, _ = case
        ref = np.asarray(jnp.argmax(
            hidden.astype(jnp.float32) @ table.astype(jnp.float32).T, -1))
        for block in (8192, 64, 100, 7):  # incl. non-dividing widths
            got = np.asarray(greedy_decode(hidden, table, block=block))
            assert np.array_equal(got, ref), block

    def test_bias_applied(self, case):
        from pytorch_ddp_template_tpu.ops.lm_head import greedy_decode

        hidden, table, _ = case
        v = table.shape[0]
        # a bias spike forces every position to the spiked id
        bias = jnp.zeros((v,), jnp.float32).at[17].set(1e4)
        got = np.asarray(greedy_decode(hidden, table, bias=bias, block=50))
        assert np.all(got == 17)

    def test_tie_break_invariant_across_visit_orders(self):
        """Exact ties break toward the LOWEST vocab id regardless of
        which block visits first: duplicate table rows land in
        different blocks under different block widths (different visit
        orders), and every blocking must pick the lower id."""
        from pytorch_ddp_template_tpu.ops.lm_head import greedy_decode

        rng = np.random.default_rng(0)
        v, e = 300, 16
        table = rng.standard_normal((v, e)).astype(np.float32)
        table[257] = table[3]  # exact duplicate -> exact logit tie
        # make the duplicated row the winner for every query
        hidden = jnp.asarray(np.tile(table[3] * 10.0, (4, 1)))
        table = jnp.asarray(table)
        for block in (300, 128, 64, 10, 7):
            got = np.asarray(greedy_decode(hidden, table, block=block))
            assert np.all(got == 3), (block, got)

    def test_agrees_with_loss_path_argmax(self, case):
        """The extracted primitive and the loss bundle's accuracy argmax
        are the same computation — pinned so a future edit to one
        cannot silently fork the other."""
        from pytorch_ddp_template_tpu.ops.lm_head import greedy_decode

        hidden, table, targets = case
        _, best = lm_head_loss(hidden, table, targets, block=64)
        got = greedy_decode(hidden, table, block=64)
        assert np.array_equal(np.asarray(best), np.asarray(got))

    def test_no_full_logits_materialised(self):
        """Peak temp memory scales with the vocab BLOCK, not the vocab:
        the serving-decode memory contract. Block-aligned vocab so the
        measurement sees the logits rows, not a one-off pad copy of the
        table (the pad path is covered functionally above)."""
        rng = np.random.default_rng(2)
        v, e, b = 49_152, 64, 32
        hidden = jnp.asarray(rng.standard_normal((b, e)), jnp.float32)
        table = jnp.asarray(rng.standard_normal((v, e)), jnp.float32)
        from pytorch_ddp_template_tpu.ops.lm_head import greedy_decode

        c = jax.jit(
            lambda h, t: greedy_decode(h, t, block=2048)
        ).lower(hidden, table).compile()
        tmp = c.memory_analysis().temp_size_in_bytes
        assert tmp < b * v * 4 / 5, tmp  # far below a (B, V) logits row
