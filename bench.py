"""Benchmark harness: one JSON line for the driver.

Measures sustained training throughput (examples/sec/chip) of the flagship
config on the available hardware, steady-state (post-compile), end-to-end
through the jitted train step, plus MFU (model FLOPs utilisation) from the
compiled executable's own cost analysis.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
ratio is against the documented era-appropriate target below for the metric
BASELINE.json names (ResNet-50 images/sec/chip on the reference's V100
hardware hints); >1.0 means this framework beats that bar per chip.

Robustness contract (this file is a driver hook): in this environment only
one process can hold the TPU at a time and backend setup can fail with
UNAVAILABLE — init retries with backoff, and ANY hard failure still emits a
single parseable JSON line (``value: 0`` + ``error``) instead of a stack
trace. Env knobs: BENCH_MODEL / BENCH_STEPS / BENCH_WARMUP / BENCH_BATCH /
BENCH_CPU=1 (force the CPU backend — the axon TPU plugin ignores the
JAX_PLATFORMS env var, so tests must force via the config API) /
BENCH_SCAN=1 + BENCH_DEPTH=N (scan-over-layers and deep-model variants of
the train mode) / BENCH_DEPTHS (the compile mode's depth sweep).
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

# Era-appropriate per-device reference throughputs (the reference targeted
# 4xV100 nodes, run.sbatch:2-9). Values are the well-known MLPerf-era
# fp32 V100 numbers; see BENCH.md.
BASELINE_PER_DEVICE = {
    "resnet50": ("resnet50_images_per_sec_per_chip", "images/sec/chip", 380.0),
    "resnet18": ("resnet18_images_per_sec_per_chip", "images/sec/chip", 2200.0),
    "bert-base": ("bert_base_seq512_per_sec_per_chip", "sequences/sec/chip", 35.0),
    "vit-b16": ("vit_b16_images_per_sec_per_chip", "images/sec/chip", 100.0),
    "gpt-small": ("gpt_small_seq1024_per_sec_per_chip", "sequences/sec/chip", 6.0),
    "mlp-wide": ("mlp_wide_examples_per_sec_per_chip", "examples/sec/chip", 1.0e6),
}

# Peak dense-matmul throughput per chip (bf16), for MFU. The table and
# the cost-analysis helper live in obs/attribution.py since r13 (the
# production loop consumes them under --perf_report); bench.py and
# tools/mfu_probe.py import THE one copy. Stdlib-only import chain —
# safe before init_devices().
from pytorch_ddp_template_tpu.obs.attribution import (  # noqa: E402
    PEAK_FLOPS, cost_of,
)

MODE = os.environ.get("BENCH_MODE", "train")  # train | e2e | scaling | flash | compile | overlap | comms | tp | overlap3d | obs | perf | fleet | mem | pipe | pipe_compose | quant | elastic | serve | spec | serve_tp
MODEL = os.environ.get("BENCH_MODEL", "resnet50")
WARMUP_STEPS = int(os.environ.get("BENCH_WARMUP", "5"))
TIMED_STEPS = int(os.environ.get("BENCH_STEPS", "30"))
PER_DEVICE_BATCH = int(os.environ.get("BENCH_BATCH", "0"))  # 0 = model default


def default_batch(model: str) -> int:
    return {"resnet50": 128, "resnet18": 512, "bert-base": 16, "vit-b16": 64,
            "gpt-small": 8, "mlp-wide": 4096}.get(model, 128)


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


#: record keys that mark an ablation run — numbers taken with a lever
#: deliberately degraded (or a kernel disabled, or the model's depth
#: changed via BENCH_DEPTH) must never be cited as the best-known
#: HEADLINE config during an outage
ABLATION_KEYS = ("remat", "fused_head", "dense_head", "flash_disabled",
                 "num_layers", "scan_layers", "ddp_overlap", "tp_overlap",
                 "fsdp_overlap", "quant_compute", "kv_quant", "paged_impl",
                 "spec_k", "draft_depth", "tp_degree", "pipe_schedule")


def _last_recorded(metric: str) -> dict | None:
    """Best-known committed record for ``metric`` from bench_records/.

    Surfaced in the error line during hardware outages so the round still
    shows the best-known number — clearly labelled as a prior record,
    never substituted into ``value`` (the driver's headline datum must
    reflect what ran NOW, or 0). Records carrying ablation keys
    (``ABLATION_KEYS``) are skipped; if ONLY ablation records exist for the
    metric, the newest is surfaced with its flags listed so a degraded
    config can never masquerade as the headline. ``BENCH_RECORDS_DIR``
    overrides the directory (tests).
    """
    import glob

    records_dir = os.environ.get("BENCH_RECORDS_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_records"
    )
    best: dict | None = None
    best_ablated: dict | None = None
    # newest file last (mtime, not name: lexicographic order would put
    # _r10 before _r5 and surface a stale round as "best-known")
    for path in sorted(glob.glob(os.path.join(records_dir, "*.jsonl")),
                       key=os.path.getmtime):
        try:
            lines = open(path).read().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except (ValueError, TypeError):
                continue
            if rec.get("metric") != metric or not rec.get("value"):
                continue
            flags = [k for k in ABLATION_KEYS if rec.get(k)]
            out = {
                "metric": rec["metric"],
                "value": rec["value"],
                "unit": rec.get("unit"),
                "vs_baseline": rec.get("vs_baseline"),
                "source": os.path.basename(path),
            }
            if flags:
                out["ablation_flags"] = flags
                best_ablated = out
            else:
                best = out
    return best if best is not None else best_ablated


def _fail(metric: str, unit: str, err: BaseException) -> None:
    """Hard failure → still one parseable JSON line (value 0, diagnosable)."""
    payload = {
        "metric": metric,
        "value": 0.0,
        "unit": unit,
        "vs_baseline": 0.0,
        "error": f"{type(err).__name__}: {err}",
    }
    try:  # best-known prior record, labelled — never merged into value
        last = _last_recorded(metric)
        if last is not None:
            payload["last_recorded"] = last
    except Exception:  # noqa: BLE001 - the error line must always emit
        pass
    _emit(payload)
    traceback.print_exc(file=sys.stderr)


def _tunnel_listening(ports=(8082, 8083), timeout_s: float = 2.0) -> bool:
    """True if the TPU tunnel relay accepts TCP connections."""
    import socket

    for port in ports:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=timeout_s).close()
            return True
        except OSError:
            continue
    return False


def init_devices(max_tries: int = 6, delay_s: float = 10.0):
    """Backend init with bounded retry and a no-hang guarantee.

    Failure modes seen in this environment: (a) UNAVAILABLE — the tunnel
    admits one client at a time, so a bench started while another process
    drains off the chip fails setup (clear backend state, back off, retry);
    (b) the relay process is dead — the plugin then blocks on reconnect
    *forever*, so pre-check the relay port and bound each init attempt with
    SIGALRM rather than hang to an opaque driver timeout.

    SIGALRM limitation (known, accepted): Python delivers signals between
    bytecodes, so if PJRT blocks inside a C call that never returns the
    alarm cannot interrupt it. The relay-port pre-check above exists
    precisely to avoid entering init in that state; the alarm bounds the
    Python-visible init phases. A thread-bound init would not help — the
    hung C thread cannot be killed and would poison the retry.
    """
    import signal

    import jax

    import importlib.util

    if os.environ.get("BENCH_CPU", "") == "1":
        jax.config.update("jax_platforms", "cpu")
        n_cpu = int(os.environ.get("BENCH_CPU_DEVICES", "1"))
        if n_cpu > 1:  # virtual mesh for the scaling sweep off-TPU
            try:
                jax.config.update("jax_num_cpu_devices", n_cpu)
            except Exception:  # noqa: BLE001 - older jax
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={n_cpu}"
                ).strip()
    elif importlib.util.find_spec("axon") is not None:
        # the axon plugin registers itself regardless of JAX_PLATFORMS (it
        # ignores that env var), so gate the dead-relay pre-check on the
        # plugin being importable, not on the env
        deadline = time.time() + float(os.environ.get("BENCH_TUNNEL_WAIT", "60"))
        while not _tunnel_listening():
            if time.time() > deadline:
                raise RuntimeError(
                    "TPU tunnel relay not listening on 127.0.0.1:8082 — "
                    "backend init would hang; aborting with a parseable error"
                )
            time.sleep(5)

    def _alarm(signum, frame):  # noqa: ARG001
        raise TimeoutError("backend init exceeded per-attempt deadline")

    last: BaseException | None = None
    for attempt in range(max_tries):
        try:
            if hasattr(signal, "SIGALRM"):
                signal.signal(signal.SIGALRM, _alarm)
                signal.alarm(int(os.environ.get("BENCH_INIT_TIMEOUT", "120")))
            try:
                return jax.devices()
            finally:
                if hasattr(signal, "SIGALRM"):
                    signal.alarm(0)
        except (RuntimeError, TimeoutError) as e:  # UNAVAILABLE / setup fail
            last = e
            retryable = isinstance(e, TimeoutError) or (
                "UNAVAILABLE" in str(e) or "initialize" in str(e).lower()
            )
            if not retryable:
                raise
            try:  # reset cached-failed backend so the retry re-inits
                jax.clear_backends()
            except Exception:  # noqa: BLE001
                try:
                    from jax._src import xla_bridge

                    xla_bridge._clear_backends()  # noqa: SLF001
                except Exception:  # noqa: BLE001
                    pass
            if attempt + 1 < max_tries:
                print(f"backend UNAVAILABLE (attempt {attempt + 1}/{max_tries}), "
                      f"retrying in {delay_s:.0f}s", file=sys.stderr)
                time.sleep(delay_s)
                delay_s *= 1.5
    raise last  # type: ignore[misc]


def _flops_of(compiled) -> float | None:
    """Model FLOPs of one optimizer step, or None when unavailable."""
    flops = cost_of(compiled)["flops"]
    return flops if flops > 0 else None


def run_bench(model: str, metric: str, unit: str, baseline: float,
              devices=None) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.parallel import shard_tree
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    per_device = PER_DEVICE_BATCH or default_batch(model)
    devices = list(devices if devices is not None else jax.devices())
    n_dev = len(devices)
    # decomposed-TP train leg (tools/tpu_followup.sh 10): carve a model
    # axis off the mesh; per-device batch then means per data-shard
    tp_overlap = os.environ.get("BENCH_TP_OVERLAP", "") == "1"
    tp_size = int(os.environ.get("BENCH_TP", "2")) if tp_overlap else 1
    if n_dev % tp_size:
        raise ValueError(
            f"BENCH_TP_OVERLAP: {n_dev} devices do not split into "
            f"model:{tp_size} groups (set BENCH_TP)")
    data_size = n_dev // tp_size
    mesh_spec = (f"data:{data_size},model:{tp_size}" if tp_overlap
                 else f"data:{n_dev}")
    mesh = make_mesh(mesh_spec, devices)
    remat = os.environ.get("BENCH_REMAT", "") == "1"
    fused_head = os.environ.get("BENCH_FUSED_HEAD", "") == "1"
    dense_head = os.environ.get("BENCH_DENSE_HEAD", "") == "1"
    config = TrainingConfig(
        model=model,
        mesh=mesh_spec,
        per_device_train_batch_size=per_device,
        bf16=True,  # TPU-native precision: bf16 compute, f32 master params
        dataset_size=per_device * n_dev * 2,
        warmup_steps=0,
        max_grad_norm=1000.0,
        remat=remat,  # bandwidth-for-flops ablation (tools/mfu_probe.py twin)
        fused_head=fused_head,  # blockwise LM head ablation (ops/lm_head.py)
    )
    seed_key = jax.random.PRNGKey(0)
    ctx = RuntimeContext(mesh=mesh, seed_key=seed_key,
                         host_key=jax.random.fold_in(seed_key, 0), config=config)
    # pass the sub-mesh explicitly: ring-attention entries otherwise build
    # one from config.mesh over ALL devices, which breaks the scaling sweep
    task, dataset = build(model, config, mesh=mesh)
    if dense_head:
        # ablation baseline for the entries that DEFAULT the blockwise
        # head on (gpt-long/bert-long): measure the dense (B,T,V) head
        if not hasattr(task.model, "fused_head"):
            raise ValueError(f"BENCH_DENSE_HEAD: model {model!r} has no LM head")
        task.model = task.model.clone(fused_head=False)
    depth = int(os.environ.get("BENCH_DEPTH", "0"))  # deep-model variants
    if depth:
        if not hasattr(task.model, "num_layers"):
            raise ValueError(f"BENCH_DEPTH: model {model!r} has no num_layers")
        task.model = task.model.clone(num_layers=depth)
    scan = os.environ.get("BENCH_SCAN", "") == "1"  # scan-over-layers leg
    if scan:
        if not hasattr(task.model, "scan_layers"):
            raise ValueError(
                f"BENCH_SCAN: model {model!r} has no transformer layer stack"
            )
        task.model = task.model.clone(scan_layers=True)
    ddp_overlap = os.environ.get("BENCH_DDP_OVERLAP", "") == "1"
    if ddp_overlap:  # compressed-DDP train leg (tools/tpu_followup.sh 9)
        if not scan:
            raise ValueError("BENCH_DDP_OVERLAP=1 needs BENCH_SCAN=1 "
                             "(the stacked layout is the schedule's unit)")
        task.model = task.model.clone(
            ddp_overlap=True, mesh=mesh,
            grad_comm=os.environ.get("BENCH_GRAD_COMM", "fp32"))
    if tp_overlap:  # decomposed-TP train leg (tools/tpu_followup.sh 10)
        if not scan:
            raise ValueError("BENCH_TP_OVERLAP=1 needs BENCH_SCAN=1 "
                             "(the scanned block is the ring's unit)")
        if dense_head:
            raise ValueError(
                "BENCH_TP_OVERLAP=1 forces the ring fused head; a "
                "BENCH_DENSE_HEAD=1 record would mislabel the run")
        if not hasattr(task.model, "tp_overlap"):
            raise ValueError(
                f"BENCH_TP_OVERLAP: model {model!r} has no tensor-parallel "
                "transformer stack to decompose")
        kwargs = {"tp_overlap": True, "mesh": mesh}
        if hasattr(task.model, "fused_head"):
            kwargs["fused_head"] = True  # the ring vocab head IS the head
        task.model = task.model.clone(**kwargs)
    fsdp_overlap = os.environ.get("BENCH_FSDP_OVERLAP", "") == "1"
    if fsdp_overlap:  # decomposed-FSDP / composed fsdp×tp train leg (r11)
        if not scan:
            raise ValueError("BENCH_FSDP_OVERLAP=1 needs BENCH_SCAN=1 "
                             "(the stacked layout is the schedule's unit)")
        if ddp_overlap:
            raise ValueError("BENCH_FSDP_OVERLAP=1 cannot compose with "
                             "BENCH_DDP_OVERLAP=1 (params cannot be both "
                             "sharded and replicated)")
        if not hasattr(task.model, "fsdp_overlap"):
            raise ValueError(
                f"BENCH_FSDP_OVERLAP: model {model!r} has no decomposed-"
                "FSDP execution path")
        task.model = task.model.clone(fsdp_overlap=True, mesh=mesh)
    quant = os.environ.get("BENCH_QUANT", "off")  # r17 quant-compute leg
    if quant not in ("off", "int8", "fp8"):
        raise ValueError(f"BENCH_QUANT={quant!r}: expected off|int8|fp8")
    if quant != "off":
        if not hasattr(task.model, "quant_compute"):
            raise ValueError(
                f"BENCH_QUANT: model {model!r} has no transformer block "
                "matmuls to quantize")
        task.model = task.model.clone(quant_compute=quant)

    global_batch = per_device * data_size
    idx = np.arange(global_batch) % len(dataset)
    host_batch = dataset.batch(idx)
    batch = {
        k: jax.device_put(v, NamedSharding(mesh, P("data")))
        for k, v in host_batch.items()
    }

    params, extra = task.init(seed_key, batch)
    tx, schedule = make_optimizer(config, total_steps=10_000)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        extra_vars=extra,
        opt_state=tx.init(params),
        rng=jax.random.clone(seed_key),
    )
    state = shard_tree(state, mesh)  # unbox + place per logical annotations
    if fsdp_overlap:
        from pytorch_ddp_template_tpu.parallel.sharding import fsdp_reshard

        # the gather schedule consumes the fsdp layout the trainer would
        # place: layer-dim (prefer_dim=0) data split over the stack
        state = state.replace(
            params=fsdp_reshard(state.params, mesh, prefer_dim=0),
            opt_state=fsdp_reshard(state.opt_state, mesh, prefer_dim=0),
        )
    # AOT-compile once and drive the loops with the same executable — a
    # plain call would trace+compile the identical program a second time
    train_step = make_train_step(task, tx, schedule, accum_steps=1).lower(
        state, batch
    ).compile()
    step_flops = _flops_of(train_step)

    # Sync by fetching a real value: on some PJRT transports (e.g. the axon
    # tunnel) block_until_ready can return before compute has finished,
    # which would inflate throughput ~100x. A host read of a scalar that
    # depends on every step cannot lie.
    for _ in range(WARMUP_STEPS):
        state, metrics = train_step(state, batch)
    if WARMUP_STEPS:
        assert np.isfinite(float(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = train_step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    examples_per_sec = TIMED_STEPS * global_batch / dt
    per_chip = examples_per_sec / n_dev
    out = {
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": unit,
        "vs_baseline": round(per_chip / baseline, 4),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n_dev,
        "global_batch": global_batch,
        "step_time_ms": round(1000 * dt / TIMED_STEPS, 2),
    }
    if remat:
        out["remat"] = True
    if fused_head:
        out["fused_head"] = True
    if dense_head:
        out["dense_head"] = True
    if depth:
        out["num_layers"] = depth  # ablation-keyed: not the headline model
    if scan:
        out["scan_layers"] = True
    if ddp_overlap:
        out["ddp_overlap"] = True
        out["grad_comm"] = os.environ.get("BENCH_GRAD_COMM", "fp32")
    if tp_overlap:
        out["tp_overlap"] = True
        out["mesh"] = mesh_spec
    if fsdp_overlap:
        out["fsdp_overlap"] = True
    if quant != "off":
        out["quant_compute"] = quant  # ablation-keyed: narrow-dot run
    if os.environ.get("FLASH_DISABLE", "") == "1":
        out["flash_disabled"] = True
    try:  # compiled-executable memory breakdown (peak-memory evidence for
        # the fused-stack ablations; not all PJRT backends implement it)
        ma = train_step.memory_analysis()
        out["temp_mb"] = round(ma.temp_size_in_bytes / 1e6, 1)
        out["argument_mb"] = round(ma.argument_size_in_bytes / 1e6, 1)
        out["output_mb"] = round(ma.output_size_in_bytes / 1e6, 1)
    except Exception:  # noqa: BLE001
        pass
    if step_flops is not None:
        kind = devices[0].device_kind
        peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), None)
        out["tflops_per_sec_per_chip"] = round(
            step_flops * TIMED_STEPS / dt / n_dev / 1e12, 2
        )
        if peak is not None:
            out["mfu"] = round(step_flops * TIMED_STEPS / dt / (n_dev * peak), 4)
    return out


def run_e2e(model: str, metric: str, unit: str, baseline: float) -> dict:
    """Steady-state throughput through ``Trainer`` + ``ShardedLoader`` —
    the loader/prefetch/H2D path included, where ``run_bench`` re-feeds one
    staged device batch (pure device compute). The reference pays its
    dataloader every step (``/root/reference/ddp.py:216-220``); emitting
    both numbers side by side keeps the comparison honest and quantifies
    the input-path gap. ``BENCH_DATA_DIR`` runs the same config against a
    memory-mapped file store instead of the synthetic source.

    A third leg drives the FULL production loop (``Trainer.train()`` with
    ``logging_steps`` on — telemetry, step accounting, stop handling) and
    reports ``host_overhead_pct``: the gap between the pure-device number
    and the full-loop number attributable to host work. ``BENCH_TELEMETRY``
    (async|sync, default async) selects the scalar sink — the sync/async
    pair IS the before/after record for the host-sync-free hot loop
    (BENCH.md); ``BENCH_LOG_STEPS`` (default 5) sets the logging cadence,
    ``BENCH_INFLIGHT`` the bounded dispatch depth."""
    import jax
    import numpy as np

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    per_device = PER_DEVICE_BATCH or default_batch(model)
    n_dev = len(jax.devices())
    total_steps = WARMUP_STEPS + TIMED_STEPS
    global_batch = per_device * n_dev
    # cached-batch comparison FIRST: running it after the trainer would
    # hold two full model+optimizer replicas live at once (HBM-tight
    # configs would OOM in the comparison that neither mode hits alone)
    cached = run_bench(model, metric, unit, baseline)
    config = TrainingConfig(
        model=model,
        mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device,
        bf16=True,
        # enough data that the timed window never re-reads a cached batch
        dataset_size=global_batch * total_steps,
        data_dir=os.environ.get("BENCH_DATA_DIR", ""),
        warmup_steps=0,
        max_grad_norm=1000.0,
        max_steps=total_steps,
        logging_steps=0,
        save_steps=0,
        output_dir=os.environ.get("BENCH_OUTPUT", "/tmp/bench_e2e"),
    )
    ctx = rt_init(config)
    task, dataset = build(model, config, mesh=ctx.mesh)
    trainer = Trainer(config, ctx, task, dataset)
    state, _ = trainer.restore_or_init()

    # one timed window over the steady state, fenced ONCE at the end by a
    # host read of the final loss (block_until_ready can lie on the axon
    # transport, see run_bench) — per-step fencing would serialise host
    # dispatch against device compute and misreport the pipelined rate
    timed = 0
    t0 = None
    metrics = None
    for i, batch in enumerate(trainer.loader.epoch(0)):
        if i == WARMUP_STEPS:
            if metrics is not None:  # drain warmup before the clock starts
                float(metrics["loss"])
            t0 = time.perf_counter()
        state, metrics = trainer.train_step(state, batch)
        if i >= WARMUP_STEPS:
            timed += 1
        if i + 1 >= total_steps:
            break
    if t0 is None or timed == 0:
        raise RuntimeError("dataset exhausted before the timed window")
    loss = float(metrics["loss"])
    dt_total = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"

    dt = dt_total / timed
    per_chip = global_batch / dt / n_dev
    # free the manual-loop replica before the full-loop leg builds its own
    # (HBM-tight configs would otherwise hold two states live at once)
    del state, metrics, trainer

    # -- full-loop leg: the production Trainer.train() with logging on ----
    telem = os.environ.get("BENCH_TELEMETRY", "async")
    log_steps = int(os.environ.get("BENCH_LOG_STEPS", "5"))
    inflight = int(os.environ.get("BENCH_INFLIGHT", "2"))
    full_cfg = TrainingConfig(
        model=model,
        mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device,
        bf16=True,
        dataset_size=global_batch * total_steps,
        data_dir=os.environ.get("BENCH_DATA_DIR", ""),
        warmup_steps=0,
        max_grad_norm=1000.0,
        max_steps=total_steps,
        logging_steps=log_steps,
        save_steps=0,
        resume=False,
        telemetry=telem,
        max_inflight_steps=inflight,
        output_dir=os.environ.get("BENCH_OUTPUT", "/tmp/bench_e2e") + "_full",
    )
    full_task, full_ds = build(model, full_cfg, mesh=ctx.mesh)
    full_trainer = Trainer(full_cfg, ctx, full_task, full_ds)
    t0 = time.perf_counter()
    full_trainer.train()
    full_wall = time.perf_counter() - t0
    # steady-state loop rate from the trainer's own timer, using the MEAN:
    # the sum of tick intervals equals elapsed loop time (compile excluded —
    # the first tick only sets the baseline), which stays honest even for
    # the unpaced sync leg where an async dispatch makes 4 of 5 ticks
    # near-zero and the logging-boundary tick absorbs the device wait for
    # all of them — a p50 there would report dispatch time, not step time
    full_ms = full_trainer.step_timer.summary().get("step_time_mean_ms")
    if full_ms is None:  # degenerate tiny run: fall back to wall clock
        full_ms = 1e3 * full_wall / total_steps
    full_per_chip = global_batch / (full_ms / 1e3) / n_dev

    return {
        "metric": f"{model}_e2e_ex_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": unit,
        "vs_baseline": round(per_chip / baseline, 4),
        "platform": jax.devices()[0].platform,
        "n_devices": n_dev,
        "global_batch": global_batch,
        "step_time_ms": round(1000 * dt, 2),
        "data_source": "filestore" if config.data_dir else "synthetic",
        "cached_batch_per_chip": cached["value"],
        "cached_step_time_ms": cached["step_time_ms"],
        "input_path_overhead_pct": round(
            100 * (cached["value"] - per_chip) / cached["value"], 2
        ) if cached["value"] else None,
        # full production loop vs pure device compute: the host-work gap.
        # sync-vs-async BENCH_TELEMETRY pairs of this field are the
        # before/after evidence for the host-sync-free hot loop
        "telemetry": telem,
        "logging_steps": log_steps,
        "max_inflight_steps": inflight,
        "full_loop_per_chip": round(full_per_chip, 2),
        "full_loop_step_time_ms": round(full_ms, 2),
        "host_overhead_pct": round(
            100 * (cached["value"] - full_per_chip) / cached["value"], 2
        ) if cached["value"] else None,
    }


def run_compile() -> dict:
    """Scan-over-layers compile-time proof: cold ``jit(...).lower().compile()``
    wall-time of the full train step, unrolled vs scanned, across depths.

    Unrolled, XLA traces and optimises ``num_layers`` copies of the same
    block, so compile time grows ~linearly in depth; scanned
    (``--scan_layers``), one block body is compiled and ``lax.scan`` drives
    it, so compile time is ~flat. Deterministic on the CPU bench host —
    compile wall-time needs no TPU, which is why this leg can commit a
    before/after pair during a tunnel outage. A steady-state step-time leg
    at the deepest depth (alternating reps, min-of-reps against ambient
    load) checks the scan is throughput-neutral. Knobs: ``BENCH_DEPTHS``
    (default "2,12,24"), ``BENCH_BATCH``, ``BENCH_SEQ``, ``BENCH_REMAT=1``
    (remat-scan vs remat-unrolled), ``BENCH_STEPS``/``BENCH_WARMUP`` for
    the step-time leg.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    depths = tuple(int(d) for d in
                   os.environ.get("BENCH_DEPTHS", "2,12,24").split(","))
    batch_size = PER_DEVICE_BATCH or 4
    seq = int(os.environ.get("BENCH_SEQ", "64"))
    vocab = 256
    remat = os.environ.get("BENCH_REMAT", "") == "1"
    ids = np.random.default_rng(0).integers(0, vocab, (batch_size, seq))
    batch = {"input_ids": jnp.asarray(ids, jnp.int32)}
    config = TrainingConfig(warmup_steps=0, max_grad_norm=1000.0)

    def build_step(depth: int, scanned: bool):
        model = GptDecoder(vocab_size=vocab, max_len=seq, num_layers=depth,
                           num_heads=2, head_dim=32, mlp_dim=128,
                           remat=remat, scan_layers=scanned)
        task = CausalLmTask(model)
        params, extra = task.init(jax.random.PRNGKey(0), batch)
        params = nn.meta.unbox(params)
        tx, schedule = make_optimizer(config, total_steps=10_000)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, extra_vars=extra,
            opt_state=tx.init(params), rng=jax.random.PRNGKey(1),
        )
        # fresh jit per call — nothing shares a cache, every timing is cold
        return make_train_step(task, tx, schedule), state

    rows = []
    for depth in depths:
        row = {"depth": depth}
        for scanned in (False, True):
            step, state = build_step(depth, scanned)
            t0 = time.perf_counter()
            lowered = step.lower(state, batch)
            t1 = time.perf_counter()
            lowered.compile()
            t2 = time.perf_counter()
            key = "scanned" if scanned else "unrolled"
            row[f"{key}_trace_s"] = round(t1 - t0, 3)
            row[f"{key}_compile_s"] = round(t2 - t1, 3)
            row[f"{key}_total_s"] = round(t2 - t0, 3)
        row["compile_speedup"] = round(
            row["unrolled_total_s"] / max(row["scanned_total_s"], 1e-9), 3
        )
        rows.append(row)

    # -- steady-state leg at the deepest depth: throughput neutrality -----
    # compile once per variant (the unrolled deep compile costs ~a minute;
    # only the timed stepping needs repeating for ambient-load robustness),
    # then alternate timed reps so load spikes hit both variants alike
    deepest = max(depths)
    variants: dict[str, list] = {}
    for scanned in (False, True):
        key = "scanned" if scanned else "unrolled"
        step, state = build_step(deepest, scanned)
        compiled = step.lower(state, batch).compile()
        metrics = None
        for _ in range(WARMUP_STEPS):
            state, metrics = compiled(state, batch)
        if metrics is not None:
            float(metrics["loss"])  # drain warmup before the clock
        variants[key] = [compiled, state]
    step_ms = {}
    for rep in range(3):
        for key, slot in variants.items():
            compiled, state = slot
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])  # host read = honest fence
            dt = time.perf_counter() - t0
            slot[1] = state  # donated input: thread the live buffer
            assert np.isfinite(loss), f"non-finite loss {loss}"
            ms = 1e3 * dt / TIMED_STEPS
            step_ms[key] = min(step_ms.get(key, ms), ms)

    # headline = the DEEPEST depth's row (BENCH_DEPTHS need not be sorted)
    headline = next(r for r in rows if r["depth"] == deepest)
    speedup = headline["compile_speedup"]
    return {
        "metric": f"scan_compile_speedup_{deepest}L",
        "value": speedup,
        "unit": "x_unrolled_compile",
        # acceptance bar: scanned <= 0.5x unrolled compile at the deepest
        # depth, i.e. speedup >= 2 (vs_baseline >= 1.0 is the pass mark)
        "vs_baseline": round(speedup / 2.0, 4),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "remat": remat,
        "batch": batch_size,
        "seq_len": seq,
        "depths": list(depths),
        "compile_table": rows,
        "step_time_unrolled_ms": round(step_ms["unrolled"], 2),
        "step_time_scanned_ms": round(step_ms["scanned"], 2),
        "step_time_ratio_scanned_vs_unrolled": round(
            step_ms["scanned"] / max(step_ms["unrolled"], 1e-9), 3
        ),
        "timed_steps": TIMED_STEPS,
    }


def run_overlap() -> dict:
    """Decomposed-FSDP proof (``--fsdp_overlap``): GSPMD-default vs
    prefetch-pipelined execution of the same scanned, FSDP-sharded stack.

    Three legs, sized for what THIS host can prove (the real v5e step-time
    pair rides in tools/tpu_followup.sh 8):

    - **bit-parity**: one optimizer step from identical init on both
      paths; records the losses and the max-abs param divergence (layer-
      granular splits are bit-exact; within-layer splits reassociate at
      the last f32 ulp).
    - **schedule evidence**: dependency analysis of the compiled HLO's
      loop bodies (``parallel/overlap.py hlo_overlap_evidence``) — the
      layer-(k+1) gather collectives must be *compute-independent* inside
      the forward body (issuable before layer k's compute retires), and
      the backward body must carry its own independent re-gathers. On the
      CPU host this proves schedulability, not achieved overlap — that is
      the TPU followup's job.
    - **memory**: compiled temp bytes of both paths plus one gathered
      layer's size; asserts the decomposed path stays within ~2 gathered
      layers of default (``live_range_ok``) — the O(2/L) claim.

    Headline value = default/overlap step-time ratio (alternating
    min-of-reps against ambient load); vs_baseline >= 1.0 at ratio 0.9 =
    the neutrality-or-better bar (CPU collectives are cheap shared-memory
    copies, so parity is the honest expectation here; the win case needs
    real ICI latency to hide). Knobs: BENCH_DEPTH (default 8), BENCH_SEQ,
    BENCH_BATCH, BENCH_STEPS/BENCH_WARMUP.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.parallel.overlap import hlo_overlap_evidence
    from pytorch_ddp_template_tpu.parallel.sharding import (
        fsdp_reshard, shard_tree,
    )
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    depth = int(os.environ.get("BENCH_DEPTH", "0")) or 8
    seq = int(os.environ.get("BENCH_SEQ", "64"))
    vocab = 256
    devices = jax.devices()
    mesh = make_mesh(f"data:{len(devices)}", devices)
    # BENCH_BATCH is per-device, like every other mode; the batch dim must
    # divide the data axis
    batch_size = (PER_DEVICE_BATCH or 2) * len(devices)
    ids = np.random.default_rng(0).integers(0, vocab, (batch_size, seq))
    batch = {"input_ids": jax.device_put(
        np.asarray(ids, np.int32), NamedSharding(mesh, P("data")))}
    config = TrainingConfig(warmup_steps=0, max_grad_norm=1000.0)
    key = jax.random.PRNGKey(0)

    variants: dict[str, list] = {}
    layer_bytes = None
    for overlap in (False, True):
        model = GptDecoder(vocab_size=vocab, max_len=seq, num_layers=depth,
                           num_heads=2, head_dim=32, mlp_dim=128,
                           scan_layers=True, fsdp_overlap=overlap,
                           mesh=mesh if overlap else None)
        task = CausalLmTask(model)
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(config, total_steps=10_000)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, extra_vars=extra,
            opt_state=tx.init(params), rng=jax.random.clone(key),
        )
        state = shard_tree(state, mesh)
        state = state.replace(
            params=fsdp_reshard(state.params, mesh, prefer_dim=0),
            opt_state=fsdp_reshard(state.opt_state, mesh, prefer_dim=0),
        )
        if layer_bytes is None:
            stacked = state.params["decoder"]["layers"]
            layer_bytes = sum(
                l.size * l.dtype.itemsize for l in jax.tree.leaves(stacked)
            ) // depth
        compiled = make_train_step(task, tx, schedule).lower(
            state, batch).compile()
        variants["overlap" if overlap else "default"] = [compiled, state]

    # -- bit-parity leg: one step each from identical init ---------------
    stepped = {}
    for kind, (compiled, state) in variants.items():
        new_state, metrics = compiled(state, batch)
        stepped[kind] = (new_state, float(metrics["loss"]))
        variants[kind][1] = new_state  # donated input: thread the buffer
    parity = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(jax.tree.leaves(stepped["default"][0].params),
                        jax.tree.leaves(stepped["overlap"][0].params))
    )

    # -- step-time leg: alternating reps, min-of-reps ---------------------
    for kind, slot in variants.items():  # extra warmup beyond parity's step
        compiled, state = slot
        metrics = None
        for _ in range(max(WARMUP_STEPS - 1, 0)):
            state, metrics = compiled(state, batch)
        if metrics is not None:
            float(metrics["loss"])  # drain before the clock starts
        slot[1] = state
    step_ms = {}
    for rep in range(3):
        for kind, slot in variants.items():
            compiled, state = slot
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])  # host read = honest fence
            dt = time.perf_counter() - t0
            slot[1] = state
            assert np.isfinite(loss), f"non-finite loss {loss}"
            ms = 1e3 * dt / TIMED_STEPS
            step_ms[kind] = min(step_ms.get(kind, ms), ms)

    # -- schedule-evidence + memory legs ----------------------------------
    evidence = hlo_overlap_evidence(variants["overlap"][0].as_text())
    out_mem = {}
    live_range_ok = None
    try:
        t_def = variants["default"][0].memory_analysis().temp_size_in_bytes
        t_ovl = variants["overlap"][0].memory_analysis().temp_size_in_bytes
        out_mem = {"temp_default_mb": round(t_def / 1e6, 2),
                   "temp_overlap_mb": round(t_ovl / 1e6, 2)}
        live_range_ok = bool(t_ovl <= t_def + 2.5 * layer_bytes)
    except Exception:  # noqa: BLE001 - not all PJRT backends implement it
        pass

    ratio = step_ms["default"] / max(step_ms["overlap"], 1e-9)
    data_size = mesh.shape.get("data", 1)
    return {
        "metric": f"fsdp_overlap_step_ratio_{depth}L",
        "value": round(ratio, 3),
        "unit": "x_default_fsdp_step_time",
        # neutrality-or-better bar: ratio >= 0.9 passes (ambient-load
        # allowance on this host; the speedup case needs real ICI)
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
        "degenerate": data_size == 1,  # no collectives to overlap at DP=1
        "depth": depth,
        "seq_len": seq,
        "batch": batch_size,
        "timed_steps": TIMED_STEPS,
        "step_time_default_ms": round(step_ms["default"], 2),
        "step_time_overlap_ms": round(step_ms["overlap"], 2),
        "loss_default": stepped["default"][1],
        "loss_overlap": stepped["overlap"][1],
        "parity_max_abs_diff": parity,
        "hlo_prefetch_gather_independent":
            evidence["prefetch_gather_independent"],
        "hlo_bwd_regather_independent":
            evidence["bwd_regather_independent"],
        "hlo_bodies": evidence["bodies"],
        "layer_mb": round(layer_bytes / 1e6, 3),
        "live_range_ok": live_range_ok,
        **out_mem,
    }


def run_comms() -> dict:
    """Compressed-DDP proof (``--ddp_overlap`` + ``--grad_comm``,
    parallel/compress.py): GSPMD-default grad reduce vs the per-layer
    overlapped/compressed reduce on the same scanned, replicated stack.

    Four legs, sized for what THIS host can prove (the real multi-chip
    step-time pair rides in tools/tpu_followup.sh 9):

    - **bit-parity + neutrality**: one optimizer step from identical init
      under ``--grad_comm fp32`` on the plain-scan baseline vs the
      overlap path (records loss delta + max param divergence), then
      alternating min-of-reps step times. The overlap backward recomputes
      each block from its boundary activation (implicit block remat, by
      construction — the price of per-layer grad locality), so the
      FLOPs-matched neutrality pair is ``--scan_layers --remat`` vs
      ``--ddp_overlap``: that ratio carries the headline with
      run_overlap's 0.9 band (CPU collectives are cheap shared-memory
      copies — parity is the honest expectation; the win case needs real
      ICI latency to hide). The ratio against the NO-remat baseline is
      recorded too: on a comm-free host it prices the recompute
      (~fwd/(fwd+bwd) extra compute), which is what a TPU trades against
      hidden collective latency.
    - **HLO schedule evidence**: ``hlo_comms_evidence`` on the compiled
      overlap step — a dot-carrying scan body must contain the reduce
      collectives (>= num_layers independent per-layer reduce launches
      per step), where GSPMD-default keeps the grad all-reduce outside.
    - **wire bytes**: ``wire_bytes_per_step`` of the stacked tree per
      precision (int8 must be <= 0.3x fp32; bf16 0.5x).
    - **convergence parity**: N-step loss curves from identical init for
      fp32 vs int8+error-feedback vs int8-no-EF at a small constant LR
      (the tracking regime, where deviation measures compression fidelity
      rather than compounding trajectory chaos); reports each curve's
      mean abs deviation from the fp32 curve plus the final param-space
      distance — EF must deviate strictly less (the telescoping-error
      claim, measured end-to-end, not only asserted-by-unit).

    Knobs: BENCH_DEPTH (default 4), BENCH_SEQ, BENCH_BATCH,
    BENCH_STEPS/BENCH_WARMUP, BENCH_CONV_STEPS (default 120),
    BENCH_CONV_LR (default 0.005).
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.parallel.compress import (
        hlo_comms_evidence, wire_bytes_per_step,
    )
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import DATA_AXIS
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    depth = int(os.environ.get("BENCH_DEPTH", "0")) or 4
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    conv_steps = int(os.environ.get("BENCH_CONV_STEPS", "120"))
    conv_lr = float(os.environ.get("BENCH_CONV_LR", "0.005"))
    vocab = 256
    devices = jax.devices()
    mesh = make_mesh(f"data:{len(devices)}", devices)
    data_size = mesh.shape.get(DATA_AXIS, 1)
    batch_size = (PER_DEVICE_BATCH or 2) * len(devices)
    key = jax.random.PRNGKey(0)
    # schedule legs run WIDE (collective launches amortised over real
    # per-layer matmul work — the regime the schedule targets); the
    # convergence leg runs NARROW at a small constant LR (the verified
    # tracking regime, where deviation measures compression fidelity,
    # and 3x120 steps stay affordable on this host)
    WIDE = dict(num_heads=4, head_dim=32, mlp_dim=1024, seq=seq)
    NARROW = dict(num_heads=2, head_dim=32, mlp_dim=128, seq=64)

    def make_batch(spec_seq):
        ids = np.random.default_rng(0).integers(
            0, vocab, (batch_size, spec_seq))
        return {"input_ids": jax.device_put(
            np.asarray(ids, np.int32), NamedSharding(mesh, P("data")))}

    batches = {WIDE["seq"]: make_batch(WIDE["seq"])}
    if NARROW["seq"] not in batches:
        batches[NARROW["seq"]] = make_batch(NARROW["seq"])

    def build_state(spec, grad_comm="fp32", ddp_overlap=False, ef=False,
                    remat=False, lr=1e-2, schedule_kind="linear"):
        config = TrainingConfig(warmup_steps=0, max_grad_norm=1000.0,
                                learning_rate=lr, lr_schedule=schedule_kind)
        batch = batches[spec["seq"]]
        model = GptDecoder(vocab_size=vocab, max_len=spec["seq"],
                           num_layers=depth, num_heads=spec["num_heads"],
                           head_dim=spec["head_dim"],
                           mlp_dim=spec["mlp_dim"],
                           scan_layers=True, remat=remat,
                           ddp_overlap=ddp_overlap,
                           grad_comm=grad_comm, grad_error_feedback=ef,
                           mesh=mesh if ddp_overlap else None)
        task = CausalLmTask(model)
        params, extra = task.init(key, batch)
        residual = (extra.pop("comm_residual", None)
                    if isinstance(extra, dict) else None)
        tx, schedule = make_optimizer(config, total_steps=10_000)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, extra_vars=extra,
            opt_state=tx.init(params), rng=jax.random.clone(key),
            comm_residual=None,  # attached post-shard_tree, like the engine
        )
        state = shard_tree(state, mesh)
        if residual is not None:
            res_sh = NamedSharding(mesh, P(None, DATA_AXIS))
            state = state.replace(comm_residual=jax.tree.map(
                lambda x: jax.device_put(x, res_sh), residual))
        compiled = make_train_step(task, tx, schedule).lower(
            state, batch).compile()
        return compiled, state, batch

    variants: dict[str, list] = {}
    for kind, kwargs in (("default", {}),
                         ("default_remat", {"remat": True}),
                         ("overlap", {"ddp_overlap": True})):
        compiled, state, batch = build_state(WIDE, **kwargs)
        variants[kind] = [compiled, state]
        if kind == "overlap":
            stacked = nn.meta.unbox(state.params)["decoder"]["layers"]

    # -- bit-parity leg: one fp32 step each from identical init -----------
    stepped = {}
    for kind, slot in variants.items():
        new_state, metrics = slot[0](slot[1], batch)
        stepped[kind] = (new_state, float(metrics["loss"]))
        slot[1] = new_state  # donated input: thread the buffer
    parity = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(jax.tree.leaves(stepped["default"][0].params),
                        jax.tree.leaves(stepped["overlap"][0].params))
    )

    # -- step-time leg: alternating reps, min-of-reps ---------------------
    for kind, slot in variants.items():
        compiled, state = slot
        metrics = None
        for _ in range(max(WARMUP_STEPS - 1, 0)):
            state, metrics = compiled(state, batch)
        if metrics is not None:
            float(metrics["loss"])  # drain before the clock starts
        slot[1] = state
    step_ms = {}
    for rep in range(3):
        for kind, slot in variants.items():
            compiled, state = slot
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])  # host read = honest fence
            dt = time.perf_counter() - t0
            slot[1] = state
            assert np.isfinite(loss), f"non-finite loss {loss}"
            ms = 1e3 * dt / TIMED_STEPS
            step_ms[kind] = min(step_ms.get(kind, ms), ms)

    # -- HLO + wire-bytes legs --------------------------------------------
    evidence = hlo_comms_evidence(variants["overlap"][0].as_text(), depth)
    wire = {m: wire_bytes_per_step(stacked, data_size, m)
            for m in ("fp32", "bf16", "int8")}

    # -- convergence-parity leg: fp32 vs int8+EF vs int8-no-EF ------------
    curves: dict[str, list[float]] = {}
    finals: dict[str, list] = {}
    for kind, kwargs in (
            ("fp32", {"ddp_overlap": True}),
            ("int8_ef", {"ddp_overlap": True, "grad_comm": "int8",
                         "ef": True}),
            ("int8_no_ef", {"ddp_overlap": True, "grad_comm": "int8"})):
        compiled, state, conv_batch = build_state(
            NARROW, lr=conv_lr, schedule_kind="constant", **kwargs)
        losses = []
        for _ in range(conv_steps):
            state, metrics = compiled(state, conv_batch)
            losses.append(float(metrics["loss"]))
        curves[kind] = losses
        finals[kind] = jax.tree.leaves(state.params)
    ref = np.asarray(curves["fp32"])
    dev_ef = float(np.mean(np.abs(np.asarray(curves["int8_ef"]) - ref)))
    dev_no_ef = float(np.mean(np.abs(np.asarray(curves["int8_no_ef"]) - ref)))

    def param_dist(kind):  # secondary, f32-print-resolution-free metric
        return float(jnp.sqrt(sum(
            jnp.sum((a.astype(jnp.float32) - b.astype(jnp.float32)) ** 2)
            for a, b in zip(finals[kind], finals["fp32"]))))

    ratio = step_ms["default_remat"] / max(step_ms["overlap"], 1e-9)
    return {
        "metric": f"ddp_overlap_step_ratio_{depth}L",
        "value": round(ratio, 3),
        # FLOPs-matched pair: both variants recompute each block once in
        # backward (remat-scan baseline vs the overlap path's implicit
        # block remat) — the schedule is the only difference
        "unit": "x_remat_scan_ddp_step_time",
        # neutrality-or-better bar: ratio >= 0.9 passes (ambient-load
        # allowance on this host; the speedup case needs real ICI)
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
        "degenerate": data_size == 1,  # no cross-replica bytes at DP=1
        "depth": depth,
        "seq_len": seq,
        "batch": batch_size,
        "model_dims": {k: v for k, v in WIDE.items() if k != "seq"},
        "conv_model_dims": NARROW,
        "timed_steps": TIMED_STEPS,
        "step_time_default_ms": round(step_ms["default"], 2),
        "step_time_default_remat_ms": round(step_ms["default_remat"], 2),
        "step_time_overlap_ms": round(step_ms["overlap"], 2),
        # vs the save-everything baseline: prices the implicit block
        # remat on a host with free comms (the cost a TPU trades against
        # hidden collective latency)
        "step_ratio_vs_no_remat": round(
            step_ms["default"] / max(step_ms["overlap"], 1e-9), 3),
        "loss_default": stepped["default"][1],
        "loss_overlap": stepped["overlap"][1],
        "parity_max_abs_diff": parity,
        "hlo_per_layer_reduce": evidence["per_layer_reduce"],
        "hlo_bwd_body_collectives": evidence["bwd_body_collectives"],
        "hlo_inscan_reduce_collectives":
            evidence["inscan_reduce_collectives"],
        "hlo_bodies": evidence["bodies"],
        "wire_mb_fp32": round(wire["fp32"] / 1e6, 3),
        "wire_mb_bf16": round(wire["bf16"] / 1e6, 3),
        "wire_mb_int8": round(wire["int8"] / 1e6, 3),
        "wire_int8_vs_fp32": round(wire["int8"] / wire["fp32"], 4),
        "wire_bf16_vs_fp32": round(wire["bf16"] / wire["fp32"], 4),
        "conv_steps": conv_steps,
        "conv_lr": conv_lr,
        "loss_dev_int8_ef": dev_ef,
        "loss_dev_int8_no_ef": dev_no_ef,
        "param_dist_int8_ef": param_dist("int8_ef"),
        "param_dist_int8_no_ef": param_dist("int8_no_ef"),
        "ef_beats_no_ef": bool(dev_ef < dev_no_ef),
        "final_loss_fp32": curves["fp32"][-1],
        "final_loss_int8_ef": curves["int8_ef"][-1],
        "final_loss_int8_no_ef": curves["int8_no_ef"][-1],
    }


def run_tp() -> dict:
    """Decomposed-TP proof (``--tp_overlap``, parallel/collective_matmul.py
    + the ring LM head in ops/lm_head.py): GSPMD-default tensor parallelism
    vs the ring-scheduled execution of the same Megatron-sharded stack on a
    ``data x model`` mesh.

    Five legs, sized for what THIS host can prove (the real multi-chip
    step-time pair rides in tools/tpu_followup.sh 10):

    - **bit/last-ulp parity**: one optimizer step from identical init on
      the GSPMD-default fused-head path vs the ring path (records loss
      delta + max param divergence — the column ops are bit-exact by
      construction, the row ops/ring head reassociate cross-device sums at
      the last f32 ulp), plus a direct column-op probe on the bench
      geometry (``col_bit_exact``).
    - **HLO schedule evidence**: ``hlo_tp_evidence`` on a loss-only
      lowering (forward rings) and the full train step — both must carry
      dot-carrying loop bodies whose ppermutes touch only loop-carried
      state (compute-independent), and the full step strictly more of them
      (its backward rings). On the CPU host this proves schedulability,
      not achieved overlap — that is the TPU followup's job.
    - **step-time neutrality**: alternating min-of-reps default-vs-ring
      pair. Both paths run identical FLOPs (same matmuls, same blockwise
      head recompute in backward — the schedule is the only difference),
      so run_overlap's 0.9 band carries the headline.
    - **wire accounting**: ``tp_wire_bytes_per_step`` for the bench
      geometry, stack and LM head split out (the r9 ``grad_wire_mb``
      convention applied to the model axis).
    - **memory / live range**: compiled temp bytes of a THIRD variant that
      materialises the (B, T, V) logits tensor (``fused_head=False``) vs
      the ring path — the ring head must come in under it by at least half
      the local logits tensor (``live_range_ok``), the r8-style evidence
      that the logits never exist on any shard.

    Degenerate contract: on a single chip there is no ``model`` axis to
    decompose — emits ``degenerate: true`` with ``value 0`` (the r8
    single-chip convention; the followup script flags these).

    Knobs: BENCH_DEPTH (default 4), BENCH_SEQ (64), BENCH_VOCAB (4096),
    BENCH_TP (model-axis size, default 2), BENCH_BATCH (per data-shard),
    BENCH_STEPS/BENCH_WARMUP.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.parallel.collective_matmul import (
        hlo_tp_evidence, tp_column_dense, tp_wire_bytes_per_step,
    )
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    depth = int(os.environ.get("BENCH_DEPTH", "0")) or 4
    seq = int(os.environ.get("BENCH_SEQ", "64"))
    vocab = int(os.environ.get("BENCH_VOCAB", "4096"))
    tp_size = int(os.environ.get("BENCH_TP", "2"))
    devices = jax.devices()
    metric = f"tp_overlap_step_ratio_{depth}L"
    unit = "x_default_tp_step_time"
    if len(devices) < 2 or len(devices) % tp_size:
        return {  # single-chip: no model axis to decompose (r8 convention)
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "degenerate": True,
            "platform": devices[0].platform,
            "device_kind": devices[0].device_kind,
            "n_devices": len(devices), "tp_size": tp_size,
            "note": "tp decomposition needs a model:N>=2 mesh axis",
        }
    data_size = len(devices) // tp_size
    mesh = make_mesh(f"data:{data_size},model:{tp_size}", devices)
    num_heads, head_dim, mlp_dim = 4, 32, 512
    embed = num_heads * head_dim
    batch_size = (PER_DEVICE_BATCH or 2) * data_size
    ids = np.random.default_rng(0).integers(0, vocab, (batch_size, seq))
    batch = {"input_ids": jax.device_put(
        np.asarray(ids, np.int32), NamedSharding(mesh, P("data")))}
    config = TrainingConfig(warmup_steps=0, max_grad_norm=1000.0)
    key = jax.random.PRNGKey(0)

    def build_variant(kind):
        model = GptDecoder(
            vocab_size=vocab, max_len=seq, num_layers=depth,
            num_heads=num_heads, head_dim=head_dim, mlp_dim=mlp_dim,
            scan_layers=True,
            fused_head=kind != "naive",
            tp_overlap=kind == "tp",
            mesh=mesh if kind == "tp" else None)
        task = CausalLmTask(model)
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(config, total_steps=10_000)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, extra_vars=extra,
            opt_state=tx.init(params), rng=jax.random.clone(key),
        )
        state = shard_tree(state, mesh)
        compiled = make_train_step(task, tx, schedule).lower(
            state, batch).compile()
        return task, compiled, state

    variants: dict[str, list] = {
        kind: list(build_variant(kind))
        for kind in ("naive", "default", "tp")
    }

    # -- parity leg: one step each from identical init --------------------
    stepped = {}
    for kind, slot in variants.items():
        new_state, metrics = slot[1](slot[2], batch)
        stepped[kind] = (new_state, float(metrics["loss"]))
        slot[2] = new_state  # donated input: thread the buffer
    parity = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(jax.tree.leaves(stepped["default"][0].params),
                        jax.tree.leaves(stepped["tp"][0].params))
    )
    # direct column-op probe on the bench geometry: bit-exact, not close
    rngp = np.random.default_rng(1)
    xp = jnp.asarray(rngp.standard_normal((data_size, seq, embed)),
                     jnp.float32)
    wp = jnp.asarray(rngp.standard_normal((embed, mlp_dim)) * 0.1,
                     jnp.float32)
    bp = jnp.asarray(rngp.standard_normal((mlp_dim,)) * 0.1, jnp.float32)
    col = jax.jit(lambda x, w, b: tp_column_dense(x, [w], [b], mesh)[0])(
        xp, wp, bp)
    col_bit_exact = bool(jnp.all(col == xp @ wp + bp))

    # -- step-time leg: alternating reps, min-of-reps ---------------------
    timed = {k: variants[k] for k in ("default", "tp")}
    for kind, slot in timed.items():
        compiled, state = slot[1], slot[2]
        metrics = None
        for _ in range(max(WARMUP_STEPS - 1, 0)):
            state, metrics = compiled(state, batch)
        if metrics is not None:
            float(metrics["loss"])  # drain before the clock starts
        slot[2] = state
    step_ms = {}
    for rep in range(3):
        for kind, slot in timed.items():
            compiled, state = slot[1], slot[2]
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])  # host read = honest fence
            dt = time.perf_counter() - t0
            slot[2] = state
            assert np.isfinite(loss), f"non-finite loss {loss}"
            ms = 1e3 * dt / TIMED_STEPS
            step_ms[kind] = min(step_ms.get(kind, ms), ms)

    # -- HLO schedule-evidence leg ----------------------------------------
    tp_task = variants["tp"][0]
    params_u = nn.meta.unbox(variants["tp"][2].params)

    def tp_loss(p):
        return tp_task.loss(p, {}, batch, None, train=False)[0]

    fwd_compiled = jax.jit(tp_loss).lower(params_u).compile()
    ev_fwd = hlo_tp_evidence(fwd_compiled.as_text())
    ev_full = hlo_tp_evidence(variants["tp"][1].as_text())
    bwd_rings = (ev_full["independent_ring_bodies"]
                 - ev_fwd["independent_ring_bodies"])

    # -- wire-accounting leg ----------------------------------------------
    wires = tp_wire_bytes_per_step(
        batch=batch_size, seq=seq, embed=embed, num_layers=depth,
        n=tp_size, vocab=vocab)

    # -- memory / live-range leg ------------------------------------------
    # local logits tensor the naive head materialises: (B/data, T, V/model)
    # f32 per shard (GSPMD shards the vocab dim over `model`)
    logits_local = (batch_size // data_size) * seq * (vocab // tp_size) * 4
    out_mem = {}
    live_range_ok = None
    try:
        temps = {k: v[1].memory_analysis().temp_size_in_bytes
                 for k, v in variants.items()}
        out_mem = {f"temp_{k}_mb": round(t / 1e6, 2)
                   for k, t in temps.items()}
        out_mem["logits_local_mb"] = round(logits_local / 1e6, 2)
        live_range_ok = bool(
            temps["tp"] + logits_local // 2 <= temps["naive"])
    except Exception:  # noqa: BLE001 - not all PJRT backends implement it
        pass

    ratio = step_ms["default"] / max(step_ms["tp"], 1e-9)
    return {
        "metric": metric,
        "value": round(ratio, 3),
        # FLOPs-matched pair: same matmuls, same blockwise-head backward
        # recompute — the ring schedule is the only difference
        "unit": unit,
        # neutrality-or-better bar: ratio >= 0.9 passes (ambient-load
        # allowance on this host; the speedup case needs real ICI)
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
        "degenerate": False,
        "tp_size": tp_size,
        "data_size": data_size,
        "depth": depth,
        "seq_len": seq,
        "vocab": vocab,
        "batch": batch_size,
        "model_dims": {"num_heads": num_heads, "head_dim": head_dim,
                       "mlp_dim": mlp_dim},
        "timed_steps": TIMED_STEPS,
        "step_time_default_ms": round(step_ms["default"], 2),
        "step_time_tp_ms": round(step_ms["tp"], 2),
        "loss_naive": stepped["naive"][1],
        "loss_default": stepped["default"][1],
        "loss_tp": stepped["tp"][1],
        "parity_max_abs_diff": parity,
        "col_bit_exact": col_bit_exact,
        "hlo_fwd_ring_bodies": ev_fwd["ring_bodies"],
        "hlo_fwd_independent_ring_bodies":
            ev_fwd["independent_ring_bodies"],
        "hlo_full_ring_bodies": ev_full["ring_bodies"],
        "hlo_full_independent_ring_bodies":
            ev_full["independent_ring_bodies"],
        "hlo_bwd_independent_ring_bodies": bwd_rings,
        "hlo_fwd_ring_independent": bool(
            ev_fwd["independent_ring_bodies"] > 0),
        "hlo_bwd_ring_independent": bool(bwd_rings > 0),
        "tp_wire_mb_stack": round(wires["stack"] / 1e6, 3),
        "tp_wire_mb_head": round(wires["head"] / 1e6, 3),
        "tp_wire_mb_per_step": round(
            (wires["stack"] + wires["head"]) / 1e6, 3),
        "live_range_ok": live_range_ok,
        **out_mem,
    }


def run_overlap3d() -> dict:
    """Composed-schedule proof (round 11, parallel/schedule.py): the
    unified decomposed scan running fsdp×tp — data-axis weight gathers
    pipelined one layer ahead WHILE the block's ring collective matmuls
    rotate over ``model`` — vs the FLOPs-matched GSPMD default on the
    same ``data × model`` mesh.

    Legs, sized for what THIS host can prove (the real multi-chip pair
    rides in tools/tpu_followup.sh 11):

    - **parity**: one optimizer step from identical init, composed vs
      default (loss delta + max param divergence; ring reassociation +
      gather psums = last-f32-ulp), plus an eval-mode loss/grad probe of
      the ddp×tp composition against the replicated GSPMD default.
    - **HLO schedule evidence**: ``hlo_composed_evidence`` on the
      composed train step — at least one dot-carrying scanned body whose
      gather-family collectives (data axis) are compute-independent AND
      that reaches compute-independent ring ppermutes (model axis): both
      axes' collectives schedulable in ONE scanned body.
    - **step-time neutrality**: alternating min-of-reps pair. The
      default runs ``--remat`` so both paths recompute blocks in
      backward (the composed path's recompute-from-boundary is implicit
      block remat — the r9 FLOPs-matching convention); the schedule is
      the only difference, 0.9 band carries the headline.
    - **wire accounting**: the model-axis TP bytes for the bench
      geometry (the fsdp gathers move layout-dependent bytes GSPMD also
      moves — not double-counted).

    Degenerate contract: fewer than 4 devices (no data×model mesh worth
    composing) emits ``degenerate: true`` with value 0 (r8 convention).

    Knobs: BENCH_DEPTH (default 4), BENCH_SEQ (64), BENCH_VOCAB (4096),
    BENCH_TP (model-axis size, default 2), BENCH_BATCH (per data-shard),
    BENCH_STEPS/BENCH_WARMUP.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.parallel.collective_matmul import (
        tp_wire_bytes_per_step,
    )
    from pytorch_ddp_template_tpu.parallel.schedule import (
        hlo_composed_evidence,
    )
    from pytorch_ddp_template_tpu.parallel.sharding import (
        fsdp_reshard, shard_tree,
    )
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    depth = int(os.environ.get("BENCH_DEPTH", "0")) or 4
    seq = int(os.environ.get("BENCH_SEQ", "64"))
    vocab = int(os.environ.get("BENCH_VOCAB", "4096"))
    tp_size = int(os.environ.get("BENCH_TP", "2"))
    devices = jax.devices()
    metric = f"overlap3d_step_ratio_{depth}L"
    unit = "x_default_step_time"
    if (len(devices) < 4 or len(devices) % tp_size
            or len(devices) // tp_size < 2):
        return {  # no data×model mesh worth composing (r8 convention)
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "degenerate": True,
            "platform": devices[0].platform,
            "device_kind": devices[0].device_kind,
            "n_devices": len(devices), "tp_size": tp_size,
            "note": "composed fsdp×tp needs data:N>=2 × model:M>=2",
        }
    data_size = len(devices) // tp_size
    mesh = make_mesh(f"data:{data_size},model:{tp_size}", devices)
    num_heads, head_dim, mlp_dim = 4, 32, 512
    embed = num_heads * head_dim
    batch_size = (PER_DEVICE_BATCH or 2) * data_size
    ids = np.random.default_rng(0).integers(0, vocab, (batch_size, seq))
    batch = {"input_ids": jax.device_put(
        np.asarray(ids, np.int32), NamedSharding(mesh, P("data")))}
    config = TrainingConfig(warmup_steps=0, max_grad_norm=1000.0)
    key = jax.random.PRNGKey(0)

    def build_variant(kind):
        model = GptDecoder(
            vocab_size=vocab, max_len=seq, num_layers=depth,
            num_heads=num_heads, head_dim=head_dim, mlp_dim=mlp_dim,
            scan_layers=True, fused_head=True,
            # FLOPs matching: the composed backward recomputes each block
            # from its boundary activation (implicit block remat), so the
            # default pairs with explicit remat-scan (r9 convention)
            remat=kind == "default",
            fsdp_overlap=kind == "composed",
            tp_overlap=kind == "composed",
            mesh=mesh if kind == "composed" else None)
        task = CausalLmTask(model)
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(config, total_steps=10_000)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, extra_vars=extra,
            opt_state=tx.init(params), rng=jax.random.clone(key),
        )
        state = shard_tree(state, mesh)
        if kind in ("default", "composed"):
            state = state.replace(
                params=fsdp_reshard(state.params, mesh, prefer_dim=0),
                opt_state=fsdp_reshard(state.opt_state, mesh,
                                       prefer_dim=0))
        compiled = make_train_step(task, tx, schedule).lower(
            state, batch).compile()
        return [task, compiled, state]

    variants = {kind: build_variant(kind)
                for kind in ("default", "composed")}

    # -- parity leg: one optimizer step each from identical init ----------
    stepped = {}
    for kind, slot in variants.items():
        new_state, metrics = slot[1](slot[2], batch)
        stepped[kind] = (new_state, float(metrics["loss"]))
        slot[2] = new_state  # donated input: thread the buffer
    parity = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(jax.tree.leaves(stepped["default"][0].params),
                        jax.tree.leaves(stepped["composed"][0].params))
    )

    # -- ddp×tp probe: eval-mode loss + grads vs the replicated default ----
    probe_model = GptDecoder(
        vocab_size=vocab, max_len=seq, num_layers=depth,
        num_heads=num_heads, head_dim=head_dim, mlp_dim=mlp_dim,
        scan_layers=True, fused_head=True, ddp_overlap=True,
        tp_overlap=True, mesh=mesh)
    probe_task = CausalLmTask(probe_model)
    ref_task = CausalLmTask(GptDecoder(
        vocab_size=vocab, max_len=seq, num_layers=depth,
        num_heads=num_heads, head_dim=head_dim, mlp_dim=mlp_dim,
        scan_layers=True, fused_head=True))
    probe_params, _ = ref_task.init(key, batch)
    probe_params = nn.meta.unbox(probe_params)

    def loss_of(task):
        return jax.jit(jax.value_and_grad(
            lambda p: task.loss(p, {}, batch, None, train=False)[0]))

    lr_, gr_ = loss_of(ref_task)(probe_params)
    lp_, gp_ = loss_of(probe_task)(probe_params)
    ddp_tp_parity = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(jax.tree.leaves(gr_), jax.tree.leaves(gp_)))

    # -- HLO schedule-evidence leg ----------------------------------------
    ev = hlo_composed_evidence(variants["composed"][1].as_text())

    # -- step-time leg: alternating reps, min-of-reps ---------------------
    for kind, slot in variants.items():
        compiled, state = slot[1], slot[2]
        metrics = None
        for _ in range(max(WARMUP_STEPS - 1, 0)):
            state, metrics = compiled(state, batch)
        if metrics is not None:
            float(metrics["loss"])  # drain before the clock starts
        slot[2] = state
    step_ms = {}
    for rep in range(3):
        for kind, slot in variants.items():
            compiled, state = slot[1], slot[2]
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])  # host read = honest fence
            dt = time.perf_counter() - t0
            slot[2] = state
            assert np.isfinite(loss), f"non-finite loss {loss}"
            ms = 1e3 * dt / TIMED_STEPS
            step_ms[kind] = min(step_ms.get(kind, ms), ms)

    # -- wire-accounting leg ----------------------------------------------
    wires = tp_wire_bytes_per_step(
        batch=batch_size, seq=seq, embed=embed, num_layers=depth,
        n=tp_size, vocab=vocab)

    ratio = step_ms["default"] / max(step_ms["composed"], 1e-9)
    return {
        "metric": metric,
        "value": round(ratio, 3),
        # FLOPs-matched pair (remat default vs recompute-from-boundary
        # composed); neutrality-or-better bar: ratio >= 0.9 passes
        "unit": unit,
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
        "degenerate": False,
        "tp_size": tp_size,
        "data_size": data_size,
        "depth": depth,
        "seq_len": seq,
        "vocab": vocab,
        "batch": batch_size,
        "model_dims": {"num_heads": num_heads, "head_dim": head_dim,
                       "mlp_dim": mlp_dim},
        "timed_steps": TIMED_STEPS,
        "step_time_default_ms": round(step_ms["default"], 2),
        "step_time_composed_ms": round(step_ms["composed"], 2),
        "loss_default": stepped["default"][1],
        "loss_composed": stepped["composed"][1],
        "parity_max_abs_diff": parity,
        "loss_ddp_tp_probe": float(lp_),
        "loss_ddp_tp_ref": float(lr_),
        "ddp_tp_parity_max_abs_diff": ddp_tp_parity,
        "hlo_independent_gather_bodies": ev["independent_gather_bodies"],
        "hlo_independent_ring_bodies": ev["independent_ring_bodies"],
        "hlo_bodies_with_both_independent":
            len(ev["bodies_with_both_independent"]),
        "hlo_composed_overlap_independent":
            ev["composed_overlap_independent"],
        "tp_wire_mb_stack": round(wires["stack"] / 1e6, 3),
        "tp_wire_mb_head": round(wires["head"] / 1e6, 3),
        "tp_wire_mb_per_step": round(
            (wires["stack"] + wires["head"]) / 1e6, 3),
    }


def run_obs() -> dict:
    """Observability proof (round 12, ``pytorch_ddp_template_tpu/obs/``):
    the flight recorder must be ~free when healthy and complete when not.

    Legs, sized for what THIS host can prove:

    - **overhead**: the jitted step with the in-step health pack compiled
      in AND the per-step sentry feed flowing through the production
      ``AsyncTelemetry`` drain (``kind="health"`` → ``AnomalySentry``)
      vs the plain step with neither — alternating min-of-reps over one
      staged batch (the r11 convention against ambient noise on this
      host). ``value`` = plain/obs step time; the 0.9 band carries the
      headline (obs may cost at most ~11% — measured, it is noise-level:
      a handful of fused reductions + a queue put).
    - **flight record**: a real production ``Trainer.train()`` run with
      ``--anomaly halt`` and a NaN injected into the step metrics at a
      fixed step (a wrapper around the jitted step — the injection is in
      the *drained telemetry*, exactly where a real NaN surfaces). The
      record asserts the triage bundle is complete
      (``obs/sentry.BUNDLE_FILES`` + the post-trigger profiler trace)
      and the run halted early through the stop machinery.
    - **hlo report**: ``schedule_report`` over the health-step HLO — the
      collective census the ``--hlo_report`` flag would log at startup.

    Knobs: BENCH_MODEL (default mlp-wide — device-bound steps; sub-ms toy
    steps would measure GIL contention, not overhead), BENCH_BATCH,
    BENCH_STEPS/BENCH_WARMUP, BENCH_NAN_STEP, BENCH_OUTPUT.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.obs.hlo_report import schedule_report
    from pytorch_ddp_template_tpu.obs.sentry import BUNDLE_FILES
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import (
        SENTRY_FEED_KEYS, Trainer,
    )

    model = os.environ.get("BENCH_MODEL") or "mlp-wide"
    per_device = PER_DEVICE_BATCH or default_batch(model)
    n_dev = len(jax.devices())
    global_batch = per_device * n_dev
    out_base = os.environ.get("BENCH_OUTPUT", "/tmp/bench_obs")
    metric = "obs_overhead_ratio"
    unit = "x_plain_step_time"

    base_cfg = dict(
        model=model, mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device, bf16=True,
        dataset_size=max(global_batch * 4, 512), warmup_steps=0,
        max_grad_norm=1000.0, max_steps=WARMUP_STEPS + TIMED_STEPS,
        logging_steps=0, save_steps=0, resume=False,
    )
    config = TrainingConfig(**base_cfg, output_dir=out_base + "_plain")
    ctx = rt_init(config)

    # -- overhead leg: plain step vs health-pack + sentry-fed step --------
    def build_variant(health: bool):
        cfg = TrainingConfig(**{
            **base_cfg, "health_pack": health,
            "anomaly": "warn" if health else "off",
            "output_dir": out_base + ("_obs" if health else "_plain")})
        task, ds = build(model, cfg, mesh=ctx.mesh)
        trainer = Trainer(cfg, ctx, task, ds)
        state, _ = trainer.restore_or_init()
        batch = next(iter(trainer.loader.epoch(0)))
        return {"trainer": trainer, "state": state, "batch": batch}

    variants = {kind: build_variant(kind == "obs")
                for kind in ("plain", "obs")}
    for slot in variants.values():  # compile + warm outside the clock
        trainer, batch = slot["trainer"], slot["batch"]
        state, metrics = trainer.train_step(slot["state"], batch)
        for _ in range(max(WARMUP_STEPS - 1, 0)):
            state, metrics = trainer.train_step(state, batch)
        float(metrics["loss"])  # drain before any clock starts
        slot["state"] = state

    step_ms: dict[str, float] = {}
    emitted = 0
    for rep in range(3):
        for kind, slot in variants.items():
            trainer, batch = slot["trainer"], slot["batch"]
            state = slot["state"]
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                state, metrics = trainer.train_step(state, batch)
                if kind == "obs":
                    # the production per-step feed: device arrays into the
                    # async queue; the drain thread converts and runs the
                    # sentry (steady loss — it must NOT trigger)
                    emitted += 1
                    trainer.telemetry.emit(
                        emitted,
                        {k: metrics[k] for k in SENTRY_FEED_KEYS
                         if k in metrics},
                        kind="health")
            loss = float(metrics["loss"])  # host read = honest fence
            dt = time.perf_counter() - t0
            slot["state"] = state
            assert np.isfinite(loss), f"non-finite loss {loss}"
            ms = 1e3 * dt / TIMED_STEPS
            step_ms[kind] = min(step_ms.get(kind, ms), ms)
    # -- hlo-report leg: the census --hlo_report would log at startup -----
    obs_trainer = variants["obs"]["trainer"]
    hlo = schedule_report(
        obs_trainer.train_step.lower(
            variants["obs"]["state"], variants["obs"]["batch"]
        ).compile().as_text())
    # close() drains the async queue inline — only AFTER it returns has
    # the sentry seen every emitted record, so the false-positive check
    # and the ring snapshot belong here, not racing the drain thread
    for slot in variants.values():
        slot["trainer"].telemetry.close()
    assert obs_trainer.sentry is not None and not obs_trainer.sentry.triggered, \
        "sentry false-positive on a healthy run"
    ring_len = len(obs_trainer.sentry.records())

    # -- flight-record leg: injected NaN through the production loop ------
    nan_step = int(os.environ.get("BENCH_NAN_STEP", "12"))
    flight_out = out_base + "_flight"
    import shutil

    shutil.rmtree(flight_out, ignore_errors=True)
    fl_cfg = TrainingConfig(
        model="mlp", mesh=f"data:{n_dev}",
        per_device_train_batch_size=4, dataset_size=512,
        warmup_steps=0, max_grad_norm=1000.0,
        max_steps=max(nan_step + 24, 40), logging_steps=0, save_steps=0,
        resume=False, anomaly="halt", output_dir=flight_out)
    fl_task, fl_ds = build("mlp", fl_cfg, mesh=ctx.mesh)
    fl_trainer = Trainer(fl_cfg, ctx, fl_task, fl_ds)
    orig_step = fl_trainer.train_step
    calls = {"n": 0}

    def poisoned(state, batch, *rest):
        new_state, m = orig_step(state, batch, *rest)
        calls["n"] += 1
        if calls["n"] == nan_step:
            m = dict(m)
            m["loss"] = m["loss"] * jnp.float32(float("nan"))
        return new_state, m

    fl_trainer.train_step = poisoned
    fl_state = fl_trainer.train()
    halted_at = int(fl_state.step)
    from pathlib import Path

    bundles = sorted((Path(flight_out) / "flight_records").glob("step_*"))
    bundle_files: list[str] = []
    complete = False
    if bundles:
        bundle_files = sorted(p.name for p in bundles[0].iterdir())
        complete = (all(f in bundle_files for f in BUNDLE_FILES)
                    and "profile" in bundle_files)

    ratio = step_ms["plain"] / max(step_ms["obs"], 1e-9)
    return {
        "metric": metric,
        "value": round(ratio, 3),
        # health-pack + sentry vs plain, same model/batch/mesh; the 0.9
        # band carries the headline (>= 0.9 = obs costs at most ~11%)
        "unit": unit,
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "model": model,
        "global_batch": global_batch,
        "timed_steps": TIMED_STEPS,
        "step_time_plain_ms": round(step_ms["plain"], 2),
        "step_time_obs_ms": round(step_ms["obs"], 2),
        "sentry_ring_len": ring_len,
        "sentry_false_positive": bool(obs_trainer.sentry.triggered),
        # flight-record leg: the bundle a real NaN'd run would leave
        "nan_injected_at_step": nan_step,
        "flight_halted_at_step": halted_at,
        "flight_halted_early": halted_at < fl_cfg.max_steps,
        "flight_bundle_files": bundle_files,
        "flight_bundle_complete": complete,
        # hlo-report leg: the startup census (--hlo_report's data)
        "hlo_collective_ops": {k: v["count"] for k, v in hlo["ops"].items()},
        "hlo_wire_mb_estimate": hlo["wire_mb_estimate"],
        "hlo_gather_independent_bodies":
            hlo["gather"]["independent_bodies"],
        "hlo_independent_ring_bodies":
            hlo["ring"]["independent_ring_bodies"],
    }


def run_perf() -> dict:
    """Performance-attribution proof (round 13, ``obs/attribution.py`` +
    ``obs/goodput.py``): the step-time X-ray must be ~free when on and
    arithmetically honest in what it reports.

    Legs, sized for what THIS host can prove (real-MFU numbers ride
    tools/tpu_followup.sh 13):

    - **neutrality**: the FULL production loop (``Trainer.train()`` —
      annotations, goodput accounting, perf snapshots at the logging
      cadence) with ``--perf_report`` + phase annotations ON vs both
      OFF, same model/batch/mesh, alternating fresh-trainer reps with
      min-of-reps steady-state step time (r11/r12 convention against
      ambient load). ``value`` = plain/perf step-time ratio; the 0.9
      band carries the headline.
    - **MFU sanity**: a production run with a peak chosen by priority —
      BENCH_PEAK_TFLOPS, else the PEAK_FLOPS spec table (real hardware:
      the reported MFU is the TRUE one, comparable with
      tools/mfu_probe.py), else calibration at 4x the achieved rate
      (CPU only — PEAK_FLOPS has no CPU entry BY DESIGN, and the
      calibration pins the expectation near 0.25). The leg then
      re-derives MFU from the cost model's FLOPs over the run's
      INDEPENDENT ``StepTimer`` mean step time and asserts the two
      agree (``mfu_consistent``) — the pipeline from cost_analysis
      through the attribution's interval walls is self-consistent, and
      MFU is in (0, 1].
    - **attribution + goodput**: the same run's fractional breakdown
      must sum to ~1.0, and ``goodput.json`` must exist with the full
      bucket set.

    Knobs: BENCH_MODEL (default mlp-wide — device-bound steps),
    BENCH_BATCH, BENCH_STEPS/BENCH_WARMUP, BENCH_LOG_STEPS,
    BENCH_PEAK_TFLOPS (skip the calibration), BENCH_OUTPUT.
    """
    import jax

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer
    from pytorch_ddp_template_tpu.utils.profiler import set_phase_annotations

    model = os.environ.get("BENCH_MODEL") or "mlp-wide"
    per_device = PER_DEVICE_BATCH or default_batch(model)
    n_dev = len(jax.devices())
    global_batch = per_device * n_dev
    out_base = os.environ.get("BENCH_OUTPUT", "/tmp/bench_perf")
    log_steps = int(os.environ.get("BENCH_LOG_STEPS", "5"))
    total_steps = WARMUP_STEPS + TIMED_STEPS

    base_cfg = dict(
        model=model, mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device, bf16=True,
        dataset_size=max(global_batch * (total_steps + 2), 512),
        warmup_steps=0, max_grad_norm=1000.0, max_steps=total_steps,
        logging_steps=log_steps, save_steps=0, resume=False,
    )
    ctx = rt_init(TrainingConfig(**base_cfg, output_dir=out_base + "_init"))

    def run_variant(kind: str, rep: int, peak_tflops: float = 0.0):
        """One full production-loop run; returns the finished Trainer."""
        perf = kind == "perf"
        set_phase_annotations(perf)
        try:
            cfg = TrainingConfig(**{
                **base_cfg, "perf_report": perf,
                "peak_tflops": peak_tflops,
                "output_dir": f"{out_base}_{kind}_{rep}"})
            import shutil

            shutil.rmtree(cfg.output_dir, ignore_errors=True)
            task, ds = build(model, cfg, mesh=ctx.mesh)
            trainer = Trainer(cfg, ctx, task, ds)
            trainer.train()
            return trainer
        finally:
            set_phase_annotations(True)

    # -- neutrality leg: alternating fresh-run reps, min-of-reps ----------
    step_ms: dict[str, float] = {}
    flops_per_step = 0.0
    for rep in range(3):
        for kind in ("plain", "perf"):
            trainer = run_variant(kind, rep)
            ms = trainer.step_timer.summary().get("step_time_mean_ms")
            if ms is None:
                raise RuntimeError("timed window produced no step samples")
            step_ms[kind] = min(step_ms.get(kind, ms), ms)
            if kind == "perf" and trainer.perf is not None:
                flops_per_step = trainer.perf.cost_model["flops_per_step"]
    ratio = step_ms["plain"] / max(step_ms["perf"], 1e-9)
    if flops_per_step <= 0:
        # cost analysis is best-effort (cost_of returns zeros when the
        # backend exposes none): without FLOPs there is no MFU to sanity-
        # check on ANY peak source — fail here with the true cause, not
        # after the sanity run with a misleading missing-records error
        raise RuntimeError(
            "cost analysis reported no FLOPs for the compiled step; the "
            "MFU-sanity leg cannot run (backend cost_analysis "
            "unavailable for this executable)")

    # -- MFU-sanity leg ---------------------------------------------------
    # peak priority: explicit BENCH_PEAK_TFLOPS > the PEAK_FLOPS spec
    # table (real hardware: the reported MFU is the TRUE one, directly
    # comparable with tools/mfu_probe.py) > calibration at 4x the
    # achieved rate (CPU hosts only — pins the expectation near 0.25 so
    # the leg proves pipeline consistency, never a hardware number)
    from pytorch_ddp_template_tpu.obs.attribution import peak_flops_for

    peak_env = float(os.environ.get("BENCH_PEAK_TFLOPS", "0") or 0)
    table_peak = peak_flops_for(jax.devices()[0].device_kind)
    peak_calibrated = False
    if peak_env > 0:
        peak_per_chip_tflops = peak_env
    elif table_peak is not None:
        peak_per_chip_tflops = table_peak / 1e12
    else:
        achieved = flops_per_step / (step_ms["perf"] / 1e3)  # whole program
        peak_per_chip_tflops = achieved * 4 / n_dev / 1e12
        peak_calibrated = True
    sanity = run_variant("perf", 99, peak_tflops=peak_per_chip_tflops)
    sanity_step_ms = sanity.step_timer.summary()["step_time_mean_ms"]

    from pathlib import Path

    recs = [json.loads(l) for l in
            (Path(f"{out_base}_perf_99") / "metrics.jsonl")
            .read_text().splitlines() if l.strip()]
    perf_recs = [r for r in recs if "perf_mfu" in r]
    if not perf_recs:
        raise RuntimeError("no perf attribution records in metrics.jsonl")
    last = perf_recs[-1]
    # steady-state reported MFU: mean over the attribution records,
    # excluding the first interval (it contains the startup compile by
    # construction — honestly low MFU, but not the steady state this
    # consistency probe is about)
    steady = perf_recs[1:] or perf_recs
    mfu_reported = sum(r["perf_mfu"] for r in steady) / len(steady)
    # cross-check against an INDEPENDENT measure of the same quantity:
    # the StepTimer's steady per-iteration mean is the FLOPs-matched
    # step time, so flops / (timer_mean * peak) must agree with what
    # the attribution reported from its own interval walls
    peak_total = peak_per_chip_tflops * 1e12 * n_dev
    mfu_expected = flops_per_step / (sanity_step_ms / 1e3) / peak_total
    mfu_consistent = (0.0 < mfu_reported <= 1.0 and mfu_expected > 0
                      and abs(mfu_reported / mfu_expected - 1.0) <= 0.35)
    frac_sum = (last["perf_frac_compute"] + last["perf_frac_comm"]
                + last["perf_frac_host"] + last["perf_frac_input"])

    gp_path = Path(f"{out_base}_perf_99") / "goodput.json"
    goodput_rec = json.loads(gp_path.read_text()) if gp_path.is_file() else {}
    from pytorch_ddp_template_tpu.obs.goodput import BUCKETS

    goodput_complete = bool(goodput_rec) and all(
        b in goodput_rec.get("buckets", {}) for b in BUCKETS)

    return {
        "metric": "perf_attribution_overhead_ratio",
        "value": round(ratio, 3),
        # perf_report + annotations vs both off, full production loop;
        # the 0.9 band carries the headline (>= 0.9 = at most ~11% cost)
        "unit": "x_plain_step_time",
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "model": model,
        "global_batch": global_batch,
        "timed_steps": TIMED_STEPS,
        "logging_steps": log_steps,
        "step_time_plain_ms": round(step_ms["plain"], 3),
        "step_time_perf_ms": round(step_ms["perf"], 3),
        # MFU-sanity leg (CPU: calibrated peak — a pipeline-consistency
        # proof, NOT a hardware MFU; the r13 followup records the real one)
        "peak_tflops_per_chip": round(peak_per_chip_tflops, 6),
        "peak_calibrated": peak_calibrated,
        "model_gflops_per_step": round(flops_per_step / 1e9, 3),
        "sanity_step_time_ms": round(sanity_step_ms, 3),
        "mfu_reported": round(mfu_reported, 4),
        "mfu_expected": round(mfu_expected, 4),
        "mfu_consistent": bool(mfu_consistent),
        # attribution fractions from the same record: must sum to ~1
        "frac_compute": last["perf_frac_compute"],
        "frac_comm": last["perf_frac_comm"],
        "frac_host": last["perf_frac_host"],
        "frac_input": last["perf_frac_input"],
        "frac_sum": round(frac_sum, 4),
        # goodput ledger: file written, every bucket present
        "goodput_file_complete": goodput_complete,
        "goodput": goodput_rec.get("goodput"),
        "goodput_buckets_s": {
            k: round(v, 3)
            for k, v in goodput_rec.get("buckets", {}).items()},
    }


def run_fleet() -> dict:
    """Fleet-watchtower proof (round 14, ``obs/fleet.py`` +
    ``obs/server.py`` + ``obs/regression.py`` + ``tools/bench_diff.py``):
    the cross-host layer must be ~free when on, must name a straggler
    when one exists, and must make the committed records executable
    tripwires.

    Legs, sized for what THIS host can prove (real multi-host exchange
    rides tools/tpu_followup.sh 14; on one process the allgather is
    skipped by construction, so this record pins the full code path
    minus the wire):

    - **neutrality**: the FULL production loop with ``--fleet`` +
      ``--status_port`` + ``--anomaly warn`` ON vs all off, same
      model/batch/mesh, alternating fresh-run reps with min-of-reps
      steady-state step time (the r11-r13 convention). ``value`` =
      plain/fleet step-time ratio; the 0.9 band carries the headline.
    - **endpoints + straggler**: one production run with an injected
      3-host fleet feed (the FleetMonitor's exchange transport faked so
      "host 2" reports a 3x step wall every window — the injection is
      in the *exchange*, exactly where a real straggler's numbers
      arrive). While it runs, ``/status``, ``/metrics`` and
      ``/healthz`` are scraped live; afterwards the leg asserts the
      straggler verdict fed the sentry as a ``kind="straggler"``
      trigger whose triage bundle names host 2.
    - **bench_diff**: ``tools/bench_diff.py`` over the committed
      records vs themselves must exit 0, and vs a synthetically slowed
      copy must exit non-zero — the tripwire trips exactly when it
      should.

    Knobs: BENCH_MODEL (default mlp-wide — device-bound steps),
    BENCH_BATCH, BENCH_STEPS/BENCH_WARMUP, BENCH_LOG_STEPS,
    BENCH_OUTPUT.
    """
    import json as _json
    import shutil
    import subprocess
    import threading
    import urllib.request
    from pathlib import Path

    import jax
    import numpy as np

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.obs.fleet import FLEET_WIRE_KEYS
    from pytorch_ddp_template_tpu.obs.sentry import BUNDLE_FILES
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    model = os.environ.get("BENCH_MODEL") or "mlp-wide"
    per_device = PER_DEVICE_BATCH or default_batch(model)
    n_dev = len(jax.devices())
    global_batch = per_device * n_dev
    out_base = os.environ.get("BENCH_OUTPUT", "/tmp/bench_fleet")
    log_steps = int(os.environ.get("BENCH_LOG_STEPS", "5"))
    total_steps = WARMUP_STEPS + TIMED_STEPS

    base_cfg = dict(
        model=model, mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device, bf16=True,
        dataset_size=max(global_batch * (total_steps + 2), 512),
        warmup_steps=0, max_grad_norm=1000.0, max_steps=total_steps,
        logging_steps=log_steps, save_steps=0, resume=False,
    )
    ctx = rt_init(TrainingConfig(**base_cfg, output_dir=out_base + "_init"))

    def build_trainer(kind: str, rep, **extra):
        cfg = TrainingConfig(**{**base_cfg,
                                "output_dir": f"{out_base}_{kind}_{rep}",
                                **extra})
        shutil.rmtree(cfg.output_dir, ignore_errors=True)
        task, ds = build(model, cfg, mesh=ctx.mesh)
        return Trainer(cfg, ctx, task, ds)

    # -- neutrality leg: alternating fresh-run reps, min-of-reps ----------
    step_ms: dict[str, float] = {}
    fleet_exchanges = 0
    for rep in range(3):
        for kind in ("plain", "fleet"):
            if kind == "fleet":
                trainer = build_trainer(kind, rep, fleet=True,
                                        anomaly="warn",
                                        status_port=-1)
            else:
                trainer = build_trainer(kind, rep)
            trainer.train()
            ms = trainer.step_timer.summary().get("step_time_mean_ms")
            if ms is None:
                raise RuntimeError("timed window produced no step samples")
            step_ms[kind] = min(step_ms.get(kind, ms), ms)
            if kind == "fleet" and trainer.fleet is not None:
                fleet_exchanges = max(fleet_exchanges,
                                      trainer.fleet.exchanges)
    ratio = step_ms["plain"] / max(step_ms["fleet"], 1e-9)
    if fleet_exchanges == 0:
        raise RuntimeError("fleet variant performed no exchanges — the "
                           "watchtower never ran; the neutrality pair "
                           "proves nothing")

    # -- endpoints + injected-straggler leg -------------------------------
    wall_i = FLEET_WIRE_KEYS.index("step_wall_ms")
    strag = build_trainer("straggler", 0, fleet=True, anomaly="warn",
                          status_port=-1, logging_steps=2,
                          straggler_windows=2, max_steps=24)

    def fake_exchange(vec):
        rows = np.stack([vec, vec, vec])
        rows[2, wall_i] *= 3.0  # "host 2" reports a 3x step wall
        return rows

    strag.fleet._exchange = fake_exchange
    probes = {"status": None, "metrics": None, "healthz": None}
    done = threading.Event()

    def probe_endpoints():
        while not done.is_set():
            port = strag.status.port if strag.status is not None else 0
            if port:
                for route in probes:
                    try:
                        body = urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/{route}",
                            timeout=2).read().decode()
                        if probes[route] is None or route == "status":
                            probes[route] = body
                    except Exception:  # noqa: BLE001 - retry next tick
                        pass
                if all(v is not None for v in probes.values()):
                    s = _json.loads(probes["status"])
                    if s.get("step", 0) >= 4:  # a mid-run snapshot
                        return
            time.sleep(0.05)

    prober = threading.Thread(target=probe_endpoints)
    prober.start()
    try:
        strag.train()
    finally:
        done.set()
        prober.join(timeout=10)
    status_rec = (_json.loads(probes["status"])
                  if probes["status"] else {})
    healthz_rec = (_json.loads(probes["healthz"])
                   if probes["healthz"] else {})
    metrics_text = probes["metrics"] or ""

    bundles = sorted(
        (Path(strag.config.output_dir) / "flight_records").glob("step_*"))
    trigger = {}
    bundle_files: list[str] = []
    if bundles:
        bundle_files = sorted(p.name for p in bundles[0].iterdir())
        try:
            trigger = _json.loads((bundles[0] / "trigger.json").read_text())
        except Exception:  # noqa: BLE001
            trigger = {}
    # a straggler bundle carries every JSON artifact; the post-trigger
    # trace belongs to the NAMED host only (here the fake host 2, so
    # this host's bundle records trace_host=2 and defers the capture)
    bundle_complete = all(f in bundle_files for f in BUNDLE_FILES)

    # -- bench_diff tripwire leg ------------------------------------------
    records_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_records")
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "bench_diff.py")
    rc_pass = subprocess.run(
        [sys.executable, tool, records_dir, records_dir],
        capture_output=True).returncode
    slowed_path = f"{out_base}_slowed.jsonl"
    src = os.path.join(records_dir, "perf_cpu_r13.jsonl")
    with open(src) as f, open(slowed_path, "w") as out_f:
        for line in f:
            if line.strip():
                rec = _json.loads(line)
                rec["value"] = rec["value"] * 0.5
                out_f.write(_json.dumps(rec) + "\n")
    drift = subprocess.run(
        [sys.executable, tool, src, slowed_path, "--format", "github"],
        capture_output=True, text=True)

    return {
        "metric": "fleet_overhead_ratio",
        "value": round(ratio, 3),
        # fleet exchange + status endpoint + sentry vs all off, full
        # production loop; the 0.9 band carries the headline
        "unit": "x_plain_step_time",
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "n_processes": jax.process_count(),
        "model": model,
        "global_batch": global_batch,
        "timed_steps": TIMED_STEPS,
        "logging_steps": log_steps,
        "step_time_plain_ms": round(step_ms["plain"], 3),
        "step_time_fleet_ms": round(step_ms["fleet"], 3),
        "fleet_exchanges": fleet_exchanges,
        # endpoint leg: all three routes answered mid-run
        "status_http_ok": bool(status_rec.get("step", 0) > 0),
        "status_step_seen": status_rec.get("step", 0),
        "status_has_fleet_table": bool(
            (status_rec.get("fleet") or {}).get("table")),
        "healthz_ok": bool(healthz_rec.get("ok")),
        "metrics_http_ok": "tpuddp_step" in metrics_text,
        # straggler leg: the verdict rode the sentry into a named bundle
        "straggler_bundle_complete": bundle_complete,
        "straggler_bundle_files": bundle_files,
        "straggler_trigger_kind": trigger.get("kind"),
        "straggler_named_host": (trigger.get("scalars") or {}).get("host"),
        "straggler_trace_host": trigger.get("trace_host"),
        "straggler_excess_pct": (trigger.get("scalars") or {})
        .get("excess_pct"),
        # bench_diff leg: committed records pass, a slowed copy trips
        "bench_diff_committed_rc": rc_pass,
        "bench_diff_slowed_rc": drift.returncode,
        "bench_diff_github_table": "| `perf_attribution_overhead_ratio` |"
        in drift.stdout,
    }


def run_mem() -> dict:
    """Memory-X-ray proof (round 15, ``obs/memory.py``): the HBM
    accounting layer must be ~free when on, its compile-time split must
    agree with XLA's own analysis, and an allocation failure must leave
    complete forensics through the production flight-recorder path.

    Legs, sized for what THIS host can prove (real ``memory_stats``
    watermarks and a real HBM limit ride ``tools/tpu_followup.sh 15``;
    the CPU backend reports no memory_stats, so the runtime records here
    pin the static-model degradation path — labelled, never dressed up
    as a measurement):

    - **neutrality**: the FULL production loop with ``--mem_report`` +
      ``--anomaly warn`` + ``--status_port`` ON vs all off, same
      model/batch/mesh, alternating fresh-run reps with min-of-reps
      steady-state step time (the r11-r14 convention). ``value`` =
      plain/mem step-time ratio; the 0.9 band carries the headline. The
      mem variant must actually have written ``kind="mem"`` records.
    - **remat A/B**: the same train step compiled with remat on and off;
      the production compile-time split's temp-bytes delta must agree in
      SIGN with raw ``memory_analysis().temp_size_in_bytes`` (remat
      exists to shrink temps — the split reporting a *growth* while the
      analysis reports a shrink would mean the X-ray mislabels its
      columns). Where the backend also measures (``memory_stats``), the
      measured peak delta is recorded alongside.
    - **mem pressure**: a production run whose monitor poll is faked to
      cross ``--mem_budget_frac`` mid-run — the drain-thread tripwire
      must ride the sentry into a ``kind="mem_pressure"`` triage bundle
      carrying ``memory.json``, and ``/metrics`` scraped DURING the run
      must expose the per-device HBM gauges.
    - **injected OOM**: a production run whose step raises
      RESOURCE_EXHAUSTED at a fixed step — the crash bundle must carry
      complete memory forensics (live-buffer census + compile-time
      split) through the production flight-recorder path.

    Knobs: BENCH_MODEL (default gpt-tiny — a transformer, so remat has
    temps to shrink), BENCH_BATCH, BENCH_STEPS/BENCH_WARMUP,
    BENCH_LOG_STEPS, BENCH_OOM_STEP, BENCH_OUTPUT.
    """
    import json as _json
    import shutil
    import threading
    import urllib.request
    from pathlib import Path

    import jax

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.obs.memory import static_memory_model
    from pytorch_ddp_template_tpu.obs.sentry import BUNDLE_FILES
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    model = os.environ.get("BENCH_MODEL") or "gpt-tiny"
    per_device = PER_DEVICE_BATCH or 32
    n_dev = len(jax.devices())
    global_batch = per_device * n_dev
    out_base = os.environ.get("BENCH_OUTPUT", "/tmp/bench_mem")
    log_steps = int(os.environ.get("BENCH_LOG_STEPS", "5"))
    total_steps = WARMUP_STEPS + TIMED_STEPS

    base_cfg = dict(
        model=model, mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device, bf16=True,
        scan_layers=True,
        dataset_size=max(global_batch * (total_steps + 2), 512),
        warmup_steps=0, max_grad_norm=1000.0, max_steps=total_steps,
        logging_steps=log_steps, save_steps=0, resume=False,
    )
    ctx = rt_init(TrainingConfig(**base_cfg, output_dir=out_base + "_init"))

    def build_trainer(kind: str, rep, **extra):
        cfg = TrainingConfig(**{**base_cfg,
                                "output_dir": f"{out_base}_{kind}_{rep}",
                                **extra})
        shutil.rmtree(cfg.output_dir, ignore_errors=True)
        task, ds = build(model, cfg, mesh=ctx.mesh)
        return Trainer(cfg, ctx, task, ds)

    # -- neutrality leg: alternating fresh-run reps, min-of-reps ----------
    step_ms: dict[str, float] = {}
    mem_records = 0
    mem_measured = None
    static_split = None
    for rep in range(3):
        for kind in ("plain", "mem"):
            if kind == "mem":
                trainer = build_trainer(kind, rep, mem_report=True,
                                        anomaly="warn", status_port=-1)
            else:
                trainer = build_trainer(kind, rep)
            trainer.train()
            ms = trainer.step_timer.summary().get("step_time_mean_ms")
            if ms is None:
                raise RuntimeError("timed window produced no step samples")
            step_ms[kind] = min(step_ms.get(kind, ms), ms)
            if kind == "mem" and trainer.memory is not None:
                st = trainer.memory.state()
                mem_records = max(mem_records, st["ring_len"])
                static_split = (st.get("static") or {}).get("split")
                last = trainer.memory.records()
                if last:
                    mem_measured = last[-1].get("mem_measured")
    ratio = step_ms["plain"] / max(step_ms["mem"], 1e-9)
    if mem_records == 0:
        raise RuntimeError("mem variant produced no kind=\"mem\" records "
                           "— the watermark poller never ran; the "
                           "neutrality pair proves nothing")

    # -- remat A/B leg: split sign vs raw memory_analysis -----------------
    temps_raw: dict[str, int] = {}
    temps_model: dict[str, int] = {}
    measured_peak: dict[str, int] = {}
    for kind, remat in (("remat_off", False), ("remat_on", True)):
        tr = build_trainer(kind, 0, remat=remat)
        state, _ = tr.restore_or_init()
        batch = next(iter(tr.loader.epoch(0)))
        lowered = tr.train_step.lower(state, batch)
        compiled = lowered.compile()
        temps_raw[kind] = int(compiled.memory_analysis().temp_size_in_bytes)
        mm = static_memory_model(compiled,
                                 getattr(lowered, "args_info", None))
        if not mm.get("available"):
            raise RuntimeError("compile-time memory split unavailable on "
                               "this backend; the remat A/B cannot run")
        temps_model[kind] = int(mm["split"]["temp_bytes"])
        # where the backend measures for real (TPU), record the peak too
        stats = jax.devices()[0].memory_stats() or {}
        if stats.get("peak_bytes_in_use"):
            st2, _ = compiled(state, batch)
            jax.block_until_ready(jax.tree.leaves(st2.params)[0])
            measured_peak[kind] = int(
                jax.devices()[0].memory_stats()["peak_bytes_in_use"])
    delta_raw = temps_raw["remat_on"] - temps_raw["remat_off"]
    delta_model = temps_model["remat_on"] - temps_model["remat_off"]
    sign = lambda x: (x > 0) - (x < 0)  # noqa: E731
    sign_ok = bool(sign(delta_model) == sign(delta_raw) and delta_raw < 0)

    # -- mem-pressure leg: faked poll through the production loop ---------
    press = build_trainer("pressure", 0, mem_report=True, anomaly="warn",
                          status_port=-1, logging_steps=2, max_steps=24)
    calls = {"n": 0}
    limit = 16 * 2**30

    def fake_poll():
        calls["n"] += 1
        frac = 0.5 if calls["n"] < 3 else 0.97  # crosses the 0.9 budget
        return [{"device": 0, "kind": "fake-hbm",
                 "bytes_in_use": int(limit * frac),
                 "peak_bytes_in_use": int(limit * frac),
                 "bytes_limit": limit}]

    press.memory._poll = fake_poll
    probes = {"metrics": None}
    done = threading.Event()

    def probe_metrics():
        while not done.is_set():
            port = press.status.port if press.status is not None else 0
            if port:
                try:
                    body = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=2).read().decode()
                    if "tpuddp_mem_device_bytes_in_use" in body:
                        probes["metrics"] = body
                        return
                except Exception:  # noqa: BLE001 - retry next tick
                    pass
            time.sleep(0.05)

    prober = threading.Thread(target=probe_metrics)
    prober.start()
    try:
        press.train()
    finally:
        done.set()
        prober.join(timeout=10)
    press_bundles = sorted(
        (Path(press.config.output_dir) / "flight_records").glob("step_*"))
    press_trigger = {}
    press_has_forensics = False
    if press_bundles:
        names = {p.name for p in press_bundles[0].iterdir()}
        press_has_forensics = ("memory.json" in names
                               and all(f in names for f in BUNDLE_FILES))
        try:
            press_trigger = _json.loads(
                (press_bundles[0] / "trigger.json").read_text())
        except Exception:  # noqa: BLE001
            press_trigger = {}

    # -- injected-OOM forensics leg ---------------------------------------
    oom_step = int(os.environ.get("BENCH_OOM_STEP", "8"))
    oom = build_trainer("oom", 0, mem_report=True, anomaly="warn",
                        logging_steps=2, max_steps=24)
    orig_step = oom.train_step
    oom_calls = {"n": 0}

    def oom_poisoned(state, batch, *rest):
        oom_calls["n"] += 1
        if oom_calls["n"] == oom_step:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating "
                "13421772800 bytes (injected by BENCH_MODE=mem)")
        return orig_step(state, batch, *rest)

    # the engine's _startup_reports AOT-lowers self.train_step — the
    # injector must keep that surface so the compile-time split (the
    # forensics bundle's static half) still lands before the crash
    oom_poisoned.lower = orig_step.lower
    oom.train_step = oom_poisoned
    oom_raised = False
    try:
        oom.train()
    except RuntimeError:
        oom_raised = True
    oom_bundles = sorted(
        (Path(oom.config.output_dir) / "flight_records").glob("step_*"))
    oom_forensics = {}
    oom_trigger = {}
    if oom_bundles:
        try:
            oom_forensics = _json.loads(
                (oom_bundles[0] / "memory.json").read_text())
            oom_trigger = _json.loads(
                (oom_bundles[0] / "trigger.json").read_text())
        except Exception:  # noqa: BLE001
            pass
    census = (oom_forensics.get("census") or {})
    oom_complete = bool(
        census.get("available") and census.get("n_arrays", 0) > 0
        and ((oom_forensics.get("static_model") or {}).get("split")
             or {}).get("temp_bytes") is not None)

    return {
        "metric": "mem_overhead_ratio",
        "value": round(ratio, 3),
        # mem_report + watermark poller + sentry vs all off, full
        # production loop; the 0.9 band carries the headline
        "unit": "x_plain_step_time",
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "model": model,
        "global_batch": global_batch,
        "timed_steps": TIMED_STEPS,
        "logging_steps": log_steps,
        "step_time_plain_ms": round(step_ms["plain"], 3),
        "step_time_mem_ms": round(step_ms["mem"], 3),
        "mem_records_written": mem_records,
        # 0.0 on CPU (no memory_stats): the static-degradation path is
        # the thing this host CAN pin; real watermarks ride the followup
        "mem_measured": mem_measured,
        "static_split_temp_bytes": (static_split or {}).get("temp_bytes"),
        "static_split_projected_peak_bytes":
            (static_split or {}).get("projected_peak_bytes"),
        # remat A/B: the production split must agree in sign with raw
        # memory_analysis, and remat must actually shrink temps
        "remat_temp_bytes_off": temps_raw["remat_off"],
        "remat_temp_bytes_on": temps_raw["remat_on"],
        "remat_temp_delta_bytes": delta_raw,
        "remat_temp_delta_model_bytes": delta_model,
        "remat_delta_sign_consistent": sign_ok,
        "remat_measured_peak_bytes": measured_peak or None,
        # mem-pressure leg: drain-thread tripwire -> sentry -> bundle
        "pressure_bundle_complete": press_has_forensics,
        "pressure_trigger_kind": press_trigger.get("kind"),
        "pressure_frac_of_limit": (press_trigger.get("scalars") or {})
        .get("frac_of_limit"),
        "metrics_http_mem_gauges": bool(probes["metrics"]),
        # injected-OOM leg: complete forensics through the crash path
        "oom_injected_at_step": oom_step,
        "oom_raised": oom_raised,
        "oom_trigger_mode": oom_trigger.get("mode"),
        "oom_trigger_flagged": oom_trigger.get("oom"),
        "oom_census_arrays": census.get("n_arrays"),
        "oom_census_total_mb": round(
            census.get("total_bytes", 0) / 1e6, 2),
        "oom_forensics_complete": oom_complete,
    }


def run_pipe() -> dict:
    """Pipeline-schedule proof (round 16, parallel/pipeline.py): GPipe
    vs 1F1B vs zero-bubble on the pipelined causal-LM entry.

    Legs, sized for what THIS host can prove (a 1-core CPU runs the 8
    virtual devices time-sliced, so wall-clock tracks total work, not
    the lockstep makespan — the bubble win that needs real parallel
    chips rides ``tools/tpu_followup.sh legs_r16``):

    - **parity**: loss + full param grads of every schedule against
      sequential stage execution (no pipeline, same init) — the fused
      slot loops and the zb tap/dw-split must reproduce plain autodiff
      to float32 tolerance.
    - **FLOPs-matched step ratios**: min-of-alternating-reps
      value_and_grad wall times. The gpipe leg wraps its stages in
      ``jax.checkpoint`` so every schedule recomputes blocks in
      backward (the r9/r11 FLOPs-matching convention; the raw no-remat
      gpipe time is also recorded, labelled). Headline =
      gpipe/1f1b >= 0.9 band; the zb-vs-1f1b wall ratio is recorded
      with its host caveat and the lockstep schedule-model ratio at
      measured branch times carries the zb comparison.
    - **bubble fractions**: the static schedule model
      (``schedule_bubble_fraction``) evaluated twice — with the unit
      cost table, and with MEASURED per-branch device times (F / fused
      B / dx / dw timed standalone at the leg geometry) — the r13
      "static schedule model + measured device time" figure. zb's must
      be strictly below 1f1b's.
    - **HLO schedule evidence**: ``obs/hlo_report.pipe_evidence`` on
      the compiled fused steps — every slot body's stage-boundary
      ppermutes compute-independent (the hops may start under the
      adjacent microbatch's work), and zb's deferred-dw computations
      present in the program.
    - **live range**: ``memory_analysis`` temp bytes of gpipe (AD
      saves every tick's residuals — O(M) activation residency) vs
      1f1b (recompute-from-boundary, O(P) in-flight) at a deeper
      microbatch count (BENCH_MICRO_MEM, default 8).

    Degenerate contract: fewer than 4 devices (no pipe×data mesh worth
    scheduling) emits ``degenerate: true`` with value 0 (r8
    convention).

    Knobs: BENCH_PIPE (stages, default 4), BENCH_MICRO (microbatches,
    default 2 — bubble-dominated on purpose), BENCH_MICRO_MEM (8),
    BENCH_SEQ (128), BENCH_BATCH (per data replica, default 16),
    BENCH_STEPS/BENCH_WARMUP.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.models.gpt_pipe import PipelinedGptTask
    from pytorch_ddp_template_tpu.obs.hlo_report import pipe_evidence
    from pytorch_ddp_template_tpu.parallel.pipeline import (
        WORK_B, WORK_BDW, WORK_BDX, WORK_F, build_pipe_table,
        pipeline_apply, schedule_bubble_fraction, schedule_makespan,
    )
    from pytorch_ddp_template_tpu.runtime import make_mesh

    n_stages = int(os.environ.get("BENCH_PIPE", "4"))
    n_micro = int(os.environ.get("BENCH_MICRO", "2"))
    n_micro_mem = int(os.environ.get("BENCH_MICRO_MEM", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    per_replica = PER_DEVICE_BATCH or 16
    devices = jax.devices()
    metric = f"pipe_step_ratio_1f1b_m{n_micro}p{n_stages}"
    unit = "x_gpipe_step_time"
    if len(devices) < 4 or len(devices) % n_stages:
        return {
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "degenerate": True,
            "n_devices": len(devices),
            "note": f"{len(devices)} device(s) cannot carve a "
                    f"pipe:{n_stages} × data mesh; the real legs ride "
                    "tools/tpu_followup.sh legs_r16",
        }
    data_size = len(devices) // n_stages
    mesh = make_mesh(f"data:{data_size},pipe:{n_stages}", devices)
    vocab, heads, head_dim, mlp = 1024, 4, 32, 512
    embed = heads * head_dim
    batch = per_replica * data_size

    def build(schedule):
        return PipelinedGptTask(
            mesh, vocab_size=vocab, seq_len=seq, num_layers=n_stages,
            num_heads=heads, head_dim=head_dim, mlp_dim=mlp,
            n_micro=n_micro, pipe_schedule=schedule)

    tasks = {k: build(k) for k in ("gpipe", "1f1b", "zb")}
    rng = np.random.default_rng(0)
    ids = np.asarray(rng.integers(0, vocab, (batch, seq)), np.int32)
    ex = {"input_ids": ids}
    params = nn.meta.unbox(tasks["gpipe"].init(jax.random.PRNGKey(1), ex))
    params = params[0] if isinstance(params, tuple) else params

    # -- sequential-stage reference (no pipeline) -------------------------
    ref_task = tasks["gpipe"]

    def seq_loss(p):
        x = ref_task._embed(p, jnp.asarray(ids))
        flat = jax.tree.map(
            lambda a: a.reshape(ref_task.num_layers, *a.shape[2:]),
            p["blocks"])
        for i in range(ref_task.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], flat)
            x = ref_task._block.apply({"params": layer}, x, None,
                                      train=False)
        h = ref_task._ln.apply({"params": p["final_ln"]},
                               x.astype(jnp.float32))
        logits = (h.astype(ref_task.dtype)
                  @ p["wte"].T.astype(ref_task.dtype)).astype(jnp.float32)
        targets = jnp.asarray(ids)[:, 1:].astype(jnp.int32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tlp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -tlp.sum() / (batch * (seq - 1))

    l_ref, g_ref = jax.jit(jax.value_and_grad(seq_loss))(params)
    l_ref = float(l_ref)
    g_ref = jax.device_get(g_ref)

    # -- schedule variants (gpipe FLOPs-matched via jax.checkpoint) -------
    def task_loss(task):
        def f(p):
            total, _, _ = task.loss(p, {}, ex, None, train=True)
            return total
        return f

    gpipe_task = tasks["gpipe"]

    def gpipe_matched_loss(p):
        # the task's gpipe forward with the stage wrapped in remat, so
        # AD's backward recomputes blocks like the fused schedules do
        x = gpipe_task._embed(p, jnp.asarray(ids))
        m = gpipe_task._microbatch_count(batch)
        xm = x.reshape(m, batch // m, seq, embed)
        stage = jax.checkpoint(
            lambda w, h: gpipe_task._stage_fwd(w, h))
        out = pipeline_apply(p["blocks"], stage, xm, mesh)
        out = out.reshape(batch, seq, embed)
        h = gpipe_task._ln.apply({"params": p["final_ln"]},
                                 out.astype(jnp.float32))
        logits = (h.astype(gpipe_task.dtype)
                  @ p["wte"].T.astype(gpipe_task.dtype)
                  ).astype(jnp.float32)
        targets = jnp.asarray(ids)[:, 1:].astype(jnp.int32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tlp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -tlp.sum() / (batch * (seq - 1))

    fns = {
        "gpipe": jax.jit(jax.value_and_grad(gpipe_matched_loss)),
        "gpipe_norec": jax.jit(jax.value_and_grad(task_loss(gpipe_task))),
        "1f1b": jax.jit(jax.value_and_grad(task_loss(tasks["1f1b"]))),
        "zb": jax.jit(jax.value_and_grad(task_loss(tasks["zb"]))),
    }

    # -- parity leg --------------------------------------------------------
    parity = {}
    losses = {}
    for kind, fn in fns.items():
        l, g = fn(params)
        losses[kind] = float(l)
        g = jax.device_get(g)
        worst = 0.0
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
            d = float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            s = max(float(np.max(np.abs(np.asarray(a)))), 1e-6)
            worst = max(worst, d / s)
        parity[kind] = worst
    max_parity = max(parity.values())
    assert max_parity < 5e-3, f"schedule grad parity broke: {parity}"
    for kind, l in losses.items():
        assert abs(l - l_ref) < 1e-4 * max(abs(l_ref), 1.0), (kind, l, l_ref)

    # -- step-ratio leg: alternating min-of-reps --------------------------
    step_ms = {}
    for kind, fn in fns.items():  # warmup (already compiled above)
        for _ in range(max(WARMUP_STEPS - 1, 1)):
            l, _ = fn(params)
        float(l)
    for rep in range(3):
        for kind, fn in fns.items():
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                l, g = fn(params)
            float(l)
            jax.block_until_ready(g)
            ms = 1e3 * (time.perf_counter() - t0) / TIMED_STEPS
            step_ms[kind] = min(step_ms.get(kind, ms), ms)
    ratio_1f1b = step_ms["gpipe"] / max(step_ms["1f1b"], 1e-9)
    ratio_zb = step_ms["1f1b"] / max(step_ms["zb"], 1e-9)

    # -- bubble leg: static model + measured branch times -----------------
    task = tasks["zb"]
    mb = batch // (n_micro * data_size)  # per-replica microbatch
    stage_w = jax.tree.map(
        lambda a: a[0], jax.device_get(params["blocks"]))
    x_mb = jnp.asarray(rng.standard_normal((mb, seq, embed)), jnp.float32)
    gy_mb = jnp.asarray(rng.standard_normal((mb, seq, embed)), jnp.float32)
    probes = task._make_probes(stage_w, jax.ShapeDtypeStruct(
        x_mb.shape, x_mb.dtype))

    def branch_f(w, x):
        return task._stage_fwd(w, x)

    def branch_b(w, x, gy):
        _, pull = jax.vjp(lambda w_, x_: task._stage_fwd(w_, x_), w, x)
        return pull(gy)

    def branch_dx(w, x, gy):
        (y, taps), pull = jax.vjp(
            lambda x_, pr: task._stage_fwd_tapped(w, x_, pr), x, probes)
        return pull((gy, jax.tree.map(jnp.zeros_like, taps)))

    (_, taps0), _ = jax.vjp(
        lambda x_, pr: task._stage_fwd_tapped(stage_w, x_, pr),
        x_mb, probes)
    taps1 = jax.tree.map(lambda a: a[None], taps0)
    gpr1 = jax.tree.map(lambda a: a[None] * 0 + 1.0, probes)

    def branch_dw(w, taps, gpr):
        # taps as ARGUMENTS: closed-over they are compile-time
        # constants and XLA folds the whole product away (a 0.1ms
        # "measurement")
        return task._dw_from_taps(w, taps, gpr)

    def time_of(fn, *args, reps=8):
        out = fn(*args)
        jax.block_until_ready(out)
        best = 1e9
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    t_f = time_of(jax.jit(branch_f), stage_w, x_mb)
    t_b = time_of(jax.jit(branch_b), stage_w, x_mb, gy_mb)
    t_dx = time_of(jax.jit(branch_dx), stage_w, x_mb, gy_mb)
    t_dw = time_of(jax.jit(branch_dw), stage_w, taps1, gpr1)
    measured_costs = {WORK_F: 1.0, WORK_B: t_b / t_f,
                      WORK_BDX: t_dx / t_f, WORK_BDW: t_dw / t_f}
    bubble = {
        kind: {
            "static": round(
                schedule_bubble_fraction(kind, n_micro, n_stages), 4),
            "measured": round(schedule_bubble_fraction(
                kind, n_micro, n_stages, costs=measured_costs), 4),
        }
        for kind in ("gpipe", "1f1b", "zb")
    }
    # the STATIC ordering is deterministic table math — assert it; the
    # MEASURED ordering rides noisy branch timings, so it is recorded
    # as a boolean leg (live_range_ok convention) rather than crashing
    # the whole record on ambient jitter
    assert bubble["zb"]["static"] < bubble["1f1b"]["static"], bubble
    bubble_measured_ok = (bubble["zb"]["measured"]
                          < bubble["1f1b"]["measured"])
    # the lockstep schedule-model step ratio at MEASURED branch times:
    # the sense in which zb >= 1f1b on hardware whose stages run in
    # parallel. This 1-core host time-slices its 8 virtual devices, so
    # its WALL clock tracks total work and additionally charges zb the
    # tap-deferral traffic while giving it no bubble to fill (idle
    # slots cost nothing when devices aren't real) — the wall ratio is
    # recorded above, labelled; the real-chip triplet rides
    # tools/tpu_followup.sh legs_r16.
    span_1f1b, _ = schedule_makespan("1f1b", n_micro, n_stages,
                                     costs=measured_costs)
    span_zb, _ = schedule_makespan("zb", n_micro, n_stages,
                                   costs=measured_costs)
    ratio_zb_modeled = span_1f1b / span_zb

    # -- HLO schedule-evidence leg ----------------------------------------
    hlo = {}
    for kind in ("1f1b", "zb"):
        text = fns[kind].lower(params).compile().as_text()
        hlo[kind] = pipe_evidence(text)
    assert hlo["1f1b"]["pipe_sends_independent"], hlo["1f1b"]
    assert hlo["zb"]["pipe_sends_independent"], hlo["zb"]
    assert hlo["zb"]["dw_ops_present"], "zb dw computations missing"

    # -- live-range leg: O(M) gpipe residency vs O(P) 1f1b ----------------
    live_range_ok = None
    temp_bytes = {}
    try:
        mem_batch = n_micro_mem * data_size * max(
            per_replica // n_micro, 1)
        ids_mem = np.asarray(
            rng.integers(0, vocab, (mem_batch, seq)), np.int32)
        ex_mem = {"input_ids": ids_mem}
        mem_tasks = {
            k: PipelinedGptTask(
                mesh, vocab_size=vocab, seq_len=seq,
                num_layers=n_stages, num_heads=heads,
                head_dim=head_dim, mlp_dim=mlp, n_micro=n_micro_mem,
                pipe_schedule=k)
            for k in ("gpipe", "1f1b")
        }

        def mem_loss(task):
            def f(p):
                total, _, _ = task.loss(p, {}, ex_mem, None, train=True)
                return total
            return f

        for kind, t_ in mem_tasks.items():
            compiled = jax.jit(
                jax.value_and_grad(mem_loss(t_))).lower(params).compile()
            temp_bytes[kind] = int(
                compiled.memory_analysis().temp_size_in_bytes)
        # the AD-through-the-loop gpipe backward saves every tick's
        # residuals (O(M + P) of them); 1f1b keeps only the in-flight
        # boundary activations (O(P)) and recomputes — at M=8 the gap
        # must be visible
        live_range_ok = bool(temp_bytes["1f1b"] < temp_bytes["gpipe"])
    except Exception as e:  # noqa: BLE001 - backends without the API
        temp_bytes = {"error": f"{type(e).__name__}: {e}"}

    return {
        "metric": metric,
        "value": round(ratio_1f1b, 3),
        # FLOPs-matched pair (remat gpipe vs recompute-from-boundary
        # fused schedules); neutrality-or-better bar: >= 0.9 passes
        "unit": unit,
        "vs_baseline": round(ratio_1f1b / 0.9, 4),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
        "degenerate": False,
        "pipe_stages": n_stages,
        "data_size": data_size,
        "n_micro": n_micro,
        "seq_len": seq,
        "vocab": vocab,
        "batch": batch,
        "model_dims": {"num_heads": heads, "head_dim": head_dim,
                       "mlp_dim": mlp},
        "timed_steps": TIMED_STEPS,
        "step_time_gpipe_ms": round(step_ms["gpipe"], 2),
        "step_time_gpipe_norecompute_ms": round(step_ms["gpipe_norec"], 2),
        "step_time_1f1b_ms": round(step_ms["1f1b"], 2),
        "step_time_zb_ms": round(step_ms["zb"], 2),
        "ratio_zb_vs_1f1b_wall": round(ratio_zb, 3),
        "ratio_zb_vs_1f1b_modeled": round(ratio_zb_modeled, 3),
        "bubble_measured_ordering_ok": bubble_measured_ok,
        "wall_caveat": ("1-core host: 8 virtual devices time-slice, so "
                        "wall tracks total work + charges zb the tap-"
                        "deferral traffic with no bubble to fill; the "
                        "lockstep model at measured branch times is the "
                        "schedule comparison (legs_r16 measures real "
                        "chips)"),
        "loss_seq_ref": l_ref,
        "losses": {k: round(v, 6) for k, v in losses.items()},
        "parity_max_rel_grad": {k: float(f"{v:.3e}")
                                for k, v in parity.items()},
        "branch_times_ms": {
            "f": round(1e3 * t_f, 3), "b": round(1e3 * t_b, 3),
            "dx": round(1e3 * t_dx, 3), "dw": round(1e3 * t_dw, 3)},
        "bubble_frac": bubble,
        "hlo_pipe": {k: {kk: v[kk] for kk in
                         ("slot_bodies", "independent_send_bodies",
                          "pipe_sends_independent", "conditional_count",
                          "dw_ops_present")}
                     for k, v in hlo.items()},
        "live_range_ok": live_range_ok,
        "temp_bytes": temp_bytes,
    }


def run_pipe_compose() -> dict:
    """4D-composition proof (round 22, parallel/pipeline.py): the 1f1b
    slot loop composing with tensor parallelism (pipe×tp) and with
    per-slot data-parallel grad reduces (pipe×ddp) through boundary-
    hoisted collective waves — every compose collective at the slot-body
    top level, NONE inside the work switch's branch computations.

    Legs, sized for what THIS host can prove (a 1-core CPU time-slices
    its 8 virtual devices, so wall tracks total work, not the lockstep
    makespan — the real-chip ratios ride ``tools/tpu_followup.sh
    legs_r22``):

    - **parity**: loss + full param grads of ``--pipe_schedule 1f1b
      --tp_overlap`` (mesh data×model:2×pipe:2) and ``--pipe_schedule
      1f1b --ddp_overlap`` (mesh data×pipe:2) against sequential stage
      execution (no pipeline, same init) — float32 tolerance, the same
      bar the plain schedules hold in BENCH_MODE=pipe.
    - **FLOPs-matched step ratio**: plain-1f1b vs composed step time on
      the SAME mesh (min-of-alternating-reps). On this host the compose
      waves are extra serialised work, so the ratio is a regression
      tripwire (>= the band), not a speedup claim.
    - **HLO slot-body evidence**: ``obs/hlo_report.pipe_evidence`` on
      the compiled composed steps — boundary ppermutes compute-
      independent AND ``branch_collectives == 0`` (the r22 invariant: a
      collective inside a divergent switch branch is a deadlock on real
      hardware, so the tripwire is load-bearing, not cosmetic).

    Degenerate contract: fewer than 4 devices (no pipe×data mesh worth
    scheduling) emits ``degenerate: true`` with value 0 (r8 convention);
    pipe×tp additionally needs ``4 | n_devices`` for its
    data×model:2×pipe:2 carve and is skipped (recorded null) when the
    host cannot shape it.

    Knobs: BENCH_MICRO (microbatches, default 4), BENCH_SEQ (128),
    BENCH_BATCH (per data replica, default 16), BENCH_STEPS/
    BENCH_WARMUP.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.models.gpt_pipe import PipelinedGptTask
    from pytorch_ddp_template_tpu.obs.hlo_report import pipe_evidence
    from pytorch_ddp_template_tpu.runtime import make_mesh

    n_micro = int(os.environ.get("BENCH_MICRO", "4"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    per_replica = PER_DEVICE_BATCH or 16
    devices = jax.devices()
    metric = f"pipe_compose_step_ratio_m{n_micro}p2"
    unit = "x_plain_1f1b_step_time"
    if len(devices) < 4 or len(devices) % 2:
        return {
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "degenerate": True,
            "n_devices": len(devices),
            "note": f"{len(devices)} device(s) cannot carve a pipe:2 × "
                    "data mesh; the real legs ride "
                    "tools/tpu_followup.sh legs_r22",
        }
    n_stages = 2
    vocab, heads, head_dim, mlp = 1024, 4, 32, 512
    embed = heads * head_dim
    can_tp = len(devices) % 4 == 0

    def seq_loss_fn(task, ids, batch):
        def seq_loss(p):
            x = task._embed(p, jnp.asarray(ids))
            flat = jax.tree.map(
                lambda a: a.reshape(task.num_layers, *a.shape[2:]),
                p["blocks"])
            h = x
            for i in range(task.num_layers):
                layer = jax.tree.map(lambda a, i=i: a[i], flat)
                h = task._block.apply({"params": layer}, h, None,
                                      train=False)
            hf = task._ln.apply({"params": p["final_ln"]},
                                h.astype(jnp.float32))
            logits = (hf.astype(task.dtype)
                      @ p["wte"].T.astype(task.dtype)).astype(jnp.float32)
            targets = jnp.asarray(ids)[:, 1:].astype(jnp.int32)
            logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            tlp = jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0]
            return -tlp.sum() / (batch * (seq - 1))
        return seq_loss

    def leg(compose, mesh_spec):
        mesh = make_mesh(mesh_spec, devices)
        data_size = mesh.shape.get("data", 1)
        batch = per_replica * data_size
        kw = dict(vocab_size=vocab, seq_len=seq, num_layers=2 * n_stages,
                  num_heads=heads, head_dim=head_dim, mlp_dim=mlp,
                  n_micro=n_micro)
        composed = PipelinedGptTask(
            mesh, pipe_schedule="1f1b",
            tp_overlap=(compose == "tp"),
            ddp_overlap=(compose == "ddp"), **kw)
        plain = PipelinedGptTask(mesh, pipe_schedule="1f1b", **kw)
        rng = np.random.default_rng(0)
        ids = np.asarray(rng.integers(0, vocab, (batch, seq)), np.int32)
        ex = {"input_ids": ids}
        params = nn.meta.unbox(
            composed.init(jax.random.PRNGKey(1), ex))
        params = params[0] if isinstance(params, tuple) else params

        def task_loss(task):
            def f(p):
                total, _, _ = task.loss(p, {}, ex, None, train=True)
                return total
            return f

        fn_comp = jax.jit(jax.value_and_grad(task_loss(composed)))
        fn_plain = jax.jit(jax.value_and_grad(task_loss(plain)))
        l_ref, g_ref = jax.jit(
            jax.value_and_grad(seq_loss_fn(composed, ids, batch)))(params)
        l_ref = float(l_ref)
        g_ref = jax.device_get(g_ref)

        l_c, g_c = fn_comp(params)
        l_c = float(l_c)
        g_c = jax.device_get(g_c)
        worst = 0.0
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_c)):
            d = float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            s = max(float(np.max(np.abs(np.asarray(a)))), 1e-6)
            worst = max(worst, d / s)
        assert worst < 5e-3, f"pipe×{compose} grad parity broke: {worst}"
        assert abs(l_c - l_ref) < 1e-4 * max(abs(l_ref), 1.0), (
            compose, l_c, l_ref)

        # step ratio: plain vs composed on the same mesh, min of
        # alternating reps
        step_ms = {}
        for fn in (fn_comp, fn_plain):  # warmup (compiled above)
            for _ in range(max(WARMUP_STEPS - 1, 1)):
                l, _ = fn(params)
            float(l)
        for rep in range(3):
            for kind, fn in (("composed", fn_comp), ("plain", fn_plain)):
                t0 = time.perf_counter()
                for _ in range(TIMED_STEPS):
                    l, g = fn(params)
                float(l)
                jax.block_until_ready(g)
                ms = 1e3 * (time.perf_counter() - t0) / TIMED_STEPS
                step_ms[kind] = min(step_ms.get(kind, ms), ms)
        ratio = step_ms["plain"] / max(step_ms["composed"], 1e-9)

        ev = pipe_evidence(fn_comp.lower(params).compile().as_text())
        assert ev["pipe_sends_independent"], (compose, ev)
        assert ev["branch_collectives_free"], (
            f"pipe×{compose}: {ev['branch_collectives']} collective(s) "
            "inside branch_computations — boundary hoisting broke")
        return {
            "mesh": mesh_spec,
            "batch": batch,
            "loss_seq_ref": l_ref,
            "loss_composed": round(l_c, 6),
            "parity_max_rel_grad": float(f"{worst:.3e}"),
            "step_time_plain_ms": round(step_ms["plain"], 2),
            "step_time_composed_ms": round(step_ms["composed"], 2),
            "step_ratio_vs_plain": round(ratio, 3),
            "hlo": {k: ev[k] for k in
                    ("slot_bodies", "independent_send_bodies",
                     "pipe_sends_independent", "conditional_count",
                     "branch_computation_count", "branch_collectives",
                     "branch_collectives_free")},
        }

    legs = {}
    if can_tp:
        legs["tp"] = leg("tp", f"data:{len(devices) // 4},model:2,pipe:2")
    legs["ddp"] = leg("ddp", f"data:{len(devices) // 2},pipe:2")

    # headline: the weakest same-mesh step ratio across the composed
    # legs — a regression tripwire (the compose waves are serialised
    # extra work on this time-sliced host), banded at 0.5
    headline = min(v["step_ratio_vs_plain"] for v in legs.values())
    return {
        "metric": metric,
        "value": round(headline, 3),
        "unit": unit,
        "vs_baseline": round(headline / 0.5, 4),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
        "degenerate": False,
        "pipe_stages": n_stages,
        "n_micro": n_micro,
        "seq_len": seq,
        "vocab": vocab,
        "model_dims": {"num_heads": heads, "head_dim": head_dim,
                       "mlp_dim": mlp},
        "timed_steps": TIMED_STEPS,
        "schedule": "1f1b",
        "compose_legs": legs,
        "tp_leg_skipped": not can_tp,
        "wall_caveat": ("1-core host: 8 virtual devices time-slice, so "
                        "the compose waves are serialised extra work and "
                        "the ratio is a regression tripwire, not the "
                        "lockstep win; legs_r22 measures real chips"),
    }


def run_quant() -> dict:
    """Low-precision compute proof (``--quant_compute {int8,fp8}``,
    ops/quant.py + the quantized ring kernels in
    parallel/collective_matmul.py): scaled narrow dots in the scanned
    block matmuls and, composed with ``--tp_overlap``, narrow ring
    payloads — wire and FLOPs shrink together.

    Six legs, sized for what THIS host can prove (the real-TPU fp8/int8
    step-time pair and the narrow-MXU FLOPs win ride in
    ``tools/tpu_followup.sh legs_r17``):

    - **off bit-parity**: one optimizer step from identical init with
      ``quant_compute="off"`` passed explicitly vs the untouched default
      path — MUST be bit-equal (the flag's off position may not perturb
      the shipped numerics, pinned here and by test). Both builds are
      the same construction by design, so the comparison alone only
      proves determinism — the off build additionally traces with the
      quant entry point POISONED and its compiled program is censused
      for narrow dtypes (either tripping aborts the leg).
    - **roundtrip bounds**: ``dequantize(quantize(x))`` max per-channel
      error vs the documented bound per dtype
      (``ops.quant.roundtrip_rel_error_bound``).
    - **FLOPs-matched step ratio**: fp32 vs int8 vs fp8 on the same
      scanned stack. CPU caveat (recorded, not hidden): this host has no
      narrow MXU — XLA upcasts the operands, so the ratio prices the
      quantize/dequantize overhead; the FLOPs win needs the real
      hardware's int8/fp8 path (obs/attribution.py per-dtype peaks).
    - **ring wire**: quantized stack wire vs fp32 at the tp geometry
      (exact accounting; the headline — the acceptance bar is <= 0.5x).
    - **HLO quant tripwire**: the compiled quant step must carry
      narrow-fed dots; the tp leg additionally narrow ppermutes with
      the quantization hoisted out of the ring loops
      (``obs/hlo_report.quant_evidence`` — the same walker
      ``--hlo_report`` runs in production), and
      ``check_overlap_expectations`` must return NO quant warnings.
    - **convergence pair** (r9 convention: small constant LR, the
      tracking regime): fp32 vs int8 vs fp8 loss curves from identical
      init — mean abs deviation + final losses + the train-works
      boolean; the fp32-master + re-derived-quantization claim measured
      end-to-end, not only asserted by unit.

    Knobs: BENCH_DEPTH (default 4), BENCH_SEQ, BENCH_BATCH,
    BENCH_STEPS/BENCH_WARMUP, BENCH_CONV_STEPS (default 120),
    BENCH_CONV_LR (default 0.005).
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models.gpt import CausalLmTask, GptDecoder
    from pytorch_ddp_template_tpu.obs.hlo_report import (
        check_overlap_expectations, quant_evidence, schedule_report,
    )
    from pytorch_ddp_template_tpu.ops.quant import (
        dequantize, quantize_channel, roundtrip_rel_error_bound,
    )
    from pytorch_ddp_template_tpu.parallel.collective_matmul import (
        tp_wire_bytes_per_step,
    )
    from pytorch_ddp_template_tpu.parallel.sharding import shard_tree
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    depth = int(os.environ.get("BENCH_DEPTH", "0")) or 4
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    conv_steps = int(os.environ.get("BENCH_CONV_STEPS", "120"))
    conv_lr = float(os.environ.get("BENCH_CONV_LR", "0.005"))
    vocab = 256
    devices = jax.devices()
    n_dev = len(devices)
    tp_size = 2 if n_dev % 2 == 0 and n_dev >= 2 else 1
    mesh = make_mesh(f"data:{n_dev}", devices)
    batch_size = (PER_DEVICE_BATCH or 2) * n_dev
    key = jax.random.PRNGKey(0)
    WIDE = dict(num_heads=4, head_dim=32, mlp_dim=1024, seq=seq)
    NARROW = dict(num_heads=2, head_dim=32, mlp_dim=128, seq=64)

    def make_batch(m, spec_seq):
        ids = np.random.default_rng(0).integers(
            0, vocab, (batch_size, spec_seq))
        return {"input_ids": jax.device_put(
            np.asarray(ids, np.int32), NamedSharding(m, P("data")))}

    def build_state(spec, m, *, quant=None, tp=False, lr=1e-2,
                    schedule_kind="linear"):
        config = TrainingConfig(warmup_steps=0, max_grad_norm=1000.0,
                                learning_rate=lr, lr_schedule=schedule_kind)
        batch = make_batch(m, spec["seq"])
        kwargs = {}
        if quant is not None:
            kwargs["quant_compute"] = quant
        model = GptDecoder(vocab_size=vocab, max_len=spec["seq"],
                           num_layers=depth, num_heads=spec["num_heads"],
                           head_dim=spec["head_dim"],
                           mlp_dim=spec["mlp_dim"], scan_layers=True,
                           tp_overlap=tp, fused_head=tp,
                           mesh=m if tp else None, **kwargs)
        task = CausalLmTask(model)
        params, extra = task.init(key, batch)
        tx, schedule = make_optimizer(config, total_steps=10_000)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, extra_vars=extra,
            opt_state=tx.init(params), rng=jax.random.clone(key))
        state = shard_tree(state, m)
        compiled = make_train_step(task, tx, schedule).lower(
            state, batch).compile()
        return compiled, state, batch

    # -- off bit-parity leg ------------------------------------------------
    # 'default' omits the kwarg and the model's quant_compute defaults to
    # "off", so the param comparison alone proves compile determinism,
    # not the claim. The claim — off never touches the quant machinery —
    # is pinned by poisoning the quant entry point while the off variant
    # traces, and by a narrow-dtype census over its compiled program:
    # either tripping fails the leg loudly (no record is emitted).
    from pytorch_ddp_template_tpu.obs.hlo_report import NARROW_DTYPES
    from pytorch_ddp_template_tpu.ops import quant as _quant_ops

    def _poisoned_quant_dense(*_a, **_k):
        raise AssertionError(
            "quant_compute=off reached ops.quant.quant_dense — the off "
            "dispatch is no longer the plain path")

    slots = {}
    _orig_quant_dense = _quant_ops.quant_dense
    for kind, q in (("default", None), ("off", "off")):
        if kind == "off":
            _quant_ops.quant_dense = _poisoned_quant_dense
        try:
            compiled, state, batch = build_state(WIDE, mesh, quant=q)
        finally:
            _quant_ops.quant_dense = _orig_quant_dense
        if kind == "off":
            off_hlo = compiled.as_text()
            narrow_leaked = [d for d in NARROW_DTYPES if f"{d}[" in off_hlo]
            assert not narrow_leaked, (
                f"quant_compute=off compiled program carries narrow "
                f"dtypes {narrow_leaked} — the off path is quantizing")
        state, metrics = compiled(state, batch)
        slots[kind] = (state, float(metrics["loss"]))
    parity_off = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(jax.tree.leaves(slots["default"][0].params),
                        jax.tree.leaves(slots["off"][0].params)))

    # -- roundtrip bound leg -----------------------------------------------
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((64, 256)).astype(np.float32) * 3)
    roundtrip = {}
    for mode in ("int8", "fp8"):
        q, s = quantize_channel(x, mode, axes=-1)
        err = jnp.max(jnp.abs(dequantize(q, s) - x), axis=-1)
        amax = jnp.max(jnp.abs(x), axis=-1)
        rel = float(jnp.max(err / amax))
        bound = roundtrip_rel_error_bound(mode)
        roundtrip[mode] = {"max_rel_err": rel, "bound": bound,
                           "ok": rel <= bound + 1e-7}

    # -- FLOPs-matched step-time leg ---------------------------------------
    variants = {}
    for kind in ("fp32", "int8", "fp8"):
        q = None if kind == "fp32" else kind
        compiled, state, batch = build_state(WIDE, mesh, quant=q)
        metrics = None
        for _ in range(WARMUP_STEPS):
            state, metrics = compiled(state, batch)
        if metrics is not None:
            float(metrics["loss"])
        variants[kind] = [compiled, state, batch]
    step_ms = {}
    for _rep in range(3):
        for kind, slot in variants.items():
            compiled, state, batch = slot
            t0 = time.perf_counter()
            for _ in range(TIMED_STEPS):
                state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])
            dt = time.perf_counter() - t0
            slot[1] = state
            assert np.isfinite(loss), f"non-finite loss {loss}"
            ms = 1e3 * dt / TIMED_STEPS
            step_ms[kind] = min(step_ms.get(kind, ms), ms)

    # -- HLO tripwire leg (data-only: narrow dots) -------------------------
    hlo_data = quant_evidence(variants["int8"][0].as_text())

    # -- tp legs: narrow ring wire + hoisted-quantize witness --------------
    tp_out: dict = {"degenerate": tp_size == 1}
    if tp_size > 1:
        tpmesh = make_mesh(f"data:{n_dev // tp_size},model:{tp_size}",
                           devices)
        compiled_tp, state_tp, batch_tp = build_state(
            WIDE, tpmesh, quant="int8", tp=True)
        txt = compiled_tp.as_text()
        hlo_tp = quant_evidence(txt)
        cfg_probe = TrainingConfig(
            model="gpt-tiny", scan_layers=True, tp_overlap=True,
            quant_compute="int8", mesh=f"data:{n_dev // tp_size},"
            f"model:{tp_size}")
        quant_warns = [w for w in check_overlap_expectations(
            schedule_report(txt), cfg_probe, dict(tpmesh.shape))
            if "quant" in w]
        # one verified step: the quantized ring path must train
        state_tp, m_tp = compiled_tp(state_tp, batch_tp)
        assert np.isfinite(float(m_tp["loss"]))
        tp_out = {
            "degenerate": False,
            "hlo_tp_narrow_ppermutes": hlo_tp["narrow_ppermutes"],
            "hlo_tp_narrow_dots": hlo_tp["narrow_dots"],
            "hlo_tp_hoisted_ring_bodies":
                hlo_tp["hoisted_quant_ring_bodies"],
            "hlo_tp_quant_warnings": quant_warns,
        }
    wire_kw = dict(batch=batch_size, seq=seq,
                   embed=WIDE["num_heads"] * WIDE["head_dim"],
                   num_layers=depth, n=max(tp_size, 2), vocab=vocab)
    wire_fp32 = tp_wire_bytes_per_step(**wire_kw)
    wires = {m: tp_wire_bytes_per_step(quant=m, **wire_kw)
             for m in ("int8", "fp8")}
    ratio_int8 = wires["int8"]["stack"] / max(wire_fp32["stack"], 1)
    ratio_fp8 = wires["fp8"]["stack"] / max(wire_fp32["stack"], 1)

    # -- convergence-tracking pair (r9 convention) -------------------------
    curves: dict[str, list[float]] = {}
    for kind in ("fp32", "int8", "fp8"):
        q = None if kind == "fp32" else kind
        compiled, state, batch = build_state(
            NARROW, mesh, quant=q, lr=conv_lr, schedule_kind="constant")
        losses = []
        for _ in range(conv_steps):
            state, metrics = compiled(state, batch)
            losses.append(float(metrics["loss"]))
        curves[kind] = losses
    ref = np.asarray(curves["fp32"])
    dev_int8 = float(np.mean(np.abs(np.asarray(curves["int8"]) - ref)))
    dev_fp8 = float(np.mean(np.abs(np.asarray(curves["fp8"]) - ref)))

    # tp-degenerate host (odd/single device count): the ring legs never
    # compiled or ran, so the headline may not claim the ring saving off
    # the phantom n=2 wire math — emit degenerate:true with value 0 (the
    # r8 convention); the wire_mb_* fields stay as static accounting
    tp_degenerate = tp_size == 1
    return {
        # headline spelled higher-is-better (the bench_diff invariant —
        # a lower-is-better ratio would invert the CI tripwire): the
        # fp32-over-narrow wire saving factor. Acceptance bar: saving
        # >= 2x (narrow <= 0.5x fp32), so vs_baseline >= 1.0 passes
        "metric": f"quant_ring_wire_saving_int8_{depth}L",
        "value": (0.0 if tp_degenerate
                  else round(1.0 / max(ratio_int8, 1e-9), 4)),
        "unit": "x_fp32_over_int8_ring_stack_bytes",
        "vs_baseline": (0.0 if tp_degenerate
                        else round(0.5 / max(ratio_int8, 1e-9), 4)),
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n_dev,
        "depth": depth,
        "seq_len": seq,
        "batch": batch_size,
        "model_dims": {k: v for k, v in WIDE.items() if k != "seq"},
        "conv_model_dims": NARROW,
        "timed_steps": TIMED_STEPS,
        "parity_off_max_abs_diff": parity_off,
        "parity_off_bitexact": parity_off == 0.0,
        "roundtrip": roundtrip,
        "step_time_fp32_ms": round(step_ms["fp32"], 2),
        "step_time_int8_ms": round(step_ms["int8"], 2),
        "step_time_fp8_ms": round(step_ms["fp8"], 2),
        # CPU caveat: no narrow MXU here — this ratio prices the
        # quantize overhead; the FLOPs win is legs_r17's to measure
        "step_ratio_int8_vs_fp32": round(
            step_ms["fp32"] / max(step_ms["int8"], 1e-9), 3),
        "step_ratio_fp8_vs_fp32": round(
            step_ms["fp32"] / max(step_ms["fp8"], 1e-9), 3),
        "cpu_no_narrow_mxu": devices[0].platform != "tpu",
        "hlo_narrow_dots": hlo_data["narrow_dots"],
        "hlo_quant_dots_present": hlo_data["quant_dots_present"],
        **tp_out,
        "wire_mb_fp32_stack": round(wire_fp32["stack"] / 1e6, 3),
        "wire_mb_int8_stack": round(wires["int8"]["stack"] / 1e6, 3),
        "wire_mb_fp8_stack": round(wires["fp8"]["stack"] / 1e6, 3),
        "wire_int8_vs_fp32": round(ratio_int8, 4),
        "wire_fp8_vs_fp32": round(ratio_fp8, 4),
        "conv_steps": conv_steps,
        "conv_lr": conv_lr,
        "loss_dev_int8": dev_int8,
        "loss_dev_fp8": dev_fp8,
        "final_loss_fp32": curves["fp32"][-1],
        "final_loss_int8": curves["int8"][-1],
        "final_loss_fp8": curves["fp8"][-1],
        "int8_trained": curves["int8"][-1] < curves["int8"][0],
        "fp8_trained": curves["fp8"][-1] < curves["fp8"][0],
    }


def run_elastic() -> dict:
    """Elastic-fleet proof (round 18, ``checkpoint/hot.py`` +
    ``checkpoint/reshard.py`` + ``train/supervisor.py``): hot snapshots
    must be ~free on the step clock, must strictly beat durable-only on
    MTTR and lost work when a crash lands, and the fallback paths
    (corrupt hot generation, partially-written durable step) must
    restore through the production path, not refuse.

    Legs, sized for what THIS host can prove (a real multi-host
    preemption drill — SIGTERM one worker, resume on fewer chips —
    rides ``tools/tpu_followup.sh legs_r18``):

    - **neutrality**: the FULL production loop with
      ``--hot_save_steps`` ON (cadence ``BENCH_HOT_EVERY``, default 5)
      vs off, same model/batch/mesh, alternating fresh-run reps;
      ``value`` = plain/hot ratio of the POOLED-median honest step
      time (per-rep means are not comparable on a shared CPU host —
      clock wander between reps exceeds the effect being measured);
      the 0.9 band carries the headline. The hot tier's actual cost is
      booked to the ``hot_checkpoint_save`` goodput bucket and
      recorded separately, and the snapshot interval plus its
      writeback-bleed successor are discarded from the timer —
      neutrality on the step clock plus a visible, bounded side-work
      bill is the design point.
    - **MTTR + lost steps**: two subprocess episodes of
      ``--inject_fault crash:K`` (hard ``os._exit`` after step K's
      saves) followed by an auto-resume — one durable-only
      (``--save_steps 8``), one with ``--hot_save_steps 2`` layered
      under the same durable cadence. MTTR is kill→first-productive-
      step measured from the resume process spawn to the first NEW
      progress record; lost steps = K - resume point. The hot episode
      must be strictly below durable-only on both, and its resume must
      log ``restored from hot snapshot``.
    - **fault fallbacks**: ``corrupt-hot-snapshot`` through a real run
      (the byte-flipped newest generation fails CRC validation and
      restore falls back) and a truncated newest durable step dir
      (restore walks back to the latest COMPLETE step) — both through
      ``restore_or_init``, the production path.

    Knobs: BENCH_MODEL (default gpt-tiny — big enough state that the
    durable-vs-hot restore cost difference is visible over process
    noise), BENCH_BATCH, BENCH_STEPS/BENCH_WARMUP, BENCH_OUTPUT.
    """
    import json as _json
    import shutil
    import subprocess
    from pathlib import Path

    import jax

    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import build
    from pytorch_ddp_template_tpu.runtime import init as rt_init
    from pytorch_ddp_template_tpu.train.engine import Trainer

    model = os.environ.get("BENCH_MODEL") or "gpt-tiny"
    # batch 4: steps slow enough that the durable tier's replayed lost
    # steps (up to save_steps-1 of them) dominate the MTTR comparison
    # over process-startup jitter
    per_device = PER_DEVICE_BATCH or 4
    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    global_batch = per_device * n_dev
    out_base = os.environ.get("BENCH_OUTPUT", "/tmp/bench_elastic")
    total_steps = WARMUP_STEPS + TIMED_STEPS
    repo = os.path.dirname(os.path.abspath(__file__))

    base_cfg = dict(
        model=model, mesh=f"data:{n_dev}",
        per_device_train_batch_size=per_device,
        dataset_size=max(global_batch * (total_steps + 2), 512),
        warmup_steps=0, max_grad_norm=1000.0, max_steps=total_steps,
        logging_steps=0, save_steps=0, resume=False,
    )
    ctx = rt_init(TrainingConfig(**base_cfg, output_dir=out_base + "_init"))

    def build_trainer(kind: str, rep, **extra):
        cfg = TrainingConfig(**{**base_cfg,
                                "output_dir": f"{out_base}_{kind}_{rep}",
                                **extra})
        shutil.rmtree(cfg.output_dir, ignore_errors=True)
        task, ds = build(model, cfg, mesh=ctx.mesh)
        return Trainer(cfg, ctx, task, ds)

    # -- neutrality leg: alternating fresh-run reps, min-of-reps ----------
    # cadence 5 (BENCH_HOT_EVERY): snapshot cost sets the cadence
    # (CheckFreq's point) — every-2 is the deterministic-test setting,
    # not a production posture, and on a ~100ms-step model it would
    # resync the bounded dispatch pipeline every other step
    hot_every = int(os.environ.get("BENCH_HOT_EVERY", "5"))
    # pooled-median estimator: this host's run-to-run clock wander
    # (~±15% on shared CPU) dwarfs the hot tier's per-step effect, so
    # per-rep means are not comparable — pool every honest (non-
    # discarded) step sample across alternating reps and compare the
    # medians instead
    samples: dict[str, list[float]] = {"plain": [], "hot": []}
    hot_save_s = 0.0
    hot_generations = 0
    import numpy as _np
    for rep in range(3):
        for kind in ("plain", "hot"):
            extra = {"hot_save_steps": hot_every} if kind == "hot" else {}
            trainer = build_trainer(kind, rep, **extra)
            trainer.train()
            trainer.ckpt.close()
            samples[kind].extend(1e3 * t
                                 for t in trainer.step_timer._times)
            if kind == "hot":
                gp = _json.loads(
                    (Path(trainer.config.output_dir) / "goodput.json")
                    .read_text())
                hot_save_s = max(hot_save_s,
                                 gp["buckets"]["hot_checkpoint_save"])
                hot_generations = len(trainer.hot.generations())
    if not samples["plain"] or not samples["hot"]:
        raise RuntimeError("timed window produced no step samples")
    step_ms = {k: float(_np.median(v)) for k, v in samples.items()}
    ratio = step_ms["plain"] / max(step_ms["hot"], 1e-9)
    if hot_generations == 0:
        raise RuntimeError("hot variant wrote no generations — the hot "
                           "tier never ran; the neutrality pair proves "
                           "nothing")

    # -- MTTR + lost-steps episodes (subprocess: the crash is os._exit) ---
    # crash at 23 against --save_steps 8: the durable tier is 7 steps
    # stale, the hot tier (cadence 2) 1 step — MTTR is kill→first
    # FRONTIER-ADVANCING step (the first step that produces work the
    # killed attempt had not already done), so the replayed lost steps
    # are priced into it, not just the restore read
    crash_step = 23
    episode_steps = 40
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={n_dev}")

    def ddp_args(outdir: str, *extra: str) -> list[str]:
        return [sys.executable, "-u", os.path.join(repo, "ddp.py"),
                "--model", model, "--mesh", f"data:{n_dev}",
                "--per_device_train_batch_size", str(per_device),
                "--dataset_size", str(base_cfg["dataset_size"]),
                "--max_steps", str(episode_steps), "--logging_steps", "1",
                "--save_steps", "8", "--seed", "7",
                "--output_dir", outdir, *extra]

    def resume_once(crashdir: str, rep: int, *extra: str) -> dict:
        """Copy the crashed dir (a resume mutates it) and time the
        resume: MTTR = spawn → first metrics record whose step ADVANCES
        past the crash frontier."""
        outdir = f"{crashdir}_resume_{rep}"
        shutil.rmtree(outdir, ignore_errors=True)
        shutil.copytree(crashdir, outdir)
        metrics = Path(outdir) / "metrics.jsonl"
        offset = metrics.stat().st_size if metrics.is_file() else 0
        t_spawn = time.perf_counter()
        proc = subprocess.Popen(
            ddp_args(outdir, *extra), env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        mttr_s = None
        deadline = time.time() + 540
        try:
            while time.time() < deadline:
                if metrics.is_file() and metrics.stat().st_size > offset:
                    with open(metrics) as f:
                        f.seek(offset)
                        fresh = f.read().splitlines()
                    recs = []
                    for l in fresh:  # last line may be torn mid-write
                        try:
                            recs.append(_json.loads(l))
                        except ValueError:
                            pass
                    if any("loss" in r and r.get("step", 0) > crash_step
                           for r in recs):
                        mttr_s = time.perf_counter() - t_spawn
                        break
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            out, _ = proc.communicate(timeout=540)
        finally:
            if proc.poll() is None:
                proc.kill()
        if mttr_s is None:
            raise RuntimeError(
                f"resume of {crashdir} never advanced past step "
                f"{crash_step}:\n{(out or '')[-2000:]}")
        describe = _json.loads((Path(outdir) / "describe.json").read_text())
        gp = _json.loads((Path(outdir) / "goodput.json").read_text())
        return {
            "mttr_s": mttr_s,
            "resume_step": describe["resumed_at_step"],
            "attempt": describe["attempt"],
            "restore_s": gp["buckets"]["restore"],
            "halted_s": gp["buckets"]["halted"],
            "hot_restore": "restored from hot snapshot" in (out or ""),
        }

    def episode(kind: str, *extra: str) -> dict:
        crashdir = f"{out_base}_mttr_{kind}"
        shutil.rmtree(crashdir, ignore_errors=True)
        crashed = subprocess.run(
            ddp_args(crashdir, "--inject_fault", f"crash:{crash_step}",
                     *extra),
            env=env, cwd=repo, capture_output=True, text=True, timeout=600)
        if crashed.returncode != 137:
            raise RuntimeError(
                f"{kind} crash leg exited rc={crashed.returncode} "
                f"(expected the injected 137):\n{crashed.stderr[-2000:]}")
        # min-of-2 resume reps (each from a fresh copy of the crashed
        # dir): interpreter + compile startup jitter is the noise floor
        # the MTTR comparison must not drown in
        reps = [resume_once(crashdir, rep, *extra) for rep in range(2)]
        best = min(reps, key=lambda r: r["mttr_s"])
        best["lost_steps"] = crash_step - best["resume_step"]
        return best

    durable = episode("durable")
    hot = episode("hot", "--hot_save_steps", "2")

    # -- fault-fallback legs (production restore path) --------------------
    from pytorch_ddp_template_tpu.checkpoint.hot import (
        HotCheckpointManager,
    )

    t = build_trainer("corrupt", 0, max_steps=6, save_steps=6,
                      hot_save_steps=2,
                      inject_fault="corrupt-hot-snapshot:4")
    t.train()
    t.ckpt.close()
    # gen@6 is newest and valid; gen@4 was byte-flipped in place. Drop
    # gen@6 so the restore faces the corrupt generation directly
    hotm = HotCheckpointManager(f"{out_base}_corrupt_0")
    shutil.rmtree(hotm.generations()[-1][2])
    rec = hotm.latest_valid()
    corrupt_detected = rec is None or rec.step < 4
    # rebuild WITHOUT build_trainer (it wipes the output dir): the
    # corrupt run's artifacts are the input
    cfg2 = TrainingConfig(**{**base_cfg, "max_steps": 6, "save_steps": 6,
                             "resume": True, "hot_save_steps": 2,
                             "output_dir": f"{out_base}_corrupt_0"})
    task2, ds2 = build(model, cfg2, mesh=ctx.mesh)
    t2 = Trainer(cfg2, ctx, task2, ds2)
    _, start = t2.restore_or_init()
    t2.ckpt.close()
    # the corrupt generation never validates; durable step 6 restores
    corrupt_fallback_ok = corrupt_detected and start == 6

    t3 = build_trainer("partial", 0, max_steps=8, save_steps=4)
    t3.train()
    t3.ckpt.close()
    for f in (Path(f"{out_base}_partial_0") / "checkpoint_8"
              / "state").rglob("*"):
        if f.is_file() and f.stat().st_size > 256:
            f.write_bytes(b"\0")
    cfg4 = TrainingConfig(**{**base_cfg, "max_steps": 8, "save_steps": 4,
                             "resume": True,
                             "output_dir": f"{out_base}_partial_0"})
    task4, ds4 = build(model, cfg4, mesh=ctx.mesh)
    t4 = Trainer(cfg4, ctx, task4, ds4)
    _, start4 = t4.restore_or_init()
    t4.ckpt.close()
    partial_fallback_ok = start4 == 4  # fell back past the torn step 8

    return {
        "metric": "elastic_hot_overhead_ratio",
        "value": round(ratio, 3),
        # hot snapshots every 2 steps vs off, full production loop; the
        # 0.9 band carries the headline (cost lives in the
        # hot_checkpoint_save bucket, off the step clock)
        "unit": "x_plain_step_time",
        "vs_baseline": round(ratio / 0.9, 4),
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "n_processes": jax.process_count(),
        "model": model,
        "global_batch": global_batch,
        "timed_steps": TIMED_STEPS,
        "step_time_plain_ms": round(step_ms["plain"], 3),
        "step_time_hot_ms": round(step_ms["hot"], 3),
        "hot_save_bucket_s": round(hot_save_s, 4),
        "hot_generations_kept": hot_generations,
        # MTTR episodes: hot strictly below durable-only on both counts
        "crash_step": crash_step,
        "mttr_durable_s": round(durable["mttr_s"], 3),
        "mttr_hot_s": round(hot["mttr_s"], 3),
        "mttr_hot_below_durable": hot["mttr_s"] < durable["mttr_s"],
        "lost_steps_durable": durable["lost_steps"],
        "lost_steps_hot": hot["lost_steps"],
        "lost_steps_hot_below_durable":
            hot["lost_steps"] < durable["lost_steps"],
        "resume_step_durable": durable["resume_step"],
        "resume_step_hot": hot["resume_step"],
        "restore_s_durable": round(durable["restore_s"], 3),
        "restore_s_hot": round(hot["restore_s"], 3),
        "hot_resume_used_hot_snapshot": hot["hot_restore"],
        "resume_attempt": hot["attempt"],
        "halted_booked_s": round(hot["halted_s"], 3),
        # fault fallbacks through the production restore path
        "corrupt_snapshot_fallback_ok": corrupt_fallback_ok,
        "partial_save_fallback_ok": partial_fallback_ok,
    }


def run_serve() -> dict:
    """Serving-engine proof (round 19, ``serve/``): continuous batching
    must beat static-batch decode at mixed sequence lengths on the SAME
    requests (FLOPs-matched — identical prompts, identical generated
    tokens, identical model), sequence growth across KV-block
    boundaries must trigger ZERO decode recompiles, and the SLO
    numbers (TTFT, per-token latency, tokens/sec/chip) plus the live
    ``tpuddp_serve_*`` gauges must come out of a real run.

    Workload: ``BENCH_SERVE_REQUESTS`` requests (prompts 4–16 tokens)
    in admission waves of ``BENCH_SERVE_SLOTS``, each wave carrying ONE
    long straggler (max_new 64) among short (4–8 token) members — the
    Orca scenario: static batching drains every wave at the straggler's
    pace with the short members' slots idle; continuous batching
    refills them the step they free. Each engine runs the workload
    twice — the SAME engine both times, so the first pass compiles the
    prefill bucket + the one decode program and the SECOND pass is
    timed fully warm (compile time is a startup cost, not a throughput
    number; the zero-recompile pin and the recorded TTFT/per-token
    numbers then describe the warm pass only).

    The record also carries a CPU paged-attention parity probe
    (``PAGED_IMPL=pallas`` interpret vs the xla gather) — the
    real-Mosaic record is ``tools/tpu_followup.sh legs_r19``'s.

    Knobs: BENCH_SERVE_REQUESTS (default 24), BENCH_SERVE_SLOTS
    (default 4), BENCH_KV_QUANT=int8 (ablation — the r17 int8 KV
    cache; record carries ``kv_quant`` so bench_diff skips it as a
    headline).
    """
    import urllib.request

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.models.gpt import gpt_tiny
    from pytorch_ddp_template_tpu.obs.goodput import GoodputLedger
    from pytorch_ddp_template_tpu.obs.server import StatusServer
    from pytorch_ddp_template_tpu.serve import ServeConfig, ServeEngine

    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", "24"))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", "4"))
    kv_quant = os.environ.get("BENCH_KV_QUANT", "off")
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())

    model = gpt_tiny(vocab_size=512, seq_len=256)
    import flax.linen as nn

    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
        train=False)["params"])

    rng = np.random.RandomState(0)
    # one straggler per wave of `slots`: mixed DECODE lengths by
    # construction (short prompts keep the workload decode-bound —
    # prefill cost is identical under both policies and only dilutes
    # the batching comparison)
    requests = []
    for i in range(n_req):
        plen = int(rng.randint(4, 17))
        max_new = 64 if i % slots == 0 else int(rng.randint(4, 9))
        requests.append(([int(t) for t in rng.randint(0, 512, plen)],
                         max_new))
    total_new = sum(m for _, m in requests)

    def make_engine(static: bool, goodput=None, status=None):
        return ServeEngine(
            model, params,
            ServeConfig(block_size=16, num_blocks=256, max_slots=slots,
                        max_model_len=128, kv_quant=kv_quant,
                        static_batch=static),
            goodput=goodput, status=status)

    def drive(eng):
        """One pass of the workload through an EXISTING engine (jit
        caches persist across passes — pass 1 compiles, pass 2 times
        the warm programs). Returns the pass's own requests + rate."""
        reqs = [eng.submit(prompt, max_new_tokens=max_new)
                for prompt, max_new in requests]
        t0 = time.perf_counter()
        eng.run()
        wall = time.perf_counter() - t0
        tokens = sum(len(r.tokens) for r in reqs)
        assert tokens == total_new, (tokens, total_new)
        return reqs, tokens / wall, wall

    gp_dir = os.environ.get("BENCH_OUTPUT", "/tmp/bench_serve")
    os.makedirs(gp_dir, exist_ok=True)
    gp_path = os.path.join(gp_dir, "goodput.json")
    if os.path.exists(gp_path):
        os.remove(gp_path)
    goodput = GoodputLedger(gp_dir)
    status = StatusServer(0)
    status.start()
    try:
        eng_c = make_engine(static=False, goodput=goodput, status=status)
        drive(eng_c)  # compile pass
        timed_reqs, tps_cont, wall_c = drive(eng_c)  # warm pass
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/metrics",
                timeout=10) as resp:
            metrics_text = resp.read().decode()
    finally:
        status.close()
    gauges_live = "tpuddp_serve_tokens_per_sec" in metrics_text
    goodput.flush()
    gp = goodput.summary()["buckets_s"]

    eng_s = make_engine(static=True)
    drive(eng_s)  # compile pass
    _, tps_static, wall_s = drive(eng_s)  # warm pass

    # the compile-cache pin: sequences grew across block boundaries
    # (up to 16-token prompts + 64 generated span 5 16-token blocks)
    # over TWO full workload passes and the decode cache still holds
    # exactly ONE program
    zero_recompile = (eng_c.decode_programs() == 1
                      and eng_s.decode_programs() == 1)
    # SLO over the TIMED pass only (the compile pass's first-wave TTFT
    # is a compile stall, not a serving number)
    ttfts = [r.ttft_s for r in timed_reqs if r.ttft_s is not None]
    pts = [r.per_token_s for r in timed_reqs if r.per_token_s is not None]
    slo = {
        "ttft_s_mean": sum(ttfts) / len(ttfts) if ttfts else None,
        "ttft_s_max": max(ttfts) if ttfts else None,
        "per_token_s_mean": sum(pts) / len(pts) if pts else None,
    }

    # CPU parity probe for the Pallas gather kernel (interpret mode)
    from pytorch_ddp_template_tpu.serve.decode_ops import (
        _paged_attention_pallas, _paged_attention_xla,
    )

    prng = np.random.RandomState(1)
    q = jnp.asarray(prng.randn(3, 2, 32).astype(np.float32))
    kp = jnp.asarray(prng.randn(12, 16, 2, 32).astype(np.float32))
    vp = jnp.asarray(prng.randn(12, 16, 2, 32).astype(np.float32))
    tb = jnp.asarray(prng.randint(0, 12, (3, 4)).astype(np.int32))
    ln = jnp.asarray(np.array([37, 9, 64], np.int32))
    parity = float(jnp.abs(
        _paged_attention_xla(q, kp, vp, tb, ln)
        - _paged_attention_pallas(q, kp, vp, tb, ln)).max())

    ratio = tps_cont / tps_static if tps_static else 0.0
    rec = {
        "metric": "serve_continuous_vs_static",
        "value": round(ratio, 3),
        # iteration-level batching vs wave admission on identical
        # requests; >= 1.5x is the acceptance bar at mixed lengths
        "unit": "x_static_tokens_per_sec",
        "vs_baseline": round(ratio / 1.5, 4),
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "model": "gpt-tiny",
        "requests": n_req,
        "max_slots": slots,
        "total_new_tokens": total_new,
        "tokens_per_sec_continuous": round(tps_cont, 2),
        "tokens_per_sec_static": round(tps_static, 2),
        "tokens_per_sec_per_chip": round(tps_cont / n_dev, 2),
        "ttft_ms_mean": round((slo["ttft_s_mean"] or 0.0) * 1e3, 3),
        "ttft_ms_max": round((slo["ttft_s_max"] or 0.0) * 1e3, 3),
        "per_token_ms_mean": round(
            (slo["per_token_s_mean"] or 0.0) * 1e3, 3),
        # the compile-cache pin, as an executable record: 1.0 means the
        # timed pass (block-boundary growth included) compiled nothing
        "decode_zero_recompile": zero_recompile,
        "decode_programs": eng_c.decode_programs(),
        "prefill_programs": eng_c.prefill_programs(),
        "kv_blocks_high_water": eng_c.kv.stats()["high_water_blocks"],
        "kv_bytes_per_token": eng_c.kv.stats()["bytes_per_token"],
        "metrics_gauges_live": gauges_live,
        "goodput_serve_prefill_s": round(gp.get("serve_prefill", 0.0), 3),
        "goodput_serve_decode_s": round(gp.get("serve_decode", 0.0), 3),
        "paged_pallas_parity_max_abs": parity,
        # interpret-mode parity only on CPU — the Mosaic lowering is
        # legs_r19's to validate (the FLASH_BWD/QUANT_IMPL convention)
        "paged_parity_interpret_only": platform != "tpu",
    }
    if kv_quant != "off":
        rec["kv_quant"] = kv_quant  # ablation-marked (ABLATION_KEYS)
    if os.environ.get("PAGED_IMPL", "xla") != "xla":
        rec["paged_impl"] = os.environ["PAGED_IMPL"]
    if not zero_recompile:
        # a recompiling decode path must fail the record loudly, not
        # ride a still-green throughput ratio
        rec["value"] = 0.0
        rec["error"] = (f"decode recompiled: {eng_c.decode_programs()} "
                        "programs in cache (expected 1)")
    return rec


def run_spec() -> list:
    """Speculative-decoding proof (round 20, ``serve/spec.py``): the
    draft+verify engine must commit MORE than one token per target
    verify step on the SAME mixed-length workload the r19 serve leg
    runs, with the draft's FLOPs accounted against the win, the output
    re-checked token-for-token against the plain engine INSIDE the
    bench (losslessness is the contract, not a hope), the two-program
    compile pin held over two full workload passes, and the
    ``tpuddp_serve_spec_*`` gauges scraped live.

    FLOPs accounting (the honest wager): plain greedy decode spends
    one target-token forward per emitted token (1.0 by definition).
    The speculative path spends, per verify round, ``k`` target lane
    forwards (the window) plus ``k`` draft steps at ``depth/L`` of a
    target forward each — so the record carries
    ``spec_flops_per_token_ratio = drafted * (1 + depth/L) /
    committed`` and the headline acceptance number DIVIDED by that
    ratio (``accepted_per_target_step_flops_adj``): > 1.0 means the
    wager wins even FLOPs-for-FLOPs, before the memory-bound decode
    regime (where the real win lives) is priced in.

    Emits the headline record first, then one ablation-marked row per
    draft depth in ``BENCH_SPEC_DEPTHS`` (literal ``draft_depth`` /
    ``spec_k`` keys — bench_diff skips them as headlines, the r17/r19
    kv_quant convention; the headline spells its config
    ``spec_k_max``/``spec_draft_depth``).

    Knobs: BENCH_SPEC_REQUESTS (default 24), BENCH_SPEC_SLOTS (4),
    BENCH_SPEC_K (4), BENCH_SPEC_DEPTH (1), BENCH_SPEC_DEPTHS ("1,2").
    """
    import urllib.request

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.models.gpt import gpt_tiny
    from pytorch_ddp_template_tpu.obs.goodput import GoodputLedger
    from pytorch_ddp_template_tpu.obs.server import StatusServer
    from pytorch_ddp_template_tpu.serve import ServeConfig, ServeEngine

    n_req = int(os.environ.get("BENCH_SPEC_REQUESTS", "24"))
    slots = int(os.environ.get("BENCH_SPEC_SLOTS", "4"))
    spec_k = int(os.environ.get("BENCH_SPEC_K", "4"))
    depth = int(os.environ.get("BENCH_SPEC_DEPTH", "1"))
    depths = [int(d) for d in os.environ.get(
        "BENCH_SPEC_DEPTHS", "1,2").split(",") if d.strip()]
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())

    model = gpt_tiny(vocab_size=512, seq_len=256)
    n_layers = model.num_layers
    import flax.linen as nn

    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
        train=False)["params"])

    # the r19 workload shape: one long straggler per wave of `slots`
    # among short members — decode-bound, continuous batching churning
    rng = np.random.RandomState(0)
    requests = []
    for i in range(n_req):
        plen = int(rng.randint(4, 17))
        max_new = 64 if i % slots == 0 else int(rng.randint(4, 9))
        requests.append(([int(t) for t in rng.randint(0, 512, plen)],
                         max_new))
    total_new = sum(m for _, m in requests)

    def make_engine(spec: bool, *, goodput=None, status=None,
                    depth_=depth, k=spec_k):
        return ServeEngine(
            model, params,
            ServeConfig(block_size=16, num_blocks=256, max_slots=slots,
                        max_model_len=128,
                        spec_k=k if spec else 0,
                        draft_depth=depth_ if spec else 0),
            goodput=goodput, status=status)

    def drive(eng):
        """One workload pass through an EXISTING engine (pass 1
        compiles, pass 2 times the warm programs)."""
        reqs = [eng.submit(prompt, max_new_tokens=max_new)
                for prompt, max_new in requests]
        t0 = time.perf_counter()
        eng.run()
        wall = time.perf_counter() - t0
        tokens = sum(len(r.tokens) for r in reqs)
        assert tokens == total_new, (tokens, total_new)
        return reqs, tokens / wall, wall

    def spec_summary(eng, d):
        """Acceptance + FLOPs bookkeeping off the SpecRunner ledger."""
        sp = eng._spec
        apts = (sp.committed_total / sp.slot_rounds
                if sp.slot_rounds else 0.0)
        flops_ratio = (sp.drafted_total * (1.0 + d / n_layers)
                       / sp.committed_total if sp.committed_total else 0.0)
        return {
            "accept_rate": round(
                sp.accepted_total / sp.drafted_total
                if sp.drafted_total else 0.0, 4),
            "accepted_per_target_step": round(apts, 3),
            "spec_flops_per_token_ratio": round(flops_ratio, 4),
            "accepted_per_target_step_flops_adj": round(
                apts / flops_ratio if flops_ratio else 0.0, 4),
            "drafted_total": sp.drafted_total,
            "accepted_total": sp.accepted_total,
            "committed_total": sp.committed_total,
            "verify_steps": sp.verify_steps,
            "draft_s_total": round(sp.draft_s, 3),
            "verify_s_total": round(sp.verify_s, 3),
        }

    # -- plain baseline: the output oracle AND the tokens/sec pair
    eng_p = make_engine(False)
    base_reqs, _, _ = drive(eng_p)
    base_out = [list(r.tokens) for r in base_reqs]
    _, tps_plain, _ = drive(eng_p)

    # -- the speculative engine, gauges + goodput attached
    gp_dir = os.environ.get("BENCH_OUTPUT", "/tmp/bench_spec")
    os.makedirs(gp_dir, exist_ok=True)
    gp_path = os.path.join(gp_dir, "goodput.json")
    if os.path.exists(gp_path):
        os.remove(gp_path)
    goodput = GoodputLedger(gp_dir)
    status = StatusServer(0)
    status.start()
    try:
        eng = make_engine(True, goodput=goodput, status=status)
        spec_reqs, _, _ = drive(eng)  # compile pass
        spec_out = [list(r.tokens) for r in spec_reqs]
        timed_reqs, tps_spec, _ = drive(eng)  # warm pass
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/metrics",
                timeout=10) as resp:
            metrics_text = resp.read().decode()
    finally:
        status.close()
    gauges_live = "tpuddp_serve_spec_accept_rate" in metrics_text
    goodput.flush()
    gp = goodput.summary()["buckets_s"]

    lossless = spec_out == base_out
    zero_recompile = (eng.decode_programs() == 2
                      and eng._spec._draft_decode_fn._cache_size() == 1
                      and eng._spec._verify_fn._cache_size() == 1
                      and eng_p.decode_programs() == 1)
    ttfts = [r.ttft_s for r in timed_reqs if r.ttft_s is not None]
    pts = [r.per_token_s for r in timed_reqs if r.per_token_s is not None]
    summ = spec_summary(eng, depth)

    rec = {
        "metric": "serve_spec_accepted_per_target_step",
        "value": summ["accepted_per_target_step"],
        # tokens committed per target verify dispatch; > 1.0 is the
        # acceptance bar — each target step must pay for more than the
        # one token plain decode gets from it
        "unit": "tokens_per_verify_step",
        "vs_baseline": round(summ["accepted_per_target_step"] / 1.0, 4),
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "model": "gpt-tiny",
        "requests": n_req,
        "max_slots": slots,
        "total_new_tokens": total_new,
        # the headline's config, informational spelling (NOT the
        # literal ablation keys — this row IS the headline)
        "spec_k_max": spec_k,
        "spec_draft_depth": depth,
        "spec_adaptive": True,
        **summ,
        # lossless re-checked inside the bench: same prompts, same
        # budgets, token-for-token against the plain engine
        "spec_lossless_checked": lossless,
        "tokens_per_sec_spec": round(tps_spec, 2),
        "tokens_per_sec_plain": round(tps_plain, 2),
        "spec_vs_plain_tokens_per_sec": round(
            tps_spec / tps_plain if tps_plain else 0.0, 3),
        "tokens_per_sec_per_chip": round(tps_spec / n_dev, 2),
        "ttft_ms_mean": round(
            (sum(ttfts) / len(ttfts) if ttfts else 0.0) * 1e3, 3),
        "per_token_ms_mean": round(
            (sum(pts) / len(pts) if pts else 0.0) * 1e3, 3),
        # the compile pin, as an executable record: TWO decode programs
        # (draft + verify, one each; the plain program never traced)
        # over two full passes of growth and k adaptation
        "decode_zero_recompile": zero_recompile,
        "decode_programs": eng.decode_programs(),
        "draft_programs": eng._spec._draft_decode_fn._cache_size(),
        "verify_programs": eng._spec._verify_fn._cache_size(),
        "prefill_programs": eng.prefill_programs(),
        "kv_blocks_high_water": eng.kv.stats()["high_water_blocks"],
        "metrics_gauges_live": gauges_live,
        "goodput_serve_draft_s": round(gp.get("serve_draft", 0.0), 3),
        "goodput_serve_decode_s": round(gp.get("serve_decode", 0.0), 3),
        "goodput_serve_prefill_s": round(gp.get("serve_prefill", 0.0), 3),
    }
    if not lossless:
        # a speculative engine that changes the output is broken, full
        # stop — no throughput or acceptance number may survive it
        rec["value"] = 0.0
        rec["error"] = "spec output != plain greedy output (lossless pin)"
    elif not zero_recompile:
        rec["value"] = 0.0
        rec["error"] = (f"decode recompiled: {eng.decode_programs()} "
                        "programs in cache (expected 2: draft + verify)")
    rows = [rec]

    # -- the draft-depth ablation sweep (marked rows, one pass each:
    # acceptance is pass-independent; warm timing is the headline's)
    for d in depths:
        eng_a = make_engine(True, depth_=d)
        a_reqs, tps_a, _ = drive(eng_a)
        a_lossless = [list(r.tokens) for r in a_reqs] == base_out
        rows.append({
            "metric": "serve_spec_depth_ablation",
            "value": spec_summary(eng_a, d)["accepted_per_target_step"],
            "unit": "tokens_per_verify_step",
            "vs_baseline": 0.0,  # ablation rows are never the headline
            "platform": platform,
            "model": "gpt-tiny",
            # literal ablation keys: bench_diff skips these rows
            "draft_depth": d,
            "spec_k": spec_k,
            **spec_summary(eng_a, d),
            "spec_lossless_checked": a_lossless,
            "tokens_per_sec_cold_pass": round(tps_a, 2),
            "decode_programs": eng_a.decode_programs(),
        })
    return rows


def run_serve_tp() -> list:
    """Tensor-parallel decode proof (round 21,
    ``serve/model.tp_decode_forward``): the ring-sharded decode program
    must be token-for-token identical to single-replica greedy on the
    SAME requests (FLOPs-matched — identical prompts, budgets, model
    and params; the tp twin differs ONLY in ``tp_overlap`` + mesh),
    hold the one-compiled-decode-program pin over two full workload
    passes of sequence growth, and show ring evidence in its own HLO
    (``obs/hlo_report.ring_evidence``: dot-carrying while bodies whose
    collective-permutes are compute-independent — the schedule the
    latency-hiding scheduler can overlap).

    The tokens/sec pair (tp=2 vs single replica) is recorded honestly:
    on the CPU interpreter the ring pays real ppermute overhead for no
    memory-bandwidth win, so the ratio is informational there — the
    acceptance bar is parity + the compile pin + ring evidence; the
    real-chip pair is ``tools/tpu_followup.sh legs_r21``'s to take.

    Emits the headline first, then one ablation-marked row (literal
    ``tp_degree``/``quant_compute`` keys — bench_diff skips it) for the
    quantized ring wire: same parity bar, narrower wire (the headline
    spells its config ``serve_tp_degree``, the ``describe_tp``
    convention).

    Hosts with fewer than 2 devices emit ``degenerate: true`` with
    value 0 (the r8 convention) — a phantom ring must not masquerade
    as a measured pair.

    Knobs: BENCH_SERVE_TP_REQUESTS (default 16), BENCH_SERVE_TP_SLOTS
    (default 4), BENCH_SERVE_TP (tp degree, default 2).
    """
    import urllib.request

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.models.gpt import gpt_tiny
    from pytorch_ddp_template_tpu.obs.hlo_report import ring_evidence
    from pytorch_ddp_template_tpu.obs.server import StatusServer
    from pytorch_ddp_template_tpu.serve import ServeConfig, ServeEngine

    n_req = int(os.environ.get("BENCH_SERVE_TP_REQUESTS", "16"))
    slots = int(os.environ.get("BENCH_SERVE_TP_SLOTS", "4"))
    tp_size = int(os.environ.get("BENCH_SERVE_TP", "2"))
    devices = jax.devices()
    platform = devices[0].platform
    n_dev = len(devices)
    metric = "serve_tp_vs_single_replica"
    unit = "x_single_replica_tokens_per_sec"
    if n_dev < 2 or n_dev % tp_size or slots % tp_size:
        return [{  # single-chip: no model axis to ring over (r8 conv.)
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "degenerate": True,
            "platform": platform, "device_kind": devices[0].device_kind,
            "n_devices": n_dev, "tp_size": tp_size,
            "note": "tp decode needs a model:N>=2 mesh axis dividing "
                    "max_slots",
        }]

    import dataclasses as _dc

    import flax.linen as nn
    from jax.sharding import Mesh

    model = gpt_tiny(vocab_size=512, seq_len=256)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
        train=False)["params"])
    tp_model = _dc.replace(model, tp_overlap=True)
    data_size = n_dev // tp_size
    mesh = Mesh(np.asarray(devices).reshape(data_size, tp_size),
                ("data", "model"))

    # the r19 workload shape: one long straggler per admission wave
    rng = np.random.RandomState(0)
    requests = []
    for i in range(n_req):
        plen = int(rng.randint(4, 17))
        max_new = 64 if i % slots == 0 else int(rng.randint(4, 9))
        requests.append(([int(t) for t in rng.randint(0, 512, plen)],
                         max_new))
    total_new = sum(m for _, m in requests)

    def make_engine(m, mesh_=None, status=None, quant="off"):
        return ServeEngine(
            _dc.replace(m, quant_compute=quant) if quant != "off" else m,
            params,
            ServeConfig(block_size=16, num_blocks=256, max_slots=slots,
                        max_model_len=128),
            mesh=mesh_, status=status)

    def drive(eng):
        reqs = [eng.submit(prompt, max_new_tokens=max_new)
                for prompt, max_new in requests]
        t0 = time.perf_counter()
        eng.run()
        wall = time.perf_counter() - t0
        tokens = sum(len(r.tokens) for r in reqs)
        assert tokens == total_new, (tokens, total_new)
        return [list(r.tokens) for r in reqs], tokens / wall

    # -- single-replica oracle + FLOPs-matched baseline side
    eng_p = make_engine(model)
    base_out, _ = drive(eng_p)  # compile pass
    _, tps_plain = drive(eng_p)  # warm pass

    # -- the TP engine: parity + compile pin + gauges, two passes
    status = StatusServer(0)
    status.start()
    try:
        eng = make_engine(tp_model, mesh_=mesh, status=status)
        tp_out, _ = drive(eng)  # compile pass
        tp_out2, tps_tp = drive(eng)  # warm pass
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/metrics",
                timeout=10) as resp:
            metrics_text = resp.read().decode()
    finally:
        status.close()
    gauges_live = "tpuddp_serve_tp_degree" in metrics_text
    lossless = tp_out == base_out and tp_out2 == base_out
    zero_recompile = (eng.decode_programs() == 1
                      and eng_p.decode_programs() == 1)

    # -- HLO ring evidence: lower the engine's OWN decode callable on
    # engine-shaped inputs and count independent ring bodies
    s = eng.cfg.max_slots
    mb = eng.max_blocks
    lowered = eng._decode_fn.lower(
        eng.params, eng.kv.pool,
        jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.int32),
        jnp.zeros((s, mb), jnp.int32), jnp.zeros((s,), jnp.int32),
        jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.int32))
    # AOT-compile the lowering (does not touch the jit cache — the
    # zero-recompile pin above is already taken): ring_evidence reads
    # optimized HLO, where the scan bodies and ppermutes are visible
    ev = ring_evidence(lowered.compile().as_text())

    # -- the quantized ring wire, same parity bar (ablation row)
    eng_q = make_engine(tp_model, mesh_=mesh, quant="int8")
    q_out, _ = drive(eng_q)
    q_lossless = q_out == base_out

    ratio = tps_tp / tps_plain if tps_plain else 0.0
    tp_desc = eng.describe_tp()
    rec = {
        "metric": metric,
        "value": round(ratio, 3),
        # FLOPs-matched pair: same requests, same params, the tp twin
        # differs only in sharding. Informational on CPU (see above);
        # parity + pin + ring evidence are the acceptance bar
        "unit": unit,
        "vs_baseline": round(ratio, 4),
        "platform": platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n_dev,
        "model": "gpt-tiny",
        "requests": n_req,
        "max_slots": slots,
        "total_new_tokens": total_new,
        # headline config spelling (NOT the literal ablation key)
        **tp_desc,
        "tokens_per_sec_tp": round(tps_tp, 2),
        "tokens_per_sec_single_replica": round(tps_plain, 2),
        # the tentpole's token-for-token pin, re-checked INSIDE the
        # bench over both passes
        "tp_lossless_checked": lossless,
        "tp_quant_wire_lossless_checked": q_lossless,
        # the compile pin: TP decode is still exactly ONE program over
        # two passes of block-boundary growth
        "decode_zero_recompile": zero_recompile,
        "decode_programs": eng.decode_programs(),
        "prefill_programs": eng.prefill_programs(),
        # ring witness in the decode program's own HLO
        "hlo_ring_bodies": ev["ring_bodies"],
        "hlo_independent_ring_bodies": ev["independent_ring_bodies"],
        "metrics_gauges_live": gauges_live,
    }
    if not lossless:
        # a sharded decode that changes tokens is broken, full stop
        rec["value"] = 0.0
        rec["error"] = ("tp decode output != single-replica greedy "
                        "(token-for-token pin)")
    elif not zero_recompile:
        rec["value"] = 0.0
        rec["error"] = (f"decode recompiled: {eng.decode_programs()} "
                        "programs in cache (expected 1)")
    elif not ev["independent_ring_bodies"]:
        rec["value"] = 0.0
        rec["error"] = ("no independent ring bodies in the decode HLO "
                        "(ring schedule not in evidence)")
    rows = [rec]
    rows.append({
        "metric": "serve_tp_quant_wire_ablation",
        "value": tp_desc["serve_tp_ring_wire_mb_per_step_quant"],
        "unit": "mb_per_step",
        "vs_baseline": 0.0,  # ablation rows are never the headline
        "platform": platform,
        "model": "gpt-tiny",
        # literal ablation keys: bench_diff skips this row
        "tp_degree": tp_size,
        "quant_compute": "int8",
        "wire_mb_wide": tp_desc["serve_tp_ring_wire_mb_per_step_wide"],
        "tp_lossless_checked": q_lossless,
        "decode_programs": eng_q.decode_programs(),
    })
    return rows


def run_scaling(model: str) -> dict:
    """DDP scaling sweep: per-chip throughput on data:1/2/4/... sub-meshes.

    BASELINE.md north star: ≥90% scaling efficiency 1→32 chips. On one real
    chip the sweep degenerates to n=1 (recorded anyway); on the 8-virtual-
    device CPU harness it exercises the full sweep mechanics so the harness
    is proven before multi-chip hardware exists.
    """
    import jax

    devices = jax.devices()
    sweep = []
    n = 1
    while n <= len(devices):
        r = run_bench(model, f"{model}_ex_per_sec_per_chip_{n}chips",
                      "examples/sec/chip", 1.0, devices=devices[:n])
        sweep.append({"n_devices": n, "per_chip": r["value"],
                      "step_time_ms": r["step_time_ms"]})
        n *= 2
    base = sweep[0]["per_chip"]
    eff = sweep[-1]["per_chip"] / base if base else 0.0
    degenerate = len(sweep) == 1  # n=1 "scaling" proves nothing
    return {
        "metric": f"scaling_efficiency_{sweep[-1]['n_devices']}chips",
        "value": round(eff, 4),
        "unit": "ratio",
        # a 1-chip sweep must not masquerade as a ≥90%-target pass
        "vs_baseline": 0.0 if degenerate else round(eff / 0.9, 4),
        "degenerate": degenerate,
        "model": model,
        "sweep": sweep,
    }


def run_flash(seq: int | None = None) -> dict:
    """Pallas flash-attention proof: numerics vs the XLA path + timing.

    On TPU this compiles the Mosaic kernel for real (the round-1 gap: the
    kernel had only ever run in the CPU interpreter); off-TPU it runs
    interpret-mode on tiny shapes so the mode itself stays CI-testable.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_ddp_template_tpu.ops.attention import dot_product_attention
    from pytorch_ddp_template_tpu.ops.flash import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    if seq is None:
        seq = int(os.environ.get("BENCH_SEQ", "1024" if on_tpu else "256"))
    b, h, d = (4, 8, 64) if on_tpu else (1, 2, 64)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, seq, h, d)), dtype)
        for _ in range(3)
    )

    results = {}
    for causal in (False, True):
        flash = jax.jit(lambda q, k, v, c=causal: flash_attention(
            q, k, v, causal=c, block_size=min(512, seq)))
        xla = jax.jit(lambda q, k, v, c=causal: dot_product_attention(
            q, k, v, causal=c))
        f, x = flash(q, k, v), xla(q, k, v)
        err = float(jnp.max(jnp.abs(f.astype(jnp.float32)
                                    - x.astype(jnp.float32))))
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-4
        if err > tol:
            raise AssertionError(
                f"flash vs XLA mismatch (causal={causal}): max err {err}"
            )

        def timed(fn, iters=20):
            fn(q, k, v)[0, 0, 0, 0].block_until_ready()  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(q, k, v)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / iters

        t_flash, t_xla = timed(flash), timed(xla)
        key = "causal" if causal else "full"
        results[f"{key}_max_err"] = round(err, 6)
        results[f"{key}_flash_ms"] = round(t_flash * 1e3, 3)
        results[f"{key}_xla_ms"] = round(t_xla * 1e3, 3)
        results[f"{key}_speedup"] = round(t_xla / t_flash, 3)

        # training path: fwd+bwd through the custom-vjp backward, each
        # impl pinned explicitly (the hardware default is the XLA
        # fallback until the Pallas kernels have a Mosaic record — this
        # bench IS that record), vs plain XLA autodiff
        def grad_of(fn):
            return jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2)))

        def timed_grad(fn, iters=20):
            jax.block_until_ready(fn(q, k, v))  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(q, k, v)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / iters

        gxla = grad_of(xla)
        gx = gxla(q, k, v)
        for impl in ("pallas", "xla"):
            label = "pallas" if impl == "pallas" else "fallback"
            os.environ["FLASH_BWD"] = impl
            try:
                # fresh outer jit per impl: FLASH_BWD is read when the
                # custom vjp is traced under it
                gflash = grad_of(flash)
                gf = gflash(q, k, v)
                gerr = max(
                    float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                          - b_.astype(jnp.float32))))
                    for a, b_ in zip(gf, gx)
                )
                gscale = max(
                    float(jnp.max(jnp.abs(b_.astype(jnp.float32))))
                    for b_ in gx
                )
                results[f"{key}_bwd_{label}_max_err"] = round(gerr, 6)
                results[f"{key}_bwd_{label}_ok"] = bool(
                    gerr <= max(tol * 50, tol * gscale))
                if not results[f"{key}_bwd_{label}_ok"] and impl == "xla":
                    # the fallback is the trusted default — a mismatch
                    # there is a real regression, not a Mosaic question
                    raise AssertionError(
                        f"flash fallback grad mismatch (causal={causal}): "
                        f"max err {gerr} (ref scale {gscale})"
                    )
                results[f"{key}_bwd_{label}_ms"] = round(
                    timed_grad(gflash) * 1e3, 3)
            except AssertionError:
                raise
            except Exception as e:  # noqa: BLE001 - a Mosaic reject on the
                # pallas impl is itself the datum this mode exists to record
                results[f"{key}_bwd_{label}_error"] = repr(e)[:300]
            finally:
                os.environ.pop("FLASH_BWD", None)
        tb_xla_ms = round(timed_grad(gxla) * 1e3, 3)
        results[f"{key}_bwd_autodiff_ms"] = tb_xla_ms
        # only numerically-correct impls compete for the headline speedup:
        # a Mosaic-miscompiled pallas bwd records its timing as a datum
        # but must not advertise a speedup no correct config achieves
        tb_best_ms = min(
            (results[f"{key}_bwd_{lbl}_ms"]
             for lbl in ("pallas", "fallback")
             if results.get(f"{key}_bwd_{lbl}_ok")
             and f"{key}_bwd_{lbl}_ms" in results),
            default=float("inf"),
        )
        if tb_best_ms < float("inf"):
            results[f"{key}_bwd_speedup"] = round(tb_xla_ms / tb_best_ms, 3)

    speedup = results["causal_speedup"]
    return {
        "metric": f"flash_attn_speedup_seq{seq}_causal",
        "value": speedup,
        "unit": "x_vs_xla",
        "vs_baseline": speedup,  # parity with stock XLA == 1.0
        "platform": jax.devices()[0].platform,
        "dtype": str(dtype.__name__ if hasattr(dtype, "__name__") else dtype),
        **results,
    }


def main() -> None:
    metric, unit, _ = BASELINE_PER_DEVICE.get(
        MODEL, (f"{MODEL}_examples_per_sec_per_chip", "examples/sec/chip", 1.0)
    )
    try:
        init_devices()
        from pytorch_ddp_template_tpu.models import available_models

        model = MODEL if MODEL in available_models() else "mlp-wide"
        metric, unit, baseline = BASELINE_PER_DEVICE.get(
            model, (f"{model}_examples_per_sec_per_chip", "examples/sec/chip", 1.0)
        )
        if MODE == "scaling":
            _emit(run_scaling(model))
        elif MODE == "flash":
            _emit(run_flash())
        elif MODE == "compile":
            _emit(run_compile())
        elif MODE == "overlap":
            _emit(run_overlap())
        elif MODE == "comms":
            _emit(run_comms())
        elif MODE == "tp":
            _emit(run_tp())
        elif MODE == "overlap3d":
            _emit(run_overlap3d())
        elif MODE == "obs":
            _emit(run_obs())
        elif MODE == "perf":
            _emit(run_perf())
        elif MODE == "fleet":
            _emit(run_fleet())
        elif MODE == "mem":
            _emit(run_mem())
        elif MODE == "pipe":
            _emit(run_pipe())
        elif MODE == "pipe_compose":
            _emit(run_pipe_compose())
        elif MODE == "quant":
            _emit(run_quant())
        elif MODE == "elastic":
            _emit(run_elastic())
        elif MODE == "serve":
            _emit(run_serve())
        elif MODE == "spec":
            for rec in run_spec():
                _emit(rec)  # headline first, then the marked ablations
        elif MODE == "serve_tp":
            for rec in run_serve_tp():
                _emit(rec)  # headline first, then the marked ablation
        elif MODE == "e2e":
            _emit(run_e2e(model, metric, unit, baseline))
        elif MODE == "train":
            _emit(run_bench(model, metric, unit, baseline))
        else:  # typo'd mode must not masquerade as a train number
            raise ValueError(
                f"unknown BENCH_MODE {MODE!r}; expected "
                "train|e2e|scaling|flash|compile|overlap|comms|tp|"
                "overlap3d|obs|perf|fleet|mem|pipe|pipe_compose|quant|"
                "elastic|serve|spec|serve_tp"
            )
    except KeyboardInterrupt:  # operator abort is not a value-0 datum
        raise
    except BaseException as e:  # noqa: BLE001 - JSON-or-bust driver contract
        _fail(metric, unit, e)
        sys.exit(1)


if __name__ == "__main__":
    main()
