"""Benchmark harness: one JSON line for the driver.

Measures sustained training throughput (examples/sec/chip) of the flagship
config on the available hardware, steady-state (post-compile), end-to-end
through the jitted train step.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
ratio is against the documented era-appropriate target below for the metric
BASELINE.json names (ResNet-50 images/sec/chip on the reference's V100
hardware hints); >1.0 means this framework beats that bar per chip.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# Era-appropriate per-device reference throughputs (the reference targeted
# 4xV100 nodes, run.sbatch:2-9). Values are the well-known MLPerf-era
# fp32 V100 numbers; see BENCH.md.
BASELINE_PER_DEVICE = {
    "resnet50": ("resnet50_images_per_sec_per_chip", "images/sec/chip", 380.0),
    "resnet18": ("resnet18_images_per_sec_per_chip", "images/sec/chip", 2200.0),
    "bert-base": ("bert_base_seq512_per_sec_per_chip", "sequences/sec/chip", 35.0),
    "vit-b16": ("vit_b16_images_per_sec_per_chip", "images/sec/chip", 100.0),
    "gpt-small": ("gpt_small_seq1024_per_sec_per_chip", "sequences/sec/chip", 6.0),
    "mlp-wide": ("mlp_wide_examples_per_sec_per_chip", "examples/sec/chip", 1.0e6),
}

MODEL = os.environ.get("BENCH_MODEL", "resnet50")
WARMUP_STEPS = int(os.environ.get("BENCH_WARMUP", "5"))
TIMED_STEPS = int(os.environ.get("BENCH_STEPS", "30"))
PER_DEVICE_BATCH = int(os.environ.get("BENCH_BATCH", "0"))  # 0 = model default


def default_batch(model: str) -> int:
    return {"resnet50": 128, "resnet18": 512, "bert-base": 16, "vit-b16": 64,
            "gpt-small": 8, "mlp-wide": 4096}.get(model, 128)


def main() -> None:
    from pytorch_ddp_template_tpu.config import TrainingConfig
    from pytorch_ddp_template_tpu.models import available_models, build
    from pytorch_ddp_template_tpu.runtime import make_mesh
    from pytorch_ddp_template_tpu.runtime.context import RuntimeContext
    from pytorch_ddp_template_tpu.train.engine import (
        TrainState,
        make_optimizer,
        make_train_step,
    )

    model = MODEL if MODEL in available_models() else "mlp-wide"
    metric, unit, baseline = BASELINE_PER_DEVICE.get(
        model, (f"{model}_examples_per_sec_per_chip", "examples/sec/chip", 1.0)
    )
    per_device = PER_DEVICE_BATCH or default_batch(model)

    n_dev = jax.device_count()
    mesh = make_mesh("data:-1")
    config = TrainingConfig(
        model=model,
        per_device_train_batch_size=per_device,
        bf16=True,  # TPU-native precision: bf16 compute, f32 master params
        dataset_size=per_device * n_dev * 2,
        warmup_steps=0,
        max_grad_norm=1000.0,
    )
    seed_key = jax.random.PRNGKey(0)
    ctx = RuntimeContext(mesh=mesh, seed_key=seed_key,
                         host_key=jax.random.fold_in(seed_key, 0), config=config)
    task, dataset = build(model, config)

    global_batch = per_device * n_dev
    idx = np.arange(global_batch) % len(dataset)
    host_batch = dataset.batch(idx)
    batch = {
        k: jax.device_put(v, NamedSharding(mesh, P("data")))
        for k, v in host_batch.items()
    }

    params, extra = task.init(seed_key, batch)
    tx, schedule = make_optimizer(config, total_steps=10_000)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        extra_vars=extra,
        opt_state=tx.init(params),
        rng=jax.random.clone(seed_key),
    )
    from pytorch_ddp_template_tpu.parallel import shard_tree

    state = shard_tree(state, mesh)  # unbox + place per logical annotations
    train_step = make_train_step(task, tx, schedule, accum_steps=1)

    # Sync by fetching a real value: on some PJRT transports (e.g. the axon
    # tunnel) block_until_ready can return before compute has finished,
    # which would inflate throughput ~100x. A host read of a scalar that
    # depends on every step cannot lie.
    for _ in range(WARMUP_STEPS):
        state, metrics = train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = train_step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    examples_per_sec = TIMED_STEPS * global_batch / dt
    per_chip = examples_per_sec / n_dev
    print(json.dumps({
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": unit,
        "vs_baseline": round(per_chip / baseline, 4),
    }))


if __name__ == "__main__":
    main()
