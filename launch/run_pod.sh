#!/usr/bin/env bash
# TPU-pod launcher (reference: run.sbatch + run.slurm.sh rendezvous dance).
# On Cloud TPU pods, `gcloud ... ssh --worker=all` starts one process per
# host; JAX discovers the coordinator automatically from the TPU metadata —
# no MASTER_ADDR/port-scan equivalent is needed (that is the TPU-native
# replacement for run.sbatch:11-12).
set -euo pipefail

TPU_NAME=${TPU_NAME:?set TPU_NAME}
ZONE=${ZONE:?set ZONE}
REPO_DIR=${REPO_DIR:-'~/pytorch_ddp_template_tpu'}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd $REPO_DIR && python ddp.py ${*@Q}"
