#!/usr/bin/env bash
# Local two-process rehearsal launcher: the reference's multi-rank localhost
# mode (run.sh: torch.distributed.launch with MASTER_ADDR=127.0.0.1) mapped
# to JAX — two processes rendezvous through jax.distributed.initialize and
# train ONE SPMD job over the union of their devices. On CPU each process
# gets N virtual devices (DEVS_PER_PROC); on a multi-host TPU slice use
# run_pod.sh instead (one process per host, addresses discovered).
#
# Usage: bash launch/run_local_2proc.sh [extra ddp.py flags...]
set -euo pipefail
cd "$(dirname "$0")/.."

PORT=${PORT:-$((10000 + RANDOM % 40000))}
DEVS_PER_PROC=${DEVS_PER_PROC:-4}
MODEL=${MODEL:-mlp}
OUTPUT_DIR=${OUTPUT_DIR:-outputs_2proc}

run_rank() {
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=${DEVS_PER_PROC}" \
  python ddp.py \
    --cpu \
    --coordinator_address "127.0.0.1:${PORT}" \
    --num_processes 2 \
    --process_id "$1" \
    --model "$MODEL" \
    --output_dir "$OUTPUT_DIR" \
    --per_device_train_batch_size "${PER_DEVICE_BATCH:-4}" \
    --max_steps "${MAX_STEPS:-24}" \
    --logging_steps "${LOGGING_STEPS:-8}" \
    --save_steps "${SAVE_STEPS:-0}" \
    "${@:2}"
}

run_rank 1 "$@" &
WORKER=$!
trap 'kill "$WORKER" 2>/dev/null || true' EXIT
run_rank 0 "$@"
wait "$WORKER"
