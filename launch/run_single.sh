#!/usr/bin/env bash
# Single-host launcher (reference: run.sh, which needed
# torch.distributed.launch to spawn one process per GPU). Under JAX a single
# process owns every local TPU chip, so "multi-device single node" is just:
set -euo pipefail

MODEL=${MODEL:-mlp}
OUTPUT_DIR=${OUTPUT_DIR:-outputs}

exec python ddp.py \
  --model "$MODEL" \
  --output_dir "$OUTPUT_DIR" \
  --per_device_train_batch_size "${PER_DEVICE_BATCH:-128}" \
  --num_train_epochs "${EPOCHS:-3}" \
  --logging_steps "${LOGGING_STEPS:-50}" \
  --save_steps "${SAVE_STEPS:-500}" \
  "$@"
