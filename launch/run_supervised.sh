#!/usr/bin/env bash
# Failure-recovery supervisor: restart-on-crash around the auto-resume path.
#
# The reference has no elastic/failure story (SURVEY.md §5.3): its
# pre-elastic torch.distributed.launch hangs or dies on any rank failure,
# and its checkpoints cannot be loaded. Here the trainer auto-resumes from
# the latest checkpoint in --output_dir, so crash recovery is just
# "run it again" — this wrapper does that with bounded retries and
# exponential backoff, which is the honest TPU-pod equivalent of elastic
# training (preemption-and-resume, the standard recovery model on TPUs).
#
# Usage: MAX_RESTARTS=5 ./launch/run_supervised.sh --model resnet50 ...

set -u

MAX_RESTARTS="${MAX_RESTARTS:-10}"
BACKOFF="${BACKOFF_SECONDS:-5}"
MIN_RUNTIME="${MIN_RUNTIME_SECONDS:-10}"

attempt=0
while true; do
  start=$(date +%s)
  python "$(dirname "$0")/../ddp.py" "$@"
  code=$?
  runtime=$(( $(date +%s) - start ))
  if [ "$code" -eq 0 ]; then
    echo "[supervisor] training completed" >&2
    exit 0
  fi
  # exit 2 = argparse/config error; sub-MIN_RUNTIME first failure = broken
  # setup, not a preemption — restarting cannot help either
  if [ "$code" -eq 2 ] || { [ "$attempt" -eq 0 ] && [ "$runtime" -lt "$MIN_RUNTIME" ]; }; then
    echo "[supervisor] non-recoverable failure (exit $code after ${runtime}s); not retrying" >&2
    exit "$code"
  fi
  attempt=$((attempt + 1))
  if [ "$attempt" -gt "$MAX_RESTARTS" ]; then
    echo "[supervisor] giving up after $MAX_RESTARTS restarts (last exit $code)" >&2
    exit "$code"
  fi
  echo "[supervisor] exit $code; restart $attempt/$MAX_RESTARTS in ${BACKOFF}s (auto-resume from latest checkpoint)" >&2
  sleep "$BACKOFF"
  BACKOFF=$((BACKOFF * 2))
  [ "$BACKOFF" -gt 300 ] && BACKOFF=300
done
