#!/usr/bin/env bash
# Per-node SLURM worker (reference: run.slurm.sh, which ran
# torch.distributed.launch with --node_rank=$SLURM_NODEID). Here each node
# runs ONE process that owns all its local chips; rendezvous goes through
# jax.distributed.initialize via the flags below.
set -euo pipefail

exec python ddp.py \
  --coordinator_address "${COORD_ADDR}:${COORD_PORT}" \
  --num_processes "$SLURM_JOB_NUM_NODES" \
  --process_id "$SLURM_NODEID" \
  "$@"
