"""Data pipeline: datasets, sharded sampling, per-host loading."""

from .dataset import (
    Subset,
    ArrayDataset,
    Dataset,
    SyntheticImageDataset,
    SyntheticRegressionDataset,
    SyntheticTokenDataset,
)
from .filestore import MemmapDataset, StoreWriter, materialize, write_store
from .loader import ShardedLoader
from .sampler import epoch_batches, shard_indices

__all__ = [
    "ArrayDataset",
    "Dataset",
    "Subset",
    "MemmapDataset",
    "StoreWriter",
    "SyntheticImageDataset",
    "SyntheticRegressionDataset",
    "SyntheticTokenDataset",
    "ShardedLoader",
    "materialize",
    "shard_indices",
    "epoch_batches",
    "write_store",
]
