"""Data pipeline: datasets, sharded sampling, per-host loading."""

from .dataset import (
    ArrayDataset,
    Dataset,
    SyntheticImageDataset,
    SyntheticRegressionDataset,
    SyntheticTokenDataset,
)
from .loader import ShardedLoader
from .sampler import epoch_batches, shard_indices

__all__ = [
    "ArrayDataset",
    "Dataset",
    "SyntheticImageDataset",
    "SyntheticRegressionDataset",
    "SyntheticTokenDataset",
    "ShardedLoader",
    "shard_indices",
    "epoch_batches",
]
