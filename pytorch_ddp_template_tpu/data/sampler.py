"""Deterministic sharded sampling with epoch reshuffle.

Reproduces the semantics of ``torch.utils.data.DistributedSampler`` as used
by the reference (``/root/reference/ddp.py:137-145`` selection,
``ddp.py:213-214`` per-epoch reshuffle) — SURVEY.md §7 names this a hard
part: disjoint cover of the dataset across shards, deterministic per-epoch
reshuffle, and padding of the tail so every shard sees the same number of
samples (a hard requirement under SPMD: every device must run every step).

Design: a pure function of ``(length, num_shards, shard_id, seed, epoch)``
— no mutable sampler object, no ``set_epoch`` side channel. The epoch is
folded into the permutation seed, which is the JAX-idiomatic spelling of
``sampler.set_epoch(epoch)``.
"""

from __future__ import annotations

import numpy as np


def shard_indices(
    length: int,
    num_shards: int,
    shard_id: int,
    *,
    seed: int = 0,
    epoch: int = 0,
    shuffle: bool = True,
    drop_last: bool = False,
) -> np.ndarray:
    """Return this shard's sample indices for one epoch.

    Guarantees (matching DistributedSampler):
    - all shards together cover every index at least once (when not
      ``drop_last``), disjointly apart from the wrap-around padding;
    - every shard gets exactly the same count;
    - ``epoch`` changes the permutation deterministically;
    - different shards never overlap within the unpadded region.
    """
    if not 0 <= shard_id < num_shards:
        raise ValueError(f"shard_id {shard_id} out of range for {num_shards} shards")
    if length <= 0:
        raise ValueError("empty dataset")

    if shuffle:
        from .. import native

        if native.available():
            # C++ Fisher-Yates keyed on (seed, epoch) — native.cc
            indices = native.permutation(seed, epoch, length)
        else:
            rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
            indices = rng.permutation(length)
    else:
        indices = np.arange(length)

    if drop_last:
        total = (length // num_shards) * num_shards
        indices = indices[:total]
    else:
        total = -(-length // num_shards) * num_shards  # ceil to multiple
        if total > length:  # wrap-around padding, like DistributedSampler
            indices = np.concatenate([indices, indices[: total - length]])

    return indices[shard_id::num_shards]


def shard_validity(length: int, num_shards: int, shard_id: int) -> np.ndarray:
    """Bool array aligned with ``shard_indices(..., drop_last=False)``:
    ``False`` where the entry is wrap-around padding (a duplicate of an
    index another position already covers).

    Invariant with :func:`shard_indices`: entry ``j`` of shard ``s`` sits at
    position ``j * num_shards + s`` of the (permuted, then padded)
    concatenated index array, and padding occupies positions ``>= length``
    regardless of shuffle — so validity is a pure position property, no
    permutation needed. Exactly-once eval coverage (every example weighted
    1.0 across all shards together) builds on this.
    """
    if length <= 0:
        raise ValueError("empty dataset")
    per_shard = -(-length // num_shards)
    return np.arange(per_shard) * num_shards + shard_id < length


def epoch_batches(
    shard: np.ndarray,
    batch_size: int,
    *,
    drop_last: bool = True,
) -> list[np.ndarray]:
    """Chunk a shard's indices into per-step batches of ``batch_size``.

    Under SPMD the global step count must be identical on every host, so the
    ragged tail is dropped by default (every host computes the same number
    of steps from the same shard length).
    """
    n = len(shard)
    n_full = n // batch_size
    batches = [shard[i * batch_size : (i + 1) * batch_size] for i in range(n_full)]
    if not drop_last and n % batch_size:
        batches.append(shard[n_full * batch_size :])
    return batches
