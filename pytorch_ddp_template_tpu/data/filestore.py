"""File-backed datasets: raw memory-mapped array stores.

The reference's data layer is host-RAM only (``/root/reference/
dataset.py:6-17`` pre-materialises tensors; ``ddp.py:148-152`` feeds them
through a ``DataLoader``) — fine for a toy, but the BASELINE ladder's
ImageNet-class rungs need data that outlives RAM. TPU-first design:

- **Storage is raw fixed-shape arrays, memory-mapped.** No TFRecord/proto
  decode on the hot path: the classic TPU input bottleneck is host CPU
  (SURVEY.md §7 hard part (e)), so the host's only per-batch work is a
  threaded row gather (``native/native.cc ddp_gather_rows``) straight out
  of the page cache into the staging buffer. uint8 images ship over PCIe
  4x cheaper than f32; normalisation/augmentation run *on device* inside
  the jitted step (``models/task.py``), where they fuse into the fwd pass.
- **One ``.bin`` per key + ``meta.json``** (dtype/shape/sample count).
  Files are plain C-order arrays — writable from any tool, inspectable
  with ``np.memmap``, shardable by byte ranges for multi-host later.
- **Streaming writer** so ImageNet-scale stores can be materialised chunk
  by chunk without ever holding the dataset in RAM.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Mapping

import numpy as np

META_NAME = "meta.json"
_VERSION = 1


class StoreWriter:
    """Append-only store writer: ``with StoreWriter(dir) as w: w.append(batch)``.

    Schema (dtypes + trailing shapes) is inferred from the first appended
    batch and enforced afterwards; ``meta.json`` is written on close so a
    crashed writer leaves no store that looks complete.
    """

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self._files: dict[str, object] = {}
        self._schema: dict[str, tuple[str, tuple[int, ...]]] = {}
        self._samples = 0
        self._closed = False

    def append(self, batch: Mapping[str, np.ndarray]) -> None:
        if self._closed:
            raise RuntimeError("writer already closed")
        batch = {k: np.asarray(v) for k, v in batch.items()}
        counts = {k: len(v) for k, v in batch.items()}
        if len(set(counts.values())) != 1:
            raise ValueError(f"inconsistent batch sizes: {counts}")
        if not self._schema:
            self._schema = {
                k: (v.dtype.name, tuple(v.shape[1:])) for k, v in batch.items()
            }
            for k in batch:
                self._files[k] = open(self.directory / f"{k}.bin", "wb")
        if set(batch) != set(self._schema):
            raise ValueError(
                f"keys {sorted(batch)} != schema keys {sorted(self._schema)}"
            )
        for k, v in batch.items():
            dtype, shape = self._schema[k]
            if v.dtype.name != dtype or tuple(v.shape[1:]) != shape:
                raise ValueError(
                    f"key {k!r}: got {v.dtype.name}{list(v.shape[1:])}, "
                    f"schema says {dtype}{list(shape)}"
                )
            self._files[k].write(np.ascontiguousarray(v).tobytes())
        self._samples += next(iter(counts.values()))

    def close(self) -> None:
        if self._closed:
            return
        for f in self._files.values():
            f.close()
        meta = {
            "version": _VERSION,
            "samples": self._samples,
            "keys": {
                k: {"dtype": dtype, "shape": list(shape)}
                for k, (dtype, shape) in self._schema.items()
            },
        }
        (self.directory / META_NAME).write_text(json.dumps(meta, indent=2))
        self._closed = True

    def __enter__(self) -> "StoreWriter":
        return self

    def __exit__(self, exc_type, *_):
        if exc_type is None:
            self.close()
        else:  # leave no meta.json behind a failed write
            for f in self._files.values():
                f.close()
            self._closed = True


def write_store(directory: str | Path, arrays: Mapping[str, np.ndarray],
                chunk: int = 4096) -> Path:
    """One-shot convenience: write in-RAM arrays as a store."""
    n = len(next(iter(arrays.values())))
    with StoreWriter(directory) as w:
        for lo in range(0, n, chunk):
            w.append({k: v[lo:lo + chunk] for k, v in arrays.items()})
    return Path(directory)


class MemmapDataset:
    """Dataset over a store directory: zero-copy memmaps + threaded gather.

    Implements the :class:`~.dataset.Dataset` protocol; ``batch(indices)``
    is a row gather from the page cache (native threaded memcpy when the
    host runtime is built), so the loader's prefetch thread overlaps disk
    I/O with device compute exactly as it does for synthetic sources.
    """

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        meta_path = self.directory / META_NAME
        if not meta_path.is_file():
            raise FileNotFoundError(
                f"{meta_path} not found — not a dataset store (incomplete "
                "write? StoreWriter only writes meta.json on clean close)"
            )
        meta = json.loads(meta_path.read_text())
        if meta.get("version") != _VERSION:
            raise ValueError(f"unsupported store version {meta.get('version')}")
        self._samples = int(meta["samples"])
        self.arrays: dict[str, np.memmap] = {}
        for key, spec in meta["keys"].items():
            path = self.directory / f"{key}.bin"
            shape = (self._samples, *spec["shape"])
            expected = int(np.prod(shape)) * np.dtype(spec["dtype"]).itemsize
            actual = path.stat().st_size
            if actual != expected:
                raise ValueError(
                    f"{path}: {actual} bytes, meta implies {expected}"
                )
            self.arrays[key] = np.memmap(path, dtype=spec["dtype"],
                                         mode="r", shape=shape)

    def __len__(self) -> int:
        return self._samples

    def batch(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        from .. import native

        indices = np.asarray(indices)
        if native.available() and len(indices) >= 64:
            return {k: native.gather_rows(v, indices)
                    for k, v in self.arrays.items()}
        return {k: np.asarray(v[indices]) for k, v in self.arrays.items()}


def materialize(dataset, directory: str | Path, *, samples: int | None = None,
                chunk: int = 1024,
                keys: Iterable[str] | None = None) -> Path:
    """Write any :class:`Dataset` out as a store (synthetic → disk)."""
    n = samples if samples is not None else len(dataset)
    n = min(n, len(dataset))
    with StoreWriter(directory) as w:
        for lo in range(0, n, chunk):
            idx = np.arange(lo, min(lo + chunk, n))
            batch = dataset.batch(idx)
            if keys is not None:
                batch = {k: batch[k] for k in keys}
            w.append(batch)
    return Path(directory)
