"""Per-host sharded batch loader producing globally-sharded ``jax.Array``s.

Capability parity with the reference's ``DataLoader`` +
``DistributedSampler`` stack (``/root/reference/ddp.py:137-152``), TPU-first:

- The reference runs one process per GPU; each process's DataLoader yields
  that rank's micro-batch and DDP averages gradients. Here one process per
  *host* loads only the slice of the global batch destined for its local
  devices, then ``jax.make_array_from_process_local_data`` assembles the
  logical global array sharded over the ``data`` mesh axis — no host ever
  materialises the full global batch (essential at pod scale).
- ``pin_memory=True`` (``ddp.py:151``) has no TPU analogue; its purpose —
  overlapping host→device transfer with compute — is covered by the
  background prefetch thread (device transfer happens ahead of the step).
- ``sampler.set_epoch`` (``ddp.py:213-214``) becomes the ``epoch`` argument
  folded into the shuffle seed (see ``sampler.py``).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime.context import DATA_AXIS, SEQ_AXIS
from .dataset import Dataset
from .sampler import epoch_batches, shard_indices, shard_validity


class ShardedLoader:
    """Iterate globally-sharded batches over the ``data`` mesh axis."""

    def __init__(
        self,
        dataset: Dataset,
        mesh: Mesh,
        global_batch_size: int,
        *,
        seed: int = 0,
        shuffle: bool = True,
        drop_last_batch: bool = True,
        prefetch: int = 2,
        accum_steps: int = 1,
        seq_dims: Mapping[str, int] | None = None,
        with_validity: bool = False,
    ):
        self.dataset = dataset
        self.mesh = mesh
        self.global_batch_size = int(global_batch_size)
        self.seed = seed
        self.shuffle = shuffle
        self.with_validity = with_validity
        if with_validity:
            if accum_steps != 1:
                raise ValueError("with_validity does not combine with accum")
            # exactly-once mode must see the ragged tail (padded, not dropped)
            drop_last_batch = False
        self.drop_last_batch = drop_last_batch
        self.prefetch = prefetch

        self._procs = jax.process_count()
        self._proc = jax.process_index()
        if self.global_batch_size % self._procs:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{self._procs} processes"
            )
        data_size = 1
        for name, size in zip(mesh.axis_names, mesh.devices.shape):
            if name == DATA_AXIS:
                data_size = size
        if self.global_batch_size % data_size:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by data-axis "
                f"size {data_size}"
            )
        self._local_batch = self.global_batch_size // self._procs
        # host input-path accounting, cumulative across epochs: gather_s is
        # producer-side work (index gather + H2D assembly, overlapped with
        # compute when prefetching); consumer_wait_s is time the *training
        # loop* actually stalled waiting on this loader — the number that
        # belongs in host-overhead attribution (engine logs it per interval
        # as input_wait_ms); producer_idle_s is time the prefetch thread
        # sat blocked on a full queue (compute-bound regime: large values
        # here with ~zero consumer_wait_s mean the input path has slack).
        # Plain float adds under the GIL: safe enough for telemetry across
        # the producer/consumer threads.
        self.stats: dict[str, float] = {
            "gather_s": 0.0, "consumer_wait_s": 0.0,
            "producer_idle_s": 0.0, "batches": 0.0,
        }
        self.accum_steps = int(accum_steps)
        if self.accum_steps < 1:
            raise ValueError("accum_steps must be >= 1")
        if self.accum_steps > 1 and self._local_batch % self.accum_steps:
            raise ValueError(
                f"per-process batch {self._local_batch} not divisible by "
                f"accum_steps {accum_steps}"
            )
        if self.accum_steps > 1 and (self.global_batch_size // self.accum_steps) % data_size:
            # with accumulation the *micro* dim is the sharded one
            raise ValueError(
                f"micro batch {self.global_batch_size // self.accum_steps} not "
                f"divisible by data-axis size {data_size}"
            )
        # With accumulation, batches are pre-shaped (accum, micro, ...) on the
        # host and sharded over the *micro* dim — the in-jit lax.scan then
        # walks the leading dim with zero resharding (SURVEY.md §7 hard
        # part (b): accumulation inside jit without recompilation).
        self._seq_dims = dict(seq_dims or {})
        self._seq_size = mesh.shape.get(SEQ_AXIS, 1)
        self._shardings: dict[tuple[str, int], NamedSharding] = {}
        # If the seq axis spans processes, each process must hand
        # make_array_from_process_local_data only ITS seq block (the
        # sampler shards the batch dim; nothing else slices seq). Compute
        # this process's contiguous seq-coordinate range once.
        self._seq_block: tuple[int, int] | None = None  # (lo, hi) coords
        if self._seq_size > 1:
            axis_idx = mesh.axis_names.index(SEQ_AXIS)
            local_coords = sorted(
                {
                    idx[axis_idx]
                    for idx, d in np.ndenumerate(mesh.devices)
                    if d.process_index == self._proc
                }
            )
            if len(local_coords) < self._seq_size:
                lo, hi = local_coords[0], local_coords[-1] + 1
                if local_coords != list(range(lo, hi)):
                    raise ValueError(
                        "seq mesh axis spans this process non-contiguously "
                        f"({local_coords}); lay the mesh out so each host's "
                        "seq shards are adjacent"
                    )
                self._seq_block = (lo, hi)

    def _sharding_for(self, key: str, ndim: int) -> NamedSharding:
        """Per-array sharding: batch dim over ``data``; for sequence keys
        (context parallelism) the sequence dim additionally over ``seq``."""
        cached = self._shardings.get((key, ndim))
        if cached is not None:
            return cached
        lead = 1 if self.accum_steps > 1 else 0  # accum dim is unsharded
        dims: list[str | None] = [None] * ndim
        dims[lead] = DATA_AXIS
        if self._seq_size > 1 and key in self._seq_dims:
            dims[lead + self._seq_dims[key]] = SEQ_AXIS
        sharding = NamedSharding(self.mesh, P(*dims))
        self._shardings[(key, ndim)] = sharding
        return sharding

    @property
    def steps_per_epoch(self) -> int:
        per_shard = -(-len(self.dataset) // self._procs)  # ceil (padded cover)
        n = per_shard // self._local_batch
        if not self.drop_last_batch and per_shard % self._local_batch:
            n += 1
        return n

    def _host_batches(
        self, epoch: int
    ) -> list[tuple[np.ndarray, np.ndarray | None]]:
        """Per-step ``(indices, weights)`` for this host. Weights are None
        in train mode; in ``with_validity`` (exactly-once eval) mode each
        batch is padded to the full SPMD shape and weights are 1.0 for real
        examples, 0.0 for shard wrap-around padding and tail padding — so
        summed weights across all hosts and steps equal ``len(dataset)``."""
        shard = shard_indices(
            len(self.dataset),
            self._procs,
            self._proc,
            seed=self.seed,
            epoch=epoch,
            shuffle=self.shuffle,
        )
        if not self.with_validity:
            return [
                (idx, None)
                for idx in epoch_batches(shard, self._local_batch,
                                         drop_last=self.drop_last_batch)
            ]
        valid = shard_validity(len(self.dataset), self._procs, self._proc)
        out = []
        # chunk positions, not indices, so validity stays aligned with the
        # (shuffled) shard entries
        for pos in epoch_batches(np.arange(len(shard)), self._local_batch,
                                 drop_last=False):
            idx = shard[pos]
            w = valid[pos].astype(np.float32)
            short = self._local_batch - len(idx)
            if short:  # ragged tail: pad to the full shape, weight 0
                idx = np.concatenate([idx, np.repeat(idx[:1], short)])
                w = np.concatenate([w, np.zeros(short, np.float32)])
            out.append((idx, w))
        return out

    def _assemble(self, local: Mapping[str, np.ndarray]) -> dict[str, jax.Array]:
        out = {}
        for k, v in local.items():
            if self.accum_steps > 1:
                v = v.reshape(self.accum_steps, -1, *v.shape[1:])
            if self._seq_block is not None and k in self._seq_dims:
                dim = self._seq_dims[k] + (1 if self.accum_steps > 1 else 0)
                block = v.shape[dim] // self._seq_size
                lo, hi = self._seq_block
                v = np.take(v, np.arange(lo * block, hi * block), axis=dim)
            out[k] = jax.make_array_from_process_local_data(
                self._sharding_for(k, v.ndim), v
            )
        return out

    def epoch(self, epoch: int, start_batch: int = 0) -> Iterator[dict[str, jax.Array]]:
        """Yield one epoch of globally-sharded batches.

        With ``prefetch > 0``, a daemon thread gathers + device-puts batches
        ahead of consumption so host I/O overlaps device compute.
        ``start_batch`` (mid-epoch resume) drops the first N index batches
        *before* any data is generated or transferred — skipping by
        iterating would pay full host gather + H2D cost per skipped batch.
        """
        batches = self._host_batches(epoch)[start_batch:]

        def _gather(idx: np.ndarray, w: np.ndarray | None) -> dict:
            t0 = time.perf_counter()
            local = dict(self.dataset.batch(idx))
            if w is not None:
                local["__weight__"] = w
            out = self._assemble(local)
            self.stats["gather_s"] += time.perf_counter() - t0
            self.stats["batches"] += 1
            return out

        if self.prefetch <= 0:
            for idx, w in batches:
                batch = _gather(idx, w)
                # no prefetch thread: the gather itself is the consumer stall
                self.stats["consumer_wait_s"] = self.stats["gather_s"]
                yield batch
            return

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        _SENTINEL = object()
        stop = threading.Event()

        def _put(item) -> bool:
            # bounded put that aborts when the consumer is gone, so an
            # abandoned generator (early break, partial iteration) never
            # leaves this thread pinned on a full queue
            t0 = time.perf_counter()
            try:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.05)
                        return True
                    except queue.Full:
                        continue
                return False
            finally:
                # time blocked on a full queue (the fast path's put is
                # ~instant, so the accumulated value reads as idle time)
                self.stats["producer_idle_s"] += time.perf_counter() - t0

        def producer() -> None:
            try:
                for idx, w in batches:
                    if stop.is_set() or not _put(_gather(idx, w)):
                        return
            except Exception as exc:  # noqa: BLE001 - surface in consumer
                _put(exc)
            finally:
                _put(_SENTINEL)

        thread = threading.Thread(target=producer, daemon=True, name="loader-prefetch")
        thread.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                self.stats["consumer_wait_s"] += time.perf_counter() - t0
                if item is _SENTINEL:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            while not q.empty():  # drop pinned device batches
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=5)
