"""Dataset protocol and synthetic sources.

Capability parity with the reference's ``FooDataset``
(``/root/reference/dataset.py:6-17``): a map-style dataset of
pre-materialised random regression pairs. TPU-first difference: datasets
here support *vectorised batch fetch* (``batch(indices)``) so the host can
assemble a whole per-process batch in one numpy gather instead of a Python
loop over ``__getitem__`` — host CPU feeding is the classic TPU bottleneck
(SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

from typing import Mapping, Protocol, runtime_checkable

import numpy as np


@runtime_checkable
class Dataset(Protocol):
    """Map-style dataset: ``len()`` + vectorised ``batch(indices)``.

    ``batch`` returns a pytree (typically a dict) of numpy arrays whose
    leading dimension is ``len(indices)``.
    """

    def __len__(self) -> int: ...

    def batch(self, indices: np.ndarray) -> Mapping[str, np.ndarray]: ...


class ArrayDataset:
    """Wrap pre-materialised arrays (leading dim = sample count)."""

    def __init__(self, **arrays: np.ndarray):
        if not arrays:
            raise ValueError("ArrayDataset needs at least one array")
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"inconsistent sample counts: {lengths}")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        self._len = next(iter(lengths.values()))

    def __len__(self) -> int:
        return self._len

    def batch(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        from .. import native

        if native.available() and len(indices) >= 64:
            # threaded memcpy gather (native.cc ddp_gather_rows); numpy
            # fancy indexing is single-threaded
            return {k: native.gather_rows(v, indices)
                    for k, v in self.arrays.items()}
        return {k: v[indices] for k, v in self.arrays.items()}


class Subset:
    """Contiguous-range view over any :class:`Dataset` (zero copy).

    The train/eval split for file-backed stores: hold out the tail rows
    without duplicating bytes on disk or in RAM.
    """

    def __init__(self, base: "Dataset", start: int, stop: int):
        if not (0 <= start <= stop <= len(base)):
            raise ValueError(
                f"subset [{start}, {stop}) out of range for {len(base)} samples"
            )
        self.base = base
        self.start = start
        self._len = stop - start

    def __len__(self) -> int:
        return self._len

    def batch(self, indices: np.ndarray) -> Mapping[str, np.ndarray]:
        indices = np.asarray(indices)
        if len(indices) and (indices.min() < -self._len
                             or indices.max() >= self._len):
            raise IndexError(f"index out of range [0, {self._len})")
        return self.base.batch(self.start + indices % self._len)


class SyntheticRegressionDataset(ArrayDataset):
    """The ``FooDataset`` equivalent (``dataset.py:6-17``): ``samples``
    standard-normal pairs ``x ∈ R^{in_dim}``, ``y ∈ R^{out_dim}``.

    Unlike the reference (fresh ``torch.randn`` every construction), data is
    deterministic in ``seed`` so loss curves are reproducible across runs
    and hosts.
    """

    def __init__(self, samples: int = 100_000, in_dim: int = 10, out_dim: int = 5,
                 seed: int = 0):
        rng = np.random.default_rng(seed)
        super().__init__(
            x=rng.standard_normal((samples, in_dim), dtype=np.float32),
            y=rng.standard_normal((samples, out_dim), dtype=np.float32),
        )


class SyntheticImageDataset:
    """Synthetic labelled images for the vision config ladder (BASELINE.md):
    NHWC uint8 images + int32 class labels, deterministic in ``seed``.

    *Lazy*: images are generated per-batch from counter-based (Philox) RNG
    streams keyed on ``(seed, sample_index)`` — an ImageNet-shaped dataset at
    the default 100k samples would otherwise pre-materialise ~15 GB of host
    RAM. Generation runs inside the loader's prefetch thread, overlapped
    with device compute.
    """

    def __init__(self, samples: int = 10_000, image_size: int = 224, channels: int = 3,
                 num_classes: int = 1000, seed: int = 0):
        self._samples = int(samples)
        self.image_size = int(image_size)
        self.channels = int(channels)
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        # labels are tiny — materialise once for O(1) batch gather
        rng = np.random.default_rng(np.random.Philox(key=[self.seed, 0]))
        self._labels = rng.integers(0, num_classes, (self._samples,), dtype=np.int32)

    def __len__(self) -> int:
        return self._samples

    def batch(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        from .. import native

        indices = np.asarray(indices)
        shape = (self.image_size, self.image_size, self.channels)
        if native.available():
            # threaded C++ per-sample streams keyed (seed, index) —
            # native.cc ddp_synth_u8; orders of magnitude faster than the
            # per-sample numpy generators below on ImageNet-sized samples
            images = native.synth_u8(
                self.seed, indices, int(np.prod(shape))
            ).reshape(len(indices), *shape)
        else:
            images = np.empty((len(indices), *shape), dtype=np.uint8)
            for row, i in enumerate(indices):
                # seed and index in separate Philox key words: additive
                # mixing would alias sample i of seed s with sample i-k of
                # seed s+k, making a different-seed eval split overlap the
                # train set
                gen = np.random.Generator(
                    np.random.Philox(key=[self.seed, 1 + int(i)])
                )
                images[row] = gen.integers(0, 256, shape, dtype=np.uint8)
        return {"image": images, "label": self._labels[indices]}


class SyntheticTokenDataset(ArrayDataset):
    """Synthetic token sequences for the language configs (BERT MLM ladder):
    int32 token ids in ``[0, vocab)``, deterministic in ``seed``.

    ``padded=True`` emits variable-length sequences (uniform in
    ``[seq_len//2, seq_len]``) padded with token 0 plus an int32
    ``attention_mask`` (1 = real token) — the padded-batch shape real
    tokenised corpora produce, exercised by the long-context rungs."""

    def __init__(self, samples: int = 10_000, seq_len: int = 128, vocab: int = 30_522,
                 seed: int = 0, padded: bool = False):
        rng = np.random.default_rng(seed)
        ids = rng.integers(0, vocab, (samples, seq_len), dtype=np.int32)
        arrays = {"input_ids": ids}
        self.padded = padded
        if padded:
            lengths = rng.integers(max(1, seq_len // 2), seq_len + 1,
                                   (samples,))
            mask = (np.arange(seq_len)[None, :] < lengths[:, None])
            arrays["input_ids"] = ids * mask
            arrays["attention_mask"] = mask.astype(np.int32)
        super().__init__(**arrays)
        self.vocab = vocab
