"""Blockwise LM-head cross-entropy: the flash-attention trick applied to
the other memory hog of causal-LM training.

A dense head materialises ``(B, T, V)`` logits AND their log-softmax —
at GPT-2 vocab (50k) and seq 4096 that is ~1.6 GB f32 per example-batch,
dominating long-context memory (the reference has no LM at all,
SURVEY.md §2a-10; this bounds OUR gpt-long rung). Here the vocab axis is
processed in blocks with an online logsumexp — peak activation memory is
``O(B*T*block)`` — and the backward recomputes each block's logits from
the saved ``(B, T)`` logsumexp, exactly like the flash backward
recomputes attention logits from the saved row statistics.

Forward per vocab block ``[v0, v1)``:
    logits_b = hidden @ table[v0:v1].T          (f32 on the MXU)
    m, l     = online max / sum-exp update      (running logsumexp)
    label    += logits_b[target] when target in the block
    best     = running argmax (for the accuracy metric)
    token_logp = label - (m + log l)

Backward (custom_vjp, recompute per block):
    p_b      = exp(logits_b - lse)
    dlogits  = g * (onehot_b - p_b)
    dhidden += dlogits @ table[v0:v1];  dtable[v0:v1] = dlogits^T @ hidden
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _num_blocks(vocab: int, block: int) -> int:
    return -(-vocab // block)


def _block_logits(hidden, table, bias, step, *, block: int, vocab: int):
    """f32 logits for vocab block ``step`` with padded rows at -inf.

    ``table``/``bias`` are pre-padded to ``n_blocks * block`` rows; padded
    logits are masked so they contribute nothing to logsumexp or argmax.
    """
    tb = lax.dynamic_slice_in_dim(table, step * block, block, axis=0)
    logits = lax.dot_general(
        hidden.astype(jnp.float32), tb.astype(jnp.float32),
        (((hidden.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (..., block)
    logits = logits + lax.dynamic_slice_in_dim(
        bias, step * block, block, axis=0).astype(jnp.float32)
    v_ids = step * block + lax.iota(jnp.int32, block)
    return jnp.where(v_ids < vocab, logits, NEG_INF), tb


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def blockwise_lm_head(hidden, table, bias, targets, block, vocab):
    out, _ = _fwd(hidden, table, bias, targets, block, vocab)
    return out


def _fwd(hidden, table, bias, targets, block, vocab):
    n = _num_blocks(vocab, block)
    shape = targets.shape  # (...,) token positions

    def body(carry, step):
        m, l, label, best_v, best_i = carry
        logits, _ = _block_logits(hidden, table, bias, step,
                                  block=block, vocab=vocab)
        # online logsumexp
        bm = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, bm)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1)
        # the target token's logit, when it falls in this block
        in_blk = (targets >= step * block) & (targets < step * block + block)
        idx = jnp.clip(targets - step * block, 0, block - 1)
        val = jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0]
        label = jnp.where(in_blk, val, label)
        # running argmax for the accuracy metric
        bi = jnp.argmax(logits, axis=-1)
        bv = jnp.take_along_axis(logits, bi[..., None], axis=-1)[..., 0]
        take = bv > best_v
        best_v = jnp.where(take, bv, best_v)
        best_i = jnp.where(take, step * block + bi, best_i)
        return (m_new, l, label, best_v, best_i), None

    init = (
        jnp.full(shape, NEG_INF, jnp.float32),  # m
        jnp.zeros(shape, jnp.float32),          # l
        jnp.zeros(shape, jnp.float32),          # label logit
        jnp.full(shape, NEG_INF, jnp.float32),  # best value
        jnp.zeros(shape, jnp.int32),            # best index
    )
    (m, l, label, _, best_i), _ = lax.scan(body, init, jnp.arange(n))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    token_logp = label - lse
    return (token_logp, best_i), (hidden, table, bias, targets, lse)


def _fwd_vjp(hidden, table, bias, targets, block, vocab):
    out, res = _fwd(hidden, table, bias, targets, block, vocab)
    return out, res


def _bwd(block, vocab, res, cotangents):
    g, _ = cotangents  # argmax is int: its cotangent is symbolic-zero
    hidden, table, bias, targets, lse = res
    n = _num_blocks(vocab, block)
    gf = g.astype(jnp.float32)

    def body(dh, step):
        logits, tb = _block_logits(hidden, table, bias, step,
                                   block=block, vocab=vocab)
        p = jnp.exp(logits - lse[..., None])                 # (..., block)
        in_blk = (targets >= step * block) & (targets < step * block + block)
        idx = jnp.clip(targets - step * block, 0, block - 1)
        onehot = (jax.nn.one_hot(idx, block, dtype=jnp.float32)
                  * in_blk[..., None].astype(jnp.float32))
        dlogits = gf[..., None] * (onehot - p)
        dh = dh + lax.dot_general(
            dlogits, tb.astype(jnp.float32),
            (((dlogits.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        batch_axes = tuple(range(dlogits.ndim - 1))
        dtb = lax.dot_general(
            dlogits, hidden.astype(jnp.float32),
            (((batch_axes), (batch_axes)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block, E)
        dbias_b = jnp.sum(dlogits, axis=batch_axes)          # (block,)
        return dh, (dtb, dbias_b)

    dh0 = jnp.zeros(hidden.shape, jnp.float32)
    dh, (dtbs, dbs) = lax.scan(body, dh0, jnp.arange(n))
    dtable = dtbs.reshape(n * block, -1)
    dbias = dbs.reshape(n * block)
    return (dh.astype(hidden.dtype), dtable.astype(table.dtype),
            dbias.astype(bias.dtype), None)


blockwise_lm_head.defvjp(_fwd_vjp, _bwd)


def lm_head_loss(hidden, table, targets, *, bias=None, block: int = 8192):
    """``(token_logp, argmax)`` of a tied LM head, never materialising
    the full ``(..., V)`` logits.

    Args:
      hidden: ``(..., E)`` final hidden states (any float dtype; logits
        accumulate in f32 on the MXU).
      table: ``(V, E)`` embedding/output table.
      targets: ``(...)`` int target token ids.
      bias: optional ``(V,)`` output bias (BERT-style MLM head).
      block: vocab tile width; peak memory is ``O(batch * block)``.
    """
    vocab, _ = table.shape
    block = min(block, vocab)
    n = _num_blocks(vocab, block)
    pad = n * block - vocab
    if bias is None:
        # a zeros constant: its cotangent is dead and XLA folds the add
        bias = jnp.zeros((vocab,), jnp.float32)
    if pad:
        table = jnp.pad(table, ((0, pad), (0, 0)))
        bias = jnp.pad(bias, (0, pad))
    return blockwise_lm_head(hidden, table, bias,
                             targets.astype(jnp.int32), block, vocab)
