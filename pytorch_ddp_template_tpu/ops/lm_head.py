"""Blockwise LM-head cross-entropy: the flash-attention trick applied to
the other memory hog of causal-LM training.

A dense head materialises ``(B, T, V)`` logits AND their log-softmax —
at GPT-2 vocab (50k) and seq 4096 that is ~1.6 GB f32 per example-batch,
dominating long-context memory (the reference has no LM at all,
SURVEY.md §2a-10; this bounds OUR gpt-long rung). Here the vocab axis is
processed in blocks with an online logsumexp — peak activation memory is
``O(B*T*block)`` — and the backward recomputes each block's logits from
the saved ``(B, T)`` logsumexp, exactly like the flash backward
recomputes attention logits from the saved row statistics.

Forward per vocab block ``[v0, v1)``:
    logits_b = hidden @ table[v0:v1].T          (f32 on the MXU)
    m, l     = online max / sum-exp update      (running logsumexp)
    label    += logits_b[target] when target in the block
    best     = running argmax (for the accuracy metric)
    token_logp = label - (m + log l)

Backward (custom_vjp, recompute per block):
    p_b      = exp(logits_b - lse)
    dlogits  = g * (onehot_b - p_b)
    dhidden += dlogits @ table[v0:v1];  dtable[v0:v1] = dlogits^T @ hidden
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _num_blocks(vocab: int, block: int) -> int:
    return -(-vocab // block)


def _block_logits(hidden, table, bias, step, *, block: int, vocab: int,
                  offset=0):
    """f32 logits for vocab block ``step`` with padded rows at -inf.

    ``table``/``bias`` are pre-padded to ``n_blocks * block`` rows; padded
    logits are masked so they contribute nothing to logsumexp or argmax.
    ``offset`` is the absolute vocab id of ``table``'s row 0 — 0 for the
    single-table path, ``shard * shard_rows`` for the TP ring head whose
    local table is one ``model``-axis shard of the padded global table.
    """
    tb = lax.dynamic_slice_in_dim(table, step * block, block, axis=0)
    logits = lax.dot_general(
        hidden.astype(jnp.float32), tb.astype(jnp.float32),
        (((hidden.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (..., block)
    logits = logits + lax.dynamic_slice_in_dim(
        bias, step * block, block, axis=0).astype(jnp.float32)
    v_ids = offset + step * block + lax.iota(jnp.int32, block)
    return jnp.where(v_ids < vocab, logits, NEG_INF), tb


def _argmax_step(best_v, best_i, logits, v0):
    """One running-argmax update for a logits block whose absolute vocab
    ids are ``[v0, v0 + logits.shape[-1])`` — the greedy-decode step of
    the online bundle, standalone so the serving engine can drive it
    without the loss machinery (:func:`greedy_decode`).

    Ties break toward the LOWEST absolute id regardless of block visit
    order (the visit-order invariant): the single-table scan, the TP
    ring head (shards visited in ring order) and the serving decode all
    pick identical predictions. Pinned by direct unit test.
    """
    bi = jnp.argmax(logits, axis=-1)
    bv = jnp.take_along_axis(logits, bi[..., None], axis=-1)[..., 0]
    cand = v0 + bi
    take = (bv > best_v) | ((bv == best_v) & (cand < best_i))
    return jnp.where(take, bv, best_v), jnp.where(take, cand, best_i)


def _online_step(carry, logits, v0, targets, block: int):
    """One online-logsumexp/label/argmax update for a logits block whose
    absolute vocab ids are ``[v0, v0 + block)``.

    Shared between the single-table scan (``v0 = step * block``) and the
    TP ring head (``v0 = shard_offset + step * block``, ops visited in
    ring order). The argmax leg is :func:`_argmax_step` (extracted —
    the serving engine's greedy decode drives it directly).
    """
    m, l, label, best_v, best_i = carry
    # online logsumexp
    bm = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, bm)
    l = l * jnp.exp(m - m_new) + jnp.sum(
        jnp.exp(logits - m_new[..., None]), axis=-1)
    # the target token's logit, when it falls in this block
    in_blk = (targets >= v0) & (targets < v0 + block)
    idx = jnp.clip(targets - v0, 0, block - 1)
    val = jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0]
    label = jnp.where(in_blk, val, label)
    best_v, best_i = _argmax_step(best_v, best_i, logits, v0)
    return m_new, l, label, best_v, best_i


def _online_init(shape):
    return (
        jnp.full(shape, NEG_INF, jnp.float32),  # m
        jnp.zeros(shape, jnp.float32),          # l
        jnp.zeros(shape, jnp.float32),          # label logit
        jnp.full(shape, NEG_INF, jnp.float32),  # best value
        jnp.zeros(shape, jnp.int32),            # best index
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def blockwise_lm_head(hidden, table, bias, targets, block, vocab):
    out, _ = _fwd(hidden, table, bias, targets, block, vocab)
    return out


def _fwd(hidden, table, bias, targets, block, vocab):
    n = _num_blocks(vocab, block)
    shape = targets.shape  # (...,) token positions

    def body(carry, step):
        logits, _ = _block_logits(hidden, table, bias, step,
                                  block=block, vocab=vocab)
        return _online_step(carry, logits, step * block, targets, block), None

    (m, l, label, _, best_i), _ = lax.scan(body, _online_init(shape),
                                           jnp.arange(n))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    token_logp = label - lse
    return (token_logp, best_i), (hidden, table, bias, targets, lse)


def _fwd_vjp(hidden, table, bias, targets, block, vocab):
    out, res = _fwd(hidden, table, bias, targets, block, vocab)
    return out, res


def _bwd(block, vocab, res, cotangents):
    g, _ = cotangents  # argmax is int: its cotangent is symbolic-zero
    hidden, table, bias, targets, lse = res
    n = _num_blocks(vocab, block)
    gf = g.astype(jnp.float32)

    def body(dh, step):
        logits, tb = _block_logits(hidden, table, bias, step,
                                   block=block, vocab=vocab)
        p = jnp.exp(logits - lse[..., None])                 # (..., block)
        in_blk = (targets >= step * block) & (targets < step * block + block)
        idx = jnp.clip(targets - step * block, 0, block - 1)
        onehot = (jax.nn.one_hot(idx, block, dtype=jnp.float32)
                  * in_blk[..., None].astype(jnp.float32))
        dlogits = gf[..., None] * (onehot - p)
        dh = dh + lax.dot_general(
            dlogits, tb.astype(jnp.float32),
            (((dlogits.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        batch_axes = tuple(range(dlogits.ndim - 1))
        dtb = lax.dot_general(
            dlogits, hidden.astype(jnp.float32),
            (((batch_axes), (batch_axes)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block, E)
        dbias_b = jnp.sum(dlogits, axis=batch_axes)          # (block,)
        return dh, (dtb, dbias_b)

    dh0 = jnp.zeros(hidden.shape, jnp.float32)
    dh, (dtbs, dbs) = lax.scan(body, dh0, jnp.arange(n))
    dtable = dtbs.reshape(n * block, -1)
    dbias = dbs.reshape(n * block)
    return (dh.astype(hidden.dtype), dtable.astype(table.dtype),
            dbias.astype(bias.dtype), None)


blockwise_lm_head.defvjp(_fwd_vjp, _bwd)


def lm_head_loss(hidden, table, targets, *, bias=None, block: int = 8192):
    """``(token_logp, argmax)`` of a tied LM head, never materialising
    the full ``(..., V)`` logits.

    Args:
      hidden: ``(..., E)`` final hidden states (any float dtype; logits
        accumulate in f32 on the MXU).
      table: ``(V, E)`` embedding/output table.
      targets: ``(...)`` int target token ids.
      bias: optional ``(V,)`` output bias (BERT-style MLM head).
      block: vocab tile width; peak memory is ``O(batch * block)``.
    """
    vocab, _ = table.shape
    block = min(block, vocab)
    n = _num_blocks(vocab, block)
    pad = n * block - vocab
    if bias is None:
        # a zeros constant: its cotangent is dead and XLA folds the add
        bias = jnp.zeros((vocab,), jnp.float32)
    if pad:
        table = jnp.pad(table, ((0, pad), (0, 0)))
        bias = jnp.pad(bias, (0, pad))
    return blockwise_lm_head(hidden, table, bias,
                             targets.astype(jnp.int32), block, vocab)


def greedy_decode(hidden, table, *, bias=None, block: int = 8192,
                  vocab: int | None = None):
    """Blockwise greedy decode: ``argmax_v(hidden @ table.T + bias)``
    without ever materialising the ``(..., V)`` logits.

    The greedy-decode step of the online-argmax bundle, standalone
    (r19): the serving engine's per-token sampler. Until now the
    running argmax was only exercised through :func:`lm_head_loss` /
    :func:`tp_lm_head_loss` as the accuracy metric; here it IS the
    output. Peak memory is ``O(batch * block)`` — at serving batch
    sizes the logits row never exists, which is what lets the decode
    step share HBM with the paged KV cache.

    Args:
      hidden: ``(..., E)`` final hidden states (any float dtype; the
        per-block logits accumulate in f32 on the MXU).
      table: ``(V, E)`` tied embedding/output table.
      bias: optional ``(V,)`` output bias.
      block: vocab tile width.
      vocab: true vocab size when ``table`` carries pad rows beyond it
        (the TP serving engine pads the tied table to ring granularity
        at placement); rows ``>= vocab`` are masked out of the argmax.

    Returns ``(...,)`` int32 argmax token ids. Ties break toward the
    lowest vocab id regardless of block visit order (the
    :func:`_argmax_step` invariant — pinned by unit test).
    """
    rows, _ = table.shape
    vocab = rows if vocab is None else min(vocab, rows)
    block = min(block, rows)
    n = _num_blocks(rows, block)
    pad = n * block - rows
    if bias is None:
        bias = jnp.zeros((rows,), jnp.float32)
    if pad:
        table = jnp.pad(table, ((0, pad), (0, 0)))
        bias = jnp.pad(bias, (0, pad))
    shape = hidden.shape[:-1]

    def body(carry, step):
        logits, _ = _block_logits(hidden, table, bias, step,
                                  block=block, vocab=vocab)
        return _argmax_step(*carry, logits, step * block), None

    init = (jnp.full(shape, NEG_INF, jnp.float32),
            jnp.zeros(shape, jnp.int32))
    (_, best_i), _ = lax.scan(body, init, jnp.arange(n))
    return best_i


#: token-sampling policies :func:`sample_tokens` serves. v1 is greedy
#: only — the serving engine's lossless speculative-decode guarantee is
#: stated (and pinned) against greedy argmax, and every policy added
#: here must either preserve it or be refused by the spec path.
SAMPLING_POLICIES = ("greedy",)


def sample_tokens(hidden, table, *, policy: str = "greedy", bias=None,
                  block: int = 8192, vocab: int | None = None):
    """The serving engine's sampling seam over the online-argmax bundle.

    One dispatcher between "final hidden states" and "next token ids",
    so temperature/top-k/top-p can later ride the same blockwise pass
    (a Gumbel-max fold is one more ``_argmax_step``-shaped reduction)
    without touching the engine again. ``policy="greedy"`` is
    BIT-IDENTICAL to :func:`greedy_decode` — the engine refactor onto
    this seam is a pinned no-op. Unknown policies are refused here, at
    trace time, with the supported list named.
    """
    if policy not in SAMPLING_POLICIES:
        raise ValueError(
            f"unknown sampling policy {policy!r}; v1 serves "
            f"{SAMPLING_POLICIES} (temperature/top-k land as a blockwise "
            "Gumbel-max fold on this same seam)")
    return greedy_decode(hidden, table, bias=bias, block=block, vocab=vocab)


# -- TP ring head (--tp_overlap): model-sharded vocab, rotating stats ------
#
# With the vocab table sharded over the ``model`` mesh axis (the
# parallel/sharding.py "vocab" rule), the GSPMD-default blockwise head
# either all-gathers the table or psums per-block partial stats — one
# blocking collective per vocab block, serialised against the logit dots.
# Here each (hidden-chunk, targets, online-stats) bundle rotates around
# the model ring (parallel/ring.py machinery, rotate-at-start): every
# device folds its LOCAL vocab shard's blockwise logits into the visiting
# bundle's logsumexp/label/argmax state, and after n hops the chunk is
# home with complete stats — the (B, T, V) logits tensor never exists on
# any device, and the single-hop ppermute (whose operands are loop-carried
# only) hides under each step's logit dots. The backward rotates
# (hidden, targets, gy, lse, dhidden-accumulator): each device drains its
# dtable/dbias shard contribution as the chunks pass, and dhidden arrives
# home fully accumulated — the transposed gather/psum pipelined the same
# way (the hand-written-vjp discipline of parallel/overlap.py).


def _tp_pad_seq(x, n, axis=1):
    t = x.shape[axis]
    pad = (-t) % n
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    return x, t


def tp_head_geometry(vocab: int, n: int, block: int = 8192):
    """``(block, shard_rows, pad_v)`` for a vocab table sharded over an
    ``n``-way model ring: the local shard is a whole number of blocks,
    and the global table is padded to ``n * shard_rows`` rows. ONE
    source of truth shared by :func:`tp_lm_head_loss`,
    :func:`tp_greedy_decode`, and the serving engine (which pads the
    tied table once at placement so the decode program's local shards
    line up with this geometry)."""
    block = min(block, -(-vocab // n))
    vs = _num_blocks(-(-vocab // n), block) * block
    return block, vs, n * vs - vocab


def _tp_head_fwd_local(h, tgt, tab, bs, block, vocab):
    """Per-shard forward: rotate the (hidden-chunk, targets, online-stats)
    bundle around the model ring; each visit folds the LOCAL vocab
    shard's blockwise logits into the visiting chunk's state. After n
    hops the chunk is home with complete stats. Returns
    ``(token_logp, argmax, lse)`` for the home chunk."""
    from ..parallel.ring import axis_size, ring_perm
    from ..runtime.context import MODEL_AXIS

    n = axis_size(MODEL_AXIS)
    perm = ring_perm(n)
    vs = tab.shape[0]
    nb = vs // block
    off = lax.axis_index(MODEL_AXIS) * vs

    def ring_step(carry, _):
        # rotate FIRST: the bundle is loop-carried state only — the hop
        # is compute-independent of this step's logit dots
        h_c, tgt_c, stats = lax.ppermute(carry, MODEL_AXIS, perm)

        def vblock(st, s):
            logits, _ = _block_logits(h_c, tab, bs, s, block=block,
                                      vocab=vocab, offset=off)
            return _online_step(st, logits, off + s * block, tgt_c,
                                block), None

        stats, _ = lax.scan(vblock, stats, jnp.arange(nb))
        return (h_c, tgt_c, stats), None

    init = (h, tgt, _online_init(tgt.shape))
    (_, _, (m, l, label, _, best_i)), _ = lax.scan(
        ring_step, init, jnp.arange(n))
    # n rotations = full circle: the stats are for OUR chunk again
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return label - lse, best_i, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _tp_head_local(h, tgt, tab, bs, block, vocab):
    logp, best, _ = _tp_head_fwd_local(h, tgt, tab, bs, block, vocab)
    return logp, best


def _tp_head_local_fwd(h, tgt, tab, bs, block, vocab):
    logp, best, lse = _tp_head_fwd_local(h, tgt, tab, bs, block, vocab)
    return (logp, best), (h, tgt, tab, bs, lse)


def _tp_head_local_bwd(block, vocab, res, cotangents):
    """Per-shard backward: rotate (hidden, targets, gy, lse, dhidden-
    accumulator); each device recomputes its vocab shard's logits
    blockwise for the visiting chunk (the flash-style recompute from the
    saved lse), drains its dtable/dbias contribution locally as the
    chunks pass, and the dhidden accumulator arrives home complete.
    dtable/dbias leave per-shard; shard_map's transpose sums them over
    ``data``. Every ppermute operand is loop-carried — both transposed
    collectives hide under the recompute dots."""
    from ..parallel.ring import axis_size, ring_perm
    from ..runtime.context import MODEL_AXIS

    g, _ = cotangents  # argmax is int: its cotangent is symbolic-zero
    h, tgt, tab, bs, lse = res
    n = axis_size(MODEL_AXIS)
    perm = ring_perm(n)
    vs = tab.shape[0]
    nb = vs // block
    off = lax.axis_index(MODEL_AXIS) * vs
    gyf = g.astype(jnp.float32)

    def ring_step(carry, _):
        bundle, dtab, dbias = carry
        h_c, tgt_c, gy_c, lse_c, dh_c = lax.ppermute(
            bundle, MODEL_AXIS, perm)

        def vblock(dh_c, s):
            logits, tb = _block_logits(h_c, tab, bs, s, block=block,
                                       vocab=vocab, offset=off)
            p = jnp.exp(logits - lse_c[..., None])
            v0 = off + s * block
            in_blk = (tgt_c >= v0) & (tgt_c < v0 + block)
            idx = jnp.clip(tgt_c - v0, 0, block - 1)
            onehot = (jax.nn.one_hot(idx, block, dtype=jnp.float32)
                      * in_blk[..., None].astype(jnp.float32))
            dlogits = gy_c[..., None] * (onehot - p)
            dh_c = dh_c + lax.dot_general(
                dlogits, tb.astype(jnp.float32),
                (((dlogits.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            batch_axes = tuple(range(dlogits.ndim - 1))
            dtb = lax.dot_general(
                dlogits, h_c.astype(jnp.float32),
                ((batch_axes, batch_axes), ((), ())),
                preferred_element_type=jnp.float32)
            return dh_c, (dtb, jnp.sum(dlogits, axis=batch_axes))

        dh_c, (dtbs, dbbs) = lax.scan(vblock, dh_c, jnp.arange(nb))
        # this shard's dtable rows accumulate as the chunks pass; the
        # per-block stacks reshape straight into the local layout
        dtab = dtab + dtbs.reshape(vs, -1)
        dbias = dbias + dbbs.reshape(vs)
        return ((h_c, tgt_c, gy_c, lse_c, dh_c), dtab, dbias), None

    dh0 = jnp.zeros(h.shape, jnp.float32)
    dtab0 = jnp.zeros(tab.shape, jnp.float32)
    dbias0 = jnp.zeros(bs.shape, jnp.float32)
    ((_, _, _, _, dh), dtab, dbias), _ = lax.scan(
        ring_step, ((h, tgt, gyf, lse, dh0), dtab0, dbias0),
        jnp.arange(n))
    return (dh.astype(h.dtype), None, dtab.astype(tab.dtype),
            dbias.astype(bs.dtype))


_tp_head_local.defvjp(_tp_head_local_fwd, _tp_head_local_bwd)


def tp_lm_head_loss(hidden, table, targets, mesh, *, bias=None,
                    block: int = 8192):
    """``(token_logp, argmax)`` of a ``model``-sharded tied LM head whose
    blockwise loss accumulates per-shard partial logits/logsumexp around
    the ring — :func:`lm_head_loss` decomposed for ``--tp_overlap``.

    Args match :func:`lm_head_loss` plus ``mesh`` (must carry a ``model``
    axis; see ``parallel/collective_matmul.validate_tp_mesh``). ``hidden``
    may arrive seq-sharded over ``model`` (the decomposed stack's output
    layout) — the region specs consume it in place. Sequence length and
    vocab are padded internally to ring granularity; outputs are sliced
    back, and padded positions contribute exactly-zero gradients (the
    pad/slice transposes zero their cotangents).

    The custom_vjp sits on the per-shard function with ``shard_map``
    outside (the ``parallel/collective_matmul.py`` structure note): the
    hand-written ring backward is pinned per shard, and shard_map's
    transpose supplies the cross-``data`` sums for dtable/dbias.
    """
    from ..parallel.collective_matmul import _batch_axis, validate_tp_mesh
    from ..parallel.shard_map_compat import shard_map
    from ..runtime.context import MODEL_AXIS
    from jax.sharding import PartitionSpec as P

    validate_tp_mesh(mesh)
    n = mesh.shape[MODEL_AXIS]
    ba = _batch_axis(mesh)
    vocab, _ = table.shape
    # local shard = a whole number of blocks; pad the global table to
    # n * vs rows (absolute-id masking keeps padded rows at -inf)
    block, vs, pad_v = tp_head_geometry(vocab, n, block)
    if bias is None:
        bias = jnp.zeros((vocab,), jnp.float32)
    if pad_v:
        table = jnp.pad(table, ((0, pad_v), (0, 0)))
        bias = jnp.pad(bias, (0, pad_v))

    hidden_p, t_real = _tp_pad_seq(hidden, n)
    targets_p, _ = _tp_pad_seq(targets.astype(jnp.int32), n)

    h_spec = P(ba, MODEL_AXIS, None)
    t_spec = P(ba, MODEL_AXIS)

    def local(h, tgt, tab, bs):
        return _tp_head_local(h, tgt, tab, bs, block, vocab)

    logp, best = shard_map(
        local, mesh=mesh,
        in_specs=(h_spec, t_spec, P(MODEL_AXIS, None), P(MODEL_AXIS)),
        out_specs=(t_spec, t_spec), check_vma=False,
    )(hidden_p, targets_p, table, bias)
    # slice the seq padding back off
    return logp[:, :t_real], best[:, :t_real]


# -- TP ring decode head (serving): rotating (hidden-chunk, argmax) --------
#
# The decode twin of the ring above (r21): the vocab shards stay
# RESIDENT, and per decode step each device's (hidden-chunk, running-
# argmax) bundle rotates around the model ring — forward-only, no
# logsumexp, no label, no custom_vjp. After n hops the chunk is home
# carrying the complete argmax over the full vocab; the logits row never
# exists on any device and no shard ever holds more than V/n table rows.
# The wire can ride the r17 quant path: the hidden chunk is quantized
# ONCE before the loop (it only rotates, it never changes), so the
# ppermute carries the narrow ints + per-row f32 scales while the
# per-block logit dots stay f32 on the MXU.


def tp_greedy_decode_local(h, tab, bs, *, block: int, vocab: int,
                           quant: str = "off"):
    """Per-shard rotating-argmax: fold the LOCAL vocab shard's blockwise
    logits into each visiting chunk's running argmax. Call inside a
    ``shard_map`` region with a live ``model`` axis — the serving
    engine's TP decode program runs this at the tail of its one region
    (``serve/model.tp_decode_forward``). ``tab (vs, E)`` / ``bs (vs,)``
    are this shard's rows of the :func:`tp_head_geometry`-padded global
    table. Returns ``(...,)`` int32 argmax ids for the home chunk."""
    from ..parallel.ring import axis_size, ring_perm
    from ..runtime.context import MODEL_AXIS

    n = axis_size(MODEL_AXIS)
    perm = ring_perm(n)
    vs = tab.shape[0]
    nb = vs // block
    off = lax.axis_index(MODEL_AXIS) * vs
    shape = h.shape[:-1]
    if quant != "off":
        from .quant import dequantize, quantize_channel

        # quantize once: the chunk is pure cargo — every hop after the
        # first carries the narrow wire, and every shard (home included,
        # after the full circle) scores the SAME quantized hidden
        hq, hs = quantize_channel(h.astype(jnp.float32), quant, axes=-1)
        bundle0 = (hq, hs)
        unpack = lambda b: dequantize(*b)  # noqa: E731
    else:
        bundle0 = (h,)
        unpack = lambda b: b[0]  # noqa: E731

    def ring_step(carry, _):
        # rotate FIRST: the bundle is loop-carried state only — the hop
        # is compute-independent of this step's logit dots
        bundle, stats = lax.ppermute(carry, MODEL_AXIS, perm)
        h_c = unpack(bundle)

        def vblock(st, s):
            logits, _ = _block_logits(h_c, tab, bs, s, block=block,
                                      vocab=vocab, offset=off)
            return _argmax_step(*st, logits, off + s * block), None

        stats, _ = lax.scan(vblock, stats, jnp.arange(nb))
        return (bundle, stats), None

    init = (bundle0, (jnp.full(shape, NEG_INF, jnp.float32),
                      jnp.zeros(shape, jnp.int32)))
    (_, (_, best_i)), _ = lax.scan(ring_step, init, jnp.arange(n))
    return best_i


def tp_sample_tokens_local(h, tab, bs, *, policy: str = "greedy",
                           block: int, vocab: int, quant: str = "off"):
    """The in-region twin of :func:`sample_tokens`: the TP decode
    program's sampling seam. Same policy registry, same trace-time
    refusal — a policy added to :data:`SAMPLING_POLICIES` must land its
    ring form here or be refused before any TP engine serves it."""
    if policy not in SAMPLING_POLICIES:
        raise ValueError(
            f"unknown sampling policy {policy!r}; v1 serves "
            f"{SAMPLING_POLICIES} (temperature/top-k land as a blockwise "
            "Gumbel-max fold on this same seam)")
    return tp_greedy_decode_local(h, tab, bs, block=block, vocab=vocab,
                                  quant=quant)


def tp_greedy_decode(hidden, table, mesh, *, bias=None, block: int = 8192,
                     quant: str = "off"):
    """Decode-shaped :func:`greedy_decode` over a ``model``-sharded
    vocab table: ``argmax_v(hidden @ table.T + bias)`` with the table
    resident in V/n shards and (hidden-chunk, argmax) bundles rotating
    the ring — the standalone form of the serving engine's TP head
    (which drives :func:`tp_greedy_decode_local` inside its fused
    decode region instead).

    Args:
      hidden: ``(S, E)`` decode-shaped final hidden states — one row
        per slot. ``S`` is padded internally to ring granularity and
        the output sliced back.
      table: ``(V, E)`` tied embedding/output table (replicated or
        vocab-sharded; the region specs consume it in place).
      mesh: mesh with a live ``model`` axis
        (``parallel/collective_matmul.validate_tp_mesh``).
      bias: optional ``(V,)`` output bias.
      block: vocab tile width (clamped to the shard size).
      quant: ``off | int8 | fp8`` — quantize the rotating hidden wire
        (``ops/quant.py``); ``off`` is bit-identical to the dense head.

    Returns ``(S,)`` int32 argmax ids; the :func:`_argmax_step`
    tie-break-to-lowest-id invariant holds across shard visit order.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.collective_matmul import validate_tp_mesh
    from ..parallel.shard_map_compat import shard_map
    from ..runtime.context import MODEL_AXIS

    validate_tp_mesh(mesh)
    n = mesh.shape[MODEL_AXIS]
    vocab, _ = table.shape
    block, vs, pad_v = tp_head_geometry(vocab, n, block)
    if bias is None:
        bias = jnp.zeros((vocab,), jnp.float32)
    if pad_v:
        table = jnp.pad(table, ((0, pad_v), (0, 0)))
        bias = jnp.pad(bias, (0, pad_v))
    hidden_p, s_real = _tp_pad_seq(hidden, n, axis=0)

    def local(h, tab, bs):
        return tp_greedy_decode_local(h, tab, bs, block=block,
                                      vocab=vocab, quant=quant)

    best = shard_map(
        local, mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS, None), P(MODEL_AXIS)),
        out_specs=P(MODEL_AXIS), check_vma=False,
    )(hidden_p, table, bias)
    return best[:s_real]
