"""Pallas TPU flash attention: fused tiled attention for the hot path.

The reference's native-code surface is third-party CUDA (NCCL/apex,
SURVEY.md §2c); the equivalent move on TPU is a Pallas kernel for the one
op where hand-tiling beats stock XLA: attention over long sequences.

Design (FlashAttention recurrence, TPU-shaped):

- Grid ``(batch, heads, q_blocks, kv_blocks)``; the kv dimension is
  ``arbitrary`` (sequential) so the running softmax state lives in VMEM
  scratch across kv iterations, while batch/head/q blocks parallelise.
- Running state per q row: max ``m``, normaliser ``l`` (stored
  lane-replicated ``(block_q, 128)`` — TPU vregs are 2D, scalars-per-row
  are cheapest as a replicated lane vector), accumulator ``acc``
  ``(block_q, head_dim)`` in f32.
- Logits/softmax in f32 on the MXU (``preferred_element_type``), output
  cast back to the input dtype (bf16 in the bf16 configs).
- Causal blocks that are fully masked are skipped (work scales with the
  triangle, not the square); the final kv iteration writes
  ``out = acc / l`` and the logsumexp.
- Backward: ``custom_vjp`` with the saved logsumexp; two Pallas kernels
  (dq over kv-sequential blocks; dk+dv over q-sequential blocks) recompute
  logits tilewise and apply the standard flash backward formulas — no
  O(seq^2) residuals anywhere, causally dead block pairs skipped with
  their DMA redirected (the public JAX flash kernel's trick).

``interpret=True`` (automatic off-TPU) runs the same kernel through the
Pallas interpreter, which is how CPU CI validates numerics.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: renamed TPUCompilerParams → CompilerParams across jax versions; same kwargs
CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

NEG_INF = -1e30
LANES = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_kv: int,
                kv_blocks: int):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block (sequential)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: kv block strictly above the diagonal touches no valid pair
    needed = (j * block_kv <= (i + 1) * block_q - 1) if causal else True

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)                  # (bkv, d)
        v = v_ref[0, 0].astype(jnp.float32)                  # (bkv, d)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, bkv)
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            k_pos = j * block_kv + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_ref[...]                                   # (bq, LANES)
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new[:, :1])                         # (bq, bkv)
        correction = jnp.exp(m_prev - m_new)                  # (bq, LANES)
        l_ref[...] = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_new
        pv = lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)  # (bq, d)
        acc_ref[...] = acc_ref[...] * correction[:, :1] + pv

    @pl.when(j == kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0, 0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _fwd_pallas(q, k, v, *, causal: bool, block_q: int, block_kv: int,
                interpret: bool):
    """(B,H,S,D) inputs -> (out, lse); lse is (B,H,S,LANES) lane-replicated."""
    b, h, s, d = q.shape
    t = k.shape[2]
    block_q = min(block_q, s)
    block_kv = min(block_kv, t)
    if s % block_q or t % block_kv:
        raise ValueError(f"seq {s}/{t} not divisible by blocks {block_q}/{block_kv}")
    grid = (b, h, s // block_q, t // block_kv)
    kernel = functools.partial(
        _fwd_kernel, scale=d ** -0.5, causal=causal,
        block_q=block_q, block_kv=block_kv, kv_blocks=grid[3],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),      # acc
            pltpu.VMEM((block_q, LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, LANES), jnp.float32),  # l
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _block_logits(q_ref, k_ref, *, scale, causal, i, j, block_q, block_kv):
    """Scaled (and causally masked) logits for one (q, kv) block pair,
    plus the f32 q tile (scale folded in — the dk formula reuses it)."""
    qf = q_ref[0, 0].astype(jnp.float32) * scale              # (bq, d)
    kf = k_ref[0, 0].astype(jnp.float32)                      # (bkv, d)
    s = lax.dot_general(qf, kf, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)   # (bq, bkv)
    if causal:
        q_pos = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        k_pos = j * block_kv + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return s, qf


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale: float, causal: bool, block_q: int,
                   block_kv: int, kv_blocks: int):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block (sequential)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    needed = (j * block_kv <= (i + 1) * block_q - 1) if causal else True

    @pl.when(needed)
    def _compute():
        s, _ = _block_logits(q_ref, k_ref, scale=scale, causal=causal,
                             i=i, j=j, block_q=block_q, block_kv=block_kv)
        # per-row scalars arrive compact (1, block_q) along lanes; the
        # reshape to a (block_q, 1) column is one in-VMEM relayout — far
        # cheaper than streaming a 128x lane-replicated HBM tensor
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)
        p = jnp.exp(s - lse)                                  # (bq, bkv)
        do = do_ref[0, 0].astype(jnp.float32)                 # (bq, d)
        v = v_ref[0, 0].astype(jnp.float32)                   # (bkv, d)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                 # (bq, bkv)
        k = k_ref[0, 0].astype(jnp.float32)
        acc_ref[...] += lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == kv_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                    causal: bool, block_q: int, block_kv: int, q_blocks: int):
    j = pl.program_id(2)  # kv block
    i = pl.program_id(3)  # q block (sequential)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    needed = ((i + 1) * block_q - 1 >= j * block_kv) if causal else True

    @pl.when(needed)
    def _compute():
        s, qf = _block_logits(q_ref, k_ref, scale=scale, causal=causal,
                              i=i, j=j, block_q=block_q, block_kv=block_kv)
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)
        p = jnp.exp(s - lse)                                  # (bq, bkv)
        do = do_ref[0, 0].astype(jnp.float32)                 # (bq, d)
        dv_acc[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),                  # p^T @ do
            preferred_element_type=jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                 # (bq, bkv)
        dk_acc[...] += lax.dot_general(
            ds, qf, (((0,), (0,)), ((), ())),                 # ds^T @ qf
            preferred_element_type=jnp.float32)

    @pl.when(i == q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_pallas(res, do, *, causal: bool, block_q: int, block_kv: int,
                interpret: bool):
    """Flash backward as two Pallas kernels (dq; dk+dv).

    Same tiling discipline as the forward: causally dead block pairs are
    skipped (work scales with the triangle) and, following the public JAX
    flash kernel's trick, a skipped step's DMA is redirected to block 0 so
    it costs no fresh HBM read. lse/delta stay compact ``(B, H, S)`` in
    HBM (blocked along lanes; one in-VMEM column reshape per tile).
    """
    q, k, v, out, lse = res  # q,k,v,out: (B,H,S,D); lse: (B,H,S)
    b, h, s, d = q.shape
    t = k.shape[2]
    scale = d ** -0.5
    # mirror the forward's clamp + guard: the nondiff block args arrive
    # unclamped, and a silently truncated grid would return garbage grads
    block_q = min(block_q, s)
    block_kv = min(block_kv, t)
    if s % block_q or t % block_kv:
        raise ValueError(
            f"seq {s}/{t} not divisible by blocks {block_q}/{block_kv}")
    q_blocks, kv_blocks = s // block_q, t // block_kv

    dof = do.astype(jnp.float32)
    # delta_i = sum_d do_i * out_i (rowwise), standard flash-bwd shortcut;
    # lse/delta stay compact (B,H,S) — blocked along lanes, reshaped to a
    # column in-kernel — instead of a 128x lane-replicated HBM tensor
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)   # (B,H,S)

    def on_diag(i, j):
        # the fwd/bwd skip predicate: q block i sees kv block j
        return (i + 1) * block_q - 1 >= j * block_kv

    # dq: grid over q blocks, kv sequential (mirrors the forward); a
    # causally skipped step's DMA is redirected to block 0 so it costs no
    # fresh HBM read (the public JAX flash kernel's trick)
    def kv_map(b_, h_, i, j):
        jj = lax.select(on_diag(i, j), j, 0) if causal else j
        return (b_, h_, jj, 0)

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    lspec = pl.BlockSpec((1, 1, block_q), lambda b_, h_, i, j: (b_, h_, i))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv,
                          kv_blocks=kv_blocks),
        grid=(b, h, q_blocks, kv_blocks),
        in_specs=[
            qspec,
            pl.BlockSpec((1, 1, block_kv, d), kv_map),
            pl.BlockSpec((1, 1, block_kv, d), kv_map),
            qspec,
            lspec,
            lspec,
        ],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid over kv blocks, q sequential; skipped q steps re-read
    # block 0 of q/do/lse/delta instead of streaming dead tiles
    def q_map(b_, h_, j, i):
        ii = lax.select(on_diag(i, j), i, 0) if causal else i
        return (b_, h_, ii, 0)

    def l_map(b_, h_, j, i):
        return q_map(b_, h_, j, i)[:3]

    kvspec = pl.BlockSpec((1, 1, block_kv, d),
                          lambda b_, h_, j, i: (b_, h_, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv,
                          q_blocks=q_blocks),
        grid=(b, h, kv_blocks, q_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_map),
            kvspec,
            kvspec,
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_q), l_map),
            pl.BlockSpec((1, 1, block_q), l_map),
        ],
        out_specs=[kvspec, kvspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_kv, interpret):
    out, _ = _fwd_pallas(q, k, v, causal=causal, block_q=block_q,
                         block_kv=block_kv, interpret=interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_kv, interpret):
    out, lse = _fwd_pallas(q, k, v, causal=causal, block_q=block_q,
                           block_kv=block_kv, interpret=interpret)
    return out, (q, k, v, out, lse)


def _bwd_blockwise_xla(res, do, *, causal: bool, block_kv: int):
    """Fallback flash backward: lax.scan over kv blocks in plain XLA.

    Escape hatch (``FLASH_BWD=xla``) for the Pallas backward: its
    in-kernel lane→sublane reshape of the per-row scalars is a Mosaic
    relayout that has only been validated in interpret mode so far. No
    causal block-skipping; O(block) memory like the kernels.
    """
    q, k, v, out, lse = res  # q,k,v,out: (B,H,S,D); lse: (B,H,S)
    b, h, s, d = q.shape
    t = k.shape[2]
    block = min(block_kv, t)
    n = t // block
    scale = d ** -0.5

    qf = q.astype(jnp.float32) * scale
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B,H,S)

    kb = jnp.moveaxis(k.astype(jnp.float32).reshape(b, h, n, block, d), 2, 0)
    vb = jnp.moveaxis(v.astype(jnp.float32).reshape(b, h, n, block, d), 2, 0)

    def body(dq_acc, inp):
        idx, kblk, vblk = inp  # kblk/vblk: (B,H,block,D)
        logits = jnp.einsum("bhsd,bhtd->bhst", qf, kblk)
        if causal:
            q_pos = lax.broadcasted_iota(jnp.int32, (s, block), 0)
            k_pos = idx * block + lax.broadcasted_iota(jnp.int32, (s, block), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        p = jnp.exp(logits - lse[..., None])                  # (B,H,S,block)
        dv = jnp.einsum("bhst,bhsd->bhtd", p, dof)
        dp = jnp.einsum("bhsd,bhtd->bhst", dof, vblk)
        ds = p * (dp - delta[..., None])                      # (B,H,S,block)
        dq_acc = dq_acc + jnp.einsum("bhst,bhtd->bhsd", ds, kblk) * scale
        dk = jnp.einsum("bhst,bhsd->bhtd", ds, qf)            # scale in qf
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((b, h, s, d), jnp.float32)
    dq, (dks, dvs) = lax.scan(body, dq0, (jnp.arange(n), kb, vb))
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, t, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, t, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_bwd_impl_logged: set[str] = set()


def _flash_bwd(causal, block_q, block_kv, interpret, res, do):
    import os

    # read at TRACE time: set before the process (or jax.clear_caches())
    impl = os.environ.get("FLASH_BWD")
    if impl is None:
        # Interpret mode (CPU CI) defaults to the Pallas kernels so they
        # stay continuously validated; real hardware defaults to the XLA
        # blockwise fallback until the Mosaic compile + gradient-parity
        # record lands (ADVICE.md round-4: the in-kernel lane→sublane
        # reshape is exactly what real Mosaic can miscompile, and a bad
        # default would silently corrupt every long-context run).
        impl = "pallas" if interpret else "xla"
    if impl not in ("pallas", "xla"):  # a typo'd escape hatch must not
        raise ValueError(                # silently keep the failing path
            f"FLASH_BWD={impl!r}: expected 'pallas' or 'xla'")
    if impl not in _bwd_impl_logged:
        # once per impl, at trace time: a stale traced value (env flipped
        # after compilation) is visible in the logs instead of silent
        _bwd_impl_logged.add(impl)
        from ..utils import get_logger

        get_logger(__name__).info(
            "flash backward impl selected (trace-time; set FLASH_BWD "
            "before first use or jax.clear_caches() to change)",
            {"impl": impl, "interpret": interpret},
        )
    if impl == "xla":
        return _bwd_blockwise_xla(res, do, causal=causal, block_kv=block_kv)
    return _bwd_pallas(res, do, causal=causal, block_q=block_q,
                       block_kv=block_kv, interpret=interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: jax.Array | None = None,
    causal: bool = False,
    block_size: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention on ``(batch, seq, heads, head_dim)`` inputs.

    Arbitrary boolean masks fall back to the blockwise XLA path (the Pallas
    kernel handles the causal structure natively; a general mask defeats
    its block-skipping).
    """
    if mask is not None:
        from .attention import blockwise_attention

        return blockwise_attention(q, k, v, mask=mask, causal=causal,
                                   block_size=block_size)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # fit blocks to the sequence: gcd keeps them divisors, so any
    # 128-multiple seq_len works (e.g. seq 768, block 512 -> 256)
    block_q = math.gcd(q.shape[1], block_size)
    block_kv = math.gcd(k.shape[1], block_size)
    if not interpret and min(block_q, block_kv) < 128:
        # a seq that only fits a sub-128 block would compile to pathological
        # Mosaic tiles (128 is the TPU lane width) — fail with intent
        # instead of silently degrading
        raise ValueError(
            f"flash_attention: seq lengths ({q.shape[1]}, {k.shape[1]}) with "
            f"block_size {block_size} fit only a {min(block_q, block_kv)}-"
            "wide block (< 128, the TPU lane width); pad the sequence to a "
            "multiple of 128 or use impl='xla'/'blockwise'"
        )
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash(qt, kt, vt, causal, block_q, block_kv, interpret)
    return out.transpose(0, 2, 1, 3)
